"""Chaos recovery suite — the fault-injection layer (flink_tpu/faults.py)
driving a windowed pipeline through run_with_recovery and asserting the
exactly-once contract survives.

Fault kinds exercised across the suite (≥5 distinct, per ISSUE 1):
  1. checkpoint-write failure      checkpoint.storage.write = raise
  2. torn manifest rename          checkpoint.storage.rename = raise
                                   (tmp dir fully written, never renamed)
  3. async-upload death            checkpoint.upload = raise
  4. storage stall                 checkpoint.storage.stall = delay
  5. RPC transport drop mid-call   rpc.client.send / recv = drop
  6. DCN peer death mid-exchange   dcn.send = drop
  7. control-plane heartbeat loss  runner.heartbeat = raise

Every test that injects prints its seed + injection log on failure
(``replayable``), so any chaos failure is reproducible: same seed →
same per-point injection schedule (asserted in TestFaultPlanDeterminism).
The deterministic fixed-seed slice below runs in tier-1 (<60s); the
randomized soak is ``slow``.
"""
import contextlib
import os
import sys
import threading
import time

import numpy as np
import pytest

from flink_tpu import faults
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import TransactionalCollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import (
    EventTimeSessionWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.config import Configuration
from flink_tpu.obs.tracing import tracer
from flink_tpu.runtime.supervisor import run_with_recovery
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = pytest.mark.chaos

CHAOS_SEED = 1234  # the fixed tier-1 seed; soak sweeps others


@contextlib.contextmanager
def replayable(plan):
    """Print the seed + injection schedule on ANY failure — the replay
    handle (re-run with the same seed to get the same schedule)."""
    try:
        yield
    except BaseException:
        print(f"\nCHAOS REPLAY: seed={plan.seed} spec={plan.spec!r} "
              f"log={plan.log}", file=sys.stderr)
        raise


def deterministic_source(n_batches, batch=64, n_keys=10):
    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(1000 * int(split) + i)
        keys = rng.integers(0, n_keys, batch).astype(np.int64)
        ts = np.sort(rng.integers(i * 500, i * 500 + 1000,
                                  batch)).astype(np.int64)
        return {"k": keys}, ts

    return gen


def committed_view(sink):
    return sorted((int(r["key"]), int(r["window_start"]), int(r["count"]))
                  for r in sink.committed)


def golden_run(tmp_path, n_batches):
    """Fault-free reference run of the same job."""
    sink = TransactionalCollectSink()
    env = StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8, "state.slots-per-shard": 64,
        "pipeline.microbatch-size": 128,
        "execution.checkpointing.dir": str(tmp_path / "golden-ckpt"),
        "execution.checkpointing.interval": 1,
    }))
    (env.from_source(GeneratorSource(deterministic_source(n_batches)),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
     .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
     .add_sink(sink))
    env.execute("chaos-golden")
    return committed_view(sink)


def chaos_conf(tmp_path, extra=None):
    c = {
        "state.num-key-shards": 8, "state.slots-per-shard": 64,
        "pipeline.microbatch-size": 128,
        "execution.checkpointing.dir": str(tmp_path / "chaos-ckpt"),
        "execution.checkpointing.interval": 1,
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 20,
        "restart-strategy.fixed-delay.delay": 1,
    }
    c.update(extra or {})
    return Configuration(c)


def run_chaos_pipeline(tmp_path, plan, n_batches, extra_conf=None):
    """The windowed pipeline under run_with_recovery with ``plan``
    active; returns (committed rows, #recovery spans, #fault spans)."""
    sink = TransactionalCollectSink()

    def build_env(conf):
        env = StreamExecutionEnvironment(conf)
        (env.from_source(
            GeneratorSource(deterministic_source(n_batches)),
            WatermarkStrategy.for_bounded_out_of_orderness(1000))
         .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
         .add_sink(sink))
        return env

    tracer.clear()
    with plan.activate(), replayable(plan):
        run_with_recovery(build_env, chaos_conf(tmp_path, extra_conf),
                          job_name="chaos-job")
    recoveries = tracer.spans("recovery")
    fault_spans = tracer.spans("fault")
    return committed_view(sink), recoveries, fault_spans


class TestFaultPlanDeterminism:
    """Same seed → same injection schedule; the replayability contract."""

    SPEC = ("checkpoint.storage.write=raise@0.3; dcn.send=drop@0.5 x3; "
            "checkpoint.storage.stall=delay~1@0.2")
    SEQ = (["checkpoint.storage.write"] * 30 + ["dcn.send"] * 20
           + ["checkpoint.storage.stall"] * 30)

    def _drive(self, seed):
        plan = faults.FaultPlan.from_spec(self.SPEC, seed=seed)
        with plan.activate():
            for pt in self.SEQ:
                try:
                    faults.fire(pt, exc=OSError)
                except Exception:
                    pass
        return plan.log

    def test_same_seed_same_schedule(self):
        assert self._drive(7) == self._drive(7)

    def test_different_seed_different_schedule(self):
        assert self._drive(7) != self._drive(8)

    def test_count_after_rules_are_exact(self):
        plan = faults.FaultPlan(seed=0).rule("p.x", "raise", count=2,
                                             after=3)
        hits = []
        with plan.activate():
            for i in range(10):
                try:
                    faults.fire("p.x")
                except RuntimeError:
                    hits.append(i)
        assert hits == [3, 4]
        assert plan.log == [("p.x", "raise", 3), ("p.x", "raise", 4)]

    def test_spec_modifier_order_free(self):
        a = faults.FaultPlan.from_spec("a.b=delay x3 ~5 +1").rules[0]
        b = faults.FaultPlan.from_spec("a.b=delay ~5 +1 x3").rules[0]
        assert (a.count, a.after, a.delay_ms) == (3, 1, 5.0)
        assert (b.count, b.after, b.delay_ms) == (3, 1, 5.0)

    def test_bad_spec_rejected(self):
        with pytest.raises(ValueError, match="bad faults.inject rule"):
            faults.FaultPlan.from_spec("a.b=explode")

    def test_injected_exception_is_tagged_and_typed(self):
        plan = faults.FaultPlan().rule("p.io", "raise")
        with plan.activate():
            with pytest.raises(OSError) as ei:
                faults.fire("p.io", exc=OSError)
        assert faults.is_injected(ei.value)


class TestChaosRecoveryExactlyOnce:
    """The headline soak: checkpoint-write failure, torn manifest
    rename, async-upload death, and storage stalls injected into a
    windowed pipeline under run_with_recovery — the committed output
    must equal the fault-free run exactly, and every injection and
    every recovery attempt must be visible in metrics + tracing."""

    N_BATCHES = 16

    @staticmethod
    def storage_chaos_plan(seed=CHAOS_SEED):
        # schedule-exact rules: in ANY interleaving exactly these five
        # injections happen, three of them fatal (upload kills attempt
        # 1 before any checkpoint; write kills attempt 2 after its
        # first checkpoint completed — so attempt 3 RESTORES; the torn
        # rename kills attempt 3; attempt 4 finishes)
        return (faults.FaultPlan(seed=seed)
                .rule("checkpoint.upload", "raise", count=1)
                .rule("checkpoint.storage.write", "raise", count=1,
                      after=1)
                .rule("checkpoint.storage.rename", "raise", count=1,
                      after=1)
                .rule("checkpoint.storage.stall", "delay", count=2,
                      delay_ms=20))

    def test_storage_chaos_exactly_once(self, tmp_path):
        golden = golden_run(tmp_path, self.N_BATCHES)
        before = faults.snapshot()
        plan = self.storage_chaos_plan()
        got, recoveries, fault_spans = run_chaos_pipeline(
            tmp_path, plan, self.N_BATCHES)

        with replayable(plan):
            # exactly-once: byte-identical committed output
            assert got == golden
            # the full injection schedule ran
            assert sorted(x[:2] for x in plan.log) == sorted([
                ("checkpoint.upload", "raise"),
                ("checkpoint.storage.write", "raise"),
                ("checkpoint.storage.rename", "raise"),
                ("checkpoint.storage.stall", "delay"),
                ("checkpoint.storage.stall", "delay")])
            # tracing: one `fault` span per injection, with attributes
            assert len(fault_spans) == len(plan.log)
            assert {(s["attributes"]["point"], s["attributes"]["kind"])
                    for s in fault_spans} == {x[:2] for x in plan.log}
            # tracing: one `recovery` span per restart, each marked as
            # caused by an injected fault
            assert len(recoveries) == 3
            assert all(s["attributes"]["injected"] for s in recoveries)
            # metrics: process-global counters advanced by exactly the
            # injected/recovered amounts
            after = faults.snapshot()

            def delta(key):
                return after.get(key, 0) - before.get(key, 0)

            assert delta("faults.checkpoint.upload.raise") == 1
            assert delta("faults.checkpoint.storage.write.raise") == 1
            assert delta("faults.checkpoint.storage.rename.raise") == 1
            assert delta("faults.checkpoint.storage.stall.delay") == 2
            assert delta("recovery.attempts") == 3

    def test_same_seed_same_recovery_trace(self, tmp_path):
        """Replay determinism end to end: the same seed yields the same
        injection log and the same recovery trace shape."""
        golden = golden_run(tmp_path, self.N_BATCHES)
        runs = []
        for i in range(2):
            plan = self.storage_chaos_plan()
            got, recoveries, _ = run_chaos_pipeline(
                tmp_path / f"r{i}", plan, self.N_BATCHES)
            assert got == golden
            runs.append((plan.log, len(recoveries)))
        assert runs[0] == runs[1]

    def test_torn_rename_leaves_no_visible_checkpoint(self, tmp_path):
        """The torn-manifest scenario in isolation: a tmp dir fully
        written (manifest included) whose final rename failed must stay
        invisible to list_complete/latest — restore lands on the last
        COMPLETE checkpoint."""
        from flink_tpu.checkpoint.storage import FsCheckpointStorage

        st = FsCheckpointStorage(str(tmp_path), "tornjob")
        st.save(1, {"a": 1, "checkpoint_id": 1})
        plan = faults.FaultPlan().rule("checkpoint.storage.rename",
                                       "raise", count=1)
        with plan.activate(), replayable(plan):
            with pytest.raises(OSError, match="injected fault"):
                st.save(2, {"a": 2, "checkpoint_id": 2})
        assert [h.checkpoint_id for h in st.list_complete()] == [1]
        assert st.latest().checkpoint_id == 1
        # the torn attempt's tmp dir is swept by the next retention pass
        st.save(3, {"a": 3, "checkpoint_id": 3})
        leftovers = [n for n in os.listdir(str(tmp_path / "tornjob"))
                     if ".inprogress" in n]
        assert leftovers == []

    def test_tolerable_failures_ride_out_persist_faults(self, tmp_path):
        """With execution.checkpointing.tolerable-failures set, injected
        persist failures do NOT restart the job: the staged 2PC epochs
        commit with the next successful checkpoint and the output is
        still exactly-once."""
        golden = golden_run(tmp_path, self.N_BATCHES)
        plan = (faults.FaultPlan(seed=CHAOS_SEED)
                .rule("checkpoint.storage.write", "raise", count=2,
                      after=1))
        got, recoveries, fault_spans = run_chaos_pipeline(
            tmp_path, plan, self.N_BATCHES,
            extra_conf={"execution.checkpointing.tolerable-failures": 5})
        with replayable(plan):
            assert got == golden
            assert recoveries == [], "tolerated failures must not restart"
            assert len(plan.log) == 2
            # the tolerated failures are visible as checkpoint.failed
            # spans (the tracing half of the acceptance criterion)
            failed = tracer.spans("checkpoint.failed")
            assert len(failed) == 2
            assert all("injected fault" in s["attributes"]["error"]
                       for s in failed)


class TestChaosRpc:
    """RPC transport drop mid-call: the client reconnect/retry path the
    harness flushed out (an ISSUE-predicted recovery bug — the old
    client surfaced the first transport error straight to the caller)."""

    def _server(self):
        from flink_tpu.runtime.rpc import RpcEndpoint, RpcServer

        class Echo(RpcEndpoint):
            def rpc_echo(self, x):
                return {"got": x}

        return RpcServer(Echo())

    def test_transport_drop_mid_call_retries_transparently(self):
        from flink_tpu.runtime.rpc import RpcClient

        srv = self._server()
        try:
            c = RpcClient("127.0.0.1", srv.port, retries=2,
                          retry_backoff_s=0.01)
            plan = (faults.FaultPlan(seed=CHAOS_SEED)
                    .rule("rpc.client.send", "drop", count=1)
                    .rule("rpc.client.recv", "drop", count=1, after=1))
            with plan.activate(), replayable(plan):
                # first call: send drops once, retry succeeds
                assert c.call("echo", x=1) == {"got": 1}
                # second call: recv drops once mid-call, retry succeeds
                assert c.call("echo", x=2) == {"got": 2}
                assert [x[:2] for x in plan.log] == [
                    ("rpc.client.send", "drop"),
                    ("rpc.client.recv", "drop")]
            c.close()
        finally:
            srv.close()

    def test_exhausted_retries_surface_rpc_error(self):
        from flink_tpu.runtime.rpc import RpcClient, RpcError

        srv = self._server()
        try:
            c = RpcClient("127.0.0.1", srv.port, retries=1,
                          retry_backoff_s=0.01)
            plan = faults.FaultPlan().rule("rpc.client.send", "drop")
            with plan.activate(), replayable(plan):
                with pytest.raises(RpcError, match="injected fault"):
                    c.call("echo", x=3)
            c.close()
        finally:
            srv.close()

    def test_rpc_drop_inside_recovery_pipeline_exactly_once(
            self, tmp_path):
        """RPC transport drop mid-call INSIDE a run_with_recovery
        pipeline: the driver's coordinator-side split enumeration RPC
        drops once; the client's reconnect/retry absorbs it and the
        committed output still equals the fault-free run."""
        from flink_tpu.runtime.coordinator import start_coordinator
        from flink_tpu.runtime.rpc import RpcClient

        n_batches = 8
        srv = start_coordinator(Configuration({}))
        c = RpcClient("127.0.0.1", srv.port)
        c.call("register_runner", runner_id="cr1", host="127.0.0.1",
               n_devices=8)
        assert c.call("submit_job",
                      job_id="rpc-chaos")["assigned"] == ["cr1"]
        c.close()

        sink = TransactionalCollectSink()

        def build_env(conf):
            env = StreamExecutionEnvironment(conf)
            (env.from_source(
                GeneratorSource(deterministic_source(n_batches),
                                n_splits=2),
                WatermarkStrategy.for_bounded_out_of_orderness(1000))
             .key_by("k").window(TumblingEventTimeWindows.of(1000))
             .count().add_sink(sink))
            return env

        conf = chaos_conf(tmp_path, {
            "source.enumeration": "coordinator",
            "cluster.coordinator": f"127.0.0.1:{srv.port}",
            "cluster.job-id": "rpc-chaos",
            "cluster.runner-id": "cr1",
        })
        plan = (faults.FaultPlan(seed=CHAOS_SEED)
                .rule("rpc.client.send", "drop", count=1))
        try:
            with plan.activate(), replayable(plan):
                run_with_recovery(build_env, conf, job_name="rpc-chaos")
                assert [x[:2] for x in plan.log] == [
                    ("rpc.client.send", "drop")]
                # 2 splits, same generator: golden covers split 0 only —
                # recompute the expected union over both splits
                expected = {}
                for split in range(2):
                    for i in range(n_batches):
                        rng = np.random.default_rng(1000 * split + i)
                        keys = rng.integers(0, 10, 64).astype(np.int64)
                        ts = np.sort(rng.integers(
                            i * 500, i * 500 + 1000, 64)).astype(np.int64)
                        for k, t in zip(keys, ts):
                            kw = (int(k), (int(t) // 1000) * 1000)
                            expected[kw] = expected.get(kw, 0) + 1
                got = committed_view(sink)
                assert got == sorted(
                    (k, w, n) for (k, w), n in expected.items())
        finally:
            srv.close()

    def test_server_dispatch_fault_reaches_caller_not_server(self):
        from flink_tpu.runtime.rpc import RpcClient, RpcError

        srv = self._server()
        try:
            c = RpcClient("127.0.0.1", srv.port, retries=0)
            plan = faults.FaultPlan().rule("rpc.server.dispatch",
                                           "raise", count=1)
            with plan.activate(), replayable(plan):
                with pytest.raises(RpcError, match="injected fault"):
                    c.call("echo", x=4)
                # the dispatch thread survived: next call works
                assert c.call("echo", x=5) == {"got": 5}
            c.close()
        finally:
            srv.close()


class TestChaosControlPlane:
    def test_heartbeat_faults_are_misses_not_deaths(self, tmp_path):
        """Injected heartbeat failures ride the miss path: the runner
        keeps beating and stays registered (no ha_dir → no failover)."""
        from flink_tpu.runtime.coordinator import start_coordinator
        from flink_tpu.runtime.rpc import RpcClient
        from flink_tpu.runtime.runner import TaskRunner

        srv = start_coordinator(Configuration(
            {"heartbeat.interval": 100, "heartbeat.timeout": 3000}))
        runner = TaskRunner("127.0.0.1", srv.port, runner_id="chaos-r1")
        plan = (faults.FaultPlan(seed=CHAOS_SEED)
                .rule("runner.heartbeat", "raise", count=2))
        try:
            with plan.activate(), replayable(plan):
                runner.start()
                # outlive 2 injected misses + a few healthy beats
                deadline = time.time() + 5
                while time.time() < deadline and plan.log != [
                        ("runner.heartbeat", "raise", 0),
                        ("runner.heartbeat", "raise", 1)]:
                    time.sleep(0.05)
                time.sleep(0.3)
                c = RpcClient("127.0.0.1", srv.port)
                assert "chaos-r1" in c.call("list_runners")
                c.close()
                assert [x[:2] for x in plan.log] == [
                    ("runner.heartbeat", "raise")] * 2
        finally:
            runner.close()
            srv.close()

    def test_deploy_fault_routes_to_redeploy(self):
        """An injected deploy RPC failure consults the restart strategy
        and re-deploys onto ANOTHER runner (the failed target is
        excluded) instead of losing the job."""
        from flink_tpu.runtime.coordinator import start_coordinator
        from flink_tpu.runtime.rpc import RpcClient, RpcEndpoint, RpcServer

        class GW(RpcEndpoint):
            def __init__(self):
                self.deployed = []

            def rpc_run_job(self, job_id, entry, config=None, attempt=1,
                            **kw):
                self.deployed.append((job_id, attempt))
                return {"accepted": True}

        srv = start_coordinator(Configuration(
            {"restart-strategy.type": "fixed-delay",
             "restart-strategy.fixed-delay.delay": 50}))
        gws = [RpcServer(GW()), RpcServer(GW())]
        plan = (faults.FaultPlan(seed=CHAOS_SEED)
                .rule("coordinator.deploy", "raise", count=1))
        try:
            with plan.activate(), replayable(plan):
                c = RpcClient("127.0.0.1", srv.port)
                for i, gw in enumerate(gws):
                    c.call("register_runner", runner_id=f"r{i}",
                           host="127.0.0.1", n_devices=8, port=gw.port)
                c.call("submit_job", job_id="dj", entry="x:y", config={})
                deadline = time.time() + 10
                while time.time() < deadline and not any(
                        gw.endpoint.deployed for gw in gws):
                    time.sleep(0.05)
                assert any(gw.endpoint.deployed for gw in gws), (
                    "job never redeployed after the injected deploy "
                    "failure")
                assert [x[:2] for x in plan.log] == [
                    ("coordinator.deploy", "raise")]
                c.close()
        finally:
            srv.close()
            for gw in gws:
                gw.close()


class TestChaosDcn:
    """DCN peer death mid-exchange: a dropped frame send collapses the
    rendezvous; both processes fail over through run_with_recovery with
    a NEGOTIATED common restore id, and the union of their committed
    outputs still equals the fault-free single-process run."""

    N_BATCHES = 8

    def _golden(self, tmp_path):
        sink = TransactionalCollectSink()
        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 8, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": 64,
            "execution.checkpointing.dir": str(tmp_path / "g-ckpt"),
            "execution.checkpointing.interval": 1,
        }))
        (env.from_source(
            GeneratorSource(deterministic_source(self.N_BATCHES, batch=64)),
            WatermarkStrategy.for_bounded_out_of_orderness(1000))
         .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
         .add_sink(sink))
        env.execute("dcn-golden")
        return committed_view(sink)

    @staticmethod
    def _free_ports(n):
        import socket

        socks = []
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        ports = [s.getsockname()[1] for s in socks]
        for s in socks:
            s.close()
        return ports

    def _run_fleet(self, tmp_path, plan, extra_conf=None,
                   expected_log=None, subdir="c-ckpt"):
        """Two in-process 'processes' through the DCN exchange under
        ``plan``; asserts both recover, the injection log matches, and
        returns the committed union."""
        ports = self._free_ports(2)
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        sinks = [TransactionalCollectSink() for _ in range(2)]
        results = [None, None]

        def make_build(pid):
            def build_env(conf):
                env = StreamExecutionEnvironment(conf)
                (env.from_source(
                    GeneratorSource(
                        deterministic_source(self.N_BATCHES, batch=64)),
                    WatermarkStrategy.for_bounded_out_of_orderness(1000))
                 .key_by("k")
                 .window(TumblingEventTimeWindows.of(1000)).count()
                 .add_sink(sinks[pid]))
                return env
            return build_env

        def run(pid):
            c = {
                "state.num-key-shards": 8, "state.slots-per-shard": 64,
                "pipeline.microbatch-size": 64,
                "cluster.num-processes": 2, "cluster.process-id": pid,
                "cluster.dcn-peers": peers,
                "cluster.dcn-port": ports[pid],
                "cluster.dcn-secret": "chaos-suite-secret",
                "execution.checkpointing.dir": str(tmp_path / subdir),
                "execution.checkpointing.interval": 1,
                "restart-strategy.type": "fixed-delay",
                "restart-strategy.fixed-delay.attempts": 10,
                "restart-strategy.fixed-delay.delay": 200,
            }
            c.update(extra_conf or {})
            try:
                results[pid] = run_with_recovery(
                    make_build(pid), Configuration(c),
                    job_name="dcn-chaos")
            except BaseException as e:  # surfaces in the assert below
                results[pid] = e

        tracer.clear()
        with plan.activate(), replayable(plan):
            ths = [threading.Thread(target=run, args=(i,))
                   for i in range(2)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(timeout=180)
            assert not any(t.is_alive() for t in ths), "dcn chaos hung"
            for pid, r in enumerate(results):
                assert not isinstance(r, BaseException), (
                    f"p{pid} did not recover: {r!r}")
            if expected_log is not None:
                assert sorted(x[:2] for x in plan.log) == sorted(
                    expected_log), plan.log
            # both processes failed over at least once, visibly
            assert len(tracer.spans("recovery")) >= 2
            return sorted(committed_view(sinks[0])
                          + committed_view(sinks[1]))

    def test_dcn_peer_death_mid_exchange_recovers_exactly_once(
            self, tmp_path):
        # one mid-run frame send (the 7th across the fleet) drops: the
        # victim attempt dies mid-exchange, its sockets close, the PEER's
        # recv collapses — both fail over and re-rendezvous
        golden = self._golden(tmp_path)
        plan = (faults.FaultPlan(seed=CHAOS_SEED)
                .rule("dcn.send", "drop", count=1, after=6))
        union = self._run_fleet(tmp_path, plan,
                                expected_log=[("dcn.send", "drop")])
        assert union == golden

    def test_dcn_parallel_send_worker_death_recovers_exactly_once(
            self, tmp_path):
        """Faults on the PARALLEL I/O plane: a sender-WORKER-thread
        write dies mid-step (dcn.send.partial — the connection cut
        under a peer, detected at the step barrier via the first-error
        cell) and later a frame encode fails on the caller thread.
        Committed union stays byte-identical to the fault-free
        golden."""
        golden = self._golden(tmp_path)
        plan = (faults.FaultPlan(seed=CHAOS_SEED)
                .rule("dcn.send.partial", "drop", count=1, after=5)
                .rule("dcn.frame.encode", "raise", count=1, after=24))
        union = self._run_fleet(
            tmp_path, plan,
            expected_log=[("dcn.send.partial", "drop"),
                          ("dcn.frame.encode", "raise")])
        assert union == golden

    def test_dcn_overlap_consume_fault_recovers_exactly_once(
            self, tmp_path):
        """A fault at the OVERLAPPED consume seam (the deferred step
        barrier) collapses the attempt while a second exchange step is
        in flight; recovery re-negotiates a common checkpoint and the
        committed union still equals the golden run — exactly-once on
        the overlapped path."""
        golden = self._golden(tmp_path)
        plan = (faults.FaultPlan(seed=CHAOS_SEED)
                .rule("dcn.overlap.consume", "raise", count=1, after=4))
        union = self._run_fleet(
            tmp_path, plan,
            expected_log=[("dcn.overlap.consume", "raise")])
        assert union == golden


@pytest.mark.slow
class TestChaosSoak:
    """Randomized multi-seed soak: probabilistic fault schedules over
    every storage/upload point, several seeds — exactly-once must hold
    for each. Failures print the seed for exact replay."""

    N_BATCHES = 12

    @pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
    def test_randomized_storage_soak(self, tmp_path, seed):
        golden = golden_run(tmp_path, self.N_BATCHES)
        plan = (faults.FaultPlan(seed=seed)
                .rule("checkpoint.upload", "raise", p=0.15, count=2)
                .rule("checkpoint.storage.write", "raise", p=0.15,
                      count=2)
                .rule("checkpoint.storage.fsync", "raise", p=0.1,
                      count=2)
                .rule("checkpoint.storage.rename", "raise", p=0.1,
                      count=2)
                .rule("checkpoint.storage.stall", "delay", p=0.3,
                      count=4, delay_ms=10))
        got, recoveries, fault_spans = run_chaos_pipeline(
            tmp_path / f"s{seed}", plan, self.N_BATCHES,
            extra_conf={"restart-strategy.fixed-delay.attempts": 40})
        with replayable(plan):
            assert got == golden
            assert len(fault_spans) == len(plan.log)
            fatal = sum(1 for x in plan.log if x[1] == "raise")
            assert len(recoveries) == fatal


class TestHostPoolChaos:
    """The §9.4 correctness gate: the sessions and spill-overflow
    pipelines recover EXACTLY-ONCE with the shared host pool ON
    (host.parallelism=4) and the ``host.pool.task`` submit seam armed —
    a worker-pool pass dying mid-batch must never corrupt committed
    output. Goldens run FAULT-FREE AT host.parallelism=1, so each
    assertion covers both the recovery contract and the serial-vs-
    parallel determinism contract at once."""

    N_BATCHES = 8
    POOL_CONF = {"host.parallelism": 4}

    # -- sessions ---------------------------------------------------------

    @staticmethod
    def sessions_source(n_batches, batch=256, n_users=30):
        def gen(split, i):
            if i >= n_batches:
                return None
            rng = np.random.default_rng(500 + 1000 * int(split) + i)
            user = rng.integers(0, n_users, batch).astype(np.int64)
            ts = (i * 400 + rng.integers(0, 600, batch)).astype(np.int64)
            return {"u": user}, ts
        return gen

    def _sessions_builder(self, sink):
        def build_env(conf):
            env = StreamExecutionEnvironment(conf)
            (env.from_source(
                GeneratorSource(self.sessions_source(self.N_BATCHES)),
                WatermarkStrategy.for_bounded_out_of_orderness(500))
             .key_by("u")
             .window(EventTimeSessionWindows.with_gap(150))
             .allowed_lateness(1000)
             .count()
             .add_sink(sink))
            return env
        return build_env

    @staticmethod
    def _session_view(sink):
        return sorted((int(r["key"]), int(r["window_start"]),
                       int(r["window_end"]), int(r["count"]))
                      for r in sink.committed)

    def _golden(self, builder_fn, view, tmp_path, extra=None):
        """Fault-free reference at host.parallelism=1 (the serial
        path's bytes are the contract both gates compare against)."""
        sink = TransactionalCollectSink()
        conf = {
            "state.num-key-shards": 8, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": 256,
            "execution.checkpointing.dir": str(tmp_path / "golden-ckpt"),
            "execution.checkpointing.interval": 1,
            "host.parallelism": 1,
        }
        conf.update(extra or {})
        builder_fn(sink)(Configuration(conf)).execute("hostpool-golden")
        return view(sink)

    def _chaos(self, builder_fn, view, tmp_path, plan, extra=None):
        sink = TransactionalCollectSink()
        conf = dict(self.POOL_CONF)
        conf.update(extra or {})
        tracer.clear()
        with plan.activate(), replayable(plan):
            run_with_recovery(builder_fn(sink),
                              chaos_conf(tmp_path, conf),
                              job_name="hostpool-chaos")
        return (view(sink), tracer.spans("recovery"),
                tracer.spans("fault"))

    def test_sessions_chaos_pool_on_exactly_once(self, tmp_path):
        golden = self._golden(self._sessions_builder, self._session_view,
                              tmp_path)
        plan = (faults.FaultPlan(seed=CHAOS_SEED)
                .rule("host.pool.task", "raise", count=1, after=6)
                .rule("checkpoint.storage.write", "raise", count=1,
                      after=1))
        got, recoveries, fault_spans = self._chaos(
            self._sessions_builder, self._session_view,
            tmp_path, plan)
        with replayable(plan):
            assert got == golden
            assert sorted(x[:2] for x in plan.log) == sorted([
                ("host.pool.task", "raise"),
                ("checkpoint.storage.write", "raise")])
            assert len(fault_spans) == len(plan.log)
            # the async persist's fault can land in the same attempt as
            # a pool fault, so recoveries ∈ [1, #raises] — what's exact
            # is the schedule (above) and the committed bytes
            assert 1 <= len(recoveries) <= 2

    # -- spill overflow ---------------------------------------------------

    @staticmethod
    def churn_source(n_batches, batch=256, n_keys=800):
        def gen(split, i):
            if i >= n_batches:
                return None
            rng = np.random.default_rng(900 + 1000 * int(split) + i)
            return ({"k": rng.integers(0, n_keys, batch).astype(np.int64)},
                    np.sort(rng.integers(i * 500, i * 500 + 1000,
                                         batch)).astype(np.int64))
        return gen

    SPILL_CONF = {"state.backend": "spill", "state.slots-per-shard": 4}

    def _spill_builder(self, sink):
        def build_env(conf):
            env = StreamExecutionEnvironment(conf)
            (env.from_source(
                GeneratorSource(self.churn_source(self.N_BATCHES)),
                WatermarkStrategy.for_bounded_out_of_orderness(500))
             .key_by("k")
             .window(TumblingEventTimeWindows.of(1000))
             .count()
             .add_sink(sink))
            return env
        return build_env

    def test_spill_overflow_chaos_pool_on_exactly_once(self, tmp_path):
        golden = self._golden(self._spill_builder, committed_view,
                              tmp_path, extra=self.SPILL_CONF)
        plan = (faults.FaultPlan(seed=CHAOS_SEED)
                .rule("host.pool.task", "raise", count=2, after=4)
                .rule("checkpoint.storage.write", "raise", count=1,
                      after=2))
        got, recoveries, fault_spans = self._chaos(
            self._spill_builder, committed_view, tmp_path, plan,
            extra=self.SPILL_CONF)
        with replayable(plan):
            assert got == golden
            assert len(fault_spans) == len(plan.log) == 3
            assert 1 <= len(recoveries) <= 3


class TestLsmChaos:
    """Chaos at the DISK tier's own durable seams (ISSUE 17): run seal,
    run fsync, compaction swap, and the checkpoint changelog hardlink.
    The spill-overflow pipeline runs with ``state.backend=lsm`` and a
    budget tiny enough that every batch seals — committed output must
    stay byte-identical to the fault-free golden of the same lsm job.
    A fault mid-seal or mid-compact kills the attempt; recovery builds
    a FRESH store dir and replays from the last checkpoint, so torn
    tmp files in the dead store's dir are abandoned debris (fsck's
    territory), never adopted state."""

    def _conf(self, tmp_path):
        return {"state.backend": "lsm", "state.slots-per-shard": 4,
                "state.memory-budget-bytes": 4096,
                "state.lsm.run-floor-bytes": 4096,
                "state.lsm.dir": str(tmp_path / "lsm"),
                "host.parallelism": 1}

    def _drive(self, tmp_path, point, after, extra=None):
        t = TestHostPoolChaos()
        conf = {**self._conf(tmp_path), **(extra or {})}
        golden = t._golden(t._spill_builder, committed_view, tmp_path,
                           extra=conf)
        plan = (faults.FaultPlan(seed=CHAOS_SEED)
                .rule(point, "raise", count=1, after=after))
        got, recoveries, fault_spans = t._chaos(
            t._spill_builder, committed_view, tmp_path, plan,
            extra=conf)
        with replayable(plan):
            assert got == golden
            assert [x[:2] for x in plan.log] == [(point, "raise")]
            assert len(fault_spans) == 1
            assert len(recoveries) >= 1

    def test_seal_fault_exactly_once(self, tmp_path):
        self._drive(tmp_path, "state.run.seal", after=3)

    def test_run_fsync_fault_exactly_once(self, tmp_path):
        self._drive(tmp_path, "state.run.fsync", after=2)

    def test_compact_swap_fault_exactly_once(self, tmp_path):
        # tumbling purge retires runs fast; compact at 2 so the pass
        # actually happens inside an 8-batch run
        self._drive(tmp_path, "state.compact.swap", after=0,
                    extra={"state.lsm.compact-min-runs": 2})

    def test_changelog_link_fault_exactly_once(self, tmp_path):
        self._drive(tmp_path, "state.changelog.link", after=2)


@pytest.mark.slow
class TestHostPoolChaosSoak:
    """Randomized multi-seed soak of the pool-on spill overflow and
    sessions pipelines (the §9.4 gate's long tail): probabilistic
    injection at the host.pool.task seam composed with storage faults.
    Failures print the seed for exact replay."""

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_spill_overflow_soak(self, tmp_path, seed):
        t = TestHostPoolChaos()
        golden = t._golden(t._spill_builder, committed_view, tmp_path,
                           extra=t.SPILL_CONF)
        plan = (faults.FaultPlan(seed=seed)
                .rule("host.pool.task", "raise", p=0.03, count=3)
                .rule("checkpoint.storage.write", "raise", p=0.15,
                      count=2))
        got, recoveries, fault_spans = t._chaos(
            t._spill_builder, committed_view, tmp_path / f"s{seed}",
            plan,
            extra={**t.SPILL_CONF,
                   "restart-strategy.fixed-delay.attempts": 40})
        fatal = sum(1 for x in plan.log if x[1] == "raise")
        with replayable(plan):
            assert got == golden
            assert len(fault_spans) == len(plan.log)
            assert len(recoveries) <= fatal
            assert (fatal == 0) == (len(recoveries) == 0)

    @pytest.mark.parametrize("seed", [21, 22])
    def test_sessions_soak(self, tmp_path, seed):
        t = TestHostPoolChaos()
        golden = t._golden(t._sessions_builder, t._session_view,
                           tmp_path)
        plan = (faults.FaultPlan(seed=seed)
                .rule("host.pool.task", "raise", p=0.05, count=3))
        got, recoveries, fault_spans = t._chaos(
            t._sessions_builder, t._session_view,
            tmp_path / f"s{seed}", plan,
            extra={"restart-strategy.fixed-delay.attempts": 40})
        fatal = sum(1 for x in plan.log if x[1] == "raise")
        with replayable(plan):
            assert got == golden
            assert len(fault_spans) == len(plan.log)
            assert len(recoveries) <= fatal
            assert (fatal == 0) == (len(recoveries) == 0)
