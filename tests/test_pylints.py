"""Repo AST-lint suite (flink_tpu/analysis/pylints.py): fixture
sources with deliberate tracer leaks prove every lint fires at the
right line, and trace-static idioms (shape reads, len(), `is None`,
static_argnums) prove it stays quiet — the false-positive budget of
the dogfood gate is ZERO, so the negatives matter as much as the
positives (tier-1)."""
import textwrap

import pytest

from flink_tpu.analysis.pylints import (
    DEFAULT_LINT_PATHS,
    LINT_CATALOG,
    LINT_RULES,
    lint_paths,
    lint_source,
)

pytestmark = pytest.mark.analysis


def lint(src):
    return lint_source(textwrap.dedent(src), "fixture.py")


def rules_of(findings):
    return [f.rule for f in findings]


# -- tracer leaks: host conversions -----------------------------------------

class TestTracerHostCall:
    def test_float_on_traced_param(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x):
                return float(x)
        """)
        assert rules_of(fs) == ["TRACER_HOST_CALL"]
        assert fs[0].line == 6
        assert fs[0].severity == "error"
        assert "kernel" in fs[0].message

    def test_np_asarray_one_assignment_hop(self):
        fs = lint("""
            import jax
            import numpy as np

            @jax.jit
            def kernel(x):
                y = x * 2
                return np.asarray(y)
        """)
        assert rules_of(fs) == ["TRACER_HOST_CALL"]
        assert "np.asarray" in fs[0].message

    def test_item_method_on_traced(self):
        fs = lint("""
            from jax import jit

            @jit
            def kernel(x):
                return x.sum().item()
        """)
        assert rules_of(fs) == ["TRACER_HOST_CALL"]

    def test_reassignment_clears_taint(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x):
                x = 3
                return float(x)
        """)
        assert fs == []

    def test_untainted_conversion_is_fine(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x, n):
                return x * int(x.shape[0])
        """)
        assert fs == []


# -- tracer leaks: host control flow ----------------------------------------

class TestTracerBranch:
    def test_if_on_traced_value(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x):
                if x > 0:
                    return x
                return -x
        """)
        assert rules_of(fs) == ["TRACER_BRANCH"]
        assert fs[0].line == 6

    def test_while_and_ternary(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x):
                while x > 0:
                    x = x - 1
                return x if x > 0 else -x
        """)
        assert rules_of(fs) == ["TRACER_BRANCH", "TRACER_BRANCH"]

    def test_range_over_traced_trip_count(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x, n):
                for i in range(n):
                    x = x + i
                return x
        """)
        assert rules_of(fs) == ["TRACER_BRANCH"]
        assert "range()" in fs[0].message

    def test_static_idioms_stay_quiet(self):
        # shape/ndim/dtype/size reads, len(), `is None`, `in` — all
        # static under tracing; flagging any of them would poison the
        # dogfood gate with false positives
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x, data):
                if x.shape[0] > 4:
                    x = x[:4]
                if x.ndim == 2:
                    x = x.sum(0)
                if len(data) > 1:
                    x = x * 2
                if x is None:
                    return x
                if "col" in data:
                    x = x + 1
                for i in range(x.shape[0]):
                    x = x + i
                return x
        """)
        assert fs == []

    def test_static_argnums_excludes_param(self):
        fs = lint("""
            import jax
            from functools import partial

            @partial(jax.jit, static_argnums=(1,))
            def kernel(x, n):
                if n > 4:
                    return x[:n]
                return x
        """)
        assert fs == []

    def test_static_argnames_excludes_param(self):
        fs = lint("""
            import jax

            @partial(jax.jit, static_argnames=("n",))
            def kernel(x, n):
                return x[:n] if n > 4 else x
        """)
        assert fs == []

    def test_nested_def_params_shadow_taint(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x):
                def helper(x):
                    # this x is the helper's own (concrete) parameter
                    return float(x)
                return x
        """)
        assert fs == []

    def test_jit_call_form_on_local_def(self):
        fs = lint("""
            import jax

            def step(x):
                if x > 0:
                    return x
                return -x

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TRACER_BRANCH"]

    def test_jit_of_shard_map_call_form(self):
        fs = lint("""
            import jax
            from flink_tpu.utils.jaxcompat import shard_map

            def shard(x):
                return bool(x.sum())

            fn = jax.jit(shard_map(shard, mesh=None, in_specs=(),
                                   out_specs=()))
        """)
        assert rules_of(fs) == ["TRACER_HOST_CALL"]

    def test_plain_function_is_not_a_kernel(self):
        fs = lint("""
            def host_side(x):
                if x > 0:
                    return float(x)
                return x
        """)
        assert fs == []


# -- registry drift ---------------------------------------------------------

class TestRegistryDrift:
    def test_unknown_fault_point_literal(self):
        fs = lint("""
            from flink_tpu import faults

            def save():
                faults.fire("checkpoint.storage.wrte")
        """)
        assert rules_of(fs) == ["FAULT_POINT_DRIFT"]
        assert "checkpoint.storage.wrte" in fs[0].message

    def test_known_fault_point_is_quiet(self):
        fs = lint("""
            from flink_tpu import faults

            def save():
                faults.fire("checkpoint.storage.write")
        """)
        assert fs == []

    def test_undeclared_get_raw_key(self):
        fs = lint("""
            def f(config):
                return config.get_raw("execution.checkpontng.interval")
        """)
        assert rules_of(fs) == ["CONFIG_KEY_DRIFT"]

    def test_dynamic_prefix_key_is_declared(self):
        fs = lint("""
            def f(config):
                return config.get_raw("test.n-batches", 6)
        """)
        assert fs == []

    def test_configuration_dict_literal_keys(self):
        fs = lint("""
            from flink_tpu.config import Configuration

            conf = Configuration({
                "state.num-key-shards": 8,
                "state.num-key-shrads": 8,
            })
        """)
        assert rules_of(fs) == ["CONFIG_KEY_DRIFT"]
        assert "shrads" in fs[0].message

    def test_metric_name_grammar(self):
        fs = lint("""
            def register(group):
                group.counter("checkpointCount")
                group.counter("checkpoint_count")
        """)
        assert rules_of(fs) == ["METRIC_NAME_INVALID"]
        assert fs[0].severity == "warn"


class TestHostpoolSharedWrite:
    """The concurrency lint plane: shared-mutable-state writes inside
    closures submitted to HostPool.run_tasks without a lock/merge
    discipline — the exact race shape PR 5 fixed by hand in
    obs/metrics.py (Counter's `self._v += n`)."""

    def test_unlocked_counter_in_lambda_list_fires(self):
        fs = lint("""
            class Op:
                def __init__(self, pool):
                    self.pool = pool
                    self.total = 0

                def absorb(self, chunks):
                    self.pool.run_tasks(
                        [lambda c=c: self._bump(c) for c in chunks])

                def _bump(self, c):
                    pass

            def drive(pool, chunks, counter):
                def task(c):
                    counter["n"] += len(c)   # racy subscript write
                    return len(c)
                pool.run_tasks([lambda c=c: task(c) for c in chunks])
        """)
        assert rules_of(fs) == ["HOSTPOOL_SHARED_WRITE"]
        assert fs[0].severity == "warn"
        assert "counter" in fs[0].message and fs[0].fix

    def test_unlocked_self_attribute_fires_one_call_hop_deep(self):
        fs = lint("""
            class Op:
                def absorb(self, chunks):
                    def merge(c):
                        self.total += len(c)   # racy attribute RMW
                    self.pool.run_tasks(
                        [lambda c=c: merge(c) for c in chunks])
        """)
        assert rules_of(fs) == ["HOSTPOOL_SHARED_WRITE"]
        assert "self.total" in fs[0].message

    def test_nonlocal_accumulator_through_append_fires(self):
        fs = lint("""
            def drive(pool, chunks):
                done = 0
                tasks = []
                for c in chunks:
                    def task(c=c):
                        nonlocal done
                        done += 1
                    tasks.append(task)
                pool.run_tasks(tasks)
        """)
        assert rules_of(fs) == ["HOSTPOOL_SHARED_WRITE"]

    def test_named_def_bound_through_list_literal_fires(self):
        """Review regression: `tasks = [merge]` (a NAMED local def, not
        a lambda) must resolve to the def — the obs/metrics.py race
        class must not escape through a plain list binding."""
        fs = lint("""
            class Op:
                def absorb(self, chunks):
                    def merge():
                        self.total += 1
                    tasks = [merge]
                    self.pool.run_tasks(tasks)
        """)
        assert rules_of(fs) == ["HOSTPOOL_SHARED_WRITE"]

    def test_annotated_and_walrus_locals_are_silent(self):
        """Review regression: `n: int = 0` and `(n := ...)` bind LOCALS
        — they must never read as shared writes."""
        fs = lint("""
            def drive(pool, chunks):
                def task(c):
                    n: int = 0
                    n += len(c)
                    if (m := len(c)) > 2:
                        m += 1
                    return n + m
                pool.run_tasks([lambda c=c: task(c) for c in chunks])
        """)
        assert fs == []

    def test_lock_guarded_write_is_silent(self):
        fs = lint("""
            import threading

            class Op:
                def __init__(self, pool):
                    self.pool = pool
                    self.total = 0
                    self._lock = threading.Lock()

                def absorb(self, chunks):
                    def task(c):
                        with self._lock:
                            self.total += len(c)
                        return len(c)
                    self.pool.run_tasks(
                        [lambda c=c: task(c) for c in chunks])
        """)
        assert fs == []

    def test_merge_discipline_returning_partials_is_silent(self):
        fs = lint("""
            def drive(pool, chunks):
                parts = pool.run_tasks(
                    [lambda c=c: sum(c) for c in chunks])
                total = sum(parts)   # combine on the CALLER: fine
                return total
        """)
        assert fs == []

    def test_local_writes_inside_tasks_are_silent(self):
        fs = lint("""
            def drive(pool, chunks):
                def task(c):
                    acc = {}
                    acc["n"] = len(c)     # local dict: per-task state
                    acc["n"] += 1
                    return acc
                pool.run_tasks([lambda c=c: task(c) for c in chunks])
        """)
        assert fs == []

    def test_obs_metrics_as_shipped_is_silent(self):
        """The PR 5 fix itself (lock-guarded primitives) must never be
        re-flagged — and neither may the shipped pool clients."""
        import os

        from flink_tpu.analysis.pylints import repo_root

        for rel in ("flink_tpu/obs/metrics.py", "flink_tpu/state/spill.py",
                    "flink_tpu/ops/session.py"):
            with open(os.path.join(repo_root(), rel)) as f:
                fs = [x for x in lint_source(f.read(), rel)
                      if x.rule == "HOSTPOOL_SHARED_WRITE"]
            assert fs == [], f"{rel}: {[x.render() for x in fs]}"


class TestLintPaths:
    def test_duplicate_option_declaration_across_files(self, tmp_path):
        a = tmp_path / "a.py"
        b = tmp_path / "b.py"
        a.write_text('X = ConfigOption("dup.key", 1, "first")\n')
        b.write_text('Y = ConfigOption("dup.key", 2, "second")\n')
        fs = lint_paths([str(a), str(b)], root=str(tmp_path))
        assert rules_of(fs) == ["CONFIG_OPTION_DUP"]
        assert fs[0].file == "b.py"
        assert "a.py:1" in fs[0].message

    def test_walks_directories_and_skips_pycache(self, tmp_path):
        pkg = tmp_path / "pkg"
        (pkg / "__pycache__").mkdir(parents=True)
        (pkg / "__pycache__" / "junk.py").write_text("syntax error ][")
        (pkg / "mod.py").write_text(
            "import jax\n\n@jax.jit\ndef k(x):\n    return float(x)\n")
        fs = lint_paths(["pkg"], root=str(tmp_path))
        assert rules_of(fs) == ["TRACER_HOST_CALL"]
        assert fs[0].file == "pkg/mod.py"

    def test_nonexistent_path_fails_loudly(self, tmp_path):
        # a typo'd CI path silently linting nothing would leave the
        # drift gate green while checking nothing
        with pytest.raises(ValueError, match="does not exist"):
            lint_paths(["no/such/dir"], root=str(tmp_path))

    def test_rule_table_covers_every_emitted_rule(self):
        assert {r for r, _ in LINT_RULES} >= {
            "TRACER_HOST_CALL", "TRACER_BRANCH", "FAULT_POINT_DRIFT",
            "CONFIG_KEY_DRIFT", "CONFIG_OPTION_DUP",
            "METRIC_NAME_INVALID"}

    def test_default_paths_cover_the_shipped_surface(self):
        assert "flink_tpu" in DEFAULT_LINT_PATHS
        assert "bench.py" in DEFAULT_LINT_PATHS


# -- one seeded violation per catalog rule ----------------------------------
#
# rule id -> (relpath, source). Each seed is the SMALLEST program that
# trips exactly its rule through the real lint_paths entry point (tmp
# tree + relpath, so the durability plane sees a durable-module path
# and CONFIG_OPTION_DUP sees the cross-file declaration scan). The
# coverage test below pins set(LINT_SEEDS) == the catalog: a rule
# cannot be de-registered (or added) without this suite noticing.

LINT_SEEDS = {
    "TRACER_HOST_CALL": ("seed.py", """
        import jax

        @jax.jit
        def kernel(x):
            return float(x)
    """),
    "TRACER_BRANCH": ("seed.py", """
        import jax

        @jax.jit
        def kernel(x):
            if x > 0:
                return x
            return -x
    """),
    "FAULT_POINT_DRIFT": ("seed.py", """
        from flink_tpu import faults

        def save():
            faults.fire("seed.not.registered")
    """),
    "FAULT_POINT_UNFIRED": ("seed.py", """
        KNOWN_FAULT_POINTS = frozenset(("seed.never.fired",))
    """),
    "CONFIG_KEY_DRIFT": ("seed.py", """
        def f(config):
            return config.get_raw("seed.key.typo")
    """),
    "CONFIG_OPTION_DUP": ("seed.py", """
        X = ConfigOption("seed.dup.key", 1, "first")
        Y = ConfigOption("seed.dup.key", 2, "second")
    """),
    "METRIC_NAME_INVALID": ("seed.py", """
        def register(group):
            group.counter("seedCamelCase")
    """),
    "HOSTPOOL_SHARED_WRITE": ("seed.py", """
        def drive(pool, chunks):
            done = 0
            def task(c):
                nonlocal done
                done += 1
            pool.run_tasks([lambda c=c: task(c) for c in chunks])
    """),
    "DURABILITY_SEAM_BYPASS": ("flink_tpu/log/topic.py", """
        def save(path, data):
            with open(path, "w") as f:
                f.write(data)
    """),
    "LOCK_ORDER_CYCLE": ("seed.py", """
        import threading

        MU_A = threading.Lock()
        MU_B = threading.Lock()

        def forward():
            with MU_A:
                with MU_B:
                    pass

        def backward():
            with MU_B:
                with MU_A:
                    pass
    """),
    "FENCE_UNVERIFIED_PUBLISH": ("seed.py", """
        class Cleaner:
            def __init__(self, store, lease):
                self.store = store
                self.lease = lease

            def heartbeat(self):
                self.lease.verify()

            def publish(self):
                self.store.write_atomic("status.json", b"{}")
    """),
}


class TestLintCatalogSeeds:
    """Every registered rule has a seeded violation that fires through
    lint_paths — the catalog and the engine cannot drift apart, and a
    rule silently dropped from _lint_graph fails here, not in prod."""

    def _run_seed(self, tmp_path, rule):
        relpath, src = LINT_SEEDS[rule]
        p = tmp_path / relpath
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        return lint_paths([relpath], root=str(tmp_path))

    @pytest.mark.parametrize("rule", sorted(LINT_SEEDS))
    def test_seed_trips_exactly_its_rule(self, tmp_path, rule):
        fs = self._run_seed(tmp_path, rule)
        assert rules_of(fs) == [rule], [f.render() for f in fs]
        assert fs[0].fix  # every finding ships an actionable fix hint

    def test_every_catalog_rule_has_a_seed(self):
        assert set(LINT_SEEDS) == {r for r, *_ in LINT_CATALOG}

    def test_catalog_planes_are_complete(self):
        from flink_tpu.analysis.pylints import LINT_PLANES

        assert set(LINT_PLANES) == set(LINT_SEEDS)
        assert {LINT_PLANES[r] for r in (
            "LOCK_ORDER_CYCLE", "FENCE_UNVERIFIED_PUBLISH",
            "DURABILITY_SEAM_BYPASS", "FAULT_POINT_UNFIRED")} == {
            "locking", "fencing", "durability", "registry"}


# -- interprocedural tracer taint -------------------------------------------

class TestInterproceduralTracer:
    """PR 19 tentpole: taint follows traced ARGUMENTS through resolved
    call edges to arbitrary depth — the helper-extraction refactor that
    used to launder a host round-trip out of sight of the lint."""

    def test_host_call_two_helpers_deep(self):
        fs = lint("""
            import jax

            def convert(v):
                return float(v)

            def relay(v):
                return convert(v)

            @jax.jit
            def kernel(x):
                return relay(x)
        """)
        assert rules_of(fs) == ["TRACER_HOST_CALL"]
        assert "helper 'convert'" in fs[0].message
        assert "kernel" in fs[0].message

    def test_branch_inside_method_helper(self):
        fs = lint("""
            import jax

            class Op:
                def decide(self, v):
                    if v > 0:
                        return v
                    return -v

                def build(self):
                    @jax.jit
                    def kernel(x):
                        return self.decide(x)
                    return kernel
        """)
        assert rules_of(fs) == ["TRACER_BRANCH"]
        assert "helper 'decide'" in fs[0].message

    def test_static_actual_does_not_taint_the_helper(self):
        # only x.shape[0] (static under tracing) flows in — the
        # helper's branch is host-side control flow on a concrete int
        fs = lint("""
            import jax

            def pick(n):
                if n > 4:
                    return 4
                return n

            @jax.jit
            def kernel(x):
                return x[:pick(x.shape[0])]
        """)
        assert fs == []

    def test_helper_rebind_clears_taint_before_host_call(self):
        fs = lint("""
            import jax

            def convert(v):
                v = 3
                return float(v)

            @jax.jit
            def kernel(x):
                return convert(x)
        """)
        assert fs == []


# -- interprocedural hostpool writes ----------------------------------------

class TestInterproceduralHostpool:
    """PR 19 tentpole: the shared-write walk follows resolved calls out
    of the submitted closure, binding-type lock recognition included."""

    def test_unlocked_write_two_call_hops_deep(self):
        fs = lint("""
            class Op:
                def absorb(self, chunks):
                    def task(c):
                        return self._merge(c)
                    self.pool.run_tasks(
                        [lambda c=c: task(c) for c in chunks])

                def _merge(self, c):
                    return self._commit(len(c))

                def _commit(self, n):
                    self.total += n   # racy RMW, two hops from the task
        """)
        assert rules_of(fs) == ["HOSTPOOL_SHARED_WRITE"]
        assert "self.total" in fs[0].message

    def test_binding_typed_lock_without_lock_in_the_name(self):
        """The `with self._mu:` fix: a guard is recognized by its
        BINDING (threading.Lock assigned in __init__), not by 'lock'
        appearing in the attribute name."""
        fs = lint("""
            import threading

            class Op:
                def __init__(self, pool):
                    self.pool = pool
                    self.total = 0
                    self._mu = threading.Lock()

                def absorb(self, chunks):
                    def task(c):
                        with self._mu:
                            self.total += len(c)
                    self.pool.run_tasks(
                        [lambda c=c: task(c) for c in chunks])
        """)
        assert fs == []

    def test_binding_typed_lock_guards_the_callee_too(self):
        fs = lint("""
            import threading

            class Op:
                def __init__(self, pool):
                    self.pool = pool
                    self.total = 0
                    self._mu = threading.RLock()

                def absorb(self, chunks):
                    def task(c):
                        self._merge(c)
                    self.pool.run_tasks(
                        [lambda c=c: task(c) for c in chunks])

                def _merge(self, c):
                    with self._mu:
                        self.total += len(c)
        """)
        assert fs == []

    def test_shared_formal_rebind_and_tuple_unpack_are_local(self):
        """Python scoping regression (the ops/session.py FP class):
        a bare rebind of a shared-bound formal is LOCAL, and
        tuple-unpack targets bind locals — neither mutates the
        caller's object."""
        fs = lint("""
            class Op:
                def absorb(self, chunks):
                    def task(c):
                        return self._count(c)
                    self.pool.run_tasks(
                        [lambda c=c: task(c) for c in chunks])

                def _count(self, c):
                    c = c[1:]
                    lo, hi = 0, len(c)
                    lo += hi
                    return lo
        """)
        assert fs == []

    def test_mutation_through_shared_formal_still_fires(self):
        # the flip side of the scoping rule: a subscript store THROUGH
        # the shared formal reaches the caller's object
        fs = lint("""
            class Op:
                def absorb(self, chunks):
                    def task(c):
                        self._count(c, self.totals)
                    self.pool.run_tasks(
                        [lambda c=c: task(c) for c in chunks])

                def _count(self, c, totals):
                    totals["n"] = len(c)
        """)
        assert rules_of(fs) == ["HOSTPOOL_SHARED_WRITE"]


# -- reverse registry drift: unfired fault points ---------------------------

class TestFaultPointUnfired:
    """PR 19 satellite: a registered point with no fire site is a dead
    chaos target — warn, with resolution through string literals,
    module constants, and one parameter-forwarding hop."""

    def test_never_fired_point_warns_at_the_registry_line(self):
        fs = lint("""
            KNOWN_FAULT_POINTS = frozenset((
                "seed.never.fired",
            ))
        """)
        assert rules_of(fs) == ["FAULT_POINT_UNFIRED"]
        assert fs[0].severity == "warn"
        assert "seed.never.fired" in fs[0].message

    def test_constant_and_param_forwarded_fires_resolve(self):
        # fs.fsync fires through a module constant; fs.rename through
        # one parameter-forwarding hop — both real registry names, so
        # FAULT_POINT_DRIFT stays quiet too
        fs = lint("""
            from flink_tpu import faults

            KNOWN_FAULT_POINTS = frozenset(("fs.fsync", "fs.rename"))
            FSYNC_POINT = "fs.fsync"

            def fire_it(point):
                faults.fire(point)

            def go():
                faults.fire(FSYNC_POINT)
                fire_it("fs.rename")
        """)
        assert fs == []

    def test_allowlist_suppresses_the_warning(self):
        fs = lint("""
            KNOWN_FAULT_POINTS = frozenset(("seed.allowed.quiet",))
            UNFIRED_ALLOWLIST = frozenset(("seed.allowed.quiet",))
        """)
        assert fs == []

    def test_no_registry_in_the_linted_set_is_quiet(self):
        # linting a subtree that fires points but does not DEFINE the
        # registry must not claim every un-fired registry entry
        fs = lint("""
            from flink_tpu import faults

            def go():
                faults.fire("fs.fsync")
        """)
        assert fs == []


# -- durability seam (promoted from tests/test_architecture.py) -------------

class TestDurabilitySeamLint:
    """PR 19 satellite: the TestDurableWriteSeam scan is now the
    DURABILITY_SEAM_BYPASS rule — same construct set, same allowed
    residue, keyed off the module RELPATH."""

    def test_raw_open_and_os_replace_in_durable_module(self):
        src = """
            import os

            def save(path, data):
                with open(path, "w") as f:
                    f.write(data)
                os.replace(path + ".tmp", path)
        """
        fs = lint_source(textwrap.dedent(src), "flink_tpu/log/topic.py")
        assert rules_of(fs) == ["DURABILITY_SEAM_BYPASS"] * 2
        assert fs[0].severity == "error"
        assert "flink_tpu/log/topic.py" in fs[0].message

    def test_same_source_outside_the_durable_tier_is_quiet(self):
        src = """
            import os

            def save(path, data):
                with open(path, "w") as f:
                    f.write(data)
                os.replace(path + ".tmp", path)
        """
        assert lint_source(textwrap.dedent(src), "fixture.py") == []

    def test_lock_to_grave_rename_residue_is_exempt(self):
        # the documented local-lock-primitive residue: os.rename of
        # lock/lease bookkeeping files is never durable payload
        src = """
            import os

            def expire(lock_path, grave_path):
                os.rename(lock_path, grave_path)
        """
        assert lint_source(textwrap.dedent(src),
                           "flink_tpu/log/topic.py") == []

    def test_roster_matches_the_architecture_contract(self):
        from flink_tpu.analysis.pylints import DURABLE_MODULES

        assert "flink_tpu/log/topic.py" in DURABLE_MODULES
        assert "flink_tpu/checkpoint/storage.py" in DURABLE_MODULES
        assert "flink_tpu/state/lsm.py" in DURABLE_MODULES


# -- lock-order cycles ------------------------------------------------------

class TestLockOrderCycle:
    """PR 19 tentpole: ABBA detection over the acquisition-order graph,
    with call-hop edges and both witness paths named in the finding."""

    def test_direct_abba_names_both_paths(self):
        fs = lint("""
            import threading

            MU_A = threading.Lock()
            MU_B = threading.Lock()

            def forward():
                with MU_A:
                    with MU_B:
                        pass

            def backward():
                with MU_B:
                    with MU_A:
                        pass
        """)
        assert rules_of(fs) == ["LOCK_ORDER_CYCLE"]
        msg = fs[0].message
        assert "one path acquires" in msg
        assert "the opposite path acquires" in msg
        assert "forward" in msg and "backward" in msg

    def test_cycle_through_a_call_hop(self):
        # one leg nests directly; the other acquires the second lock
        # inside a CALLEE while holding the first
        fs = lint("""
            import threading

            class Store:
                def __init__(self):
                    self._index_mu = threading.Lock()
                    self._flush_mu = threading.Lock()

                def _seal(self):
                    with self._flush_mu:
                        pass

                def put(self):
                    with self._index_mu:
                        self._seal()

                def compact(self):
                    with self._flush_mu:
                        with self._index_mu:
                            pass
        """)
        assert rules_of(fs) == ["LOCK_ORDER_CYCLE"]
        assert "via the call" in fs[0].message

    def test_consistent_global_order_is_quiet(self):
        fs = lint("""
            import threading

            MU_A = threading.Lock()
            MU_B = threading.Lock()

            def one():
                with MU_A:
                    with MU_B:
                        pass

            def two():
                with MU_A:
                    with MU_B:
                        pass
        """)
        assert fs == []

    def test_rlock_reentry_is_not_a_self_edge(self):
        fs = lint("""
            import threading

            class Op:
                def __init__(self):
                    self._mu = threading.RLock()

                def outer(self):
                    with self._mu:
                        self.inner()

                def inner(self):
                    with self._mu:
                        pass
        """)
        assert fs == []


# -- fence discipline on leased publishers ----------------------------------

class TestFencePublish:
    """PR 19 tentpole: a fenced-record publication reachable from a
    leased class's public method with no verify()/renew on the path is
    a post-takeover write a deposed leaseholder could still make."""

    SEED = """
        class Cleaner:
            def __init__(self, store, lease):
                self.store = store
                self.lease = lease

            def heartbeat(self):
                self.lease.verify()

            def publish(self):
                self.store.write_atomic("status.json", b"{}")
    """

    def test_unverified_status_publish_fires(self):
        fs = lint(self.SEED)
        assert rules_of(fs) == ["FENCE_UNVERIFIED_PUBLISH"]
        assert fs[0].severity == "error"
        assert "status" in fs[0].message
        assert "Cleaner.publish()" in fs[0].message

    def test_verify_before_publish_is_quiet(self):
        fs = lint("""
            class Cleaner:
                def __init__(self, store, lease):
                    self.store = store
                    self.lease = lease

                def publish(self):
                    self.lease.verify()
                    self.store.write_atomic("status.json", b"{}")
        """)
        assert fs == []

    def test_verify_inside_a_called_helper_counts(self):
        # the fence gate may live in a private helper — the walk
        # threads the verified flag through resolved calls
        fs = lint("""
            class Cleaner:
                def __init__(self, store, lease):
                    self.store = store
                    self.lease = lease

                def _gate(self):
                    self.lease.verify()

                def publish(self):
                    self._gate()
                    self.store.write_atomic("marker.json", b"{}")
        """)
        assert fs == []

    def test_lease_record_publication_is_the_fence_itself(self):
        fs = lint("""
            class Cleaner:
                def __init__(self, store, lease):
                    self.store = store
                    self.lease = lease

                def heartbeat(self):
                    self.lease.verify()

                def claim(self):
                    self.store.put_if("cleaner.lease", b"{}", None)
        """)
        assert fs == []

    def test_unleased_class_is_out_of_scope(self):
        # no self.<attr>.verify() signature anywhere: the class holds
        # no epoch-fenced lease, so its publications are unconstrained
        fs = lint("""
            class Writer:
                def __init__(self, store):
                    self.store = store

                def publish(self):
                    self.store.write_atomic("status.json", b"{}")
        """)
        assert fs == []
