"""Repo AST-lint suite (flink_tpu/analysis/pylints.py): fixture
sources with deliberate tracer leaks prove every lint fires at the
right line, and trace-static idioms (shape reads, len(), `is None`,
static_argnums) prove it stays quiet — the false-positive budget of
the dogfood gate is ZERO, so the negatives matter as much as the
positives (tier-1)."""
import textwrap

import pytest

from flink_tpu.analysis.pylints import (
    DEFAULT_LINT_PATHS,
    LINT_RULES,
    lint_paths,
    lint_source,
)

pytestmark = pytest.mark.analysis


def lint(src):
    return lint_source(textwrap.dedent(src), "fixture.py")


def rules_of(findings):
    return [f.rule for f in findings]


# -- tracer leaks: host conversions -----------------------------------------

class TestTracerHostCall:
    def test_float_on_traced_param(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x):
                return float(x)
        """)
        assert rules_of(fs) == ["TRACER_HOST_CALL"]
        assert fs[0].line == 6
        assert fs[0].severity == "error"
        assert "kernel" in fs[0].message

    def test_np_asarray_one_assignment_hop(self):
        fs = lint("""
            import jax
            import numpy as np

            @jax.jit
            def kernel(x):
                y = x * 2
                return np.asarray(y)
        """)
        assert rules_of(fs) == ["TRACER_HOST_CALL"]
        assert "np.asarray" in fs[0].message

    def test_item_method_on_traced(self):
        fs = lint("""
            from jax import jit

            @jit
            def kernel(x):
                return x.sum().item()
        """)
        assert rules_of(fs) == ["TRACER_HOST_CALL"]

    def test_reassignment_clears_taint(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x):
                x = 3
                return float(x)
        """)
        assert fs == []

    def test_untainted_conversion_is_fine(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x, n):
                return x * int(x.shape[0])
        """)
        assert fs == []


# -- tracer leaks: host control flow ----------------------------------------

class TestTracerBranch:
    def test_if_on_traced_value(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x):
                if x > 0:
                    return x
                return -x
        """)
        assert rules_of(fs) == ["TRACER_BRANCH"]
        assert fs[0].line == 6

    def test_while_and_ternary(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x):
                while x > 0:
                    x = x - 1
                return x if x > 0 else -x
        """)
        assert rules_of(fs) == ["TRACER_BRANCH", "TRACER_BRANCH"]

    def test_range_over_traced_trip_count(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x, n):
                for i in range(n):
                    x = x + i
                return x
        """)
        assert rules_of(fs) == ["TRACER_BRANCH"]
        assert "range()" in fs[0].message

    def test_static_idioms_stay_quiet(self):
        # shape/ndim/dtype/size reads, len(), `is None`, `in` — all
        # static under tracing; flagging any of them would poison the
        # dogfood gate with false positives
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x, data):
                if x.shape[0] > 4:
                    x = x[:4]
                if x.ndim == 2:
                    x = x.sum(0)
                if len(data) > 1:
                    x = x * 2
                if x is None:
                    return x
                if "col" in data:
                    x = x + 1
                for i in range(x.shape[0]):
                    x = x + i
                return x
        """)
        assert fs == []

    def test_static_argnums_excludes_param(self):
        fs = lint("""
            import jax
            from functools import partial

            @partial(jax.jit, static_argnums=(1,))
            def kernel(x, n):
                if n > 4:
                    return x[:n]
                return x
        """)
        assert fs == []

    def test_static_argnames_excludes_param(self):
        fs = lint("""
            import jax

            @partial(jax.jit, static_argnames=("n",))
            def kernel(x, n):
                return x[:n] if n > 4 else x
        """)
        assert fs == []

    def test_nested_def_params_shadow_taint(self):
        fs = lint("""
            import jax

            @jax.jit
            def kernel(x):
                def helper(x):
                    # this x is the helper's own (concrete) parameter
                    return float(x)
                return x
        """)
        assert fs == []

    def test_jit_call_form_on_local_def(self):
        fs = lint("""
            import jax

            def step(x):
                if x > 0:
                    return x
                return -x

            fn = jax.jit(step)
        """)
        assert rules_of(fs) == ["TRACER_BRANCH"]

    def test_jit_of_shard_map_call_form(self):
        fs = lint("""
            import jax
            from flink_tpu.utils.jaxcompat import shard_map

            def shard(x):
                return bool(x.sum())

            fn = jax.jit(shard_map(shard, mesh=None, in_specs=(),
                                   out_specs=()))
        """)
        assert rules_of(fs) == ["TRACER_HOST_CALL"]

    def test_plain_function_is_not_a_kernel(self):
        fs = lint("""
            def host_side(x):
                if x > 0:
                    return float(x)
                return x
        """)
        assert fs == []


# -- registry drift ---------------------------------------------------------

class TestRegistryDrift:
    def test_unknown_fault_point_literal(self):
        fs = lint("""
            from flink_tpu import faults

            def save():
                faults.fire("checkpoint.storage.wrte")
        """)
        assert rules_of(fs) == ["FAULT_POINT_DRIFT"]
        assert "checkpoint.storage.wrte" in fs[0].message

    def test_known_fault_point_is_quiet(self):
        fs = lint("""
            from flink_tpu import faults

            def save():
                faults.fire("checkpoint.storage.write")
        """)
        assert fs == []

    def test_undeclared_get_raw_key(self):
        fs = lint("""
            def f(config):
                return config.get_raw("execution.checkpontng.interval")
        """)
        assert rules_of(fs) == ["CONFIG_KEY_DRIFT"]

    def test_dynamic_prefix_key_is_declared(self):
        fs = lint("""
            def f(config):
                return config.get_raw("test.n-batches", 6)
        """)
        assert fs == []

    def test_configuration_dict_literal_keys(self):
        fs = lint("""
            from flink_tpu.config import Configuration

            conf = Configuration({
                "state.num-key-shards": 8,
                "state.num-key-shrads": 8,
            })
        """)
        assert rules_of(fs) == ["CONFIG_KEY_DRIFT"]
        assert "shrads" in fs[0].message

    def test_metric_name_grammar(self):
        fs = lint("""
            def register(group):
                group.counter("checkpointCount")
                group.counter("checkpoint_count")
        """)
        assert rules_of(fs) == ["METRIC_NAME_INVALID"]
        assert fs[0].severity == "warn"


class TestHostpoolSharedWrite:
    """The concurrency lint plane: shared-mutable-state writes inside
    closures submitted to HostPool.run_tasks without a lock/merge
    discipline — the exact race shape PR 5 fixed by hand in
    obs/metrics.py (Counter's `self._v += n`)."""

    def test_unlocked_counter_in_lambda_list_fires(self):
        fs = lint("""
            class Op:
                def __init__(self, pool):
                    self.pool = pool
                    self.total = 0

                def absorb(self, chunks):
                    self.pool.run_tasks(
                        [lambda c=c: self._bump(c) for c in chunks])

                def _bump(self, c):
                    pass

            def drive(pool, chunks, counter):
                def task(c):
                    counter["n"] += len(c)   # racy subscript write
                    return len(c)
                pool.run_tasks([lambda c=c: task(c) for c in chunks])
        """)
        assert rules_of(fs) == ["HOSTPOOL_SHARED_WRITE"]
        assert fs[0].severity == "warn"
        assert "counter" in fs[0].message and fs[0].fix

    def test_unlocked_self_attribute_fires_one_call_hop_deep(self):
        fs = lint("""
            class Op:
                def absorb(self, chunks):
                    def merge(c):
                        self.total += len(c)   # racy attribute RMW
                    self.pool.run_tasks(
                        [lambda c=c: merge(c) for c in chunks])
        """)
        assert rules_of(fs) == ["HOSTPOOL_SHARED_WRITE"]
        assert "self.total" in fs[0].message

    def test_nonlocal_accumulator_through_append_fires(self):
        fs = lint("""
            def drive(pool, chunks):
                done = 0
                tasks = []
                for c in chunks:
                    def task(c=c):
                        nonlocal done
                        done += 1
                    tasks.append(task)
                pool.run_tasks(tasks)
        """)
        assert rules_of(fs) == ["HOSTPOOL_SHARED_WRITE"]

    def test_named_def_bound_through_list_literal_fires(self):
        """Review regression: `tasks = [merge]` (a NAMED local def, not
        a lambda) must resolve to the def — the obs/metrics.py race
        class must not escape through a plain list binding."""
        fs = lint("""
            class Op:
                def absorb(self, chunks):
                    def merge():
                        self.total += 1
                    tasks = [merge]
                    self.pool.run_tasks(tasks)
        """)
        assert rules_of(fs) == ["HOSTPOOL_SHARED_WRITE"]

    def test_annotated_and_walrus_locals_are_silent(self):
        """Review regression: `n: int = 0` and `(n := ...)` bind LOCALS
        — they must never read as shared writes."""
        fs = lint("""
            def drive(pool, chunks):
                def task(c):
                    n: int = 0
                    n += len(c)
                    if (m := len(c)) > 2:
                        m += 1
                    return n + m
                pool.run_tasks([lambda c=c: task(c) for c in chunks])
        """)
        assert fs == []

    def test_lock_guarded_write_is_silent(self):
        fs = lint("""
            import threading

            class Op:
                def __init__(self, pool):
                    self.pool = pool
                    self.total = 0
                    self._lock = threading.Lock()

                def absorb(self, chunks):
                    def task(c):
                        with self._lock:
                            self.total += len(c)
                        return len(c)
                    self.pool.run_tasks(
                        [lambda c=c: task(c) for c in chunks])
        """)
        assert fs == []

    def test_merge_discipline_returning_partials_is_silent(self):
        fs = lint("""
            def drive(pool, chunks):
                parts = pool.run_tasks(
                    [lambda c=c: sum(c) for c in chunks])
                total = sum(parts)   # combine on the CALLER: fine
                return total
        """)
        assert fs == []

    def test_local_writes_inside_tasks_are_silent(self):
        fs = lint("""
            def drive(pool, chunks):
                def task(c):
                    acc = {}
                    acc["n"] = len(c)     # local dict: per-task state
                    acc["n"] += 1
                    return acc
                pool.run_tasks([lambda c=c: task(c) for c in chunks])
        """)
        assert fs == []

    def test_obs_metrics_as_shipped_is_silent(self):
        """The PR 5 fix itself (lock-guarded primitives) must never be
        re-flagged — and neither may the shipped pool clients."""
        import os

        from flink_tpu.analysis.pylints import repo_root

        for rel in ("flink_tpu/obs/metrics.py", "flink_tpu/state/spill.py",
                    "flink_tpu/ops/session.py"):
            with open(os.path.join(repo_root(), rel)) as f:
                fs = [x for x in lint_source(f.read(), rel)
                      if x.rule == "HOSTPOOL_SHARED_WRITE"]
            assert fs == [], f"{rel}: {[x.render() for x in fs]}"


class TestLintPaths:
    def test_duplicate_option_declaration_across_files(self, tmp_path):
        a = tmp_path / "a.py"
        b = tmp_path / "b.py"
        a.write_text('X = ConfigOption("dup.key", 1, "first")\n')
        b.write_text('Y = ConfigOption("dup.key", 2, "second")\n')
        fs = lint_paths([str(a), str(b)], root=str(tmp_path))
        assert rules_of(fs) == ["CONFIG_OPTION_DUP"]
        assert fs[0].file == "b.py"
        assert "a.py:1" in fs[0].message

    def test_walks_directories_and_skips_pycache(self, tmp_path):
        pkg = tmp_path / "pkg"
        (pkg / "__pycache__").mkdir(parents=True)
        (pkg / "__pycache__" / "junk.py").write_text("syntax error ][")
        (pkg / "mod.py").write_text(
            "import jax\n\n@jax.jit\ndef k(x):\n    return float(x)\n")
        fs = lint_paths(["pkg"], root=str(tmp_path))
        assert rules_of(fs) == ["TRACER_HOST_CALL"]
        assert fs[0].file == "pkg/mod.py"

    def test_nonexistent_path_fails_loudly(self, tmp_path):
        # a typo'd CI path silently linting nothing would leave the
        # drift gate green while checking nothing
        with pytest.raises(ValueError, match="does not exist"):
            lint_paths(["no/such/dir"], root=str(tmp_path))

    def test_rule_table_covers_every_emitted_rule(self):
        assert {r for r, _ in LINT_RULES} >= {
            "TRACER_HOST_CALL", "TRACER_BRANCH", "FAULT_POINT_DRIFT",
            "CONFIG_KEY_DRIFT", "CONFIG_OPTION_DUP",
            "METRIC_NAME_INVALID"}

    def test_default_paths_cover_the_shipped_surface(self):
        assert "flink_tpu" in DEFAULT_LINT_PATHS
        assert "bench.py" in DEFAULT_LINT_PATHS
