"""Tracing spans (checkpoint/restore/recovery), thread sampling, and
the adaptive microbatch debloater (ref: SURVEY §6.1 Span/TraceReporter,
flame graphs; §3.6 BufferDebloater)."""
import numpy as np
import pytest

from flink_tpu.config import Configuration
from flink_tpu.obs.metrics import Histogram
from flink_tpu.obs.tracing import Tracer, sample_threads, tracer


class TestTracer:
    def test_span_lifecycle_and_reporter(self):
        t = Tracer()
        seen = []
        t.add_reporter(seen.append)
        with t.span("checkpoint.freeze", checkpoint_id=7) as sp:
            sp.set("bytes", 123)
        spans = t.spans("checkpoint")
        assert len(spans) == 1
        s = spans[0]
        assert s["name"] == "checkpoint.freeze"
        assert s["attributes"] == {"checkpoint_id": 7, "bytes": 123}
        assert s["duration_ms"] is not None and s["duration_ms"] >= 0
        assert seen and seen[0].name == "checkpoint.freeze"

    def test_span_records_error(self):
        t = Tracer()
        with pytest.raises(ValueError):
            with t.span("x"):
                raise ValueError("boom")
        assert "ValueError" in t.spans()[0]["attributes"]["error"]

    def test_ring_bounded(self):
        t = Tracer(capacity=8)
        for i in range(20):
            with t.span(f"s{i}"):
                pass
        assert len(t.spans()) == 8

    def test_checkpoint_emits_spans_end_to_end(self, tmp_path):
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sinks import CollectSink
        from flink_tpu.api.windowing import TumblingEventTimeWindows

        tracer.clear()
        rng = np.random.default_rng(0)
        ts = np.sort(rng.integers(0, 4000, 1000)).astype(np.int64)
        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 4, "state.slots-per-shard": 16,
            "pipeline.microbatch-size": 250,
            "execution.checkpointing.dir": str(tmp_path),
            "execution.checkpointing.interval": 1,
        }))
        sink = CollectSink()
        (env.from_collection({"k": rng.integers(0, 5, 1000).astype(np.int64)},
                             ts, batch_size=250)
         .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
         .add_sink(sink))
        env.execute("traced")
        freezes = tracer.spans("checkpoint.freeze")
        persists = tracer.spans("checkpoint.persist")
        assert freezes and persists
        assert all(s["duration_ms"] is not None for s in freezes + persists)

    def test_sample_threads_collapsed_stacks(self):
        import threading, time

        stop = threading.Event()

        def busy():
            while not stop.is_set():
                time.sleep(0.001)

        th = threading.Thread(target=busy, daemon=True)
        th.start()
        try:
            out = sample_threads(seconds=0.2, hz=50)
            assert out["samples"] > 0
            assert any("busy@" in stack for stack in out["stacks"])
        finally:
            stop.set()


class TestHistogramRecent:
    def test_quantile_recent_window(self):
        h = Histogram(size=64)
        for _ in range(50):
            h.update(1000.0)
        for _ in range(16):
            h.update(10.0)
        assert h.quantile_recent(0.99, window=16) == pytest.approx(10.0)
        assert h.quantile(0.5) == pytest.approx(1000.0)


class TestDebloater:
    def _run(self, conf_extra):
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sinks import CollectSink
        from flink_tpu.api.windowing import TumblingEventTimeWindows

        rng = np.random.default_rng(1)
        n = 120_000
        ts = np.sort(rng.integers(0, 60_000, n)).astype(np.int64)
        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 4, "state.slots-per-shard": 16,
            "pipeline.microbatch-size": 20_000, **conf_extra}))
        sink = CollectSink()
        (env.from_collection({"k": rng.integers(0, 5, n).astype(np.int64)},
                             ts, batch_size=20_000)
         .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
         .add_sink(sink))
        res = env.execute("debloat")
        return res, sink

    def test_off_by_default_single_batches(self):
        res, sink = self._run({})
        assert res.metrics["batches"] == 6  # source batches pass whole

    def test_target_rechunk_exact_results(self):
        res_a, sink_a = self._run({})
        # an absurdly low target drives the chunk down — results must
        # stay exactly equal regardless of how ingest re-chunks
        res_b, sink_b = self._run({"pipeline.target-latency": 1})
        key = lambda rows: sorted(
            (int(r["key"]), int(r["window_end"]), int(r["count"]))
            for r in rows)
        assert key(sink_a.rows) == key(sink_b.rows)

    def test_control_loop_halves_and_regrows(self):
        """Deterministic unit drive of the BufferDebloater control law:
        overshoot halves the chunk (floored), undershoot regrows it."""
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.graph.compiler import compile_job
        from flink_tpu.runtime.driver import Driver
        from flink_tpu.api.windowing import TumblingEventTimeWindows
        from flink_tpu.api.sinks import CollectSink

        env = StreamExecutionEnvironment(Configuration({
            "pipeline.target-latency": 100}))
        ts = np.arange(100, dtype=np.int64)
        (env.from_collection({"k": np.zeros(100, np.int64)}, ts)
         .key_by("k").window(TumblingEventTimeWindows.of(10)).count()
         .add_sink(CollectSink()))
        d = Driver(compile_job(env._transforms, env.config,
                               env._watermark_strategy), env.config)
        d._debloat_min = 4
        data = {"k": np.arange(32, dtype=np.int64)}
        ts32 = np.arange(32, dtype=np.int64)

        # first batch seeds the chunk at the source batch size
        out = list(d._debloat_split(data, ts32))
        assert len(out) == 1 and d._debloat_chunk == 32

        # overshoot: p99 of recent samples above target -> halve
        for _ in range(4):
            d._lat_hist.update(500.0)
        d._debloat_adjust()
        assert d._debloat_chunk == 16
        out = list(d._debloat_split(data, ts32))
        assert [len(t) for _, t in out] == [16, 16]
        # records preserved in order across chunks
        assert np.array_equal(
            np.concatenate([t for _, t in out]), ts32)

        # keep overshooting: floors at the minimum
        for _ in range(8):
            d._lat_hist.update(500.0)
            d._debloat_adjust()
        assert d._debloat_chunk == 4

        # deep undershoot: regrows 2x per step
        for _ in range(16):
            d._lat_hist.update(1.0)
        d._debloat_adjust()
        assert d._debloat_chunk == 8
