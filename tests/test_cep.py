"""CEP pattern matching (ref: flink-cep NFAITCase / CEPITCase patterns:
strict vs relaxed contiguity, within windows, non-overlapping matches)."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.cep import CEP, CepOperator, Pattern
from flink_tpu.config import Configuration
from flink_tpu.time.watermarks import WatermarkStrategy


def small_large_pattern(within=None):
    p = (Pattern.begin("small").where(lambda d: d["amount"] < 10)
         .followed_by("large").where(lambda d: d["amount"] > 500))
    return p.within(within) if within else p


def feed(op, keys, ts, amounts):
    op.process_batch(np.asarray(keys, np.int64), np.asarray(ts, np.int64),
                     {"amount": np.asarray(amounts, np.float64)})


def matches(op, a="small", b="large"):
    f = op.take_fired()
    if f is None:
        return []
    d = dict(f)
    return sorted(zip(map(int, d["key"]), map(int, d[f"{a}_ts"]),
                      map(int, d[f"{b}_ts"])))


class TestOperator:
    def test_relaxed_skips_intervening(self):
        op = CepOperator(small_large_pattern(), num_shards=4,
                         slots_per_shard=16)
        # small at 10, noise at 20/30, large at 40 -> one match
        feed(op, [1, 1, 1, 1], [10, 20, 30, 40], [5, 100, 200, 600])
        assert matches(op) == [(1, 10, 40)]

    def test_strict_next_requires_adjacency(self):
        p = (Pattern.begin("a").where(lambda d: d["amount"] < 10)
             .next("b").where(lambda d: d["amount"] > 500))
        op = CepOperator(p, num_shards=4, slots_per_shard=16)
        feed(op, [1, 1, 1], [10, 20, 30], [5, 100, 600])  # 100 breaks it
        assert matches(op, "a", "b") == []
        feed(op, [2, 2], [10, 20], [5, 600])              # adjacent: match
        assert matches(op, "a", "b") == [(2, 10, 20)]

    def test_strict_break_restarts_on_breaking_event(self):
        p = (Pattern.begin("a").where(lambda d: d["amount"] < 10)
             .next("b").where(lambda d: d["amount"] > 500))
        op = CepOperator(p, num_shards=4, slots_per_shard=16)
        # 5 (a), 3 (breaks strict b BUT matches a -> restart), 600 (b)
        feed(op, [1, 1, 1], [10, 20, 30], [5, 3, 600])
        assert matches(op, "a", "b") == [(1, 20, 30)]

    def test_within_expires_partial(self):
        op = CepOperator(small_large_pattern(within=1000), num_shards=4,
                         slots_per_shard=16)
        feed(op, [1, 1], [10, 2000], [5, 600])  # large too late
        assert matches(op) == []
        # fresh small then large inside the window
        feed(op, [1, 1], [3000, 3500], [5, 600])
        assert matches(op) == [(1, 3000, 3500)]

    def test_skip_past_last_no_overlap(self):
        op = CepOperator(small_large_pattern(), num_shards=4,
                         slots_per_shard=16)
        # s s L L: greedy earliest small matches first large; second
        # large has no remaining small partial (skip-past-last)
        feed(op, [1, 1, 1, 1], [10, 20, 30, 40], [5, 6, 600, 700])
        assert matches(op) == [(1, 10, 30)]

    def test_cross_batch_partials(self):
        op = CepOperator(small_large_pattern(), num_shards=4,
                         slots_per_shard=16)
        feed(op, [7], [100], [5])
        assert matches(op) == []
        feed(op, [7], [200], [900])
        assert matches(op) == [(7, 100, 200)]

    def test_many_keys_vectorized_vs_bruteforce(self):
        rng = np.random.default_rng(11)
        K, N = 200, 4000
        keys = rng.integers(0, K, N)
        ts = np.arange(N) * 3
        amounts = np.where(rng.random(N) < 0.2, rng.uniform(0, 9, N),
                           np.where(rng.random(N) < 0.1,
                                    rng.uniform(501, 900, N),
                                    rng.uniform(20, 400, N)))
        op = CepOperator(small_large_pattern(within=5000), num_shards=8,
                         slots_per_shard=64)
        got = []
        for c in range(0, N, 500):  # ragged batch boundaries
            feed(op, keys[c:c+500], ts[c:c+500], amounts[c:c+500])
            got += matches(op)

        # brute force per key, same documented semantics
        want = []
        state = {}  # key -> small_ts or None
        for k, t, a in zip(keys.tolist(), ts.tolist(), amounts.tolist()):
            st = state.get(k)
            if st is not None and t - st > 5000:
                st = None
            if st is None:
                if a < 10:
                    state[k] = t
            else:
                if a > 500:
                    want.append((k, st, t))
                    state[k] = None
        assert sorted(got) == sorted(want)

    def test_snapshot_restore_roundtrip(self):
        def mk():
            return CepOperator(small_large_pattern(), num_shards=4,
                               slots_per_shard=16)

        a = mk()
        feed(a, [1], [10], [5])
        b = mk()
        b.restore_state(a.snapshot_state())
        feed(b, [1], [20], [700])
        assert matches(b) == [(1, 10, 20)]


class TestRegressions:
    def test_missing_where_raises_at_build(self):
        p = (Pattern.begin("a").where(lambda d: d["amount"] < 10)
             .next("b"))  # where() forgotten
        with pytest.raises(ValueError, match="has no where"):
            CepOperator(p, num_shards=4, slots_per_shard=16)

    def test_cross_batch_out_of_order_drops_with_accounting(self):
        """An event timestamped before its key's processed frontier
        cannot be sequenced (no cross-batch buffering) — it must drop
        and COUNT, never weave into a backwards match."""
        op = CepOperator(small_large_pattern(), num_shards=4,
                         slots_per_shard=16)
        feed(op, [1], [200], [5])     # small at 200
        feed(op, [1], [100], [700])   # large BEFORE the frontier: late
        assert matches(op) == []
        assert op.late_records == 1
        feed(op, [1], [300], [700])   # in-order large still matches
        assert matches(op) == [(1, 200, 300)]


class TestCepE2E:
    def test_pattern_stream_pipeline(self):
        def gen(split, i):
            if i >= 3:
                return None
            data = [([1, 2, 1], [5.0, 800.0, 3.0]),
                    ([2, 1, 2], [4.0, 900.0, 2.0]),
                    ([1, 2, 2], [600.0, 700.0, 100.0])][i]
            return ({"acct": np.array(data[0], np.int64),
                     "amount": np.array(data[1], np.float64)},
                    np.arange(3, dtype=np.int64) + i * 10)

        env = StreamExecutionEnvironment(Configuration(
            {"pipeline.microbatch-size": 8,
             "state.num-key-shards": 4, "state.slots-per-shard": 16}))
        sink = CollectSink()
        stream = (env.from_source(GeneratorSource(gen),
                                  WatermarkStrategy.for_monotonous_timestamps())
                  .key_by("acct"))
        CEP.pattern(stream, small_large_pattern()).add_sink(sink)
        env.execute("cep-e2e")
        got = sorted((int(r["key"]), int(r["small_ts"]), int(r["large_ts"]))
                     for r in sink.rows)
        # acct 1: small@0, large@11; acct 2: first small@10, large@21
        assert got == [(1, 0, 11), (2, 10, 21)]
