"""CEP pattern matching (ref: flink-cep NFAITCase / CEPITCase patterns:
strict vs relaxed contiguity, within windows, non-overlapping matches)."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.cep import CEP, CepOperator, Pattern
from flink_tpu.config import Configuration
from flink_tpu.time.watermarks import WatermarkStrategy


def small_large_pattern(within=None):
    p = (Pattern.begin("small").where(lambda d: d["amount"] < 10)
         .followed_by("large").where(lambda d: d["amount"] > 500))
    return p.within(within) if within else p


def feed(op, keys, ts, amounts):
    op.process_batch(np.asarray(keys, np.int64), np.asarray(ts, np.int64),
                     {"amount": np.asarray(amounts, np.float64)})


def matches(op, a="small", b="large"):
    f = op.take_fired()
    if f is None:
        return []
    d = dict(f)
    return sorted(zip(map(int, d["key"]), map(int, d[f"{a}_ts"]),
                      map(int, d[f"{b}_ts"])))


class TestOperator:
    def test_relaxed_skips_intervening(self):
        op = CepOperator(small_large_pattern(), num_shards=4,
                         slots_per_shard=16)
        # small at 10, noise at 20/30, large at 40 -> one match
        feed(op, [1, 1, 1, 1], [10, 20, 30, 40], [5, 100, 200, 600])
        assert matches(op) == [(1, 10, 40)]

    def test_strict_next_requires_adjacency(self):
        p = (Pattern.begin("a").where(lambda d: d["amount"] < 10)
             .next("b").where(lambda d: d["amount"] > 500))
        op = CepOperator(p, num_shards=4, slots_per_shard=16)
        feed(op, [1, 1, 1], [10, 20, 30], [5, 100, 600])  # 100 breaks it
        assert matches(op, "a", "b") == []
        feed(op, [2, 2], [10, 20], [5, 600])              # adjacent: match
        assert matches(op, "a", "b") == [(2, 10, 20)]

    def test_strict_break_restarts_on_breaking_event(self):
        p = (Pattern.begin("a").where(lambda d: d["amount"] < 10)
             .next("b").where(lambda d: d["amount"] > 500))
        op = CepOperator(p, num_shards=4, slots_per_shard=16)
        # 5 (a), 3 (breaks strict b BUT matches a -> restart), 600 (b)
        feed(op, [1, 1, 1], [10, 20, 30], [5, 3, 600])
        assert matches(op, "a", "b") == [(1, 20, 30)]

    def test_within_expires_partial(self):
        op = CepOperator(small_large_pattern(within=1000), num_shards=4,
                         slots_per_shard=16)
        feed(op, [1, 1], [10, 2000], [5, 600])  # large too late
        assert matches(op) == []
        # fresh small then large inside the window
        feed(op, [1, 1], [3000, 3500], [5, 600])
        assert matches(op) == [(1, 3000, 3500)]

    def test_skip_past_last_no_overlap(self):
        op = CepOperator(small_large_pattern(), num_shards=4,
                         slots_per_shard=16)
        # s s L L: greedy earliest small matches first large; second
        # large has no remaining small partial (skip-past-last)
        feed(op, [1, 1, 1, 1], [10, 20, 30, 40], [5, 6, 600, 700])
        assert matches(op) == [(1, 10, 30)]

    def test_cross_batch_partials(self):
        op = CepOperator(small_large_pattern(), num_shards=4,
                         slots_per_shard=16)
        feed(op, [7], [100], [5])
        assert matches(op) == []
        feed(op, [7], [200], [900])
        assert matches(op) == [(7, 100, 200)]

    def test_many_keys_vectorized_vs_bruteforce(self):
        rng = np.random.default_rng(11)
        K, N = 200, 4000
        keys = rng.integers(0, K, N)
        ts = np.arange(N) * 3
        amounts = np.where(rng.random(N) < 0.2, rng.uniform(0, 9, N),
                           np.where(rng.random(N) < 0.1,
                                    rng.uniform(501, 900, N),
                                    rng.uniform(20, 400, N)))
        op = CepOperator(small_large_pattern(within=5000), num_shards=8,
                         slots_per_shard=64)
        got = []
        for c in range(0, N, 500):  # ragged batch boundaries
            feed(op, keys[c:c+500], ts[c:c+500], amounts[c:c+500])
            got += matches(op)

        # brute force per key, same documented semantics
        want = []
        state = {}  # key -> small_ts or None
        for k, t, a in zip(keys.tolist(), ts.tolist(), amounts.tolist()):
            st = state.get(k)
            if st is not None and t - st > 5000:
                st = None
            if st is None:
                if a < 10:
                    state[k] = t
            else:
                if a > 500:
                    want.append((k, st, t))
                    state[k] = None
        assert sorted(got) == sorted(want)

    def test_snapshot_restore_roundtrip(self):
        def mk():
            return CepOperator(small_large_pattern(), num_shards=4,
                               slots_per_shard=16)

        a = mk()
        feed(a, [1], [10], [5])
        b = mk()
        b.restore_state(a.snapshot_state())
        feed(b, [1], [20], [700])
        assert matches(b) == [(1, 10, 20)]


class TestRegressions:
    def test_missing_where_raises_at_build(self):
        p = (Pattern.begin("a").where(lambda d: d["amount"] < 10)
             .next("b"))  # where() forgotten
        with pytest.raises(ValueError, match="has no where"):
            CepOperator(p, num_shards=4, slots_per_shard=16)

    def test_cross_batch_out_of_order_drops_with_accounting(self):
        """An event timestamped before its key's processed frontier
        cannot be sequenced (no cross-batch buffering) — it must drop
        and COUNT, never weave into a backwards match."""
        op = CepOperator(small_large_pattern(), num_shards=4,
                         slots_per_shard=16)
        feed(op, [1], [200], [5])     # small at 200
        feed(op, [1], [100], [700])   # large BEFORE the frontier: late
        assert matches(op) == []
        assert op.late_records == 1
        feed(op, [1], [300], [700])   # in-order large still matches
        assert matches(op) == [(1, 200, 300)]


class TestCepE2E:
    def test_pattern_stream_pipeline(self):
        def gen(split, i):
            if i >= 3:
                return None
            data = [([1, 2, 1], [5.0, 800.0, 3.0]),
                    ([2, 1, 2], [4.0, 900.0, 2.0]),
                    ([1, 2, 2], [600.0, 700.0, 100.0])][i]
            return ({"acct": np.array(data[0], np.int64),
                     "amount": np.array(data[1], np.float64)},
                    np.arange(3, dtype=np.int64) + i * 10)

        env = StreamExecutionEnvironment(Configuration(
            {"pipeline.microbatch-size": 8,
             "state.num-key-shards": 4, "state.slots-per-shard": 16}))
        sink = CollectSink()
        stream = (env.from_source(GeneratorSource(gen),
                                  WatermarkStrategy.for_monotonous_timestamps())
                  .key_by("acct"))
        CEP.pattern(stream, small_large_pattern()).add_sink(sink)
        env.execute("cep-e2e")
        got = sorted((int(r["key"]), int(r["small_ts"]), int(r["large_ts"]))
                     for r in sink.rows)
        # acct 1: small@0, large@11; acct 2: first small@10, large@21
        assert got == [(1, 0, 11), (2, 10, 21)]


# ---------------------------------------------------------------------------
# Quantifiers: times(n), one_or_more, optional — property-tested against
# a SCALAR oracle implementing the same documented semantics
# (greedy loop, SKIP_PAST_LAST_EVENT, one partial per key).
# ---------------------------------------------------------------------------

def scalar_oracle(stages, within, events):
    """Per-key scalar engine over EXPANDED stages: the independent
    reference the vectorized rank-step engine is checked against.
    events: list of (key, ts, {field: value}) in arrival order.
    Returns list of (key, match_start, match_end). Covers negation:
    mid-pattern not_followed_by/not_next kills, and a trailing
    not_followed_by fires its absence matches both in-stream (an event
    past the deadline) and at end-of-stream (the final-watermark
    flush), with match_end = match_start + within."""
    S = len(stages)
    out = []
    by_key = {}
    trail_neg = stages[-1].negated
    for k, t, d in events:
        by_key.setdefault(k, []).append((t, d))
    for k, evs in by_key.items():
        evs.sort(key=lambda e: e[0])
        cur, cnt = 0, 0
        stage_ts = [None] * S
        for t, d in evs:
            def hit(i):
                return bool(stages[i].where(
                    {f: np.asarray([v]) for f, v in d.items()})[0])

            # trailing absence completes BEFORE the expiry reset (the
            # same age condition) — mirrors the engine's ordering
            if trail_neg and cur == S - 1 and t - stage_ts[0] > within:
                out.append((k, stage_ts[0], stage_ts[0] + within))
                cur, cnt = 0, 0
            if within is not None and cur > 0 and \
                    t - stage_ts[0] > within:
                cur, cnt = 0, 0
            sc = min(cur, S - 1)
            lp = stages[sc].loop and cur < S
            op_ = stages[sc].optional and cur < S
            ng = stages[sc].negated and cur < S
            ng_strict = ng and stages[sc].strict
            in_loop = lp and cnt > 0
            h = hit(sc) if cur < S else False
            hn = hit(cur + 1) if cur + 1 < S else False
            if lp and h:                       # A: loop enter/continue
                if cnt == 0:
                    stage_ts[cur] = t
                cnt += 1
            elif in_loop and not h and hn:     # B: loop exit
                stage_ts[cur + 1] = t
                cur += 2
            elif op_ and not h and hn:         # C: optional skip
                stage_ts[cur] = -1
                stage_ts[cur + 1] = t
                cur += 2
            elif ng and h and (ng_strict or not hn):  # N: kill
                if hit(0):                     # killer re-tests stage 0
                    stage_ts[0] = t
                    cur = 1
                else:
                    cur = 0
            elif ng and hn:                    # N: pass over (+2)
                stage_ts[sc] = -1
                stage_ts[cur + 1] = t
                cur += 2
            elif ng_strict and not hn:         # N: not_next spent (+1)
                stage_ts[sc] = -1
                cur += 1
            elif not lp and not ng and h:      # D: plain advance
                stage_ts[cur] = t
                cur += 1
            elif not h and not ng and \
                    stages[sc].strict and cur > 0:
                if hit(0):                     # E: strict restart
                    stage_ts[0] = t
                    cur = 1
                else:
                    cur = 0
            if cur >= S:
                out.append((k, stage_ts[0], t))
                cur, cnt = 0, 0
        if trail_neg and cur == S - 1:         # end-of-stream flush
            out.append((k, stage_ts[0], stage_ts[0] + within))
    return sorted(out)


def run_op(pattern, events):
    op = CepOperator(pattern, num_shards=8, slots_per_shard=64)
    keys = np.asarray([e[0] for e in events], np.int64)
    ts = np.asarray([e[1] for e in events], np.int64)
    fields = {f: np.asarray([e[2][f] for e in events])
              for f in events[0][2]}
    op.process_batch(keys, ts, fields)
    f = op.take_fired()
    if f is None:
        return [], op
    d = dict(f)
    return sorted(zip(map(int, d["key"]), map(int, d["match_start"]),
                      map(int, d["match_end"]))), op


class TestQuantifiers:
    def test_times_expands_and_matches(self):
        # small followed by exactly 2 larges
        p = (Pattern.begin("small").where(lambda d: d["amount"] < 10)
             .followed_by("large").where(lambda d: d["amount"] > 500)
             .times(2))
        events = [(1, 0, {"amount": 5}), (1, 10, {"amount": 600}),
                  (1, 20, {"amount": 700}), (1, 30, {"amount": 800})]
        got, op = run_op(p, events)
        assert got == [(1, 0, 20)]
        f_names = [s.name for s in p.stages]
        assert f_names == ["small", "large_1", "large_2"]

    def test_times_strict_consecutive(self):
        p = (Pattern.begin("a").where(lambda d: d["v"] == 1)
             .next("b").where(lambda d: d["v"] == 2).times(2))
        ok = [(1, 0, {"v": 1}), (1, 1, {"v": 2}), (1, 2, {"v": 2})]
        got, _ = run_op(p, ok)
        assert got == [(1, 0, 2)]
        broken = [(2, 0, {"v": 1}), (2, 1, {"v": 2}), (2, 2, {"v": 9}),
                  (2, 3, {"v": 2})]
        got, _ = run_op(p, broken)
        assert got == []

    def test_one_or_more_greedy_counts(self):
        p = (Pattern.begin("up").where(lambda d: d["v"] > 0)
             .one_or_more()
             .followed_by("down").where(lambda d: d["v"] < 0))
        events = [(1, 0, {"v": 1}), (1, 10, {"v": 2}), (1, 20, {"v": 3}),
                  (1, 30, {"v": -1})]
        op = CepOperator(p, num_shards=4, slots_per_shard=16)
        feed_events(op, events)
        f = dict(op.take_fired())
        assert list(map(int, f["up_count"])) == [3]
        assert list(map(int, f["up_ts"])) == [0]
        assert list(map(int, f["up_last_ts"])) == [20]
        assert list(map(int, f["down_ts"])) == [30]

    def test_optional_present_and_absent(self):
        p = (Pattern.begin("a").where(lambda d: d["v"] == 1)
             .followed_by("b").where(lambda d: d["v"] == 2).optional()
             .followed_by("c").where(lambda d: d["v"] == 3))
        present = [(1, 0, {"v": 1}), (1, 1, {"v": 2}), (1, 2, {"v": 3})]
        op = CepOperator(p, num_shards=4, slots_per_shard=16)
        feed_events(op, present)
        f = dict(op.take_fired())
        assert list(map(int, f["b_ts"])) == [1]
        absent = [(2, 0, {"v": 1}), (2, 1, {"v": 3})]
        op2 = CepOperator(p, num_shards=4, slots_per_shard=16)
        feed_events(op2, absent)
        f2 = dict(op2.take_fired())
        assert list(map(int, f2["b_ts"])) == [-1]
        assert list(map(int, f2["c_ts"])) == [1]

    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
    def test_property_vs_scalar_oracle(self, seed):
        """Random event streams over random quantified patterns: the
        vectorized engine must agree with the scalar oracle exactly."""
        rng = np.random.default_rng(seed)
        variant = seed % 3
        if variant == 0:
            p = (Pattern.begin("a").where(lambda d: d["v"] < 3)
                 .followed_by("b").where(lambda d: d["v"] >= 7).times(2)
                 .within(50))
        elif variant == 1:
            p = (Pattern.begin("a").where(lambda d: d["v"] < 3)
                 .one_or_more()
                 .followed_by("b").where(lambda d: d["v"] >= 7))
        else:
            p = (Pattern.begin("a").where(lambda d: d["v"] < 3)
                 .followed_by("b").where(lambda d: (d["v"] >= 3)
                                         & (d["v"] < 5)).optional()
                 .followed_by("c").where(lambda d: d["v"] >= 7))
        n = 400
        events = [(int(k), int(t), {"v": int(v)})
                  for k, t, v in zip(rng.integers(0, 12, n),
                                     np.sort(rng.integers(0, 3000, n)),
                                     rng.integers(0, 10, n))]
        # unique (key, ts) pairs: both engines sequence per key by ts
        seen = set()
        events = [e for e in events
                  if (e[0], e[1]) not in seen
                  and not seen.add((e[0], e[1]))]
        got, _ = run_op(p, events)
        want = scalar_oracle(p.stages, p.within_ms, events)
        assert got == want

    @pytest.mark.parametrize("build,msg", [
        (lambda: Pattern.begin("a").where(lambda d: d["v"] > 0)
         .one_or_more().stages, "trailing one_or_more"),
        (lambda: (Pattern.begin("a").where(lambda d: d["v"] > 0)
                  .followed_by("b").where(lambda d: d["v"] < 0)
                  .optional()).stages, "trailing optional"),
        (lambda: (Pattern.begin("a").where(lambda d: d["v"] > 0)
                  .optional()
                  .followed_by("b").where(lambda d: d["v"] < 0)).stages,
         "first stage"),
        (lambda: (Pattern.begin("a").where(lambda d: d["v"] > 0)
                  .one_or_more()
                  .next("b").where(lambda d: d["v"] < 0)).stages,
         "followed_by"),
        (lambda: Pattern.begin("a").where(lambda d: d["v"] > 0)
         .next("b").where(lambda d: d["v"] < 0).one_or_more(),
         "relaxed contiguity"),
    ])
    def test_invalid_quantifier_shapes_raise(self, build, msg):
        with pytest.raises(ValueError, match=msg):
            build()


def feed_events(op, events):
    keys = np.asarray([e[0] for e in events], np.int64)
    ts = np.asarray([e[1] for e in events], np.int64)
    fields = {f: np.asarray([e[2][f] for e in events])
              for f in events[0][2]}
    op.process_batch(keys, ts, fields)


class TestNoSkip:
    """after_match('NO_SKIP'): overlapping-match enumeration from the
    bounded per-key partial buffer (ref: AfterMatchSkipStrategy.noSkip
    + the SharedBuffer role, capped with loud overflow)."""

    @staticmethod
    def _run(pattern, keys, ts, fields=None):
        op = CepOperator(pattern, num_shards=4, slots_per_shard=64)
        op.process_batch(np.asarray(keys, np.int64),
                         np.asarray(ts, np.int64), fields or {})
        f = op.take_fired()
        if f is None:
            return []
        d = dict(f)
        return sorted(zip([int(x) for x in d["key"]],
                          [int(x) for x in d["match_start"]],
                          [int(x) for x in d["match_end"]]))

    @staticmethod
    def _oracle(stages, keys, ts, fields, within=None):
        """Independent scalar enumeration of the SAME semantics:
        per-key partial list; every event advances each live partial
        (greedy take; strict miss kills), and a stage-0 match spawns a
        new partial."""
        from collections import defaultdict
        parts = defaultdict(list)  # key -> list of [stage, [ts...]]
        out = []
        order = np.lexsort((ts, keys))
        for i in order:
            k, t = int(keys[i]), int(ts[i])
            ev = {f: v[i] for f, v in fields.items()}
            hits = [bool(np.asarray(st.where(
                {f: np.asarray([v]) for f, v in ev.items()}))[0])
                for st in stages]
            nxt = []
            for stage_i, tss in parts[k]:
                if within is not None and t - tss[0] > within:
                    continue  # expired partial dies
                if hits[stage_i]:
                    tss = tss + [t]
                    if stage_i + 1 == len(stages):
                        out.append((k, tss[0], t))
                        continue
                    nxt.append([stage_i + 1, tss])
                elif stages[stage_i].strict:
                    continue  # strict miss kills the partial
                else:
                    nxt.append([stage_i, tss])
            if hits[0]:
                if len(stages) == 1:
                    out.append((k, t, t))
                else:
                    nxt.append([1, [t]])
            parts[k] = nxt
        return sorted(out)

    def test_overlapping_matches_enumerated(self):
        # a a b with followed_by: BOTH partials complete on b
        p = (Pattern.begin("a").where(lambda d: d["v"] == 0)
             .followed_by("b").where(lambda d: d["v"] == 1)
             .after_match("NO_SKIP"))
        got = self._run(p, [1, 1, 1], [10, 20, 30],
                        {"v": np.array([0, 0, 1])})
        assert got == [(1, 10, 30), (1, 20, 30)]

    def test_strict_kills_only_that_partial(self):
        # a1 a2 b with next(): a1's partial dies on a2; a2's completes
        p = (Pattern.begin("a").where(lambda d: d["v"] == 0)
             .next("b").where(lambda d: d["v"] == 1)
             .after_match("NO_SKIP"))
        got = self._run(p, [1, 1, 1], [10, 20, 30],
                        {"v": np.array([0, 0, 1])})
        assert got == [(1, 20, 30)]

    def test_property_vs_oracle(self):
        rng = np.random.default_rng(11)
        p = (Pattern.begin("a").where(lambda d: d["v"] % 3 == 0)
             .followed_by("b").where(lambda d: d["v"] % 3 == 1)
             .followed_by("c").where(lambda d: d["v"] % 3 == 2)
             .within(40)
             .after_match("NO_SKIP"))
        keys = rng.integers(0, 5, 200)
        ts = np.sort(rng.integers(0, 400, 200))
        v = rng.integers(0, 9, 200)
        got = self._run(p, keys, ts, {"v": v})
        want = self._oracle(p.stages, keys, ts, {"v": v}, within=40)
        assert got == want
        assert len(got) > 0

    def test_overflow_is_loud(self):
        p = (Pattern.begin("a").where(lambda d: d["v"] >= 0)
             .followed_by("b").where(lambda d: d["v"] < 0)
             .after_match("NO_SKIP"))
        op = CepOperator(p, num_shards=4, slots_per_shard=64)
        with pytest.raises(RuntimeError, match="partial-buffer overflow"):
            # 9 consecutive stage-0 matches with no completions > cap 8
            op.process_batch(np.ones(9, np.int64),
                             np.arange(9, dtype=np.int64),
                             {"v": np.zeros(9, np.int64)})

    def test_quantifiers_refused(self):
        p = (Pattern.begin("a").where(lambda d: d["v"] == 0)
             .followed_by("b").where(lambda d: d["v"] == 1).one_or_more()
             .followed_by("c").where(lambda d: d["v"] == 2)
             .after_match("NO_SKIP"))
        with pytest.raises(NotImplementedError, match="NO_SKIP"):
            CepOperator(p, num_shards=4, slots_per_shard=64)

    def test_snapshot_restore_carries_partials(self):
        p = (Pattern.begin("a").where(lambda d: d["v"] == 0)
             .followed_by("b").where(lambda d: d["v"] == 1)
             .after_match("NO_SKIP"))

        def mk():
            return CepOperator(p, num_shards=4, slots_per_shard=64)

        a = mk()
        a.process_batch(np.array([1, 1]), np.array([10, 20]),
                        {"v": np.array([0, 0])})
        snap = a.snapshot_state()
        b = mk()
        b.restore_state(snap)
        b.process_batch(np.array([1]), np.array([30]),
                        {"v": np.array([1])})
        d = dict(b.take_fired())
        assert sorted(int(x) for x in d["match_start"]) == [10, 20]


class TestNoSkipOverflowAtomicity:
    """ADVICE r5: the NO_SKIP partial-buffer overflow used to raise
    MID-batch, after earlier rank steps had mutated p_stage/p_ts and
    appended matches — a caller catching the error and retrying would
    double-emit. The batch must now be atomic: overflow leaves the
    operator exactly as before the batch."""

    @staticmethod
    def _pattern():
        return (Pattern.begin("a").where(lambda d: d["v"] == 0)
                .followed_by("b").where(lambda d: d["v"] == 1)
                .after_match("NO_SKIP"))

    @staticmethod
    def _snap_view(op):
        s = op.snapshot_state()
        return {k: (v.copy() if hasattr(v, "copy") else v)
                for k, v in s.items() if k not in ("late_records",)}

    def test_overflow_rolls_back_partials_and_matches(self):
        import numpy as _np

        op = CepOperator(self._pattern(), num_shards=4, slots_per_shard=64)
        P = op.max_partials
        # seed SOME live partials, and one completable pair, in batch 1
        op.process_batch(np.array([1, 1, 2], np.int64),
                         np.array([10, 20, 30], np.int64),
                         {"v": np.array([0, 0, 0])})
        before = self._snap_view(op)
        # batch 2: key 1 floods past the partial budget (P more starts on
        # top of the 2 live ones) AND carries a completion for key 2 plus
        # earlier in-batch matches for key 1 — all must vanish on rollback
        n = P + 1
        keys = np.array([1] * n + [2], np.int64)
        ts = np.arange(100, 100 + n + 1, dtype=np.int64)
        vals = np.array([0] * n + [1])
        with pytest.raises(RuntimeError, match="partial-buffer overflow"):
            op.process_batch(keys, ts, {"v": vals})
        after = self._snap_view(op)
        for k, v in before.items():
            if isinstance(v, _np.ndarray):
                assert (after[k] == v).all(), f"state {k} mutated"
        assert op.take_fired() is None, "overflow leaked matches"

    def test_recovery_after_overflow_matches_fresh_run(self):
        """After a rolled-back overflow the operator keeps working: the
        subsequent (non-overflowing) batches produce exactly what a
        fresh operator fed only the good batches produces."""
        good1 = (np.array([1, 1], np.int64), np.array([10, 20], np.int64),
                 {"v": np.array([0, 0])})
        good2 = (np.array([1], np.int64), np.array([200], np.int64),
                 {"v": np.array([1])})

        op = CepOperator(self._pattern(), num_shards=4, slots_per_shard=64)
        op.process_batch(*good1)
        P = op.max_partials
        n = P + 1
        with pytest.raises(RuntimeError, match="partial-buffer overflow"):
            op.process_batch(np.array([1] * n, np.int64),
                             np.arange(100, 100 + n, dtype=np.int64),
                             {"v": np.zeros(n, np.int64)})
        op.process_batch(*good2)
        got = dict(op.take_fired())

        ref = CepOperator(self._pattern(), num_shards=4, slots_per_shard=64)
        ref.process_batch(*good1)
        ref.process_batch(*good2)
        want = dict(ref.take_fired())
        assert sorted(map(int, got["match_start"])) == sorted(
            map(int, want["match_start"]))
        assert sorted(map(int, got["match_end"])) == sorted(
            map(int, want["match_end"]))


# ---------------------------------------------------------------------------
# Negation: not_next / not_followed_by, trailing absence windows —
# property-tested against the extended scalar oracle above.
# ---------------------------------------------------------------------------

def run_op_neg(pattern, events):
    """run_op + the end-of-input watermark flush that fires pending
    trailing-absence matches (what the driver does at final)."""
    op = CepOperator(pattern, num_shards=8, slots_per_shard=64)
    feed_events(op, events)
    rows = []
    f = op.take_fired()
    if f is not None:
        rows.append(dict(f))
    d2 = dict(op.advance_watermark(op.final_watermark()))
    if len(d2["__ts__"]):
        rows.append(d2)
    out = []
    for d in rows:
        out += zip(map(int, d["key"]), map(int, d["match_start"]),
                   map(int, d["match_end"]))
    return sorted(out), op


class TestNegation:
    def test_not_followed_by_mid_pattern(self):
        p = (Pattern.begin("a").where(lambda d: d["v"] == 1)
             .not_followed_by("b").where(lambda d: d["v"] == 2)
             .followed_by("c").where(lambda d: d["v"] == 3))
        op = CepOperator(p, num_shards=4, slots_per_shard=16)
        # key 1: a, noise, c -> match; key 2: a, b, c -> killed
        feed_events(op, [(1, 10, {"v": 1}), (1, 20, {"v": 9}),
                         (1, 30, {"v": 3}),
                         (2, 10, {"v": 1}), (2, 20, {"v": 2}),
                         (2, 30, {"v": 3})])
        d = dict(op.take_fired())
        assert list(map(int, d["key"])) == [1]
        assert list(map(int, d["b_ts"])) == [-1]
        assert list(map(int, d["c_ts"])) == [30]

    def test_event_matching_both_counts_as_next_stage(self):
        # v==3 matches BOTH the forbidden (>=3) and the following
        # (==3) predicate: no forbidden event occurred strictly
        # between — the match completes
        p = (Pattern.begin("a").where(lambda d: d["v"] == 1)
             .not_followed_by("b").where(lambda d: d["v"] >= 3)
             .followed_by("c").where(lambda d: d["v"] == 3))
        got, _ = run_op_neg(p, [(1, 10, {"v": 1}), (1, 20, {"v": 3})])
        assert got == [(1, 10, 20)]

    def test_not_next_kills_on_adjacent_only(self):
        p = (Pattern.begin("a").where(lambda d: d["v"] == 1)
             .not_next("b").where(lambda d: d["v"] == 2)
             .followed_by("c").where(lambda d: d["v"] == 3))
        # key 1: forbidden event immediately next -> dead
        # key 2: benign event next, then c -> match
        # key 3: c itself is the next event (passes not_next AND c)
        got, _ = run_op_neg(p, [
            (1, 10, {"v": 1}), (1, 20, {"v": 2}), (1, 30, {"v": 3}),
            (2, 10, {"v": 1}), (2, 20, {"v": 9}), (2, 30, {"v": 3}),
            (3, 10, {"v": 1}), (3, 20, {"v": 3})])
        assert got == [(2, 10, 30), (3, 10, 20)]

    @staticmethod
    def _absence_pattern():
        return (Pattern.begin("a").where(lambda d: d["v"] == 1)
                .followed_by("b").where(lambda d: d["v"] == 2)
                .not_followed_by("c").where(lambda d: d["v"] == 3)
                .within(100))

    def test_trailing_absence_fires_on_watermark(self):
        op = CepOperator(self._absence_pattern(), num_shards=4,
                         slots_per_shard=16)
        # key 1: forbidden c inside the window -> killed
        # key 2: nothing after b -> fires when wm passes start+within
        feed_events(op, [(1, 10, {"v": 1}), (1, 20, {"v": 2}),
                         (1, 50, {"v": 3}),
                         (2, 10, {"v": 1}), (2, 20, {"v": 2})])
        assert op.take_fired() is None
        assert len(dict(op.advance_watermark(105))["__ts__"]) == 0
        d = dict(op.advance_watermark(110))
        assert sorted(zip(map(int, d["key"]), map(int, d["match_start"]),
                          map(int, d["match_end"]))) == [(2, 10, 110)]
        assert list(map(int, d["c_ts"])) == [-1]
        # idempotent: the partial was consumed
        assert len(dict(op.advance_watermark(500))["__ts__"]) == 0

    def test_trailing_absence_in_stream_completion(self):
        op = CepOperator(self._absence_pattern(), num_shards=4,
                         slots_per_shard=16)
        feed_events(op, [(7, 10, {"v": 1}), (7, 20, {"v": 2})])
        assert op.take_fired() is None
        # a later event of the SAME key past the deadline proves the
        # absence without any watermark movement
        feed_events(op, [(7, 300, {"v": 9})])
        d = dict(op.take_fired())
        assert list(zip(map(int, d["key"]),
                        map(int, d["match_end"]))) == [(7, 110)]

    def test_snapshot_restore_pending_absence(self):
        def mk():
            return CepOperator(self._absence_pattern(), num_shards=4,
                               slots_per_shard=16)

        a = mk()
        feed_events(a, [(1, 10, {"v": 1}), (1, 20, {"v": 2})])
        b = mk()
        b.restore_state(a.snapshot_state())
        d = dict(b.advance_watermark(200))
        assert list(map(int, d["match_start"])) == [10]
        assert list(map(int, d["match_end"])) == [110]

    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4, 5])
    def test_property_vs_scalar_oracle(self, seed):
        """Random keyed streams over negated patterns: the vectorized
        engine (including the end-of-input absence flush) must agree
        with the scalar oracle exactly."""
        rng = np.random.default_rng(100 + seed)
        variant = seed % 3
        if variant == 0:
            p = (Pattern.begin("a").where(lambda d: d["v"] < 3)
                 .not_followed_by("nb").where(lambda d: (d["v"] >= 3)
                                              & (d["v"] < 5))
                 .followed_by("c").where(lambda d: d["v"] >= 7)
                 .within(80))
        elif variant == 1:
            p = (Pattern.begin("a").where(lambda d: d["v"] < 3)
                 .not_next("nn").where(lambda d: (d["v"] == 5)
                                       | (d["v"] == 6))
                 .followed_by("c").where(lambda d: d["v"] >= 7)
                 .within(60))
        else:
            p = (Pattern.begin("a").where(lambda d: d["v"] < 3)
                 .followed_by("b").where(lambda d: d["v"] >= 7)
                 .not_followed_by("nc").where(lambda d: (d["v"] >= 3)
                                              & (d["v"] < 5))
                 .within(50))
        n = 400
        events = [(int(k), int(t), {"v": int(v)})
                  for k, t, v in zip(rng.integers(0, 12, n),
                                     np.sort(rng.integers(0, 3000, n)),
                                     rng.integers(0, 10, n))]
        seen = set()
        events = [e for e in events
                  if (e[0], e[1]) not in seen
                  and not seen.add((e[0], e[1]))]
        got, _ = run_op_neg(p, events)
        want = scalar_oracle(p.stages, p.within_ms, events)
        assert got == want, f"seed={seed} variant={variant}"

    @pytest.mark.parametrize("build,msg", [
        (lambda: (Pattern.begin("a").where(lambda d: d["v"] > 0)
                  .not_followed_by("b").where(lambda d: d["v"] < 0)
                  ).stages, "needs within"),
        (lambda: (Pattern.begin("a").where(lambda d: d["v"] > 0)
                  .not_next("b").where(lambda d: d["v"] < 0)).stages,
         "trailing not_next"),
        (lambda: (Pattern.begin("a").where(lambda d: d["v"] > 0)
                  .not_followed_by("b").where(lambda d: d["v"] < 0)
                  .next("c").where(lambda d: d["v"] == 0)).stages,
         "followed_by"),
        (lambda: (Pattern.begin("a").where(lambda d: d["v"] > 0)
                  .not_followed_by("b").where(lambda d: d["v"] < 0)
                  .not_followed_by("c").where(lambda d: d["v"] == 0)
                  .within(10)).stages, "adjacent negated"),
        (lambda: (Pattern.begin("a").where(lambda d: d["v"] > 0)
                  .not_followed_by("b").where(lambda d: d["v"] < 0)
                  .times(2)), "cannot be quantified"),
        (lambda: (Pattern.begin("a").where(lambda d: d["v"] > 0)
                  .one_or_more()
                  .not_followed_by("b").where(lambda d: d["v"] < 0)
                  .followed_by("c").where(lambda d: d["v"] == 0)
                  ).stages, "quantified stage"),
    ])
    def test_invalid_negation_shapes_raise(self, build, msg):
        with pytest.raises(ValueError, match=msg):
            build()

    def test_negation_refused_on_multi_partial_engine(self):
        p = (Pattern.begin("a").where(lambda d: d["v"] == 0)
             .not_followed_by("x").where(lambda d: d["v"] == 5)
             .followed_by("b").where(lambda d: d["v"] == 1)
             .after_match("NO_SKIP"))
        with pytest.raises(NotImplementedError, match="negated"):
            CepOperator(p, num_shards=4, slots_per_shard=64)


class TestSkipToStrategies:
    """after_match('SKIP_TO_FIRST'/'SKIP_TO_LAST', stage): each match
    prunes partials starting before the first/last event it mapped to
    the referenced stage (ref: AfterMatchSkipStrategy.skipToFirst/
    skipToLast), on the bounded multi-partial engine."""

    @staticmethod
    def _base():
        return (Pattern.begin("a").where(lambda d: d["v"] == 0)
                .followed_by("b").where(lambda d: d["v"] == 1))

    @staticmethod
    def _run(pattern, keys, ts, fields):
        op = CepOperator(pattern, num_shards=4, slots_per_shard=64)
        op.process_batch(np.asarray(keys, np.int64),
                         np.asarray(ts, np.int64), fields)
        f = op.take_fired()
        if f is None:
            return []
        d = dict(f)
        return sorted(zip([int(x) for x in d["key"]],
                          [int(x) for x in d["match_start"]],
                          [int(x) for x in d["match_end"]]))

    @staticmethod
    def _oracle(stages, keys, ts, fields, within=None, ref=None):
        """TestNoSkip._oracle + skip-to pruning: completions on an
        event resolve ascending match_start; each emitted match sets
        the cut to its referenced stage's ts (monotone — a surviving
        later match starts at/after the previous cut) and partials
        starting before the final cut are pruned."""
        from collections import defaultdict
        parts = defaultdict(list)
        out = []
        order = np.lexsort((ts, keys))
        for i in order:
            k, t = int(keys[i]), int(ts[i])
            ev = {f: v[i] for f, v in fields.items()}
            hits = [bool(np.asarray(st.where(
                {f: np.asarray([v]) for f, v in ev.items()}))[0])
                for st in stages]
            nxt, done = [], []
            for stage_i, tss in parts[k]:
                if within is not None and t - tss[0] > within:
                    continue
                if hits[stage_i]:
                    tss = tss + [t]
                    if stage_i + 1 == len(stages):
                        done.append(tss)
                        continue
                    nxt.append([stage_i + 1, tss])
                elif stages[stage_i].strict:
                    continue
                else:
                    nxt.append([stage_i, tss])
            if ref is None:
                for tss in done:
                    out.append((k, tss[0], t))
            else:
                cut = None
                for tss in sorted(done, key=lambda x: x[0]):
                    if cut is not None and tss[0] < cut:
                        continue
                    out.append((k, tss[0], t))
                    cut = tss[ref]
                if cut is not None:
                    nxt = [pp for pp in nxt if pp[1][0] >= cut]
            if hits[0]:
                if len(stages) == 1:
                    out.append((k, t, t))
                else:
                    nxt.append([1, [t]])
            parts[k] = nxt
        return sorted(out)

    def test_skip_to_first_prunes_earlier_starts(self):
        # a@10 a@20 b@30: NO_SKIP emits both; SKIP_TO_FIRST('b') emits
        # the earliest, whose b-event ts (30) prunes the other partial
        fields = {"v": np.array([0, 0, 1])}
        got = self._run(self._base().after_match("SKIP_TO_FIRST", "b"),
                        [1, 1, 1], [10, 20, 30], fields)
        assert got == [(1, 10, 30)]
        # anchored to 'a' instead: the cut is the match's own start, so
        # the second partial (started later) survives and also emits
        got = self._run(self._base().after_match("SKIP_TO_FIRST", "a"),
                        [1, 1, 1], [10, 20, 30], fields)
        assert got == [(1, 10, 30), (1, 20, 30)]

    def test_skip_to_last_resolves_times_expansion(self):
        p = (Pattern.begin("a").where(lambda d: d["v"] == 0)
             .followed_by("b").where(lambda d: d["v"] == 1).times(2)
             .after_match("SKIP_TO_LAST", "b"))
        op = CepOperator(p, num_shards=4, slots_per_shard=64)
        assert [s.name for s in op.stages] == ["a", "b_1", "b_2"]
        assert op._skip_ref == 2   # b_2 — the LAST expansion
        p_first = (Pattern.begin("a").where(lambda d: d["v"] == 0)
                   .followed_by("b").where(lambda d: d["v"] == 1)
                   .times(2).after_match("SKIP_TO_FIRST", "b"))
        assert CepOperator(p_first, num_shards=4,
                           slots_per_shard=64)._skip_ref == 1

    @pytest.mark.parametrize("seed,mode,ref_name", [
        (0, "SKIP_TO_FIRST", "b"), (1, "SKIP_TO_LAST", "b"),
        (2, "SKIP_TO_FIRST", "c"), (3, "SKIP_TO_LAST", "a"),
    ])
    def test_property_vs_oracle(self, seed, mode, ref_name):
        rng = np.random.default_rng(200 + seed)
        p = (Pattern.begin("a").where(lambda d: d["v"] % 3 == 0)
             .followed_by("b").where(lambda d: d["v"] % 3 == 1)
             .followed_by("c").where(lambda d: d["v"] % 3 == 2)
             .within(40).after_match(mode, ref_name))
        keys = rng.integers(0, 5, 200)
        ts = np.sort(rng.integers(0, 400, 200))
        v = rng.integers(0, 9, 200)
        got = self._run(p, keys, ts, {"v": v})
        op = CepOperator(p, num_shards=4, slots_per_shard=64)
        want = self._oracle(p.stages, keys, ts, {"v": v}, within=40,
                            ref=op._skip_ref)
        assert got == want, f"seed={seed} mode={mode} ref={ref_name}"
        assert len(got) > 0

    def test_unknown_stage_refused(self):
        with pytest.raises(ValueError, match="no stage named"):
            CepOperator(self._base().after_match("SKIP_TO_FIRST", "zz"),
                        num_shards=4, slots_per_shard=64)

    def test_mode_argument_validation(self):
        with pytest.raises(ValueError, match="needs the stage name"):
            self._base().after_match("SKIP_TO_FIRST")
        with pytest.raises(ValueError, match="takes no stage name"):
            self._base().after_match("NO_SKIP", "b")
        with pytest.raises(ValueError, match="supported modes"):
            self._base().after_match("SKIP_TO_NEXT")
