"""Async + incremental checkpointing (ref: HeapSnapshotStrategy's
async snapshot part + RocksDBIncrementalSnapshotStrategy's shared-SST
reuse, SURVEY §6.4). Contracts under test: the 2PC commit happens only
after the manifest is durable; unchanged operators hardlink the base
checkpoint's blob; v1 single-pickle checkpoints stay loadable."""
import json
import os
import pickle
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.checkpoint.coordinator import CheckpointCoordinator
from flink_tpu.checkpoint.storage import FsCheckpointStorage
from flink_tpu.config import Configuration
from flink_tpu.time.watermarks import WatermarkStrategy


def make_env(tmp_path, extra=None):
    conf = {
        "state.num-key-shards": 4,
        "state.slots-per-shard": 32,
        "pipeline.microbatch-size": 64,
        "execution.checkpointing.dir": str(tmp_path),
        "execution.checkpointing.interval": 1,
    }
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def simple_gen(n_batches):
    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        keys = rng.integers(0, 10, 32).astype(np.int64)
        ts = np.sort(rng.integers(i * 500, i * 500 + 900, 32)).astype(np.int64)
        return {"k": keys}, ts
    return gen


class TestCommitAfterDurable:
    def test_commit_waits_for_persistence(self, tmp_path):
        """The 2PC commit must not run until the manifest is on disk —
        a gate in the executor holds the write; the commit callback must
        not fire while the gate is closed."""
        storage = FsCheckpointStorage(str(tmp_path), "job")
        coord = CheckpointCoordinator(storage)
        gate = threading.Event()
        committed = []

        real_save_v2 = storage.save_v2

        def slow_save_v2(*a, **kw):
            gate.wait(timeout=10)
            return real_save_v2(*a, **kw)

        storage.save_v2 = slow_save_v2
        with ThreadPoolExecutor(max_workers=1) as ex:
            pend = coord.trigger_async(
                lambda: {"operators": {0: {"x": np.arange(4)}}},
                commit_fns=[committed.append],
                prepare_fns=[lambda cid: None],
                executor=ex)
            time.sleep(0.15)
            assert not pend.done()
            assert committed == []           # nothing durable yet
            assert storage.latest() is None  # no manifest either
            gate.set()
            handle = pend.complete()
        assert committed == [pend.checkpoint_id]
        assert storage.latest().checkpoint_id == handle.checkpoint_id

    def test_abandoned_checkpoint_never_commits(self, tmp_path):
        storage = FsCheckpointStorage(str(tmp_path), "job")
        coord = CheckpointCoordinator(storage)
        committed = []
        gate = threading.Event()
        real = storage.save_v2
        storage.save_v2 = lambda *a, **kw: (gate.wait(10), real(*a, **kw))[1]
        with ThreadPoolExecutor(max_workers=1) as ex:
            pend = coord.trigger_async(
                lambda: {"operators": {0: {"x": 1}}},
                commit_fns=[committed.append],
                prepare_fns=[], executor=ex)
            pend.abandon()
            gate.set()
            time.sleep(0.1)
        assert committed == []  # persisted maybe, committed never


class TestIncrementalReuse:
    def test_job_checkpoints_use_v2_layout(self, tmp_path):
        """Interval checkpoints of a real job land in the v2 per-op-blob
        layout with a manifest op map."""
        env2 = make_env(tmp_path)
        sink2 = CollectSink()
        (env2.from_source(GeneratorSource(simple_gen(4)),
                          WatermarkStrategy.for_bounded_out_of_orderness(400))
         .key_by("k")
         .window(TumblingEventTimeWindows.of(1_000))
         .count()
         .add_sink(sink2))
        env2.execute("inc-job")
        job_dir = os.path.join(str(tmp_path), "inc-job")
        chks = sorted(d for d in os.listdir(job_dir) if d.startswith("chk-"))
        assert len(chks) >= 2
        # format v3 layout everywhere
        for c in chks:
            mf = json.load(open(os.path.join(job_dir, c, "MANIFEST.json")))
            assert mf["format_version"] == 3
            assert os.path.exists(os.path.join(job_dir, c, "meta.blob"))

    def test_idle_op_blob_is_hardlinked(self, tmp_path):
        """Direct storage check: save_v2 with a ReusedOpState must link
        the same inode as the base checkpoint's blob."""
        from flink_tpu.checkpoint.storage import ReusedOpState

        st = FsCheckpointStorage(str(tmp_path), "j")
        blob = pickle.dumps({"state": np.arange(1000)})
        h1 = st.save_v2(1, {"op_versions": {"5": 3}}, {"5": blob}, {})
        f1 = os.path.join(h1.path, "op-5.blob")
        h2 = st.save_v2(2, {"op_versions": {"5": 3}}, {},
                        {"5": ReusedOpState(f1, 3)})
        f2 = os.path.join(h2.path, "op-5.blob")
        assert os.path.samefile(f1, f2)          # same inode — zero bytes
        # retiring the base keeps the reused blob readable
        st.retained = 1
        st._retire_old()
        assert not os.path.exists(h1.path)
        assert pickle.loads(open(f2, "rb").read())["state"][999] == 999

    def test_restored_checkpoint_seeds_reuse_base(self, tmp_path):
        """Run, restore from the checkpoint, run again without touching
        one op — its blob must hardlink the restored checkpoint's file
        via the manifest-adopted state_version."""
        from flink_tpu.checkpoint.storage import FsCheckpointStorage as S

        env = make_env(tmp_path)
        sink = CollectSink()
        (env.from_source(GeneratorSource(simple_gen(3)),
                         WatermarkStrategy.for_bounded_out_of_orderness(400))
         .key_by("k").window(TumblingEventTimeWindows.of(1_000))
         .count().add_sink(sink))
        env.execute("seed-job")
        # v2 load returns op files + versions for the base seed
        st = S(str(tmp_path), "seed-job")
        payload = S.load(st.latest())
        assert payload["op_files"] and payload["op_file_versions"]


class TestV1Compat:
    def test_v1_checkpoint_still_loads(self, tmp_path):
        d = os.path.join(str(tmp_path), "j", "chk-7")
        os.makedirs(d)
        with open(os.path.join(d, "state.pkl"), "wb") as f:
            pickle.dump({"checkpoint_id": 7, "operators": {0: {"a": 1}}}, f)
        with open(os.path.join(d, "MANIFEST.json"), "w") as f:
            json.dump({"checkpoint_id": 7, "timestamp_ms": 1,
                       "job_id": "j", "savepoint": False,
                       "format_version": 1}, f)
        st = FsCheckpointStorage(str(tmp_path), "j")
        h = st.latest()
        assert h.checkpoint_id == 7
        payload = FsCheckpointStorage.load(h)
        assert payload["operators"][0]["a"] == 1
