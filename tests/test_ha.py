"""HA services: leader election on a shared directory + job store
recovery by a replacement coordinator (ref: ZooKeeperLeaderElection /
JobGraphStore / Dispatcher.recoverJobs)."""
import time

import pytest

from flink_tpu.config import Configuration
from flink_tpu.runtime.coordinator import start_coordinator
from flink_tpu.runtime.ha import JobStore, LeaderElection, leader_address
from flink_tpu.runtime.rpc import RpcClient, RpcEndpoint, RpcServer


class TestLeaderElection:
    def test_single_winner_and_address(self, tmp_path):
        d = str(tmp_path)
        a = LeaderElection(d, "127.0.0.1:1111", lease_timeout_s=0.5)
        b = LeaderElection(d, "127.0.0.1:2222", lease_timeout_s=0.5)
        try:
            a.start(); b.start()
            deadline = time.time() + 5
            while time.time() < deadline and not (a.is_leader or b.is_leader):
                time.sleep(0.02)
            assert a.is_leader != b.is_leader  # exactly one
            leader = a if a.is_leader else b
            assert leader_address(d) == leader.address
        finally:
            a.close(); b.close()

    def test_takeover_on_stale_lease(self, tmp_path):
        d = str(tmp_path)
        a = LeaderElection(d, "127.0.0.1:1111", lease_timeout_s=0.4)
        try:
            a.start()
            deadline = time.time() + 5
            while time.time() < deadline and not a.is_leader:
                time.sleep(0.02)
            assert a.is_leader
            epoch1 = a.epoch
            # incumbent dies WITHOUT cleanup (thread stops renewing)
            a._closed = True
            a._thread.join(timeout=2)
            b = LeaderElection(d, "127.0.0.1:2222", lease_timeout_s=0.4)
            try:
                b.start()
                deadline = time.time() + 5
                while time.time() < deadline and not b.is_leader:
                    time.sleep(0.02)
                assert b.is_leader
                assert b.epoch > epoch1  # fencing token advanced
                assert leader_address(d) == "127.0.0.1:2222"
            finally:
                b.close()
        finally:
            a.close()

    def test_clean_release_hands_over_fast(self, tmp_path):
        d = str(tmp_path)
        a = LeaderElection(d, "127.0.0.1:1111", lease_timeout_s=5.0)
        a.start()
        deadline = time.time() + 5
        while time.time() < deadline and not a.is_leader:
            time.sleep(0.02)
        a.close()  # removes the lease
        b = LeaderElection(d, "127.0.0.1:2222", lease_timeout_s=5.0)
        try:
            b.start()
            deadline = time.time() + 5
            while time.time() < deadline and not b.is_leader:
                time.sleep(0.02)
            assert b.is_leader  # no need to wait out the 5s timeout
        finally:
            b.close()


class TestRacingContenders:
    """ISSUE 11 satellite: the lease-steal follows the rename-first
    stale-lock-breaking discipline PR 9 established for bus leases —
    two racing breakers must never unlink each other's FRESH lease,
    and release is inode/identity-checked."""

    def _stale_lease(self, d, epoch=3):
        import json as _json
        import os as _os

        lease = _os.path.join(d, "leader.lease")
        with open(lease, "w") as f:
            _json.dump({"leader_id": "dead", "address": "h:9",
                        "epoch": epoch, "claimed_at": time.time() - 60},
                       f)
        _os.utime(lease, (time.time() - 60, time.time() - 60))
        return lease

    def test_racing_breaker_cannot_unlink_fresh_lease(self, tmp_path):
        """The exact race the old tmp+replace steal lost: contender B
        reads the stale record, contender A completes its steal (fresh
        lease claimed), THEN B's steal fires with the stale record it
        observed. B must neither become leader nor destroy A's fresh
        lease."""
        d = str(tmp_path)
        self._stale_lease(d)
        a = LeaderElection(d, "127.0.0.1:1111", lease_timeout_s=0.3,
                           leader_id="breaker-a")
        b = LeaderElection(d, "127.0.0.1:2222", lease_timeout_s=0.3,
                           leader_id="breaker-b")
        try:
            stale_as_b_saw_it = b._read()
            a._steal_stale(a._read())
            assert a.is_leader and a.epoch == 4
            # B races in with its stale observation
            b._steal_stale(stale_as_b_saw_it)
            assert not b.is_leader
            survivor = a._read()
            assert survivor is not None, (
                "the racing breaker unlinked the fresh lease")
            assert survivor.leader_id == "breaker-a"
            assert leader_address(d) == "127.0.0.1:1111"
        finally:
            a.close()
            b.close()

    def test_concurrent_steals_exactly_one_winner(self, tmp_path):
        """N contenders breaking one stale lease concurrently: exactly
        one wins, the surviving lease is the winner's, and the epoch
        advanced past the stale incumbent's."""
        import threading as _threading

        d = str(tmp_path)
        self._stale_lease(d, epoch=7)
        contenders = [
            LeaderElection(d, f"127.0.0.1:{1000 + i}",
                           lease_timeout_s=0.3, leader_id=f"c{i}")
            for i in range(4)]
        try:
            stale = contenders[0]._read()
            barrier = _threading.Barrier(len(contenders))

            def steal(c):
                barrier.wait()
                c._steal_stale(stale)

            ts = [_threading.Thread(target=steal, args=(c,))
                  for c in contenders]
            for t in ts:
                t.start()
            for t in ts:
                t.join(10)
            winners = [c for c in contenders if c.is_leader]
            assert len(winners) == 1, (
                f"split brain: {[c.leader_id for c in winners]}")
            rec = winners[0]._read()
            assert rec is not None
            assert rec.leader_id == winners[0].leader_id
            assert rec.epoch > 7
        finally:
            for c in contenders:
                c.close()

    def test_release_is_identity_checked(self, tmp_path):
        """close() of a leader whose lease was already stolen must NOT
        unlink the thief's fresh lease (inode-checked release)."""
        d = str(tmp_path)
        a = LeaderElection(d, "127.0.0.1:1111", lease_timeout_s=0.2,
                           leader_id="old")
        a.start()
        deadline = time.time() + 5
        while time.time() < deadline and not a.is_leader:
            time.sleep(0.02)
        assert a.is_leader
        # stop the renewal thread, age the lease, let a thief steal it
        a._closed = True
        a._thread.join(timeout=2)
        import os as _os

        lease = _os.path.join(d, "leader.lease")
        _os.utime(lease, (time.time() - 60, time.time() - 60))
        thief = LeaderElection(d, "127.0.0.1:2222", lease_timeout_s=0.2,
                               leader_id="thief")
        try:
            thief._steal_stale(thief._read())
            assert thief.is_leader
            # the deposed leader exits believing it still leads
            # (is_leader was never flipped): its release must no-op
            a.close()
            rec = thief._read()
            assert rec is not None and rec.leader_id == "thief", (
                "release unlinked the thief's fresh lease")
        finally:
            thief.close()


class TestTakeoverCount:
    """`takeovers` is a durable count of lease STEALS — a clean
    stop/restart advances the fencing epoch but is NOT a takeover
    (review regression: epoch-1 arithmetic false-alarmed on every
    routine restart)."""

    def _lead(self, d, addr, timeout=0.3):
        e = LeaderElection(d, addr, lease_timeout_s=timeout)
        e.start()
        deadline = time.time() + 5
        while time.time() < deadline and not e.is_leader:
            time.sleep(0.02)
        assert e.is_leader
        return e

    def test_clean_restart_is_not_a_takeover(self, tmp_path):
        from flink_tpu.runtime.ha import takeover_count

        d = str(tmp_path)
        a = self._lead(d, "127.0.0.1:1111")
        a.close()  # clean handover
        b = self._lead(d, "127.0.0.1:2222")
        try:
            assert b.epoch > 1  # fencing epoch still advanced
            assert takeover_count(d) == 0  # but nothing was stolen
        finally:
            b.close()

    def test_steal_increments_the_counter(self, tmp_path):
        from flink_tpu.runtime.ha import takeover_count

        d = str(tmp_path)
        a = self._lead(d, "127.0.0.1:1111")
        # incumbent dies WITHOUT cleanup
        a._closed = True
        a._thread.join(timeout=2)
        b = self._lead(d, "127.0.0.1:2222", timeout=0.3)
        try:
            assert takeover_count(d) == 1
        finally:
            b.close()
            a.close()


class TestJobStore:
    def test_roundtrip_and_recoverable_filter(self, tmp_path):
        s = JobStore(str(tmp_path))
        s.put("a", entry="m:f", config={"x": 1}, state="RUNNING", attempts=1)
        s.put("b", entry="m:g", config={}, state="FINISHED", attempts=1)
        s.put("c", entry=None, config={}, state="RUNNING", attempts=1)
        assert s.get("a")["config"] == {"x": 1}
        rec = s.recoverable()
        assert [r["job_id"] for r in rec] == ["a"]
        s.remove("a")
        assert s.recoverable() == []


class _FakeRunnerGateway(RpcEndpoint):
    def __init__(self):
        self.deployed = []

    def rpc_run_job(self, job_id, entry, config=None, attempt=1, **kw):
        self.deployed.append((job_id, attempt, dict(config or {})))
        return {"accepted": True}

    def rpc_cancel_job(self, job_id):
        return {"ok": True}


class TestCoordinatorFailover:
    def test_new_coordinator_recovers_and_redeploys(self, tmp_path):
        ha = str(tmp_path)
        conf = Configuration({"high-availability.dir": ha})
        # coordinator A accepts the job, deploys it, then dies
        srv_a = start_coordinator(conf)
        gw = RpcServer(_FakeRunnerGateway())
        try:
            c = RpcClient("127.0.0.1", srv_a.port)
            c.call("register_runner", runner_id="r1", host="127.0.0.1",
                   n_devices=4, port=gw.port)
            c.call("submit_job", job_id="j", entry="mod:build",
                   config={"cluster.mesh-devices": "2"})
            deadline = time.time() + 5
            while time.time() < deadline and not gw.endpoint.deployed:
                time.sleep(0.02)
            assert gw.endpoint.deployed[0][:2] == ("j", 1)
            c.close()
        finally:
            srv_a.close()

        # coordinator B on the same HA dir: recovers the job, and when
        # the runner re-registers, re-deploys with restore:latest
        srv_b = start_coordinator(Configuration({
            "high-availability.dir": ha}))
        try:
            c = RpcClient("127.0.0.1", srv_b.port)
            st = c.call("job_status", job_id="j")
            assert st["state"] == "WAITING_FOR_RESOURCES"
            assert st["attempts"] == 2
            c.call("register_runner", runner_id="r1", host="127.0.0.1",
                   n_devices=4, port=gw.port)
            deadline = time.time() + 5
            while time.time() < deadline and len(gw.endpoint.deployed) < 2:
                time.sleep(0.02)
            job_id, attempt, config = gw.endpoint.deployed[1]
            assert job_id == "j" and attempt == 2
            assert config.get("execution.checkpointing.restore") == "latest"
            # terminal state persists: finishing removes recoverability
            c.call("finish_job", job_id="j")
            assert JobStore(ha).recoverable() == []
            c.close()
        finally:
            srv_b.close()
            gw.close()


class TestRevokeAndFollow:
    def test_revoke_fires_when_lease_stolen(self, tmp_path):
        d = str(tmp_path)
        a = LeaderElection(d, "127.0.0.1:1111", lease_timeout_s=0.4)
        revoked = []
        a.on_revoke = lambda: revoked.append(True)
        try:
            a.start()
            deadline = time.time() + 5
            while time.time() < deadline and not a.is_leader:
                time.sleep(0.02)
            # simulate a contender stealing the lease out from under A
            import json as _json
            import os as _os

            lease = _os.path.join(d, "leader.lease")
            with open(lease + ".x", "w") as f:
                _json.dump({"leader_id": "other", "address": "h:1",
                            "epoch": 9, "claimed_at": time.time()}, f)
            _os.replace(lease + ".x", lease)
            deadline = time.time() + 5
            while time.time() < deadline and not revoked:
                time.sleep(0.02)
            assert revoked and not a.is_leader
        finally:
            a.close()

    def test_runner_follows_new_leader(self, tmp_path):
        """Heartbeat misses against a dead leader make the runner
        re-resolve the lease and register with the new one."""
        import json as _json
        import os as _os

        from flink_tpu.runtime.runner import TaskRunner

        ha = str(tmp_path)
        srv_a = start_coordinator(Configuration({
            "high-availability.dir": ha, "heartbeat.interval": 100}))
        # lease file points at A
        with open(_os.path.join(ha, "leader.lease"), "w") as f:
            _json.dump({"leader_id": "A",
                        "address": f"127.0.0.1:{srv_a.port}",
                        "epoch": 1, "claimed_at": time.time()}, f)
        runner = TaskRunner("127.0.0.1", srv_a.port, runner_id="fr1",
                            ha_dir=ha)
        try:
            runner.start()
            assert "fr1" in RpcClient("127.0.0.1", srv_a.port).call(
                "list_runners")
            # A dies; B takes over with a new lease
            srv_a.close()
            srv_b = start_coordinator(Configuration({
                "high-availability.dir": ha}))
            with open(_os.path.join(ha, "leader.lease"), "w") as f:
                _json.dump({"leader_id": "B",
                            "address": f"127.0.0.1:{srv_b.port}",
                            "epoch": 2, "claimed_at": time.time()}, f)
            try:
                c = RpcClient("127.0.0.1", srv_b.port)
                # follow latency: 2 heartbeat misses x 5s client timeout
                deadline = time.time() + 30
                while time.time() < deadline:
                    if "fr1" in c.call("list_runners"):
                        break
                    time.sleep(0.2)
                assert "fr1" in c.call("list_runners")
                c.close()
            finally:
                srv_b.close()
        finally:
            runner.close()

    def test_terminal_put_archives(self, tmp_path):
        s = JobStore(str(tmp_path))
        s.put("j", entry="m:f", config={}, state="RUNNING", attempts=1)
        assert [r["job_id"] for r in s.recoverable()] == ["j"]
        s.put("j", entry="m:f", config={}, state="FINISHED", attempts=1)
        assert s.recoverable() == []
        assert s.get("j")["state"] == "FINISHED"  # archived, still readable

    def test_epoch_never_regresses_after_clean_handover(self, tmp_path):
        d = str(tmp_path)
        a = LeaderElection(d, "127.0.0.1:1111", lease_timeout_s=0.3)
        a.start()
        deadline = time.time() + 5
        while time.time() < deadline and not a.is_leader:
            time.sleep(0.02)
        e1 = a.epoch
        a.close()  # clean handover (removes lease)
        b = LeaderElection(d, "127.0.0.1:2222", lease_timeout_s=0.3)
        try:
            b.start()
            deadline = time.time() + 5
            while time.time() < deadline and not b.is_leader:
                time.sleep(0.02)
            assert b.epoch > e1  # fencing token monotone across handover
        finally:
            b.close()


class TestStorageWriteFencing:
    """Round-3 weak #7 (three rounds on the list): a deposed leader's
    in-flight checkpoint write must not corrupt the store after a new
    leader (higher epoch) has taken over."""

    def test_deposed_writer_fenced_after_successor_writes(self, tmp_path):
        from flink_tpu.checkpoint.storage import (
            FsCheckpointStorage, StaleCheckpointWriter)

        # old leader (epoch 1) completes checkpoint 4, then stalls
        # mid-checkpoint-5 (its writer paused past the lease)
        old = FsCheckpointStorage(str(tmp_path), "job", epoch=1)
        old.save(4, {"who": "old", "n": 4})
        # new leader (epoch 2) takes over and completes 5 and 6
        new = FsCheckpointStorage(str(tmp_path), "job", epoch=2)
        new.save(5, {"who": "new", "n": 5})
        new.save(6, {"who": "new", "n": 6})
        # the old writer resumes and tries to finish ITS checkpoint 5:
        # fenced — and the successor's data is untouched
        with pytest.raises(StaleCheckpointWriter):
            old.save(5, {"who": "old", "n": 5})
        with pytest.raises(StaleCheckpointWriter):
            old.save(7, {"who": "old", "n": 7})  # even a NEWER id
        latest = new.latest()
        assert latest.checkpoint_id == 6
        assert FsCheckpointStorage.load(latest)["who"] == "new"
        assert FsCheckpointStorage.load(
            new.list_complete()[-2])["who"] == "new"

    def test_deposed_v2_writer_fenced(self, tmp_path):
        from flink_tpu.checkpoint import blobformat
        from flink_tpu.checkpoint.storage import (
            FsCheckpointStorage, StaleCheckpointWriter)

        old = FsCheckpointStorage(str(tmp_path), "job", epoch=3)
        new = FsCheckpointStorage(str(tmp_path), "job", epoch=4)
        new.save_v2(1, {"meta": 1, "op_versions": {}},
                    {"0": blobformat.encode({"s": 1})}, {})
        with pytest.raises(StaleCheckpointWriter):
            old.save_v2(2, {"meta": 2, "op_versions": {}},
                        {"0": blobformat.encode({"s": 2})}, {})
        assert new.latest().checkpoint_id == 1

    def test_unfenced_local_storage_unchanged(self, tmp_path):
        from flink_tpu.checkpoint.storage import FsCheckpointStorage

        st = FsCheckpointStorage(str(tmp_path), "job")  # epoch 0
        st.save(1, {"n": 1})
        st.save(2, {"n": 2})
        assert st.latest().checkpoint_id == 2


class TestEpochQualifiedFinalNames:
    """ADVICE r5 low (storage.py fence race): _check_fence is
    check-then-rename — a deposed leader whose fence check passed just
    before the successor's first write landed could still
    delete-and-replace the successor's completed checkpoint of the same
    id. Final names are now epoch-qualified (chk-<id>.e<epoch>) under
    fencing, so the stale rename lands on a DIFFERENT path and the
    successor's directory is physically unclobberable."""

    def test_raced_stale_writer_cannot_clobber_successor(self, tmp_path):
        from flink_tpu.checkpoint.storage import FsCheckpointStorage

        old = FsCheckpointStorage(str(tmp_path), "job", epoch=1)
        new = FsCheckpointStorage(str(tmp_path), "job", epoch=2)
        new.save(5, {"who": "new", "n": 5})
        # simulate the race: the old writer's fence check ran BEFORE the
        # successor's manifest landed (so it passed), and its rename
        # fires now — neutralize the re-check to model that exact window
        old._check_fence = lambda: None
        old.save(5, {"who": "old", "n": 5})
        # both directories exist under distinct epoch-qualified names...
        import os as _os

        names = sorted(n for n in _os.listdir(str(tmp_path / "job"))
                       if n.startswith("chk-5"))
        assert names == ["chk-5.e1", "chk-5.e2"]
        # ...and resolution picks the successor's (highest epoch)
        latest = new.latest()
        assert (latest.checkpoint_id, latest.epoch) == (5, 2)
        assert FsCheckpointStorage.load(latest)["who"] == "new"

    def test_latest_orders_by_epoch_then_id(self, tmp_path):
        """The epoch is the leadership fencing token: the newest
        timeline outranks ANY id from a dead one. A deposed leader that
        got further (higher ids) before losing the lease must not have
        its late checkpoints eclipse the successor's — restoring the
        dead timeline would rewind sources past output the live
        timeline's 2PC sinks already committed."""
        from flink_tpu.checkpoint.storage import FsCheckpointStorage

        w1 = FsCheckpointStorage(str(tmp_path), "job", epoch=1)
        w2 = FsCheckpointStorage(str(tmp_path), "job", epoch=2)
        w2._check_fence = lambda: None  # keep both timelines writable
        w1._check_fence = lambda: None
        w1.save(1, {"n": 1})
        w2.save(1, {"n": 1, "who": "new"})
        w1.save(2, {"n": 2, "who": "old"})  # stale leader got further
        assert [(h.checkpoint_id, h.epoch)
                for h in w2.list_complete()] == [(1, 1), (2, 1), (1, 2)]
        # the live (highest-epoch) timeline wins, not the dead higher id
        assert (w2.latest().checkpoint_id, w2.latest().epoch) == (1, 2)
        assert FsCheckpointStorage.load(w2.latest())["who"] == "new"
