"""Unit tests for checkpoint/repartition.py — the key-group state
repartition plane (ref: StateAssignmentOperation round-trip coverage).

Scheme: EQUIVALENCE BY ROUTING. A reference operator fed every record
must behave identically to a fleet of N per-process operators fed
hash-routed shares whose savepoints were fused by ``merge_payloads`` —
both when merging down (2 -> 1: the merged state continues the
reference timeline) and when splitting up (1 -> 2: the union of the new
processes' emissions equals the reference and nothing fires twice).
"""
import numpy as np
import pytest

from flink_tpu.api.functions import KeyedProcessFunction
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.checkpoint.repartition import RescaleError, merge_payloads
from flink_tpu.exchange.partitioners import hash_shards
from flink_tpu.ops.aggregates import count, sum_of
from flink_tpu.ops.count_window import CountWindowOperator
from flink_tpu.ops.global_agg import GlobalAggregateOperator
from flink_tpu.ops.process import KeyedProcessOperator
from flink_tpu.ops.session import SessionOperator
from flink_tpu.ops.window import WindowOperator
from flink_tpu.state.api import ValueStateDescriptor

NS, SPS = 8, 16           # num_shards, slots_per_shard
R = NS * SPS


# ---------------------------------------------------------------------------
# harness helpers
# ---------------------------------------------------------------------------

def _route(keys, ts, data, n_old):
    """Split one batch into per-old-process shares along shard spans —
    exactly what hybrid_route does across the DCN exchange."""
    owner = hash_shards(np.asarray(keys, np.int64), NS) // (NS // n_old)
    out = []
    for o in range(n_old):
        m = owner == o
        out.append((keys[m], ts[m], {f: v[m] for f, v in data.items()}))
    return out


def _norm(v):
    if isinstance(v, (float, np.floating)):
        return round(float(v), 6)
    return int(v)


def _rows(fired):
    """FiredWindows/dict -> sorted list of value tuples (field order
    fixed by sorted name) for order-insensitive comparison."""
    if fired is None:
        return []
    names = sorted(k for k in fired if not k.startswith("__"))
    if not names:
        return []
    n = len(fired[names[0]])
    return sorted(tuple(_norm(np.asarray(fired[f])[i]) for f in names)
                  for i in range(n))


def _batch(seed, t0, n=64, n_keys=24):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n_keys, n).astype(np.int64)
    ts = rng.integers(t0, t0 + 1000, n).astype(np.int64)
    return keys, ts, {"v": rng.random(n)}


def _payload(ops, pid, nproc, ckpt=3):
    """A driver-shaped savepoint payload wrapping real operator snaps."""
    spp = NS // nproc
    return {
        "sources": {"src": {i: 100 * pid + i for i in range(2)}},
        "sub_factors": {"src": 1},
        "wm_gens": {"src": [("gen", pid, i) for i in range(2)]},
        "max_ts": {"src": 1000 + pid},
        "out_wm": {"src": 900 - pid},
        "operators": ops,
        "op_versions": {"w": 1},
        "partitioners": {"rr": 7},
        "sinks": {},
        "metrics": {"records": 10 * (pid + 1), "name": f"p{pid}"},
        "checkpoint_id": ckpt + pid,
        "rescale": {"nproc": nproc, "pid": pid, "num_shards": NS,
                    "shard_range": [pid * spp, (pid + 1) * spp]},
    }


def _merge(payloads, new_pid, new_nproc, kinds):
    return merge_payloads(payloads, new_pid=new_pid, new_nproc=new_nproc,
                          num_shards=NS, slots_per_shard=SPS,
                          op_kinds=kinds)


# ---------------------------------------------------------------------------
# device window operator (factory kind "window")
# ---------------------------------------------------------------------------

class TestWindowRescale:
    def _mk(self, shard_range=None):
        return WindowOperator(TumblingEventTimeWindows.of(1000),
                              sum_of("v"), num_shards=NS,
                              slots_per_shard=SPS, shard_range=shard_range)

    def test_merge_down_continues_reference_timeline(self):
        """2 ranged processes -> 1 full process: pre-cut fires match and
        the merged state finishes the open windows exactly like the
        never-rescaled reference."""
        ref = self._mk()
        olds = [self._mk((0, 4)), self._mk((4, 8))]
        got_ref, got_old = [], []
        for seed, t0, wm in [(1, 0, None), (2, 1000, 1500)]:
            keys, ts, data = _batch(seed, t0)
            ref.process_batch(keys, ts, data)
            for op, (k, t, d) in zip(olds, _route(keys, ts, data, 2)):
                op.process_batch(k, t, d)
            if wm is not None:
                got_ref += _rows(ref.advance_watermark(wm))
                for op in olds:
                    got_old += _rows(op.advance_watermark(wm))
        assert sorted(got_old) == sorted(got_ref)  # pre-cut equivalence

        payloads = [_payload({"w": op.snapshot_state()}, pid, 2)
                    for pid, op in enumerate(olds)]
        merged = _merge(payloads, 0, 1, {"w": "window"})
        new = self._mk()
        new.restore_state(merged["operators"]["w"])

        keys, ts, data = _batch(3, 2000)
        ref.process_batch(keys, ts, data)
        new.process_batch(keys, ts, data)
        assert (_rows(new.advance_watermark(5000))
                == _rows(ref.advance_watermark(5000)))

    def test_split_up_no_window_fires_twice(self):
        """1 full process -> 2 ranged: every open window fires on exactly
        one new process and the union equals the reference."""
        ref = self._mk()
        keys, ts, data = _batch(4, 0)
        ref.process_batch(keys, ts, data)
        payload = _payload({"w": ref.snapshot_state()}, 0, 1)

        news = []
        for pid in (0, 1):
            merged = _merge([payload], pid, 2, {"w": "window"})
            op = self._mk((pid * 4, (pid + 1) * 4))
            op.restore_state(merged["operators"]["w"])
            news.append(op)

        keys, ts, data = _batch(5, 1000)
        ref.process_batch(keys, ts, data)
        for op, (k, t, d) in zip(news, _route(keys, ts, data, 2)):
            op.process_batch(k, t, d)
        got = []
        for op in news:
            got += _rows(op.advance_watermark(2500))
        exp = _rows(ref.advance_watermark(2500))
        assert sorted(got) == exp  # equality <=> union complete, no dupes

    def test_spilled_state_refuses_to_repartition(self):
        olds = [self._mk((0, 4)), self._mk((4, 8))]
        snaps = [op.snapshot_state() for op in olds]
        snaps[1]["spill"] = {"panes": [("pane", 0)]}
        payloads = [_payload({"w": s}, pid, 2)
                    for pid, s in enumerate(snaps)]
        with pytest.raises(RescaleError, match="spill"):
            _merge(payloads, 0, 1, {"w": "window"})

    def test_lsm_spilled_state_repartitions(self, tmp_path):
        """ISSUE 17: the DISK tier's snapshot repartitions where the
        RAM tier refuses — run rows carry their key-group shard, so
        merge-down (2 -> 1) continues the reference timeline with
        host-spilled aggregates intact."""
        def mk(name, shard_range=None):
            from flink_tpu.state.lsm import LsmSpillStore

            store = LsmSpillStore(
                sum_of("v"), store_dir=str(tmp_path / name),
                memory_budget_bytes=0, num_shards=NS)
            return WindowOperator(
                TumblingEventTimeWindows.of(1000), sum_of("v"),
                num_shards=NS, slots_per_shard=SPS,
                shard_range=shard_range, spill_store=store)

        ref = mk("ref")
        olds = [mk("old0", (0, 4)), mk("old1", (4, 8))]
        for seed, t0 in [(1, 0), (2, 1000)]:
            # ~5x the resident capacity: most keys spill to the tier
            keys, ts, data = _batch(seed, t0, n=512, n_keys=600)
            ref.process_batch(keys, ts, data)
            for op, (k, t, d) in zip(olds, _route(keys, ts, data, 2)):
                op.process_batch(k, t, d)

        payloads = [_payload({"w": op.snapshot_state()}, pid, 2)
                    for pid, op in enumerate(olds)]
        assert any(p["operators"]["w"]["spill"]["runs"]
                   for p in payloads), "nothing sealed — vacuous"
        merged = _merge(payloads, 0, 1, {"w": "window"})
        new = mk("new")
        new.restore_state(merged["operators"]["w"])

        keys, ts, data = _batch(3, 2000, n=512, n_keys=600)
        ref.process_batch(keys, ts, data)
        new.process_batch(keys, ts, data)
        assert (_rows(new.advance_watermark(5000))
                == _rows(ref.advance_watermark(5000)))

    def test_lsm_num_shards_mismatch_refuses(self, tmp_path):
        from flink_tpu.state.lsm import LsmSpillStore

        olds = [self._mk((0, 4)), self._mk((4, 8))]
        snaps = [op.snapshot_state() for op in olds]
        store = LsmSpillStore(sum_of("v"),
                              store_dir=str(tmp_path / "s"),
                              memory_budget_bytes=1 << 30,
                              num_shards=NS * 2)  # different key space
        snaps[1]["spill"] = store.snapshot()
        payloads = [_payload({"w": s}, pid, 2)
                    for pid, s in enumerate(snaps)]
        with pytest.raises(RescaleError, match="num_shards"):
            _merge(payloads, 0, 1, {"w": "window"})

    def test_diverged_pane_rings_refuse_to_splice(self):
        olds = [self._mk((0, 4)), self._mk((4, 8))]
        snaps = [op.snapshot_state() for op in olds]
        snaps[1]["ring"] = snaps[1]["ring"] + 8  # process-local auto-grow
        payloads = [_payload({"w": s}, pid, 2)
                    for pid, s in enumerate(snaps)]
        with pytest.raises(RescaleError, match="ring"):
            _merge(payloads, 0, 1, {"w": "window"})


# ---------------------------------------------------------------------------
# KeyedProcessOperator: named state + user timers
# ---------------------------------------------------------------------------

class _RunningSum(KeyedProcessFunction):
    def process_batch(self, ctx):
        vs = ctx.value_state(ValueStateDescriptor("sum", 0.0))
        order = np.argsort(ctx.slots, kind="stable")
        sl, v = ctx.slots[order], ctx.data["v"][order]
        uniq, starts = np.unique(sl, return_index=True)
        totals = np.add.reduceat(v.astype(np.float64), starts)
        vs[uniq] = vs[uniq] + totals
        ctx.emit({"key": ctx.keys[order][starts], "total": vs[uniq]},
                 ts=ctx.timestamps[order][starts])


class _IdleTimeout(KeyedProcessFunction):
    def __init__(self, gap):
        self.gap = gap

    def process_batch(self, ctx):
        last = ctx.value_state(ValueStateDescriptor("last_ts", -1.0))
        order = np.argsort(ctx.slots, kind="stable")
        sl, ts = ctx.slots[order], ctx.timestamps[order]
        uniq, starts = np.unique(sl, return_index=True)
        mx = np.maximum.reduceat(ts, starts)
        newer = mx > last[uniq]
        last[uniq[newer]] = mx[newer].astype(np.float64)
        ctx.register_event_time_timers(mx[newer] + self.gap,
                                       slots=uniq[newer])

    def on_timer(self, ctx):
        last = ctx.value_state(ValueStateDescriptor("last_ts", -1.0))
        live = last[ctx.slots] + self.gap == ctx.timestamps
        ctx.emit({"key": ctx.keys[live],
                  "idle_since": last[ctx.slots[live]].astype(np.int64)},
                 ts=ctx.timestamps[live])


class TestProcessRescale:
    def test_merge_down_carries_value_state(self):
        ref = KeyedProcessOperator(_RunningSum(), num_shards=NS,
                                   slots_per_shard=SPS)
        olds = [KeyedProcessOperator(_RunningSum(), num_shards=NS,
                                     slots_per_shard=SPS) for _ in range(2)]
        for seed in (10, 11):
            keys, ts, data = _batch(seed, 1000 * seed)
            ref.process_batch(keys, ts, data)
            got = []
            for op, (k, t, d) in zip(olds, _route(keys, ts, data, 2)):
                op.process_batch(k, t, d)
                got += _rows(dict(op.take_fired()))
            assert sorted(got) == _rows(dict(ref.take_fired()))

        payloads = [_payload({"p": op.snapshot_state()}, pid, 2)
                    for pid, op in enumerate(olds)]
        merged = _merge(payloads, 0, 1, {"p": "process"})
        new = KeyedProcessOperator(_RunningSum(), num_shards=NS,
                                   slots_per_shard=SPS)
        new.restore_state(merged["operators"]["p"])

        keys, ts, data = _batch(12, 12000)
        ref.process_batch(keys, ts, data)
        new.process_batch(keys, ts, data)
        # totals continue from the merged per-key sums
        assert _rows(dict(new.take_fired())) == _rows(dict(ref.take_fired()))

    def test_split_up_each_timer_fires_exactly_once(self):
        ref = KeyedProcessOperator(_IdleTimeout(1000), num_shards=NS,
                                   slots_per_shard=SPS)
        keys = np.arange(20, dtype=np.int64)
        ts = (100 + 17 * keys).astype(np.int64)
        ref.process_batch(keys, ts, {})  # arms one timer per key
        payload = _payload({"p": ref.snapshot_state()}, 0, 1)

        news = []
        for pid in (0, 1):
            merged = _merge([payload], pid, 2, {"p": "process"})
            op = KeyedProcessOperator(_IdleTimeout(1000), num_shards=NS,
                                      slots_per_shard=SPS)
            op.restore_state(merged["operators"]["p"])
            news.append(op)

        exp = _rows(dict(ref.advance_watermark(5000)))
        got = []
        for op in news:
            got += _rows(dict(op.advance_watermark(5000)))
        assert len(exp) == len(keys)
        assert sorted(got) == exp  # every key once, on one process only


# ---------------------------------------------------------------------------
# count windows, global aggregate, session windows
# ---------------------------------------------------------------------------

class TestCountWindowRescale:
    def test_merge_down_completes_partial_windows(self):
        def mk():
            return CountWindowOperator(sum_of("v"), 3, num_shards=NS,
                                       slots_per_shard=SPS)

        ref, olds = mk(), [mk(), mk()]
        keys = np.tile(np.arange(16, dtype=np.int64), 2)  # 2 of 3 per key
        ts = np.arange(len(keys), dtype=np.int64)
        data = {"v": np.arange(len(keys), dtype=np.float64)}
        ref.process_batch(keys, ts, data)
        assert _rows(ref.take_fired()) == []  # 2 of 3: nothing fires yet
        for op, (k, t, d) in zip(olds, _route(keys, ts, data, 2)):
            op.process_batch(k, t, d)
            assert _rows(op.take_fired()) == []

        payloads = [_payload({"c": op.snapshot_state()}, pid, 2)
                    for pid, op in enumerate(olds)]
        merged = _merge(payloads, 0, 1, {"c": "count_window"})
        new = mk()
        new.restore_state(merged["operators"]["c"])

        # the 3rd record per key completes windows whose first two
        # records pre-date the rescale cut
        keys2 = np.arange(16, dtype=np.int64)
        ts2 = np.full(16, 99, np.int64)
        data2 = {"v": np.full(16, 0.5)}
        ref.process_batch(keys2, ts2, data2)
        new.process_batch(keys2, ts2, data2)
        assert _rows(new.take_fired()) == _rows(ref.take_fired())


class TestGlobalAggRescale:
    def test_merge_down_upserts_running_totals(self):
        def mk():
            return GlobalAggregateOperator(sum_of("v"), num_shards=NS,
                                           slots_per_shard=SPS)

        ref, olds = mk(), [mk(), mk()]
        keys, ts, data = _batch(20, 0, n_keys=16)
        ref.process_batch(keys, ts, data)
        ref.take_fired()
        for op, (k, t, d) in zip(olds, _route(keys, ts, data, 2)):
            op.process_batch(k, t, d)
            op.take_fired()

        payloads = [_payload({"g": op.snapshot_state()}, pid, 2)
                    for pid, op in enumerate(olds)]
        merged = _merge(payloads, 0, 1, {"g": "global_agg"})
        new = mk()
        new.restore_state(merged["operators"]["g"])

        keys2, ts2, data2 = _batch(21, 1000, n_keys=16)
        ref.process_batch(keys2, ts2, data2)
        new.process_batch(keys2, ts2, data2)
        assert _rows(new.take_fired()) == _rows(ref.take_fired())


class TestSessionRescale:
    def test_merge_down_closes_open_sessions(self):
        def mk():
            return SessionOperator(1000, count())

        ref, olds = mk(), [mk(), mk()]
        keys, ts, data = _batch(30, 0, n_keys=16)
        ref.process_batch(keys, ts, data)
        for op, (k, t, d) in zip(olds, _route(keys, ts, data, 2)):
            op.process_batch(k, t, d)
        # keep sessions open across the cut
        ref.advance_watermark(500)
        for op in olds:
            op.advance_watermark(500)

        payloads = [_payload({"s": op.snapshot_state()}, pid, 2)
                    for pid, op in enumerate(olds)]
        merged = _merge(payloads, 0, 1, {"s": "session"})
        new = mk()
        new.restore_state(merged["operators"]["s"])

        # extend some sessions post-cut, then close everything
        keys2, ts2, data2 = _batch(31, 800, n_keys=16)
        ref.process_batch(keys2, ts2, data2)
        new.process_batch(keys2, ts2, data2)
        assert (_rows(new.advance_watermark(10_000))
                == _rows(ref.advance_watermark(10_000)))


# ---------------------------------------------------------------------------
# driver plane + savepoint-set validation
# ---------------------------------------------------------------------------

class TestDriverPlaneMerge:
    def _payloads(self):
        ops = []
        for pid in range(2):
            op = KeyedProcessOperator(_RunningSum(), num_shards=NS,
                                      slots_per_shard=SPS)
            ops.append(op)
        keys, ts, data = _batch(40, 0)
        for op, (k, t, d) in zip(ops, _route(keys, ts, data, 2)):
            op.process_batch(k, t, d)
            op.take_fired()
        return [_payload({"p": op.snapshot_state()}, pid, 2)
                for pid, op in enumerate(ops)]

    def test_driver_state_merges_by_rule(self):
        merged = _merge(self._payloads(), 0, 1, {"p": "process"})
        # split position from its old OWNER (owner of split s = s % 2)
        assert merged["sources"]["src"] == {0: 0, 1: 101}
        assert merged["wm_gens"]["src"] == [("gen", 0, 0), ("gen", 1, 1)]
        assert merged["max_ts"]["src"] == 1001    # max
        assert merged["out_wm"]["src"] == 899     # min
        assert merged["metrics"]["records"] == 30  # numeric sum
        assert merged["metrics"]["name"] == "p0"   # first non-numeric
        assert merged["checkpoint_id"] == 4        # max
        assert merged["partitioners"] == {}        # reset on rescale
        assert merged["sinks"] == {}               # committed by savepoint
        assert merged["rescale"] == {"nproc": 1, "pid": 0,
                                     "num_shards": NS,
                                     "shard_range": [0, NS]}

    def test_empty_set_rejected(self):
        with pytest.raises(RescaleError, match="empty"):
            _merge([], 0, 1, {})

    def test_divisibility_enforced(self):
        with pytest.raises(RescaleError, match="divide"):
            _merge(self._payloads(), 0, 3, {"p": "process"})

    def test_foreign_fleet_size_rejected(self):
        payloads = self._payloads()
        payloads[0]["rescale"]["nproc"] = 4
        with pytest.raises(RescaleError, match="4-process"):
            _merge(payloads, 0, 1, {"p": "process"})

    def test_out_of_order_set_rejected(self):
        payloads = self._payloads()
        with pytest.raises(RescaleError, match="out of order"):
            _merge(payloads[::-1], 0, 1, {"p": "process"})

    def test_operator_missing_from_part_of_set_rejected(self):
        payloads = self._payloads()
        del payloads[1]["operators"]["p"]
        with pytest.raises(RescaleError, match="missing"):
            _merge(payloads, 0, 1, {"p": "process"})

    def test_unknown_keyed_kind_rejected(self):
        payloads = self._payloads()
        with pytest.raises(RescaleError, match="no repartition rule"):
            _merge(payloads, 0, 1, {"p": "quantum_window"})

    def test_keyless_kind_taken_verbatim(self):
        payloads = self._payloads()
        for pid, p in enumerate(payloads):
            p["operators"]["a"] = {"marker": pid}
        merged = _merge(payloads, 0, 1,
                        {"p": "process", "a": "window_all"})
        assert merged["operators"]["a"] == {"marker": 0}
