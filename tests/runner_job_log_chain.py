"""Entry points for the log-chained CLI smoke test: job A
(``produce``) writes a deterministic word stream into a log topic
through LogSink; job B (``consume``) replays the topic's committed
offsets through LogSource into a windowed count with a columnar
FileSink — two ``python -m flink_tpu run --local`` invocations chained
through the durable log (tests/test_log.py TestCliChainSmoke)."""
import os

import numpy as np

from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import LogOptions
from flink_tpu.connectors import FileSink
from flink_tpu.formats_columnar import ColumnarFormat
from flink_tpu.log import LogSink, LogSource
from flink_tpu.time.watermarks import WatermarkStrategy

BATCH = 64
VOCAB = 12
TOPIC = "chain-words"

OUT_SCHEMA = (("key", "i64"), ("window_end", "i64"), ("count", "i64"))


def batch_of(i: int):
    rng = np.random.default_rng(9100 + i)
    words = rng.integers(0, VOCAB, BATCH).astype(np.int64)
    ts = (i * BATCH + np.arange(BATCH, dtype=np.int64)) * 10
    return {"word": words, "ts_ms": ts}, ts


def expected_counts(n_batches: int):
    """Independent golden: per-(word, 1s window) counts."""
    counts = {}
    for i in range(n_batches):
        data, ts = batch_of(i)
        for w, t in zip(data["word"].tolist(), ts.tolist()):
            key = (int(w), (int(t) // 1000 + 1) * 1000)  # window_end
            counts[key] = counts.get(key, 0) + 1
    return sorted((w, we, c) for (w, we), c in counts.items())


def read_committed_counts(sink_dir: str):
    sink = FileSink(sink_dir, ColumnarFormat(OUT_SCHEMA))
    rows = []
    for b in sink.committed_batches():
        rows.extend(zip(b["key"].tolist(), b["window_end"].tolist(),
                        b["count"].tolist()))
    return sorted((int(k), int(w), int(c)) for k, w, c in rows)


def produce(env):
    n_batches = int(env.config.get_raw("test.n-batches", 5))

    def gen(split, i):
        return batch_of(i) if i < n_batches else None

    env.from_source(GeneratorSource(gen)).add_sink(
        LogSink.from_config(env.config, TOPIC, key_field="word"))


def consume(env):
    sink_dir = env.config.get_raw("test.sink-dir")
    assert sink_dir, "test.sink-dir must be set"
    topic = os.path.join(str(env.config.get(LogOptions.DIR)), TOPIC)
    (env.from_source(LogSource(topic, ts_field="ts_ms"),
                     WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("word")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .add_sink(FileSink(sink_dir, ColumnarFormat(OUT_SCHEMA))))
