"""Window operator harness tests — the WindowOperatorTest analogue.

ref: flink-streaming-java/src/test/java/.../streaming/runtime/operators/
windowing/WindowOperatorTest.java — assigner × trigger × lateness × purge
matrix, driven through a single-operator harness with explicit elements
and watermarks, golden-checked against a pure-Python reference model.

Semantics note: firing is batch-granular here (late elements re-fire
their windows at the next watermark call, not per element) — the
documented microbatching tradeoff; the golden model implements the same
granularity so contents must match exactly.
"""
import collections
import dataclasses

import numpy as np
import pytest

from flink_tpu.api.windowing import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.ops.aggregates import avg_of, count, max_of, min_of, multi, sum_of
from flink_tpu.ops.window import WindowOperator
from flink_tpu.time.watermarks import LONG_MIN


# ---------------------------------------------------------------------------
# Golden reference model (scalar, dict-based — reference semantics).
# ---------------------------------------------------------------------------

class GoldenWindows:
    def __init__(self, assigner, lateness=0):
        self.assigner = assigner
        self.lateness = lateness
        self.contents = collections.defaultdict(lambda: collections.defaultdict(list))
        self.wm = LONG_MIN
        self.pending_refire = set()
        self.attempted_max_end = None
        self.dropped = 0

    def add_batch(self, recs):
        """recs: list of (key, ts, value)"""
        for key, ts, v in recs:
            windows = self.assigner.assign_windows(ts)
            live = [w for w in windows if not (w.end - 1 + self.lateness <= self.wm)]
            if not live:
                self.dropped += 1
                continue
            for w in live:
                self.contents[w][key].append(v)
                already_passed = self.wm >= w.end - 1
                if already_passed:
                    self.pending_refire.add(w)

    def advance(self, wm):
        """Returns list of (key, window_start, window_end, values_list)."""
        if wm < self.wm:
            return []
        prev, self.wm = self.wm, wm
        fire = set(self.pending_refire)
        self.pending_refire.clear()
        for w in list(self.contents):
            if prev < w.end - 1 <= wm:
                fire.add(w)
        out = []
        for w in sorted(fire):
            for key, vals in sorted(self.contents.get(w, {}).items()):
                if vals:
                    out.append((key, w.start, w.end, list(vals)))
        # purge
        for w in list(self.contents):
            if w.end - 1 + self.lateness <= wm:
                del self.contents[w]
        return out


def run_pair(assigner, agg, events, watermarks, lateness=0, ooo=0, golden_agg=None):
    """Drive operator and golden model through interleaved batches and
    watermark advances; return (ours, golden) emission lists."""
    op = WindowOperator(assigner, agg, num_shards=8, slots_per_shard=64,
                        allowed_lateness_ms=lateness, max_out_of_orderness_ms=ooo)
    gold = GoldenWindows(assigner, lateness)
    ours, golden = [], []
    for batch, wm in zip(events, watermarks):
        if batch:
            keys = np.array([k for k, _, _ in batch], dtype=np.int64)
            ts = np.array([t for _, t, _ in batch], dtype=np.int64)
            vals = np.array([v for _, _, v in batch], dtype=np.float64)
            op.process_batch(keys, ts, {"v": vals})
            gold.add_batch(batch)
        if wm is not None:
            fired = op.advance_watermark(wm)
            for i in range(len(fired["key"])):
                row = {f: fired[f][i] for f in fired}
                ours.append(row)
            for key, ws, we, vals in gold.advance(wm):
                golden.append((key, ws, we, vals, golden_agg(vals) if golden_agg else len(vals)))
    return op, ours, golden


def assert_match(ours, golden, result_field, approx=False):
    ours_set = sorted(
        (int(r["key"]), int(r["window_start"]), int(r["window_end"]),
         round(float(r[result_field]), 4))
        for r in ours)
    gold_set = sorted(
        (int(k), int(ws), int(we), round(float(res), 4))
        for k, ws, we, vals, res in golden)
    assert ours_set == gold_set, f"\nours:   {ours_set}\ngolden: {gold_set}"


# ---------------------------------------------------------------------------


class TestTumblingCount:
    def test_basic_single_key(self):
        a = TumblingEventTimeWindows.of(1000)
        events = [[(1, 100, 1.0), (1, 200, 1.0), (1, 1100, 1.0)]]
        op, ours, golden = run_pair(a, count(), events, [2000])
        assert_match(ours, golden, "count")
        assert len(ours) == 2  # two windows fired

    def test_multiple_keys(self):
        a = TumblingEventTimeWindows.of(1000)
        events = [[(k, t, 1.0) for k in range(5) for t in (10, 500, 990)]]
        op, ours, golden = run_pair(a, count(), events, [999])
        assert_match(ours, golden, "count")
        assert len(ours) == 5
        assert all(int(r["count"]) == 3 for r in ours)

    def test_watermark_exactly_at_max_timestamp(self):
        # fire iff wm >= end - 1 (ref: EventTimeTrigger.onEventTime)
        a = TumblingEventTimeWindows.of(1000)
        op, ours, golden = run_pair(a, count(), [[(1, 0, 1.0)], []], [998, 999])
        assert_match(ours, golden, "count")
        assert len(ours) == 1

    def test_empty_windows_not_emitted(self):
        a = TumblingEventTimeWindows.of(1000)
        op, ours, golden = run_pair(a, count(), [[(1, 100, 1.0)]], [10_000])
        assert len(ours) == 1

    def test_no_regression_on_old_watermark(self):
        a = TumblingEventTimeWindows.of(1000)
        op = WindowOperator(a, count(), num_shards=4, slots_per_shard=16)
        op.process_batch(np.array([1]), np.array([100]), {})
        op.advance_watermark(2000)
        fired = op.advance_watermark(1000)
        assert len(fired["key"]) == 0


class TestAggregates:
    def test_sum_max_min_avg(self):
        a = TumblingEventTimeWindows.of(1000)
        agg = multi(count(), sum_of("v"), max_of("v"), min_of("v"), avg_of("v"))
        events = [[(1, 100, 3.0), (1, 200, 5.0), (1, 800, 1.0), (2, 300, 10.0)]]
        op, ours, golden = run_pair(a, agg, events, [1500])
        by_key = {int(r["key"]): r for r in ours}
        assert by_key[1]["count"] == 3
        assert by_key[1]["sum_v"] == 9.0
        assert by_key[1]["max_v"] == 5.0
        assert by_key[1]["min_v"] == 1.0
        assert abs(by_key[1]["avg_v"] - 3.0) < 1e-6
        assert by_key[2]["max_v"] == 10.0

    def test_sum_golden(self):
        a = TumblingEventTimeWindows.of(500)
        events = [[(k, t, float(k * t % 7)) for k in range(3) for t in (10, 400, 600, 900)]]
        op, ours, golden = run_pair(a, sum_of("v"), events, [2000], golden_agg=sum)
        assert_match(ours, golden, "sum_v")


class TestSlidingWindows:
    def test_q5_shape_sliding_count(self):
        # 10s window / 1s slide — the Nexmark Q5 configuration
        a = SlidingEventTimeWindows.of(10_000, 1_000)
        events = [[(1, 500, 1.0), (1, 5500, 1.0), (2, 9_999, 1.0)]]
        op, ours, golden = run_pair(a, count(), events, [30_000])
        assert_match(ours, golden, "count")
        # element at 500 belongs to 10 windows (ends 1000..10000)
        k1 = [r for r in ours if r["key"] == 1]
        assert sum(int(r["count"]) for r in k1) == 10 + 10

    def test_sliding_incremental_watermarks(self):
        a = SlidingEventTimeWindows.of(3000, 1000)
        events = [[(1, 500, 1.0)], [(1, 1500, 1.0)], [(1, 2500, 1.0)], []]
        op, ours, golden = run_pair(a, count(), events, [999, 1999, 2999, 10_000])
        assert_match(ours, golden, "count")


class TestLateness:
    def test_late_beyond_lateness_dropped(self):
        a = TumblingEventTimeWindows.of(1000)
        op = WindowOperator(a, count(), num_shards=4, slots_per_shard=16,
                            allowed_lateness_ms=0, max_out_of_orderness_ms=5000)
        op.process_batch(np.array([1]), np.array([100]), {})
        op.advance_watermark(2000)
        op.process_batch(np.array([1]), np.array([500]), {})  # window [0,1000) dead
        assert op.late_records == 1
        fired = op.advance_watermark(3000)
        assert len(fired["key"]) == 0

    def test_allowed_lateness_refires(self):
        a = TumblingEventTimeWindows.of(1000)
        events = [[(1, 100, 1.0)], [(1, 500, 1.0)], []]
        # wm 1500: window [0,1000) fired with count 1; late element at 500
        # arrives within lateness 1000 → refire with count 2
        op, ours, golden = run_pair(a, count(), events, [1500, 1500, 1600],
                                    lateness=1000, ooo=2000)
        assert_match(ours, golden, "count")
        counts = sorted(int(r["count"]) for r in ours)
        assert counts == [1, 2]

    def test_lateness_cleanup_boundary(self):
        # window [0,1000): dead exactly when wm >= end - 1 + lateness = 1499
        a = TumblingEventTimeWindows.of(1000)
        op = WindowOperator(a, count(), num_shards=4, slots_per_shard=16,
                            allowed_lateness_ms=500, max_out_of_orderness_ms=5000)
        op.process_batch(np.array([1]), np.array([100]), {})
        op.advance_watermark(1498)  # not yet dead
        op.process_batch(np.array([1]), np.array([200]), {})
        assert op.late_records == 0
        fired = op.advance_watermark(1498)
        assert [int(c) for c in fired["count"]] == [2]  # refire with update
        op.advance_watermark(1499)  # now dead
        op.process_batch(np.array([1]), np.array([300]), {})
        assert op.late_records == 1


class TestPurge:
    def test_state_cleared_after_lateness(self):
        a = TumblingEventTimeWindows.of(1000)
        op = WindowOperator(a, count(), num_shards=4, slots_per_shard=16,
                            max_out_of_orderness_ms=2000)
        op.process_batch(np.array([1]), np.array([100]), {})
        op.advance_watermark(5000)
        # all counts back to zero after purge
        assert int(np.asarray(op.state.counts).sum()) == 0


class TestSnapshotRestore:
    def test_snapshot_restore_mid_window(self):
        # ref pattern: WindowOperatorTest snapshot→restore→continue
        a = SlidingEventTimeWindows.of(3000, 1000)
        op1 = WindowOperator(a, count(), num_shards=4, slots_per_shard=16)
        op1.process_batch(np.array([1, 2]), np.array([500, 700]), {})
        op1.advance_watermark(999)
        snap = op1.snapshot_state()

        op2 = WindowOperator(a, count(), num_shards=4, slots_per_shard=16)
        op2.restore_state(snap)
        op2.process_batch(np.array([1]), np.array([1500]), {})
        fired = op2.advance_watermark(10_000)

        # golden: same events, no restore
        op3 = WindowOperator(a, count(), num_shards=4, slots_per_shard=16)
        op3.process_batch(np.array([1, 2]), np.array([500, 700]), {})
        op3.advance_watermark(999)
        op3.process_batch(np.array([1]), np.array([1500]), {})
        expected = op3.advance_watermark(10_000)

        got = sorted(zip(fired["key"], fired["window_end"], fired["count"]))
        want = sorted(zip(expected["key"], expected["window_end"], expected["count"]))
        assert [tuple(map(int, g)) for g in got] == [tuple(map(int, w)) for w in want]


class TestSnapshotPendingRefire:
    def test_refire_survives_restore(self):
        # checkpoint between a late element and its re-firing must not
        # lose the emission (exactly-once recovery)
        a = TumblingEventTimeWindows.of(1000)
        kw = dict(num_shards=4, slots_per_shard=16,
                  allowed_lateness_ms=1000, max_out_of_orderness_ms=2000)
        op1 = WindowOperator(a, count(), **kw)
        op1.process_batch(np.array([1]), np.array([100]), {})
        op1.advance_watermark(1500)                      # fires count=1
        op1.process_batch(np.array([1]), np.array([500]), {})  # pending refire
        snap = op1.snapshot_state()
        op2 = WindowOperator(a, count(), **kw)
        op2.restore_state(snap)
        fired = op2.advance_watermark(1600)
        assert [int(c) for c in fired["count"]] == [2]


class TestNonDivisibleSlide:
    def test_size_not_multiple_of_slide(self):
        # windows START at slide multiples; ends are offset by size
        a = SlidingEventTimeWindows.of(5000, 2000)
        events = [[(1, 100, 1.0)], []]
        op, ours, golden = run_pair(a, count(), events, [None, 60_000])
        assert_match(ours, golden, "count")
        ends = sorted(int(r["window_end"]) for r in ours)
        assert ends == [1000, 3000, 5000]

    def test_degenerate_pane_rejected(self):
        from flink_tpu.ops.window import WindowPlan
        with pytest.raises(ValueError, match="degenerate"):
            WindowPlan.plan(SlidingEventTimeWindows.of(3600_000, 7))


class TestFuzzVsGolden:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize("size,slide,lateness", [
        (1000, 1000, 0),
        (5000, 1000, 1500),
        (4000, 2000, 0),
        (5000, 2000, 0),     # size NOT a multiple of slide
        (5000, 2000, 1500),
    ])
    def test_randomized(self, seed, size, slide, lateness):
        rng = np.random.default_rng(seed)
        a = SlidingEventTimeWindows.of(size, slide) if slide != size \
            else TumblingEventTimeWindows.of(size)
        ooo = 3000
        n_batches, batch = 12, 40
        events, wms = [], []
        max_ts = 0
        for i in range(n_batches):
            ts = rng.integers(max(0, max_ts - ooo), max_ts + 2000, batch)
            max_ts = max(max_ts, int(ts.max()))
            keys = rng.integers(0, 10, batch)
            b = [(int(k), int(t), 1.0) for k, t in zip(keys, ts)]
            events.append(b)
            wms.append(max_ts - ooo - 1)
        events.append([])
        wms.append(max_ts + size + lateness + 10_000)
        op, ours, golden = run_pair(a, count(), events, wms,
                                    lateness=lateness, ooo=ooo)
        assert_match(ours, golden, "count")


class TestLateAfterIdleGap:
    def test_late_window_after_idle_gap_fires(self):
        """Regression: a record in a window the watermark passed during an
        idle gap (within allowed lateness) must fire that window late
        (ref: EventTimeTrigger.onElement fires immediately when
        window.maxTimestamp() <= currentWatermark)."""
        from flink_tpu.api.windowing import TumblingEventTimeWindows
        from flink_tpu.ops import aggregates

        op = WindowOperator(
            TumblingEventTimeWindows.of(1000), aggregates.count(),
            num_shards=8, slots_per_shard=16, allowed_lateness_ms=10_000,
            max_out_of_orderness_ms=10_000)
        op.process_batch(np.array([1]), np.array([500]), {})
        fired = op.advance_watermark(50_000)  # idle gap: only [0,1000) fires
        assert {(int(k), int(e)) for k, e in zip(fired["key"], fired["window_end"])} == {(1, 1000)}
        # late but within lateness: 45999 + 10000 > 50000
        op.process_batch(np.array([2]), np.array([45_500]), {})
        fired = op.advance_watermark(50_001)
        assert {(int(k), int(e)) for k, e in zip(fired["key"], fired["window_end"])} == {(2, 46_000)}
        assert op.late_records == 0


class TestRingAutoGrow:
    def test_oversized_batch_grows_ring_exact_results(self):
        """A microbatch spanning more event time than the pane ring holds
        must grow the ring and remap live columns — not crash, not lose
        data (the backpressure answer is memory, then correctness)."""
        op = WindowOperator(
            TumblingEventTimeWindows.of(1000), count(),
            num_shards=8, slots_per_shard=16)
        ring0 = op.plan.ring
        # one batch covering 40 windows: far beyond the initial ring
        keys = np.arange(200) % 5
        ts = np.linspace(0, 40_000, 200).astype(np.int64)
        op.process_batch(keys, ts, {})
        assert op.plan.ring > ring0
        fired = op.advance_watermark(50_000).materialize()
        # golden: exact per-(key, window) counts
        expect = collections.Counter(
            (int(k), (int(t) // 1000) * 1000 + 1000) for k, t in zip(keys, ts))
        got = {(int(k), int(e)): int(c) for k, e, c in
               zip(fired["key"], fired["window_end"], fired["count"])}
        assert got == dict(expect)

    def test_grow_preserves_live_panes_mid_stream(self):
        """Grow while earlier panes hold data: pre-grow contents must
        survive the column remap."""
        op = WindowOperator(
            SlidingEventTimeWindows.of(4000, 2000), sum_of("v"),
            num_shards=8, slots_per_shard=16)
        op.process_batch(np.array([1, 2]), np.array([500, 1500]),
                         {"v": np.array([10.0, 20.0], np.float32)})
        # second batch leaps 60 windows ahead → forces growth
        op.process_batch(np.array([1]), np.array([120_000]),
                         {"v": np.array([7.0], np.float32)})
        fired = op.advance_watermark(200_000).materialize()
        rows = {(int(k), int(e)): float(s) for k, e, s in
                zip(fired["key"], fired["window_end"], fired["sum_v"])}
        # EXACT equality: the remap must not duplicate pre-grow panes
        # into phantom windows beyond the applied range
        assert rows == {
            (1, 2000): 10.0, (1, 4000): 10.0,
            (2, 2000): 20.0, (2, 4000): 20.0,
            (1, 122_000): 7.0, (1, 124_000): 7.0,
        }
        assert len(fired["key"]) == 6

    def test_grow_after_forward_leap_no_phantom_windows(self):
        """Advisor r2 repro: 2-record batch then a forward leap; the grow
        remap must not duplicate pre-grow sums into windows beyond the
        applied pane range (exact full-output equality)."""
        op = WindowOperator(
            TumblingEventTimeWindows.of(1000), sum_of("v"),
            num_shards=8, slots_per_shard=16)
        op.process_batch(np.array([1, 2]), np.array([100, 900]),
                         {"v": np.array([3.0, 4.0], np.float32)})
        # leap far ahead in the SAME operator — forces ring growth with
        # the new max pane way beyond anything applied to state
        op.process_batch(np.array([1]), np.array([116_000]),
                         {"v": np.array([5.0], np.float32)})
        fired = op.advance_watermark(200_000).materialize()
        rows = {(int(k), int(e)): float(s) for k, e, s in
                zip(fired["key"], fired["window_end"], fired["sum_v"])}
        assert rows == {(1, 1000): 3.0, (2, 1000): 4.0, (1, 117_000): 5.0}

    def test_snapshot_restore_across_grown_ring(self):
        op = WindowOperator(
            TumblingEventTimeWindows.of(1000), count(),
            num_shards=8, slots_per_shard=16)
        op.process_batch(np.arange(50) % 3,
                         np.linspace(0, 30_000, 50).astype(np.int64), {})
        snap = op.snapshot_state()
        op2 = WindowOperator(
            TumblingEventTimeWindows.of(1000), count(),
            num_shards=8, slots_per_shard=16)
        op2.restore_state(snap)
        a = op.advance_watermark(40_000).materialize()
        b = op2.advance_watermark(40_000).materialize()
        assert sorted(zip(a["key"], a["window_end"], a["count"])) == \
               sorted(zip(b["key"], b["window_end"], b["count"]))


class TestTopN:
    """Device-fused per-window top-n (the Q5 hot-items shape) — ref:
    Nexmark Q5 RANK() <= n semantics, ties at the n-th value kept."""

    def _op(self, n, by="count", **kw):
        return WindowOperator(
            TumblingEventTimeWindows.of(1000), count(),
            num_shards=8, slots_per_shard=64, top_n=(by, n), **kw)

    def test_fewer_candidates_than_n_emits_all(self):
        """Advisor r2 high: a window with fewer than n candidate keys
        must emit ALL of them (top_k pads with -inf ⇒ thresh=-inf ⇒
        every real candidate selects)."""
        op = self._op(5)
        op.process_batch(np.array([1, 2, 3]), np.array([100, 200, 300]), {})
        fired = op.advance_watermark(2000).materialize()
        got = {(int(k), int(c)) for k, c in zip(fired["key"], fired["count"])}
        assert got == {(1, 1), (2, 1), (3, 1)}

    def test_top1_picks_max_with_ties(self):
        op = self._op(1)
        # key 1: 3 bids, key 2: 3 bids, key 3: 1 bid → top(1) keeps ties
        keys = np.array([1, 1, 1, 2, 2, 2, 3])
        ts = np.full(7, 100)
        op.process_batch(keys, ts, {})
        fired = op.advance_watermark(2000).materialize()
        got = {(int(k), int(c)) for k, c in zip(fired["key"], fired["count"])}
        assert got == {(1, 3), (2, 3)}

    def test_top2_across_windows(self):
        op = self._op(2)
        keys = np.array([1, 1, 1, 2, 2, 3,   4, 5, 5])
        ts = np.array([0, 1, 2, 3, 4, 5,     1500, 1501, 1502])
        op.process_batch(keys, ts, {})
        fired = op.advance_watermark(3000).materialize()
        got = {(int(k), int(e), int(c)) for k, e, c in
               zip(fired["key"], fired["window_end"], fired["count"])}
        # window 1: counts 3,2,1 → top2 = {1:3, 2:2}; window 2: 1,2 → both
        assert got == {(1, 1000, 3), (2, 1000, 2), (4, 2000, 1), (5, 2000, 2)}

    def test_tie_explosion_raises_loudly(self):
        """More tied winners than the selection capacity must RAISE at
        drain (advisor r2 medium: no silent truncation)."""
        op = self._op(1)
        cap = op._topn_cap(1)
        nk = cap + 40
        assert nk <= 8 * 64
        keys = np.arange(nk)
        ts = np.full(nk, 100)
        op.process_batch(keys, ts, {})  # every key count=1 → all tie
        with pytest.raises(RuntimeError, match="truncation|tie"):
            op.advance_watermark(2000).materialize()


class TestLateLowPaneGrowth:
    def test_low_pane_batch_below_live_range_triggers_growth(self):
        """A batch arriving BELOW the live range (watermark not yet
        advanced, so not late) whose span vs the live max exceeds the
        ring must grow it — the batch max alone understates the span,
        and without growth the low pane's column write aliases the live
        max pane's column."""
        op = WindowOperator(
            TumblingEventTimeWindows.of(1000), sum_of("v"),
            num_shards=8, slots_per_shard=16)
        ring0 = op.plan.ring            # 6: 1 pane + 1 + 4 headroom
        hi_pane = ring0 + 4
        lo_pane = 4                     # collides: hi_pane % ring0 == 4
        assert hi_pane % ring0 == lo_pane % ring0
        op.process_batch(np.array([1]), np.array([hi_pane * 1000 + 499]),
                         {"v": np.array([2.0], np.float32)})
        op.process_batch(np.array([2]), np.array([lo_pane * 1000 + 500]),
                         {"v": np.array([9.0], np.float32)})
        assert op.plan.ring > ring0
        fired = op.advance_watermark(10_000_000).materialize()
        rows = {(int(k), int(e)): float(s) for k, e, s in
                zip(fired["key"], fired["window_end"], fired["sum_v"])}
        assert rows == {(1, (hi_pane + 1) * 1000): 2.0,
                        (2, (lo_pane + 1) * 1000): 9.0}


class TestSplitUpload:
    """The 3-byte/record (uint16 slot + uint8 column) upload encoding
    must be byte-identical to the packed-int32 path (apply_kernel vs
    apply_kernel_split), and layouts too large for it must fall back."""

    def _drive(self, op):
        rng = np.random.default_rng(7)
        out = []
        for i in range(4):
            n = 257
            keys = rng.integers(0, 50, n)
            ts = rng.integers(i * 2000, i * 2000 + 4000, n)
            vals = rng.random(n).astype(np.float32)
            op.process_batch(keys, ts, {"v": vals})
            fired = op.advance_watermark(i * 2000)
            for j in range(len(fired["key"])):
                out.append(tuple(
                    round(float(fired[f][j]), 4) if f.startswith("sum")
                    else int(fired[f][j])
                    for f in ("key", "window_start", "window_end", "sum_v")))
        fired = op.advance_watermark(10_000_000)
        for j in range(len(fired["key"])):
            out.append(tuple(
                round(float(fired[f][j]), 4) if f.startswith("sum")
                else int(fired[f][j])
                for f in ("key", "window_start", "window_end", "sum_v")))
        return sorted(out)

    def test_split_matches_packed(self):
        mk = lambda: WindowOperator(
            SlidingEventTimeWindows.of(3000, 1000), sum_of("v"),
            num_shards=8, slots_per_shard=16)
        op_split = mk()
        assert op_split._split_upload
        op_packed = mk()
        op_packed._split_upload = False
        assert self._drive(op_split) == self._drive(op_packed)

    def test_oversized_layout_falls_back(self):
        op = WindowOperator(
            TumblingEventTimeWindows.of(1000), count(),
            num_shards=16, slots_per_shard=8192)   # 131072 rows > u16
        assert not op._split_upload
        op.process_batch(np.array([1, 2]), np.array([100, 200]), {})
        fired = op.advance_watermark(5000)
        assert sorted(int(c) for c in fired["count"]) == [1, 1]


class TestHostPreaggregation:
    """The host combiner path (LaneAggregate.sum_fields): batches big
    enough to pass the decisive-win gate must produce results identical
    to the per-record upload path."""

    def _run(self, agg, golden_agg, field_vals, result_field,
             expect_preagg=True):
        assigner = SlidingEventTimeWindows.of(10_000, 1_000)
        rng = np.random.default_rng(3)
        B = 4096
        events, wms = [], []
        t = 0
        for i in range(4):
            keys = rng.integers(0, 40, B)
            ts = t + rng.integers(0, 3000, B)
            vals = field_vals(rng, B)
            events.append(list(zip(keys.tolist(), ts.tolist(), vals.tolist())))
            t += 3000
            wms.append(t - 1000)
        wms[-1] = t + 20_000
        op, ours, golden = run_pair(
            assigner, agg, events, wms, golden_agg=golden_agg)
        took_preagg = op.prof.get("pb_preagg", 0) > 0
        assert took_preagg == expect_preagg
        # f32 lane accumulation order differs between the paths; compare
        # with an f32-level tolerance, not digit-exact
        gold = {(int(k), int(ws), int(we)): res
                for k, ws, we, vals, res in golden}
        assert len(ours) == len(gold)
        for r in ours:
            key = (int(r["key"]), int(r["window_start"]), int(r["window_end"]))
            assert abs(float(r[result_field]) - gold[key]) < 1e-3 * max(
                1.0, abs(gold[key]))

    def test_count_preagg_matches_golden(self):
        self._run(count(), len, lambda rng, b: np.ones(b), "count")

    def test_sum_lane_preagg_matches_golden(self):
        self._run(sum_of("v"), sum,
                  lambda rng, b: rng.integers(0, 100, b).astype(np.float64),
                  "sum_v")

    def test_avg_lane_preagg_matches_golden(self):
        self._run(avg_of("v"), lambda vs: sum(vs) / len(vs),
                  lambda rng, b: rng.integers(0, 100, b).astype(np.float64),
                  "avg_v")

    def test_max_lane_falls_through(self):
        # max lanes are not host-combinable: sum_fields is None, the
        # operator must keep the per-record path and stay correct
        self._run(max_of("v"), max,
                  lambda rng, b: rng.integers(0, 100, b).astype(np.float64),
                  "max_v", expect_preagg=False)
