"""Storage fsck (flink_tpu/fsck.py + the ``fsck`` CLI): the five
seeded corruption classes each detected with exit 1 and a named
finding, clean topic + clean checkpoint dir exit 0, and ``--repair``
applying only the already-safe sweeps (tier-1 CLI smoke, PR 14)."""
import glob
import json
import os
import time

import numpy as np
import pytest

from flink_tpu.checkpoint.storage import FsCheckpointStorage
from flink_tpu.cli import main as cli_main
from flink_tpu.fsck import detect_kind, fsck_path
from flink_tpu.log.bus import Compactor
from flink_tpu.log.topic import TopicAppender, TopicReader, create_topic


def make_topic(root, rows=8, partitions=2, commit=True):
    topic = os.path.join(str(root), "topic")
    ap = TopicAppender(topic, partitions=partitions, segment_records=4)
    b = {"k": np.arange(rows, dtype=np.int64),
         "v": np.arange(rows, dtype=np.float64)}
    ap.stage(1, {p: [b] for p in range(partitions)})
    if commit:
        ap.commit(1)
    return topic


def make_checkpoints(root):
    st = FsCheckpointStorage(os.path.join(str(root), "chk"), "job")
    st.save(1, {"sources": {"0": 1}, "operators": {}})
    st.save_v2(2, {"op_versions": {"7": 1}},
               {"7": b"legacy-opaque-bytes"}, {})
    return os.path.join(str(root), "chk", "job")


def rules_of(findings):
    return {f["rule"] for f in findings}


def age(path, seconds=3600):
    """Back-date a seeded debris file past --repair's stage-window
    grace (a live producer's fresh files are deliberately skipped)."""
    t = time.time() - seconds
    os.utime(path, (t, t))


def cli(capsys, *argv):
    rc = cli_main(list(argv))
    out = capsys.readouterr().out
    return rc, out


class TestCleanStorage:
    def test_clean_topic_exits_0(self, tmp_path, capsys):
        topic = make_topic(tmp_path)
        rc, out = cli(capsys, "fsck", topic)
        assert rc == 0 and "clean" in out

    def test_clean_checkpoint_dir_exits_0(self, tmp_path, capsys):
        jdir = make_checkpoints(tmp_path)
        rc, _ = cli(capsys, "fsck", jdir)
        assert rc == 0
        # the storage root above the job dir autodetects too
        rc, _ = cli(capsys, "fsck", os.path.dirname(jdir))
        assert rc == 0

    def test_unrecognizable_path_exits_2(self, tmp_path, capsys):
        rc = cli_main(["fsck", str(tmp_path / "nope")])
        assert rc == 2
        (tmp_path / "plain").mkdir()
        assert cli_main(["fsck", str(tmp_path / "plain")]) == 2
        capsys.readouterr()


class TestSeededCorruption:
    """The five acceptance corruption classes, each by name."""

    def test_crc_flip_detected(self, tmp_path, capsys):
        topic = make_topic(tmp_path)
        seg = sorted(glob.glob(os.path.join(topic, "p0", "seg-*.colb")))[0]
        data = bytearray(open(seg, "rb").read())
        data[-20] ^= 0xFF
        open(seg, "wb").write(bytes(data))
        findings = fsck_path(topic)
        assert "SEGMENT_CRC" in rules_of(findings)
        rc, out = cli(capsys, "fsck", topic, "--json")
        assert rc == 1
        assert any(json.loads(ln)["rule"] == "SEGMENT_CRC"
                   for ln in out.strip().splitlines())

    def test_truncated_segment_detected(self, tmp_path, capsys):
        topic = make_topic(tmp_path)
        seg = sorted(glob.glob(os.path.join(topic, "p1", "seg-*.colb")))[0]
        data = open(seg, "rb").read()
        open(seg, "wb").write(data[: len(data) // 2])
        findings = fsck_path(topic)
        assert "SEGMENT_TRUNCATED" in rules_of(findings)
        assert cli_main(["fsck", topic]) == 1
        capsys.readouterr()

    def test_missing_checkpoint_manifest_detected_and_repaired(
            self, tmp_path, capsys):
        jdir = make_checkpoints(tmp_path)
        os.remove(os.path.join(jdir, "chk-2", "MANIFEST.json"))
        findings = fsck_path(jdir)
        assert "CHECKPOINT_MANIFEST_MISSING" in rules_of(findings)
        assert cli_main(["fsck", jdir]) == 1
        capsys.readouterr()
        # repair: the manifest-less dir is invisible to restore —
        # deleting it is the safe sweep; afterwards the dir is clean
        repaired = fsck_path(jdir, repair=True)
        assert any(f["rule"] == "CHECKPOINT_MANIFEST_MISSING"
                   and f["repaired"] for f in repaired)
        assert not os.path.exists(os.path.join(jdir, "chk-2"))
        assert fsck_path(jdir) == []
        # chk-1 still restores
        st = FsCheckpointStorage(os.path.dirname(jdir), "job")
        assert st.latest().checkpoint_id == 1

    def test_orphan_pre_marker_detected(self, tmp_path, capsys):
        topic = make_topic(tmp_path, commit=False)  # staged, no commit
        findings = fsck_path(topic)
        assert "ORPHAN_PRE_MARKER" in rules_of(findings)
        assert cli_main(["fsck", topic]) == 1
        capsys.readouterr()

    def test_stale_lease_detected(self, tmp_path, capsys):
        topic = make_topic(tmp_path)
        ldir = os.path.join(topic, "leases")
        os.makedirs(ldir)
        with open(os.path.join(ldir, "p0.json"), "w") as f:
            json.dump({"owner": "dead-producer", "epoch": 3,
                       "acquired_ms": 1000,
                       "deadline_ms": int(time.time() * 1000) - 60_000},
                      f)
        findings = fsck_path(topic)
        assert "STALE_LEASE" in rules_of(findings)
        assert cli_main(["fsck", topic]) == 1
        capsys.readouterr()


class TestRepairSafety:
    def test_repair_sweeps_orphans_only(self, tmp_path, capsys):
        topic = make_topic(tmp_path)
        # seed repairable debris: a .tmp leftover and an unreferenced
        # segment (torn prepare)
        tmp_file = os.path.join(topic, "p0", "seg-junk.colb.tmp")
        open(tmp_file, "wb").write(b"torn")
        age(tmp_file)
        orphan = os.path.join(
            topic, "p0", "seg-000000000099-c0000000099-e0.colb")
        open(orphan, "wb").write(b"unreferenced")
        age(orphan)
        # and an UNSAFE finding: a staged pre marker (not repairable)
        ap = TopicAppender(topic, partitions=2, segment_records=4)
        b = {"k": np.arange(4, dtype=np.int64),
             "v": np.arange(4, dtype=np.float64)}
        ap.stage(2, {0: [b]})
        findings = fsck_path(topic, repair=True)
        swept = {f["path"] for f in findings if f["repaired"]}
        assert tmp_file in swept and orphan in swept
        assert not os.path.exists(tmp_file)
        assert not os.path.exists(orphan)
        # the live staged transaction survived the repair pass
        assert ap.staged_ids() == [2]
        assert any(f["rule"] == "ORPHAN_PRE_MARKER"
                   and not f["repaired"] for f in findings)
        # committed data untouched
        r = TopicReader(topic)
        assert r.committed_offsets() == {0: 8, 1: 8}
        # repairable-swept findings no longer fail the exit code once
        # the unsafe ones are resolved (commit the staged txn)
        ap.commit(2)
        assert cli_main(["fsck", topic]) == 0
        capsys.readouterr()

    def test_repair_after_compaction_crash_debris(self, tmp_path,
                                                  capsys):
        topic = os.path.join(str(tmp_path), "keyed")
        create_topic(topic, 1, key_field="k")
        ap = TopicAppender(topic, partitions=1, segment_records=6)
        for cid in (1, 2):
            ap.stage(cid, {0: [{
                "k": np.arange(6, dtype=np.int64) % 3,
                "v": np.arange(6, dtype=np.int64) + cid * 10}]})
            ap.commit(cid)
        Compactor(topic, min_segments=2).compact()
        # superseded raw segments linger when the post-swap delete
        # crashed — simulate by re-creating one
        stray = os.path.join(
            topic, "p0", "seg-000000000000-c0000000001-e0.colb")
        open(stray, "wb").write(b"superseded debris")
        age(stray)
        findings = fsck_path(topic, repair=True)
        assert any(f["path"] == stray and f["repaired"]
                   for f in findings)
        assert cli_main(["fsck", topic]) == 0
        capsys.readouterr()


class TestDetect:
    def test_kind_autodetection(self, tmp_path):
        topic = make_topic(tmp_path)
        jdir = make_checkpoints(tmp_path)
        assert detect_kind(topic) == "topic"
        assert detect_kind(jdir) == "checkpoint"
        assert detect_kind(os.path.dirname(jdir)) == "checkpoint"
        assert detect_kind(glob.glob(jdir + "/chk-1")[0]) == "checkpoint"
        assert detect_kind(str(tmp_path)) is None


class TestCoordinationRecords:
    """PR 18: fsck learns the bus-tier coordination records — group
    membership generations (offset commits never run AHEAD of the
    manifest the fence admitted them against), the background
    cleaner's lease, and objstore conditional-put serialization
    scratch (swept only under the maintenance lock + age grace)."""

    def _bus_topic(self, tmp_path, scheme_prefix=""):
        from flink_tpu.log.bus import ConsumerGroups

        topic = scheme_prefix + os.path.join(str(tmp_path), "bus")
        ap = TopicAppender(topic, partitions=2, segment_records=4,
                           key_field="k")
        ap.stage(1, {p: [{"k": np.arange(8, dtype=np.int64),
                          "v": np.arange(8, dtype=np.float64)}]
                     for p in range(2)})
        ap.commit(1)
        gen, _ix, _n = ConsumerGroups.join(topic, "g", "m1")
        ConsumerGroups.commit(topic, "g", {0: 8}, generation=gen)
        return topic

    def test_coherent_bus_topic_is_clean(self, tmp_path):
        topic = self._bus_topic(tmp_path, "objstore://")
        assert fsck_path(topic) == []

    def test_offset_generation_ahead_of_manifest(self, tmp_path,
                                                 capsys):
        topic = self._bus_topic(tmp_path)
        opath = os.path.join(topic, "groups", "g", "p1.json")
        with open(opath, "w") as f:
            json.dump({"offset": 4, "generation": 7}, f)
        findings = fsck_path(topic)
        assert rules_of(findings) == {"GROUP_GENERATION_INCOHERENT"}
        assert "ahead" in findings[0]["message"]
        assert cli_main(["fsck", topic]) == 1
        capsys.readouterr()

    def test_generation_keyed_offset_without_manifest(self, tmp_path):
        topic = self._bus_topic(tmp_path)
        os.unlink(os.path.join(topic, "groups", "g",
                               "membership.json"))
        findings = fsck_path(topic)
        assert rules_of(findings) == {"GROUP_GENERATION_INCOHERENT"}
        assert "no membership manifest" in findings[0]["message"]

    def test_torn_membership_manifest(self, tmp_path):
        topic = self._bus_topic(tmp_path)
        with open(os.path.join(topic, "groups", "g",
                               "membership.json"), "w") as f:
            f.write('{"generation": 1, "mem')
        assert "CORRUPT_CONTROL" in rules_of(fsck_path(topic))

    def test_stale_cleaner_lease_flagged_live_and_released_quiet(
            self, tmp_path):
        topic = self._bus_topic(tmp_path)
        lease = os.path.join(topic, "cleaner.lease")
        now = int(time.time() * 1000)
        # live (unexpired) lease: healthy running service, no finding
        with open(lease, "w") as f:
            json.dump({"owner": "svc", "epoch": 1, "pid": os.getpid(),
                       "acquired_ms": now, "deadline_ms": now + 60_000},
                      f)
        assert fsck_path(topic) == []
        # released lease: clean shutdown, no finding
        with open(lease, "w") as f:
            json.dump({"owner": "svc", "epoch": 1, "pid": os.getpid(),
                       "acquired_ms": now, "deadline_ms": now + 60_000,
                       "released": True}, f)
        assert fsck_path(topic) == []
        # expired without release: crashed cleaner service
        with open(lease, "w") as f:
            json.dump({"owner": "svc", "epoch": 2, "pid": os.getpid(),
                       "acquired_ms": 0, "deadline_ms": 5}, f)
        findings = fsck_path(topic)
        assert rules_of(findings) == {"STALE_CLEANER_LEASE"}
        assert findings[0]["severity"] == "warn"
        assert "epoch+1" in findings[0]["message"]

    def test_lock_debris_found_through_objstore_and_local(
            self, tmp_path):
        topic = self._bus_topic(tmp_path, "objstore://")
        local = os.path.join(str(tmp_path), "bus")
        debris = os.path.join(local, "groups", "g", "p0.json.lock~")
        open(debris, "w").close()
        # the objstore fs hides the scratch from listdir, the local
        # view shows it raw — fsck reports it either way
        for path in (topic, local):
            findings = fsck_path(path)
            assert rules_of(findings) == {"OBJSTORE_LOCK_DEBRIS"}
            assert findings[0]["repairable"]

    def test_lock_debris_repair_respects_grace_and_maintenance_lock(
            self, tmp_path):
        from flink_tpu.log.topic import (release_maintenance_lock,
                                         try_maintenance_lock)

        topic = self._bus_topic(tmp_path, "objstore://")
        local = os.path.join(str(tmp_path), "bus")
        debris = os.path.join(local, "cleaner.lease.lock~")
        open(debris, "w").close()
        # fresh: a put_if may hold it this instant — kept
        findings = fsck_path(topic, repair=True)
        assert not findings[0]["repaired"] and os.path.exists(debris)
        # aged past the grace but the maintenance lock is busy — kept
        age(debris)
        fd = try_maintenance_lock(topic)
        assert fd is not None
        try:
            findings = fsck_path(topic, repair=True)
            assert (not findings[0]["repaired"]
                    and os.path.exists(debris))
        finally:
            release_maintenance_lock(topic, fd)
        # aged + lock free: swept
        findings = fsck_path(topic, repair=True)
        assert findings[0]["repaired"] and not os.path.exists(debris)
        assert fsck_path(topic) == []
