"""Test harness environment.

Tests run on CPU jax with a virtual 8-device mesh — the MiniCluster
analogue (ref: flink-runtime/.../runtime/minicluster/MiniCluster.java runs
a whole cluster in one JVM; here XLA's forced host platform device count
gives N "chips" in one process, so keyBy all_to_all, sharded state, and
checkpoint/reshard are all testable without TPUs). SURVEY.md §5 mapping.

Must run before jax initializes a backend, hence top of conftest.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the outer env selects the TPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon site hook (PYTHONPATH sitecustomize) re-selects the TPU platform
# regardless of JAX_PLATFORMS, so pin it at the config level too — before
# any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: the deterministic chaos slice (fixed
    # seeds, <60s) stays in; the long randomized soaks are `slow`
    config.addinivalue_line(
        "markers", "slow: long-running soak/benchmark tests, excluded "
        "from the tier-1 `-m 'not slow'` run")
    config.addinivalue_line(
        "markers", "chaos: fault-injection chaos tests (flink_tpu.faults)"
        " — every failure report prints the fault seed for replay")
    config.addinivalue_line(
        "markers", "batch: bounded-execution (execution.runtime-mode="
        "batch) tests — blocking shuffle, columnar exchange, final-only "
        "fires")
    config.addinivalue_line(
        "markers", "log: durable-log exchange tests (flink_tpu/log/) — "
        "embedded replayable topics, 2PC commit markers, exactly-once "
        "job chaining")
