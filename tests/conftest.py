"""Test harness environment.

Tests run on CPU jax with a virtual 8-device mesh — the MiniCluster
analogue (ref: flink-runtime/.../runtime/minicluster/MiniCluster.java runs
a whole cluster in one JVM; here XLA's forced host platform device count
gives N "chips" in one process, so keyBy all_to_all, sharded state, and
checkpoint/reshard are all testable without TPUs). SURVEY.md §5 mapping.

Must run before jax initializes a backend, hence top of conftest.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the outer env selects the TPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon site hook (PYTHONPATH sitecustomize) re-selects the TPU platform
# regardless of JAX_PLATFORMS, so pin it at the config level too — before
# any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Capability probe: shard_map moved from jax.experimental.shard_map to
# jax.shard_map across jax releases; flink_tpu.utils.jaxcompat resolves
# whichever spelling this container has. When NEITHER exists, every
# mesh/exchange test (marked ``shard_map``) SKIPS instead of erroring —
# tier-1 stays green-or-skipped on environments that reproduce the
# seed's jax.shard_map AttributeError failures.
from flink_tpu.utils.jaxcompat import HAS_SHARD_MAP  # noqa: E402


def pytest_collection_modifyitems(config, items):
    if HAS_SHARD_MAP:
        return
    skip = pytest.mark.skip(
        reason="jax.shard_map unavailable (neither jax.shard_map nor "
               "jax.experimental.shard_map imports in this container)")
    for item in items:
        if "shard_map" in item.keywords:
            item.add_marker(skip)


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: the deterministic chaos slice (fixed
    # seeds, <60s) stays in; the long randomized soaks are `slow`
    config.addinivalue_line(
        "markers", "slow: long-running soak/benchmark tests, excluded "
        "from the tier-1 `-m 'not slow'` run")
    config.addinivalue_line(
        "markers", "chaos: fault-injection chaos tests (flink_tpu.faults)"
        " — every failure report prints the fault seed for replay")
    config.addinivalue_line(
        "markers", "batch: bounded-execution (execution.runtime-mode="
        "batch) tests — blocking shuffle, columnar exchange, final-only "
        "fires")
    config.addinivalue_line(
        "markers", "log: durable-log exchange tests (flink_tpu/log/) — "
        "embedded replayable topics, 2PC commit markers, exactly-once "
        "job chaining")
    config.addinivalue_line(
        "markers", "shard_map: needs jax shard_map (device-mesh "
        "execution) — skipped by the conftest capability probe when "
        "neither jax.shard_map nor jax.experimental.shard_map exists")
    config.addinivalue_line(
        "markers", "analysis: static-analysis suite (flink_tpu/analysis"
        "/) — plan-analyzer rules, repo AST lints, and the dogfood gate "
        "that keeps the shipped tree at zero findings (tier-1)")
    config.addinivalue_line(
        "markers", "hostpool: shared host worker-pool plane (flink_tpu/"
        "parallel/hostpool.py) — pool unit tests and the serial-vs-"
        "parallel byte-identical parity gates on the sessions, "
        "windowAll, and spill golden pipelines (tier-1)")
    config.addinivalue_line(
        "markers", "subbatch: sub-batch fire/emit decoupling "
        "(pipeline.sub-batches) — K-parity gates on the golden Q5/"
        "sessions pipelines, checkpoint/restore across a sub-batch "
        "boundary, chaos at K=4, and the CLI smoke (tier-1)")
    config.addinivalue_line(
        "markers", "session: session-cluster runtime mode (flink_tpu/"
        "runtime/session.py) — slot quotas, FIFO admission queue, fair "
        "drain scheduling, autoscaler, per-job isolation, multi-tenant "
        "chaos, and the `session` CLI smoke (tier-1)")
    config.addinivalue_line(
        "markers", "changelog: changelog/retraction plane (records."
        "OP_FIELD) — op-typed retract streams, signed window lanes, "
        "session -U/+U refires, RetractSink exactly-once under chaos, "
        "and the lifted SQL shapes (agg-over-join, HAVING) (tier-1)")
    config.addinivalue_line(
        "markers", "firegate: fire-gated dispatch + piggybacked "
        "readiness (pipeline.fire-gate / pipeline.readiness, PROFILE.md "
        "§12) — gate-on/off byte-identity at K∈{1,2,4}, the host-fed "
        "late-refire gate predicate, readiness-mode parity, and the "
        "FIRE_GATE_INVALID / READINESS_INVALID analyzer rules (tier-1)")
