"""Live rescale: savepoint → stop → restore at a new mesh width, driven
through the coordinator against a REAL runner process (ref:
AdaptiveScheduler / reactive mode + the REST rescale endpoint;
key-group re-assignment happens in the reshard-on-restore path)."""
import os
import signal
import subprocess
import sys
import time

import pytest

from flink_tpu.api.sinks import FileTransactionalSink
from flink_tpu.config import Configuration
from flink_tpu.runtime.coordinator import JobCoordinator
from flink_tpu.runtime.rpc import RpcServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_mesh_runner(coord_port: int, runner_id: str,
                      devices: int = 8) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + os.path.join(REPO, "tests")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices}")
    return subprocess.Popen(
        [sys.executable, "-m", "flink_tpu.runtime.runner",
         "--coordinator", f"127.0.0.1:{coord_port}",
         "--runner-id", runner_id],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def wait_until(pred, timeout=120.0, interval=0.2, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.shard_map
def test_live_rescale_exactly_once(tmp_path):
    import runner_job

    coord = JobCoordinator(Configuration({
        "heartbeat.interval": 500,
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 3,
        "restart-strategy.fixed-delay.delay": 200,
    }))
    srv = RpcServer(coord)
    runner = None
    n_batches = 60
    try:
        runner = spawn_mesh_runner(srv.port, "mesh-r1")
        wait_until(lambda: "mesh-r1" in coord.runners, what="registration")

        sink_dir = str(tmp_path / "sink")
        coord.rpc_submit_job(
            "rescale-job", entry="runner_job:build",
            config={
                "cluster.mesh-devices": "2",
                "state.num-key-shards": 8,
                "state.slots-per-shard": 16,
                "pipeline.microbatch-size": 64,
                "execution.checkpointing.dir": str(tmp_path / "ckpt"),
                "execution.checkpointing.interval": 500,
                "test.n-batches": str(n_batches),
                "test.batch-sleep-ms": "200",
                "test.sink-dir": sink_dir,
            })
        wait_until(lambda: coord.rpc_job_status("rescale-job")["state"]
                   == "RUNNING", what="deploy")
        # let it make checkpointed progress at width 2
        time.sleep(4.0)

        resp = coord.rpc_rescale_job("rescale-job", devices=4)
        assert resp["ok"], resp

        # the rescale lands: attempt 2 at the new width
        wait_until(lambda: coord.rpc_job_status("rescale-job")["attempts"]
                   >= 2, what="rescale redeploy")
        wait_until(lambda: coord.rpc_job_status("rescale-job")["state"]
                   == "FINISHED", what="job finish")

        eg = coord.rpc_execution_graph("rescale-job")
        assert eg["parallelism"] == 4  # physical graph re-widened

        # exactly-once across the rescale boundary
        got = {}
        for r in FileTransactionalSink.committed_rows(sink_dir):
            k = (int(r["key"]), int(r["window_start"]))
            assert k not in got, f"duplicate window {k}"
            got[k] = int(r["count"])
        assert got == runner_job.golden_counts(n_batches)
    finally:
        if runner is not None:
            runner.terminate()
            runner.wait(timeout=15)
        srv.close()
        coord.close()


class TestRescaleLifecycle:
    """Rescale arming must not leak (review regressions)."""

    def _mk(self):
        from flink_tpu.runtime.rpc import RpcEndpoint

        class Gw(RpcEndpoint):
            def __init__(self):
                self.deployed = []
                self.savepoint_ok = True
                self.cancels = []

            def rpc_run_job(self, job_id, entry, config=None, attempt=1,
                            py_blobs=None, **kw):
                self.deployed.append((job_id, attempt))
                return {"accepted": True}

            def rpc_cancel_job(self, job_id, attempt=None):
                self.cancels.append((job_id, attempt))
                return {"ok": True}

            def rpc_trigger_savepoint(self, job_id, stop=False, token=None):
                self.savepoints = getattr(self, "savepoints", [])
                self.savepoints.append((job_id, stop, token))
                return {"ok": self.savepoint_ok}

        return Gw()

    def test_rejected_savepoint_disarms_rescale(self):
        gw = self._mk()
        gw.savepoint_ok = False  # job has no checkpointing configured
        gwsrv = RpcServer(gw)
        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
            coord.rpc_submit_job("j", entry="x:y",
                                 config={"cluster.mesh-devices": "2"})
            wait_until(lambda: gw.deployed, what="deploy")
            resp = coord.rpc_rescale_job("j", devices=4)
            assert resp["ok"]  # dispatched — rejection is async
            wait_until(lambda: coord.jobs["j"].pending_rescale is None,
                       what="disarm after rejected savepoint")
            # a new rescale is possible again (not 'already in flight')
            gw.savepoint_ok = True
            assert coord.rpc_rescale_job("j", devices=4)["ok"]
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_failure_disarms_pending_rescale(self):
        gw = self._mk()
        gwsrv = RpcServer(gw)
        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
            coord.rpc_submit_job("j", entry="x:y", config={})
            wait_until(lambda: gw.deployed, what="deploy")
            coord.jobs["j"].pending_rescale = 4  # armed, savepoint pending
            coord.rpc_report_failure("j", "task crashed")
            assert coord.jobs["j"].pending_rescale is None
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_rescale_cancel_is_attempt_fenced(self):
        gw = self._mk()
        gwsrv = RpcServer(gw)
        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
            coord.rpc_submit_job("j", entry="x:y",
                                 config={"cluster.mesh-devices": "2"})
            wait_until(lambda: gw.deployed, what="deploy")
            coord.rpc_rescale_job("j", devices=4)
            wait_until(lambda: getattr(gw, "savepoints", []),
                       what="savepoint dispatch")
            job_id, stop, token = gw.savepoints[0]
            assert stop  # stop-with-savepoint: old attempt halts at SP
            # completion must carry the rescale's token to be consumed
            coord.rpc_savepoint_complete("j", "/sp/path", token=token)
            wait_until(lambda: len(gw.deployed) >= 2, what="redeploy")
            wait_until(lambda: gw.cancels, what="cancel push")
            # the cancel carried the OLD attempt as its fence
            assert gw.cancels[0] == ("j", 1)
            assert gw.deployed[1] == ("j", 2)
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_unrelated_savepoint_does_not_consume_rescale(self):
        gw = self._mk()
        gwsrv = RpcServer(gw)
        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
            coord.rpc_submit_job("j", entry="x:y", config={})
            wait_until(lambda: gw.deployed, what="deploy")
            coord.rpc_rescale_job("j", devices=4)
            # a ROUTINE savepoint (no token) completes while the rescale
            # savepoint is still in flight: it must not fire the rescale
            coord.rpc_savepoint_complete("j", "/routine/sp")
            assert coord.jobs["j"].pending_rescale == 4  # still armed
            assert len(gw.deployed) == 1  # no redeploy
            assert coord.jobs["j"].last_savepoint == "/routine/sp"
        finally:
            srv.close(); gwsrv.close(); coord.close()


# ---------------------------------------------------------------------------
# process-level rescale (N -> M key-group repartition) — the tentpole e2e
# ---------------------------------------------------------------------------

def spawn_runner(coord_port: int, runner_id: str) -> subprocess.Popen:
    """Single-CPU-device runner (process-level rescale moves PROCESSES,
    not mesh width)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + os.path.join(REPO, "tests")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen(
        [sys.executable, "-m", "flink_tpu.runtime.runner",
         "--coordinator", f"127.0.0.1:{coord_port}",
         "--runner-id", runner_id],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def _committed_union(sink_dir: str) -> dict:
    """Union of every process's committed rows; asserts exactly-once
    (no (key, window) committed twice across any rescale cut)."""
    got = {}
    for pid in (0, 1):
        for r in FileTransactionalSink.committed_rows(f"{sink_dir}-p{pid}"):
            kk = (int(r["key"]), int(r["window_start"]))
            assert kk not in got, f"duplicate emission for {kk}"
            got[kk] = int(r["count"])
    return got


def test_q5_process_rescale_one_to_two_to_one_exactly_once(tmp_path):
    """THE acceptance run: the Q5 hot path rescaled 1→2→1 PROCESSES
    mid-run. Each cut is a savepoint-set barrier; restore repartitions
    every keyed op's key-group ranges to the new process set; committed
    output must be byte-identical to the unrescaled golden."""
    import runner_job_q5_rescale

    coord = JobCoordinator(Configuration({
        "heartbeat.interval": "300ms",
        "heartbeat.timeout": "8s",
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 6,
        "restart-strategy.fixed-delay.delay": "100ms",
    }))
    srv = RpcServer(coord)
    procs = {}
    n_batches, batch_size = 28, 512
    try:
        procs["r1"] = spawn_runner(srv.port, "r1")
        procs["r2"] = spawn_runner(srv.port, "r2")
        wait_until(lambda: len(coord.runners) == 2, 90,
                   what="both runners registered")
        sink_dir = str(tmp_path / "sink")
        coord.rpc_submit_job(
            "q5-rescale", entry="runner_job_q5_rescale:build",
            config={
                "test.n-batches": n_batches,
                "test.batch-size": batch_size,
                "test.batch-sleep-ms": 120,
                "test.sink-dir": sink_dir,
                "execution.checkpointing.dir": str(tmp_path / "chk"),
                "execution.checkpointing.interval": "300ms",
                "state.num-key-shards": 8,
                "state.slots-per-shard": 64,
            })
        # phase 1 (nproc=1): real committed progress first
        wait_until(
            lambda: len(FileTransactionalSink.committed_rows(
                f"{sink_dir}-p0")) > 0,
            90, what="first committed epoch at nproc=1")

        # cut 1: 1 -> 2 processes (key-group ranges split)
        resp = coord.rpc_rescale_job("q5-rescale", devices=1, processes=2)
        assert resp["ok"], resp
        wait_until(
            lambda: (coord.jobs["q5-rescale"].state == "RUNNING"
                     and int(coord.jobs["q5-rescale"].config.get(
                         "cluster.num-processes", 1)) == 2),
            120, what="running at 2 processes")
        # proof the SECOND process owns live state now: it commits
        wait_until(
            lambda: len(FileTransactionalSink.committed_rows(
                f"{sink_dir}-p1")) > 0,
            120, what="process 1 committing after the split")

        # cut 2: 2 -> 1 processes (key-group ranges merge back)
        resp = coord.rpc_rescale_job("q5-rescale", devices=1, processes=1)
        assert resp["ok"], resp
        wait_until(
            lambda: (coord.jobs["q5-rescale"].state in
                     ("RUNNING", "FINISHED")
                     and int(coord.jobs["q5-rescale"].config.get(
                         "cluster.num-processes", 1)) == 1),
            120, what="running at 1 process again")

        wait_until(lambda: coord.jobs["q5-rescale"].state == "FINISHED",
                   180, what="job FINISHED after both cuts")

        # byte-identical to the unrescaled golden, exactly-once
        got = _committed_union(sink_dir)
        assert got == runner_job_q5_rescale.golden_counts(
            n_batches, batch_size)

        # time-to-rescale observability: both rescales recorded
        st = coord.rpc_job_status("q5-rescale")
        metrics = st["rescale"]["metrics"]
        assert metrics.get("coordinator.rescale.armed") >= 2
        assert metrics.get("coordinator.rescale.redeploy") >= 2
        assert metrics.get("coordinator.rescale.duration_ms.count") >= 2
        assert metrics.get("coordinator.rescale.duration_ms.max") > 0
        assert st["rescale"]["last_completed_at"] is not None
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        srv.close()
        coord.close()


def test_runner_kill_during_process_rescale_never_strands(tmp_path):
    """Chaos: SIGKILL the runner hosting the old attempt right after the
    rescale is armed (the savepoint may or may not have landed — both
    races are legal). Invariant: the job always ends FINISHED with
    golden output, either rescaled or with the intent cleanly disarmed
    — never stranded mid-handshake."""
    import runner_job_q5_rescale

    coord = JobCoordinator(Configuration({
        "heartbeat.interval": "200ms",
        "heartbeat.timeout": "1500ms",
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 6,
        "restart-strategy.fixed-delay.delay": "100ms",
    }))
    srv = RpcServer(coord)
    procs = {}
    n_batches, batch_size = 16, 512
    try:
        # 3 runners: after the SIGKILL the fleet must still be able to
        # host a 2-process redeploy (savepoint-landed race branch)
        for rid in ("r1", "r2", "r3"):
            procs[rid] = spawn_runner(srv.port, rid)
        wait_until(lambda: len(coord.runners) == 3, 90,
                   what="all runners registered")
        sink_dir = str(tmp_path / "sink")
        coord.rpc_submit_job(
            "chaos-rescale", entry="runner_job_q5_rescale:build",
            config={
                "test.n-batches": n_batches,
                "test.batch-size": batch_size,
                "test.batch-sleep-ms": 120,
                "test.sink-dir": sink_dir,
                "execution.checkpointing.dir": str(tmp_path / "chk"),
                "execution.checkpointing.interval": "300ms",
                "state.num-key-shards": 8,
                "state.slots-per-shard": 64,
            })
        wait_until(
            lambda: len(FileTransactionalSink.committed_rows(
                f"{sink_dir}-p0")) > 0,
            90, what="committed progress before the kill")
        victim_id = coord.jobs["chaos-rescale"].assigned_runners[0]

        resp = coord.rpc_rescale_job("chaos-rescale", devices=1,
                                     processes=2)
        assert resp["ok"], resp
        procs[victim_id].send_signal(signal.SIGKILL)
        procs[victim_id].wait(timeout=10)

        wait_until(lambda: coord.jobs["chaos-rescale"].state == "FINISHED",
                   180, what="job FINISHED despite the mid-rescale kill")
        j = coord.jobs["chaos-rescale"]
        assert j.pending_rescale is None          # never stranded armed
        assert j.rescale_token is None
        got = _committed_union(sink_dir)
        assert got == runner_job_q5_rescale.golden_counts(
            n_batches, batch_size)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        srv.close()
        coord.close()


# ---------------------------------------------------------------------------
# reactive controller (fake clock — _rescale_tick(now=...) is injectable)
# ---------------------------------------------------------------------------

class _Gw:
    """Fake runner gateway; **kw-tolerant so HA leader-epoch fences and
    future wire fields never break it."""

    def __init__(self):
        self.deployed = []       # (job_id, attempt, config)
        self.cancels = []
        self.savepoints = []     # (job_id, stop, token)
        self.savepoint_ok = True

    def rpc_run_job(self, job_id, entry, config=None, attempt=1,
                    py_blobs=None, **kw):
        self.deployed.append((job_id, attempt, dict(config or {})))
        return {"accepted": True}

    def rpc_cancel_job(self, job_id, attempt=None, **kw):
        self.cancels.append((job_id, attempt))
        return {"ok": True}

    def rpc_trigger_savepoint(self, job_id, stop=False, token=None, **kw):
        self.savepoints.append((job_id, stop, token))
        return {"ok": self.savepoint_ok}


def _quiet_coordinator(config=None):
    """Coordinator whose monitor loop is STOPPED: the loop drives
    _rescale_tick with REAL time, which would race a fake-clock test.
    _closed is flipped before the first iteration can observe metrics;
    the 1.2s drain outlasts one full sleep(<=1.0) cycle."""
    coord = JobCoordinator(config or Configuration({}))
    coord._closed = True
    time.sleep(1.2)
    return coord


class TestReactiveController:
    """The pressure-driven policy loop, demonstrated under a fake clock:
    sustained out-of-band pressure arms, hysteresis never flaps."""

    def _up(self, config=None):
        gw = _Gw()
        gwsrv = RpcServer(gw)
        coord = _quiet_coordinator(config)
        srv = RpcServer(coord)
        coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
        return gw, gwsrv, coord, srv

    def _submit(self, coord, job_id="j", **over):
        cfg = {"cluster.mesh-devices": "2", "rescale.mode": "reactive",
               "rescale.sustained-window": "1s",
               "rescale.cooldown": "0ms"}
        cfg.update(over)
        coord.rpc_submit_job(job_id, entry="x:y", config=cfg)
        wait_until(lambda: coord.jobs[job_id].state == "RUNNING",
                   what="deploy")

    def test_sustained_high_pressure_arms_scale_out(self):
        gw, gwsrv, coord, srv = self._up()
        try:
            self._submit(coord)
            coord.jobs["j"].last_metrics = {"backpressure_pct": 95.0}
            t0 = time.time()
            coord._rescale_tick(now=t0)        # leaves the band: clock starts
            coord._rescale_tick(now=t0 + 0.5)  # not sustained yet
            assert coord.jobs["j"].pending_rescale is None
            coord._rescale_tick(now=t0 + 1.1)  # sustained >= 1s: arm
            j = coord.jobs["j"]
            assert j.pending_rescale == 4      # doubling, 128 % 4 == 0
            assert j.rescale_token is not None
            # the arm ran the REAL handshake: stop-with-savepoint out
            wait_until(lambda: gw.savepoints, what="stop-with-savepoint")
            assert gw.savepoints[0] == ("j", True, j.rescale_token)
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_one_in_band_sample_resets_the_clock(self):
        gw, gwsrv, coord, srv = self._up()
        try:
            self._submit(coord)
            j = coord.jobs["j"]
            t0 = time.time()
            j.last_metrics = {"backpressure_pct": 95.0}
            coord._rescale_tick(now=t0)
            j.last_metrics = {"backpressure_pct": 45.0}  # transient dip
            coord._rescale_tick(now=t0 + 0.6)            # resets the clock
            j.last_metrics = {"backpressure_pct": 95.0}
            coord._rescale_tick(now=t0 + 0.7)
            coord._rescale_tick(now=t0 + 1.5)  # only 0.8s sustained
            assert j.pending_rescale is None
            coord._rescale_tick(now=t0 + 1.8)  # 1.1s sustained now
            assert j.pending_rescale == 4
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_oscillating_pressure_never_flaps(self):
        gw, gwsrv, coord, srv = self._up()
        try:
            self._submit(coord)
            j = coord.jobs["j"]
            t0 = time.time()
            # violent oscillation ACROSS the band, sampled faster than
            # the sustained window — each side flip restarts the clock
            for i in range(40):
                j.last_metrics = {"backpressure_pct":
                                  95.0 if i % 2 == 0 else 5.0}
                coord._rescale_tick(now=t0 + i * 0.3)
            assert j.pending_rescale is None
            assert not gw.savepoints
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_cooldown_gates_rearm_after_a_completed_rescale(self):
        gw, gwsrv, coord, srv = self._up()
        try:
            self._submit(coord, **{"rescale.cooldown": "60s"})
            j = coord.jobs["j"]
            t0 = time.time()
            j.last_rescale_done_at = t0  # a rescale just completed
            j.last_metrics = {"backpressure_pct": 95.0}
            for i in range(10):
                coord._rescale_tick(now=t0 + i)  # sustained, but cooling
            assert j.pending_rescale is None
            coord._rescale_tick(now=t0 + 61)
            coord._rescale_tick(now=t0 + 62.5)   # sustained past cooldown
            assert j.pending_rescale == 4
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_sustained_low_pressure_arms_scale_in(self):
        gw, gwsrv, coord, srv = self._up()
        try:
            self._submit(coord)
            j = coord.jobs["j"]
            t0 = time.time()
            j.last_metrics = {"backpressure_pct": 3.0,
                              "drain_busy_pct": 4.0}
            coord._rescale_tick(now=t0)
            coord._rescale_tick(now=t0 + 1.1)
            assert j.pending_rescale == 1  # halving from 2
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_queued_fleet_demand_defers_scale_out(self):
        gw, gwsrv, coord, srv = self._up()
        try:
            self._submit(coord)
            # a parked job is unmet fleet demand: scaling OUT now would
            # starve it further — the controller waits its turn
            coord.rpc_submit_job("parked", entry="x:y",
                                 config={"cluster.mesh-devices": "64"})
            wait_until(lambda: coord.jobs["parked"].state ==
                       "WAITING_FOR_RESOURCES", what="parked job")
            j = coord.jobs["j"]
            t0 = time.time()
            j.last_metrics = {"backpressure_pct": 95.0}
            coord._rescale_tick(now=t0)
            coord._rescale_tick(now=t0 + 2.0)
            assert j.pending_rescale is None  # deferred, not armed
            # scale-IN is still allowed under queued demand
            j.last_metrics = {"backpressure_pct": 3.0}
            coord._rescale_tick(now=t0 + 3.0)
            coord._rescale_tick(now=t0 + 4.5)
            assert j.pending_rescale == 1
        finally:
            srv.close(); gwsrv.close(); coord.close()


# ---------------------------------------------------------------------------
# chaos: a fault at every phase of the handshake (arm/savepoint/redeploy)
# ---------------------------------------------------------------------------

class TestRescaleChaosPhases:
    """The job must end rescaled or cleanly disarmed — never stranded —
    whichever phase of the handshake the fault lands in."""

    def test_arm_fault_fails_the_rpc_and_leaves_job_clean(self):
        from flink_tpu import faults

        gw = _Gw()
        gwsrv = RpcServer(gw)
        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
            coord.rpc_submit_job("j", entry="x:y",
                                 config={"cluster.mesh-devices": "2"})
            wait_until(lambda: gw.deployed, what="deploy")
            plan = faults.FaultPlan.from_spec("rescale.arm=raise x1",
                                              seed=7)
            with plan.activate():
                resp = coord.rpc_rescale_job("j", devices=4)
                assert not resp["ok"] and "arm failed" in resp["reason"]
                j = coord.jobs["j"]
                assert j.pending_rescale is None      # disarmed
                assert j.rescale_token is None
                assert j.state == "RUNNING"           # job untouched
                assert not gw.savepoints              # never dispatched
                # x1 consumed: the retry goes through
                assert coord.rpc_rescale_job("j", devices=4)["ok"]
                wait_until(lambda: gw.savepoints, what="retry savepoint")
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_savepoint_fault_disarms_async_and_retry_succeeds(self):
        from flink_tpu import faults

        gw = _Gw()
        gwsrv = RpcServer(gw)
        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
            coord.rpc_submit_job("j", entry="x:y",
                                 config={"cluster.mesh-devices": "2"})
            wait_until(lambda: gw.deployed, what="deploy")
            plan = faults.FaultPlan.from_spec("rescale.savepoint=raise x1",
                                              seed=7)
            with plan.activate():
                resp = coord.rpc_rescale_job("j", devices=4)
                assert resp["ok"]  # ack = DISPATCHED; the fault is async
                wait_until(lambda: coord.jobs["j"].pending_rescale is None,
                           what="async disarm after savepoint fault")
                assert coord.jobs["j"].state == "RUNNING"
                assert not gw.savepoints  # push died before any trigger
                assert coord.rpc_rescale_job("j", devices=4)["ok"]
                wait_until(lambda: gw.savepoints, what="retry savepoint")
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_redeploy_fault_retries_onto_surviving_runner(self):
        from flink_tpu import faults

        gw1, gw2 = _Gw(), _Gw()
        gwsrv1, gwsrv2 = RpcServer(gw1), RpcServer(gw2)
        coord = JobCoordinator(Configuration({
            "restart-strategy.type": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 3,
            "restart-strategy.fixed-delay.delay": "50ms",
        }))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "127.0.0.1", 8,
                                      port=gwsrv1.port)
            coord.rpc_register_runner("r2", "127.0.0.1", 8,
                                      port=gwsrv2.port)
            coord.rpc_submit_job("j", entry="x:y",
                                 config={"cluster.mesh-devices": "2"})
            wait_until(lambda: gw1.deployed or gw2.deployed, what="deploy")
            plan = faults.FaultPlan.from_spec("rescale.redeploy=raise x1",
                                              seed=7)
            with plan.activate():
                assert coord.rpc_rescale_job("j", devices=4)["ok"]
                wait_until(lambda: gw1.savepoints or gw2.savepoints,
                           what="stop-with-savepoint")
                tok = (gw1.savepoints or gw2.savepoints)[0][2]
                coord.rpc_savepoint_complete("j", "/sp/p0", token=tok)
                # first rescale redeploy raises; the failure routes
                # through restart and lands on the OTHER runner
                wait_until(
                    lambda: coord.jobs["j"].state == "RUNNING"
                    and coord.jobs["j"].config.get(
                        "cluster.mesh-devices") == "4",
                    what="job running at the new width after the retry")
            j = coord.jobs["j"]
            assert j.pending_rescale is None       # handshake fully done
            assert j.last_rescale_done_at is not None
            snap = coord.registry.snapshot()
            assert snap["coordinator.rescale.duration_ms.count"] >= 1
            # the rescaled topology reached a gateway (retry path)
            new_deploys = [d for d in gw1.deployed + gw2.deployed
                           if d[2].get("cluster.mesh-devices") == "4"]
            assert new_deploys
        finally:
            srv.close(); gwsrv1.close(); gwsrv2.close(); coord.close()


# ---------------------------------------------------------------------------
# leader takeover with an armed rescale (the satellite bugfix regression)
# ---------------------------------------------------------------------------

class TestRescaleTakeover:
    """PRE-FIX: a dispatcher takeover FORGOT an armed rescale — the
    intent was in memory only, so the new leader re-adopted the job and
    the stop-with-savepoint never re-fired; the handshake hung armed
    forever. The fix persists the intent in the JobStore record and has
    re-adoption re-trigger the savepoint under the stored token."""

    def test_takeover_preserves_and_resumes_armed_rescale(self, tmp_path):
        gw = _Gw()
        gwsrv = RpcServer(gw)
        ha_cfg = Configuration(
            {"high-availability.dir": str(tmp_path / "ha")})
        coord_a = JobCoordinator(ha_cfg)
        srv_a = RpcServer(coord_a)
        coord_b = None
        srv_b = None
        try:
            coord_a.rpc_register_runner("r1", "127.0.0.1", 8,
                                        port=gwsrv.port)
            coord_a.rpc_submit_job("j", entry="x:y",
                                   config={"cluster.mesh-devices": "2"})
            wait_until(lambda: gw.deployed, what="deploy on leader A")
            assert coord_a.rpc_rescale_job("j", devices=4,
                                           processes=1)["ok"]
            wait_until(lambda: gw.savepoints, what="savepoint dispatch")
            tok = gw.savepoints[0][2]
            assert tok is not None

            # leader A dies mid-handshake: intent armed, savepoint
            # dispatched but NEVER completed
            srv_a.close()
            coord_a.close()

            coord_b = JobCoordinator(ha_cfg)
            srv_b = RpcServer(coord_b)
            j = coord_b.jobs["j"]
            # the durable intent survived the takeover verbatim
            assert j.pending_rescale == 4
            assert j.rescale_token == tok

            # the runner re-registers CARRYING the live execution: it is
            # re-adopted in place AND the armed rescale's
            # stop-with-savepoint re-fires under the SAME token
            n_sp = len(gw.savepoints)
            coord_b.rpc_register_runner(
                "r1", "127.0.0.1", 8, port=gwsrv.port,
                jobs=[{"job_id": "j", "attempt": 1}])
            wait_until(lambda: coord_b.jobs["j"].state == "RUNNING",
                       what="re-adoption")
            assert coord_b.jobs["j"].attempts == 1  # no redeploy
            wait_until(lambda: len(gw.savepoints) > n_sp,
                       what="re-triggered stop-with-savepoint")
            assert gw.savepoints[-1] == ("j", True, tok)

            # completion on the NEW leader consumes the recovered intent
            coord_b.rpc_savepoint_complete("j", "/sp/p0", token=tok)
            wait_until(
                lambda: any(a == 2 for _, a, _c in gw.deployed),
                what="redeploy at the new width")
            jid, att, conf = gw.deployed[-1]
            assert conf["cluster.mesh-devices"] == "4"
            assert conf["execution.checkpointing.restore"] == "/sp/p0"
            assert conf["cluster.rescale-from"] == "/sp/p0"
            assert coord_b.jobs["j"].pending_rescale is None
        finally:
            if srv_b is not None:
                srv_b.close()
            if coord_b is not None:
                coord_b.close()
            gwsrv.close()
            coord_a.close()
