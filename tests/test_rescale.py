"""Live rescale: savepoint → stop → restore at a new mesh width, driven
through the coordinator against a REAL runner process (ref:
AdaptiveScheduler / reactive mode + the REST rescale endpoint;
key-group re-assignment happens in the reshard-on-restore path)."""
import os
import subprocess
import sys
import time

import pytest

from flink_tpu.api.sinks import FileTransactionalSink
from flink_tpu.config import Configuration
from flink_tpu.runtime.coordinator import JobCoordinator
from flink_tpu.runtime.rpc import RpcServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_mesh_runner(coord_port: int, runner_id: str,
                      devices: int = 8) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + os.path.join(REPO, "tests")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices}")
    return subprocess.Popen(
        [sys.executable, "-m", "flink_tpu.runtime.runner",
         "--coordinator", f"127.0.0.1:{coord_port}",
         "--runner-id", runner_id],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def wait_until(pred, timeout=120.0, interval=0.2, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.shard_map
def test_live_rescale_exactly_once(tmp_path):
    import runner_job

    coord = JobCoordinator(Configuration({
        "heartbeat.interval": 500,
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 3,
        "restart-strategy.fixed-delay.delay": 200,
    }))
    srv = RpcServer(coord)
    runner = None
    n_batches = 60
    try:
        runner = spawn_mesh_runner(srv.port, "mesh-r1")
        wait_until(lambda: "mesh-r1" in coord.runners, what="registration")

        sink_dir = str(tmp_path / "sink")
        coord.rpc_submit_job(
            "rescale-job", entry="runner_job:build",
            config={
                "cluster.mesh-devices": "2",
                "state.num-key-shards": 8,
                "state.slots-per-shard": 16,
                "pipeline.microbatch-size": 64,
                "execution.checkpointing.dir": str(tmp_path / "ckpt"),
                "execution.checkpointing.interval": 500,
                "test.n-batches": str(n_batches),
                "test.batch-sleep-ms": "200",
                "test.sink-dir": sink_dir,
            })
        wait_until(lambda: coord.rpc_job_status("rescale-job")["state"]
                   == "RUNNING", what="deploy")
        # let it make checkpointed progress at width 2
        time.sleep(4.0)

        resp = coord.rpc_rescale_job("rescale-job", devices=4)
        assert resp["ok"], resp

        # the rescale lands: attempt 2 at the new width
        wait_until(lambda: coord.rpc_job_status("rescale-job")["attempts"]
                   >= 2, what="rescale redeploy")
        wait_until(lambda: coord.rpc_job_status("rescale-job")["state"]
                   == "FINISHED", what="job finish")

        eg = coord.rpc_execution_graph("rescale-job")
        assert eg["parallelism"] == 4  # physical graph re-widened

        # exactly-once across the rescale boundary
        got = {}
        for r in FileTransactionalSink.committed_rows(sink_dir):
            k = (int(r["key"]), int(r["window_start"]))
            assert k not in got, f"duplicate window {k}"
            got[k] = int(r["count"])
        assert got == runner_job.golden_counts(n_batches)
    finally:
        if runner is not None:
            runner.terminate()
            runner.wait(timeout=15)
        srv.close()
        coord.close()


class TestRescaleLifecycle:
    """Rescale arming must not leak (review regressions)."""

    def _mk(self):
        from flink_tpu.runtime.rpc import RpcEndpoint

        class Gw(RpcEndpoint):
            def __init__(self):
                self.deployed = []
                self.savepoint_ok = True
                self.cancels = []

            def rpc_run_job(self, job_id, entry, config=None, attempt=1,
                            py_blobs=None, **kw):
                self.deployed.append((job_id, attempt))
                return {"accepted": True}

            def rpc_cancel_job(self, job_id, attempt=None):
                self.cancels.append((job_id, attempt))
                return {"ok": True}

            def rpc_trigger_savepoint(self, job_id, stop=False, token=None):
                self.savepoints = getattr(self, "savepoints", [])
                self.savepoints.append((job_id, stop, token))
                return {"ok": self.savepoint_ok}

        return Gw()

    def test_rejected_savepoint_disarms_rescale(self):
        gw = self._mk()
        gw.savepoint_ok = False  # job has no checkpointing configured
        gwsrv = RpcServer(gw)
        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
            coord.rpc_submit_job("j", entry="x:y",
                                 config={"cluster.mesh-devices": "2"})
            wait_until(lambda: gw.deployed, what="deploy")
            resp = coord.rpc_rescale_job("j", devices=4)
            assert resp["ok"]  # dispatched — rejection is async
            wait_until(lambda: coord.jobs["j"].pending_rescale is None,
                       what="disarm after rejected savepoint")
            # a new rescale is possible again (not 'already in flight')
            gw.savepoint_ok = True
            assert coord.rpc_rescale_job("j", devices=4)["ok"]
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_failure_disarms_pending_rescale(self):
        gw = self._mk()
        gwsrv = RpcServer(gw)
        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
            coord.rpc_submit_job("j", entry="x:y", config={})
            wait_until(lambda: gw.deployed, what="deploy")
            coord.jobs["j"].pending_rescale = 4  # armed, savepoint pending
            coord.rpc_report_failure("j", "task crashed")
            assert coord.jobs["j"].pending_rescale is None
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_rescale_cancel_is_attempt_fenced(self):
        gw = self._mk()
        gwsrv = RpcServer(gw)
        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
            coord.rpc_submit_job("j", entry="x:y",
                                 config={"cluster.mesh-devices": "2"})
            wait_until(lambda: gw.deployed, what="deploy")
            coord.rpc_rescale_job("j", devices=4)
            wait_until(lambda: getattr(gw, "savepoints", []),
                       what="savepoint dispatch")
            job_id, stop, token = gw.savepoints[0]
            assert stop  # stop-with-savepoint: old attempt halts at SP
            # completion must carry the rescale's token to be consumed
            coord.rpc_savepoint_complete("j", "/sp/path", token=token)
            wait_until(lambda: len(gw.deployed) >= 2, what="redeploy")
            wait_until(lambda: gw.cancels, what="cancel push")
            # the cancel carried the OLD attempt as its fence
            assert gw.cancels[0] == ("j", 1)
            assert gw.deployed[1] == ("j", 2)
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_unrelated_savepoint_does_not_consume_rescale(self):
        gw = self._mk()
        gwsrv = RpcServer(gw)
        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
            coord.rpc_submit_job("j", entry="x:y", config={})
            wait_until(lambda: gw.deployed, what="deploy")
            coord.rpc_rescale_job("j", devices=4)
            # a ROUTINE savepoint (no token) completes while the rescale
            # savepoint is still in flight: it must not fire the rescale
            coord.rpc_savepoint_complete("j", "/routine/sp")
            assert coord.jobs["j"].pending_rescale == 4  # still armed
            assert len(gw.deployed) == 1  # no redeploy
            assert coord.jobs["j"].last_savepoint == "/routine/sp"
        finally:
            srv.close(); gwsrv.close(); coord.close()
