"""Checkpoint / resume / exactly-once tests — the RescalingITCase /
UnalignedCheckpointITCase analogues (ref: flink-tests/.../test/
checkpointing/*.java), driven on the local driver with simulated failure
(re-running the job from the latest checkpoint with replayable sources).
"""
import os

import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import TransactionalCollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import SlidingEventTimeWindows, TumblingEventTimeWindows
from flink_tpu.checkpoint.storage import FsCheckpointStorage
from flink_tpu.config import Configuration
from flink_tpu.ops import aggregates
from flink_tpu.ops.window import WindowOperator
from flink_tpu.time.watermarks import WatermarkStrategy


def make_conf(tmp_path, extra=None):
    c = {
        "state.num-key-shards": 8,
        "state.slots-per-shard": 64,
        "pipeline.microbatch-size": 128,
        "execution.checkpointing.dir": str(tmp_path),
        "execution.checkpointing.interval": 1,  # every loop pass (1ms wall)
    }
    c.update(extra or {})
    return Configuration(c)


def failing_source(n_batches, fail_after=None):
    """Deterministic generator; optionally raises mid-stream to simulate
    a task failure (ref: the throwing-mapper pattern in ITCases)."""

    def gen(split, i):
        if i >= n_batches:
            return None
        if fail_after is not None and i == fail_after:
            raise RuntimeError("injected failure")
        rng = np.random.default_rng(1000 * int(split) + i)
        keys = rng.integers(0, 10, 64).astype(np.int64)
        ts = np.sort(rng.integers(i * 500, i * 500 + 1000, 64)).astype(np.int64)
        return {"k": keys}, ts

    return gen


def golden_counts(n_batches, n_splits=1):
    expect = {}
    for split in range(n_splits):
        for i in range(n_batches):
            rng = np.random.default_rng(1000 * split + i)
            keys = rng.integers(0, 10, 64).astype(np.int64)
            ts = np.sort(rng.integers(i * 500, i * 500 + 1000, 64)).astype(np.int64)
            for k, t in zip(keys, ts):
                kk = (int(k), (int(t) // 1000) * 1000)
                expect[kk] = expect.get(kk, 0) + 1
    return expect


class TestCheckpointStorage:
    def test_save_load_latest_retention(self, tmp_path):
        st = FsCheckpointStorage(str(tmp_path), "job1", retained=2)
        for i in range(1, 5):
            st.save(i, {"x": np.arange(i), "checkpoint_id": i})
        hs = st.list_complete()
        assert [h.checkpoint_id for h in hs] == [3, 4]
        latest = st.latest()
        assert latest.checkpoint_id == 4
        payload = FsCheckpointStorage.load(latest)
        assert list(payload["x"]) == [0, 1, 2, 3]

    def test_savepoints_never_retired(self, tmp_path):
        st = FsCheckpointStorage(str(tmp_path), "job1", retained=1)
        st.save(1, {"a": 1}, savepoint=True)
        for i in range(2, 5):
            st.save(i, {"a": i})
        hs = st.list_complete()
        assert [(h.checkpoint_id, h.is_savepoint) for h in hs] == [
            (1, True), (4, False)]

    def test_incomplete_checkpoint_ignored(self, tmp_path):
        st = FsCheckpointStorage(str(tmp_path), "job1")
        st.save(1, {"a": 1})
        os.makedirs(os.path.join(str(tmp_path), "job1", "chk-2"))
        # chk-2 has no manifest → ignored
        assert st.latest().checkpoint_id == 1


class TestExactlyOnceResume:
    def test_fail_resume_exactly_once(self, tmp_path):
        """Run → crash mid-stream → resume from latest checkpoint →
        committed rows must equal the golden exactly (no loss, no dupes).
        """
        n_batches = 12
        sink = TransactionalCollectSink()

        def build(env, source):
            return (env.from_source(
                        source,
                        WatermarkStrategy.for_bounded_out_of_orderness(1000))
                    .key_by("k")
                    .window(TumblingEventTimeWindows.of(1000))
                    .count()
                    .add_sink(sink))

        env = StreamExecutionEnvironment(make_conf(tmp_path))
        build(env, GeneratorSource(failing_source(n_batches, fail_after=7)))
        with pytest.raises(RuntimeError, match="injected failure"):
            env.execute("eo-job")

        committed_before = len(sink.committed)
        # resume: same job name, restore=latest; sources replay from
        # recorded positions; uncommitted epochs discarded
        env2 = StreamExecutionEnvironment(make_conf(
            tmp_path, {"execution.checkpointing.restore": "latest"}))
        build(env2, GeneratorSource(failing_source(n_batches)))
        env2.execute("eo-job")

        got = {}
        for r in sink.committed:
            kk = (int(r["key"]), int(r["window_start"]))
            assert kk not in got, f"duplicate emission for {kk}"
            got[kk] = int(r["count"])
        assert got == golden_counts(n_batches)
        assert committed_before < len(sink.committed)

    def test_resume_without_failure_is_noop_restart(self, tmp_path):
        """Restoring from the final checkpoint of a completed job and
        re-running yields no duplicate commits (positions at end)."""
        n_batches = 4
        sink = TransactionalCollectSink()
        env = StreamExecutionEnvironment(make_conf(tmp_path))
        (env.from_source(GeneratorSource(failing_source(n_batches)),
                         WatermarkStrategy.for_bounded_out_of_orderness(1000))
         .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
         .add_sink(sink))
        env.execute("noop-job")
        n1 = len(sink.committed)
        assert {(int(r["key"]), int(r["window_start"])): int(r["count"])
                for r in sink.committed} == golden_counts(n_batches)

        env2 = StreamExecutionEnvironment(make_conf(
            tmp_path, {"execution.checkpointing.restore": "latest"}))
        (env2.from_source(GeneratorSource(failing_source(n_batches)),
                          WatermarkStrategy.for_bounded_out_of_orderness(1000))
         .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
         .add_sink(sink))
        env2.execute("noop-job")
        assert len(sink.committed) == n1  # nothing new: already at end


@pytest.mark.shard_map
class TestReshard:
    def test_restore_local_snapshot_into_mesh(self):
        """Rescale 1 → 8 devices: snapshot from a local operator restores
        into a sharded one (key-shard space fixed, rows re-blocked)."""
        import jax

        from flink_tpu.parallel.mesh import make_mesh_plan

        rng = np.random.default_rng(9)
        keys = rng.integers(0, 200, 500).astype(np.int64)
        ts = rng.integers(0, 3000, 500).astype(np.int64)

        op1 = WindowOperator(SlidingEventTimeWindows.of(2000, 1000),
                             aggregates.count(), num_shards=8,
                             slots_per_shard=64)
        op1.process_batch(keys, ts, {})
        snap = op1.snapshot_state()

        mp = make_mesh_plan(8, 64, jax.devices()[:8])
        op8 = WindowOperator(SlidingEventTimeWindows.of(2000, 1000),
                             aggregates.count(), mesh_plan=mp)
        op8.restore_state(snap)

        f1 = op1.advance_watermark(5000).materialize()
        f8 = op8.advance_watermark(5000).materialize()
        a = sorted(zip(f1["key"], f1["window_end"], f1["count"]))
        b = sorted(zip(f8["key"], f8["window_end"], f8["count"]))
        assert a == b and len(a) > 0

    def test_restore_mesh_snapshot_into_local(self):
        """Rescale 8 → 1 device."""
        import jax

        from flink_tpu.parallel.mesh import make_mesh_plan

        rng = np.random.default_rng(10)
        keys = rng.integers(0, 100, 400).astype(np.int64)
        ts = rng.integers(0, 2000, 400).astype(np.int64)

        mp = make_mesh_plan(8, 32, jax.devices()[:8])
        op8 = WindowOperator(TumblingEventTimeWindows.of(1000),
                             aggregates.count(), mesh_plan=mp)
        op8.process_batch(keys, ts, {})
        snap = op8.snapshot_state()

        op1 = WindowOperator(TumblingEventTimeWindows.of(1000),
                             aggregates.count(), num_shards=8,
                             slots_per_shard=32)
        op1.restore_state(snap)

        f8 = op8.advance_watermark(3000).materialize()
        f1 = op1.advance_watermark(3000).materialize()
        a = sorted(zip(f8["key"], f8["window_end"], f8["count"]))
        b = sorted(zip(f1["key"], f1["window_end"], f1["count"]))
        assert a == b and len(a) > 0


class _CrashOnCommitSink(TransactionalCollectSink):
    """Crashes between the checkpoint manifest write and the 2PC commit
    round — the exact window the staged-epoch persistence covers."""

    def __init__(self, crash_at_cid):
        super().__init__()
        self._crash_at = crash_at_cid
        self._crashed = False

    def notify_checkpoint_complete(self, checkpoint_id):
        if checkpoint_id == self._crash_at and not self._crashed:
            self._crashed = True
            raise RuntimeError("injected crash before commit")
        super().notify_checkpoint_complete(checkpoint_id)


class TestTwoPhaseCommitRecovery:
    def test_crash_between_save_and_commit_recommits_epoch(self, tmp_path):
        """Checkpoint N is saved but the process dies before the sink
        commit round. On restore the staged epoch persisted INSIDE
        checkpoint N must be re-committed, not aborted — otherwise that
        epoch's output is lost forever (sources replay only post-N).
        ref: TwoPhaseCommitSinkFunction pending-transaction state."""
        n_batches = 12
        sink = _CrashOnCommitSink(crash_at_cid=3)

        def build(env, source):
            return (env.from_source(
                        source,
                        WatermarkStrategy.for_bounded_out_of_orderness(1000))
                    .key_by("k")
                    .window(TumblingEventTimeWindows.of(1000))
                    .count()
                    .add_sink(sink))

        env = StreamExecutionEnvironment(make_conf(tmp_path))
        build(env, GeneratorSource(failing_source(n_batches)))
        with pytest.raises(RuntimeError, match="injected crash before commit"):
            env.execute("cp-crash-job")

        env2 = StreamExecutionEnvironment(make_conf(
            tmp_path, {"execution.checkpointing.restore": "latest"}))
        build(env2, GeneratorSource(failing_source(n_batches)))
        env2.execute("cp-crash-job")

        got = {}
        for r in sink.committed:
            kk = (int(r["key"]), int(r["window_start"]))
            assert kk not in got, f"duplicate emission for {kk}"
            got[kk] = int(r["count"])
        assert got == golden_counts(n_batches)

    def test_restore_with_no_checkpoint_aborts_reused_sink(self, tmp_path):
        """Failure BEFORE the first checkpoint: restore finds nothing, yet
        a sink instance reused across attempts must still drop the
        crashed attempt's pending rows or the full replay duplicates
        them."""
        n_batches = 6
        sink = TransactionalCollectSink()
        conf = {"execution.checkpointing.interval": 10_000_000}  # never mid-run

        def build(env, source):
            return (env.from_source(
                        source,
                        WatermarkStrategy.for_bounded_out_of_orderness(1000))
                    .key_by("k")
                    .window(TumblingEventTimeWindows.of(1000))
                    .count()
                    .add_sink(sink))

        env = StreamExecutionEnvironment(make_conf(tmp_path, conf))
        build(env, GeneratorSource(failing_source(n_batches, fail_after=4)))
        with pytest.raises(RuntimeError, match="injected failure"):
            env.execute("early-crash-job")

        conf2 = dict(conf, **{"execution.checkpointing.restore": "latest"})
        env2 = StreamExecutionEnvironment(make_conf(tmp_path, conf2))
        build(env2, GeneratorSource(failing_source(n_batches)))
        env2.execute("early-crash-job")

        got = {}
        for r in sink.committed:
            kk = (int(r["key"]), int(r["window_start"]))
            assert kk not in got, f"duplicate emission for {kk}"
            got[kk] = int(r["count"])
        assert got == golden_counts(n_batches)

    def test_crashed_attempt_drain_never_pollutes_next_attempt(self, tmp_path):
        """A crashing run must take its emit-drain thread down WITH it.
        The drain holds fired-but-undelivered windows; left running (it
        is a daemon), it would deliver them into the sink instance the
        NEXT attempt reuses — duplicates after recovery. A large
        emit-defer forces fires to still be queued at crash time, making
        the race deterministic (ref: StreamTask.cleanUpInternal cancels
        the output flusher before failover)."""
        n_batches = 6
        sink = TransactionalCollectSink()
        conf = {
            "execution.checkpointing.interval": 10_000_000,
            "pipeline.emit-defer": "500ms",  # fires sit queued at crash
        }

        def build(env, source):
            return (env.from_source(
                        source,
                        WatermarkStrategy.for_bounded_out_of_orderness(1000))
                    .key_by("k")
                    .window(TumblingEventTimeWindows.of(1000))
                    .count()
                    .add_sink(sink))

        env = StreamExecutionEnvironment(make_conf(tmp_path, conf))
        build(env, GeneratorSource(failing_source(n_batches, fail_after=4)))
        with pytest.raises(RuntimeError, match="injected failure"):
            env.execute("drain-leak-job")

        conf2 = dict(conf, **{"execution.checkpointing.restore": "latest",
                              "pipeline.emit-defer": "0ms"})
        env2 = StreamExecutionEnvironment(make_conf(tmp_path, conf2))
        build(env2, GeneratorSource(failing_source(n_batches)))
        env2.execute("drain-leak-job")

        # outlive attempt 1's deferral window: a leaked drain thread
        # would deliver its held fires into the reused sink about now
        import time as _time
        _time.sleep(0.8)
        assert sink._pending == [], (
            "crashed attempt's drain thread delivered into the reused sink")
        got = {}
        for r in sink.committed:
            kk = (int(r["key"]), int(r["window_start"]))
            assert kk not in got, f"duplicate emission for {kk}"
            got[kk] = int(r["count"])
        assert got == golden_counts(n_batches)
