"""Durable log exchange (flink_tpu/log/): append-only segmented
topics with 2PC commit markers, committed-offset read isolation,
offset-addressed replayable LogSource splits, and two jobs chained
through a topic producing output identical to the fused single job —
plus the tier-1 CLI smoke chaining two ``python -m flink_tpu run
--local`` jobs through a topic (ISSUE 3)."""
import json
import os

import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import TransactionalCollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.log import (
    LogError,
    LogSink,
    LogSource,
    TopicReader,
    create_topic,
    describe_topic,
)
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = pytest.mark.log


def word_gen(n_batches, batch=64, vocab=10):
    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(500 + i)
        words = rng.integers(0, vocab, batch).astype(np.int64)
        ts = (i * batch + np.arange(batch, dtype=np.int64)) * 10
        return {"word": words, "ts_ms": ts}, ts

    return gen


def committed_view(sink):
    return sorted((int(r["key"]), int(r["window_start"]), int(r["count"]))
                  for r in sink.committed)


def run_consumer(topic, shards=8):
    sink = TransactionalCollectSink()
    env = StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": shards, "state.slots-per-shard": 64}))
    (env.from_source(LogSource(topic, ts_field="ts_ms"),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
     .key_by("word").window(TumblingEventTimeWindows.of(1000)).count()
     .add_sink(sink))
    env.execute("log-consumer")
    return committed_view(sink)


def golden_fused(n_batches, shards=8):
    sink = TransactionalCollectSink()
    env = StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": shards, "state.slots-per-shard": 64}))
    (env.from_source(GeneratorSource(word_gen(n_batches)),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
     .key_by("word").window(TumblingEventTimeWindows.of(1000)).count()
     .add_sink(sink))
    env.execute("log-golden")
    return committed_view(sink)


class TestTopicCore:
    def _batch(self, lo, n):
        return {"k": np.arange(lo, lo + n, dtype=np.int64),
                "v": np.arange(n, dtype=np.int64) * 10}

    def test_stage_commit_offsets_and_segment_roll(self, tmp_path):
        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1, segment_records=5)
        sink.write(self._batch(0, 12))
        sink.prepare_commit(1)
        assert TopicReader(topic).committed_offsets() == {0: 0}
        sink.notify_checkpoint_complete(1)
        r = TopicReader(topic)
        assert r.committed_offsets() == {0: 12}
        # 12 rows at 5/segment -> 3 sealed segments
        d = describe_topic(topic)
        assert d["segments"] == {"0": 3}
        rows = [b["k"].tolist() for _, b in r.read(0)]
        assert [x for blk in rows for x in blk] == list(range(12))

    def test_commit_idempotent_and_staged_stack(self, tmp_path):
        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1)
        sink.write(self._batch(0, 4))
        sink.prepare_commit(1)
        sink.write(self._batch(4, 4))
        sink.prepare_commit(2)  # stacks ABOVE staged txn 1
        assert sink.staged_transaction_ids() == [1, 2]
        sink.notify_checkpoint_complete(2)  # commits both, in order
        sink.notify_checkpoint_complete(2)  # replayed commit: no-op
        r = TopicReader(topic)
        assert r.committed_offsets() == {0: 8}
        got = [x for _, b in r.read(0) for x in b["k"].tolist()]
        assert got == list(range(8))

    def test_abort_rolls_segments_and_offsets_back(self, tmp_path):
        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1)
        sink.write(self._batch(0, 4))
        sink.prepare_commit(1)
        sink.notify_checkpoint_complete(1)
        sink.write(self._batch(4, 4))
        sink.prepare_commit(2)
        assert sink._appender.next_offset(0) == 8
        sink.abort_uncommitted()
        assert sink.staged_transaction_ids() == []
        assert sink._appender.next_offset(0) == 4
        # the rolled-back segment file is gone, not just unreferenced
        segs = os.listdir(tmp_path / "t" / "p0")
        assert len([s for s in segs if s.endswith(".colb")]) == 1
        # offsets reuse after rollback: the next epoch lands at 4
        sink.write(self._batch(100, 2))
        sink.prepare_commit(3)
        sink.notify_checkpoint_complete(3)
        got = [x for _, b in TopicReader(topic).read(0)
               for x in b["k"].tolist()]
        assert got == [0, 1, 2, 3, 100, 101]

    def test_partition_count_is_fixed(self, tmp_path):
        topic = str(tmp_path / "t")
        create_topic(topic, 2)
        with pytest.raises(LogError, match="refusing to reopen"):
            LogSink(topic, key_field="k", partitions=3)

    def test_schema_drift_rejected(self, tmp_path):
        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1)
        sink.write(self._batch(0, 2))
        sink.prepare_commit(1)
        sink.notify_checkpoint_complete(1)
        sink.write({"other": np.arange(2, dtype=np.int64)})
        with pytest.raises(LogError, match="schema drift"):
            sink.prepare_commit(2)

    def test_multi_partition_routing_preserves_per_key_order(
            self, tmp_path):
        topic = str(tmp_path / "t")
        sink = LogSink(topic, key_field="k", partitions=4,
                       segment_records=7)
        rng = np.random.default_rng(3)
        keys = rng.integers(0, 20, 200).astype(np.int64)
        seq = np.arange(200, dtype=np.int64)
        sink.write({"k": keys, "seq": seq})
        sink.prepare_commit(1)
        sink.notify_checkpoint_complete(1)
        r = TopicReader(topic)
        assert sum(r.committed_offsets().values()) == 200
        per_key = {}
        for p in range(4):
            for _, b in r.read(p):
                for k, s in zip(b["k"].tolist(), b["seq"].tolist()):
                    per_key.setdefault(k, []).append(s)
        for k, seqs in per_key.items():
            assert seqs == sorted(seqs), f"key {k} out of order"


class TestCommittedOffsetIsolation:
    def test_staged_is_never_observable(self, tmp_path):
        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1)
        sink.write({"k": np.arange(6, dtype=np.int64)})
        sink.prepare_commit(1)
        # pre-committed (durable!) but uncommitted: invisible to the
        # reader AND to LogSource
        assert TopicReader(topic).committed_offsets() == {0: 0}
        assert list(LogSource(topic).open_split("0")) == []
        assert describe_topic(topic)["staged_transactions"] == [1]
        sink.notify_checkpoint_complete(1)
        got = [x for _, b in TopicReader(topic).read(0)
               for x in b["k"].tolist()]
        assert got == list(range(6))

    def test_orphan_segments_are_swept_not_read(self, tmp_path):
        """A crash between segment write and pre-marker rename leaves an
        unreferenced segment: readers never see it; the writer's
        recovery sweep removes it."""
        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1)
        sink.write({"k": np.arange(3, dtype=np.int64)})
        sink.prepare_commit(1)
        sink.notify_checkpoint_complete(1)
        # forge the torn-prepare debris: a sealed segment, no marker
        orphan = tmp_path / "t" / "p0" / "seg-000000000099-c0000000099-e0.colb"
        with open(tmp_path / "t" / "p0" /
                  os.listdir(tmp_path / "t" / "p0")[0], "rb") as f:
            orphan.write_bytes(f.read())
        got = [x for _, b in TopicReader(topic).read(0)
               for x in b["k"].tolist()]
        assert got == [0, 1, 2]
        sink2 = LogSink(topic, partitions=1)  # recovery sweeps at init
        assert not orphan.exists()

    def test_truncated_committed_segment_fails_loudly(self, tmp_path):
        """Reader at a truncated tail: a committed range that cannot be
        read back whole is data loss, surfaced as ColumnarError — never
        a silent short read."""
        from flink_tpu.formats_columnar import ColumnarError

        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1)
        sink.write({"k": np.arange(50, dtype=np.int64)})
        sink.prepare_commit(1)
        sink.notify_checkpoint_complete(1)
        pdir = tmp_path / "t" / "p0"
        (seg,) = [n for n in os.listdir(pdir) if n.endswith(".colb")]
        raw = (pdir / seg).read_bytes()
        (pdir / seg).write_bytes(raw[:len(raw) - 9])  # tear the tail off
        with pytest.raises(ColumnarError):
            list(TopicReader(topic).read(0))


class TestLogSourceReplay:
    def _topic(self, tmp_path, n=20, segment_records=6):
        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1,
                       segment_records=segment_records)
        sink.write({"k": np.arange(n, dtype=np.int64),
                    "ts_ms": np.arange(n, dtype=np.int64) * 100})
        sink.prepare_commit(1)
        sink.notify_checkpoint_complete(1)
        return topic

    def test_positions_are_offsets(self, tmp_path):
        topic = self._topic(tmp_path)
        src = LogSource(topic, ts_field="ts_ms")
        pos = 0
        rows = []
        for data, ts in src.open_split("0"):
            pos = src.position_after(pos, data, ts)
            rows.extend(data["k"].tolist())
        assert rows == list(range(20)) and pos == 20

    def test_replay_resumes_mid_segment_mid_block(self, tmp_path):
        topic = self._topic(tmp_path)
        src = LogSource(topic, ts_field="ts_ms")
        for start in (0, 1, 5, 6, 7, 13, 19, 20):
            got = [x for data, _ in src.open_split("0", start_pos=start)
                   for x in data["k"].tolist()]
            assert got == list(range(start, 20)), start

    def test_missing_ts_field_is_loud(self, tmp_path):
        topic = self._topic(tmp_path)
        src = LogSource(topic, ts_field="nope")
        with pytest.raises(LogError, match="ts_field"):
            list(src.open_split("0"))

    def test_missing_topic_is_loud(self, tmp_path):
        with pytest.raises(LogError, match="no such log topic"):
            LogSource(str(tmp_path / "absent")).splits()


class TestLogSink2pcRecovery:
    def test_restore_rebuilds_and_commits_covered_epoch(self, tmp_path):
        """Crash between the checkpoint manifest write and the commit
        round, worst case: the dead attempt's cleanup also deleted the
        staged segments. The covering checkpoint's payload rebuilds and
        commits the epoch."""
        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1)
        sink.write({"k": np.arange(4, dtype=np.int64)})
        sink.prepare_commit(7)
        snap = sink.snapshot_staged()
        sink.abort_uncommitted()  # crashed attempt's cleanup
        assert TopicReader(topic).committed_offsets() == {0: 0}
        sink2 = LogSink(topic, partitions=1)
        sink2.restore_staged(snap, 7)
        got = [x for _, b in TopicReader(topic).read(0)
               for x in b["k"].tolist()]
        assert got == [0, 1, 2, 3]

    def test_restore_rolls_uncovered_epochs_back(self, tmp_path):
        """Epochs staged AFTER the restored checkpoint replay from
        source positions — restore must roll their segments back."""
        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1)
        sink.write({"k": np.arange(4, dtype=np.int64)})
        sink.prepare_commit(1)
        sink.notify_checkpoint_complete(1)
        sink.write({"k": np.arange(4, 8, dtype=np.int64)})
        sink.prepare_commit(2)  # staged, never committed, uncovered
        snap = sink.snapshot_staged()
        sink2 = LogSink(topic, partitions=1)
        sink2.restore_staged(snap, 1)  # restored checkpoint is 1
        assert sink2.staged_transaction_ids() == []
        assert TopicReader(topic).committed_offsets() == {0: 4}
        assert sink2._appender.next_offset(0) == 4

    def test_fresh_sink_rolls_dead_attempts_staged_back(self, tmp_path):
        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1)
        sink.write({"k": np.arange(4, dtype=np.int64)})
        sink.prepare_commit(1)  # dead attempt: staged, never committed
        sink2 = LogSink(topic, partitions=1)  # new owner
        assert sink2.staged_transaction_ids() == []
        assert TopicReader(topic).committed_offsets() == {0: 0}

    def test_successor_epoch_rolls_lower_epoch_staged_back(self, tmp_path):
        """The construction-time sweep runs at the default epoch and the
        abort fence skips higher epochs — set_attempt_epoch must re-run
        recovery so a successor actually rolls a dead lower-epoch
        attempt's staged transactions back."""
        topic = str(tmp_path / "t")
        dead = LogSink(topic, partitions=1)
        dead.set_attempt_epoch(1)
        dead.write({"k": np.arange(4, dtype=np.int64)})
        dead.prepare_commit(1)  # staged at epoch 1, attempt dies
        succ = LogSink(topic, partitions=1)
        succ.set_attempt_epoch(2)
        assert succ.staged_transaction_ids() == []
        assert succ._appender.next_offset(0) == 0

    def test_deposed_abort_cannot_roll_back_successor_staged(
            self, tmp_path):
        """Abort is EPOCH-FENCED: a deposed attempt's late-running
        failure-path cleanup must not delete the live successor's
        staged transaction (the marker-file analogue of the
        epoch-qualified part-name fence)."""
        topic = str(tmp_path / "t")
        deposed = LogSink(topic, partitions=1)
        deposed.set_attempt_epoch(1)
        succ = LogSink(topic, partitions=1)
        succ.set_attempt_epoch(2)
        succ.write({"k": np.arange(4, dtype=np.int64)})
        succ.prepare_commit(5)
        deposed.abort_uncommitted()  # the deposed attempt wakes up
        assert succ.staged_transaction_ids() == [5]
        succ.notify_checkpoint_complete(5)
        got = [x for _, b in TopicReader(topic).read(0)
               for x in b["k"].tolist()]
        assert got == [0, 1, 2, 3]

    def test_vanished_precommit_marker_is_loud(self, tmp_path):
        """stage() returned True, so a missing pre marker at commit
        time is a rolled-back LIVE transaction (single-writer
        discipline violated) — committing must raise, never silently
        drop the epoch. Checked on the PROTOCOL path: the commit round
        walks the in-memory live-staged set too, so the vanished cid
        is not silently absent from the on-disk staged listing."""
        topic = str(tmp_path / "t")
        sink = LogSink(topic, partitions=1)
        sink.write({"k": np.arange(4, dtype=np.int64)})
        sink.prepare_commit(1)
        os.remove(tmp_path / "t" / "txn" / "pre-0000000001.json")
        with pytest.raises(LogError, match="vanished"):
            sink.notify_checkpoint_complete(1)

    def test_deposed_commit_cannot_publish_successor_staged(
            self, tmp_path):
        """Commit is epoch-fenced like abort: a deposed attempt's
        lagging commit round finds the successor's pre marker for the
        same cid and must NOT publish it — the successor's covering
        checkpoint hasn't completed, so committing would expose (and,
        after the successor replays, duplicate) uncovered rows."""
        topic = str(tmp_path / "t")
        deposed = LogSink(topic, partitions=1)
        deposed.set_attempt_epoch(1)
        succ = LogSink(topic, partitions=1)
        succ.set_attempt_epoch(2)
        succ.write({"k": np.arange(4, dtype=np.int64)})
        succ.prepare_commit(5)
        deposed.commit_transaction(5)  # lagging deposed commit round
        assert describe_topic(topic)["committed_transactions"] == []
        assert succ.staged_transaction_ids() == [5]
        succ.notify_checkpoint_complete(5)  # the real owner commits
        assert describe_topic(topic)["committed_transactions"] == [5]


class TestChainedJobs:
    N = 6

    def test_chain_matches_fused_job(self, tmp_path):
        topic = str(tmp_path / "words")
        env = StreamExecutionEnvironment(Configuration({}))
        env.from_source(GeneratorSource(word_gen(self.N))).add_sink(
            LogSink(topic, key_field="word", partitions=2))
        env.execute("log-producer")
        assert run_consumer(topic) == golden_fused(self.N)

    def test_chain_with_checkpointed_producer(self, tmp_path):
        """Producer committing epoch-by-epoch with its checkpoints (the
        streaming path) feeds the same bytes as the terminal-commit
        bounded path."""
        topic = str(tmp_path / "words")
        env = StreamExecutionEnvironment(Configuration({
            "execution.checkpointing.dir": str(tmp_path / "ckpt"),
            "execution.checkpointing.interval": 1,
        }))
        env.from_source(GeneratorSource(word_gen(self.N))).add_sink(
            LogSink(topic, key_field="word", partitions=2))
        env.execute("log-producer-chk")
        d = describe_topic(topic)
        assert d["staged_transactions"] == []
        assert len(d["committed_transactions"]) >= 1
        assert run_consumer(topic) == golden_fused(self.N)


class TestCliChainSmoke:
    """Tier-1 CLI smoke: two ``python -m flink_tpu run --local`` jobs
    chained through a log topic; the consumer's committed FileSink
    output is diffed against independently computed counts."""

    def _cli(self, capsys, *argv):
        from flink_tpu.cli import main as cli_main

        rc = cli_main(list(argv))
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1]) if out else {}

    def test_two_local_jobs_chained_through_topic(self, tmp_path, capsys):
        import runner_job_log_chain as jobs

        log_dir = str(tmp_path / "logroot")
        sink_dir = str(tmp_path / "sink")
        n = 5
        rc, out = self._cli(
            capsys, "run", "--local",
            "--entry", "runner_job_log_chain:produce",
            "--job-id", "chain-a",
            "--conf", f"log.dir={log_dir}",
            "--conf", "log.partitions=2",
            "--conf", f"test.n-batches={n}")
        assert rc == 0 and out["state"] == "FINISHED"
        assert out["records_in"] == n * jobs.BATCH

        rc, out = self._cli(
            capsys, "run", "--local",
            "--entry", "runner_job_log_chain:consume",
            "--job-id", "chain-b",
            "--conf", f"log.dir={log_dir}",
            "--conf", f"test.sink-dir={sink_dir}",
            "--conf", "state.num-key-shards=8",
            "--conf", "state.slots-per-shard=64")
        assert rc == 0 and out["state"] == "FINISHED"
        assert out["records_in"] == n * jobs.BATCH

        # the log CLI sees the committed topic
        rc, topic_info = self._cli(
            capsys, "log", os.path.join(log_dir, jobs.TOPIC))
        assert rc == 0
        assert topic_info["partitions"] == 2
        assert topic_info["committed_records"] == n * jobs.BATCH
        assert topic_info["staged_transactions"] == []

        # diff committed consumer output against independent counts
        got = jobs.read_committed_counts(sink_dir)
        assert got == jobs.expected_counts(n) and len(got) > 0

    def test_log_command_on_missing_topic_fails(self, tmp_path, capsys):
        from flink_tpu.cli import main as cli_main

        # exit 2 = usage/path error (the analyze/lint contract; ISSUE 9
        # aligned `log` with it — a typo'd TOPIC_DIR must not read like
        # corrupt topic state)
        assert cli_main(["log", str(tmp_path / "nope")]) == 2
        assert "no such log topic" in capsys.readouterr().err
