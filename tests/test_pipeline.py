"""End-to-end pipeline tests through the fluent DataStream API — the
analogue of the reference's streaming examples ITCases (ref:
flink-examples/.../streaming/examples/wordcount/WordCount.java and
flink-tests windowing ITCases on MiniCluster)."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import SlidingEventTimeWindows, TumblingEventTimeWindows
from flink_tpu.config import Configuration, StateOptions
from flink_tpu.ops.aggregates import count, max_of, sum_of
from flink_tpu.records import hash_string_key
from flink_tpu.time.watermarks import WatermarkStrategy


def small_env():
    conf = Configuration({
        "state.num-key-shards": 8,
        "state.slots-per-shard": 64,
        "pipeline.microbatch-size": 256,
    })
    return StreamExecutionEnvironment.get_execution_environment(conf)


class TestWordCount:
    def test_streaming_wordcount_tumbling_1s(self):
        """BASELINE.json config #0: streaming WordCount, 1s tumbling
        count window."""
        sentences = [
            (0, "to be or not to be"),
            (500, "that is the question"),
            (1200, "to be is to do"),
            (1700, "do be do"),
            (2500, "question the question"),
        ]
        env = small_env()

        def tokenize(data, ts, valid):
            words, wts = [], []
            for line, t in zip(data["line"], ts):
                for w in line.split():
                    words.append(hash_string_key(w))
                    wts.append(t)
            return ({"word": np.array(words, np.int64)},
                    np.array(wts, np.int64), np.ones(len(words), bool))

        lines = {"line": np.array([s for _, s in sentences], object)}
        ts = np.array([t for t, _ in sentences], np.int64)
        sink = (
            env.from_collection(lines, ts)
            .map_with_timestamps(tokenize, name="tokenize")
            .key_by("word")
            .window(TumblingEventTimeWindows.of(1000))
            .count()
            .collect()
        )
        env.execute("wordcount")

        # golden: python wordcount per 1s window
        expect = {}
        for t, line in sentences:
            for w in line.split():
                k = (hash_string_key(w), (t // 1000) * 1000)
                expect[k] = expect.get(k, 0) + 1
        got = {(int(r["key"]), int(r["window_start"])): int(r["count"])
               for r in sink.rows}
        assert got == expect

    def test_map_filter_chain_and_sum(self):
        env = small_env()
        n = 1000
        rng = np.random.default_rng(3)
        vals = rng.integers(0, 100, n).astype(np.int64)
        keys = rng.integers(0, 10, n).astype(np.int64)
        ts = np.sort(rng.integers(0, 5000, n)).astype(np.int64)

        sink = (
            env.from_collection({"k": keys, "v": vals}, ts)
            .map(lambda d: {**d, "v2": d["v"] * 2})
            .filter(lambda d: d["v2"] >= 100)          # keep v >= 50
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1000))
            .sum("v2")
            .collect()
        )
        env.execute()

        expect = {}
        for k, v, t in zip(keys, vals, ts):
            if v * 2 >= 100:
                kk = (int(k), (int(t) // 1000) * 1000)
                expect[kk] = expect.get(kk, 0) + int(v) * 2
        got = {(int(r["key"]), int(r["window_start"])): int(r["sum_v2"])
               for r in sink.rows}
        assert got == expect

    def test_sliding_window_with_out_of_orderness(self):
        env = small_env()
        rng = np.random.default_rng(11)
        n = 2000
        keys = rng.integers(0, 5, n).astype(np.int64)
        ts = rng.integers(0, 8000, n).astype(np.int64)  # heavily out of order

        stream = env.from_collection({"k": keys}, ts).assign_timestamps_and_watermarks(
            WatermarkStrategy.for_bounded_out_of_orderness(8000))
        sink = (
            stream.key_by("k")
            .window(SlidingEventTimeWindows.of(2000, 1000))
            .count()
            .collect()
        )
        env.execute()

        expect = {}
        for k, t in zip(keys, ts):
            start = (int(t) // 1000) * 1000
            for ws in (start, start - 1000):
                if ws >= 0 or True:
                    if ws <= t < ws + 2000:
                        kk = (int(k), ws)
                        expect[kk] = expect.get(kk, 0) + 1
        got = {(int(r["key"]), int(r["window_start"])): int(r["count"])
               for r in sink.rows}
        assert got == expect

    def test_two_stage_windowing_q5_shape(self):
        """Stage 1: per-key count per tumbling second; stage 2: global
        max of those counts per second (Nexmark Q5's hot-item shape)."""
        env = small_env()
        rng = np.random.default_rng(5)
        n = 3000
        keys = rng.integers(0, 20, n).astype(np.int64)
        ts = np.sort(rng.integers(0, 4000, n)).astype(np.int64)

        counts = (
            env.from_collection({"k": keys}, ts)
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1000))
            .count()
        )
        sink = (
            counts
            .map(lambda d: {"wstart": d["window_start"], "cnt": d["count"]})
            .key_by(lambda d: np.asarray(d["wstart"], np.int64) // 1000)
            .window(TumblingEventTimeWindows.of(1000))
            .max("cnt")
            .collect()
        )
        env.execute()

        stage1 = {}
        for k, t in zip(keys, ts):
            kk = (int(k), (int(t) // 1000) * 1000)
            stage1[kk] = stage1.get(kk, 0) + 1
        expect = {}
        for (k, ws), c in stage1.items():
            expect[ws // 1000] = max(expect.get(ws // 1000, 0), c)
        got = {int(r["key"]): int(r["max_cnt"]) for r in sink.rows}
        assert got == expect

    def test_generator_source_multiple_splits(self):
        env = small_env()

        def gen(split, i):
            if i >= 3:
                return None
            base = int(split) * 10_000 + i * 1000
            ts = np.arange(base, base + 500, 10, dtype=np.int64) % 3000
            keys = np.full(len(ts), int(split), np.int64)
            return {"k": keys}, ts

        src = GeneratorSource(gen, n_splits=2)
        sink = (
            env.from_source(src, WatermarkStrategy.for_bounded_out_of_orderness(3000))
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1000))
            .count()
            .collect()
        )
        env.execute()
        expect = {}
        for split in ("0", "1"):
            for i in range(3):
                base = int(split) * 10_000 + i * 1000
                for t in range(base, base + 500, 10):
                    t = t % 3000
                    kk = (int(split), (t // 1000) * 1000)
                    expect[kk] = expect.get(kk, 0) + 1
        got = {(int(r["key"]), int(r["window_start"])): int(r["count"])
               for r in sink.rows}
        assert got == expect

    def test_union(self):
        env = small_env()
        a = env.from_collection({"k": np.array([1, 1], np.int64)},
                                np.array([100, 200], np.int64))
        b = env.from_collection({"k": np.array([1, 2], np.int64)},
                                np.array([300, 1500], np.int64))
        sink = (
            a.union(b)
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1000))
            .count()
            .collect()
        )
        env.execute()
        got = {(int(r["key"]), int(r["window_start"])): int(r["count"])
               for r in sink.rows}
        assert got == {(1, 0): 3, (2, 1000): 1}
