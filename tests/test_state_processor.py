"""State processor API (ref: flink-state-processor-api
SavepointReader/Writer ITCases: read keyed state out of a savepoint,
transform it, write a restorable one)."""
import os

import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.state_processor import SavepointWriter, load_savepoint
from flink_tpu.time.watermarks import WatermarkStrategy


def run_job(tmp_path, restore_path=None, n_batches=4, sink=None):
    conf = {
        "state.num-key-shards": 4, "state.slots-per-shard": 32,
        "pipeline.microbatch-size": 64,
        "execution.checkpointing.dir": str(tmp_path),
        "execution.checkpointing.interval": 1,
    }
    if restore_path:
        conf["execution.checkpointing.restore"] = restore_path

    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        return ({"k": rng.integers(0, 8, 64).astype(np.int64),
                 "v": rng.integers(1, 9, 64).astype(np.int64)},
                np.sort(rng.integers(i * 500, i * 500 + 900, 64)).astype(np.int64))

    env = StreamExecutionEnvironment(Configuration(conf))
    sink = sink if sink is not None else CollectSink()
    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(400))
     .key_by("k").window(TumblingEventTimeWindows.of(1_000))
     .sum("v").add_sink(sink))
    env.execute("sp-job")
    return sink


def latest_chk(tmp_path):
    from flink_tpu.checkpoint.storage import FsCheckpointStorage

    return FsCheckpointStorage(str(tmp_path), "sp-job").latest().path


class TestReader:
    def test_read_operators_and_keyed_rows(self, tmp_path):
        run_job(tmp_path)
        from flink_tpu.checkpoint.storage import FsCheckpointStorage

        st = FsCheckpointStorage(str(tmp_path), "sp-job")
        # a MID-stream checkpoint still holds live panes (the final one
        # is post-purge and may be empty)
        first = st.list_complete()[0]
        r = load_savepoint(first.path)
        ops = r.operator_ids()
        assert len(ops) == 1
        rows = r.window_keyed_rows(ops[0])
        assert set(rows) == {"key", "ring_pane", "sums", "maxs", "mins",
                             "count"}
        assert len(rows["key"]) > 0
        assert set(rows["key"].tolist()) <= set(range(8))
        assert rows["count"].sum() > 0
        # and the latest checkpoint reports end-of-stream positions
        assert load_savepoint(
            st.latest().path).source_positions() == {0: {0: 4}}

    def test_non_window_snapshot_rejected(self, tmp_path):
        run_job(tmp_path)
        r = load_savepoint(latest_chk(tmp_path))
        with pytest.raises(ValueError, match="not a window"):
            # sources dict is not an operator id; fabricate a bad snap
            r.payload["operators"]["fake"] = {"x": 1}
            r.window_keyed_rows("fake")


class TestReprocessOnTop:
    def test_rewind_keeping_state_replays_fully(self, tmp_path):
        """reset_watermarks() must rewind the OPERATOR clocks too
        (watermark, fired/cleared horizons), or replayed records sit
        behind the old end-of-stream watermark and drop as late. With
        the full reset, a rewound replay over the (already-purged) final
        state recomputes every window; without operator reset, almost
        nothing comes out — the review-found failure mode."""
        s1 = run_job(tmp_path)
        base = {(int(r["key"]), int(r["window_end"])): float(r["sum_v"])
                for r in s1.rows}

        r = load_savepoint(latest_chk(tmp_path))
        sp = (SavepointWriter(r)
              .set_source_positions({0: {0: 0}})
              .reset_watermarks()
              .write(str(tmp_path), "sp-job"))
        s2 = run_job(tmp_path, restore_path=sp)
        got = {(int(r["key"]), int(r["window_end"])): float(r["sum_v"])
               for r in s2.rows}
        assert got == base  # full recompute, nothing dropped as late

        # contrast: driver-only reset leaves the operator clock at
        # end-of-stream — the replay drops (late) instead of recomputing
        r2 = load_savepoint(latest_chk(tmp_path))
        sp2 = (SavepointWriter(r2)
               .set_source_positions({0: {0: 0}})
               .reset_watermarks(include_operators=False)
               .write(str(tmp_path), "sp-job"))
        s3 = run_job(tmp_path, restore_path=sp2)
        assert len(s3.rows) < len(s1.rows)


class TestWriterRoundTrip:
    def test_transform_and_restore(self, tmp_path):
        """Bootstrap flow: take a mid-stream checkpoint, REWIND its
        source positions offline, write a savepoint, restore from it —
        the job replays from the rewritten position and produces the
        full output again (proves the written savepoint is genuinely
        restorable)."""
        s1 = run_job(tmp_path)
        base = sorted((int(r["key"]), int(r["window_end"]),
                       float(r["sum_v"])) for r in s1.rows)

        r = load_savepoint(latest_chk(tmp_path))
        w = SavepointWriter(r)
        # rewind to the beginning and CLEAR operator state: restore
        # must recompute everything
        ops = r.operator_ids()
        from flink_tpu.ops.window import WindowOperator
        from flink_tpu.ops import aggregates

        fresh = WindowOperator(
            TumblingEventTimeWindows.of(1_000), aggregates.sum_of("v"),
            num_shards=4, slots_per_shard=32, max_out_of_orderness_ms=400)
        w.transform_operator(ops[0], lambda snap: fresh.snapshot_state())
        w.set_source_positions({0: {0: 0}})
        w.reset_watermarks()
        sp_path = w.write(str(tmp_path), "sp-job")
        assert os.path.basename(sp_path).startswith("savepoint-")

        s2 = run_job(tmp_path, restore_path=sp_path)
        got = sorted((int(r["key"]), int(r["window_end"]),
                      float(r["sum_v"])) for r in s2.rows)
        assert got == base  # full recompute from rewound positions
