"""ENOSPC graceful degradation (storage.enospc-policy, PR 14).

Acceptance: ENOSPC injected mid-checkpoint and mid-segment-write under
``retry`` completes with committed output equal to the fault-free
golden (retries visible on the storage.enospc_retries metric); under
``fail`` it fails loudly with no torn committed artifact — the
storage fsck-s clean afterwards."""
import os

import numpy as np
import pytest

from flink_tpu import faults
from flink_tpu import fs as fsmod
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import TransactionalCollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.log.topic import TopicAppender, TopicReader
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _default_policy():
    """Every test leaves the process on the declared default."""
    yield
    fsmod.install_enospc_policy("retry")


def _source(n_batches, batch=64, n_keys=8):
    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(7000 + i)
        keys = rng.integers(0, n_keys, batch).astype(np.int64)
        ts = np.sort(rng.integers(i * 500, i * 500 + 1000,
                                  batch)).astype(np.int64)
        return {"k": keys}, ts

    return gen


def _conf(tmp_path, sub, extra=None):
    c = {
        "state.num-key-shards": 8, "state.slots-per-shard": 64,
        "pipeline.microbatch-size": 128,
        "execution.checkpointing.dir": str(tmp_path / sub),
        "execution.checkpointing.interval": 1,
    }
    c.update(extra or {})
    return Configuration(c)


def _run(tmp_path, sub, extra=None, plan=None):
    sink = TransactionalCollectSink()
    env = StreamExecutionEnvironment(_conf(tmp_path, sub, extra))
    (env.from_source(GeneratorSource(_source(6)),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
     .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
     .add_sink(sink))
    if plan is None:
        env.execute("enospc-job")
    else:
        with plan.activate():
            env.execute("enospc-job")
    return sorted((int(r["key"]), int(r["window_start"]), int(r["count"]))
                  for r in sink.committed)


def _retries() -> int:
    return int(fsmod.registry.snapshot().get(
        "storage.enospc_retries", 0))


class TestRetryPolicy:
    def test_mid_checkpoint_enospc_retries_to_golden(self, tmp_path):
        golden = _run(tmp_path, "golden")
        before = _retries()
        # two injections at the fs write seam, landing in checkpoint
        # blob/manifest writes (the only fs.open_write calls this
        # pipeline makes); the per-write retry budget absorbs both
        plan = faults.FaultPlan(seed=3).rule(
            "fs.write.enospc", "raise", count=2, after=2)
        got = _run(tmp_path, "retry", extra={
            "storage.enospc-policy": "retry",
            "storage.enospc-backoff-ms": 1,
        }, plan=plan)
        assert got == golden
        assert len(plan.log) == 2, "schedule injected nothing"
        assert _retries() >= before + 2, (
            "retries must be visible on storage.enospc_retries")

    def test_mid_segment_write_enospc_retries_to_golden(self, tmp_path):
        def stage_all(topic_dir, plan=None):
            fsmod.install_enospc_policy("retry", retries=4, backoff_ms=1)
            ap = TopicAppender(topic_dir, partitions=2,
                               segment_records=4)
            b = {"k": np.arange(10, dtype=np.int64),
                 "v": np.arange(10, dtype=np.float64)}
            ctx = plan.activate() if plan else None
            if ctx:
                ctx.__enter__()
            try:
                ap.stage(1, {0: [b], 1: [b]})
                ap.commit(1)
            finally:
                if ctx:
                    ctx.__exit__(None, None, None)
            r = TopicReader(topic_dir)
            return {p: [(o, {k: v.tolist() for k, v in blk.items()})
                        for o, blk in r.read(p)] for p in range(2)}

        golden = stage_all(os.path.join(str(tmp_path), "g"))
        before = _retries()
        plan = faults.FaultPlan(seed=5).rule(
            "fs.write.enospc", "raise", count=1, after=3)
        got = stage_all(os.path.join(str(tmp_path), "c"), plan)
        assert got == golden
        assert plan.log, "schedule injected nothing"
        assert _retries() >= before + 1

    def test_invalid_policy_is_loud(self):
        with pytest.raises(ValueError):
            fsmod.install_enospc_policy("yolo")
        with pytest.raises(ValueError):
            fsmod.install_enospc_policy_from_config(Configuration(
                {"storage.enospc-policy": "bogus"}))


class TestFailPolicy:
    def test_mid_checkpoint_enospc_fails_loud_and_fsck_clean(
            self, tmp_path):
        from flink_tpu.fsck import fsck_path

        plan = faults.FaultPlan(seed=3).rule(
            "fs.write.enospc", "raise", count=1, after=2)
        with pytest.raises(Exception) as ei:
            _run(tmp_path, "fail", extra={
                "storage.enospc-policy": "fail"}, plan=plan)
        assert "enospc" in str(ei.value).lower()
        # no torn committed artifact: whatever checkpoints completed
        # before the failure verify clean
        ckpt = str(tmp_path / "fail")
        if os.path.isdir(ckpt):
            findings = [f for f in fsck_path(ckpt)
                        if f["severity"] == "error"]
            assert findings == [], f"torn committed artifact: {findings}"

    def test_mid_segment_write_enospc_fails_loud_and_fsck_clean(
            self, tmp_path):
        from flink_tpu.fsck import fsck_path

        fsmod.install_enospc_policy("fail")
        topic = os.path.join(str(tmp_path), "t")
        ap = TopicAppender(topic, partitions=1, segment_records=4)
        b = {"k": np.arange(6, dtype=np.int64),
             "v": np.arange(6, dtype=np.float64)}
        ap.stage(1, {0: [b]})
        ap.commit(1)
        plan = faults.FaultPlan(seed=9).rule(
            "fs.write.enospc", "raise", count=1)
        with plan.activate():
            with pytest.raises(OSError):
                ap.stage(2, {0: [b]})
        # recovery sweeps the debris; the committed prefix is intact
        ap2 = TopicAppender(topic, partitions=1, segment_records=4)
        ap2.recover()
        findings = [f for f in fsck_path(topic)
                    if f["severity"] == "error"]
        assert findings == [], f"torn committed artifact: {findings}"
        r = TopicReader(topic)
        assert r.committed_offsets() == {0: 6}
