"""Broadcast state pattern: a control stream replicated into broadcast
state, joined with a data stream (ref: BroadcastConnectedStream +
CoBroadcastWithNonKeyedOperator, SURVEY §3.7)."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.config import Configuration
from flink_tpu.ops.broadcast import BroadcastProcessFunction
from flink_tpu.time.watermarks import WatermarkStrategy


class RuleFilter(BroadcastProcessFunction):
    """Control stream carries (key, allowed) rules; data records pass
    only while their key is currently allowed — the canonical dynamic-
    filter use of broadcast state."""

    def process_element(self, data, ts, state):
        allowed = state.get("allowed", set())
        if not len(ts):
            return None
        mask = np.array([int(k) in allowed for k in data["k"]], bool)
        return {"k": data["k"][mask], "v": data["v"][mask],
                "__ts__": ts[mask]}

    def process_broadcast_element(self, data, ts, state):
        allowed = state.setdefault("allowed", set())
        for k, on in zip(data["rule_key"], data["enable"]):
            (allowed.add if int(on) else allowed.discard)(int(k))


def test_dynamic_rules_apply_in_arrival_order():
    # batches interleave: rules arrive between data batches and change
    # what subsequently passes
    def data_gen(split, i):
        if i >= 4:
            return None
        n = 100
        rng = np.random.default_rng(i)
        return ({"k": np.full(n, i % 2, np.int64),
                 "v": rng.integers(0, 10, n).astype(np.int64)},
                np.full(n, i * 1000, np.int64))

    def rule_gen(split, i):
        # batch 0: enable key 0; batch 1: enable key 1 disable key 0
        rules = [([0], [1]), ([1, 0], [1, 0])]
        if i >= len(rules):
            return None
        ks, en = rules[i]
        return ({"rule_key": np.asarray(ks, np.int64),
                 "enable": np.asarray(en, np.int64)},
                np.full(len(ks), i * 1000, np.int64))

    env = StreamExecutionEnvironment(Configuration({}))
    data = env.from_source(GeneratorSource(data_gen),
                           WatermarkStrategy.for_bounded_out_of_orderness(0))
    control = env.from_source(GeneratorSource(rule_gen),
                              WatermarkStrategy.for_bounded_out_of_orderness(0))
    sink = CollectSink()
    data.connect(control).process(RuleFilter()).add_sink(sink)
    env.execute("broadcast-rules")

    passed = [int(r["k"]) for r in sink.rows]
    assert passed, "no records passed the dynamic filter"
    # key 1 only passes after rule batch 1 enabled it; key 0 never
    # passes after being disabled there. Exact interleaving is arrival
    # order; invariants that must hold regardless:
    assert set(passed) <= {0, 1}


def test_state_rides_checkpoints(tmp_path):
    """Broadcast state must survive restore: rules applied before the
    checkpoint still filter after a restore."""
    from flink_tpu.graph.compiler import compile_job
    from flink_tpu.runtime.driver import Driver
    from flink_tpu.ops.broadcast import BroadcastConnectOperator

    op = BroadcastConnectOperator(RuleFilter())
    op.process_broadcast(np.array([0]),
                         {"rule_key": np.array([7]),
                          "enable": np.array([1])},
                         np.array([True]))
    v1 = op.state_version
    snap = op.snapshot_state()
    op2 = BroadcastConnectOperator(RuleFilter())
    op2.restore_state(snap)
    op2.process_main(np.array([5, 6]),
                     {"k": np.array([7, 8]), "v": np.array([1, 2])},
                     np.array([True, True]))
    out = op2.take_fired()
    assert out["k"].tolist() == [7]
    assert v1 == 1  # mutation bumped the incremental-dirtiness version


def test_ragged_output_rejected():
    class Bad(BroadcastProcessFunction):
        def process_element(self, data, ts, state):
            return {"a": np.arange(3), "b": np.arange(2)}

        def process_broadcast_element(self, data, ts, state):
            pass

    from flink_tpu.ops.broadcast import BroadcastConnectOperator

    op = BroadcastConnectOperator(Bad())
    with pytest.raises(ValueError, match="ragged"):
        op.process_main(np.array([1]), {"x": np.array([1])},
                        np.array([True]))
