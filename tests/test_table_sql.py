"""Table/SQL frontend tests: parsing, planning, and golden parity with
the DataStream API (the two frontends must lower onto the same runtime
and produce identical results). ref: flink-table-planner's
plan/runtime tests + Nexmark Q5 SQL shape (SURVEY §3.8)."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.windowing import SlidingEventTimeWindows, TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.ops import aggregates
from flink_tpu.table import (
    AggCall, Hop, SqlError, TableEnvironment, Tumble, col,
)
from flink_tpu.table.sql import parse


def _bids(env, n=4000, keys=30, seed=0):
    rng = np.random.default_rng(seed)
    ts = np.sort(rng.integers(0, 20_000, n)).astype(np.int64)
    data = {
        "auction": rng.integers(0, keys, n).astype(np.int64),
        "price": rng.integers(1, 500, n).astype(np.float32),
        "ts": ts,
    }
    return env.from_collection(data, ts, batch_size=1000), data


def _fresh():
    env = StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8, "state.slots-per-shard": 32}))
    return env, TableEnvironment.create(env)


def _rowset(rows, fields):
    return sorted(
        tuple(round(float(r[f]), 4) for f in fields) for r in rows)


class TestParser:
    def test_basic_shapes(self):
        q = parse("SELECT a, COUNT(*) AS c FROM t GROUP BY a")
        assert q.group_by == ["a"]
        assert q.items[1].agg == ("count", None)
        assert q.items[1].alias == "c"

    def test_hop_tvf(self):
        q = parse(
            "SELECT COUNT(*) FROM TABLE(HOP(TABLE bids, DESCRIPTOR(ts),"
            " INTERVAL '1' SECOND, INTERVAL '10' SECOND))")
        assert q.source.kind == "hop"
        assert q.source.intervals == [1000, 10_000]

    def test_where_expr_precedence(self):
        q = parse("SELECT a FROM t WHERE a + 1 * 2 > 3 AND b = 'x'")
        got = q.where.eval({"a": np.array([0, 2]), "b": np.array(["x", "y"])})
        assert got.tolist() == [False, False]  # 0+2>3 F; b='y' F
        got = q.where.eval({"a": np.array([2, 9]), "b": np.array(["x", "y"])})
        assert got.tolist() == [True, False]   # 2+2>3 T & 'x'; b='y' F

    def test_errors(self):
        with pytest.raises(SqlError):
            parse("SELECT FROM t")
        with pytest.raises(SqlError):
            parse("SELECT a FROM t; DROP TABLE t")
        with pytest.raises(SqlError):
            parse("SELECT SUM(*) FROM t")

    def test_having_without_aggregate_rejected_at_plan_time(self):
        # HAVING parses fine; the semantic check happens when the query
        # is planned against a real source table.
        q = parse("SELECT a FROM t HAVING a > 1")
        assert q.having is not None
        env, t_env = _fresh()
        stream, _ = _bids(env)
        t_env.create_temporary_view(
            "t", stream, schema=["a", "price", "ts"], time_attr="ts")
        with pytest.raises(SqlError, match="HAVING"):
            t_env.sql_query("SELECT a FROM t HAVING a > 1")


class TestSqlVsDataStream:
    def test_q5_sql_matches_datastream(self):
        # SQL side
        env, t_env = _fresh()
        stream, data = _bids(env)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        res = t_env.sql_query(
            "SELECT auction, window_end, COUNT(*) AS bid_count "
            "FROM TABLE(HOP(TABLE bids, DESCRIPTOR(ts), "
            "INTERVAL '1' SECOND, INTERVAL '4' SECOND)) "
            "GROUP BY auction, window_start, window_end").execute()

        # DataStream side, same data
        env2 = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 8, "state.slots-per-shard": 32}))
        stream2, _ = _bids(env2)
        sink = CollectSink()
        (stream2.key_by("auction")
         .window(SlidingEventTimeWindows.of(4000, 1000))
         .count().add_sink(sink))
        env2.execute("ds")

        fields_sql = ("auction", "window_end", "bid_count")
        fields_ds = ("key", "window_end", "count")
        assert _rowset(res.rows, fields_sql) == _rowset(sink.rows, fields_ds)
        assert len(res.rows) > 0

    def test_sql_topn_matches_datastream_top(self):
        env, t_env = _fresh()
        stream, _ = _bids(env, seed=3)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        res = t_env.sql_query(
            "SELECT auction, window_end, COUNT(*) AS c "
            "FROM TABLE(HOP(TABLE bids, DESCRIPTOR(ts), "
            "INTERVAL '1' SECOND, INTERVAL '4' SECOND)) "
            "GROUP BY auction, window_start, window_end "
            "ORDER BY c DESC LIMIT 2").execute()

        env2 = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 8, "state.slots-per-shard": 32}))
        stream2, _ = _bids(env2, seed=3)
        sink = CollectSink()
        (stream2.key_by("auction")
         .window(SlidingEventTimeWindows.of(4000, 1000))
         .count().top(2, by="count").add_sink(sink))
        env2.execute("ds-top")

        assert (_rowset(res.rows, ("auction", "window_end", "c"))
                == _rowset(sink.rows, ("key", "window_end", "count")))
        assert len(res.rows) > 0

    def test_where_and_sum_tumble(self):
        env, t_env = _fresh()
        stream, data = _bids(env, seed=5)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        res = t_env.sql_query(
            "SELECT auction, window_end, SUM(price) AS total, "
            "MAX(price) AS hi "
            "FROM TABLE(TUMBLE(TABLE bids, DESCRIPTOR(ts), "
            "INTERVAL '2' SECOND)) "
            "WHERE price > 250 "
            "GROUP BY auction, window_start, window_end").execute()

        # numpy golden
        m = data["price"] > 250
        golden = {}
        for a, p, t in zip(data["auction"][m], data["price"][m],
                           data["ts"][m]):
            we = (int(t) // 2000 + 1) * 2000
            key = (int(a), we)
            s, h = golden.get(key, (0.0, -np.inf))
            golden[key] = (s + float(p), max(h, float(p)))
        got = sorted((int(r["auction"]), int(r["window_end"]),
                      round(float(r["total"]), 2), float(r["hi"]))
                     for r in res.rows)
        want = sorted((a, we, round(s, 2), h)
                      for (a, we), (s, h) in golden.items())
        assert got == want


class TestTableApi:
    def test_fluent_matches_sql(self):
        env, t_env = _fresh()
        stream, _ = _bids(env, seed=7)
        t = t_env.from_data_stream(
            stream, schema=["auction", "price", "ts"], time_attr="ts")
        res = (t.filter(col("price") > 100)
               .window(Hop.of_ms(4000, 1000))
               .group_by("auction")
               .aggregate(AggCall("count", None, "c"))
               .execute())

        env2, t_env2 = _fresh()
        stream2, _ = _bids(env2, seed=7)
        t_env2.create_temporary_view(
            "bids", stream2, schema=["auction", "price", "ts"],
            time_attr="ts")
        res2 = t_env2.sql_query(
            "SELECT auction, window_end, COUNT(*) AS c "
            "FROM TABLE(HOP(TABLE bids, DESCRIPTOR(ts), "
            "INTERVAL '1' SECOND, INTERVAL '4' SECOND)) "
            "WHERE price > 100 "
            "GROUP BY auction, window_start, window_end").execute()
        f = ("auction", "window_end", "c")
        assert _rowset(res.rows, f) == _rowset(res2.rows, f)
        assert res.rows

    def test_projection_only_query(self):
        env, t_env = _fresh()
        stream, data = _bids(env, n=500, seed=9)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        res = t_env.sql_query(
            "SELECT auction, price * 2 AS dbl FROM bids "
            "WHERE auction < 5").execute()
        m = data["auction"] < 5
        assert len(res.rows) == int(m.sum())
        got = sorted(round(float(r["dbl"]), 2) for r in res.rows)
        want = sorted(np.round(data["price"][m] * 2, 2).tolist())
        assert got == want

    def test_global_windowed_aggregate(self):
        env, t_env = _fresh()
        stream, data = _bids(env, n=1000, seed=11)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        res = t_env.sql_query(
            "SELECT window_end, MAX(price) AS hi "
            "FROM TABLE(TUMBLE(TABLE bids, DESCRIPTOR(ts), "
            "INTERVAL '5' SECOND)) "
            "GROUP BY window_start, window_end").execute()
        golden = {}
        for p, t in zip(data["price"], data["ts"]):
            we = (int(t) // 5000 + 1) * 5000
            golden[we] = max(golden.get(we, -np.inf), float(p))
        got = sorted((int(r["window_end"]), float(r["hi"]))
                     for r in res.rows)
        assert got == sorted(golden.items())

    def test_plan_errors(self):
        env, t_env = _fresh()
        stream, _ = _bids(env, n=100)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        # unwindowed GROUP BY now PLANS (the upsert/changelog path —
        # tests/test_global_agg.py covers its semantics)
        t = t_env.sql_query(
            "SELECT auction, COUNT(*) AS c FROM bids GROUP BY auction")
        assert t.schema.columns == ("auction", "c")
        with pytest.raises(SqlError, match="one non-window"):
            t_env.sql_query(
                "SELECT COUNT(*) FROM TABLE(TUMBLE(TABLE bids, "
                "DESCRIPTOR(ts), INTERVAL '1' SECOND)) "
                "GROUP BY auction, price")
        with pytest.raises(KeyError, match="nope"):
            t_env.sql_query("SELECT a FROM nope")
        with pytest.raises(SqlError, match="DESC"):
            t_env.sql_query(
                "SELECT auction, COUNT(*) AS c FROM TABLE(TUMBLE(TABLE "
                "bids, DESCRIPTOR(ts), INTERVAL '1' SECOND)) "
                "GROUP BY auction, window_end ORDER BY c LIMIT 2")


class TestReviewRegressions:
    """Regression cases from the round-3 review of this module."""

    def test_second_query_does_not_refire_first_sink(self):
        env, t_env = _fresh()
        stream, data = _bids(env, n=500, seed=13)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        r1 = t_env.sql_query(
            "SELECT auction FROM bids WHERE price > 400").execute()
        n1 = len(r1.rows)
        assert n1 == int((data["price"] > 400).sum())
        t_env.sql_query("SELECT auction FROM bids WHERE price > 100").execute()
        assert len(r1.rows) == n1  # first result must not grow

    def test_duplicate_aggregates_fan_out(self):
        env, t_env = _fresh()
        stream, data = _bids(env, n=800, seed=15)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        res = t_env.sql_query(
            "SELECT auction, SUM(price) AS a, SUM(price) AS b, "
            "COUNT(*) AS c "
            "FROM TABLE(TUMBLE(TABLE bids, DESCRIPTOR(ts), "
            "INTERVAL '5' SECOND)) "
            "GROUP BY auction, window_start, window_end").execute()
        assert res.rows
        for r in res.rows:
            assert r["a"] == r["b"]

    def test_select_literal_column(self):
        env, t_env = _fresh()
        stream, _ = _bids(env, n=200, seed=17)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        res = t_env.sql_query("SELECT auction, 1 AS one FROM bids").execute()
        assert len(res.rows) == 200
        assert all(int(r["one"]) == 1 for r in res.rows)

    def test_window_tvf_without_aggregates_rejected(self):
        env, t_env = _fresh()
        stream, _ = _bids(env, n=100)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        with pytest.raises(SqlError, match="aggregate"):
            t_env.sql_query(
                "SELECT * FROM TABLE(TUMBLE(TABLE bids, DESCRIPTOR(ts), "
                "INTERVAL '2' SECOND))")

    def test_global_topn_rejected(self):
        env, t_env = _fresh()
        stream, _ = _bids(env, n=100)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        with pytest.raises(SqlError, match="grouping column"):
            t_env.sql_query(
                "SELECT window_end, COUNT(*) AS c FROM TABLE(TUMBLE("
                "TABLE bids, DESCRIPTOR(ts), INTERVAL '2' SECOND)) "
                "GROUP BY window_start, window_end "
                "ORDER BY c DESC LIMIT 1")

    def test_fractional_limit_rejected(self):
        with pytest.raises(SqlError, match="integer"):
            parse("SELECT auction, COUNT(*) AS c FROM TABLE(TUMBLE("
                  "TABLE bids, DESCRIPTOR(ts), INTERVAL '1' SECOND)) "
                  "GROUP BY auction ORDER BY c DESC LIMIT 2.5")

    def test_topn_output_pruned_to_select_list(self):
        env, t_env = _fresh()
        stream, _ = _bids(env, seed=19)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        res = t_env.sql_query(
            "SELECT auction, window_end, COUNT(*) AS c "
            "FROM TABLE(HOP(TABLE bids, DESCRIPTOR(ts), "
            "INTERVAL '1' SECOND, INTERVAL '4' SECOND)) "
            "GROUP BY auction, window_start, window_end "
            "ORDER BY c DESC LIMIT 1").execute()
        assert res.rows
        assert set(res.rows[0]) == {"auction", "window_end", "c"}

    def test_session_topn_rejected(self):
        env, t_env = _fresh()
        stream, _ = _bids(env, n=100)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        with pytest.raises(SqlError, match="SESSION"):
            t_env.sql_query(
                "SELECT auction, COUNT(*) AS c FROM TABLE(SESSION("
                "TABLE bids, DESCRIPTOR(ts), INTERVAL '2' SECOND)) "
                "GROUP BY auction ORDER BY c DESC LIMIT 2")

    def test_limit_zero_on_projection_rejected(self):
        env, t_env = _fresh()
        stream, _ = _bids(env, n=100)
        t_env.create_temporary_view(
            "bids", stream, schema=["auction", "price", "ts"],
            time_attr="ts")
        with pytest.raises(SqlError, match="windowed"):
            t_env.sql_query("SELECT auction FROM bids LIMIT 0")

    def test_avg_runtime_field_tracks_aggregates_module(self):
        from flink_tpu.table.api import AggCall
        from flink_tpu.ops.aggregates import avg_of, result_fields

        assert (AggCall("avg", "price", "x").runtime_field
                == result_fields(avg_of("price"))[0])


class TestSqlJoin:
    """SQL windowed equi-join lowering onto ops/join.py (Q8's shape),
    golden-equal to the DataStream pipeline."""

    def _streams(self, env, n=3000, seed=3):
        rng = np.random.default_rng(seed)
        ts_p = np.sort(rng.integers(0, 12_000, n)).astype(np.int64)
        persons = {
            "person": rng.integers(0, 50, n).astype(np.int64),
            "state_id": rng.integers(0, 5, n).astype(np.int64),
            "ts": ts_p,
        }
        ts_a = np.sort(rng.integers(0, 12_000, n)).astype(np.int64)
        auctions = {
            "seller": rng.integers(0, 50, n).astype(np.int64),
            "reserve": rng.integers(1, 100, n).astype(np.int64),
            "ts2": ts_a,
        }
        p = env.from_collection(persons, ts_p, batch_size=500)
        a = env.from_collection(auctions, ts_a, batch_size=500)
        return p, a, persons, auctions

    def test_join_golden_vs_datastream(self):
        # SQL side
        env, te = _fresh()
        p, a, _, _ = self._streams(env)
        te.create_temporary_view("P", p, ["person", "state_id", "ts"])
        te.create_temporary_view("A", a, ["seller", "reserve", "ts2"])
        t = te.sql_query(
            "SELECT P.person AS who, window_end, P.state_id, A.reserve "
            "FROM TABLE(TUMBLE(TABLE P, DESCRIPTOR(ts), "
            "INTERVAL '1' SECOND)) "
            "JOIN TABLE(TUMBLE(TABLE A, DESCRIPTOR(ts2), "
            "INTERVAL '1' SECOND)) "
            "ON P.person = A.seller")
        rows = t.execute("sql-join").collect()
        got = _rowset(rows, ("who", "window_end", "state_id", "reserve"))

        # DataStream side (Q8 wiring)
        env2 = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 8, "state.slots-per-shard": 32}))
        p2, a2, _, _ = self._streams(env2)
        sink = CollectSink()
        (p2.join(a2).where("person").equal_to("seller")
         .window(TumblingEventTimeWindows.of(1000))
         .apply(left_fields=("state_id",), right_fields=("reserve",))
         .add_sink(sink))
        env2.execute("ds-join")
        want = sorted(
            (round(float(r["key"]), 4), round(float(r["window_end"]), 4),
             round(float(r["left_state_id"]), 4),
             round(float(r["right_reserve"]), 4))
            for r in sink.rows)
        assert len(got) > 0
        assert got == want

    def test_join_where_on_output(self):
        env, te = _fresh()
        p, a, _, _ = self._streams(env, n=800)
        te.create_temporary_view("P", p, ["person", "state_id", "ts"])
        te.create_temporary_view("A", a, ["seller", "reserve", "ts2"])
        t = te.sql_query(
            "SELECT P.person AS who, A.reserve "
            "FROM TABLE(TUMBLE(TABLE P, DESCRIPTOR(ts), "
            "INTERVAL '1' SECOND)) "
            "JOIN TABLE(TUMBLE(TABLE A, DESCRIPTOR(ts2), "
            "INTERVAL '1' SECOND)) "
            "ON P.person = A.seller WHERE reserve > 50")
        rows = t.execute("sql-join-where").collect()
        assert rows and all(float(r["reserve"]) > 50 for r in rows)

    def test_window_equalities_accepted(self):
        q = parse(
            "SELECT P.person FROM TABLE(TUMBLE(TABLE P, DESCRIPTOR(ts),"
            " INTERVAL '1' SECOND)) JOIN TABLE(TUMBLE(TABLE A,"
            " DESCRIPTOR(ts2), INTERVAL '1' SECOND)) ON"
            " P.person = A.seller AND window_start = window_start"
            " AND window_end = window_end")
        assert len(q.source.conds) == 3

    @pytest.mark.parametrize("sql,msg", [
        ("SELECT x FROM a JOIN b ON a.x = b.y",
         "window TVF on BOTH sides"),
        ("SELECT x FROM TABLE(TUMBLE(TABLE a, DESCRIPTOR(ts), INTERVAL"
         " '1' SECOND)) JOIN TABLE(TUMBLE(TABLE b, DESCRIPTOR(ts),"
         " INTERVAL '2' SECOND)) ON a.x = b.y",
         "share one window spec"),
        ("SELECT x FROM TABLE(SESSION(TABLE a, DESCRIPTOR(ts), INTERVAL"
         " '1' SECOND)) JOIN TABLE(SESSION(TABLE b, DESCRIPTOR(ts),"
         " INTERVAL '1' SECOND)) ON a.x = b.y",
         "SESSION window JOIN"),
        ("SELECT COUNT(*) FROM TABLE(TUMBLE(TABLE a, DESCRIPTOR(ts),"
         " INTERVAL '1' SECOND)) JOIN TABLE(TUMBLE(TABLE b,"
         " DESCRIPTOR(ts), INTERVAL '1' SECOND)) ON a.x = b.y",
         "aggregation over a JOIN"),
        ("SELECT x FROM TABLE(TUMBLE(TABLE a, DESCRIPTOR(ts), INTERVAL"
         " '1' SECOND)) JOIN TABLE(TUMBLE(TABLE b, DESCRIPTOR(ts),"
         " INTERVAL '1' SECOND)) ON a.x = b.y AND a.z = b.w",
         "exactly one cross-side key equality"),
    ])
    def test_unsupported_join_shapes_raise(self, sql, msg):
        env, te = _fresh()
        s1, s2, _, _ = self._streams(env, n=50)
        te.create_temporary_view("a", s1, ["x", "z", "ts"])
        te.create_temporary_view("b", s2, ["y", "w", "ts"])
        with pytest.raises(SqlError, match=msg):
            te.sql_query(sql)
