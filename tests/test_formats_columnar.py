"""Columnar format edge cases (ISSUE 2 satellite: empty file,
truncation, schema mismatch, zero-row batches — all loud; randomized
round-trip property vs the jsonlines oracle).

ref role: flink-formats/{flink-avro,flink-parquet} serialization tests
(SURVEY §3.9) — except this format is self-contained (pure
struct+numpy; the acceptance criterion bans pyarrow/fastavro)."""
import io

import numpy as np
import pytest

from flink_tpu.formats import JsonLinesFormat
from flink_tpu.formats_columnar import (
    ColumnarError,
    ColumnarFormat,
    ColumnarWriter,
    infer_schema,
    iter_blocks,
)

SCHEMA = (("k", "i64"), ("x", "f32"), ("d", "f64"), ("s", "str"))


def _batch(rng, n):
    return {
        "k": rng.integers(-2**40, 2**40, n).astype(np.int64),
        "x": rng.random(n).astype(np.float32),
        "d": rng.random(n).astype(np.float64),
        "s": np.array(["w" + str(int(v)) + ("é" if v % 3 == 0 else "")
                       for v in rng.integers(0, 1000, n)], dtype=object),
    }


class TestRoundTrip:
    def test_single_block_round_trip(self):
        rng = np.random.default_rng(0)
        fmt = ColumnarFormat(SCHEMA)
        b = _batch(rng, 257)
        out = fmt.deserialize(fmt.serialize(b))
        for name in b:
            np.testing.assert_array_equal(out[name], b[name])

    def test_multi_block_writer_preserves_block_structure(self):
        rng = np.random.default_rng(1)
        buf = io.BytesIO()
        w = ColumnarWriter(buf, SCHEMA)
        batches = [_batch(rng, n) for n in (3, 1, 128)]
        for b in batches:
            w.write_batch(b)
        w.close()
        got = list(iter_blocks(buf.getvalue(), expect_schema=SCHEMA))
        assert [len(g["k"]) for g in got] == [3, 1, 128]
        for g, b in zip(got, batches):
            for name in b:
                np.testing.assert_array_equal(g[name], b[name])

    def test_zero_row_batch_round_trips_typed(self):
        """A zero-row block is legal and yields schema-TYPED empty
        columns (downstream chains index columns on every batch)."""
        buf = io.BytesIO()
        w = ColumnarWriter(buf, SCHEMA)
        w.write_batch(ColumnarFormat(SCHEMA).empty_batch())
        w.close()
        (got,) = iter_blocks(buf.getvalue())
        assert len(got["k"]) == 0 and got["k"].dtype == np.int64
        assert got["s"].dtype == object

    def test_zero_row_serialize(self):
        fmt = ColumnarFormat(SCHEMA)
        data = fmt.serialize({n: np.array([], np.int64) for n, _ in SCHEMA})
        out = fmt.deserialize(data)
        assert len(out["k"]) == 0 and out["x"].dtype == np.float32

    def test_property_round_trip_vs_jsonlines(self):
        """Randomized rows: the columnar format and the jsonlines
        format must reconstruct the SAME columns from the same batch —
        jsonlines is the established oracle, columnar must agree
        bit-exactly (i64/f32 survive the JSON double round trip)."""
        schema = (("k", "i64"), ("x", "f32"), ("s", "str"))
        col = ColumnarFormat(schema)
        jl = JsonLinesFormat(schema)
        rng = np.random.default_rng(42)
        for trial in range(20):
            n = int(rng.integers(0, 200))
            b = {"k": rng.integers(-2**31, 2**31, n).astype(np.int64),
                 "x": rng.random(n).astype(np.float32),
                 "s": np.array([f"w{i}" for i in rng.integers(0, 99, n)],
                               dtype=object)}
            via_col = col.deserialize(col.serialize(b))
            via_jl = jl.deserialize(jl.serialize(b))
            for name in b:
                np.testing.assert_array_equal(via_col[name], b[name])
                np.testing.assert_array_equal(via_col[name], via_jl[name])

    def test_bytes_values_decode_as_text(self):
        """np.bytes_ / 'S'-dtype values must round-trip as the DECODED
        text, never the Python repr "b'...'" (silent corruption)."""
        fmt = ColumnarFormat((("s", "str"),))
        b = {"s": np.array([b"abc", "café".encode("utf-8")],
                           dtype=object)}
        out = fmt.deserialize(fmt.serialize(b))
        assert list(out["s"]) == ["abc", "café"]
        out2 = fmt.deserialize(fmt.serialize(
            {"s": np.array([b"x", b"yy"], dtype="S2")}))
        assert list(out2["s"]) == ["x", "yy"]

    def test_streaming_file_reader_matches_bytes_reader(self, tmp_path):
        from flink_tpu.formats_columnar import iter_file_blocks

        rng = np.random.default_rng(9)
        p = tmp_path / "f.colb"
        with open(p, "wb") as f:
            w = ColumnarWriter(f, SCHEMA)
            batches = [_batch(rng, n) for n in (5, 64)]
            for b in batches:
                w.write_batch(b)
            w.close()
        with open(p, "rb") as f:
            got = list(iter_file_blocks(f, expect_schema=SCHEMA))
        assert [len(g["k"]) for g in got] == [5, 64]
        for g, b in zip(got, batches):
            for name in b:
                np.testing.assert_array_equal(g[name], b[name])
        # truncated tail is loud on the streaming path too
        raw = open(p, "rb").read()
        with open(p, "wb") as f:
            f.write(raw[:-8])
        with pytest.raises(ColumnarError, match="truncated|footer"):
            with open(p, "rb") as f:
                list(iter_file_blocks(f))

    def test_skip_elides_decoding_but_still_validates(self):
        """skip=N (the replay position) yields only blocks >= N, but
        the frame walk + CRC still cover the whole file — a truncated
        tail is loud even when every block is skipped."""
        rng = np.random.default_rng(11)
        buf = io.BytesIO()
        w = ColumnarWriter(buf, SCHEMA)
        batches = [_batch(rng, n) for n in (4, 8, 16)]
        for b in batches:
            w.write_batch(b)
        w.close()
        data = buf.getvalue()
        got = list(iter_blocks(data, expect_schema=SCHEMA, skip=2))
        assert [len(g["k"]) for g in got] == [16]
        np.testing.assert_array_equal(got[0]["k"], batches[2]["k"])
        with pytest.raises(ColumnarError, match="truncated|footer"):
            list(iter_blocks(data[:-6], skip=3))

    def test_infer_schema(self):
        b = {"a": np.arange(3, dtype=np.int32),
             "b": np.zeros(3, np.float32),
             "c": np.array(["x", "y", "z"], dtype=object)}
        assert infer_schema(b) == (("a", "i64"), ("b", "f32"),
                                   ("c", "str"))


class TestZeroCopyDecode:
    """ISSUE 13: ``iter_blocks(..., zero_copy=True)`` — views instead
    of copies, same bytes, same loudness."""

    def test_round_trip_matches_copying_reader(self):
        rng = np.random.default_rng(9)
        buf = io.BytesIO()
        w = ColumnarWriter(buf, SCHEMA)
        batches = [_batch(rng, n) for n in (64, 1, 0, 257)]
        for b in batches:
            w.write_batch(b)
        w.close()
        image = buf.getvalue()
        copy = list(iter_blocks(image, expect_schema=SCHEMA))
        zc = list(iter_blocks(memoryview(image), expect_schema=SCHEMA,
                              zero_copy=True))
        assert len(copy) == len(zc) == 4
        for a, b in zip(copy, zc):
            for name in a:
                np.testing.assert_array_equal(a[name], b[name])

    def test_views_not_copies(self):
        """The regression guard: fixed columns' ``.base`` chains into
        the image (a copy has base None) and the views are read-only."""
        rng = np.random.default_rng(10)
        fmt = ColumnarFormat(SCHEMA)
        image = fmt.serialize(_batch(rng, 100))
        (blk,) = iter_blocks(memoryview(image), zero_copy=True)
        for name, typ in SCHEMA:
            if typ == "str":
                continue  # utf-8 decode is inherently a materialization
            assert blk[name].base is not None, f"{name} was copied"
            assert not blk[name].flags.writeable
        (copy_blk,) = iter_blocks(image)
        assert copy_blk["k"].base is None  # the control

    def test_mmap_image_survives_closed_handle(self, tmp_path):
        from flink_tpu.formats_columnar import map_file_image

        rng = np.random.default_rng(11)
        fmt = ColumnarFormat(SCHEMA)
        b = _batch(rng, 500)
        path = tmp_path / "f.colb"
        path.write_bytes(fmt.serialize(b))
        view = map_file_image(str(path))
        (blk,) = iter_blocks(view, expect_schema=SCHEMA,
                             zero_copy=True)
        del view  # the arrays' .base chain keeps the mapping alive
        for name in b:
            np.testing.assert_array_equal(blk[name], b[name])

    def test_corruption_exactly_as_loud(self):
        rng = np.random.default_rng(12)
        fmt = ColumnarFormat(SCHEMA)
        image = bytearray(fmt.serialize(_batch(rng, 200)))
        image[len(image) // 2] ^= 0xFF
        with pytest.raises(ColumnarError, match="CRC"):
            list(iter_blocks(memoryview(bytes(image)), zero_copy=True))

    def test_truncation_and_footer_loss_exactly_as_loud(self):
        rng = np.random.default_rng(13)
        fmt = ColumnarFormat(SCHEMA)
        image = fmt.serialize(_batch(rng, 200))
        with pytest.raises(ColumnarError, match="truncated"):
            list(iter_blocks(memoryview(image[:len(image) // 2]),
                             zero_copy=True))
        with pytest.raises(ColumnarError):
            list(iter_blocks(memoryview(image[:-16]), zero_copy=True))


class TestScatterWriterByteIdentity:
    """The scatter write path must emit BYTE-IDENTICAL files to the
    legacy copying writer (chained CRC == CRC of the concatenation):
    a reference image is built here with the pre-PR algorithm
    (tobytes + join + zlib.crc32) and compared whole."""

    def _legacy_image(self, schema, batches):
        import struct as st
        import zlib

        from flink_tpu.formats_columnar import (_FIXED_DTYPES, _MAGIC,
                                                _BLOCK_MAGIC,
                                                _FOOTER_MAGIC, _VERSION)
        import json as js

        header = js.dumps(
            {"fields": [[n, t] for n, t in schema]},
            separators=(",", ":")).encode()
        out = (_MAGIC + st.pack("<BBH", _VERSION, 0, len(schema))
               + st.pack("<I", len(header)) + header
               + st.pack("<I", zlib.crc32(header)))
        rows = 0
        for b in batches:
            nrows = len(np.asarray(b[schema[0][0]]))
            payload = b""
            for n, t in schema:
                if t == "str":
                    items = [str(x).encode() for x in b[n]]
                    offs = np.zeros(nrows + 1, np.uint32)
                    if nrows:
                        offs[1:] = np.cumsum([len(i) for i in items])
                    payload += (offs.astype("<u4").tobytes()
                                + b"".join(items))
                else:
                    payload += np.ascontiguousarray(
                        b[n], _FIXED_DTYPES[t]).tobytes()
            out += (_BLOCK_MAGIC + st.pack("<II", nrows, len(payload))
                    + payload + st.pack("<I", zlib.crc32(payload)))
            rows += nrows
        return out + _FOOTER_MAGIC + st.pack("<IQ", len(batches), rows)

    def test_bytes_identical_to_legacy_writer(self):
        rng = np.random.default_rng(14)
        batches = [_batch(rng, n) for n in (33, 128)]
        buf = io.BytesIO()
        w = ColumnarWriter(buf, SCHEMA)
        for b in batches:
            w.write_batch(b)
        w.close()
        assert buf.getvalue() == self._legacy_image(
            tuple(SCHEMA), batches)
        assert w.bytes_written == len(buf.getvalue())


class TestLoudFailures:
    def test_empty_file_rejected(self):
        with pytest.raises(ColumnarError, match="empty columnar file"):
            ColumnarFormat(SCHEMA).deserialize(b"")

    def test_bad_magic_rejected(self):
        with pytest.raises(ColumnarError, match="not a flink-tpu"):
            ColumnarFormat(SCHEMA).deserialize(b"NOPE" + b"\x00" * 64)

    def test_truncated_block_rejected(self):
        fmt = ColumnarFormat(SCHEMA)
        data = fmt.serialize(_batch(np.random.default_rng(2), 64))
        with pytest.raises(ColumnarError, match="truncated"):
            fmt.deserialize(data[: len(data) // 2])

    def test_missing_footer_rejected(self):
        """A writer that died before close(): blocks intact, footer
        absent — must read as truncation, never as a complete file."""
        rng = np.random.default_rng(3)
        buf = io.BytesIO()
        w = ColumnarWriter(buf, SCHEMA)
        w.write_batch(_batch(rng, 16))
        data = buf.getvalue()  # no close() → no footer
        with pytest.raises(ColumnarError, match="truncated"):
            list(iter_blocks(data))

    def test_corrupt_payload_rejected_by_crc(self):
        fmt = ColumnarFormat(SCHEMA)
        data = bytearray(fmt.serialize(_batch(np.random.default_rng(4),
                                              64)))
        data[len(data) // 2] ^= 0xFF  # flip one payload byte
        with pytest.raises(ColumnarError, match="CRC mismatch"):
            fmt.deserialize(bytes(data))

    def test_reader_schema_mismatch_rejected(self):
        written = ColumnarFormat(SCHEMA).serialize(
            _batch(np.random.default_rng(5), 8))
        other = ColumnarFormat((("k", "i64"), ("x", "f64"),
                                ("d", "f64"), ("s", "str")))
        with pytest.raises(ColumnarError, match="schema mismatch"):
            other.deserialize(written)

    def test_writer_schema_mismatch_rejected(self):
        fmt = ColumnarFormat((("a", "i64"), ("b", "i64")))
        with pytest.raises(ColumnarError, match="schema mismatch"):
            fmt.serialize({"a": np.arange(4), "WRONG": np.arange(4)})

    def test_writer_dtype_mismatch_rejected(self):
        fmt = ColumnarFormat((("a", "i64"),))
        with pytest.raises(ColumnarError, match="declared i64"):
            fmt.serialize({"a": np.zeros(4, np.float32)})

    def test_ragged_batch_rejected(self):
        fmt = ColumnarFormat((("a", "i64"), ("b", "i64")))
        with pytest.raises(ColumnarError, match="ragged"):
            fmt.serialize({"a": np.arange(4), "b": np.arange(3)})
