"""Session-cluster HA (ISSUE 11): dispatcher failover with a durable
session registry, epoch-fenced runners, and kill-the-leader chaos.

The contract under test (PAPER §3.4 Dispatcher/ResourceManager HA,
here on the shared-filesystem lease of runtime/ha.py):

- every ``rpc_submit_session_job`` persists the job — entry, config,
  quota, FIFO position — BEFORE admission returns (a store failure
  loses the submission cleanly, never half-registers it);
- a standby granted leadership re-hydrates the registry, re-queues
  undeployed jobs in ORIGINAL FIFO order, and re-attaches RUNNING jobs
  that runners carry back (in place — no redeploy, so committed output
  stays exactly-once across the takeover);
- every dispatcher→runner RPC carries the leader epoch and a deposed
  leader's late deploy/cancel is REJECTED at the runner (the bus
  writer-lease fencing, PR 9, mirrored onto the control plane);
- jobs whose runner died in the failover window restart through the
  existing checkpoint-restore path.

The in-process "SIGKILL" models a leader crash faithfully at the
protocol level: the RPC endpoint vanishes mid-conversation and the
lease stops renewing WITHOUT a clean handover. The real-signal variant
(subprocess + os.kill SIGKILL) is the tier-1 CLI smoke below.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from flink_tpu import faults
from flink_tpu.config import Configuration, HighAvailabilityOptions
from flink_tpu.runtime.ha import JobStore, LeaderElection, leader_address
from flink_tpu.runtime.rpc import RpcClient, RpcEndpoint, RpcServer
from flink_tpu.runtime.session import (
    LocalSessionCluster,
    SessionDispatcher,
    _build_dispatcher,
)

from test_runner_process import wait_until

pytestmark = [pytest.mark.session, pytest.mark.chaos]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _cluster_conf(ha_dir, extra=None):
    conf = {
        "high-availability.dir": str(ha_dir),
        "high-availability.lease-timeout": "700ms",
        "heartbeat.interval": "150ms",
        # wide: the fake-gateway runners of the unit tests never beat,
        # and a loss-declared runner under full-suite load would park
        # the redeploy these tests wait on (real-runner scenarios
        # detect leader death via CLIENT-side misses, not this timeout)
        "heartbeat.timeout": "60s",
        "session.autoscale": False,
        "session.ha.reattach-grace": "6s",
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 3,
        "restart-strategy.fixed-delay.delay": "100ms",
    }
    conf.update(extra or {})
    return Configuration(conf)


def _job_conf(tmp_path, tag, n_batches, sleep_ms=0):
    return {
        "test.n-batches": n_batches,
        "test.batch-sleep-ms": sleep_ms,
        "test.sink-dir": str(tmp_path / f"sink-{tag}"),
        "execution.checkpointing.dir": str(tmp_path / "chk"),
        "execution.checkpointing.interval": "150ms",
        "state.num-key-shards": 8,
        "state.slots-per-shard": 16,
    }


def _has_checkpoint(tmp_path, job_id):
    """A completed checkpoint exists for the job (admission namespaces
    the dir by job id, then storage namespaces by job name again:
    <base>/<job_id>/<job_id>/chk-*)."""
    d = tmp_path / "chk" / job_id / job_id
    return d.is_dir() and any(n.startswith("chk-")
                              for n in os.listdir(d))


def _committed(sink_dir):
    from flink_tpu.api.sinks import FileTransactionalSink

    return sorted(
        (int(r["key"]), int(r["window_start"]), int(r["count"]))
        for r in FileTransactionalSink.committed_rows(sink_dir))


def _assert_exactly_once(sink_dir, n_batches):
    import runner_job
    from flink_tpu.api.sinks import FileTransactionalSink

    got = {}
    for r in FileTransactionalSink.committed_rows(sink_dir):
        kk = (int(r["key"]), int(r["window_start"]))
        assert kk not in got, f"duplicate emission for {kk}"
        got[kk] = int(r["count"])
    assert got == runner_job.golden_counts(n_batches)


class Contender:
    """One `session start [--standby]` process in miniature: election +
    (on grant) dispatcher + RPC server — the serve_session cycle with
    the process boundary removed so the test can SIGKILL it
    surgically."""

    def __init__(self, ha_dir, conf, name):
        self.conf = conf
        self.name = name
        self.port = _free_port()
        self.address = f"127.0.0.1:{self.port}"
        self.granted = threading.Event()
        self.revoked = threading.Event()
        self.election = LeaderElection(
            str(ha_dir), self.address,
            conf.get(HighAvailabilityOptions.LEASE_TIMEOUT) / 1000,
            leader_id=name)
        self.election.on_grant = lambda epoch: self.granted.set()
        self.election.on_revoke = self.revoked.set
        self.dispatcher = None
        self.server = None
        self.election.start()

    def serve(self, timeout=20.0) -> SessionDispatcher:
        assert self.granted.wait(timeout), f"{self.name} never granted"
        self.dispatcher = _build_dispatcher(self.conf)
        # stamped between construction and serving (serve_session's
        # discipline): no push can leave unstamped
        self.dispatcher.leader_epoch = self.election.epoch
        self.server = RpcServer(self.dispatcher, self.port)
        return self.dispatcher

    def sigkill(self):
        """Crash without cleanup: the lease is NOT released (no clean
        handover — a standby must wait it out and STEAL it) and the
        endpoint vanishes mid-conversation."""
        self.election._closed = True
        if self.election._thread is not None:
            self.election._thread.join(timeout=2)
        if self.server is not None:
            self.server.close()
        if self.dispatcher is not None:
            self.dispatcher.close()

    def close(self):
        if self.server is not None:
            self.server.close()
        if self.dispatcher is not None:
            self.dispatcher.close()
        self.election.close()


# ---------------------------------------------------------------------------
# durable session registry
# ---------------------------------------------------------------------------

class TestDurableRegistry:
    def test_fifo_order_quota_and_attempts_survive_recovery(
            self, tmp_path):
        """Queued (never-deployed) jobs recover at their ORIGINAL
        attempt and ORIGINAL submission order — the FIFO position is
        part of the durable record, not an accident of directory
        listing order."""
        conf = _cluster_conf(tmp_path / "ha")
        d1 = SessionDispatcher(conf)
        try:
            # no runners: every submission parks WAITING_FOR_RESOURCES
            for jid, extra in (("j-early", {}),
                               ("j-mid", {"session.slots-per-job": 2}),
                               ("j-late", {})):
                assert d1.rpc_submit_session_job(
                    jid, "runner_job:build", extra)["admitted"]
            stamps = {j: d1.jobs[j].submitted_at
                      for j in ("j-early", "j-mid", "j-late")}
        finally:
            d1.close()
        d2 = _build_dispatcher(conf)
        try:
            assert d2.recovered_jobs == 3
            with d2._lock:
                assert d2._waiting_locked() == [
                    "j-early", "j-mid", "j-late"]
            for jid in stamps:
                j = d2.jobs[jid]
                assert j.attempts == 1  # never deployed: no restore bump
                assert j.submitted_at == stamps[jid]
                assert j.reattach_attempt is None
            assert d2.jobs["j-mid"].required_devices == 2  # quota kept
        finally:
            d2.close()

    def test_admission_persists_before_returning(self, tmp_path):
        """The durable write happens BEFORE rpc_submit_session_job
        returns: the store already holds the record (with its FIFO
        stamp) by the time the caller sees admitted=True."""
        ha = tmp_path / "ha"
        d = SessionDispatcher(_cluster_conf(ha))
        try:
            assert d.rpc_submit_session_job(
                "durable", "runner_job:build", {})["admitted"]
            rec = JobStore(str(ha)).get("durable")
            assert rec is not None
            assert rec["state"] == "WAITING_FOR_RESOURCES"
            assert rec["submitted_at"] == d.jobs["durable"].submitted_at
            assert rec["config"]["session.slots-per-job"] == 1
        finally:
            d.close()

    def test_terminal_state_erased_from_active_registry(self, tmp_path):
        ha = tmp_path / "ha"
        d = SessionDispatcher(_cluster_conf(ha))
        try:
            assert d.rpc_submit_session_job(
                "gone", "runner_job:build", {})["admitted"]
            assert d.rpc_cancel_job("gone")["ok"]
            store = JobStore(str(ha))
            assert store.recoverable() == []  # a new leader re-runs nothing
            assert store.get("gone")["state"] == "CANCELED"  # archived
        finally:
            d.close()


# ---------------------------------------------------------------------------
# re-attach mechanics (fake gateway: deterministic, no drivers)
# ---------------------------------------------------------------------------

class _GW(RpcEndpoint):
    def __init__(self):
        self.jobs = []

    def rpc_run_job(self, job_id, entry, config=None, attempt=1, **kw):
        self.jobs.append((job_id, attempt, dict(config or {})))
        return {"accepted": True}

    def rpc_cancel_job(self, job_id, attempt=None, **kw):
        return {"ok": True}


class TestReattach:
    def _running_job(self, tmp_path, gw_srv):
        """Leader 1: register a runner, deploy one job, then die.

        Waits for the deploy PUSH to land at the gateway, not the
        in-memory RUNNING flip: the durable record and the push both
        trail the (unlocked-readable) state assignment, and a leader
        killed in that gap correctly recovers the job as still-queued
        — which is not the scenario these tests stage."""
        conf = _cluster_conf(tmp_path / "ha")
        d1 = SessionDispatcher(conf)
        d1.leader_epoch = 1
        try:
            d1.rpc_register_runner("r1", "127.0.0.1", 1,
                                   port=gw_srv.port)
            assert d1.rpc_submit_session_job(
                "live", "runner_job:build", {})["admitted"]
            wait_until(lambda: len(gw_srv.endpoint.jobs) >= 1, 10,
                       what="deploy pushed by leader 1")
        finally:
            d1.close()
        return conf

    def test_register_with_inventory_reattaches_in_place(
            self, tmp_path):
        gw = _GW()
        srv = RpcServer(gw)
        d2 = None
        try:
            conf = self._running_job(tmp_path, srv)
            d2 = _build_dispatcher(conf)
            d2.leader_epoch = 2
            j = d2.jobs["live"]
            assert j.state == "WAITING_FOR_RESOURCES"
            assert j.reattach_attempt == 1
            assert j.attempts == 2  # pre-bumped for the fallback path
            # the runner comes back CARRYING the live execution:
            # re-adopted in place — slot occupancy rebuilt from truth
            d2.rpc_register_runner("r1", "127.0.0.1", 1, port=srv.port,
                                   jobs=[{"job_id": "live",
                                          "attempt": 1}])
            assert d2.jobs["live"].state == "RUNNING"
            assert d2.jobs["live"].attempts == 1  # rolled back: no restore
            assert d2.jobs["live"].assigned_runners == ["r1"]
            assert d2._slots.used_devices("r1") == 1
            time.sleep(0.4)  # any stray deploy kick would land by now
            # the ONLY pushes ever: leader 1's original deploy (which
            # may land late). A re-attach must never push attempt 2.
            assert all(a == 1 for _, a, _ in gw.jobs), (
                f"re-attach must not redeploy: {gw.jobs}")
        finally:
            if d2 is not None:
                d2.close()
            srv.close()

    def test_runner_back_without_job_redeploys_with_restore(
            self, tmp_path):
        gw = _GW()
        srv = RpcServer(gw)
        d2 = None
        try:
            conf = self._running_job(tmp_path, srv)
            d2 = _build_dispatcher(conf)
            d2.leader_epoch = 2
            # the stored runner re-registers WITHOUT the job (it died
            # there): the window collapses early and the checkpoint-
            # restore redeploy fires without waiting out the grace
            d2.rpc_register_runner("r1", "127.0.0.1", 1, port=srv.port,
                                   jobs=[])
            wait_until(lambda: any(a == 2 for _, a, _ in gw.jobs), 10,
                       what="fallback redeploy pushed")
            job_id, attempt, config = next(
                e for e in gw.jobs if e[1] == 2)
            assert job_id == "live"
            assert config["execution.checkpointing.restore"] == "latest"
            assert config["cluster.attempt"] == 2
        finally:
            if d2 is not None:
                d2.close()
            srv.close()

    def test_cancel_during_window_is_not_resurrected(self, tmp_path):
        """A job canceled while its re-attach window is open must STAY
        canceled when its runner re-registers carrying it (review
        regression: the unconditional re-adopt silently undid a cancel
        that had already returned ok=true)."""
        gw = _GW()
        srv = RpcServer(gw)
        d2 = None
        try:
            conf = self._running_job(tmp_path, srv)
            d2 = _build_dispatcher(conf)
            d2.leader_epoch = 2
            assert d2.jobs["live"].reattach_attempt == 1
            assert d2.rpc_cancel_job("live")["ok"]
            assert d2.jobs["live"].reattach_attempt is None
            d2.rpc_register_runner("r1", "127.0.0.1", 1, port=srv.port,
                                   jobs=[{"job_id": "live",
                                          "attempt": 1}])
            assert d2.jobs["live"].state == "CANCELED"
            # and the runner-side zombie is revocation-fenced
            hb = d2.rpc_heartbeat("r1", jobs=["live"])
            assert "live" in hb["revoked_jobs"]
            # the terminal state is durable (archived)
            assert JobStore(
                str(tmp_path / "ha")).get("live")["state"] == "CANCELED"
        finally:
            if d2 is not None:
                d2.close()
            srv.close()

    def test_second_failover_keeps_the_reattach_window(self, tmp_path):
        """Recovery must NOT overwrite the durable RUNNING record with
        its parked WAITING view: a second leader failing during the
        window would otherwise recover the job as never-deployed and
        blind-redeploy beside the live attempt (review regression)."""
        gw = _GW()
        srv = RpcServer(gw)
        try:
            conf = self._running_job(tmp_path, srv)
            d2 = _build_dispatcher(conf)
            assert d2.jobs["live"].reattach_attempt == 1
            d2.close()  # leader 2 dies before any runner came back
            rec = JobStore(str(tmp_path / "ha")).get("live")
            assert rec["state"] == "RUNNING"  # durable truth survives
            assert rec["attempts"] == 1
            assert rec["assigned_runners"] == ["r1"]
            d3 = _build_dispatcher(conf)
            try:
                # leader 3 re-opens the window at the ORIGINAL attempt
                assert d3.jobs["live"].reattach_attempt == 1
                assert d3.jobs["live"].reattach_runners == ["r1"]
            finally:
                d3.close()
        finally:
            srv.close()

    def test_duplicate_submit_after_takeover_acks(self, tmp_path):
        """The HA client's retry of a submit whose response died with
        the leader re-sends the same (job_id, entry) to the new
        leader, which recovered the job — it must ack the duplicate,
        not fail a script whose job IS admitted (review regression)."""
        conf = _cluster_conf(tmp_path / "ha")
        d1 = SessionDispatcher(conf)
        assert d1.rpc_submit_session_job(
            "retry-me", "runner_job:build", {})["admitted"]
        d1.close()  # response lost with the leader
        d2 = _build_dispatcher(conf)
        try:
            r = d2.rpc_submit_session_job(
                "retry-me", "runner_job:build", {})
            assert r["admitted"] and r.get("duplicate")
            # a DIFFERENT job under the recovered id is still refused
            r = d2.rpc_submit_session_job("retry-me", "other:entry", {})
            assert not r["admitted"]
        finally:
            d2.close()

    def test_cross_host_job_never_adopts_through_one_runner(
            self, tmp_path):
        """A cross-host (num-processes > 1) job is only whole with ALL
        its process allocations: one runner carrying it back must not
        re-adopt it single-runner — the window collapses into the
        restore redeploy path instead (which parks until enough
        distinct runners exist)."""
        ha = tmp_path / "ha"
        store = JobStore(str(ha))
        store.put("xh", entry="runner_job:build",
                  config={"cluster.num-processes": 2},
                  state="RUNNING", attempts=1,
                  submitted_at=time.time(),
                  assigned_runners=["r1", "r2"])
        gw = _GW()
        srv = RpcServer(gw)
        d2 = None
        try:
            d2 = _build_dispatcher(_cluster_conf(ha))
            d2.leader_epoch = 2
            assert d2.jobs["xh"].reattach_attempt == 1
            d2.rpc_register_runner("r1", "127.0.0.1", 2, port=srv.port,
                                   jobs=[{"job_id": "xh",
                                          "attempt": 1}])
            j = d2.jobs["xh"]
            assert j.reattach_attempt is None  # collapsed, not adopted
            assert j.attempts == 2  # the restore redeploy's attempt
            # one runner cannot host a 2-process job: it parks instead
            # of being mis-adopted RUNNING on r1 alone
            time.sleep(0.3)
            assert j.state == "WAITING_FOR_RESOURCES"
            assert gw.jobs == []
        finally:
            if d2 is not None:
                d2.close()
            srv.close()

    def test_grace_expiry_redeploys_on_fresh_capacity(self, tmp_path):
        gw = _GW()
        srv = RpcServer(gw)
        d2 = None
        try:
            conf = self._running_job(tmp_path, srv)
            conf.set("session.ha.reattach-grace", "1500ms")
            d2 = _build_dispatcher(conf)
            d2.leader_epoch = 2
            deadline = time.time() + 1.0  # well inside the grace
            # a DIFFERENT runner registers (the stored one is gone for
            # good): the job must not deploy inside the grace window...
            d2.rpc_register_runner("r2", "127.0.0.1", 1, port=srv.port,
                                   jobs=[])
            time.sleep(0.15)
            if time.time() < deadline:  # loaded-host guard
                assert all(a == 1 for _, a, _ in gw.jobs), (
                    "redeployed inside the re-attach grace window")
            # ...but does once the window expires (monitor-loop kick)
            wait_until(lambda: any(a == 2 for _, a, _ in gw.jobs), 15,
                       what="post-grace redeploy")
            assert next(e for e in gw.jobs if e[1] == 2)[0] == "live"
        finally:
            if d2 is not None:
                d2.close()
            srv.close()


# ---------------------------------------------------------------------------
# the new fault points, each wired into a chaos schedule
# ---------------------------------------------------------------------------

class TestHaFaultPoints:
    def test_lease_renew_chaos_deposes_stalled_leader(self, tmp_path):
        """ha.lease.renew chaos: a leader whose renewals fail (frozen
        process, NFS blip) ages past its lease — the standby steals it
        with a bumped epoch and the incumbent sees a revoke, never a
        crash of its contender thread."""
        d = str(tmp_path)
        a = LeaderElection(d, "127.0.0.1:1111", lease_timeout_s=0.4,
                           leader_id="stall-a")
        b = LeaderElection(d, "127.0.0.1:2222", lease_timeout_s=0.4,
                           leader_id="steal-b")
        revoked = threading.Event()
        a.on_revoke = revoked.set
        plan = faults.FaultPlan(seed=11).rule("ha.lease.renew", "raise")
        try:
            with plan.activate():
                a.start()
                wait_until(lambda: a.is_leader, 10, what="a leads")
                epoch_a = a.epoch
                b.start()
                wait_until(lambda: b.is_leader, 15,
                           what="standby stole the stalled lease")
                assert b.epoch > epoch_a  # fencing token advanced
                assert revoked.wait(10), "deposed leader never revoked"
            assert any(p == "ha.lease.renew" for p, _, _ in plan.log)
        finally:
            a.close()
            b.close()

    def test_store_write_chaos_loses_submission_cleanly(self, tmp_path):
        """ha.store.write chaos at admission: persisted-BEFORE-
        registered means an injected store failure loses the
        submission whole — no half-admitted job in memory, nothing on
        disk, and the caller's retry admits normally."""
        ha = tmp_path / "ha"
        disp = SessionDispatcher(_cluster_conf(ha))
        plan = faults.FaultPlan(seed=7).rule("ha.store.write", "raise",
                                             count=1)
        try:
            with plan.activate():
                with pytest.raises(OSError) as e:
                    disp.rpc_submit_session_job(
                        "s1", "runner_job:build", {})
                assert faults.is_injected(e.value)
                assert "s1" not in disp.jobs, (
                    "a failed durable write must not half-register")
                assert JobStore(str(ha)).get("s1") is None
                r = disp.rpc_submit_session_job(
                    "s1", "runner_job:build", {})
                assert r["admitted"]
                assert JobStore(str(ha)).get("s1")["state"] == (
                    "WAITING_FOR_RESOURCES")
        finally:
            disp.close()

    def test_takeover_chaos_retries_construction(self, tmp_path):
        """session.failover.takeover chaos: a standby dying mid-
        re-hydration — the serve loop's bounded construction retry
        (serve_session/_build_dispatcher) absorbs it and the second
        pass recovers the full registry."""
        conf = _cluster_conf(tmp_path / "ha")
        d1 = SessionDispatcher(conf)
        assert d1.rpc_submit_session_job(
            "q1", "runner_job:build", {})["admitted"]
        stamp = d1.jobs["q1"].submitted_at
        d1.close()
        plan = faults.FaultPlan(seed=3).rule(
            "session.failover.takeover", "raise", count=1)
        with plan.activate():
            d2 = _build_dispatcher(conf)
        try:
            assert plan.log and plan.log[0][0] == (
                "session.failover.takeover")
            assert d2.recovered_jobs == 1
            assert d2.jobs["q1"].submitted_at == stamp
        finally:
            d2.close()
    # runner.reattach is wired into the kill-the-leader schedule below
    # (a dropped re-registration rides the next heartbeat miss)


# ---------------------------------------------------------------------------
# THE acceptance scenario: kill the leader under load
# ---------------------------------------------------------------------------

class TestKillTheLeaderChaos:
    # tenant A is the shorter job (its checkpoints are mid-flight at
    # the kill), tenant B outlives A so the freed headroom admits the
    # queue strictly FIFO while B still runs
    N_A, N_B, N_Q = 50, 65, 6

    def _run_scenario(self, tmp_path, seed, kill_after_checkpoint=True):
        """Two tenants live (one mid-checkpoint) + two queued jobs;
        SIGKILL the leader; the standby takes over; everything
        finishes exactly-once; the deposed epoch is fenced at the
        runner. Returns the standby's dispatcher state for extra
        asserts (seed varies the reattach-drop schedule in the soak)."""
        from flink_tpu.runtime.runner import TaskRunner

        ha = tmp_path / "ha"
        conf = _cluster_conf(ha, {"session.max-jobs": 2,
                                  "session.runner-slots": 2})
        A = Contender(ha, conf, "leader-a")
        disp_a = A.serve()
        assert disp_a.leader_epoch == 1
        B = Contender(ha, conf, "standby-b")  # hot standby: contends
        runner = TaskRunner("127.0.0.1", A.port, runner_id="r-ha",
                            ha_dir=str(ha))
        try:
            runner.start()
            wait_until(lambda: "r-ha" in disp_a.runners, 15,
                       what="runner registered with leader")
            for tag, n in (("a", self.N_A), ("b", self.N_B)):
                assert disp_a.rpc_submit_session_job(
                    f"job-{tag}", "runner_job:build",
                    _job_conf(tmp_path, tag, n, sleep_ms=100)
                )["admitted"]
            wait_until(
                lambda: all(disp_a.jobs[f"job-{t}"].state == "RUNNING"
                            for t in ("a", "b")), 30,
                what="both tenants running")
            for tag in ("c", "d"):  # past max-jobs=2: queued FIFO
                assert disp_a.rpc_submit_session_job(
                    f"job-{tag}", "runner_job:build",
                    _job_conf(tmp_path, tag, self.N_Q))["admitted"]
            jobs_view = {j["job_id"]: j for j in
                         disp_a.rpc_session_jobs()["jobs"]}
            assert jobs_view["job-c"]["queue_position"] == 0
            assert jobs_view["job-d"]["queue_position"] == 1
            if kill_after_checkpoint:
                # tenant A mid-checkpoint: at least one completed
                # checkpoint exists and more land every 150ms
                wait_until(
                    lambda: _has_checkpoint(tmp_path, "job-a"), 30,
                    what="tenant A checkpointing")

            # ---- SIGKILL the leader; the re-attach push itself is
            # under chaos (runner.reattach drop: the first
            # re-registration is lost and rides the next beat) -------
            plan = faults.FaultPlan(seed=seed).rule(
                "runner.reattach", "drop", count=1)
            with plan.activate():
                A.sigkill()
                disp_b = B.serve(timeout=25)
                assert disp_b.leader_epoch == 2
                assert disp_b.recovered_jobs == 4
                # the standby re-attaches the LIVE tenants in place:
                # same attempt (no redeploy), slots rebuilt from truth
                wait_until(
                    lambda: all(
                        disp_b.jobs[j].state in ("RUNNING", "FINISHED")
                        for j in ("job-a", "job-b")), 30,
                    what="tenants re-attached to the new leader")
            assert any(p == "runner.reattach" for p, _, _ in plan.log)
            for j in ("job-a", "job-b"):
                assert disp_b.jobs[j].attempts == 1, (
                    f"{j} was redeployed instead of re-attached")
            if disp_b.jobs["job-b"].state == "RUNNING":
                with disp_b._lock:
                    assert disp_b._slots.used_devices("r-ha") >= 1

            # ---- the deposed leader's late RPCs are fenced ----------
            c = RpcClient("127.0.0.1", runner._server.port)
            try:
                late = c.call("run_job", job_id="zombie-from-epoch-1",
                              entry="runner_job:build",
                              config={}, attempt=1, leader_epoch=1)
                assert late["accepted"] is False
                assert "stale leader epoch" in late["reason"]
                late = c.call("cancel_job", job_id="job-b",
                              leader_epoch=1)
                assert late["ok"] is False
                assert "stale leader epoch" in late["reason"]
            finally:
                c.close()

            # ---- everything runs to completion, FIFO preserved ------
            wait_until(lambda: disp_b.jobs["job-a"].state == "FINISHED",
                       90, what="tenant A finished")
            # started_at is stamped at deploy: unlike a state poll it
            # cannot be missed when the short queued job races through
            # RUNNING between two polls
            wait_until(
                lambda: disp_b.jobs["job-c"].started_at is not None,
                30, what="queued job-c deployed on freed slot")
            if (disp_b.jobs["job-b"].state == "RUNNING"
                    and disp_b.jobs["job-c"].state == "RUNNING"):
                # strict FIFO: while B and C hold both slots, job-d
                # must not have jumped job-c's admission
                assert disp_b.jobs["job-d"].state == (
                    "WAITING_FOR_RESOURCES")
            for j in ("job-b", "job-c", "job-d"):
                wait_until(
                    lambda j=j: disp_b.jobs[j].state == "FINISHED",
                    120, what=f"{j} finished")
            assert disp_b.jobs["job-c"].started_at <= (
                disp_b.jobs["job-d"].started_at)
            # the fenced cancel never landed: job-b ran to completion
            assert disp_b.jobs["job-b"].state == "FINISHED"
            info = disp_b.rpc_session_info()
            assert info["leader_epoch"] == 2
            assert info["takeovers"] == 1
            return disp_b
        finally:
            runner.close()
            A.sigkill()
            B.close()

    def test_kill_leader_standby_takes_over_exactly_once(
            self, tmp_path):
        # the no-failover golden for tenant A (the mid-checkpoint
        # one): a fault-free run of the identical job on a plain
        # cluster — its committed rows are the byte-comparable bar
        with LocalSessionCluster(Configuration({
                "heartbeat.interval": "200ms",
                "session.autoscale": False}), runners=1,
                runner_prefix="golden") as g:
            r = g.submit("runner_job:build",
                         config=_job_conf(tmp_path / "solo", "a",
                                          self.N_A),
                         job_id="golden-a")
            assert r["admitted"]
            assert g.wait("golden-a") == "FINISHED"
        golden_a = _committed(str(tmp_path / "solo" / "sink-a"))
        assert golden_a

        self._run_scenario(tmp_path, seed=1)

        # exactly-once across the takeover: tenant A's committed rows
        # are identical to the fault-free golden, row for row; every
        # other job matches the deterministic model
        assert _committed(str(tmp_path / "sink-a")) == golden_a
        _assert_exactly_once(str(tmp_path / "sink-a"), self.N_A)
        _assert_exactly_once(str(tmp_path / "sink-b"), self.N_B)
        _assert_exactly_once(str(tmp_path / "sink-c"), self.N_Q)
        _assert_exactly_once(str(tmp_path / "sink-d"), self.N_Q)
        # checkpoint subtrees stayed disjoint per tenant
        assert sorted(os.listdir(tmp_path / "chk")) == [
            "job-a", "job-b", "job-c", "job-d"]

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [2, 3, 5])
    def test_kill_leader_soak(self, tmp_path, seed):
        """Multi-seed soak: the same takeover under varied reattach-
        drop schedules (the seed drives the fault plan's per-point
        PRNG). Printed on failure for replay."""
        print(f"kill-the-leader soak seed={seed}")
        self._run_scenario(tmp_path, seed=seed,
                           kill_after_checkpoint=(seed % 2 == 0))
        for tag, n in (("a", self.N_A), ("b", self.N_B),
                       ("c", self.N_Q), ("d", self.N_Q)):
            _assert_exactly_once(str(tmp_path / f"sink-{tag}"), n)


# ---------------------------------------------------------------------------
# tier-1 CLI smoke: real subprocesses, real SIGKILL
# ---------------------------------------------------------------------------

class TestSessionHaCliSmoke:
    """ISSUE 11 satellite: `session start` leader + `session start
    --standby` as REAL subprocesses sharing one --ha-dir; two jobs
    submitted through the lease; SIGKILL the leader mid-run; the
    standby is granted leadership, redeploys both jobs through
    checkpoint restore (the leader's in-process runner died with it),
    and both committed outputs match the no-failover golden.
    `session stop` against the NEW leader exits 0."""

    def _cli(self, env, *argv, timeout=120):
        p = subprocess.run([sys.executable, "-m", "flink_tpu", *argv],
                           env=env, capture_output=True, text=True,
                           cwd=REPO, timeout=timeout)
        out = p.stdout.strip().splitlines()
        return p.returncode, (json.loads(out[-1]) if out else {})

    def _read_json_line(self, proc, want_key, deadline_s=60):
        deadline = time.time() + deadline_s
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise AssertionError("process closed stdout early")
            line = line.strip()
            if line.startswith("{"):
                obj = json.loads(line)
                if want_key in obj:
                    return obj
        raise AssertionError(f"no {want_key!r} line within {deadline_s}s")

    def test_sigkill_leader_standby_finishes_both_jobs(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + os.path.join(
            REPO, "tests")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        ha = str(tmp_path / "ha")
        common = ["--ha-dir", ha,
                  "--conf", "heartbeat.interval=200ms",
                  "--conf", "high-availability.lease-timeout=700ms",
                  "--conf", "session.ha.reattach-grace=1500ms",
                  "--conf", "session.autoscale=false"]
        leader = subprocess.Popen(
            [sys.executable, "-m", "flink_tpu", "session", "start",
             "--local-runners", "1", *common],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        standby = None
        try:
            assert self._read_json_line(leader, "session")
            elected = self._read_json_line(leader, "elected")
            assert elected["epoch"] == 1
            standby = subprocess.Popen(
                [sys.executable, "-m", "flink_tpu", "session", "start",
                 "--standby", "--local-runners", "1", *common],
                env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            assert self._read_json_line(standby, "session")["standby"]

            n = 150  # ~7.5s+ of batches: still mid-run at the kill
            for tag in ("a", "b"):
                conf_args = []
                for k, v in _job_conf(tmp_path, tag, n,
                                      sleep_ms=50).items():
                    conf_args += ["--conf", f"{k}={v}"]
                rc, out = self._cli(
                    env, "session", "submit", "--ha-dir", ha,
                    "--entry", "runner_job:build",
                    "--job-id", f"ha-{tag}", *conf_args)
                assert rc == 0 and out["admitted"], out
            # kill only once both jobs checkpointed: the redeploy must
            # travel the restore path, not a fresh re-execution
            for tag in ("a", "b"):
                wait_until(
                    lambda tag=tag: _has_checkpoint(tmp_path,
                                                    f"ha-{tag}"),
                    60, what=f"ha-{tag} first checkpoint")
            os.kill(leader.pid, signal.SIGKILL)
            leader.wait(timeout=10)

            deadline = time.time() + 180
            states = {}
            while time.time() < deadline:
                rc, out = self._cli(env, "session", "list",
                                    "--ha-dir", ha)
                if rc == 0 and out.get("jobs"):
                    states = {j["job_id"]: j["state"]
                              for j in out["jobs"]}
                    assert "FAILED" not in states.values(), states
                    if set(states.values()) == {"FINISHED"}:
                        break
                time.sleep(1.0)
            else:
                raise AssertionError(
                    f"jobs never finished after failover: {states}")
            assert out["leader_epoch"] == 2  # the standby's incumbency

            # exactly-once through the takeover: committed rows match
            # the no-failover golden model despite kill + restore
            _assert_exactly_once(str(tmp_path / "sink-a"), n)
            _assert_exactly_once(str(tmp_path / "sink-b"), n)

            rc, out = self._cli(env, "session", "info", "--ha-dir", ha)
            assert rc == 0
            assert out["leader_epoch"] == 2 and out["takeovers"] == 1
            rc, out = self._cli(env, "session", "stop", "--ha-dir", ha)
            assert rc == 0 and out["ok"]
            assert standby.wait(timeout=30) == 0
        finally:
            for p in (leader, standby):
                if p is not None and p.poll() is None:
                    p.kill()
