"""Restart-strategy backoff logic under an injected clock.

The window pruning of FailureRateRestartStrategy and the
reset-after-quiet-period of ExponentialDelayRestartStrategy are
time-dependent paths that real-time tests cannot reach (an hour-long
quiet period); the ``now_fn`` seam drives them with a fake clock (ref:
the ManualClock the reference's *RestartBackoffTimeStrategyTest*s use).
"""
from flink_tpu.runtime.restart import (
    ExponentialDelayRestartStrategy,
    FailureRateRestartStrategy,
)


class FakeClock:
    def __init__(self, t0: float = 1_000_000.0) -> None:
        self.t = t0

    def __call__(self) -> float:
        return self.t

    def advance(self, seconds: float) -> None:
        self.t += seconds


class TestFailureRateWindowPruning:
    def test_failures_inside_window_exhaust_budget(self):
        clk = FakeClock()
        s = FailureRateRestartStrategy(max_failures=3, interval_ms=60_000,
                                       delay_ms=100, now_fn=clk)
        for _ in range(3):
            assert s.can_restart()
            assert s.next_delay_ms() == 100
            clk.advance(1.0)
        assert not s.can_restart()  # 3 failures within 60s: budget spent

    def test_window_pruning_restores_budget(self):
        clk = FakeClock()
        s = FailureRateRestartStrategy(max_failures=3, interval_ms=60_000,
                                       delay_ms=100, now_fn=clk)
        for _ in range(3):
            s.next_delay_ms()
            clk.advance(1.0)
        assert not s.can_restart()
        # the oldest failure is 3s old; once it ages past the 60s window
        # the budget frees exactly one slot
        clk.advance(58.0)  # oldest now 61s old, the other two inside
        assert s.can_restart()
        s.next_delay_ms()
        assert not s.can_restart()  # refilled slot spent again

    def test_prune_is_by_age_not_count(self):
        clk = FakeClock()
        s = FailureRateRestartStrategy(max_failures=2, interval_ms=10_000,
                                       now_fn=clk)
        s.next_delay_ms()
        clk.advance(11.0)  # first failure leaves the window entirely
        s.next_delay_ms()
        assert s.can_restart()  # only one failure inside the window


class TestExponentialDelayReset:
    def test_delay_doubles_to_cap(self):
        clk = FakeClock()
        s = ExponentialDelayRestartStrategy(
            initial_ms=1000, max_ms=8000, multiplier=2.0,
            reset_after_ms=3_600_000, now_fn=clk)
        got = []
        for _ in range(6):
            got.append(s.next_delay_ms())
            clk.advance(1.0)
        assert got == [1000, 2000, 4000, 8000, 8000, 8000]

    def test_quiet_period_resets_backoff(self):
        clk = FakeClock()
        s = ExponentialDelayRestartStrategy(
            initial_ms=1000, max_ms=300_000, multiplier=2.0,
            reset_after_ms=3_600_000, now_fn=clk)
        for _ in range(4):
            s.next_delay_ms()
            clk.advance(60.0)
        assert s.next_delay_ms() == 16_000
        # a full quiet HOUR since the last failure: backoff starts over
        clk.advance(3600.0)
        assert s.next_delay_ms() == 1000
        clk.advance(1.0)
        assert s.next_delay_ms() == 2000

    def test_just_under_quiet_period_keeps_backoff(self):
        clk = FakeClock()
        s = ExponentialDelayRestartStrategy(
            initial_ms=1000, max_ms=300_000, multiplier=2.0,
            reset_after_ms=3_600_000, now_fn=clk)
        s.next_delay_ms()  # 1000
        clk.advance(3599.0)  # one second short of the reset threshold
        assert s.next_delay_ms() == 2000
