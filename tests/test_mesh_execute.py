"""Mesh execution through the PUBLIC API (SURVEY §3.7, §4.A): with
``cluster.mesh-devices`` set, ``env.execute()`` runs the sharded step
over the virtual 8-device CPU mesh — and the results must be
byte-identical to single-device local execution. This is the
parallelism-rescaling correctness contract (ref: AbstractOperatorRestore
/ RescalingITCase compare-parallelism pattern).
"""
import numpy as np
import pytest
import jax

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import SlidingEventTimeWindows, TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = pytest.mark.shard_map  # device-mesh suite: skipped when shard_map is unavailable


def make_env(mesh=None, extra=None):
    conf = {
        "state.num-key-shards": 32,
        "state.slots-per-shard": 16,
        "pipeline.microbatch-size": 256,
    }
    if mesh:
        conf["cluster.mesh-devices"] = mesh
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def rows_of(sink):
    out = []
    for row in sink.rows:
        out.append(tuple(
            (k, int(v) if np.issubdtype(np.asarray(v).dtype, np.integer)
             else round(float(v), 4))
            for k, v in sorted(row.items())))
    return sorted(out)


def source(n_batches=8, n_keys=100, seed=0):
    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(seed * 1000 + i)
        b = 192
        return ({"k": rng.integers(0, n_keys, b).astype(np.int64),
                 "v": rng.integers(1, 50, b).astype(np.int64)},
                np.sort(rng.integers(i * 700, i * 700 + 1400, b)).astype(np.int64))
    return gen


def build_q5_shape(env, sink, topn=None, n_batches=8, n_keys=100):
    """The Q5 pipeline shape: keyed sliding-window count (+ device
    top-n when ``topn``)."""
    s = (env.from_source(
            GeneratorSource(source(n_batches, n_keys)),
            WatermarkStrategy.for_bounded_out_of_orderness(500))
         .key_by("k")
         .window(SlidingEventTimeWindows.of(4_000, 1_000))
         .count())
    if topn:
        s = s.top(topn, by="count")
    s.add_sink(sink)
    return s


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
class TestMeshExecute:
    def test_q5_sharded_via_public_api_matches_local(self):
        env_local = make_env()
        local_sink = CollectSink()
        build_q5_shape(env_local, local_sink)
        env_local.execute("q5-local")

        env_mesh = make_env(mesh="all")
        mesh_sink = CollectSink()
        build_q5_shape(env_mesh, mesh_sink)
        env_mesh.execute("q5-mesh")

        assert rows_of(local_sink) == rows_of(mesh_sink)
        assert len(rows_of(local_sink)) > 0

    def test_q5_topn_sharded_matches_local(self):
        env_local = make_env()
        local_sink = CollectSink()
        build_q5_shape(env_local, local_sink, topn=3)
        env_local.execute("q5top-local")

        env_mesh = make_env(mesh="all")
        mesh_sink = CollectSink()
        build_q5_shape(env_mesh, mesh_sink, topn=3)
        env_mesh.execute("q5top-mesh")

        assert rows_of(local_sink) == rows_of(mesh_sink)
        assert len(rows_of(local_sink)) > 0

    def test_topn_cross_device_ties_kept(self):
        """Keys engineered so the n-th count TIES across device
        boundaries: the distributed RANK()<=n (all_gather threshold)
        must keep every tying key, exactly like the local path."""
        def gen(split, i):
            if i >= 1:
                return None
            # 12 keys spread over all shards; counts: four keys tie at 5
            # (the n=2 threshold), others below
            keys, counts = [], {}
            rng = np.random.default_rng(42)
            tie_keys = [3, 40, 77, 90]     # hash to different shards
            low_keys = [5, 21, 55, 68]
            rows = []
            for k in tie_keys:
                rows += [k] * 5
            for k in low_keys:
                rows += [k] * 2
            rows = np.asarray(rows, np.int64)
            ts = np.full(len(rows), 500, np.int64)
            return ({"k": rows}, ts)

        def build(env, sink):
            (env.from_source(GeneratorSource(gen),
                             WatermarkStrategy.for_bounded_out_of_orderness(0))
             .key_by("k")
             .window(TumblingEventTimeWindows.of(1_000))
             .count()
             .top(2, by="count")
             .add_sink(sink))

        env_local, local_sink = make_env(), CollectSink()
        build(env_local, local_sink)
        env_local.execute("ties-local")

        env_mesh, mesh_sink = make_env(mesh="all"), CollectSink()
        build(env_mesh, mesh_sink)
        env_mesh.execute("ties-mesh")

        local_rows = rows_of(local_sink)
        assert local_rows == rows_of(mesh_sink)
        # all four tying keys survive the distributed threshold
        keys_out = {dict(r)["key"] for r in local_rows}
        assert keys_out == {3, 40, 77, 90}

    def test_sum_aggregate_sharded_matches_local(self):
        def build(env, sink):
            (env.from_source(GeneratorSource(source(6, 64, seed=9)),
                             WatermarkStrategy.for_bounded_out_of_orderness(500))
             .key_by("k")
             .window(TumblingEventTimeWindows.of(2_000))
             .sum("v")
             .add_sink(sink))

        env_local, local_sink = make_env(), CollectSink()
        build(env_local, local_sink)
        env_local.execute("sum-local")

        env_mesh, mesh_sink = make_env(mesh="all"), CollectSink()
        build(env_mesh, mesh_sink)
        env_mesh.execute("sum-mesh")

        assert rows_of(local_sink) == rows_of(mesh_sink)

    def test_mesh_devices_n_selects_subset(self):
        env = make_env(mesh="4")
        mp = env.build_mesh_plan()
        assert mp.n_devices == 4
        assert make_env(mesh="1").build_mesh_plan() is None
        assert make_env().build_mesh_plan() is None


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
class TestExchangeNoLoss:
    def test_skewed_keys_tiny_capacity_exact_results(self):
        """Worst-case skew: ONE key (every record routes to one shard on
        one device) with exchange capacity 8. The host-side batch split
        must deliver every record — exact counts, zero overflow — where
        the counted-drop design silently lost data (round-2 weakness)."""
        def gen(split, i):
            if i >= 4:
                return None
            rng = np.random.default_rng(i)
            b = 192
            return ({"k": np.zeros(b, np.int64)},
                    np.sort(rng.integers(i * 700, i * 700 + 1400, b)).astype(np.int64))

        def build(env, sink):
            (env.from_source(GeneratorSource(gen),
                             WatermarkStrategy.for_bounded_out_of_orderness(500))
             .key_by("k")
             .window(TumblingEventTimeWindows.of(1_000))
             .count()
             .add_sink(sink))

        env_local, local_sink = make_env(), CollectSink()
        build(env_local, local_sink)
        env_local.execute("skew-local")

        env_mesh, mesh_sink = make_env(
            mesh="all", extra={"pipeline.exchange-capacity": 8}), CollectSink()
        build(env_mesh, mesh_sink)
        res = env_mesh.execute("skew-mesh")

        assert rows_of(local_sink) == rows_of(mesh_sink)
        assert sum(int(r["count"]) for r in mesh_sink.rows) == 4 * 192
        assert res.metrics.get("exchange_overflow", 0) == 0

    def test_mixed_skew_capacity_split_matches_local(self):
        """Hot key + long tail under a small capacity: split batches
        must still aggregate identically to the local path."""
        def gen(split, i):
            if i >= 5:
                return None
            rng = np.random.default_rng(100 + i)
            b = 256
            hot = rng.random(b) < 0.7
            keys = np.where(hot, 7, rng.integers(0, 50, b)).astype(np.int64)
            return ({"k": keys},
                    np.sort(rng.integers(i * 700, i * 700 + 1400, b)).astype(np.int64))

        def build(env, sink):
            (env.from_source(GeneratorSource(gen),
                             WatermarkStrategy.for_bounded_out_of_orderness(500))
             .key_by("k")
             .window(TumblingEventTimeWindows.of(2_000))
             .count()
             .add_sink(sink))

        env_local, local_sink = make_env(), CollectSink()
        build(env_local, local_sink)
        env_local.execute("mix-local")

        env_mesh, mesh_sink = make_env(
            mesh="all", extra={"pipeline.exchange-capacity": 16}), CollectSink()
        build(env_mesh, mesh_sink)
        env_mesh.execute("mix-mesh")

        assert rows_of(local_sink) == rows_of(mesh_sink)

    def test_split_invariant_padded_layout(self):
        """Property check on the splitter itself: every accepted chunk,
        re-bucketed with the PADDED dispatch layout (block length
        target // n_dev — what the device-side arrival split uses),
        stays within capacity. Guards the check-vs-dispatch layout
        mismatch class of bug directly."""
        from flink_tpu.ops.aggregates import count
        from flink_tpu.ops.window import WindowOperator
        from flink_tpu.parallel.mesh import make_mesh_plan

        mp = make_mesh_plan(num_shards=32, slots_per_shard=16)
        op = WindowOperator(TumblingEventTimeWindows.of(1_000), count(),
                            num_shards=32, slots_per_shard=16,
                            max_out_of_orderness_ms=500,
                            mesh_plan=mp, exchange_capacity=4)
        rng = np.random.default_rng(7)
        ring, spd, n_dev = op.plan.ring, mp.slots_per_device, mp.n_devices
        for trial in range(6):
            b = int(rng.integers(3, 400))
            # heavy skew: most records pack into few slots
            slots = np.where(rng.random(b) < 0.8, 0,
                             rng.integers(0, 32 * 16, b))
            pk = (slots * ring + rng.integers(0, ring, b)).astype(np.int64)
            chunks = op._split_for_exchange(pk, {"v": np.ones(b)}, n_dev)
            got = np.concatenate([c[0] for c in chunks])
            assert np.array_equal(np.sort(got), np.sort(pk))  # no loss
            for cpk, _, target in chunks:
                assert target % n_dev == 0 and target >= len(cpk)
                L = target // n_dev
                dest = (cpk // ring) // spd
                block = np.arange(len(cpk)) // L
                flat = block * n_dev + dest
                counts = np.bincount(flat, minlength=n_dev * n_dev)
                assert counts.max(initial=0) <= 4 or len(cpk) == 1

    def test_negative_exchange_capacity_rejected(self):
        env = make_env(mesh="all",
                       extra={"pipeline.exchange-capacity": -1})
        sink = CollectSink()
        build_q5_shape(env, sink, n_batches=1)
        with pytest.raises(ValueError, match="exchange-capacity"):
            env.execute("bad-cap")
