"""Mesh execution through the PUBLIC API (SURVEY §3.7, §4.A): with
``cluster.mesh-devices`` set, ``env.execute()`` runs the sharded step
over the virtual 8-device CPU mesh — and the results must be
byte-identical to single-device local execution. This is the
parallelism-rescaling correctness contract (ref: AbstractOperatorRestore
/ RescalingITCase compare-parallelism pattern).
"""
import numpy as np
import pytest
import jax

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import SlidingEventTimeWindows, TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.time.watermarks import WatermarkStrategy


def make_env(mesh=None, extra=None):
    conf = {
        "state.num-key-shards": 32,
        "state.slots-per-shard": 16,
        "pipeline.microbatch-size": 256,
    }
    if mesh:
        conf["cluster.mesh-devices"] = mesh
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def rows_of(sink):
    out = []
    for row in sink.rows:
        out.append(tuple(
            (k, int(v) if np.issubdtype(np.asarray(v).dtype, np.integer)
             else round(float(v), 4))
            for k, v in sorted(row.items())))
    return sorted(out)


def source(n_batches=8, n_keys=100, seed=0):
    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(seed * 1000 + i)
        b = 192
        return ({"k": rng.integers(0, n_keys, b).astype(np.int64),
                 "v": rng.integers(1, 50, b).astype(np.int64)},
                np.sort(rng.integers(i * 700, i * 700 + 1400, b)).astype(np.int64))
    return gen


def build_q5_shape(env, sink, topn=None, n_batches=8, n_keys=100):
    """The Q5 pipeline shape: keyed sliding-window count (+ device
    top-n when ``topn``)."""
    s = (env.from_source(
            GeneratorSource(source(n_batches, n_keys)),
            WatermarkStrategy.for_bounded_out_of_orderness(500))
         .key_by("k")
         .window(SlidingEventTimeWindows.of(4_000, 1_000))
         .count())
    if topn:
        s = s.top(topn, by="count")
    s.add_sink(sink)
    return s


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
class TestMeshExecute:
    def test_q5_sharded_via_public_api_matches_local(self):
        env_local = make_env()
        local_sink = CollectSink()
        build_q5_shape(env_local, local_sink)
        env_local.execute("q5-local")

        env_mesh = make_env(mesh="all")
        mesh_sink = CollectSink()
        build_q5_shape(env_mesh, mesh_sink)
        env_mesh.execute("q5-mesh")

        assert rows_of(local_sink) == rows_of(mesh_sink)
        assert len(rows_of(local_sink)) > 0

    def test_q5_topn_sharded_matches_local(self):
        env_local = make_env()
        local_sink = CollectSink()
        build_q5_shape(env_local, local_sink, topn=3)
        env_local.execute("q5top-local")

        env_mesh = make_env(mesh="all")
        mesh_sink = CollectSink()
        build_q5_shape(env_mesh, mesh_sink, topn=3)
        env_mesh.execute("q5top-mesh")

        assert rows_of(local_sink) == rows_of(mesh_sink)
        assert len(rows_of(local_sink)) > 0

    def test_topn_cross_device_ties_kept(self):
        """Keys engineered so the n-th count TIES across device
        boundaries: the distributed RANK()<=n (all_gather threshold)
        must keep every tying key, exactly like the local path."""
        def gen(split, i):
            if i >= 1:
                return None
            # 12 keys spread over all shards; counts: four keys tie at 5
            # (the n=2 threshold), others below
            keys, counts = [], {}
            rng = np.random.default_rng(42)
            tie_keys = [3, 40, 77, 90]     # hash to different shards
            low_keys = [5, 21, 55, 68]
            rows = []
            for k in tie_keys:
                rows += [k] * 5
            for k in low_keys:
                rows += [k] * 2
            rows = np.asarray(rows, np.int64)
            ts = np.full(len(rows), 500, np.int64)
            return ({"k": rows}, ts)

        def build(env, sink):
            (env.from_source(GeneratorSource(gen),
                             WatermarkStrategy.for_bounded_out_of_orderness(0))
             .key_by("k")
             .window(TumblingEventTimeWindows.of(1_000))
             .count()
             .top(2, by="count")
             .add_sink(sink))

        env_local, local_sink = make_env(), CollectSink()
        build(env_local, local_sink)
        env_local.execute("ties-local")

        env_mesh, mesh_sink = make_env(mesh="all"), CollectSink()
        build(env_mesh, mesh_sink)
        env_mesh.execute("ties-mesh")

        local_rows = rows_of(local_sink)
        assert local_rows == rows_of(mesh_sink)
        # all four tying keys survive the distributed threshold
        keys_out = {dict(r)["key"] for r in local_rows}
        assert keys_out == {3, 40, 77, 90}

    def test_sum_aggregate_sharded_matches_local(self):
        def build(env, sink):
            (env.from_source(GeneratorSource(source(6, 64, seed=9)),
                             WatermarkStrategy.for_bounded_out_of_orderness(500))
             .key_by("k")
             .window(TumblingEventTimeWindows.of(2_000))
             .sum("v")
             .add_sink(sink))

        env_local, local_sink = make_env(), CollectSink()
        build(env_local, local_sink)
        env_local.execute("sum-local")

        env_mesh, mesh_sink = make_env(mesh="all"), CollectSink()
        build(env_mesh, mesh_sink)
        env_mesh.execute("sum-mesh")

        assert rows_of(local_sink) == rows_of(mesh_sink)

    def test_mesh_devices_n_selects_subset(self):
        env = make_env(mesh="4")
        mp = env.build_mesh_plan()
        assert mp.n_devices == 4
        assert make_env(mesh="1").build_mesh_plan() is None
        assert make_env().build_mesh_plan() is None
