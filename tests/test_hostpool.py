"""Shared host worker-pool plane (flink_tpu/parallel/hostpool.py).

Two layers: unit tests of the pool's lifecycle/determinism/fault-seam
contract, and the §9.4 PARITY GATE — the sessions, windowAll, and
spill golden pipelines must produce BYTE-IDENTICAL output (same
fields, dtypes, values, and row order) at host.parallelism 1, 2, and
4, where 1 is the exact pre-pool serial path. The parity aggregates
are the exact lane monoids (count/max, integer-valued sums below
2**24), matching the §9 determinism contract's terms.
"""
import os
import threading
import time

import numpy as np
import pytest

from flink_tpu import faults
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import FnSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import (
    EventTimeSessionWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.config import Configuration, HostOptions
from flink_tpu.obs.metrics import MetricRegistry
from flink_tpu.ops import aggregates
from flink_tpu.ops.session import SessionOperator
from flink_tpu.parallel.hostpool import HostPool, default_parallelism
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = pytest.mark.hostpool

PARALLELISMS = (1, 2, 4)


# -- pool unit contract -----------------------------------------------------

class TestHostPoolUnit:
    def test_parallelism_one_is_inline_and_threadless(self):
        pool = HostPool(1)
        assert pool._executor is None  # the serial path makes no threads
        tids = []
        out = pool.run_tasks(
            [lambda i=i: (tids.append(threading.get_ident()), i)[1]
             for i in range(5)])
        assert out == [0, 1, 2, 3, 4]
        assert set(tids) == {threading.get_ident()}
        pool.close()

    def test_results_in_submission_order(self):
        pool = HostPool(4)
        try:
            def task(i):
                time.sleep(0.02 * (4 - i % 5))  # finish out of order
                return i
            out = pool.run_tasks([lambda i=i: task(i) for i in range(16)])
            assert out == list(range(16))
        finally:
            pool.close()

    def test_first_exception_by_index_propagates(self):
        pool = HostPool(4)
        try:
            def task(i):
                if i in (3, 7):
                    raise ValueError(f"boom-{i}")
                return i
            with pytest.raises(ValueError, match="boom-3"):
                pool.run_tasks([lambda i=i: task(i) for i in range(10)])
        finally:
            pool.close()

    def test_close_degrades_to_inline(self):
        pool = HostPool(4)
        pool.close()
        assert pool.run_tasks([lambda: 1, lambda: 2]) == [1, 2]

    def test_parallelism_below_one_rejected(self):
        with pytest.raises(ValueError, match="host.parallelism"):
            HostPool(0)

    def test_from_config_default_is_min_4_cores(self):
        pool = HostPool.from_config(Configuration())
        try:
            assert pool.parallelism == default_parallelism()
            assert pool.parallelism == min(4, os.cpu_count() or 1)
        finally:
            pool.close()

    def test_per_task_metrics(self):
        reg = MetricRegistry()
        pool = HostPool(2, registry=reg)
        try:
            pool.run_tasks([lambda: None] * 7)
        finally:
            pool.close()
        snap = reg.snapshot()
        assert snap["hostpool.tasks_total"] == 7
        assert snap["hostpool.task_ms.count"] == 7
        assert snap["hostpool.parallelism"] == 2.0

    def test_fault_point_registered_and_fires_at_submit(self):
        assert "host.pool.task" in faults.KNOWN_FAULT_POINTS
        for w in (1, 4):  # the seam behaves identically at any width
            pool = HostPool(w)
            plan = faults.FaultPlan(seed=0).rule(
                "host.pool.task", "raise", count=1, after=2)
            try:
                with plan.activate():
                    with pytest.raises(RuntimeError) as ei:
                        pool.run_tasks([lambda: 1] * 6)
                assert faults.is_injected(ei.value)
            finally:
                pool.close()


# -- the §9.4 serial-vs-parallel parity gates -------------------------------

def collect_ordered(env_builder):
    """Run the pipeline and return its sink output as one
    field→array dict, concatenated in DELIVERY order — the comparison
    covers values, dtypes, AND row order."""
    batches = []
    env = env_builder(FnSink(lambda b: batches.append(
        {k: np.asarray(v).copy() for k, v in b.items()})))
    env.execute("hostpool-parity")
    if not batches:
        return {}
    return {k: np.concatenate([b[k] for b in batches])
            for k in batches[0]}


def assert_byte_identical(ref, got, label):
    assert set(ref) == set(got), label
    for k in ref:
        assert ref[k].dtype == got[k].dtype, (label, k)
        assert np.array_equal(ref[k], got[k]), (label, k)
    for k in ref:
        assert len(ref[k])  # the gate must compare real output


def sessions_env(sink, w):
    """The sessions golden shape (bench config #4): bursty users, gap
    sessions, allowed lateness, ~5% late records — exercises merge,
    re-fire, beyond-lateness drops, and expiry on every shard."""
    def gen(split, i):
        if i >= 6:
            return None
        rng = np.random.default_rng(11 + i)
        user = rng.integers(0, 300, 4096).astype(np.int64)
        base = i * 512
        ts = base + rng.integers(0, 700, 4096)
        late = rng.random(4096) < 0.05
        ts = np.where(late, np.maximum(ts - 2500, 0), ts).astype(np.int64)
        return ({"user": user}, ts)

    env = StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8, "state.slots-per-shard": 64,
        "pipeline.microbatch-size": 4096,
        "host.parallelism": w}))
    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(800))
        .key_by("user")
        .window(EventTimeSessionWindows.with_gap(150))
        .allowed_lateness(3000)
        .count()
        .add_sink(sink))
    return env


def window_all_env(sink, w, agg_builder):
    """The windowAll golden shape (Q7) with the tree-fold floor lowered
    so the chunked fold engages on test-sized batches."""
    def gen(split, i):
        if i >= 6:
            return None
        rng = np.random.default_rng(23 + i)
        return ({"v": rng.integers(1, 100, 8192).astype(np.int64)},
                np.sort(rng.integers(i * 700, i * 700 + 1400,
                                     8192)).astype(np.int64))

    env = StreamExecutionEnvironment(Configuration({
        "pipeline.microbatch-size": 8192,
        "host.parallelism": w,
        "host.fold-chunk-records": 2048}))
    s = (env.from_source(GeneratorSource(gen),
                         WatermarkStrategy.for_bounded_out_of_orderness(800))
         .window_all(TumblingEventTimeWindows.of(1000)))
    agg_builder(s).add_sink(sink)
    return env


def spill_env(sink, w):
    """The spill golden shape: 1600 keys into 32 resident slots —
    every batch overflows into the host store's pane merges."""
    def gen(split, i):
        if i >= 6:
            return None
        rng = np.random.default_rng(42 + i)
        return ({"k": rng.integers(0, 1600, 512).astype(np.int64),
                 "v": rng.integers(1, 100, 512).astype(np.int64)},
                np.sort(rng.integers(i * 700, i * 700 + 1400,
                                     512)).astype(np.int64))

    env = StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8, "state.slots-per-shard": 4,
        "state.backend": "spill",
        "pipeline.microbatch-size": 512,
        "host.parallelism": w}))
    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(800))
        .key_by("k")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .add_sink(sink))
    return env


class TestSerialParallelParity:
    def test_sessions_parity_1_2_4(self):
        ref = collect_ordered(lambda s: sessions_env(s, 1))
        for w in PARALLELISMS[1:]:
            got = collect_ordered(lambda s: sessions_env(s, w))
            assert_byte_identical(ref, got, f"sessions w={w}")

    def test_window_all_max_parity_1_2_4(self):
        ref = collect_ordered(
            lambda s: window_all_env(s, 1, lambda ws: ws.max("v")))
        for w in PARALLELISMS[1:]:
            got = collect_ordered(
                lambda s: window_all_env(s, w, lambda ws: ws.max("v")))
            assert_byte_identical(ref, got, f"window_all max w={w}")

    def test_window_all_int_sum_parity_1_2_4(self):
        """Integer-valued sums below 2**24 are exact in f32 at every
        association, so even the CHUNKED tree fold (whose reduction
        tree differs from serial) must reproduce the serial bytes."""
        agg = aggregates.multi(aggregates.sum_of("v"), aggregates.count())
        ref = collect_ordered(
            lambda s: window_all_env(s, 1, lambda ws: ws.aggregate(agg)))
        for w in PARALLELISMS[1:]:
            got = collect_ordered(
                lambda s: window_all_env(s, w,
                                         lambda ws: ws.aggregate(agg)))
            assert_byte_identical(ref, got, f"window_all sum w={w}")

    def test_spill_parity_1_2_4(self):
        ref = collect_ordered(lambda s: spill_env(s, 1))
        for w in PARALLELISMS[1:]:
            got = collect_ordered(lambda s: spill_env(s, w))
            assert_byte_identical(ref, got, f"spill w={w}")


class TestSnapshotAcrossParallelism:
    """Checkpoints are shard-count-agnostic: the session registry
    snapshots as ONE (key, start)-sorted block, so a snapshot taken at
    one host.parallelism restores at another."""

    def _feed(self, op):
        rng = np.random.default_rng(7)
        for i in range(4):
            keys = rng.integers(0, 40, 512)
            ts = i * 300 + rng.integers(0, 400, 512)
            op.process_batch(keys, ts, {})
        return op

    def _fire_all(self, op):
        f = op.advance_watermark(10_000_000)
        return {k: np.asarray(v) for k, v in f.to_dict().items()} \
            if hasattr(f, "to_dict") else f._data

    def test_serial_snapshot_restores_into_parallel(self):
        agg = aggregates.count()
        serial = self._feed(SessionOperator(gap_ms=100, agg=agg,
                                            allowed_lateness_ms=500))
        snap = serial.snapshot_state()
        pool = HostPool(4)
        try:
            par = SessionOperator(gap_ms=100, agg=agg,
                                  allowed_lateness_ms=500, host_pool=pool)
            par.restore_state(snap)
            assert len(par._shards) == 4
            ref = self._fire_all(self._feed(SessionOperator(
                gap_ms=100, agg=agg, allowed_lateness_ms=500)))
            got = self._fire_all(par)
            for k in ref:
                assert np.array_equal(ref[k], got[k]), k
        finally:
            pool.close()

    def test_parallel_snapshot_equals_serial_snapshot(self):
        agg = aggregates.count()
        serial = self._feed(SessionOperator(gap_ms=100, agg=agg,
                                            allowed_lateness_ms=500))
        pool = HostPool(4)
        try:
            par = self._feed(SessionOperator(
                gap_ms=100, agg=agg, allowed_lateness_ms=500,
                host_pool=pool))
            s1, s2 = serial.snapshot_state(), par.snapshot_state()
            assert s1["watermark"] == s2["watermark"]
            for c in s1["columns"]:
                assert np.array_equal(s1["columns"][c],
                                      s2["columns"][c]), c
        finally:
            pool.close()


class TestConfigSurface:
    def test_host_options_declared(self):
        from flink_tpu.config import is_declared_key

        assert is_declared_key("host.parallelism")
        assert is_declared_key("host.fold-chunk-records")
        assert Configuration().get(HostOptions.PARALLELISM) == \
            min(4, os.cpu_count() or 1)
