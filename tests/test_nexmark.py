"""Nexmark query correctness tests (golden-checked against plain-python
evaluation of the query semantics)."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.config import Configuration
from flink_tpu.nexmark.generator import (
    NexmarkConfig,
    auction_stream,
    bid_stream,
    person_stream,
)
from flink_tpu.nexmark.queries import q5_hot_items, q7_highest_bid, q8_monitor_new_users


def small_env():
    return StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8,
        "state.slots-per-shard": 512,
        "pipeline.microbatch-size": 1024,
    }))


CFG = NexmarkConfig(batch_size=512, n_batches=8, events_per_ms=1,
                    num_active_auctions=50, num_active_people=30)


def materialize(source):
    rows = []
    for split in source.splits():
        for data, ts in source.open_split(split):
            rows.append((data, ts))
    return rows


class TestQ5:
    def test_hot_items_golden(self):
        env = small_env()
        sink = CollectSink()
        q5_hot_items(env, bid_stream(CFG), sink,
                     window_ms=2000, slide_ms=1000)
        env.execute("q5")

        # golden: count per (auction, window), then argmax set per window
        counts = {}
        for data, ts in materialize(bid_stream(CFG)):
            for a, t in zip(data["auction"], ts):
                start = (int(t) // 1000) * 1000
                for ws in (start, start - 1000):
                    if ws <= t < ws + 2000:
                        counts[(int(a), ws + 2000)] = counts.get(
                            (int(a), ws + 2000), 0) + 1
        best = {}
        for (a, wend), c in counts.items():
            best[wend] = max(best.get(wend, 0), c)
        expect = {(a, wend, c) for (a, wend), c in counts.items()
                  if c == best[wend]}
        got = {(int(r["auction"]), int(r["window_end"]), int(r["bid_count"]))
               for r in sink.rows}
        assert got == expect


class TestQ7:
    def test_highest_bid_golden(self):
        env = small_env()
        sink = CollectSink()
        q7_highest_bid(env, bid_stream(CFG), sink, window_ms=1000)
        env.execute("q7")

        expect = {}
        for data, ts in materialize(bid_stream(CFG)):
            for p, t in zip(data["price"], ts):
                ws = (int(t) // 1000) * 1000
                expect[ws] = max(expect.get(ws, 0.0), float(p))
        got = {int(r["window_start"]): float(r["max_price"]) for r in sink.rows}
        assert got.keys() == expect.keys()
        for ws in expect:
            assert got[ws] == pytest.approx(expect[ws], rel=1e-6)


class TestQ8:
    def test_monitor_new_users_golden(self):
        env = small_env()
        sink = CollectSink()
        q8_monitor_new_users(env, person_stream(CFG), auction_stream(CFG),
                             sink, window_ms=1000)
        env.execute("q8")

        pw, aw = set(), set()
        for data, ts in materialize(person_stream(CFG)):
            for p, t in zip(data["person"], ts):
                pw.add((int(p), (int(t) // 1000) * 1000))
        for data, ts in materialize(auction_stream(CFG)):
            for s, t in zip(data["seller"], ts):
                aw.add((int(s), (int(t) // 1000) * 1000))
        expect = pw & aw
        got = {(int(r["key"]), int(r["window_start"])) for r in sink.rows}
        assert got == expect
