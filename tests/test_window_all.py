"""windowAll / global windowed aggregation (ref: AllWindowedStream at
parallelism 1 — here a host pane reduce with no funnel; the Q7 shape)."""
import numpy as np
import pytest
import jax

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import SlidingEventTimeWindows, TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.ops import aggregates
from flink_tpu.ops.window_all import WindowAllOperator
from flink_tpu.time.watermarks import WatermarkStrategy


def make_env(extra=None):
    conf = {"pipeline.microbatch-size": 256}
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def source(n_batches=6, b=200):
    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(3 + i)
        return ({"v": rng.integers(1, 1000, b).astype(np.int64)},
                np.sort(rng.integers(i * 700, i * 700 + 1400, b)).astype(np.int64))
    return gen


class TestWindowAllE2E:
    def test_global_max_golden(self):
        env = make_env()
        sink = CollectSink()
        (env.from_source(GeneratorSource(source()),
                         WatermarkStrategy.for_bounded_out_of_orderness(800))
         .window_all(TumblingEventTimeWindows.of(1_000))
         .max("v")
         .add_sink(sink))
        env.execute("wa-max")
        want = {}
        for i in range(6):
            rng = np.random.default_rng(3 + i)
            v = rng.integers(1, 1000, 200)
            ts = np.sort(rng.integers(i * 700, i * 700 + 1400, 200))
            for vv, t in zip(v, ts):
                w = (int(t) // 1000) * 1000 + 1000
                want[w] = max(want.get(w, 0), int(vv))
        got = {int(r["window_end"]): float(r["max_v"]) for r in sink.rows}
        assert got == {w: float(m) for w, m in want.items()}

    def test_mesh_mode_no_hotspot_same_results(self):
        """windowAll on a mesh env must produce identical results — there
        is no keyed exchange, so no device can be a hotspot (the round-2
        Q7 funnel weakness)."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8-device mesh")
        res = {}
        for mesh in (None, "all"):
            env = make_env({"cluster.mesh-devices": mesh} if mesh else None)
            sink = CollectSink()
            (env.from_source(GeneratorSource(source()),
                             WatermarkStrategy.for_bounded_out_of_orderness(800))
             .window_all(SlidingEventTimeWindows.of(2_000, 1_000))
             .sum("v")
             .add_sink(sink))
            env.execute(f"wa-{mesh}")
            res[mesh] = sorted(
                (int(r["window_end"]), float(r["sum_v"])) for r in sink.rows)
        assert res[None] == res["all"]

    def test_late_within_lateness_refires(self):
        op = WindowAllOperator(
            TumblingEventTimeWindows.of(1_000), aggregates.max_of("v"),
            allowed_lateness_ms=5_000)
        op.process_batch(np.array([500], np.int64),
                         {"v": np.array([10.0], np.float32)})
        f1 = dict(op.advance_watermark(1_500))
        assert [float(v) for v in f1["max_v"]] == [10.0]
        # late-but-allowed record raises the max -> window refires
        op.process_batch(np.array([600], np.int64),
                         {"v": np.array([99.0], np.float32)})
        f2 = dict(op.advance_watermark(1_500))
        assert [float(v) for v in f2["max_v"]] == [99.0]
        # beyond-lateness record is dropped and counted
        op.advance_watermark(20_000)
        op.process_batch(np.array([100], np.int64),
                         {"v": np.array([1000.0], np.float32)})
        assert op.late_records == 1

    def test_snapshot_restore_roundtrip(self):
        def mk():
            return WindowAllOperator(
                TumblingEventTimeWindows.of(1_000), aggregates.avg_of("v"))

        straight = mk()
        straight.process_batch(np.array([100], np.int64),
                               {"v": np.array([4.0], np.float32)})
        straight.process_batch(np.array([700], np.int64),
                               {"v": np.array([8.0], np.float32)})
        want = dict(straight.advance_watermark(2_000))

        a = mk()
        a.process_batch(np.array([100], np.int64),
                        {"v": np.array([4.0], np.float32)})
        b = mk()
        b.restore_state(a.snapshot_state())
        b.process_batch(np.array([700], np.int64),
                        {"v": np.array([8.0], np.float32)})
        got = dict(b.advance_watermark(2_000))
        assert [float(v) for v in got["avg_v"]] == \
            [float(v) for v in want["avg_v"]]
