"""Cross-host data plane (tier-5): one job spanning MULTIPLE runner
processes through the per-step DCN all-to-all (exchange/dcn.py), with
checkpoint/restore. ref: SURVEY §3.6 data network stack (the
TaskManager-to-TaskManager plane) + §5.4 MiniCluster ITCases."""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from flink_tpu.checkpoint import blobformat
from flink_tpu.exchange import frames
from flink_tpu.exchange.dcn import DcnExchange

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _hello(sender, attempt, codec=1, auth=0, secret=None):
    """A v2 wire hello (magic + sender + attempt + codec + auth flag,
    optionally MAC'd) — what a well-formed dialer sends."""
    import hmac
    import struct

    h = (b"D2" + bytes([sender]) + struct.pack(">I", attempt)
         + bytes([codec, auth]))
    if secret is not None:
        h += hmac.new(secret, h, "sha256").digest()
    return h


class TestExchange:
    def test_three_process_rendezvous(self):
        """In-process smoke of the N-way exchange: 3 endpoints in
        threads, each routes a share to each peer and all metas
        propagate."""
        import threading

        n = 3
        exs = [DcnExchange(i, n) for i in range(n)]
        peers = [f"127.0.0.1:{e.port}" for e in exs]
        results = [None] * n

        def run(i):
            exs[i].connect(peers)
            shares = {j: {"data": {"v": np.array([i * 10 + j])},
                          "ts": np.array([j])} for j in range(n)}
            payloads, metas = exs[i].exchange(shares, {"wm": 100 + i})
            results[i] = (payloads, metas)

        ths = [threading.Thread(target=run, args=(i,)) for i in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=30)
        for i in range(n):
            payloads, metas = results[i]
            # process i received j*10+i from every j
            got = sorted(int(p["data"]["v"][0]) for p in payloads)
            assert got == sorted(j * 10 + i for j in range(n))
            assert sorted(m["wm"] for m in metas) == [100, 101, 102]
        for e in exs:
            e.close()


WORKER = r"""
import os, sys, json
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import SlidingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.connectors import FileSink
from flink_tpu.formats import CsvFormat
from flink_tpu.time.watermarks import WatermarkStrategy

pid = int(sys.argv[1]); n = int(sys.argv[2])
peers = sys.argv[3]; my_port = int(sys.argv[4])
out_path = sys.argv[5]
crash_at = int(sys.argv[6]) if len(sys.argv) > 6 else -1
restore = len(sys.argv) > 7 and sys.argv[7] == "restore"

N_BATCHES = 24
B = 512

def gen(split, i):
    if i >= N_BATCHES:
        return None
    rng = np.random.default_rng(1000 * int(split) + i)
    base = i * 1000
    keys = rng.integers(0, 64, B).astype(np.int64)
    ts = base + rng.integers(0, 1000, B).astype(np.int64)
    return ({{"auction": keys}}, ts)

# durable exactly-once sink: committed part files survive the crash
# (the in-memory sink pattern only works for in-process attempts)
sink = FileSink(out_path + f"/sink-p{{pid}}",
                CsvFormat([("key", "i64"), ("window_end", "i64"),
                           ("count", "i64")]))

conf = {{
    "state.num-key-shards": 8, "state.slots-per-shard": 32,
    "pipeline.microbatch-size": B,
    "cluster.num-processes": n, "cluster.process-id": pid,
    "cluster.dcn-peers": peers, "cluster.dcn-port": my_port,
    "execution.checkpointing.interval": 1,
    "execution.checkpointing.dir": out_path + "/ckpt",
}}
mesh = os.environ.get("FLINK_TPU_MESH_DEVICES", "")
if mesh:
    conf["cluster.mesh-devices"] = mesh
if restore:
    conf["execution.checkpointing.restore"] = "latest"
if crash_at >= 0:
    # crash injection: die after N source batches via a poisoned source
    real_gen = gen
    def gen(split, i, _g=real_gen):
        if i == crash_at:
            os._exit(43)
        return _g(split, i)

env = StreamExecutionEnvironment(Configuration(conf))
src = GeneratorSource(gen, n_splits=2)
(env.from_source(src,
                 WatermarkStrategy.for_bounded_out_of_orderness(1000))
 .key_by("auction")
 .window(SlidingEventTimeWindows.of(4000, 2000))
 .count()
 .add_sink(sink))
env.execute("dcnq5")
print("WORKER_DONE", flush=True)
"""


def _spawn(tmp, pid, n, peers, port, crash_at=-1, restore=False,
           mesh_devices=0):
    script = tmp / f"worker-{pid}.py"
    script.write_text(WORKER.format(repo=REPO))
    args = [sys.executable, str(script), str(pid), str(n), peers,
            str(port), str(tmp), str(crash_at)]
    if restore:
        args.append("restore")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if mesh_devices:
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{mesh_devices}").strip()
        env["FLINK_TPU_MESH_DEVICES"] = str(mesh_devices)
    return subprocess.Popen(args, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, env=env)


def _golden(tmp):
    """Single-process run of the same job → expected rows."""
    import jax

    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sinks import FnSink
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import SlidingEventTimeWindows
    from flink_tpu.config import Configuration
    from flink_tpu.time.watermarks import WatermarkStrategy

    N_BATCHES, B = 24, 512

    def gen(split, i):
        if i >= N_BATCHES:
            return None
        rng = np.random.default_rng(1000 * int(split) + i)
        base = i * 1000
        keys = rng.integers(0, 64, B).astype(np.int64)
        ts = base + rng.integers(0, 1000, B).astype(np.int64)
        return ({"auction": keys}, ts)

    rows = []

    def sink(b):
        if b:
            for k, w, c in zip(np.asarray(b["key"]),
                               np.asarray(b["window_end"]),
                               np.asarray(b["count"])):
                rows.append((int(k), int(w), int(c)))

    env = StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8, "state.slots-per-shard": 32,
        "pipeline.microbatch-size": 512}))
    (env.from_source(GeneratorSource(gen, n_splits=2),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
     .key_by("auction")
     .window(SlidingEventTimeWindows.of(4000, 2000))
     .count()
     .add_sink(FnSink(sink)))
    env.execute("golden")
    return sorted(rows)


def _free_ports(n):
    import socket

    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _collect(tmp, n):
    rows = []
    for pid in range(n):
        cd = tmp / f"sink-p{pid}" / "committed"
        assert cd.exists(), f"process {pid} committed nothing"
        for part in sorted(os.listdir(cd)):
            for line in (cd / part).read_text().splitlines():
                k, w, c = line.split(",")
                rows.append((int(k), int(w), int(c)))
    return sorted(rows)


class TestAttemptFencing:
    def test_stale_attempt_peer_rejected(self):
        """A process from a PREVIOUS attempt dialing a new attempt's
        listener must be fenced out at the handshake (the static
        cluster.dcn-peers mode has no coordinator rendezvous key to
        protect it — the attempt epoch in the hello is the fence)."""
        import socket as _socket
        import struct as _struct
        import threading

        n = 2
        fresh = [DcnExchange(i, n, attempt=2) for i in range(n)]
        peers = [f"127.0.0.1:{e.port}" for e in fresh]

        # stale dialer (attempt 1) connects first and must NOT occupy
        # peer slot 1
        stale = _socket.create_connection(("127.0.0.1", fresh[0].port))
        stale.sendall(_hello(1, 1))
        time.sleep(0.1)

        done = []

        def run(i):
            fresh[i].connect(peers, timeout_s=10)
            payloads, metas = fresh[i].exchange(
                {}, {"from": i, "attempt": 2})
            done.append((i, [m.get("from") for m in metas]))

        ths = [threading.Thread(target=run, args=(i,))
               for i in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=20)
        assert len(done) == 2
        for i, froms in sorted(done):
            assert froms == [0, 1]  # the REAL peers, not the stale one
        # the stale connection was closed by the fence
        stale.settimeout(2)
        assert stale.recv(1) == b""
        for e in fresh:
            e.close()
        stale.close()

    def test_same_attempt_connects(self):
        import threading

        n = 2
        exs = [DcnExchange(i, n, attempt=7) for i in range(n)]
        peers = [f"127.0.0.1:{e.port}" for e in exs]
        out = []

        def run(i):
            exs[i].connect(peers, timeout_s=10)
            p, m = exs[i].exchange({}, {"pid": i})
            out.append([mm.get("pid") for mm in m])

        ths = [threading.Thread(target=run, args=(i,)) for i in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=20)
        assert out == [[0, 1], [0, 1]]
        for e in exs:
            e.close()


@pytest.mark.shard_map
class TestTier5TwoProcessQ5:
    def test_two_process_q5_matches_single_process(self, tmp_path):
        """Q5-shaped job over 2 processes: the union of both processes'
        emitted rows must equal the single-process run exactly (each
        key fires on exactly one process — its shard owner)."""
        golden = _golden(tmp_path / "g")
        ports = _free_ports(2)
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        ps = [_spawn(tmp_path, i, 2, peers, ports[i]) for i in range(2)]
        outs = [p.communicate(timeout=300)[0].decode() for p in ps]
        for i, p in enumerate(ps):
            assert p.returncode == 0, f"p{i} failed:\n{outs[i][-3000:]}"
        assert _collect(tmp_path, 2) == golden

    def test_two_process_crash_restore_exactly_once(self, tmp_path):
        """One process crashes mid-run; BOTH restart with
        restore=latest (negotiated common checkpoint id) and the final
        output union still equals the golden run exactly — the
        step-rendezvous checkpoint cut is globally consistent."""
        golden = _golden(tmp_path / "g")
        ports = _free_ports(2)
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        # attempt 1: p1 crashes after 10 source batches; p0 dies on the
        # broken exchange
        ps = [_spawn(tmp_path, 0, 2, peers, ports[0]),
              _spawn(tmp_path, 1, 2, peers, ports[1], crash_at=10)]
        for p in ps:
            p.communicate(timeout=300)
        assert ps[1].returncode == 43
        assert ps[0].returncode != 0
        # attempt 2: fresh ports, negotiated restore
        ports2 = _free_ports(2)
        peers2 = ",".join(f"127.0.0.1:{p}" for p in ports2)
        ps = [_spawn(tmp_path, i, 2, peers2, ports2[i], restore=True)
              for i in range(2)]
        outs = [p.communicate(timeout=300)[0].decode() for p in ps]
        for i, p in enumerate(ps):
            assert p.returncode == 0, f"p{i} failed:\n{outs[i][-3000:]}"
        assert _collect(tmp_path, 2) == golden


    def test_two_process_local_mesh_q5(self, tmp_path):
        """The full tier-5 shape: 2 runner processes x 4 virtual
        devices each — records cross PROCESSES via the DCN exchange and
        cross each process's local DEVICES via the in-step keyBy
        all_to_all; output still equals the single-process run."""
        golden = _golden(tmp_path / "g")
        ports = _free_ports(2)
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        ps = [_spawn(tmp_path, i, 2, peers, ports[i], mesh_devices=4)
              for i in range(2)]
        outs = [p.communicate(timeout=600)[0].decode() for p in ps]
        for i, p in enumerate(ps):
            assert p.returncode == 0, f"p{i} failed:\n{outs[i][-3000:]}"
        assert _collect(tmp_path, 2) == golden


class TestDcnSubBatchAndOverlap:
    """Cross-host contract of pipeline.sub-batches (the rendezvous is
    per-LOGICAL-batch; K slices the local push only, so committed rows
    are identical across K) and of cluster.dcn-overlap on/off (the
    barrier moves, the consensus does not)."""

    N_BATCHES = 8
    B = 64

    def _gen(self):
        n_batches, b = self.N_BATCHES, self.B

        def gen(split, i):
            if i >= n_batches:
                return None
            rng = np.random.default_rng(500 * int(split) + i)
            keys = rng.integers(0, 32, b).astype(np.int64)
            ts = i * 1000 + rng.integers(0, 1000, b).astype(np.int64)
            return {"k": keys}, ts
        return gen

    def _golden(self):
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sinks import FnSink
        from flink_tpu.api.sources import GeneratorSource
        from flink_tpu.api.windowing import TumblingEventTimeWindows
        from flink_tpu.config import Configuration
        from flink_tpu.time.watermarks import WatermarkStrategy

        rows = []
        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 8, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": self.B}))
        (env.from_source(GeneratorSource(self._gen(), n_splits=2),
                         WatermarkStrategy.for_bounded_out_of_orderness(
                             1000))
         .key_by("k")
         .window(TumblingEventTimeWindows.of(1000))
         .count()
         .add_sink(FnSink(lambda b: rows.extend(
             zip(np.asarray(b["key"]).tolist(),
                 np.asarray(b["window_end"]).tolist(),
                 np.asarray(b["count"]).tolist())) if b else None)))
        env.execute("golden")
        return sorted(rows)

    def _two_proc(self, extra_conf):
        import threading

        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sinks import FnSink
        from flink_tpu.api.sources import GeneratorSource
        from flink_tpu.api.windowing import TumblingEventTimeWindows
        from flink_tpu.config import Configuration
        from flink_tpu.time.watermarks import WatermarkStrategy

        ports = _free_ports(2)
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        per_pid = [[], []]
        errs = [None, None]

        def run(pid):
            rows = per_pid[pid]
            conf = {
                "state.num-key-shards": 8, "state.slots-per-shard": 64,
                "pipeline.microbatch-size": self.B,
                "cluster.num-processes": 2, "cluster.process-id": pid,
                "cluster.dcn-peers": peers,
                "cluster.dcn-port": ports[pid],
            }
            conf.update(extra_conf)
            env = StreamExecutionEnvironment(Configuration(conf))
            (env.from_source(GeneratorSource(self._gen(), n_splits=2),
                             WatermarkStrategy
                             .for_bounded_out_of_orderness(1000))
             .key_by("k")
             .window(TumblingEventTimeWindows.of(1000))
             .count()
             .add_sink(FnSink(lambda b: rows.extend(
                 zip(np.asarray(b["key"]).tolist(),
                     np.asarray(b["window_end"]).tolist(),
                     np.asarray(b["count"]).tolist())) if b else None)))
            try:
                env.execute(f"subbatch-p{pid}")
            except BaseException as e:  # surfaced by the caller
                errs[pid] = e

        ths = [threading.Thread(target=run, args=(i,)) for i in range(2)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in ths), "2-proc run hung"
        for pid, e in enumerate(errs):
            assert e is None, f"p{pid} failed: {e!r}"
        return [sorted(r) for r in per_pid]

    def test_sub_batches_no_longer_rejected_and_byte_identical(self):
        """K=4 cross-host runs (was a hard NotImplementedError at the
        driver) and every process emits EXACTLY the rows its K=1 twin
        does — the global watermark still advances once per rendezvous,
        so fire content, ownership, and late classification are
        untouched by the sub-batch slicing."""
        golden = self._golden()
        k1 = self._two_proc({"pipeline.sub-batches": 1})
        k4 = self._two_proc({"pipeline.sub-batches": 4})
        assert sorted(k1[0] + k1[1]) == golden
        assert k4 == k1  # per-process byte-identity, not just the union

    def test_overlap_without_drain_completes_and_matches(self, tmp_path):
        """The analyzer-warned loss mode (overlap on, barrier drain
        off) under checkpointing, with NO faults: nothing is in flight
        at end-of-input, so output still matches — and the undrained
        step's STALE ckpt flag is absorbed exactly once (it rode
        behind the snapshot), so the fleet stays in lockstep instead
        of double-checkpointing every interval."""
        rows = self._two_proc({
            "cluster.dcn-overlap-drain": False,
            "execution.checkpointing.interval": 25,
            "execution.checkpointing.dir": str(tmp_path / "ckpt")})
        assert sorted(rows[0] + rows[1]) == self._golden()

    def test_overlap_off_matches_overlap_on(self):
        """cluster.dcn-overlap moves the barrier, not the semantics:
        lockstep (off) and overlapped (on, the default) runs emit
        identical rows per process."""
        on = self._two_proc({})
        off = self._two_proc({"cluster.dcn-overlap": False})
        assert on == off
        assert sorted(on[0] + on[1]) == self._golden()


class TestExchangeSecurity:
    """ADVICE r5 medium: the exchange port was an unauthenticated RCE
    surface on cross-host (0.0.0.0) deployments — frames decode through
    blobformat, whose __pickle__ escape deserializes attacker pickle.
    Closed two independent ways: an HMAC-over-hello shared secret
    admission check, and a frame decoder that rejects the pickle escape
    outright."""

    def test_unauthenticated_hello_rejected(self):
        """A dialer that knows the wire format but not the secret must
        be dropped at the handshake, while the real (keyed) peers still
        form the mesh."""
        import socket as _socket
        import struct as _struct
        import threading

        n = 2
        exs = [DcnExchange(i, n, attempt=1, secret="job-secret")
               for i in range(n)]
        peers = [f"127.0.0.1:{e.port}" for e in exs]

        # attacker: well-formed keyed hello, garbage MAC
        bad = _socket.create_connection(("127.0.0.1", exs[0].port))
        bad.sendall(_hello(1, 1, auth=1) + b"\x00" * 32)
        time.sleep(0.1)

        out = []

        def run(i):
            exs[i].connect(peers, timeout_s=10)
            p, m = exs[i].exchange({}, {"pid": i})
            out.append([mm.get("pid") for mm in m])

        ths = [threading.Thread(target=run, args=(i,)) for i in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=20)
        assert out == [[0, 1], [0, 1]]  # real peers, not the attacker
        bad.settimeout(2)
        assert bad.recv(1) == b"", "unauthenticated hello not dropped"
        bad.close()
        for e in exs:
            e.close()

    def test_secretless_hello_against_keyed_listener_rejected(self):
        """A peer declaring no auth (flag 0) to a keyed listener must
        not be admitted — closed at the handshake, before any frame
        bytes are interpreted."""
        import socket as _socket
        import struct as _struct

        ex = DcnExchange(0, 2, attempt=1, secret="job-secret")
        legacy = _socket.create_connection(("127.0.0.1", ex.port))
        legacy.sendall(_hello(1, 1))
        raw = blobformat.encode({"data": None, "meta": {}})
        legacy.sendall(_struct.pack(">Q", len(raw)) + raw)
        legacy.settimeout(2)
        try:
            got = legacy.recv(1)
        except ConnectionResetError:
            got = b""  # hard reset is rejection too
        assert got == b"", "secretless hello not dropped"
        assert 1 not in ex._in
        legacy.close()
        ex.close()

    def test_keyed_hello_against_unkeyed_listener_rejected(self):
        """The asymmetric rollout in the other direction: a keyed
        dialer hitting an UNKEYED listener is closed cleanly at the
        handshake — its 32 MAC bytes are drained, never parsed as a
        frame length (which would hang or try a huge allocation)."""
        import hmac as _hmac2
        import socket as _socket
        import struct as _struct

        ex = DcnExchange(0, 2, attempt=1)  # no secret
        keyed = _socket.create_connection(("127.0.0.1", ex.port))
        keyed.sendall(_hello(1, 1, auth=1, secret=b"other-secret"))
        keyed.settimeout(2)
        try:
            got = keyed.recv(1)
        except ConnectionResetError:
            got = b""
        assert got == b"", "keyed hello not rejected by unkeyed listener"
        assert 1 not in ex._in
        keyed.close()
        ex.close()

    def test_pickle_escape_frame_rejected(self):
        """A legacy frame smuggling a __pickle__ escape must fail the
        decode loudly instead of deserializing attacker-controlled
        pickle (the legacy codec survives as the benchmark baseline —
        it keeps the rejection)."""
        import socket as _socket
        import struct as _struct

        # an object-dtype array routes through the __pickle__ escape —
        # the exact in-band vector an attacker's crafted frame uses
        evil = np.array([{"x": 1}], dtype=object)
        raw = blobformat.encode({"data": evil, "meta": {}})
        assert b"__pickle__" in raw  # the attack vector exists in-band

        ex = DcnExchange(0, 2, attempt=1, codec="legacy")
        s = _socket.create_connection(("127.0.0.1", ex.port))
        s.sendall(_hello(1, 1, codec=0))  # valid unkeyed legacy hello
        deadline = time.time() + 5
        while 1 not in ex._in and time.time() < deadline:
            time.sleep(0.02)
        assert 1 in ex._in
        s.sendall(_struct.pack(">Q", len(raw)) + raw)
        with pytest.raises(ValueError, match="__pickle__ escape rejected"):
            ex.exchange({}, {})
        s.close()
        ex.close()

    def test_binary_frame_has_no_pickle_vector(self):
        """The binary wire rejects foreign objects AT ENCODE — there is
        no pickle escape for a hostile frame to smuggle through, and a
        corrupt frame fails the CRC, not the keyspace."""
        evil = np.array([{"x": 1}], dtype=object)
        with pytest.raises(frames.FrameError, match="no pickle escape"):
            frames.encode_bytes(0, 0, {}, {"data": evil})

    def test_corrupt_binary_frame_fails_loudly_at_the_barrier(self):
        """Garbage after a valid binary hello must surface as a loud
        FrameError at the exchange barrier — never a silent partial
        decode into operator state."""
        import socket as _socket

        ex = DcnExchange(0, 2, attempt=1)
        s = _socket.create_connection(("127.0.0.1", ex.port))
        s.sendall(_hello(1, 1))
        deadline = time.time() + 5
        while 1 not in ex._in and time.time() < deadline:
            time.sleep(0.02)
        assert 1 in ex._in
        ex._start_io()  # the mesh is "up" for this half-duplex probe
        s.sendall(b"\x00" * frames.HEADER_LEN)
        with pytest.raises(frames.FrameError, match="magic"):
            ex.exchange_async({}, {"wm": 0}).result()
        s.close()
        ex.close()

    def test_legacy_v0_hello_rejected_at_handshake(self):
        """A pre-binary-wire peer (the v0 6-byte hello: no magic) must
        be fenced out AT THE HELLO with a recorded reason — a
        mixed-version fleet fails at admission, never by misparsing a
        foreign frame mid-stream."""
        import socket as _socket
        import struct as _struct

        ex = DcnExchange(0, 2, attempt=1)
        old = _socket.create_connection(("127.0.0.1", ex.port))
        # the exact v0 hello wire shape + enough follow-on bytes that
        # the 9-byte v2 read never blocks on a short hello
        old.sendall(bytes([1]) + _struct.pack(">I", 1) + b"\x00"
                    + b"\x00" * 8)
        old.settimeout(5)
        try:
            got = old.recv(1)
        except (ConnectionResetError, _socket.timeout):
            got = b""
        assert got == b"", "v0 hello not dropped"
        assert 1 not in ex._in
        assert any("wire version" in r for r in ex.hello_rejects), (
            ex.hello_rejects)
        old.close()
        ex.close()

    def test_codec_mismatch_rejected_at_handshake(self):
        """A peer pinned to the LEGACY codec dialing a binary listener
        (or vice versa) is rejected at the hello — a frame-format split
        brain would otherwise corrupt mid-stream."""
        import socket as _socket

        ex = DcnExchange(0, 2, attempt=1)  # binary listener
        peer = _socket.create_connection(("127.0.0.1", ex.port))
        peer.sendall(_hello(1, 1, codec=0))  # legacy dialer
        peer.settimeout(5)
        try:
            got = peer.recv(1)
        except (ConnectionResetError, _socket.timeout):
            got = b""
        assert got == b"", "codec-mismatched hello not dropped"
        assert 1 not in ex._in
        assert any("codec mismatch" in r for r in ex.hello_rejects), (
            ex.hello_rejects)
        peer.close()
        ex.close()

    def test_mixed_codec_fleet_fails_loudly_at_connect(self):
        """Fleet-level interop: one binary and one legacy process can
        never form a mesh — connect() times out with the listener's
        reject recorded, instead of the fleet limping into mid-frame
        garbage."""
        import threading

        a = DcnExchange(0, 2, attempt=1, codec="binary")
        b = DcnExchange(1, 2, attempt=1, codec="legacy")
        peers = [f"127.0.0.1:{a.port}", f"127.0.0.1:{b.port}"]
        errs = {}

        def run(ex, i):
            try:
                ex.connect(peers, timeout_s=3)
            except TimeoutError as e:
                errs[i] = e

        ths = [threading.Thread(target=run, args=(ex, i))
               for i, ex in enumerate((a, b))]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=20)
        assert set(errs) == {0, 1}, "mixed fleet formed a mesh"
        assert any("codec mismatch" in r for r in a.hello_rejects)
        assert any("codec mismatch" in r for r in b.hello_rejects)
        a.close()
        b.close()

    def test_numeric_frames_unaffected_by_pickle_rejection(self):
        """The production payload shape (numeric arrays + scalar meta)
        round-trips identically under allow_pickle=False."""
        payload = {"data": {"k": np.arange(5, dtype=np.int64),
                            "v": np.linspace(0, 1, 5)},
                   "meta": {"wm": 123, "done": False, "persisted": -1}}
        raw = blobformat.encode(payload)
        got = blobformat.decode(raw, allow_pickle=False)
        assert got["meta"] == payload["meta"]
        assert (got["data"]["k"] == payload["data"]["k"]).all()
        assert (got["data"]["v"] == payload["data"]["v"]).all()

    def test_string_columns_cross_without_pickle(self):
        """Text columns (object-dtype string arrays, the socket/file
        source shape) encode via the native __strs__ tag — no pickle
        escape — so they survive the exchange's allow_pickle=False."""
        payload = {"data": {"line": np.array(["a", "bb", "ccc"],
                                             dtype=object),
                            "k": np.arange(3, dtype=np.int64)},
                   "meta": {"wm": 7}}
        raw = blobformat.encode(payload)
        assert b"__pickle__" not in raw
        got = blobformat.decode(raw, allow_pickle=False)
        assert list(got["data"]["line"]) == ["a", "bb", "ccc"]
        assert got["data"]["line"].dtype == object
        assert (got["data"]["k"] == payload["data"]["k"]).all()
