"""Cross-host data plane (tier-5): one job spanning MULTIPLE runner
processes through the per-step DCN all-to-all (exchange/dcn.py), with
checkpoint/restore. ref: SURVEY §3.6 data network stack (the
TaskManager-to-TaskManager plane) + §5.4 MiniCluster ITCases."""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from flink_tpu.checkpoint import blobformat
from flink_tpu.exchange.dcn import DcnExchange

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestExchange:
    def test_three_process_rendezvous(self):
        """In-process smoke of the N-way exchange: 3 endpoints in
        threads, each routes a share to each peer and all metas
        propagate."""
        import threading

        n = 3
        exs = [DcnExchange(i, n) for i in range(n)]
        peers = [f"127.0.0.1:{e.port}" for e in exs]
        results = [None] * n

        def run(i):
            exs[i].connect(peers)
            shares = {j: {"data": {"v": np.array([i * 10 + j])},
                          "ts": np.array([j])} for j in range(n)}
            payloads, metas = exs[i].exchange(shares, {"wm": 100 + i})
            results[i] = (payloads, metas)

        ths = [threading.Thread(target=run, args=(i,)) for i in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=30)
        for i in range(n):
            payloads, metas = results[i]
            # process i received j*10+i from every j
            got = sorted(int(p["data"]["v"][0]) for p in payloads)
            assert got == sorted(j * 10 + i for j in range(n))
            assert sorted(m["wm"] for m in metas) == [100, 101, 102]
        for e in exs:
            e.close()


WORKER = r"""
import os, sys, json
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import SlidingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.connectors import FileSink
from flink_tpu.formats import CsvFormat
from flink_tpu.time.watermarks import WatermarkStrategy

pid = int(sys.argv[1]); n = int(sys.argv[2])
peers = sys.argv[3]; my_port = int(sys.argv[4])
out_path = sys.argv[5]
crash_at = int(sys.argv[6]) if len(sys.argv) > 6 else -1
restore = len(sys.argv) > 7 and sys.argv[7] == "restore"

N_BATCHES = 24
B = 512

def gen(split, i):
    if i >= N_BATCHES:
        return None
    rng = np.random.default_rng(1000 * int(split) + i)
    base = i * 1000
    keys = rng.integers(0, 64, B).astype(np.int64)
    ts = base + rng.integers(0, 1000, B).astype(np.int64)
    return ({{"auction": keys}}, ts)

# durable exactly-once sink: committed part files survive the crash
# (the in-memory sink pattern only works for in-process attempts)
sink = FileSink(out_path + f"/sink-p{{pid}}",
                CsvFormat([("key", "i64"), ("window_end", "i64"),
                           ("count", "i64")]))

conf = {{
    "state.num-key-shards": 8, "state.slots-per-shard": 32,
    "pipeline.microbatch-size": B,
    "cluster.num-processes": n, "cluster.process-id": pid,
    "cluster.dcn-peers": peers, "cluster.dcn-port": my_port,
    "execution.checkpointing.interval": 1,
    "execution.checkpointing.dir": out_path + "/ckpt",
}}
mesh = os.environ.get("FLINK_TPU_MESH_DEVICES", "")
if mesh:
    conf["cluster.mesh-devices"] = mesh
if restore:
    conf["execution.checkpointing.restore"] = "latest"
if crash_at >= 0:
    # crash injection: die after N source batches via a poisoned source
    real_gen = gen
    def gen(split, i, _g=real_gen):
        if i == crash_at:
            os._exit(43)
        return _g(split, i)

env = StreamExecutionEnvironment(Configuration(conf))
src = GeneratorSource(gen, n_splits=2)
(env.from_source(src,
                 WatermarkStrategy.for_bounded_out_of_orderness(1000))
 .key_by("auction")
 .window(SlidingEventTimeWindows.of(4000, 2000))
 .count()
 .add_sink(sink))
env.execute("dcnq5")
print("WORKER_DONE", flush=True)
"""


def _spawn(tmp, pid, n, peers, port, crash_at=-1, restore=False,
           mesh_devices=0):
    script = tmp / f"worker-{pid}.py"
    script.write_text(WORKER.format(repo=REPO))
    args = [sys.executable, str(script), str(pid), str(n), peers,
            str(port), str(tmp), str(crash_at)]
    if restore:
        args.append("restore")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if mesh_devices:
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{mesh_devices}").strip()
        env["FLINK_TPU_MESH_DEVICES"] = str(mesh_devices)
    return subprocess.Popen(args, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, env=env)


def _golden(tmp):
    """Single-process run of the same job → expected rows."""
    import jax

    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sinks import FnSink
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import SlidingEventTimeWindows
    from flink_tpu.config import Configuration
    from flink_tpu.time.watermarks import WatermarkStrategy

    N_BATCHES, B = 24, 512

    def gen(split, i):
        if i >= N_BATCHES:
            return None
        rng = np.random.default_rng(1000 * int(split) + i)
        base = i * 1000
        keys = rng.integers(0, 64, B).astype(np.int64)
        ts = base + rng.integers(0, 1000, B).astype(np.int64)
        return ({"auction": keys}, ts)

    rows = []

    def sink(b):
        if b:
            for k, w, c in zip(np.asarray(b["key"]),
                               np.asarray(b["window_end"]),
                               np.asarray(b["count"])):
                rows.append((int(k), int(w), int(c)))

    env = StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8, "state.slots-per-shard": 32,
        "pipeline.microbatch-size": 512}))
    (env.from_source(GeneratorSource(gen, n_splits=2),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
     .key_by("auction")
     .window(SlidingEventTimeWindows.of(4000, 2000))
     .count()
     .add_sink(FnSink(sink)))
    env.execute("golden")
    return sorted(rows)


def _free_ports(n):
    import socket

    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _collect(tmp, n):
    rows = []
    for pid in range(n):
        cd = tmp / f"sink-p{pid}" / "committed"
        assert cd.exists(), f"process {pid} committed nothing"
        for part in sorted(os.listdir(cd)):
            for line in (cd / part).read_text().splitlines():
                k, w, c = line.split(",")
                rows.append((int(k), int(w), int(c)))
    return sorted(rows)


class TestAttemptFencing:
    def test_stale_attempt_peer_rejected(self):
        """A process from a PREVIOUS attempt dialing a new attempt's
        listener must be fenced out at the handshake (the static
        cluster.dcn-peers mode has no coordinator rendezvous key to
        protect it — the attempt epoch in the hello is the fence)."""
        import socket as _socket
        import struct as _struct
        import threading

        n = 2
        fresh = [DcnExchange(i, n, attempt=2) for i in range(n)]
        peers = [f"127.0.0.1:{e.port}" for e in fresh]

        # stale dialer (attempt 1) connects first and must NOT occupy
        # peer slot 1
        stale = _socket.create_connection(("127.0.0.1", fresh[0].port))
        stale.sendall(bytes([1]) + _struct.pack(">I", 1) + b"\x00")
        time.sleep(0.1)

        done = []

        def run(i):
            fresh[i].connect(peers, timeout_s=10)
            payloads, metas = fresh[i].exchange(
                {}, {"from": i, "attempt": 2})
            done.append((i, [m.get("from") for m in metas]))

        ths = [threading.Thread(target=run, args=(i,))
               for i in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=20)
        assert len(done) == 2
        for i, froms in sorted(done):
            assert froms == [0, 1]  # the REAL peers, not the stale one
        # the stale connection was closed by the fence
        stale.settimeout(2)
        assert stale.recv(1) == b""
        for e in fresh:
            e.close()
        stale.close()

    def test_same_attempt_connects(self):
        import threading

        n = 2
        exs = [DcnExchange(i, n, attempt=7) for i in range(n)]
        peers = [f"127.0.0.1:{e.port}" for e in exs]
        out = []

        def run(i):
            exs[i].connect(peers, timeout_s=10)
            p, m = exs[i].exchange({}, {"pid": i})
            out.append([mm.get("pid") for mm in m])

        ths = [threading.Thread(target=run, args=(i,)) for i in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=20)
        assert out == [[0, 1], [0, 1]]
        for e in exs:
            e.close()


@pytest.mark.shard_map
class TestTier5TwoProcessQ5:
    def test_two_process_q5_matches_single_process(self, tmp_path):
        """Q5-shaped job over 2 processes: the union of both processes'
        emitted rows must equal the single-process run exactly (each
        key fires on exactly one process — its shard owner)."""
        golden = _golden(tmp_path / "g")
        ports = _free_ports(2)
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        ps = [_spawn(tmp_path, i, 2, peers, ports[i]) for i in range(2)]
        outs = [p.communicate(timeout=300)[0].decode() for p in ps]
        for i, p in enumerate(ps):
            assert p.returncode == 0, f"p{i} failed:\n{outs[i][-3000:]}"
        assert _collect(tmp_path, 2) == golden

    def test_two_process_crash_restore_exactly_once(self, tmp_path):
        """One process crashes mid-run; BOTH restart with
        restore=latest (negotiated common checkpoint id) and the final
        output union still equals the golden run exactly — the
        step-rendezvous checkpoint cut is globally consistent."""
        golden = _golden(tmp_path / "g")
        ports = _free_ports(2)
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        # attempt 1: p1 crashes after 10 source batches; p0 dies on the
        # broken exchange
        ps = [_spawn(tmp_path, 0, 2, peers, ports[0]),
              _spawn(tmp_path, 1, 2, peers, ports[1], crash_at=10)]
        for p in ps:
            p.communicate(timeout=300)
        assert ps[1].returncode == 43
        assert ps[0].returncode != 0
        # attempt 2: fresh ports, negotiated restore
        ports2 = _free_ports(2)
        peers2 = ",".join(f"127.0.0.1:{p}" for p in ports2)
        ps = [_spawn(tmp_path, i, 2, peers2, ports2[i], restore=True)
              for i in range(2)]
        outs = [p.communicate(timeout=300)[0].decode() for p in ps]
        for i, p in enumerate(ps):
            assert p.returncode == 0, f"p{i} failed:\n{outs[i][-3000:]}"
        assert _collect(tmp_path, 2) == golden


    def test_two_process_local_mesh_q5(self, tmp_path):
        """The full tier-5 shape: 2 runner processes x 4 virtual
        devices each — records cross PROCESSES via the DCN exchange and
        cross each process's local DEVICES via the in-step keyBy
        all_to_all; output still equals the single-process run."""
        golden = _golden(tmp_path / "g")
        ports = _free_ports(2)
        peers = ",".join(f"127.0.0.1:{p}" for p in ports)
        ps = [_spawn(tmp_path, i, 2, peers, ports[i], mesh_devices=4)
              for i in range(2)]
        outs = [p.communicate(timeout=600)[0].decode() for p in ps]
        for i, p in enumerate(ps):
            assert p.returncode == 0, f"p{i} failed:\n{outs[i][-3000:]}"
        assert _collect(tmp_path, 2) == golden


class TestExchangeSecurity:
    """ADVICE r5 medium: the exchange port was an unauthenticated RCE
    surface on cross-host (0.0.0.0) deployments — frames decode through
    blobformat, whose __pickle__ escape deserializes attacker pickle.
    Closed two independent ways: an HMAC-over-hello shared secret
    admission check, and a frame decoder that rejects the pickle escape
    outright."""

    def test_unauthenticated_hello_rejected(self):
        """A dialer that knows the wire format but not the secret must
        be dropped at the handshake, while the real (keyed) peers still
        form the mesh."""
        import socket as _socket
        import struct as _struct
        import threading

        n = 2
        exs = [DcnExchange(i, n, attempt=1, secret="job-secret")
               for i in range(n)]
        peers = [f"127.0.0.1:{e.port}" for e in exs]

        # attacker: well-formed keyed hello, garbage MAC
        bad = _socket.create_connection(("127.0.0.1", exs[0].port))
        bad.sendall(bytes([1]) + _struct.pack(">I", 1) + b"\x01"
                    + b"\x00" * 32)
        time.sleep(0.1)

        out = []

        def run(i):
            exs[i].connect(peers, timeout_s=10)
            p, m = exs[i].exchange({}, {"pid": i})
            out.append([mm.get("pid") for mm in m])

        ths = [threading.Thread(target=run, args=(i,)) for i in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=20)
        assert out == [[0, 1], [0, 1]]  # real peers, not the attacker
        bad.settimeout(2)
        assert bad.recv(1) == b"", "unauthenticated hello not dropped"
        bad.close()
        for e in exs:
            e.close()

    def test_secretless_hello_against_keyed_listener_rejected(self):
        """A peer declaring no auth (flag 0) to a keyed listener must
        not be admitted — closed at the handshake, before any frame
        bytes are interpreted."""
        import socket as _socket
        import struct as _struct

        ex = DcnExchange(0, 2, attempt=1, secret="job-secret")
        legacy = _socket.create_connection(("127.0.0.1", ex.port))
        legacy.sendall(bytes([1]) + _struct.pack(">I", 1) + b"\x00")
        raw = blobformat.encode({"data": None, "meta": {}})
        legacy.sendall(_struct.pack(">Q", len(raw)) + raw)
        legacy.settimeout(2)
        try:
            got = legacy.recv(1)
        except ConnectionResetError:
            got = b""  # hard reset is rejection too
        assert got == b"", "secretless hello not dropped"
        assert 1 not in ex._in
        legacy.close()
        ex.close()

    def test_keyed_hello_against_unkeyed_listener_rejected(self):
        """The asymmetric rollout in the other direction: a keyed
        dialer hitting an UNKEYED listener is closed cleanly at the
        handshake — its 32 MAC bytes are drained, never parsed as a
        frame length (which would hang or try a huge allocation)."""
        import hmac as _hmac2
        import socket as _socket
        import struct as _struct

        ex = DcnExchange(0, 2, attempt=1)  # no secret
        keyed = _socket.create_connection(("127.0.0.1", ex.port))
        hello = bytes([1]) + _struct.pack(">I", 1) + b"\x01"
        keyed.sendall(hello + _hmac2.new(b"other-secret", hello,
                                         "sha256").digest())
        keyed.settimeout(2)
        try:
            got = keyed.recv(1)
        except ConnectionResetError:
            got = b""
        assert got == b"", "keyed hello not rejected by unkeyed listener"
        assert 1 not in ex._in
        keyed.close()
        ex.close()

    def test_pickle_escape_frame_rejected(self):
        """A frame smuggling a __pickle__ escape must fail the decode
        loudly instead of deserializing attacker-controlled pickle."""
        import socket as _socket
        import struct as _struct

        # an object-dtype array routes through the __pickle__ escape —
        # the exact in-band vector an attacker's crafted frame uses
        evil = np.array([{"x": 1}], dtype=object)
        raw = blobformat.encode({"data": evil, "meta": {}})
        assert b"__pickle__" in raw  # the attack vector exists in-band

        ex = DcnExchange(0, 2, attempt=1)
        s = _socket.create_connection(("127.0.0.1", ex.port))
        s.sendall(bytes([1]) + _struct.pack(">I", 1)
                  + b"\x00")  # valid unkeyed hello
        deadline = time.time() + 5
        while 1 not in ex._in and time.time() < deadline:
            time.sleep(0.02)
        assert 1 in ex._in
        s.sendall(_struct.pack(">Q", len(raw)) + raw)
        with pytest.raises(ValueError, match="__pickle__ escape rejected"):
            ex.exchange({}, {})
        s.close()
        ex.close()

    def test_numeric_frames_unaffected_by_pickle_rejection(self):
        """The production payload shape (numeric arrays + scalar meta)
        round-trips identically under allow_pickle=False."""
        payload = {"data": {"k": np.arange(5, dtype=np.int64),
                            "v": np.linspace(0, 1, 5)},
                   "meta": {"wm": 123, "done": False, "persisted": -1}}
        raw = blobformat.encode(payload)
        got = blobformat.decode(raw, allow_pickle=False)
        assert got["meta"] == payload["meta"]
        assert (got["data"]["k"] == payload["data"]["k"]).all()
        assert (got["data"]["v"] == payload["data"]["v"]).all()

    def test_string_columns_cross_without_pickle(self):
        """Text columns (object-dtype string arrays, the socket/file
        source shape) encode via the native __strs__ tag — no pickle
        escape — so they survive the exchange's allow_pickle=False."""
        payload = {"data": {"line": np.array(["a", "bb", "ccc"],
                                             dtype=object),
                            "k": np.arange(3, dtype=np.int64)},
                   "meta": {"wm": 7}}
        raw = blobformat.encode(payload)
        assert b"__pickle__" not in raw
        got = blobformat.decode(raw, allow_pickle=False)
        assert list(got["data"]["line"]) == ["a", "bb", "ccc"]
        assert got["data"]["line"].dtype == object
        assert (got["data"]["k"] == payload["data"]["k"]).all()
