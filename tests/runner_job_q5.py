"""Golden Q5 entry point for the analyzer surfaces — ``python -m
flink_tpu analyze --entry runner_job_q5:build --explain`` walks the
same pipeline shape bench.py's headline measures (nexmark bid stream →
keyBy(auction) → 10s/1s sliding COUNT → device top-1 → rename → sink),
so the --explain facts in tests/test_dataflow.py are facts about THE
golden plan, not a toy."""
from flink_tpu.api.sinks import CollectSink
from flink_tpu.nexmark.generator import NexmarkConfig, bid_stream
from flink_tpu.nexmark.queries import q5_hot_items


def build(env):
    cfg = NexmarkConfig(
        batch_size=int(env.config.get_raw("test.batch-size", 8192)),
        n_batches=int(env.config.get_raw("test.n-batches", 2)))
    q5_hot_items(env, bid_stream(cfg), CollectSink(),
                 out_of_orderness_ms=1_000)
