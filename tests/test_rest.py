"""REST API (ref: rest/RestServerEndpoint + dispatcher handler tests:
jobs overview, job detail, cancellation, savepoint trigger)."""
import json
import urllib.error
import urllib.request

import pytest

from flink_tpu.config import Configuration
from flink_tpu.obs.rest import RestServer
from flink_tpu.runtime.coordinator import JobCoordinator


@pytest.fixture
def cluster():
    coord = JobCoordinator(Configuration())
    rest = RestServer(coord, port=0)
    yield coord, rest
    rest.close()
    coord.close()


def get(rest, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{rest.port}{path}") as r:
        return r.status, json.loads(r.read())


def req(rest, method, path):
    r = urllib.request.Request(
        f"http://127.0.0.1:{rest.port}{path}", method=method)
    try:
        with urllib.request.urlopen(r) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestRest:
    def test_overview_and_jobs(self, cluster):
        coord, rest = cluster
        coord.rpc_register_runner("r1", "127.0.0.1", 8, 0)
        coord.rpc_submit_job("job-a")
        code, body = get(rest, "/overview")
        assert code == 200
        assert body["taskmanagers"] == 1
        assert body["jobs"] == {"RUNNING": 1}

        code, body = get(rest, "/jobs")
        assert [j["job_id"] for j in body["jobs"]] == ["job-a"]

        code, body = get(rest, "/jobs/job-a")
        assert code == 200 and body["state"] == "RUNNING"

        code, body = get(rest, "/taskmanagers")
        assert "r1" in body["taskmanagers"]

    def test_unknown_job_404(self, cluster):
        _, rest = cluster
        code, body = req(rest, "GET", "/jobs/nope")
        assert code == 404

    def test_cancel_via_patch(self, cluster):
        coord, rest = cluster
        coord.rpc_submit_job("job-b")
        code, body = req(rest, "PATCH", "/jobs/job-b?mode=cancel")
        assert code == 202 and body["ok"]
        assert coord.rpc_job_status("job-b")["state"] == "CANCELED"
        code, _ = req(rest, "PATCH", "/jobs/job-b?mode=explode")
        assert code == 400

    def test_savepoint_trigger_conflict_when_not_running(self, cluster):
        coord, rest = cluster
        coord.rpc_submit_job("job-c")
        coord.rpc_cancel_job("job-c")
        code, body = req(rest, "POST", "/jobs/job-c/savepoints")
        assert code == 409 and not body["ok"]

    def test_html_index(self, cluster):
        # client-rendered dashboard: the page ships the fetch/render
        # logic; the DATA arrives from the JSON routes it polls
        coord, rest = cluster
        coord.rpc_submit_job("job-d")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rest.port}/") as r:
            html = r.read().decode()
        assert "flink_tpu" in html and "/graph" in html
        code, jobs = req(rest, "GET", "/jobs")
        assert code == 200
        assert any(j["job_id"] == "job-d" for j in jobs["jobs"])

    def test_unknown_route_404(self, cluster):
        _, rest = cluster
        code, _ = req(rest, "GET", "/nonexistent")
        assert code == 404

    def test_patch_and_savepoint_unknown_job_404(self, cluster):
        _, rest = cluster
        code, _ = req(rest, "PATCH", "/jobs/typo?mode=cancel")
        assert code == 404
        code, _ = req(rest, "POST", "/jobs/typo/savepoints")
        assert code == 404

    def test_html_escapes_job_ids(self, cluster):
        coord, rest = cluster
        coord.rpc_submit_job("<script>alert(1)</script>")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{rest.port}/") as r:
            html = r.read().decode()
        # the page never embeds job ids server-side; every client-side
        # interpolation routes through the esc() helper
        assert "<script>alert" not in html
        assert "function esc(" in html
        assert "esc(jb.job_id)" in html

    def test_dispatch_through_rpc_server(self):
        """REST fronted by the RpcServer rides its single dispatch
        thread (the documented no-locks contract)."""
        from flink_tpu.runtime.rpc import RpcServer

        coord = JobCoordinator(Configuration())
        srv = RpcServer(coord)
        rest = RestServer(srv, port=0)
        try:
            coord.rpc_submit_job("via-rpc")
            code, body = get(rest, "/jobs/via-rpc")
            assert code == 200 and body["state"] == "RUNNING"
        finally:
            rest.close()
            srv.close()
            coord.close()


class TestJobGraphRoute:
    def test_graph_route_serves_dag_and_metrics(self, cluster):
        coord, rest = cluster
        coord.rpc_submit_job("job-g")
        coord.rpc_report_plan("job-g", ["source", "window", "sink"])
        coord.jobs["job-g"].last_metrics = {
            "eps": 123.0, "records_in": 10, "records_out": 5,
            "wm_lag_ms": 7, "backpressure_s": 0.1,
            "checkpoints": [{"id": 1, "ts": 0, "bytes": 100}]}
        code, g = req(rest, "GET", "/jobs/job-g/graph")
        assert code == 200
        assert g["stages"] == ["source", "window", "sink"]
        assert g["metrics"]["eps"] == 123.0
        assert g["metrics"]["checkpoints"][0]["id"] == 1
