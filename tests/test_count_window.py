"""Count windows + trigger family (ref: WindowOperatorTest count/purging
trigger cases, KeyedStream.countWindow). Semantics under test are the
documented microbatch-boundary ones: a key crossing N inside one batch
fires once with its full accumulated aggregate; with batch size 1 the
behavior equals the reference's exact every-Nth-element firing."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import (
    CountTrigger, EventTimeTrigger, PurgingTrigger, TumblingEventTimeWindows)
from flink_tpu.config import Configuration
from flink_tpu.ops import aggregates
from flink_tpu.ops.count_window import GLOBAL_WINDOW_END, CountWindowOperator
from flink_tpu.time.watermarks import WatermarkStrategy


def make_env(extra=None):
    conf = {"state.num-key-shards": 4, "state.slots-per-shard": 32,
            "pipeline.microbatch-size": 64}
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def single_record_source(keys, values):
    """One record per batch — exact reference semantics territory."""
    def gen(split, i):
        if i >= len(keys):
            return None
        return ({"k": np.array([keys[i]], np.int64),
                 "v": np.array([values[i]], np.int64)},
                np.array([i * 10], np.int64))
    return gen


class TestCountWindowE2E:
    def test_fires_every_n_exact_reference_semantics(self):
        """Batch size 1: countWindow(3) fires at the 3rd, 6th... element
        per key with the purged (per-window) sum — the reference's exact
        behavior (ref: CountTrigger.onElement + PurgingTrigger)."""
        keys = [7, 7, 9, 7, 9, 9, 7, 7, 7]
        vals = [1, 2, 10, 3, 20, 30, 4, 5, 6]
        env = make_env()
        sink = CollectSink()
        (env.from_source(GeneratorSource(single_record_source(keys, vals)),
                         WatermarkStrategy.for_monotonous_timestamps())
         .key_by("k")
         .count_window(3)
         .sum("v")
         .add_sink(sink))
        env.execute("cw")
        got = [(int(r["key"]), float(r["sum_v"]), int(r["count"]))
               for r in sink.rows]
        assert got == [(7, 6.0, 3), (9, 60.0, 3), (7, 15.0, 3)]
        # partial group (none left: key 7 fired twice at 6 elements,
        # key 9 once at 3) — nothing else emitted
        assert all(int(r["window_end"]) == GLOBAL_WINDOW_END
                   for r in sink.rows)

    def test_incomplete_groups_emit_nothing_at_end(self):
        """GlobalWindows never completes: keys below N at end-of-input
        emit nothing (reference behavior)."""
        env = make_env()
        sink = CollectSink()
        (env.from_source(
            GeneratorSource(single_record_source([1, 1, 2], [5, 6, 7])),
            WatermarkStrategy.for_monotonous_timestamps())
         .key_by("k")
         .count_window(3)
         .count()
         .add_sink(sink))
        env.execute("cw-partial")
        assert sink.rows == [] or all(len(np.atleast_1d(
            list(r.values())[0])) == 0 for r in sink.rows)

    def test_batched_crossing_fires_once_with_full_aggregate(self):
        """A key receiving 2N elements within ONE microbatch fires once
        with all of them — the documented batching tradeoff."""
        def gen(split, i):
            if i >= 1:
                return None
            return ({"k": np.zeros(7, np.int64),
                     "v": np.arange(1, 8, dtype=np.int64)},
                    np.arange(7, dtype=np.int64) * 10)

        env = make_env()
        sink = CollectSink()
        (env.from_source(GeneratorSource(gen),
                         WatermarkStrategy.for_monotonous_timestamps())
         .key_by("k").count_window(3).sum("v").add_sink(sink))
        env.execute("cw-batched")
        got = [(float(r["sum_v"]), int(r["count"])) for r in sink.rows]
        assert got == [(28.0, 7)]  # one fire, full batch accumulated


    def test_count_window_downstream_of_time_window_is_stateful(self):
        """A count window fed by a time window's fires must run on the
        ingest thread (stateful-downstream rule), not the async drain —
        and produce correct two-stage results (regression: the
        stateless-downstream check omitted count_window)."""
        def gen(split, i):
            if i >= 6:
                return None
            return ({"k": np.array([1, 1, 2], np.int64)},
                    np.full(3, i * 1000 + 500, np.int64))

        env = make_env()
        sink = CollectSink()
        (env.from_source(GeneratorSource(gen),
                         WatermarkStrategy.for_monotonous_timestamps())
         .key_by("k")
         .window(TumblingEventTimeWindows.of(1_000))
         .count()                      # per (key, second): k1=2, k2=1
         .key_by("key")
         .count_window(3)
         .sum("count")
         .add_sink(sink))
        env.execute("two-stage")
        got = sorted((int(r["key"]), float(r["sum_count"]))
                     for r in sink.rows)
        # 6 windows per key; count_window(3) fires twice per key with
        # 3 window-counts summed each time
        assert got == [(1, 6.0), (1, 6.0), (2, 3.0), (2, 3.0)]


class TestTriggerValidation:
    def test_count_trigger_on_time_window_routes_to_element_path(self):
        """Previously raised; now runs with exact per-element semantics
        on the element-buffer operator (see tests/test_evicting_window
        for the behavioral coverage)."""
        from flink_tpu.graph.transformations import (
            EvictingWindowTransformation)

        env = make_env()
        s = (env.from_source(
            GeneratorSource(single_record_source([1], [1])),
            WatermarkStrategy.for_monotonous_timestamps())
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1_000))
            .trigger(CountTrigger.of(5)))
        out = s.count()
        assert isinstance(out.transform, EvictingWindowTransformation)

    def test_purging_event_time_ok_without_lateness(self):
        env = make_env()
        sink = CollectSink()
        (env.from_source(
            GeneratorSource(single_record_source([1, 1], [1, 2])),
            WatermarkStrategy.for_monotonous_timestamps())
         .key_by("k")
         .window(TumblingEventTimeWindows.of(1_000))
         .trigger(PurgingTrigger.of(EventTimeTrigger.create()))
         .count()
         .add_sink(sink))
        env.execute("purging-ok")
        assert sum(int(r["count"]) for r in sink.rows) == 2

    def test_purging_event_time_with_lateness_routes_to_element_path(self):
        """Previously refused (the pane backend cannot express
        fresh-state re-fires); the element-buffer operator CAN — a late
        record after a purge re-fires with only the fresh contents,
        which is exactly the reference's PurgingTrigger semantics."""
        from flink_tpu.graph.transformations import (
            EvictingWindowTransformation)

        env = make_env()
        s = (env.from_source(
            GeneratorSource(single_record_source([1], [1])),
            WatermarkStrategy.for_monotonous_timestamps())
            .key_by("k")
            .window(TumblingEventTimeWindows.of(1_000))
            .allowed_lateness(5_000)
            .trigger(PurgingTrigger.of(EventTimeTrigger.create())))
        out = s.count()
        assert isinstance(out.transform, EvictingWindowTransformation)


class TestCountWindowOperator:
    def test_non_purging_accumulates_across_fires(self):
        """Bare CountTrigger (no purge): window contents accumulate;
        only the trigger count resets (ref: CountTrigger clears its own
        ReducingState, not the window state)."""
        op = CountWindowOperator(aggregates.sum_of("v"), 2, purge=False,
                                 num_shards=2, slots_per_shard=8)
        for vals in ([1, 2], [3, 4]):
            op.process_batch(np.zeros(2, np.int64),
                             np.zeros(2, np.int64),
                             {"v": np.array(vals, np.int64)})
        fired = op.take_fired().materialize()
        sums = [float(v) for v in fired["sum_v"]]
        assert sums == [3.0, 10.0]  # 1+2 then 1+2+3+4

    def test_snapshot_restore_roundtrip(self):
        def mk():
            return CountWindowOperator(aggregates.count(), 3,
                                       num_shards=2, slots_per_shard=8)

        a = mk()
        a.process_batch(np.array([4, 4], np.int64),
                        np.zeros(2, np.int64), {})
        a.take_fired()
        snap = a.snapshot_state()
        b = mk()
        b.restore_state(snap)
        b.process_batch(np.array([4], np.int64), np.zeros(1, np.int64), {})
        fired = b.take_fired().materialize()
        assert [int(k) for k in fired["key"]] == [4]
        assert [int(c) for c in fired["count"]] == [3]
