"""Tier-5 e2e: REAL runner processes, a real kill, coordinator-driven
recovery, exactly-once output (SURVEY §5 tier 5; ref: the
ProcessFailureCancelingITCase / TaskExecutorITCase family — actual
process death, not simulated failure).

Topology: coordinator (in-test RpcServer) + two runner SUBPROCESSES.
A job is submitted with a deployment descriptor (``runner_job:build``);
the assigned runner is SIGKILLed mid-job; heartbeat expiry routes the
loss through the restart budget; the coordinator re-deploys to the
surviving runner with restore=latest; the file-backed 2PC sink must
show every window exactly once.
"""
import os
import signal
import subprocess
import sys
import time

import pytest

from flink_tpu.api.sinks import FileTransactionalSink
from flink_tpu.config import Configuration
from flink_tpu.runtime.coordinator import JobCoordinator
from flink_tpu.runtime.rpc import RpcServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_runner(coord_port: int, runner_id: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + os.path.join(REPO, "tests")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # single CPU device is plenty per runner
    return subprocess.Popen(
        [sys.executable, "-m", "flink_tpu.runtime.runner",
         "--coordinator", f"127.0.0.1:{coord_port}",
         "--runner-id", runner_id],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def wait_until(pred, timeout=60.0, interval=0.1, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def test_runner_kill_recovery_exactly_once(tmp_path):
    import runner_job

    coord = JobCoordinator(Configuration({
        "heartbeat.interval": "200ms",
        "heartbeat.timeout": "1200ms",
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 3,
        "restart-strategy.fixed-delay.delay": "100ms",
    }))
    srv = RpcServer(coord)
    procs = {}
    try:
        procs["r1"] = spawn_runner(srv.port, "r1")
        procs["r2"] = spawn_runner(srv.port, "r2")
        wait_until(lambda: len(coord.runners) == 2, 90,
                   what="both runners registered")

        n_batches = 40
        sink_dir = str(tmp_path / "sink")
        coord.rpc_submit_job(
            "kill-job",
            entry="runner_job:build",
            config={
                "test.n-batches": n_batches,
                "test.batch-sleep-ms": 150,
                "test.sink-dir": sink_dir,
                "execution.checkpointing.dir": str(tmp_path / "chk"),
                "execution.checkpointing.interval": "200ms",
                "state.num-key-shards": 8,
                "state.slots-per-shard": 16,
            })

        # wait for real progress: at least one COMMITTED epoch on disk
        wait_until(
            lambda: len(FileTransactionalSink.committed_rows(sink_dir)) > 0,
            90, what="first committed epoch")
        assigned = coord.jobs["kill-job"].assigned_runners[0]
        victim = procs[assigned]
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)

        # coordinator notices the silence, burns one restart attempt,
        # re-deploys to the survivor with restore=latest
        wait_until(lambda: coord.jobs["kill-job"].state == "FINISHED",
                   120, what="job FINISHED after recovery")
        assert coord.jobs["kill-job"].attempts >= 2
        survivor = coord.jobs["kill-job"].assigned_runners[0]
        assert survivor != assigned

        got = {}
        for r in FileTransactionalSink.committed_rows(sink_dir):
            kk = (int(r["key"]), int(r["window_start"]))
            assert kk not in got, f"duplicate emission for {kk}"
            got[kk] = int(r["count"])
        assert got == runner_job.golden_counts(n_batches)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        coord.close()
        srv.close()


def test_runner_registers_runs_and_finishes(tmp_path):
    """Happy path: register → push deploy → run → FINISHED, output
    committed exactly once (the submitTask round trip)."""
    import runner_job

    coord = JobCoordinator(Configuration({
        "heartbeat.interval": "200ms",
        "heartbeat.timeout": "5s",
    }))
    srv = RpcServer(coord)
    proc = None
    try:
        proc = spawn_runner(srv.port, "solo")
        wait_until(lambda: len(coord.runners) == 1, 90,
                   what="runner registered")
        n_batches = 6
        sink_dir = str(tmp_path / "sink")
        coord.rpc_submit_job(
            "ok-job",
            entry="runner_job:build",
            config={
                "test.n-batches": n_batches,
                "test.sink-dir": sink_dir,
                "execution.checkpointing.dir": str(tmp_path / "chk"),
                "execution.checkpointing.interval": "100ms",
                "state.num-key-shards": 8,
                "state.slots-per-shard": 16,
            })
        wait_until(lambda: coord.jobs["ok-job"].state == "FINISHED", 90,
                   what="job FINISHED")
        got = {}
        for r in FileTransactionalSink.committed_rows(sink_dir):
            kk = (int(r["key"]), int(r["window_start"]))
            assert kk not in got
            got[kk] = int(r["count"])
        assert got == runner_job.golden_counts(n_batches)
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        coord.close()
        srv.close()


def test_cancel_job_stops_runner_and_state_sticks(tmp_path):
    """Cancel flows coordinator → runner gateway → driver batch
    boundary; the job stops producing, CANCELED is terminal (a late
    finish/failure report must not resurrect it)."""
    coord = JobCoordinator(Configuration({
        "heartbeat.interval": "200ms",
        "heartbeat.timeout": "5s",
    }))
    srv = RpcServer(coord)
    proc = None
    try:
        proc = spawn_runner(srv.port, "c1")
        wait_until(lambda: len(coord.runners) == 1, 90,
                   what="runner registered")
        sink_dir = str(tmp_path / "sink")
        coord.rpc_submit_job(
            "cancel-job",
            entry="runner_job:build",
            config={
                "test.n-batches": 200,           # would run ~30s
                "test.batch-sleep-ms": 150,
                "test.sink-dir": sink_dir,
                "execution.checkpointing.dir": str(tmp_path / "chk"),
                "execution.checkpointing.interval": "200ms",
                "state.num-key-shards": 8,
                "state.slots-per-shard": 16,
            })
        wait_until(
            lambda: len(FileTransactionalSink.committed_rows(sink_dir)) > 0,
            90, what="job producing output")
        coord.rpc_cancel_job("cancel-job")
        # the runner drops the job within a couple of batch boundaries
        import json as _json
        from flink_tpu.runtime.rpc import RpcClient
        r = coord.runners["c1"]
        c = RpcClient(r.host, r.port)
        wait_until(lambda: c.call("ping")["jobs"] == [], 30,
                   what="runner dropped the cancelled job")
        c.close()
        assert coord.jobs["cancel-job"].state == "CANCELED"
        # no further commits after cancellation settles
        n0 = len(FileTransactionalSink.committed_rows(sink_dir))
        time.sleep(1.0)
        assert len(FileTransactionalSink.committed_rows(sink_dir)) == n0
        time.sleep(0.5)
        assert coord.jobs["cancel-job"].state == "CANCELED"
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        coord.close()
        srv.close()


def test_coordinator_deploys_one_job_across_two_runners(tmp_path):
    """Tier-5 (SURVEY §3.6): ONE submitted job spans TWO runner
    processes — the coordinator allocates a distinct runner per
    process, the DCN exchange ports rendezvous through
    rpc_dcn_register/peers, keyed records cross processes, and the
    union of both processes' committed output equals the golden run."""
    import runner_job_dcn

    coord = JobCoordinator(Configuration({
        "heartbeat.interval": "200ms",
        "heartbeat.timeout": "5000ms",
    }))
    srv = RpcServer(coord)
    procs = {}
    try:
        procs["r1"] = spawn_runner(srv.port, "r1")
        procs["r2"] = spawn_runner(srv.port, "r2")
        wait_until(lambda: len(coord.runners) == 2, 90,
                   what="both runners registered")
        n_batches = 16
        sink_dir = str(tmp_path / "sink")
        coord.rpc_submit_job(
            "dcn-job",
            entry="runner_job_dcn:build",
            config={
                "test.n-batches": n_batches,
                "test.sink-dir": sink_dir,
                "cluster.num-processes": 2,
                "execution.checkpointing.dir": str(tmp_path / "chk"),
                "execution.checkpointing.interval": "300ms",
                "state.num-key-shards": 8,
                "state.slots-per-shard": 32,
            })
        wait_until(lambda: coord.jobs["dcn-job"].state == "FINISHED",
                   180, what="cross-runner job FINISHED")
        assert sorted(coord.jobs["dcn-job"].assigned_runners) == [
            "r1", "r2"]
        got = {}
        for pid in (0, 1):
            for r in FileTransactionalSink.committed_rows(
                    f"{sink_dir}-p{pid}"):
                kk = (int(r["key"]), int(r["window_start"]))
                assert kk not in got, f"duplicate emission for {kk}"
                got[kk] = int(r["count"])
        assert got == runner_job_dcn.golden_counts(n_batches)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        srv.close()
