"""Metrics subsystem tests (ref: flink-metrics-core semantics +
PrometheusReporter exposition format)."""
import urllib.request

import numpy as np

from flink_tpu.obs.metrics import (
    Counter, Gauge, Histogram, Meter, MetricRegistry, MetricsServer)


class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = MetricRegistry()
        g = reg.group("job", "task")
        c = g.counter("records")
        c.inc(); c.inc(5)
        ga = g.gauge("lag"); ga.set(42.0)
        h = g.histogram("lat")
        for v in range(100):
            h.update(float(v))
        snap = reg.snapshot()
        assert snap["job.task.records"] == 6
        assert snap["job.task.lag"] == 42.0
        assert snap["job.task.lat.count"] == 100
        assert 95 <= snap["job.task.lat.p99"] <= 99

    def test_callable_gauge(self):
        reg = MetricRegistry()
        state = {"v": 1.0}
        reg.group("g").gauge("x", lambda: state["v"])
        assert reg.snapshot()["g.x"] == 1.0
        state["v"] = 7.0
        assert reg.snapshot()["g.x"] == 7.0

    def test_prometheus_format(self):
        reg = MetricRegistry()
        reg.group("driver").counter("records-in").inc(3)
        text = reg.to_prometheus()
        assert "# TYPE flink_tpu_driver_records_in gauge" in text
        assert "flink_tpu_driver_records_in 3.0" in text

    def test_http_server_scrape(self):
        reg = MetricRegistry()
        reg.group("d").counter("n").inc(9)
        srv = MetricsServer(reg, 0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics").read().decode()
            assert "flink_tpu_d_n 9.0" in body
        finally:
            srv.close()


class TestDriverMetrics:
    def test_job_result_carries_metrics(self):
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.windowing import TumblingEventTimeWindows
        from flink_tpu.config import Configuration

        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 8, "state.slots-per-shard": 16,
            "pipeline.microbatch-size": 64}))
        (env.from_collection({"k": np.arange(100, dtype=np.int64) % 5},
                             np.arange(100, dtype=np.int64) * 20)
         .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
         .collect())
        res = env.execute("m")
        assert res.metrics["records_in"] == 100
        assert res.metrics["fired_windows"] > 0
        assert "driver.emit_latency_ms.p99" in res.metrics
        assert res.metrics["driver.records_in"] == 100


class TestThreadSafety:
    """The primitives' write paths are lock-guarded: host-pool worker
    threads (flink_tpu/parallel/hostpool.py), the drain thread, and the
    scrape thread share one registry — an unguarded `+=` loses updates
    under contention. Regression: concurrent writers must land EXACTLY."""

    THREADS = 8
    PER_THREAD = 5_000

    def _hammer(self, fn, per_thread=None):
        import threading

        start = threading.Barrier(self.THREADS)
        per_thread = per_thread or self.PER_THREAD

        def work():
            start.wait()
            for _ in range(per_thread):
                fn()

        ts = [threading.Thread(target=work) for _ in range(self.THREADS)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    def test_concurrent_counter_inc_exact(self):
        from flink_tpu.obs.metrics import Counter

        c = Counter()
        self._hammer(lambda: c.inc())
        assert c.value == self.THREADS * self.PER_THREAD

    def test_concurrent_histogram_update_exact_count(self):
        from flink_tpu.obs.metrics import Histogram

        h = Histogram(size=256)
        self._hammer(lambda: h.update(1.0))
        assert h.count == self.THREADS * self.PER_THREAD
        assert h.quantile(0.5) == 1.0  # every reservoir slot intact

    def test_concurrent_gauge_set_and_meter_mark(self):
        from flink_tpu.obs.metrics import Gauge, Meter

        g = Gauge()
        m = Meter()

        def touch():
            g.set(42.0)
            m.mark()
            m.rate  # reader racing the marker's head-pop

        # smaller sweep: rate re-scans the event list per call, so the
        # hammer is quadratic in marks — 200/thread races plenty
        self._hammer(touch, per_thread=200)
        assert g.value == 42.0
        assert m.rate > 0.0
