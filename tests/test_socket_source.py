"""Socket ingest source: C line-framed reader + Python fallback parity,
end-to-end windowed pipeline fed over TCP (SURVEY §3.10 item 3)."""
import socket
import threading
import time

import numpy as np
import pytest

from flink_tpu import native_codec as nc
from flink_tpu.config import Configuration
from flink_tpu.connectors import SocketSource, _PySocketReader
from flink_tpu.formats import CsvFormat


def _feed(port, payload: bytes, chunk=7, delay=0.0):
    """Background producer writing payload in awkward chunk sizes (to
    exercise the partial-line carry), then disconnecting."""
    def run():
        s = socket.create_connection(("127.0.0.1", port))
        for lo in range(0, len(payload), chunk):
            s.sendall(payload[lo:lo + chunk])
            if delay:
                time.sleep(delay)
        s.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def _drain(reader, payload, cap=64):
    """Producer in the background, consume blocks until EOF."""
    t = _feed(reader.port, payload)
    deadline = time.time() + 30
    while reader.accept(100) == 0:
        assert time.time() < deadline, "producer never connected"
    got = b""
    while True:
        b = reader.read_block(cap, timeout_ms=200)
        if b is None:
            break
        got += b
        # block invariant: always ends at a newline
        assert b == b"" or b.endswith(b"\n")
        assert time.time() < deadline, "reader never saw EOF"
    t.join()
    reader.close()
    return got


class TestReaders:
    PAYLOAD = b"".join(f"{i},{i*3}\n".encode() for i in range(100))

    def test_native_reader_reassembles_lines(self):
        r = nc.NativeSocketReader.create()
        if r is None:
            pytest.skip("codec library unavailable")
        assert _drain(r, self.PAYLOAD) == self.PAYLOAD

    def test_python_reader_parity(self):
        assert _drain(_PySocketReader(), self.PAYLOAD) == self.PAYLOAD

    def test_unterminated_tail_discarded(self):
        r = _PySocketReader()
        _feed(r.port, b"1,2\n3,4")  # second record never terminated
        while r.accept(1000) == 0:
            pass
        got = b""
        while True:
            b = r.read_block(64, timeout_ms=200)
            if b is None:
                break
            got += b
        r.close()
        assert got == b"1,2\n"


class TestSocketPipeline:
    def test_windowed_count_over_tcp(self):
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sinks import CollectSink
        from flink_tpu.api.windowing import TumblingEventTimeWindows
        from flink_tpu.time.watermarks import WatermarkStrategy

        rng = np.random.default_rng(0)
        n = 4000
        keys = rng.integers(0, 6, n)
        ts = np.sort(rng.integers(0, 8000, n))
        payload = b"".join(f"{k},{t}\n".encode()
                           for k, t in zip(keys, ts))

        src = SocketSource(format=CsvFormat([("k", "i64"), ("ts", "i64")]),
                           ts_field="ts", poll_ms=50)
        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 8, "state.slots-per-shard": 16}))
        sink = CollectSink()
        (env.from_source(src,
                         WatermarkStrategy.for_bounded_out_of_orderness(0))
         .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
         .add_sink(sink))
        _feed(src.port, payload, chunk=1024)
        env.execute("socket-count")

        golden = {}
        for k, t in zip(keys, ts):
            kk = (int(k), (int(t) // 1000 + 1) * 1000)
            golden[kk] = golden.get(kk, 0) + 1
        got = {(int(r["key"]), int(r["window_end"])): int(r["count"])
               for r in sink.rows}
        assert got == golden


class TestReviewRegressions:
    def test_oversized_line_raises_in_both_readers(self):
        r = _PySocketReader()
        _feed(r.port, b"x" * 500 + b"\n")
        while r.accept(1000) == 0:
            pass
        with pytest.raises(IOError, match="exceeded"):
            while True:
                b = r.read_block(64, timeout_ms=200)
                if b is None:
                    break

    def test_accept_wait_yields_typed_empty_batches(self):
        src = SocketSource(format=CsvFormat([("k", "i64"), ("ts", "i64")]),
                           ts_field="ts", poll_ms=20)
        it = src.open_split("socket")
        data, ts = next(it)  # nobody connected: typed empty batch
        assert set(data) == {"k", "ts"}
        assert len(ts) == 0 and data["k"].dtype == np.int64
        src._reader.close()

    def test_finished_runners_reset_on_restart(self):
        from flink_tpu.runtime.coordinator import JobCoordinator

        coord = JobCoordinator(Configuration({}))
        try:
            for r in ("a", "b"):
                coord.rpc_register_runner(r, "h", 1)
            coord.rpc_submit_job("j", runners=["a", "b"])
            coord.rpc_finish_job("j", runner_id="a")
            assert coord.rpc_job_status("j")["state"] == "RUNNING"
            coord.rpc_report_failure("j", "b crashed")
            assert coord.jobs["j"].finished_runners == []
            # attempt 2: BOTH must finish again
            coord.jobs["j"].state = "RUNNING"
            coord.rpc_finish_job("j", runner_id="b")
            assert coord.rpc_job_status("j")["state"] == "RUNNING"
            coord.rpc_finish_job("j", runner_id="a")
            assert coord.rpc_job_status("j")["state"] == "FINISHED"
        finally:
            coord.close()
