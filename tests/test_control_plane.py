"""Control-plane tests: RPC, heartbeats/failure detection, restart
strategies, supervised recovery (ref: the testing-gateway pattern,
flink-runtime/src/test/.../utils/Testing*Gateway.java — RPC is an
interface, so distributed logic tests in-process)."""
import time

import numpy as np
import pytest

from flink_tpu.config import Configuration
from flink_tpu.runtime.coordinator import JobCoordinator, start_coordinator
from flink_tpu.runtime.restart import (
    ExponentialDelayRestartStrategy,
    FailureRateRestartStrategy,
    FixedDelayRestartStrategy,
    NoRestartStrategy,
)
from flink_tpu.runtime.rpc import RpcClient, RpcEndpoint, RpcError, RpcServer


class TestRpc:
    def test_call_roundtrip_and_errors(self):
        class Echo(RpcEndpoint):
            def rpc_echo(self, x):
                return {"got": x}

            def rpc_boom(self):
                raise ValueError("nope")

        srv = RpcServer(Echo())
        try:
            c = RpcClient("127.0.0.1", srv.port)
            assert c.call("echo", x=[1, 2]) == {"got": [1, 2]}
            with pytest.raises(RpcError, match="nope"):
                c.call("boom")
            with pytest.raises(RpcError, match="no such method"):
                c.call("missing")
            c.close()
        finally:
            srv.close()

    def test_single_threaded_dispatch(self):
        """Concurrent calls serialize on the endpoint thread — the
        main-thread discipline means no endpoint locks needed."""
        import threading

        class Count(RpcEndpoint):
            def __init__(self):
                self.v = 0

            def rpc_bump(self):
                cur = self.v
                time.sleep(0.001)  # a data race would lose increments
                self.v = cur + 1
                return self.v

        ep = Count()
        srv = RpcServer(ep)
        try:
            def worker():
                c = RpcClient("127.0.0.1", srv.port)
                for _ in range(10):
                    c.call("bump")
                c.close()

            ts = [threading.Thread(target=worker) for _ in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert ep.v == 40
        finally:
            srv.close()


class TestCoordinator:
    def test_register_submit_status(self):
        srv = start_coordinator(Configuration({"heartbeat.timeout": 500}))
        try:
            c = RpcClient("127.0.0.1", srv.port)
            r = c.call("register_runner", runner_id="r1", host="h1", n_devices=8)
            assert r["heartbeat_interval_ms"] > 0
            assert c.call("submit_job", job_id="j1")["assigned"] == ["r1"]
            assert c.call("job_status", job_id="j1")["state"] == "RUNNING"
            c.call("finish_job", job_id="j1")
            assert c.call("job_status", job_id="j1")["state"] == "FINISHED"
        finally:
            srv.close()

    def test_heartbeat_timeout_marks_runner_dead_and_restarts_job(self):
        srv = start_coordinator(Configuration({"heartbeat.timeout": 300}))
        try:
            c = RpcClient("127.0.0.1", srv.port)
            c.call("register_runner", runner_id="r1", host="h1", n_devices=8)
            c.call("submit_job", job_id="j1")
            assert c.call("heartbeat", runner_id="r1")["known"]
            deadline = time.time() + 3
            while time.time() < deadline:
                rs = c.call("list_runners")
                if not rs["r1"]["alive"]:
                    break
                time.sleep(0.05)
            assert not c.call("list_runners")["r1"]["alive"]
            st = c.call("job_status", job_id="j1")
            assert st["state"] == "RESTARTING"
        finally:
            srv.close()

    def test_deploy_transport_drop_routes_failure(self):
        """PR-14 chaos-seam audit regression: faults `drop`-kind rules
        raise ConnectionError, NOT RpcError — the coordinator.deploy
        point fires before the client's RpcError wrapping, so the
        deploy catch must handle both or an injected transport drop
        kills the deploy thread silently and the job parks forever
        (the PR-11 flake class)."""
        from flink_tpu import faults

        class _Gw(RpcEndpoint):
            def __init__(self):
                self.jobs = []

            def rpc_run_job(self, **kw):
                self.jobs.append(kw)
                return {"accepted": True}

        # two gateways: the failure handler EXCLUDES the runner whose
        # push died, so the routed restart lands on the second
        gws = [_Gw(), _Gw()]
        gw_srvs = [RpcServer(g) for g in gws]
        srv = start_coordinator(Configuration({
            "heartbeat.timeout": 60_000,  # fake runners never beat
            "restart-strategy.type": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 3,
            "restart-strategy.fixed-delay.delay": 10}))
        plan = faults.FaultPlan(seed=1).rule(
            "coordinator.deploy", "drop", count=1)
        try:
            c = RpcClient("127.0.0.1", srv.port)
            for i, gs in enumerate(gw_srvs):
                c.call("register_runner", runner_id=f"r{i}",
                       host="127.0.0.1", n_devices=1, port=gs.port)
            with plan.activate():
                c.call("submit_job", job_id="j-drop",
                       entry="runner_job:build")
                deadline = time.time() + 5
                while (time.time() < deadline
                       and not any(g.jobs for g in gws)):
                    time.sleep(0.05)
            assert plan.log, "the drop never fired"
            landed = [kw for g in gws for kw in g.jobs]
            assert landed, (
                "deploy thread died on the injected ConnectionError — "
                "the failure was never routed to a restart")
            assert landed[0]["job_id"] == "j-drop"
        finally:
            srv.close()
            for gs in gw_srvs:
                gs.close()

    def test_report_failure_restart_then_fail(self):
        srv = start_coordinator(Configuration({
            "restart-strategy.type": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 2,
            "restart-strategy.fixed-delay.delay": 10}))
        try:
            c = RpcClient("127.0.0.1", srv.port)
            c.call("register_runner", runner_id="r1", host="h", n_devices=1)
            c.call("submit_job", job_id="j")
            a1 = c.call("report_failure", job_id="j", error="e1")
            assert a1["action"] == "restart" and a1["restore"] == "latest"
            a2 = c.call("report_failure", job_id="j", error="e2")
            assert a2["action"] == "restart"
            a3 = c.call("report_failure", job_id="j", error="e3")
            assert a3["action"] == "fail"
            assert c.call("job_status", job_id="j")["state"] == "FAILED"
        finally:
            srv.close()


class TestRestartStrategies:
    def test_fixed_delay(self):
        s = FixedDelayRestartStrategy(max_attempts=2, delay_ms=5)
        assert s.can_restart() and s.next_delay_ms() == 5
        assert s.can_restart() and s.next_delay_ms() == 5
        assert not s.can_restart()

    def test_exponential(self):
        s = ExponentialDelayRestartStrategy(initial_ms=100, max_ms=400)
        assert s.next_delay_ms() == 100
        assert s.next_delay_ms() == 200
        assert s.next_delay_ms() == 400
        assert s.next_delay_ms() == 400  # capped

    def test_failure_rate(self):
        s = FailureRateRestartStrategy(max_failures=2, interval_ms=60_000,
                                       delay_ms=1)
        assert s.can_restart(); s.next_delay_ms()
        assert s.can_restart(); s.next_delay_ms()
        assert not s.can_restart()

    def test_none(self):
        assert not NoRestartStrategy().can_restart()


class TestSupervisedRecovery:
    def test_run_with_recovery_resumes_exactly_once(self, tmp_path):
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sinks import TransactionalCollectSink
        from flink_tpu.api.sources import GeneratorSource
        from flink_tpu.api.windowing import TumblingEventTimeWindows
        from flink_tpu.runtime.supervisor import run_with_recovery
        from flink_tpu.time.watermarks import WatermarkStrategy

        sink = TransactionalCollectSink()
        crashes = {"left": 2}

        def gen(split, i):
            if i >= 8:
                return None
            if i == 5 and crashes["left"] > 0:
                crashes["left"] -= 1
                raise RuntimeError("flaky task")
            rng = np.random.default_rng(i)
            return ({"k": rng.integers(0, 4, 64).astype(np.int64)},
                    np.sort(rng.integers(i * 300, i * 300 + 600, 64)).astype(np.int64))

        conf = Configuration({
            "state.num-key-shards": 8, "state.slots-per-shard": 32,
            "pipeline.microbatch-size": 64,
            "execution.checkpointing.dir": str(tmp_path),
            "execution.checkpointing.interval": 1,
            "restart-strategy.type": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 3,
            "restart-strategy.fixed-delay.delay": 1,
        })

        def build(c):
            env = StreamExecutionEnvironment(c)
            (env.from_source(GeneratorSource(gen),
                             WatermarkStrategy.for_bounded_out_of_orderness(600))
             .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
             .add_sink(sink))
            return env

        res = run_with_recovery(build, conf, "supervised")
        golden = {}
        for i in range(8):
            rng = np.random.default_rng(i)
            ks = rng.integers(0, 4, 64).astype(np.int64)
            ts = np.sort(rng.integers(i * 300, i * 300 + 600, 64)).astype(np.int64)
            for k, t in zip(ks, ts):
                kk = (int(k), (int(t) // 1000) * 1000)
                golden[kk] = golden.get(kk, 0) + 1
        got = {}
        for r in sink.committed:
            kk = (int(r["key"]), int(r["window_start"]))
            assert kk not in got, f"duplicate {kk}"
            got[kk] = int(r["count"])
        assert got == golden
        assert crashes["left"] == 0  # actually crashed twice


class TestRunnerLossRestartBudget:
    def test_runner_loss_respects_restart_budget(self):
        """Heartbeat-timeout failovers must consume the same restart
        budget as reported failures — with attempts exhausted, runner
        loss FAILs the job instead of restarting unboundedly (ref:
        ExecutionFailureHandler routing every failure through the
        RestartBackoffTimeStrategy)."""
        import time as _t

        from flink_tpu.runtime.coordinator import start_coordinator
        from flink_tpu.runtime.rpc import RpcClient

        srv = start_coordinator(Configuration({
            "heartbeat.timeout": 500,
            "restart-strategy.type": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 1,
            "restart-strategy.fixed-delay.delay": 10}))
        try:
            c = RpcClient("127.0.0.1", srv.port)
            c.call("register_runner", runner_id="r1", host="h1", n_devices=8)
            c.call("submit_job", job_id="j1")
            # burn the single allowed restart via a reported failure,
            # heartbeating first so the monitor can't race us to it
            c.call("heartbeat", runner_id="r1")
            assert c.call("report_failure", job_id="j1",
                          error="boom")["action"] == "restart"
            # job back to RUNNING for the next attempt (under the
            # endpoint lock — the monitor thread reads this state)
            with srv.endpoint._lock:
                srv.endpoint.jobs["j1"].state = "RUNNING"
            deadline = _t.time() + 5
            while _t.time() < deadline:
                if c.call("job_status", job_id="j1")["state"] == "FAILED":
                    break
                _t.sleep(0.05)
            assert c.call("job_status", job_id="j1")["state"] == "FAILED"
        finally:
            srv.close()


class TestSchedulerExecutionGraph:
    """Slot allocation + ExecutionGraph (ref: DefaultScheduler /
    ExecutionSlotAllocator / ExecutionGraph attempt bookkeeping)."""

    class _FakeRunnerGateway(RpcEndpoint):
        """Accepts run_job and records deployments (the
        TestingTaskExecutorGateway pattern)."""

        def __init__(self):
            self.deployed = []

        def rpc_run_job(self, job_id, entry, config=None, attempt=1, **kw):
            self.deployed.append((job_id, attempt))
            return {"accepted": True}

        def rpc_cancel_job(self, job_id):
            return {"ok": True}

    def _register(self, coord_client, gw_port, rid, n_devices):
        coord_client.call("register_runner", runner_id=rid,
                          host="127.0.0.1", n_devices=n_devices,
                          port=gw_port)

    def test_best_fit_slot_allocation(self):
        srv = start_coordinator(Configuration({}))
        gw_small = RpcServer(self._FakeRunnerGateway())
        gw_big = RpcServer(self._FakeRunnerGateway())
        try:
            c = RpcClient("127.0.0.1", srv.port)
            self._register(c, gw_small.port, "small", 2)
            self._register(c, gw_big.port, "big", 8)
            # a 2-device job best-fits the SMALL runner, leaving the big
            # one free for big jobs
            c.call("submit_job", job_id="j2", entry="x:y",
                   config={"cluster.mesh-devices": "2"})
            deadline = time.time() + 5
            while time.time() < deadline and not gw_small.endpoint.deployed:
                time.sleep(0.02)
            assert gw_small.endpoint.deployed == [("j2", 1)]
            assert not gw_big.endpoint.deployed
            # an 8-device job only fits the big runner
            c.call("submit_job", job_id="j8", entry="x:y",
                   config={"cluster.mesh-devices": "8"})
            deadline = time.time() + 5
            while time.time() < deadline and not gw_big.endpoint.deployed:
                time.sleep(0.02)
            assert gw_big.endpoint.deployed == [("j8", 1)]
            c.close()
        finally:
            srv.close(); gw_small.close(); gw_big.close()

    def test_waiting_for_resources_then_deploy_on_register(self):
        srv = start_coordinator(Configuration({}))
        gw = RpcServer(self._FakeRunnerGateway())
        try:
            c = RpcClient("127.0.0.1", srv.port)
            c.call("submit_job", job_id="j", entry="x:y",
                   config={"cluster.mesh-devices": "4"})
            deadline = time.time() + 5
            while time.time() < deadline:
                st = c.call("job_status", job_id="j")
                if st["state"] == "WAITING_FOR_RESOURCES":
                    break
                time.sleep(0.02)
            assert st["state"] == "WAITING_FOR_RESOURCES"
            # capacity arrives -> deploys
            self._register(c, gw.port, "r1", 8)
            deadline = time.time() + 5
            while time.time() < deadline and not gw.endpoint.deployed:
                time.sleep(0.02)
            assert gw.endpoint.deployed == [("j", 1)]
            deadline = time.time() + 5
            while time.time() < deadline:
                if c.call("job_status", job_id="j")["state"] == "RUNNING":
                    break
                time.sleep(0.02)
            assert c.call("job_status", job_id="j")["state"] == "RUNNING"
            c.close()
        finally:
            srv.close(); gw.close()

    def test_execution_graph_materializes_from_reported_plan(self):
        srv = start_coordinator(Configuration({}))
        gw = RpcServer(self._FakeRunnerGateway())
        try:
            c = RpcClient("127.0.0.1", srv.port)
            self._register(c, gw.port, "r1", 4)
            c.call("submit_job", job_id="j", entry="x:y",
                   config={"cluster.mesh-devices": "2"})
            deadline = time.time() + 5
            while time.time() < deadline and not gw.endpoint.deployed:
                time.sleep(0.02)
            # the runner reports its compiled stages
            c.call("report_plan", job_id="j",
                   stages=["source:bids", "window:hot", "sink:out"])
            eg = c.call("execution_graph", job_id="j")
            assert eg["found"]
            assert eg["stages"] == ["source:bids", "window:hot", "sink:out"]
            assert eg["parallelism"] == 2
            assert len(eg["vertices"]) == 6  # 3 stages x 2 subtasks
            states = {a["state"] for v in eg["vertices"]
                      for a in v["attempts"]}
            assert states <= {"RUNNING", "DEPLOYING"}
            runners = {a["runner"] for v in eg["vertices"]
                       for a in v["attempts"]}
            assert runners == {"r1"}
            c.close()
        finally:
            srv.close(); gw.close()

    def test_slots_released_on_finish(self):
        srv = start_coordinator(Configuration({}))
        gw = RpcServer(self._FakeRunnerGateway())
        try:
            c = RpcClient("127.0.0.1", srv.port)
            self._register(c, gw.port, "r1", 2)
            c.call("submit_job", job_id="a", entry="x:y",
                   config={"cluster.mesh-devices": "2"})
            deadline = time.time() + 5
            while time.time() < deadline and not gw.endpoint.deployed:
                time.sleep(0.02)
            # second 2-device job cannot fit until the first finishes
            c.call("submit_job", job_id="b", entry="x:y",
                   config={"cluster.mesh-devices": "2"})
            time.sleep(0.3)
            assert c.call("job_status",
                          job_id="b")["state"] == "WAITING_FOR_RESOURCES"
            c.call("finish_job", job_id="a")  # freed slots kick the queue
            deadline = time.time() + 5
            while time.time() < deadline:
                if ("b", 1) in gw.endpoint.deployed:
                    break
                time.sleep(0.02)
            assert ("b", 1) in gw.endpoint.deployed
            c.close()
        finally:
            srv.close(); gw.close()

    def test_mesh_devices_all_reserves_whole_runner(self):
        srv = start_coordinator(Configuration({}))
        gw = RpcServer(self._FakeRunnerGateway())
        try:
            c = RpcClient("127.0.0.1", srv.port)
            self._register(c, gw.port, "r1", 8)
            c.call("submit_job", job_id="whole", entry="x:y",
                   config={"cluster.mesh-devices": "all"})
            deadline = time.time() + 5
            while time.time() < deadline and not gw.endpoint.deployed:
                time.sleep(0.02)
            assert ("whole", 1) in gw.endpoint.deployed
            # runner is fully reserved: a 1-device job must now wait
            c.call("submit_job", job_id="one", entry="x:y",
                   config={"cluster.mesh-devices": "1"})
            time.sleep(0.3)
            assert c.call("job_status",
                          job_id="one")["state"] == "WAITING_FOR_RESOURCES"
            c.close()
        finally:
            srv.close(); gw.close()


class TestActiveProvisioning:
    """Provisioner seam + scale-in drain (ref: ActiveResourceManager,
    SURVEY §3.5)."""

    class _GW(RpcEndpoint):
        def __init__(self):
            self.deployed = []
            self.savepoints = []

        def rpc_run_job(self, job_id, entry, config=None, attempt=1, **kw):
            self.deployed.append((job_id, attempt))
            return {"accepted": True}

        def rpc_cancel_job(self, job_id, attempt=None):
            return {"ok": True}

        def rpc_trigger_savepoint(self, job_id, stop=False, token=None):
            self.savepoints.append((job_id, stop, token))
            return {"ok": True}

    def _register(self, c, port, rid, n):
        c.call("register_runner", runner_id=rid, host="127.0.0.1",
               n_devices=n, port=port)

    def test_unmet_demand_reaches_provisioner(self):
        from flink_tpu.runtime.provisioner import KubectlScaleProvisioner

        srv = start_coordinator(Configuration({}))
        prov = KubectlScaleProvisioner(dry_run=True)
        srv.endpoint.provisioner = prov
        try:
            c = RpcClient("127.0.0.1", srv.port)
            c.call("submit_job", job_id="jw", entry="x:y",
                   config={"cluster.mesh-devices": "4"})
            deadline = time.time() + 5
            while time.time() < deadline and not prov.commands:
                time.sleep(0.02)
            assert prov.commands, "provisioner never saw the demand"
            assert prov.commands[0][:2] == ["kubectl", "-n"]
            assert any("--replicas=" in a for a in prov.commands[0])
            c.close()
        finally:
            srv.close()

    def test_drain_moves_job_via_stop_with_savepoint(self):
        """Drain r1: its job stop-with-savepoints; on savepoint
        completion it redeploys on r2 (never back on the draining
        runner) restoring from the savepoint."""
        srv = start_coordinator(Configuration({}))
        gw1, gw2 = RpcServer(self._GW()), RpcServer(self._GW())
        try:
            c = RpcClient("127.0.0.1", srv.port)
            self._register(c, gw1.port, "r1", 4)
            c.call("submit_job", job_id="jd", entry="x:y",
                   config={"cluster.mesh-devices": "2"})
            deadline = time.time() + 5
            while time.time() < deadline and not gw1.endpoint.deployed:
                time.sleep(0.02)
            assert gw1.endpoint.deployed == [("jd", 1)]
            # second runner appears; drain the first
            self._register(c, gw2.port, "r2", 4)
            resp = c.call("drain_runner", runner_id="r1")
            assert resp["ok"] and resp["moving_jobs"] == ["jd"]
            deadline = time.time() + 5
            while time.time() < deadline and not gw1.endpoint.savepoints:
                time.sleep(0.02)
            jid, stop, token = gw1.endpoint.savepoints[0]
            assert (jid, stop) == ("jd", True) and token.startswith("drain-")
            # the runner reports the savepoint durable -> redeploy on r2
            c.call("savepoint_complete", job_id="jd",
                   path="/tmp/sp-jd", token=token)
            deadline = time.time() + 5
            while time.time() < deadline and not gw2.endpoint.deployed:
                time.sleep(0.02)
            assert gw2.endpoint.deployed == [("jd", 2)]
            assert not gw1.endpoint.deployed[1:], \
                "job must not redeploy on the draining runner"
            st = c.call("job_status", job_id="jd")
            assert st["state"] in ("RESTARTING", "RUNNING")
            # a drained runner receives no NEW jobs either
            c.call("submit_job", job_id="jn", entry="x:y",
                   config={"cluster.mesh-devices": "2"})
            deadline = time.time() + 5
            while time.time() < deadline and \
                    ("jn", 1) not in gw2.endpoint.deployed:
                time.sleep(0.02)
            assert ("jn", 1) in gw2.endpoint.deployed
            assert all(j != "jn" for j, _ in gw1.endpoint.deployed)
            c.close()
        finally:
            srv.close(); gw1.close(); gw2.close()


class TestRetryIdempotence:
    """The RpcClient transport retry re-delivers requests whose response
    was lost; the deploy/savepoint surfaces must absorb duplicates, not
    re-execute or fail them."""

    def test_run_job_duplicate_of_completed_push_not_reexecuted(self):
        from flink_tpu.runtime.runner import TaskRunner

        r = TaskRunner("127.0.0.1", 1, runner_id="idem")
        # the push ran to completion and its record was popped; the
        # token-keyed tombstone is what's left
        r._done_attempts[("j1", 3, "tok-abc")] = True
        resp = r.rpc_run_job(job_id="j1", entry="x:y", attempt=3,
                             deploy_token="tok-abc")
        assert resp == {"accepted": True, "runner_id": "idem",
                        "duplicate": True}
        assert "j1" not in r._jobs  # nothing re-spawned

    def test_fresh_submission_of_finished_job_id_still_runs(self):
        """A NEW submission reusing a finished job's id carries a fresh
        deploy token and must execute, not be swallowed by the old
        push's tombstone."""
        from flink_tpu.runtime.runner import TaskRunner

        r = TaskRunner("127.0.0.1", 1, runner_id="idem4")
        r._done_attempts[("nightly", 1, "tok-old")] = True
        resp = r.rpc_run_job(job_id="nightly", entry="x:y", attempt=1,
                             deploy_token="tok-new")
        assert resp["accepted"] and not resp.get("duplicate")
        assert "nightly" in r._jobs  # a real worker thread was spawned
        r._jobs["nightly"]["cancel"].set()
        r._jobs["nightly"]["thread"].join(timeout=30)

    def test_run_job_duplicate_of_running_attempt_accepted(self):
        import threading

        from flink_tpu.runtime.runner import SavepointRequest, TaskRunner

        r = TaskRunner("127.0.0.1", 1, runner_id="idem2")
        r._jobs["j2"] = {"cancel": threading.Event(), "attempt": 2,
                         "savepoint": SavepointRequest(r, "j2"),
                         "config": {}}
        resp = r.rpc_run_job(job_id="j2", entry="x:y", attempt=2)
        assert resp["accepted"] and resp.get("duplicate")
        # a STALE attempt is still rejected
        assert not r.rpc_run_job(job_id="j2", entry="x:y",
                                 attempt=1)["accepted"]

    def test_trigger_savepoint_duplicate_request_is_ok(self):
        import threading

        from flink_tpu.runtime.runner import SavepointRequest, TaskRunner

        r = TaskRunner("127.0.0.1", 1, runner_id="idem3")
        r._jobs["j3"] = {
            "cancel": threading.Event(), "attempt": 1,
            "savepoint": SavepointRequest(r, "j3"),
            "config": {"execution.checkpointing.interval": 1000},
        }
        assert r.rpc_trigger_savepoint("j3", stop=True,
                                       token="tok-1")["ok"]
        # same request re-delivered (transport retry): absorbed as ok
        dup = r.rpc_trigger_savepoint("j3", stop=True, token="tok-1")
        assert dup["ok"] and dup.get("duplicate")
        # a DIFFERENT request while one is pending: still refused
        assert not r.rpc_trigger_savepoint("j3", stop=False,
                                           token="tok-2")["ok"]


class TestFaultPlanConfigLifecycle:
    def test_empty_spec_uninstalls_config_plan(self):
        from flink_tpu import faults

        chaos = Configuration({"faults.inject": "rpc.client.send=drop x1",
                               "faults.seed": 5})
        clean = Configuration({})
        try:
            assert faults.install_from_config(chaos) is not None
            assert faults.active_plan() is not None
            # the next job's config has no faults.*: the plan must not
            # leak into it
            assert faults.install_from_config(clean) is None
            assert faults.active_plan() is None
        finally:
            faults.clear()

    def test_empty_spec_leaves_test_activated_plan_alone(self):
        from flink_tpu import faults

        plan = faults.FaultPlan(seed=1).rule("x.y", "raise")
        with plan.activate():
            assert faults.install_from_config(Configuration({})) is None
            assert faults.active_plan() is plan
