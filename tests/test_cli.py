"""CLI frontend (ref: flink-clients CliFrontend + CliFrontendTestBase
patterns: run/list/status/cancel/savepoint against a live cluster)."""
import json
import os
import time

import pytest

from flink_tpu.cli import main as cli_main
from flink_tpu.config import Configuration
from flink_tpu.runtime.coordinator import JobCoordinator
from flink_tpu.runtime.rpc import RpcServer

from test_runner_process import spawn_runner, wait_until


def cli(capsys, *argv):
    rc = cli_main(list(argv))
    out = capsys.readouterr().out.strip().splitlines()
    return rc, json.loads(out[-1]) if out else {}


class TestExitCodeContract:
    """The documented CI contract of both analysis CLIs (RULES.md):
    0 = clean at the threshold, 1 = findings at the threshold,
    2 = usage/path error — plus the shared Finding.to_dict JSON shape
    (one object per line under --json)."""

    FINDING_KEYS = {"rule", "severity", "message", "fix", "node",
                    "node_name", "file", "line"}

    def test_analyze_clean_is_0_findings_1_bad_path_2(self, tmp_path,
                                                      capsys):
        conf = tmp_path / "job.conf"
        conf.write_text("execution.checkpointing.interval: 500\n")
        assert cli_main(["analyze", str(conf)]) == 0
        conf.write_text("faults.inject: bogus.point=raise\n")
        assert cli_main(["analyze", str(conf)]) == 1
        assert cli_main(["analyze", str(tmp_path / "absent.conf")]) == 2
        assert cli_main(["analyze", "--entry", "no.such:build"]) == 2
        assert cli_main(["analyze", "--explain"]) == 2
        capsys.readouterr()

    def test_lint_clean_is_0_findings_1_bad_path_2(self, tmp_path,
                                                   capsys):
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        assert cli_main(["lint", str(clean)]) == 0
        dirty = tmp_path / "dirty.py"
        dirty.write_text(
            "import jax\n\n@jax.jit\ndef k(x):\n    return float(x)\n")
        assert cli_main(["lint", str(dirty)]) == 1
        assert cli_main(["lint", str(tmp_path / "absent.py")]) == 2
        capsys.readouterr()

    def test_lint_plane_filter_keeps_the_exit_contract(self, tmp_path,
                                                       capsys):
        """PR 19: `lint --plane NAME` keeps 0/1/2 — 0 when the named
        plane is clean (even if OTHER planes have findings), 1 when it
        has findings, 2 for an unknown plane name."""
        dirty = tmp_path / "dirty.py"
        # one tracer-plane finding + one metrics-plane finding
        dirty.write_text(
            "import jax\n\n@jax.jit\ndef k(x):\n    return float(x)\n\n\n"
            "def reg(group):\n    group.counter('camelCase')\n")
        assert cli_main(["lint", str(dirty)]) == 1
        assert cli_main(["lint", str(dirty), "--plane", "tracer"]) == 1
        assert cli_main(["lint", str(dirty), "--plane", "metrics"]) == 1
        # the locking plane is clean in this file: filtered exit is 0
        assert cli_main(["lint", str(dirty), "--plane", "locking"]) == 0
        capsys.readouterr()
        # unknown plane = usage error, naming the known planes
        assert cli_main(["lint", str(dirty), "--plane", "bogus"]) == 2
        err = capsys.readouterr().err
        assert "unknown lint plane" in err and "locking" in err
        # --json emits only the filtered plane's findings
        cli_main(["lint", str(dirty), "--plane", "tracer", "--json"])
        lines = capsys.readouterr().out.strip().splitlines()
        assert [json.loads(x)["rule"] for x in lines] == [
            "TRACER_HOST_CALL"]

    def test_both_clis_share_the_finding_json_shape(self, tmp_path,
                                                    capsys):
        conf = tmp_path / "job.conf"
        conf.write_text("faults.inject: bogus.point=raise\n")
        cli_main(["analyze", str(conf), "--json"])
        analyze_lines = capsys.readouterr().out.strip().splitlines()
        dirty = tmp_path / "dirty.py"
        dirty.write_text(
            "import jax\n\n@jax.jit\ndef k(x):\n    return float(x)\n")
        cli_main(["lint", str(dirty), "--json"])
        lint_lines = capsys.readouterr().out.strip().splitlines()
        for line in analyze_lines + lint_lines:
            f = json.loads(line)
            assert set(f) == self.FINDING_KEYS, f
            assert f["severity"] in ("error", "warn")

    def test_fsck_clean_is_0_findings_1_bad_path_2(self, tmp_path,
                                                   capsys):
        """The storage fsck joins the CI exit contract (PR 14): 0 =
        clean, 1 = findings remain, 2 = usage/path error — with one
        JSON finding object per line under --json."""
        import numpy as np

        from flink_tpu.log.topic import TopicAppender

        topic = str(tmp_path / "topic")
        ap = TopicAppender(topic, partitions=1, segment_records=4)
        b = {"k": np.arange(4, dtype=np.int64),
             "v": np.arange(4, dtype=np.float64)}
        ap.stage(1, {0: [b]})
        ap.commit(1)
        assert cli_main(["fsck", topic]) == 0
        # seed a finding: tmp debris (back-dated past --repair's
        # live-stage grace window)
        debris = os.path.join(topic, "p0", "seg-x.colb.tmp")
        with open(debris, "wb") as f:
            f.write(b"torn")
        old = time.time() - 3600
        os.utime(debris, (old, old))
        assert cli_main(["fsck", topic]) == 1
        capsys.readouterr()
        cli_main(["fsck", topic, "--json"])
        lines = capsys.readouterr().out.strip().splitlines()
        for line in lines:
            f = json.loads(line)
            assert {"rule", "severity", "path", "message",
                    "repairable", "repaired"} <= set(f)
        # repair sweeps it; the topic is clean again
        assert cli_main(["fsck", topic, "--repair"]) == 0
        assert cli_main(["fsck", topic]) == 0
        assert cli_main(["fsck", str(tmp_path / "absent")]) == 2
        capsys.readouterr()

    def test_fsck_lsm_store_exit_contract(self, tmp_path, capsys):
        """ISSUE 17: the lsm state tier joins the same fsck contract —
        a healthy store is 0, seeded debris (orphan run + tmp, both
        back-dated past the live-seal grace) is 1, --repair sweeps the
        debris back to 0, and a run the manifest promises but the disk
        lost stays a non-repairable 1."""
        import numpy as np

        from flink_tpu.state.lsm import LsmSpillStore

        class _Agg:
            sum_width = max_width = min_width = 1

            def lift_masked(self, data, valid):
                v = np.asarray(data["v"], np.float32)[:, None]
                return v, v, v

        store_dir = str(tmp_path / "store")
        store = LsmSpillStore(_Agg(), store_dir=store_dir,
                              memory_budget_bytes=0, num_shards=8,
                              compact_min_runs=99)
        store.absorb(np.arange(8, dtype=np.int64),
                     np.zeros(8, dtype=np.int64),
                     {"v": np.arange(8, dtype=np.float32)})
        assert cli_main(["fsck", store_dir]) == 0
        # seed repairable debris: an unreferenced run + seal tmp
        old = time.time() - 3600
        for name in ("run-000099.seg", "run-000100.seg.tmp"):
            p = os.path.join(store_dir, name)
            with open(p, "wb") as f:
                f.write(b"debris")
            os.utime(p, (old, old))
        assert cli_main(["fsck", store_dir]) == 1
        capsys.readouterr()
        cli_main(["fsck", store_dir, "--json"])
        for line in capsys.readouterr().out.strip().splitlines():
            f = json.loads(line)
            assert {"rule", "severity", "path", "message",
                    "repairable", "repaired"} <= set(f)
        assert cli_main(["fsck", store_dir, "--repair"]) == 0
        assert cli_main(["fsck", store_dir]) == 0
        # a manifest-promised run the disk lost is loud and NOT
        # repairable — fsck must never "fix" state loss by forgetting
        live_run = store._runs[0]["name"]
        os.unlink(os.path.join(store_dir, live_run))
        assert cli_main(["fsck", store_dir]) == 1
        assert cli_main(["fsck", store_dir, "--repair"]) == 1
        capsys.readouterr()


class TestSessionHaCli:
    """ISSUE 11 satellite: the session CLI resolves the leader through
    --ha-dir (runtime/ha.leader_address) and RE-resolves on connection
    failure with a bounded retry budget — exit-code contract 0/1/2
    preserved: 0 = ok, 1 = refused / no reachable leader (clean error,
    never a traceback), 2 = usage error."""

    def _lease(self, d, address, epoch=1):
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "leader.lease"), "w") as f:
            json.dump({"leader_id": "L", "address": address,
                       "epoch": epoch, "claimed_at": time.time()}, f)

    def test_no_leader_exits_1_cleanly(self, tmp_path, capsys,
                                       monkeypatch):
        import flink_tpu.cli as cli_mod

        monkeypatch.setattr(cli_mod, "_HA_RETRIES", 3)
        monkeypatch.setattr(cli_mod, "_HA_RETRY_DELAY_S", 0.05)
        rc = cli_main(["session", "list", "--ha-dir",
                       str(tmp_path / "empty")])
        err = capsys.readouterr().err
        assert rc == 1
        assert "error:" in err and "Traceback" not in err

    def test_lease_resolution_without_session_flag(self, tmp_path,
                                                   capsys):
        from flink_tpu.config import Configuration
        from flink_tpu.runtime.session import LocalSessionCluster

        with LocalSessionCluster(Configuration(
                {"session.autoscale": False})) as c:
            self._lease(str(tmp_path), c.address)
            rc, out = cli(capsys, "session", "list",
                          "--ha-dir", str(tmp_path))
            assert rc == 0 and out["jobs"] == []
            # `session info` prints the leadership view
            rc, out = cli(capsys, "session", "info",
                          "--ha-dir", str(tmp_path))
            assert rc == 0
            assert "leader_epoch" in out and "takeovers" in out

    def test_refused_connection_re_resolves_mid_retry(
            self, tmp_path, capsys, monkeypatch):
        """The failover flow a client sees: the lease points at a DEAD
        leader; the new leader's lease lands DURING the retry budget —
        the call re-resolves and succeeds (exit 0)."""
        import socket
        import threading

        import flink_tpu.cli as cli_mod
        from flink_tpu.config import Configuration
        from flink_tpu.runtime.session import LocalSessionCluster

        monkeypatch.setattr(cli_mod, "_HA_RETRIES", 30)
        monkeypatch.setattr(cli_mod, "_HA_RETRY_DELAY_S", 0.1)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        self._lease(str(tmp_path), f"127.0.0.1:{dead_port}", epoch=1)
        with LocalSessionCluster(Configuration(
                {"session.autoscale": False})) as c:
            def takeover():
                time.sleep(0.5)
                self._lease(str(tmp_path), c.address, epoch=2)

            threading.Thread(target=takeover, daemon=True).start()
            rc, out = cli(capsys, "session", "list",
                          "--ha-dir", str(tmp_path))
            assert rc == 0 and out["jobs"] == []

    def test_standby_without_ha_dir_exits_2(self, capsys):
        assert cli_main(["session", "start", "--standby"]) == 2
        assert "standby" in capsys.readouterr().err

    def test_neither_session_nor_ha_dir_exits_2(self, capsys):
        with pytest.raises(SystemExit) as e:
            cli_main(["session", "list"])
        assert e.value.code == 2
        assert "--ha-dir" in capsys.readouterr().err


class TestLogCli:
    """ISSUE 9: `flink_tpu log TOPIC_DIR` prints the message-bus view
    — compaction generation, retention floor, active leases with
    epochs, per-consumer-group committed offsets — and honors the
    0/1/2 exit-code contract (0 = ok, 1 = topic/maintenance error,
    2 = usage/path error)."""

    def _seed_topic(self, tmp_path):
        import numpy as np

        from flink_tpu.log import (ConsumerGroups, LeaseManager,
                                   TopicAppender)

        topic = str(tmp_path / "topic")
        ap = TopicAppender(topic, 2, segment_records=8, key_field="k")
        for cid in (1, 2, 3):
            batch = {p: [{"k": np.arange(8, dtype=np.int64) % 4,
                          "ts": np.arange(8, dtype=np.int64) + cid}]
                     for p in range(2)}
            assert ap.stage(cid, batch)
            ap.commit(cid)
        ConsumerGroups.commit(topic, "readers", {0: 24, 1: 24})
        lease = LeaseManager(topic, "prod-a", [0], ttl_ms=60_000)
        lease.acquire()
        return topic

    def test_describe_prints_bus_state_exit_0(self, tmp_path, capsys):
        topic = self._seed_topic(tmp_path)
        rc, out = cli(capsys, "log", topic)
        assert rc == 0
        assert out["compaction_generation"] == 0
        assert out["retention_floor"] == {"0": 0, "1": 0}
        assert out["leases"]["0"]["owner"] == "prod-a"
        assert out["leases"]["0"]["epoch"] == 1
        assert out["groups"] == {"readers": {"0": 24, "1": 24}}
        assert out["key_field"] == "k"

    def test_compact_flag_runs_a_pass_and_describes(self, tmp_path,
                                                    capsys):
        topic = self._seed_topic(tmp_path)
        rc, out = cli(capsys, "log", topic, "--compact")
        assert rc == 0
        assert out["compaction_generation"] == 1
        assert out["compaction"]["gen"] == 1
        # latest-per-key survivors only, committed end preserved
        assert out["compaction"]["partitions"]["0"]["rows_out"] == 4
        assert out["committed_offsets"] == {"0": 24, "1": 24}
        assert out["compacted_end"] == {"0": 24, "1": 24}

    def test_retain_flag_advances_the_floor(self, tmp_path, capsys):
        topic = self._seed_topic(tmp_path)
        rc, out = cli(capsys, "log", topic, "--retain",
                      "--conf", "log.retention.ms=1",
                      "--conf", "log.retention.ts-field=ts")
        assert rc == 0
        assert out["retention"]["gen"] == 1
        assert out["retention_floor"] == {"0": 24, "1": 24}

    def test_missing_topic_exits_2(self, tmp_path, capsys):
        assert cli_main(["log", str(tmp_path / "absent")]) == 2
        err = capsys.readouterr().err
        assert "no such log topic" in err

    def test_maintenance_error_exits_1(self, tmp_path, capsys):
        import numpy as np

        from flink_tpu.log import TopicAppender

        # a topic created WITHOUT a key_field: --compact has no key
        # column to compact by — a maintenance error, not a path error
        topic = str(tmp_path / "nokey")
        ap = TopicAppender(topic, 1, segment_records=8)
        for cid in (1, 2):
            assert ap.stage(cid, {0: [{"k": np.arange(
                8, dtype=np.int64)}]})
            ap.commit(cid)
        assert cli_main(["log", topic, "--compact"]) == 1
        assert "key" in capsys.readouterr().err

    def test_live_cleaner_lease_refuses_manual_maintenance(
            self, tmp_path, capsys):
        """PR 18 exit contract: while a live cleaner service owns the
        topic (cleaner.lease unexpired, unreleased), a manual
        --compact/--retain exits 1 instead of fighting the service
        for the maintenance lock; a released lease lifts the gate."""
        topic = self._seed_topic(tmp_path)
        now = int(time.time() * 1000)
        lease = os.path.join(topic, "cleaner.lease")
        with open(lease, "w") as f:
            json.dump({"owner": "cleaner-svc", "epoch": 1,
                       "pid": os.getpid(), "acquired_ms": now,
                       "deadline_ms": now + 60_000}, f)
        assert cli_main(["log", topic, "--compact"]) == 1
        err = capsys.readouterr().err
        assert "cleaner" in err and "cleaner-svc" in err
        # plain describe (no maintenance) still works and surfaces it
        rc, out = cli(capsys, "log", topic)
        assert rc == 0
        assert out["cleaner"]["live_owner"] == "cleaner-svc"
        assert out["cleaner"]["lease"]["epoch"] == 1
        # released lease: the manual pass proceeds
        with open(lease, "w") as f:
            json.dump({"owner": "cleaner-svc", "epoch": 1,
                       "pid": os.getpid(), "acquired_ms": now,
                       "deadline_ms": now + 60_000,
                       "released": True}, f)
        rc, out = cli(capsys, "log", topic, "--compact")
        assert rc == 0
        assert out["compaction_generation"] == 1

    def test_describe_surfaces_group_generations(self, tmp_path,
                                                 capsys):
        from flink_tpu.log import ConsumerGroups

        topic = self._seed_topic(tmp_path)
        gen, _ix, _n = ConsumerGroups.join(topic, "dyn", "m1")
        ConsumerGroups.join(topic, "dyn", "m2")
        rc, out = cli(capsys, "log", topic)
        assert rc == 0
        # static group "readers" (no manifest) is absent; the dynamic
        # group reports its current membership generation
        assert out["group_generations"] == {"dyn": 2}


class TestObjstoreCliChain:
    """PR 18 tier-1 CLI smoke: two ``run --local`` jobs chained
    through an ``objstore://`` topic — every commit marker, lease,
    group offset, and manifest rides the conditional-put driver — with
    the background cleaner enabled on the producing job (lease
    acquired, passes published, released with the job)."""

    def test_chain_with_cleaner_enabled(self, tmp_path, capsys):
        import runner_job_log_chain as jobs

        log_dir = "objstore://" + str(tmp_path / "logroot")
        sink_dir = str(tmp_path / "sink")
        n = 5
        rc, out = cli(
            capsys, "run", "--local",
            "--entry", "runner_job_log_chain:produce",
            "--job-id", "obj-chain-a",
            "--conf", f"log.dir={log_dir}",
            "--conf", "log.partitions=2",
            "--conf", "log.cleaner.enabled=true",
            "--conf", "log.cleaner.interval-ms=10",
            "--conf", f"test.n-batches={n}")
        assert rc == 0 and out["state"] == "FINISHED"
        assert out["records_in"] == n * jobs.BATCH

        # the driver-owned cleaner ran under its lease and released
        # it at job teardown — no live owner survives the process
        from flink_tpu.log.cleaner import (cleaner_status,
                                           live_cleaner_owner,
                                           read_cleaner_lease)

        topic = os.path.join(log_dir, jobs.TOPIC)
        status = cleaner_status(topic)
        assert status is not None and status["passes"] >= 1
        assert live_cleaner_owner(topic) is None
        assert read_cleaner_lease(topic)["released"]

        rc, out = cli(
            capsys, "run", "--local",
            "--entry", "runner_job_log_chain:consume",
            "--job-id", "obj-chain-b",
            "--conf", f"log.dir={log_dir}",
            "--conf", f"test.sink-dir={sink_dir}",
            "--conf", "state.num-key-shards=8",
            "--conf", "state.slots-per-shard=64")
        assert rc == 0 and out["state"] == "FINISHED"
        assert out["records_in"] == n * jobs.BATCH

        # the log CLI reads the object-store topic and surfaces the
        # cleaner lifecycle next to the bus state
        rc, info = cli(capsys, "log", topic)
        assert rc == 0
        assert info["partitions"] == 2
        assert info["committed_records"] == n * jobs.BATCH
        assert info["cleaner"]["status"]["passes"] >= 1
        assert info["cleaner"]["live_owner"] is None

        # consumer output diffs clean against the independent golden
        got = jobs.read_committed_counts(sink_dir)
        assert got == jobs.expected_counts(n) and len(got) > 0

        # and fsck blesses the whole topic through the driver
        assert cli_main(["fsck", topic]) == 0
        capsys.readouterr()


class TestLocalRun:
    def test_run_local_executes_entry(self, tmp_path, capsys):
        import runner_job

        sink_dir = str(tmp_path / "sink")
        rc, out = cli(
            capsys, "run", "--local", "--entry", "runner_job:build",
            "--job-id", "local-1",
            "--conf", "test.n-batches=5",
            "--conf", f"test.sink-dir={sink_dir}",
            "--conf", "state.num-key-shards=4",
            "--conf", "state.slots-per-shard=16",
            "--conf", "pipeline.microbatch-size=64")
        assert rc == 0
        assert out["state"] == "FINISHED"
        assert out["records_in"] == 5 * 64

    def test_conf_parsing_rejects_bad_pair(self):
        with pytest.raises(SystemExit):
            cli_main(["run", "--local", "--entry", "x:y", "--conf", "oops"])


class TestClusterFlow:
    def test_run_status_list_savepoint_cancel(self, tmp_path, capsys):
        coord = JobCoordinator(Configuration({
            "heartbeat.interval": "200ms",
            "heartbeat.timeout": "2000ms",
        }))
        srv = RpcServer(coord)
        addr = f"127.0.0.1:{srv.port}"
        proc = None
        try:
            proc = spawn_runner(srv.port, "cli-r1")
            wait_until(lambda: len(coord.runners) == 1, 90,
                       what="runner registered")

            sink_dir = str(tmp_path / "sink")
            chk_dir = str(tmp_path / "chk")
            rc, out = cli(
                capsys, "run", "--coordinator", addr,
                "--entry", "runner_job:build", "--job-id", "cli-job",
                "--conf", "test.n-batches=60",
                "--conf", "test.batch-sleep-ms=50",
                "--conf", f"test.sink-dir={sink_dir}",
                "--conf", f"execution.checkpointing.dir={chk_dir}",
                "--conf", "execution.checkpointing.interval=200",
                "--conf", "state.num-key-shards=4",
                "--conf", "state.slots-per-shard=16",
                "--conf", "pipeline.microbatch-size=64")
            assert rc == 0 and out["job_id"] == "cli-job"

            rc, out = cli(capsys, "status", "--coordinator", addr, "cli-job")
            assert out["state"] in ("RUNNING", "RESTARTING")

            rc, out = cli(capsys, "list", "--coordinator", addr)
            assert [j["job_id"] for j in out["jobs"]] == ["cli-job"]

            rc, out = cli(capsys, "runners", "--coordinator", addr)
            assert len(out) == 1 and "cli-r1" in out

            # savepoint mid-run lands as a savepoint-N directory
            wait_until(lambda: os.path.isdir(os.path.join(chk_dir, "cli-job")),
                       60, what="first checkpoint")

            def try_savepoint():
                rc2, out2 = cli(capsys, "savepoint", "--coordinator",
                                addr, "cli-job")
                return rc2 == 0 and out2.get("ok")

            wait_until(try_savepoint, 30, interval=0.5,
                       what="savepoint accepted")
            job_dir = os.path.join(chk_dir, "cli-job")
            wait_until(
                lambda: any(d.startswith("savepoint-")
                            for d in os.listdir(job_dir)),
                30, what="savepoint directory")
            # the runner reports the completed path; status surfaces it
            wait_until(
                lambda: cli(capsys, "status", "--coordinator", addr,
                            "cli-job")[1].get("last_savepoint"),
                30, what="savepoint path in status")

            # a job WITHOUT checkpoint storage must reject savepoints
            # loudly instead of acking a savepoint that can never land
            rc, out = cli(
                capsys, "run", "--coordinator", addr,
                "--entry", "runner_job:build", "--job-id", "no-chk",
                "--conf", "test.n-batches=40",
                "--conf", "test.batch-sleep-ms=50",
                "--conf", f"test.sink-dir={sink_dir}2",
                "--conf", "state.num-key-shards=4",
                "--conf", "state.slots-per-shard=16",
                "--conf", "pipeline.microbatch-size=64")

            def rejected():
                rc2, out2 = cli(capsys, "savepoint", "--coordinator",
                                addr, "no-chk")
                # dispatched ack is ok=True; the rejection is visible as
                # status never gaining a savepoint — but the RUNNER-side
                # validation makes the next poll report no path; verify
                # the job reports none after a grace period
                return rc2 == 0
            time.sleep(1.0)
            rejected()
            rc, out = cli(capsys, "status", "--coordinator", addr, "no-chk")
            assert out.get("last_savepoint") is None
            cli(capsys, "cancel", "--coordinator", addr, "no-chk")

            rc, out = cli(capsys, "cancel", "--coordinator", addr, "cli-job")
            assert out["ok"]
            wait_until(lambda: coord.rpc_job_status("cli-job")["state"]
                       == "CANCELED", 30, what="cancel acknowledged")
        finally:
            if proc is not None:
                proc.kill()
                proc.wait(timeout=10)
            srv.close()
            coord.close()


class TestRescaleCli:
    """ISSUE 16 satellite: `flink_tpu rescale JOB --devices N
    [--processes M]` + `session rescale`, same 0/1/2 exit contract as
    every other verb: 0 = dispatched, 1 = the coordinator refused
    (divisibility / unknown job / not running), 2 = usage error."""

    def _coord(self):
        class Gw:
            def __init__(self):
                self.deployed = []
                self.savepoints = []

            def rpc_run_job(self, job_id, entry, config=None, attempt=1,
                            py_blobs=None, **kw):
                self.deployed.append((job_id, attempt))
                return {"accepted": True}

            def rpc_cancel_job(self, job_id, attempt=None, **kw):
                return {"ok": True}

            def rpc_trigger_savepoint(self, job_id, stop=False,
                                      token=None, **kw):
                self.savepoints.append((job_id, stop, token))
                return {"ok": True}

        gw = Gw()
        gwsrv = RpcServer(gw)
        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        coord.rpc_register_runner("r1", "127.0.0.1", 8, port=gwsrv.port)
        coord.rpc_register_runner("r2", "127.0.0.1", 8, port=gwsrv.port)
        return gw, gwsrv, coord, srv

    def test_dispatched_0_refused_1_usage_2(self, capsys):
        gw, gwsrv, coord, srv = self._coord()
        addr = f"127.0.0.1:{srv.port}"
        try:
            coord.rpc_submit_job(
                "j", entry="x:y",
                config={"cluster.mesh-devices": "2",
                        "state.num-key-shards": "8"})
            wait_until(lambda: gw.deployed, what="deploy")

            # 1: refused — 8 shards are not divisible by 3 processes
            # (key-group ranges could not be contiguous)
            rc, out = cli(capsys, "rescale", "--coordinator", addr,
                          "--devices", "1", "--processes", "3", "j")
            assert rc == 1 and not out["ok"]
            assert "divisible" in out["reason"]

            # 1: refused — unknown job
            rc, out = cli(capsys, "rescale", "--coordinator", addr,
                          "--devices", "2", "ghost")
            assert rc == 1 and not out["ok"]

            # 0: a process rescale dispatches (8 shards / 2 procs = 4,
            # 4 % 4 devices == 0) and the wire carried --processes
            rc, out = cli(capsys, "rescale", "--coordinator", addr,
                          "--devices", "4", "--processes", "2", "j")
            assert rc == 0 and out["ok"] and out["processes"] == 2
            wait_until(lambda: gw.savepoints, what="stop-with-savepoint")

            # 2: usage — --devices is required
            with pytest.raises(SystemExit) as e:
                cli_main(["rescale", "--coordinator", addr, "j"])
            assert e.value.code == 2
        finally:
            srv.close(); gwsrv.close(); coord.close()

    def test_session_rescale_same_contract(self, tmp_path, capsys):
        from flink_tpu.runtime.session import LocalSessionCluster

        with LocalSessionCluster(Configuration(
                {"session.autoscale": False})) as c:
            sink = str(tmp_path / "sink")
            r = c.submit("runner_job:build", job_id="sj", config={
                "test.n-batches": "60", "test.batch-sleep-ms": "100",
                f"test.sink-dir": sink,
                "execution.checkpointing.dir": str(tmp_path / "chk"),
                "execution.checkpointing.interval": "300ms",
                "state.num-key-shards": "4",
                "state.slots-per-shard": "16",
                "pipeline.microbatch-size": "64"})
            assert r.get("admitted")
            wait_until(lambda: c.dispatcher.jobs["sj"].state == "RUNNING",
                       60, what="session job running")
            # 0: dispatched against the session leader
            rc, out = cli(capsys, "session", "rescale",
                          "--session", c.address, "--devices", "1", "sj")
            assert rc == 0 and out["ok"]
            # 1: refused — unknown job
            rc, out = cli(capsys, "session", "rescale",
                          "--session", c.address, "--devices", "1",
                          "ghost")
            assert rc == 1 and not out["ok"]
            # 2: usage — --devices required
            with pytest.raises(SystemExit) as e:
                cli_main(["session", "rescale", "--session", c.address,
                          "sj"])
            assert e.value.code == 2
            c.dispatcher.rpc_cancel_job("sj")
