"""Checkpoint compression + the incremental toggle (ref:
execution.checkpointing.snapshot-compression and the incremental
config; SnapshotCompressionTest patterns)."""
import json
import os

import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.checkpoint.storage import FsCheckpointStorage
from flink_tpu.config import Configuration
from flink_tpu.time.watermarks import WatermarkStrategy


def run_job(tmp_path, extra=None, restore=False):
    conf = {
        "state.num-key-shards": 4, "state.slots-per-shard": 32,
        "pipeline.microbatch-size": 64,
        "execution.checkpointing.dir": str(tmp_path),
        "execution.checkpointing.interval": 1,
    }
    if restore:
        conf["execution.checkpointing.restore"] = "latest"
    conf.update(extra or {})

    def gen(split, i):
        if i >= 4:
            return None
        rng = np.random.default_rng(i)
        return ({"k": rng.integers(0, 8, 64).astype(np.int64)},
                np.sort(rng.integers(i * 500, i * 500 + 900, 64)).astype(np.int64))

    env = StreamExecutionEnvironment(Configuration(conf))
    sink = CollectSink()
    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(400))
     .key_by("k").window(TumblingEventTimeWindows.of(1_000))
     .count().add_sink(sink))
    env.execute("comp-job")
    return sink


class TestCompression:
    def test_zlib_checkpoints_restore_and_shrink(self, tmp_path):
        plain_dir = tmp_path / "plain"
        comp_dir = tmp_path / "comp"
        run_job(plain_dir)
        run_job(comp_dir,
                {"execution.checkpointing.compression": "zlib"})

        def latest_size(d):
            st = FsCheckpointStorage(str(d), "comp-job")
            h = st.latest()
            return h, sum(
                os.path.getsize(os.path.join(h.path, f))
                for f in os.listdir(h.path))

        hp, sp = latest_size(plain_dir)
        hc, sc = latest_size(comp_dir)
        assert sc < sp  # dense zero-heavy pane state compresses well
        mf = json.load(open(os.path.join(hc.path, "MANIFEST.json")))
        assert mf["compression"] == "zlib"
        # compressed checkpoints restore transparently (self-described)
        s2 = run_job(comp_dir,
                     {"execution.checkpointing.compression": "zlib"},
                     restore=True)
        assert s2 is not None  # restore path exercised without error

    def test_bad_compression_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="compression"):
            FsCheckpointStorage(str(tmp_path), "j", compression="lz9")

    def test_compression_change_across_restart_stays_readable(self, tmp_path):
        """Restore an uncompressed checkpoint into a zlib-configured
        run: blob reuse must be refused (a hardlinked blob keeps its
        original encoding), so every subsequent checkpoint re-serializes
        and stays self-consistently decodable (regression: reuse used to
        link raw blobs under a zlib manifest — undecodable)."""
        run_job(tmp_path)  # compression: none
        s2 = run_job(tmp_path,
                     {"execution.checkpointing.compression": "zlib"},
                     restore=True)
        st = FsCheckpointStorage(str(tmp_path), "comp-job",
                                 compression="zlib")
        # every retained checkpoint loads cleanly, whatever its era
        for h in st.list_complete():
            payload = FsCheckpointStorage.load(h)
            assert "operators" in payload or "checkpoint_id" in payload

    def test_incremental_toggle_off_reserializes(self, tmp_path):
        """With incremental=False every checkpoint's op blob is a fresh
        inode — no hardlink reuse."""
        run_job(tmp_path,
                {"execution.checkpointing.incremental": False})
        st = FsCheckpointStorage(str(tmp_path), "comp-job")
        chks = st.list_complete()
        inodes = set()
        for h in chks:
            for f in os.listdir(h.path):
                if f.startswith("op-"):
                    inodes.add(os.stat(os.path.join(h.path, f)).st_ino)
        # all distinct: len(inodes) == number of op files
        n_op_files = sum(
            1 for h in chks for f in os.listdir(h.path)
            if f.startswith("op-"))
        assert len(inodes) == n_op_files
