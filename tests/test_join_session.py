"""Window join (Q8 shape) and session window tests — harness-style
(ref: WindowOperatorTest patterns) plus fluent-API e2e."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.windowing import EventTimeSessionWindows, TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.ops import aggregates
from flink_tpu.ops.join import WindowJoinOperator
from flink_tpu.ops.session import SessionOperator
from flink_tpu.time.watermarks import WatermarkStrategy


def small_env():
    conf = Configuration({
        "state.num-key-shards": 8,
        "state.slots-per-shard": 64,
        "pipeline.microbatch-size": 256,
    })
    return StreamExecutionEnvironment.get_execution_environment(conf)


class TestWindowJoinOperator:
    def test_basic_equi_join(self):
        op = WindowJoinOperator(
            TumblingEventTimeWindows.of(1000),
            left_fields=("price",), right_fields=("name",),
            num_shards=8, slots_per_shard=16, mode="aggregate")
        # window [0,1000): keys 1,2 left; keys 2,3 right → join on 2
        op.process_left(np.array([1, 2]), np.array([100, 200]),
                        {"price": np.array([10.0, 20.0], np.float32)})
        op.process_right(np.array([2, 3]), np.array([300, 400]),
                         {"name": np.array([7.0, 8.0], np.float32)})
        f = op.advance_watermark(1000)
        assert list(f["key"]) == [2]
        assert list(f["left_price"]) == [20.0]
        assert list(f["right_name"]) == [7.0]
        assert list(f["left_count"]) == [1] and list(f["right_count"]) == [1]

    def test_join_counts_multiplicity(self):
        op = WindowJoinOperator(TumblingEventTimeWindows.of(1000),
                                num_shards=8, slots_per_shard=16,
                                mode="aggregate")
        op.process_left(np.array([1, 1, 1]), np.array([10, 20, 30]), {})
        op.process_right(np.array([1, 1]), np.array([40, 50]), {})
        f = op.advance_watermark(1000)
        assert list(f["key"]) == [1]
        assert list(f["left_count"]) == [3]
        assert list(f["right_count"]) == [2]

    def test_join_no_match_no_output(self):
        op = WindowJoinOperator(TumblingEventTimeWindows.of(1000),
                                num_shards=8, slots_per_shard=16)
        op.process_left(np.array([1]), np.array([10]), {})
        op.process_right(np.array([2]), np.array([20]), {})
        f = op.advance_watermark(1000)
        assert len(f["key"]) == 0

    def test_join_windows_isolated(self):
        op = WindowJoinOperator(TumblingEventTimeWindows.of(1000),
                                num_shards=8, slots_per_shard=16)
        op.process_left(np.array([1]), np.array([500]), {})    # window 0
        op.process_right(np.array([1]), np.array([1500]), {})  # window 1
        f = op.advance_watermark(3000)
        assert len(f["key"]) == 0  # same key, different windows

    def test_join_e2e_fluent(self):
        env = small_env()
        persons = env.from_collection(
            {"person": np.array([1, 2, 3], np.int64),
             "age": np.array([30.0, 40.0, 50.0], np.float32)},
            np.array([100, 200, 1500], np.int64))
        auctions = env.from_collection(
            {"seller": np.array([1, 1, 3], np.int64),
             "reserve": np.array([5.0, 7.0, 9.0], np.float32)},
            np.array([150, 250, 2500], np.int64))
        sink = (
            persons.join(auctions)
            .where("person").equal_to("seller")
            .window(TumblingEventTimeWindows.of(1000))
            .apply(left_fields=("age",), right_fields=("reserve",))
            .collect()
        )
        env.execute()
        rows = sorted((int(r["key"]), int(r["window_start"]),
                       float(r["left_age"]), float(r["right_reserve"]))
                      for r in sink.rows)
        # pairs mode (default): person 1 (left) x 2 auctions -> TWO rows
        # person 3: left at window 1, right at window 2 -> no join
        assert rows == [(1, 0, 30.0, 5.0), (1, 0, 30.0, 7.0)]


class TestWindowJoinPairs:
    """Exact cross-product semantics (the reference's JoinFunction
    contract): one output row per matching left x right pair."""

    def test_multi_auction_seller_emits_all_pairs(self):
        """The round-2 weakness: multi-auction sellers collapsed into
        one max-carried row. Pairs mode must emit every pair."""
        op = WindowJoinOperator(
            TumblingEventTimeWindows.of(1000),
            left_fields=("age",), right_fields=("reserve",),
            num_shards=8, slots_per_shard=16)
        op.process_left(np.array([1]), np.array([100]),
                        {"age": np.array([30.0], np.float32)})
        op.process_right(np.array([1, 1, 1]), np.array([200, 300, 400]),
                         {"reserve": np.array([5.0, 7.0, 9.0], np.float32)})
        f = op.advance_watermark(1000)
        rows = sorted((int(k), float(a), float(r)) for k, a, r in
                      zip(f["key"], f["left_age"], f["right_reserve"]))
        assert rows == [(1, 30.0, 5.0), (1, 30.0, 7.0), (1, 30.0, 9.0)]

    def test_m_by_n_cross_product(self):
        op = WindowJoinOperator(TumblingEventTimeWindows.of(1000),
                                left_fields=("a",), right_fields=("b",),
                                num_shards=8, slots_per_shard=16)
        op.process_left(np.array([7, 7, 9]), np.array([10, 20, 30]),
                        {"a": np.array([1.0, 2.0, 3.0], np.float32)})
        op.process_right(np.array([7, 7, 7, 9]), np.array([40, 50, 60, 70]),
                         {"b": np.array([10.0, 20.0, 30.0, 40.0], np.float32)})
        f = op.advance_watermark(1000)
        got = sorted((int(k), float(a), float(b)) for k, a, b in
                     zip(f["key"], f["left_a"], f["right_b"]))
        want = sorted([(7, a, b) for a in (1.0, 2.0) for b in (10.0, 20.0, 30.0)]
                      + [(9, 3.0, 40.0)])
        assert got == want

    def test_late_record_refires_full_pair_set(self):
        op = WindowJoinOperator(TumblingEventTimeWindows.of(1000),
                                right_fields=("v",),
                                allowed_lateness_ms=5000,
                                num_shards=8, slots_per_shard=16)
        op.process_left(np.array([1]), np.array([100]), {})
        op.process_right(np.array([1]), np.array([200]),
                         {"v": np.array([5.0], np.float32)})
        f = op.advance_watermark(1500)
        assert len(f["key"]) == 1
        # late right-side row within lateness -> window refires with the
        # UPDATED full pair set (now 2 pairs)
        op.process_right(np.array([1]), np.array([300]),
                         {"v": np.array([9.0], np.float32)})
        f = op.advance_watermark(1500)
        assert sorted(float(v) for v in f["right_v"]) == [5.0, 9.0]

    def test_snapshot_restore_roundtrip(self):
        def mk():
            return WindowJoinOperator(
                TumblingEventTimeWindows.of(1000), left_fields=("a",),
                num_shards=8, slots_per_shard=16)

        a = mk()
        a.process_left(np.array([1, 1]), np.array([100, 200]),
                       {"a": np.array([1.0, 2.0], np.float32)})
        b = mk()
        b.restore_state(a.snapshot_state())
        for op in (a, b):
            op.process_right(np.array([1]), np.array([300]), {})
        fa = dict(a.advance_watermark(2000))
        fb = dict(b.advance_watermark(2000))
        assert sorted(map(float, fa["left_a"])) == \
            sorted(map(float, fb["left_a"])) == [1.0, 2.0]

    def test_mode_mismatch_restore_refuses(self):
        a = WindowJoinOperator(TumblingEventTimeWindows.of(1000),
                               num_shards=8, slots_per_shard=16)
        snap = a.snapshot_state()
        b = WindowJoinOperator(TumblingEventTimeWindows.of(1000),
                               num_shards=8, slots_per_shard=16,
                               mode="aggregate")
        with pytest.raises(ValueError, match="mode"):
            b.restore_state(snap)


class TestSessionScaleAndFuzz:
    def test_million_key_churn_under_10s(self):
        """Round-2 mandate: the registry must survive Criteo-scale key
        cardinality. 1M distinct keys across batches, vectorized merge —
        wall-clocked under 10s (the dict-of-dataclasses registry took
        minutes)."""
        import time

        op = SessionOperator(1000, aggregates.count(), num_shards=8)
        # warm up the CPU-jax lift compile so the timed region measures
        # the registry merge, not first-call tracing
        op.process_batch(np.zeros(4, np.int64), np.zeros(4, np.int64), {})
        t0 = time.time()
        rng = np.random.default_rng(0)
        total = 4  # the warm-up records fire too
        for i in range(10):
            b = 100_000
            keys = rng.integers(0, 1_000_000, b).astype(np.int64)
            ts = np.sort(rng.integers(i * 2000, i * 2000 + 3000, b)).astype(np.int64)
            op.process_batch(keys, ts, {})
            op.advance_watermark(i * 2000)
            total += b
        fired = op.advance_watermark(10 * 2000 + 5000)
        elapsed = time.time() - t0
        assert int(np.sum(fired["count"])) <= total
        assert elapsed < 10.0, f"1M-key session churn took {elapsed:.1f}s"

    def test_fuzz_vs_bruteforce_reference(self):
        """Randomized batches vs a per-record python interval-merge
        reference — exact (key, start, end, count) row parity, including
        cross-batch merges, bridges, and late refires."""
        rng = np.random.default_rng(7)
        gap, lateness = 100, 300
        op = SessionOperator(gap, aggregates.count(),
                             allowed_lateness_ms=lateness, num_shards=8)
        got = []
        # brute reference: replay all records at the end, no lateness
        # drops (watermarks chosen to keep everything on time)
        all_recs = []
        wm = 0
        for i in range(12):
            b = rng.integers(5, 40)
            keys = rng.integers(0, 6, b).astype(np.int64)
            ts = (wm + rng.integers(0, 400, b)).astype(np.int64)
            all_recs += list(zip(keys.tolist(), ts.tolist()))
            op.process_batch(keys, ts, {})  # operator lexsorts internally
            wm += rng.integers(50, 250)
            f = op.advance_watermark(wm)
            got += list(zip(map(int, f["key"]),
                            map(int, f["window_start"]),
                            map(int, f["window_end"]),
                            map(int, f["count"])))
        f = op.advance_watermark(wm + 10_000)
        got += list(zip(map(int, f["key"]), map(int, f["window_start"]),
                        map(int, f["window_end"]), map(int, f["count"])))
        assert op.late_records == 0

        # reference sessions: merge intervals per key
        want = []
        by_key = {}
        for k, t in all_recs:
            by_key.setdefault(k, []).append(t)
        for k, tss in by_key.items():
            tss.sort()
            start, last, cnt = tss[0], tss[0], 1
            for t in tss[1:]:
                if t - last > gap:
                    want.append((k, start, last + gap, cnt))
                    start, last, cnt = t, t, 1
                else:
                    last, cnt = t, cnt + 1
            want.append((k, start, last + gap, cnt))
        # the operator may emit a session several times (refires); the
        # FINAL emission per (key, start-range) must equal the reference
        final = {}
        for k, s, e, c in got:
            # later emissions of a grown session supersede earlier ones:
            # keep the last row whose span contains s
            final = {kk: v for kk, v in final.items()
                     if not (kk[0] == k and s <= v[0] < e)}
            final[(k, s, e)] = (s, e, c)
        got_final = sorted((k, s, e, c) for (k, s, e), (_, _, c) in
                           ((kk, vv) for kk, vv in final.items()))
        assert got_final == sorted(want)


class TestSessionOperator:
    def test_basic_session_merge(self):
        op = SessionOperator(1000, aggregates.count(), num_shards=8)
        # key 1: events at 0, 500, 900 → one session [0, 1900)
        op.process_batch(np.array([1, 1, 1]), np.array([0, 500, 900]), {})
        f = op.advance_watermark(1898)
        assert len(f["key"]) == 0  # not complete yet (end-1 = 1899)
        f = op.advance_watermark(1899)
        assert list(f["key"]) == [1]
        assert list(f["window_start"]) == [0]
        assert list(f["window_end"]) == [1900]
        assert list(f["count"]) == [3]

    def test_gap_splits_sessions(self):
        op = SessionOperator(1000, aggregates.count(), num_shards=8)
        op.process_batch(np.array([1, 1]), np.array([0, 2000]), {})
        f = op.advance_watermark(5000)
        got = sorted(zip(f["window_start"], f["window_end"], f["count"]))
        assert [(int(a), int(b), int(c)) for a, b, c in got] == [
            (0, 1000, 1), (2000, 3000, 1)]

    def test_cross_batch_merge(self):
        op = SessionOperator(1000, aggregates.sum_of("v"), num_shards=8)
        op.process_batch(np.array([1]), np.array([0]),
                         {"v": np.array([1.0], np.float32)})
        op.process_batch(np.array([1]), np.array([800]),
                         {"v": np.array([2.0], np.float32)})
        f = op.advance_watermark(2000)
        assert list(f["window_start"]) == [0]
        assert list(f["window_end"]) == [1800]
        assert list(f["sum_v"]) == [3.0]

    def test_bridging_merge(self):
        """An event bridging two existing sessions merges all three."""
        op = SessionOperator(1000, aggregates.count(), num_shards=8)
        op.process_batch(np.array([1, 1]), np.array([0, 1800]), {})
        op.process_batch(np.array([1]), np.array([900]), {})  # bridges
        f = op.advance_watermark(4000)
        assert list(f["window_start"]) == [0]
        assert list(f["window_end"]) == [2800]
        assert list(f["count"]) == [3]

    def test_late_merge_refires(self):
        op = SessionOperator(1000, aggregates.count(),
                             allowed_lateness_ms=5000, num_shards=8)
        op.process_batch(np.array([1]), np.array([0]), {})
        f = op.advance_watermark(1500)
        assert list(f["count"]) == [1]
        # late event within lateness, inside the fired session's span
        op.process_batch(np.array([1]), np.array([500]), {})
        f = op.advance_watermark(1500)
        assert list(f["count"]) == [2]
        assert list(f["window_end"]) == [1500]

    def test_late_beyond_lateness_dropped(self):
        op = SessionOperator(1000, aggregates.count(),
                             allowed_lateness_ms=0, num_shards=8)
        op.process_batch(np.array([1]), np.array([0]), {})
        op.advance_watermark(5000)
        op.process_batch(np.array([1]), np.array([100]), {})
        assert op.late_records == 1
        f = op.advance_watermark(6000)
        assert len(f["key"]) == 0

    def test_snapshot_restore(self):
        op1 = SessionOperator(1000, aggregates.count(), num_shards=8)
        op1.process_batch(np.array([1, 2]), np.array([100, 300]), {})
        snap = op1.snapshot_state()
        op2 = SessionOperator(1000, aggregates.count(), num_shards=8)
        op2.restore_state(snap)
        op1.process_batch(np.array([1]), np.array([900]), {})
        op2.process_batch(np.array([1]), np.array([900]), {})
        f1 = op1.advance_watermark(3000).materialize()
        f2 = op2.advance_watermark(3000).materialize()
        a = sorted(zip(f1["key"], f1["window_end"], f1["count"]))
        b = sorted(zip(f2["key"], f2["window_end"], f2["count"]))
        assert a == b and len(a) == 2

    def test_session_e2e_fluent(self):
        env = small_env()
        keys = np.array([1, 1, 1, 2, 2], np.int64)
        ts = np.array([0, 400, 3000, 100, 5000], np.int64)
        sink = (
            env.from_collection({"k": keys}, ts)
            .assign_timestamps_and_watermarks(
                WatermarkStrategy.for_bounded_out_of_orderness(6000))
            .key_by("k")
            .window(EventTimeSessionWindows.with_gap(1000))
            .count()
            .collect()
        )
        env.execute()
        got = sorted((int(r["key"]), int(r["window_start"]), int(r["window_end"]),
                      int(r["count"])) for r in sink.rows)
        assert got == [
            (1, 0, 1400, 2), (1, 3000, 4000, 1),
            (2, 100, 1100, 1), (2, 5000, 6000, 1),
        ]

    def test_late_record_merges_into_retained_session(self):
        """A record whose singleton session is dead must still merge into
        a live retained span (post-merge lateness check; review finding)."""
        op = SessionOperator(1000, aggregates.count(),
                             allowed_lateness_ms=5000, num_shards=8)
        op.process_batch(np.array([1, 1]), np.array([0, 900]), {})
        f = op.advance_watermark(6500)  # fires [0,1900); retained till 6899
        assert list(f["count"]) == [2]
        op.process_batch(np.array([1]), np.array([100]), {})  # singleton dead, span live
        assert op.late_records == 0
        f = op.advance_watermark(6500)
        assert list(f["count"]) == [3]
