"""Unwindowed keyed running aggregation — the upsert/changelog path
(ops/global_agg.py; ref: table-runtime GroupAggFunction + the
retract/changelog stream model, SURVEY §3.8, degenerated to upserts
for insert-only input)."""
import numpy as np
import pytest

from flink_tpu.time.watermarks import WatermarkStrategy

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import FnSink, UpsertSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.config import Configuration
from flink_tpu.ops import aggregates
from flink_tpu.table.api import TableEnvironment
from flink_tpu.table.sql import SqlError


def _env(extra=None):
    return StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8, "state.slots-per-shard": 64,
        "pipeline.microbatch-size": 100, **(extra or {})}))


def _data(n=1000, nk=20, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, nk, n).astype(np.int64),
            rng.random(n).astype(np.float32),
            np.arange(n, dtype=np.int64))


def _oracle(k, v):
    out = {}
    for kk, vv in zip(k, v):
        c, s, mx = out.get(int(kk), (0, 0.0, -np.inf))
        out[int(kk)] = (c + 1, s + float(vv), max(mx, float(vv)))
    return out


class TestSqlUnwindowed:
    def test_group_by_without_window_upserts(self):
        env = _env()
        t_env = TableEnvironment.create(env)
        k, v, ts = _data()
        stream = env.from_collection({"k": k, "v": v}, ts, batch_size=100)
        t_env.create_temporary_view(
            "t", stream, schema=["k", "v", "ts"], time_attr="ts")
        tbl = t_env.sql_query(
            "SELECT k, COUNT(*) AS c, SUM(v) AS sv, MAX(v) AS mv "
            "FROM t GROUP BY k")
        sink = UpsertSink(key_fields=("k",))
        tbl.stream.add_sink(sink)
        env.execute("running-sql")
        want = _oracle(k, v)
        got = {int(r["k"]): (int(r["c"]), float(r["sv"]), float(r["mv"]))
               for r in sink.view()}
        assert set(got) == set(want)
        for kk in want:
            assert got[kk][0] == want[kk][0]
            assert got[kk][1] == pytest.approx(want[kk][1], rel=1e-3)
            assert got[kk][2] == pytest.approx(want[kk][2], rel=1e-5)

    def test_upsert_stream_supersedes(self):
        # the RAW stream carries multiple rows per key; the LAST row
        # per key equals the final aggregate — the upsert contract
        env = _env()
        t_env = TableEnvironment.create(env)
        k, v, ts = _data()
        stream = env.from_collection({"k": k, "v": v}, ts, batch_size=100)
        t_env.create_temporary_view(
            "t", stream, schema=["k", "v", "ts"], time_attr="ts")
        tbl = t_env.sql_query("SELECT k, COUNT(*) AS c FROM t GROUP BY k")
        rows = []
        tbl.stream.add_sink(FnSink(rows.append))
        env.execute("upserts")
        seen = {}
        total_rows = 0
        for b in rows:
            for kk, c in zip(b["k"], b["c"]):
                seen[int(kk)] = int(c)
                total_rows += 1
        want = _oracle(k, v)
        assert total_rows > len(want)  # genuinely a changelog
        assert seen == {kk: c for kk, (c, _, _) in want.items()}

    def test_refusals(self):
        env = _env()
        t_env = TableEnvironment.create(env)
        k, v, ts = _data()
        stream = env.from_collection({"k": k, "v": v}, ts, batch_size=100)
        t_env.create_temporary_view(
            "t", stream, schema=["k", "v", "ts"], time_attr="ts")
        # HAVING over an unwindowed aggregate now plans (changelog
        # filter over the op-typed rows) — only the re-ranking shape
        # still refuses
        t_env.sql_query(
            "SELECT k, COUNT(*) AS c FROM t GROUP BY k HAVING c > 2")
        with pytest.raises(SqlError, match="ORDER BY"):
            t_env.sql_query(
                "SELECT k, COUNT(*) AS c FROM t GROUP BY k "
                "ORDER BY c DESC LIMIT 3")


class TestDataStreamRunning:
    def test_running_aggregate_api(self):
        env = _env()
        k, v, ts = _data(seed=3)
        sink = UpsertSink(key_fields=("key",))
        (env.from_collection({"k": k, "v": v}, ts, batch_size=100)
            .key_by("k")
            .running_aggregate(aggregates.multi(
                aggregates.count(), aggregates.min_of("v")))
            .add_sink(sink))
        env.execute("running-ds")
        want = {}
        for kk, vv in zip(k, v):
            c, mn = want.get(int(kk), (0, np.inf))
            want[int(kk)] = (c + 1, min(mn, float(vv)))
        got = {int(r["key"]): (int(r["count"]), float(r["min_v"]))
               for r in sink.view()}
        assert set(got) == set(want)
        for kk in want:
            assert got[kk][0] == want[kk][0]
            assert got[kk][1] == pytest.approx(want[kk][1], rel=1e-5)


class TestExactlyOnceRestore:
    def test_crash_resume_final_view_exact(self, tmp_path):
        n_batches, B, nk = 10, 256, 16
        all_k, all_v = [], []

        def gen(split, i):
            if i >= n_batches:
                return None
            r = np.random.default_rng(40 + i)
            kk = r.integers(0, nk, B).astype(np.int64)
            vv = r.random(B).astype(np.float32)
            return ({"k": kk, "v": vv},
                    (i * B + np.arange(B)).astype(np.int64))

        # oracle over the whole stream
        for i in range(n_batches):
            r = np.random.default_rng(40 + i)
            all_k.append(r.integers(0, nk, B).astype(np.int64))
            all_v.append(r.random(B).astype(np.float32))
        want = _oracle(np.concatenate(all_k), np.concatenate(all_v))

        base = {
            "state.num-key-shards": 8, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": B,
            "state.checkpoints.dir": str(tmp_path / "ck"),
        }

        class Boom(Exception):
            pass

        sink = UpsertSink(key_fields=("key",))
        seen = [0]

        def poison(b):
            sink.write(b)
            seen[0] += 1
            if seen[0] == 4:
                raise Boom()

        env = StreamExecutionEnvironment(Configuration({
            **base, "execution.checkpointing.interval": "1ms"}))
        (env.from_source(GeneratorSource(gen),
                         WatermarkStrategy.for_monotonous_timestamps())
            .key_by("k")
            .running_aggregate(aggregates.multi(
                aggregates.count(), aggregates.sum_of("v")))
            .add_sink(FnSink(poison)))
        with pytest.raises(Exception):
            env.execute("crash")

        env2 = StreamExecutionEnvironment(Configuration({
            **base, "execution.checkpointing.restore": "latest"}))
        (env2.from_source(GeneratorSource(gen),
                          WatermarkStrategy.for_monotonous_timestamps())
             .key_by("k")
             .running_aggregate(aggregates.multi(
                 aggregates.count(), aggregates.sum_of("v")))
             .add_sink(FnSink(sink.write)))
        env2.execute("resume")
        got = {int(r["key"]): (int(r["count"]), float(r["sum_v"]))
               for r in sink.view()}
        assert set(got) == set(want)
        for kk in want:
            assert got[kk][0] == want[kk][0], kk
            assert got[kk][1] == pytest.approx(want[kk][1], rel=1e-3)
