"""Sharded-path tests on a virtual 8-device CPU mesh — the MiniCluster
analogue (SURVEY §5 tier 3/4): keyBy all_to_all, sharded pane state,
parity with the single-device operator, snapshot/restore.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flink_tpu.api.windowing import SlidingEventTimeWindows, TumblingEventTimeWindows
from flink_tpu.exchange.keyby import bucket_by_destination, keyby_exchange
from flink_tpu.ops.aggregates import count, max_of, multi, sum_of
from flink_tpu.ops.window import WindowOperator
from flink_tpu.parallel.mesh import AXIS, make_mesh_plan
from flink_tpu.utils.jaxcompat import shard_map


pytestmark = pytest.mark.shard_map  # device-mesh suite: skipped when shard_map is unavailable


@pytest.fixture(scope="module")
def mesh_plan():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return make_mesh_plan(num_shards=32, slots_per_shard=64)


class TestBucketing:
    def test_bucket_by_destination(self):
        dest = jnp.array([2, 0, 2, 1, 0], dtype=jnp.int32)
        valid = jnp.array([True, True, True, False, True])
        payload = {"x": jnp.array([10, 11, 12, 13, 14], dtype=jnp.int64)}
        buckets, bv, overflow = bucket_by_destination(
            dest, valid, payload, n_dest=3, capacity=4)
        assert buckets["x"].shape == (3, 4)
        # dest 0 gets 11, 14; dest 1 nothing (record invalid); dest 2 gets 10, 12
        got0 = sorted(np.asarray(buckets["x"][0])[np.asarray(bv[0])].tolist())
        got1 = np.asarray(bv[1]).sum()
        got2 = sorted(np.asarray(buckets["x"][2])[np.asarray(bv[2])].tolist())
        assert got0 == [11, 14]
        assert got1 == 0
        assert got2 == [10, 12]
        assert np.asarray(overflow).tolist() == [0, 0, 0]

    def test_overflow_counted_not_silent(self):
        dest = jnp.zeros(6, dtype=jnp.int32)
        valid = jnp.ones(6, dtype=bool)
        payload = {"x": jnp.arange(6, dtype=jnp.int64)}
        buckets, bv, overflow = bucket_by_destination(
            dest, valid, payload, n_dest=2, capacity=4)
        assert int(np.asarray(bv[0]).sum()) == 4
        assert np.asarray(overflow).tolist() == [2, 0]


class TestAllToAll:
    def test_exchange_routes_every_record_to_owner(self, mesh_plan):
        n = mesh_plan.n_devices
        b_per_dev = 16

        def step(slot, valid):
            dest = (slot // mesh_plan.slots_per_device).astype(jnp.int32)
            recv, rv, overflow = keyby_exchange(
                dest, valid, {"slot": slot},
                n_devices=n, capacity=b_per_dev)
            my = jax.lax.axis_index(AXIS).astype(jnp.int64)
            ok = (recv["slot"] // mesh_plan.slots_per_device) == my
            misrouted = jnp.sum(jnp.where(rv, ~ok, False))
            return jnp.sum(rv)[None], misrouted[None]

        fn = jax.jit(shard_map(
            step, mesh=mesh_plan.mesh,
            in_specs=(P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS))))

        rng = np.random.default_rng(0)
        slots = rng.integers(0, mesh_plan.total_slots, n * b_per_dev)
        valid = rng.random(n * b_per_dev) < 0.9
        received, misrouted = fn(jnp.asarray(slots), jnp.asarray(valid))
        assert int(np.asarray(received).sum()) == int(valid.sum())
        assert int(np.asarray(misrouted).sum()) == 0


class TestShardedWindowParity:
    """The sharded operator must produce byte-identical emissions to the
    single-device operator for identical input."""

    def _run(self, op, batches, wms):
        out = []
        for (keys, ts, data), wm in zip(batches, wms):
            if keys is not None:
                op.process_batch(keys, ts, data)
            fired = op.advance_watermark(wm)
            for i in range(len(fired["key"])):
                out.append(tuple(
                    (k, float(fired[k][i])) for k in sorted(fired)))
        return sorted(out)

    @pytest.mark.parametrize("case", ["tumbling", "sliding"])
    def test_parity(self, mesh_plan, case):
        if case == "tumbling":
            assigner = TumblingEventTimeWindows.of(1000)
            agg = multi(count(), sum_of("v"), max_of("v"))
        else:
            assigner = SlidingEventTimeWindows.of(5000, 1000)
            agg = count()
        kw = dict(allowed_lateness_ms=1000, max_out_of_orderness_ms=2000)
        local = WindowOperator(assigner, agg,
                               num_shards=mesh_plan.num_shards,
                               slots_per_shard=mesh_plan.slots_per_shard, **kw)
        sharded = WindowOperator(assigner, agg, mesh_plan=mesh_plan, **kw)

        rng = np.random.default_rng(3)
        batches, wms = [], []
        t = 0
        for _ in range(6):
            n = 100
            ts = rng.integers(max(0, t - 2000), t + 1200, n)
            t = max(t, int(ts.max()))
            keys = rng.integers(0, 50, n)
            vals = rng.random(n).astype(np.float32) * 10
            batches.append((keys, ts, {"v": vals}))
            wms.append(t - 2001)
        batches.append((None, None, None))
        wms.append(t + 20_000)

        got_local = self._run(local, batches, wms)
        got_sharded = self._run(sharded, batches, wms)
        assert got_local == got_sharded
        assert sharded.exchange_overflow == 0

    def test_sharded_snapshot_restore(self, mesh_plan):
        assigner = TumblingEventTimeWindows.of(1000)
        op1 = WindowOperator(assigner, count(), mesh_plan=mesh_plan,
                             max_out_of_orderness_ms=2000)
        op1.process_batch(np.array([1, 2, 3]), np.array([500, 600, 700]), {})
        snap = op1.snapshot_state()

        op2 = WindowOperator(assigner, count(), mesh_plan=mesh_plan,
                             max_out_of_orderness_ms=2000)
        op2.restore_state(snap)
        fired = op2.advance_watermark(5000)
        assert sorted(fired["key"].tolist()) == [1, 2, 3]
        assert all(int(c) == 1 for c in fired["count"])
