"""Spill-tiered LSM keyed-state backend (flink_tpu/state/lsm.py, ISSUE
17): keyed state beyond the in-memory budget degrades to DISK, never
wrong — the RocksDB + flink-dstl changelog analogue. The golden
contract extends test_spill.py's: a run with ``state.backend='lsm'``
and a budget ~100x below the working set must produce byte-identical
results to a roomy in-memory run, the restore path must be
byte-identical across the spill/no-spill config flip, and compaction
must never change fired bytes (one shared fold order: runs in seal
order, delta last)."""
import os

import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.config import Configuration
from flink_tpu.ops import aggregates
from flink_tpu.ops.window import WindowOperator
from flink_tpu.state.lsm import LsmSpillStore, merge_rescale_spill
from flink_tpu.state.spill import HostSpillStore
from flink_tpu.time.watermarks import WatermarkStrategy

from tests.test_spill import churn_source, rows_of, run_pipeline


def make_env(tmp_path, slots=4, backend="lsm", budget=4096, extra=None):
    conf = {
        "state.num-key-shards": 4,
        "state.slots-per-shard": slots,
        "state.backend": backend,
        "pipeline.microbatch-size": 256,
    }
    if backend == "lsm":
        # tiny-run shape on purpose: floor lowered to match (the
        # STATE_BUDGET_INVALID self-consistency contract)
        conf.update({
            "state.memory-budget-bytes": budget,
            "state.lsm.run-floor-bytes": min(budget, 65536),
            "state.lsm.dir": str(tmp_path / "lsm"),
        })
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def _mk_store(tmp_path, name="store", budget=0, agg=None, **kw):
    return LsmSpillStore(
        agg or aggregates.multi(aggregates.sum_of("v"),
                                aggregates.max_of("v")),
        store_dir=str(tmp_path / name), memory_budget_bytes=budget,
        num_shards=4, **kw)


def _churn(store, n_batches=6, n_keys=400, b=128):
    for i in range(n_batches):
        rng = np.random.default_rng(42 + i)
        store.absorb(rng.integers(0, n_keys, b).astype(np.int64),
                     rng.integers(0, 4, b).astype(np.int64),
                     {"v": rng.integers(1, 100, b).astype(np.int64)})


def _fired(store):
    rows = store.fire([4], panes_per_window=4, pane_ms=1000,
                      offset_ms=0, size_ms=4000)
    return {k: np.asarray(v) for k, v in dict(rows).items()}


class TestLsmGolden:
    def test_count_100x_budget_exact(self, tmp_path):
        """1600 distinct keys through a 4 KiB delta budget (the working
        set is ~100x larger): disk-tiered run == roomy in-memory run."""
        roomy, _ = run_pipeline(
            StreamExecutionEnvironment(Configuration({
                "state.num-key-shards": 4,
                "state.slots-per-shard": 2048,
                "pipeline.microbatch-size": 256})),
            lambda s: s.count(), TumblingEventTimeWindows.of(1_000))
        tiny, res = run_pipeline(make_env(tmp_path),
                                 lambda s: s.count(),
                                 TumblingEventTimeWindows.of(1_000))
        assert rows_of(roomy) == rows_of(tiny)
        assert res.metrics["records_spilled"] > 0

    def test_multi_lane_sliding_exact(self, tmp_path):
        agg = aggregates.multi(
            aggregates.sum_of("v"), aggregates.max_of("v"),
            aggregates.avg_of("v"))
        roomy, _ = run_pipeline(
            StreamExecutionEnvironment(Configuration({
                "state.num-key-shards": 4,
                "state.slots-per-shard": 2048,
                "pipeline.microbatch-size": 256})),
            lambda s: s.aggregate(agg),
            SlidingEventTimeWindows.of(2_000, 1_000))
        tiny, res = run_pipeline(make_env(tmp_path),
                                 lambda s: s.aggregate(agg),
                                 SlidingEventTimeWindows.of(2_000, 1_000))
        assert rows_of(roomy) == rows_of(tiny)
        assert res.metrics["records_spilled"] > 0

    def test_budget_flip_is_byte_identical(self, tmp_path):
        """The spill/no-spill flip: a budget large enough that nothing
        ever seals vs one that seals constantly — same bytes out (the
        tiering decision is invisible to results)."""
        never, _ = run_pipeline(
            make_env(tmp_path / "roomy", budget=1 << 30),
            lambda s: s.count(), TumblingEventTimeWindows.of(1_000))
        always, _ = run_pipeline(
            make_env(tmp_path / "tiny", budget=4096),
            lambda s: s.count(), TumblingEventTimeWindows.of(1_000))
        assert rows_of(never) == rows_of(always)

    def test_ram_spill_backend_unchanged(self, tmp_path):
        """The RAM tier and the disk tier agree row-for-row on the
        same churn (the tiers share the HostSpillStore fold)."""
        ram, _ = run_pipeline(
            StreamExecutionEnvironment(Configuration({
                "state.num-key-shards": 4, "state.slots-per-shard": 4,
                "state.backend": "spill",
                "pipeline.microbatch-size": 256})),
            lambda s: s.count(), TumblingEventTimeWindows.of(1_000))
        disk, _ = run_pipeline(make_env(tmp_path),
                               lambda s: s.count(),
                               TumblingEventTimeWindows.of(1_000))
        assert rows_of(ram) == rows_of(disk)


class TestLsmCheckpoint:
    def _op(self, tmp_path, name):
        store = LsmSpillStore(
            aggregates.count(), store_dir=str(tmp_path / name),
            memory_budget_bytes=0, num_shards=4)
        return WindowOperator(
            TumblingEventTimeWindows.of(1_000), aggregates.count(),
            num_shards=4, slots_per_shard=2,
            max_out_of_orderness_ms=500, spill_store=store), store

    def test_snapshot_restore_roundtrip_with_runs(self, tmp_path):
        """Snapshot mid-stream with SEALED RUNS on disk, restore into a
        fresh operator with a fresh store dir (runs adopted via the
        aux-path hardlink map), continue — results match an
        uninterrupted twin."""
        keys1 = np.arange(40, dtype=np.int64)
        ts1 = np.full(40, 300, np.int64)
        keys2 = np.arange(40, dtype=np.int64)
        ts2 = np.full(40, 700, np.int64)

        straight, _ = self._op(tmp_path, "straight")
        straight.process_batch(keys1, ts1, {})
        straight.process_batch(keys2, ts2, {})
        want = dict(straight.advance_watermark(2_000))

        a, sa = self._op(tmp_path, "a")
        a.process_batch(keys1, ts1, {})
        snap = a.snapshot_state()
        assert snap["__aux_files__"], "no sealed runs rode the snapshot"
        # what storage.load() does: aux logical names -> on-disk paths
        snap["__aux_paths__"] = snap["__aux_files__"]
        b, sb = self._op(tmp_path, "b")
        b.restore_state(snap)
        b.process_batch(keys2, ts2, {})
        got = dict(b.advance_watermark(2_000))

        ow = np.lexsort((np.asarray(want["key"]),
                         np.asarray(want["window_end"])))
        og = np.lexsort((np.asarray(got["key"]),
                         np.asarray(got["window_end"])))
        for f in want:
            np.testing.assert_array_equal(
                np.asarray(want[f])[ow], np.asarray(got[f])[og],
                err_msg=f)

    def test_restore_with_runs_into_ram_spill_refuses(self, tmp_path):
        a, _ = self._op(tmp_path, "a")
        a.process_batch(np.arange(10, dtype=np.int64),
                        np.full(10, 100, np.int64), {})
        snap = a.snapshot_state()
        assert snap["spill"]["runs"]
        b = WindowOperator(
            TumblingEventTimeWindows.of(1_000), aggregates.count(),
            num_shards=4, slots_per_shard=2, max_out_of_orderness_ms=500,
            spill=True)
        with pytest.raises(ValueError, match="lsm"):
            b.restore_state(snap)

    def test_restore_into_hbm_refuses(self, tmp_path):
        a, _ = self._op(tmp_path, "a")
        a.process_batch(np.arange(10, dtype=np.int64),
                        np.full(10, 100, np.int64), {})
        snap = a.snapshot_state()
        b = WindowOperator(
            TumblingEventTimeWindows.of(1_000), aggregates.count(),
            num_shards=4, slots_per_shard=2, max_out_of_orderness_ms=500)
        with pytest.raises(ValueError, match="spill"):
            b.restore_state(snap)

    def test_ram_spill_snapshot_restores_into_lsm(self, tmp_path):
        """The spill→lsm backend flip: a plain RAM spill snapshot
        restores into the disk tier (it becomes the delta)."""
        a = WindowOperator(
            TumblingEventTimeWindows.of(1_000), aggregates.count(),
            num_shards=4, slots_per_shard=2, max_out_of_orderness_ms=500,
            spill=True)
        a.process_batch(np.arange(40, dtype=np.int64),
                        np.full(40, 300, np.int64), {})
        snap = a.snapshot_state()
        assert snap["spill"]["panes"]
        b, sb = self._op(tmp_path, "b")
        b.restore_state(snap)
        got = dict(b.advance_watermark(2_000))
        assert sorted(int(k) for k in got["key"]) == list(range(40))


class TestLsmStoreUnit:
    def test_tiered_fire_matches_ram_fire_bitwise(self, tmp_path):
        """Every absorb seals (budget 0) and the fire must still be
        bit-identical to the all-RAM store fed the same churn: one
        shared fold order (runs in seal order, delta last)."""
        ram = HostSpillStore(aggregates.multi(
            aggregates.sum_of("v"), aggregates.max_of("v")))
        disk = _mk_store(tmp_path, budget=0)
        _churn(ram)
        _churn(disk)
        assert disk.seals > 0
        want, got = _fired(ram), _fired(disk)
        assert set(want) == set(got)
        for f in want:
            np.testing.assert_array_equal(want[f], got[f], err_msg=f)

    def test_compaction_preserves_fired_bytes(self, tmp_path):
        disk = _mk_store(tmp_path, budget=0, compact_min_runs=99)
        _churn(disk)
        before = _fired(disk)
        n_before = len(disk._runs)
        assert disk.compact()
        assert len(disk._runs) < n_before
        after = _fired(disk)
        for f in before:
            np.testing.assert_array_equal(before[f], after[f],
                                          err_msg=f)

    def test_purge_drops_dead_runs_and_floor_persists(self, tmp_path):
        disk = _mk_store(tmp_path, budget=0)
        _churn(disk)
        disk.purge_below(4)
        assert disk.fire([4], 4, 1000, 0, 4000) is None
        # dead runs left the manifest; a warm restart keeps the floor
        again = _mk_store(tmp_path, budget=0)
        assert again._floor == 4
        assert again.fire([4], 4, 1000, 0, 4000) is None

    def test_warm_restart_adopts_manifest(self, tmp_path):
        a = _mk_store(tmp_path, budget=0)
        _churn(a)
        want = _fired(a)
        b = _mk_store(tmp_path, budget=0)  # same dir: manifest is truth
        got = _fired(b)
        for f in want:
            np.testing.assert_array_equal(want[f], got[f], err_msg=f)

    def test_orphan_run_swept_on_open(self, tmp_path):
        a = _mk_store(tmp_path, budget=0)
        _churn(a, n_batches=2)
        orphan = os.path.join(a.dir, "run-000099.seg")
        with open(orphan, "wb") as f:
            f.write(b"crashed seal")
        _mk_store(tmp_path, budget=0)
        assert not os.path.exists(orphan)


class TestLsmRescale:
    def test_full_range_merge_matches_own_fold_bitwise(self, tmp_path):
        """merge_rescale_spill over the store's full shard range must
        reproduce the store's OWN fold exactly — the fold order (seal
        order, delta last) is shared, so not a single float moves."""
        store = _mk_store(tmp_path, budget=4096)
        _churn(store)
        assert store._runs, "churn never sealed — test is vacuous"
        snap = store.snapshot()
        merged = merge_rescale_spill(
            [(snap, snap.get("aux_files") or {})],
            num_shards=4, shard_lo=0, shard_hi=4)
        own = store._fold_runs(store._live_runs(), include_delta=True)
        got = {int(p): t for p, t in merged["delta"]["panes"].items()}
        assert set(got) == set(int(p) for p in own.panes)
        for p, want in own.panes.items():
            for i in range(5):
                np.testing.assert_array_equal(
                    np.asarray(want[i]), np.asarray(got[int(p)][i]),
                    err_msg=f"pane {p} lane {i}")

    def test_half_range_merge_filters_by_stored_shard(self, tmp_path):
        from flink_tpu.exchange.partitioners import hash_shards

        store = _mk_store(tmp_path, budget=4096)
        _churn(store)
        snap = store.snapshot()
        merged = merge_rescale_spill(
            [(snap, snap.get("aux_files") or {})],
            num_shards=4, shard_lo=0, shard_hi=2)
        own = store._fold_runs(store._live_runs(), include_delta=True)
        for p, want in own.panes.items():
            keys = np.asarray(want[0])
            keep = hash_shards(keys, 4) < 2
            got = merged["delta"]["panes"].get(int(p))
            if not keep.any():
                assert got is None or len(got[0]) == 0
                continue
            for i in range(5):
                np.testing.assert_array_equal(
                    np.asarray(want[i])[keep] if i else keys[keep],
                    np.asarray(got[i]), err_msg=f"pane {p} lane {i}")

    def test_missing_aux_is_loud(self, tmp_path):
        store = _mk_store(tmp_path, budget=0)
        _churn(store, n_batches=2)
        snap = store.snapshot()
        assert snap["runs"]
        with pytest.raises(ValueError, match="aux"):
            merge_rescale_spill([(snap, {})],
                                num_shards=4, shard_lo=0, shard_hi=4)
