"""Operator factory SPI (pluggable operator construction, ref:
OneInputStreamOperatorFactory) + coordinator-side split enumeration
(ref: FLIP-27 SplitEnumerator / SourceCoordinator)."""
import numpy as np
import pytest

from flink_tpu.config import Configuration
from flink_tpu.ops.factory import (
    OperatorBuildContext,
    lookup_operator_factory,
    register_operator_factory,
    unregister_operator_factory,
)
from flink_tpu.runtime.coordinator import JobCoordinator
from flink_tpu.runtime.rpc import RpcServer


class TestOperatorFactory:
    def test_builtin_window_goes_through_registry(self):
        assert lookup_operator_factory("window") is not None
        assert lookup_operator_factory("no-such-kind") is None

    def test_override_swaps_the_hot_path(self):
        """Registering a factory for 'window' replaces the built-in
        operator for EVERY pipeline — the swap-the-implementation-
        without-touching-the-API property the seam exists for."""
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sinks import CollectSink
        from flink_tpu.api.windowing import TumblingEventTimeWindows

        default = lookup_operator_factory("window")
        built = []

        def spy_factory(node, ctx):
            op = default(node, ctx)
            built.append((node.kind, type(op).__name__,
                          ctx.num_shards))
            return op

        register_operator_factory("window", spy_factory)
        try:
            env = StreamExecutionEnvironment(Configuration({
                "state.num-key-shards": 4, "state.slots-per-shard": 16}))
            ts = np.arange(200, dtype=np.int64) * 10
            sink = CollectSink()
            (env.from_collection({"k": np.arange(200, dtype=np.int64) % 5},
                                 ts)
             .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
             .add_sink(sink))
            env.execute("spy")
            assert built == [("window", "WindowOperator", 4)]
            assert sink.rows  # pipeline still correct through the spy
        finally:
            register_operator_factory("window", default)

    def test_unregister_restores_builtin_error(self):
        default = lookup_operator_factory("window")
        unregister_operator_factory("window")
        try:
            assert lookup_operator_factory("window") is None
        finally:
            register_operator_factory("window", default)


class TestSplitEnumerator:
    def test_disjoint_cover(self):
        coord = JobCoordinator(Configuration({}))
        try:
            coord.rpc_register_runner("a", "h", 1)
            coord.rpc_register_runner("b", "h", 1)
            coord.rpc_submit_job("j", runners=["a", "b"])
            sa = coord.rpc_enumerate_splits("j", 0, 10, "a")["splits"]
            sb = coord.rpc_enumerate_splits("j", 0, 10, "b")["splits"]
            assert sorted(sa + sb) == list(range(10))
            assert not set(sa) & set(sb)
            # a zombie runner gets an ERROR (an empty share would let a
            # stale attempt finish instantly and report finish_job)
            with pytest.raises(RuntimeError, match="stale attempt"):
                coord.rpc_enumerate_splits("j", 0, 10, "z")
        finally:
            coord.close()

    def test_two_drivers_divide_a_file_source(self, tmp_path):
        """Two in-process 'runners' with coordinator enumeration read
        disjoint file splits whose union is the whole source."""
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sinks import CollectSink
        from flink_tpu.connectors import FileSource
        from flink_tpu.formats import CsvFormat

        for f in range(4):
            with open(tmp_path / f"part{f}.csv", "w") as fh:
                for r in range(25):
                    fh.write(f"{f * 100 + r},{r}\n")

        coord = JobCoordinator(Configuration({}))
        srv = RpcServer(coord)
        try:
            coord.rpc_register_runner("r1", "h", 1)
            coord.rpc_register_runner("r2", "h", 1)
            coord.rpc_submit_job("j", runners=["r1", "r2"])

            def run(runner_id):
                env = StreamExecutionEnvironment(Configuration({
                    "source.enumeration": "coordinator",
                    "cluster.coordinator": f"127.0.0.1:{srv.port}",
                    "cluster.job-id": "j",
                    "cluster.runner-id": runner_id,
                }))
                sink = CollectSink()
                src = FileSource(str(tmp_path / "*.csv"),
                                 CsvFormat([("v", "i64"), ("ts", "i64")]),
                                 ts_field="ts")
                env.from_source(src).add_sink(sink)
                env.execute(f"enum-{runner_id}")
                return {int(r["v"]) for r in sink.rows}

            got1 = run("r1")
            got2 = run("r2")
            everything = {f * 100 + r for f in range(4) for r in range(25)}
            assert not got1 & got2          # disjoint
            assert got1 | got2 == everything  # complete
        finally:
            srv.close()
            coord.close()

    def test_more_runners_than_splits_still_finishes_correctly(self):
        """An assigned runner with an empty share must not end the job
        while peers still read: finish requires ALL runners."""
        coord = JobCoordinator(Configuration({}))
        try:
            for r in ("a", "b", "c"):
                coord.rpc_register_runner(r, "h", 1)
            coord.rpc_submit_job("j", runners=["a", "b", "c"])
            shares = {r: coord.rpc_enumerate_splits("j", 0, 2, r)["splits"]
                      for r in ("a", "b", "c")}
            all_ix = sorted(i for s in shares.values() for i in s)
            assert all_ix == [0, 1]
            empty = [r for r, s in shares.items() if not s]
            assert empty  # someone owns nothing
            # the empty-share runner finishing does NOT end the job
            resp = coord.rpc_finish_job("j", runner_id=empty[0])
            assert resp.get("pending_runners")
            assert coord.rpc_job_status("j")["state"] == "RUNNING"
            for r in ("a", "b", "c"):
                coord.rpc_finish_job("j", runner_id=r)
            assert coord.rpc_job_status("j")["state"] == "FINISHED"
        finally:
            coord.close()
