"""Host spill store: state beyond HBM capacity degrades to slower, never
wrong — the RocksDBKeyedStateBackend role (ref: runtime/state/
RocksDBKeyedStateBackend, SURVEY §3.4, §3.10 item 1). The golden
contract: a run with tiny slot capacity + state.backend='spill' must
produce byte-identical results to a run with ample capacity, at key
cardinality ~100x the resident capacity (round-2 mandate #5)."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import SlidingEventTimeWindows, TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.ops import aggregates
from flink_tpu.ops.window import WindowOperator
from flink_tpu.state.spill import HostSpillStore
from flink_tpu.time.watermarks import WatermarkStrategy


def make_env(slots, backend="hbm", extra=None):
    conf = {
        "state.num-key-shards": 4,
        "state.slots-per-shard": slots,
        "state.backend": backend,
        "pipeline.microbatch-size": 256,
    }
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def rows_of(sink):
    out = []
    for row in sink.rows:
        out.append(tuple(
            (k, int(v) if np.issubdtype(np.asarray(v).dtype, np.integer)
             else round(float(v), 3))
            for k, v in sorted(row.items())))
    return sorted(out)


def churn_source(n_batches=6, n_keys=1600, b=256):
    """~100x the 16-slot resident capacity (4 shards x 4 slots)."""
    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(42 + i)
        return ({"k": rng.integers(0, n_keys, b).astype(np.int64),
                 "v": rng.integers(1, 100, b).astype(np.int64)},
                np.sort(rng.integers(i * 700, i * 700 + 1400, b)).astype(np.int64))
    return gen


def run_pipeline(env, agg_builder, window, src=None):
    sink = CollectSink()
    s = (env.from_source(GeneratorSource(src or churn_source()),
                         WatermarkStrategy.for_bounded_out_of_orderness(800))
         .key_by("k")
         .window(window))
    agg_builder(s).add_sink(sink)
    res = env.execute("spill-job")
    return sink, res


class TestSpillGolden:
    def test_count_100x_capacity_exact(self):
        """16 resident slots, 1600 distinct keys: spill run == roomy run."""
        roomy, _ = run_pipeline(make_env(2048),
                                lambda s: s.count(),
                                TumblingEventTimeWindows.of(1_000))
        tiny, res = run_pipeline(make_env(4, backend="spill"),
                                 lambda s: s.count(),
                                 TumblingEventTimeWindows.of(1_000))
        assert rows_of(roomy) == rows_of(tiny)
        assert res.metrics["records_spilled"] > 0
        assert res.metrics.get("records_dropped_full", 0) == 0

    def test_multi_lane_sum_max_avg_exact(self):
        agg = aggregates.multi(
            aggregates.sum_of("v"), aggregates.max_of("v"),
            aggregates.avg_of("v"))
        roomy, _ = run_pipeline(make_env(2048),
                                lambda s: s.aggregate(agg),
                                SlidingEventTimeWindows.of(2_000, 1_000))
        tiny, res = run_pipeline(make_env(4, backend="spill"),
                                 lambda s: s.aggregate(agg),
                                 SlidingEventTimeWindows.of(2_000, 1_000))
        assert rows_of(roomy) == rows_of(tiny)
        assert res.metrics["records_spilled"] > 0

    def test_hbm_backend_default_refuses_to_drop(self):
        """Default-safe policy: the 'hbm' backend at tiny capacity FAILS
        the job (the reference degrades, never drops — SURVEY §3.4)
        unless drops are explicitly allowed."""
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="key directory shard full"):
            run_pipeline(make_env(4),
                         lambda s: s.count(),
                         TumblingEventTimeWindows.of(1_000))

    def test_hbm_backend_drops_with_accounting_when_allowed(self):
        """state.allow-drops=true restores counted degradation — loud
        (records_dropped_full gauge), never silent."""
        env = make_env(4, extra={"state.allow-drops": True})
        _, res = run_pipeline(env,
                              lambda s: s.count(),
                              TumblingEventTimeWindows.of(1_000))
        assert res.metrics["records_dropped_full"] > 0
        assert res.metrics.get("records_spilled", 0) == 0

    def test_late_within_lateness_refires_spilled_key(self):
        """A late record for a HOST-resident key must re-fire its window
        with the updated result, mirroring the device path's
        late-within-lateness semantics."""
        def gen(split, i):
            if i == 0:  # 20 keys fill the 4x1 slots; most spill
                return ({"k": np.arange(20, dtype=np.int64)},
                        np.full(20, 500, np.int64))
            if i == 1:  # watermark passes window [0,1000) -> fires
                return ({"k": np.array([100], np.int64)},
                        np.array([1800], np.int64))
            if i == 2:  # late-but-allowed record for spilled key 19
                return ({"k": np.array([19], np.int64)},
                        np.array([600], np.int64))
            return None

        env = make_env(1, backend="spill",
                       extra={"pipeline.microbatch-size": 32})
        sink = CollectSink()
        (env.from_source(GeneratorSource(gen),
                         WatermarkStrategy.for_bounded_out_of_orderness(200))
         .key_by("k")
         .window(TumblingEventTimeWindows.of(1_000))
         .allowed_lateness(5_000)
         .count()
         .add_sink(sink))
        env.execute("late-spill")
        k19 = [(int(r["count"])) for r in sink.rows
               if int(r["key"]) == 19 and int(r["window_end"]) == 1000]
        # initial fire (count 1) then the late re-fire (count 2)
        assert k19 == [1, 2]

    def test_topn_union_rerank_exact(self):
        """Top-n winners must come from the UNION of device-resident and
        host-spilled keys — the hot key living on the host must not
        vanish from the leaderboard."""
        def gen(split, i):
            if i >= 4:
                return None
            rng = np.random.default_rng(9 + i)
            b = 200
            keys = rng.integers(0, 300, b).astype(np.int64)
            return ({"k": keys, "v": np.ones(b, np.int64)},
                    np.sort(rng.integers(i * 600, i * 600 + 1200, b)).astype(np.int64))

        def build(s):
            return s.count().top(3, "count")

        roomy, _ = run_pipeline(make_env(2048), build,
                                SlidingEventTimeWindows.of(2_000, 1_000),
                                src=gen)
        tiny, res = run_pipeline(make_env(4, backend="spill"), build,
                                 SlidingEventTimeWindows.of(2_000, 1_000),
                                 src=gen)
        assert res.metrics["records_spilled"] > 0
        assert rows_of(roomy) == rows_of(tiny)


class TestCoalescedDrainTopN:
    def test_union_rerank_survives_marker_coalescing(self):
        """The drain thread coalescing two fire markers into one ring
        poll must still re-rank each window's device winners against its
        host-spill rows — per-fire attribution rides the operator-level
        extras queue, not the markers (regression: a coalesced drain
        used to emit the displaced resident key alongside the spilled
        winner)."""
        from flink_tpu.ops.window import FiredWindows

        op = WindowOperator(
            TumblingEventTimeWindows.of(1_000), aggregates.count(),
            num_shards=1, slots_per_shard=1, max_out_of_orderness_ms=0,
            spill=True, top_n=("count", 1))
        # W1 [0,1000): resident key 7 (count 2) beats spilled key 50 (1)
        op.process_batch(np.array([7, 7, 50], np.int64),
                         np.array([100, 200, 300], np.int64), {})
        f1 = op.advance_watermark(1_500)
        # W2 [1000,2000): spilled key 50 (count 5) beats resident 7 (1)
        op.process_batch(np.array([7, 50, 50, 50, 50, 50], np.int64),
                         np.array([1100, 1200, 1200, 1300, 1300, 1400],
                                  np.int64), {})
        f2 = op.advance_watermark(2_500)
        FiredWindows.materialize_many([f1, f2])  # ONE coalesced poll
        rows = {}
        for f in (f1, f2):
            d = dict(f)
            for k, w, c in zip(d["key"], d["window_end"], d["count"]):
                rows.setdefault(int(w), []).append((int(k), int(c)))
        assert rows[1000] == [(7, 2)]
        assert rows[2000] == [(50, 5)]


    def test_refire_nonmonotone_rank_field_exact(self):
        """A late record can LOWER a key's avg, so the refire's winner
        set differs in a non-monotone way; the sync per-fire drain must
        deliver each fire's exact union leaderboard (regression: the
        coalesced dedup kept a stale device row that out-ranked the
        refire's true winner)."""
        from flink_tpu.ops.window import FiredWindows

        op = WindowOperator(
            TumblingEventTimeWindows.of(1_000), aggregates.avg_of("v"),
            num_shards=1, slots_per_shard=2, max_out_of_orderness_ms=0,
            allowed_lateness_ms=5_000, spill=True, top_n=("avg_v", 1))
        # resident A=1 (avg 900), B=2 (avg 600); spilled C=3 (avg 100)
        op.process_batch(
            np.array([1, 2, 3], np.int64),
            np.array([100, 200, 300], np.int64),
            {"v": np.array([900, 600, 100], np.int64)})
        f1 = op.advance_watermark(1_500)
        # late-within-lateness: A drops to avg 500 -> refire winner is B
        op.process_batch(np.array([1], np.int64),
                         np.array([400], np.int64),
                         {"v": np.array([100], np.int64)})
        f2 = op.advance_watermark(1_500)
        FiredWindows.materialize_many([f1, f2])
        w1 = [(int(k), float(v)) for k, v in zip(f1["key"], f1["avg_v"])]
        w2 = [(int(k), float(v)) for k, v in zip(f2["key"], f2["avg_v"])]
        assert w1 == [(1, 900.0)]
        assert w2 == [(2, 600.0)]

    def test_misrouted_records_not_absorbed(self):
        """slot == -1 (key outside this operator's shard range) is a
        routing error — the spill store must NOT aggregate it (the key
        would live on two workers at once); it drops with accounting."""
        from flink_tpu.records import hash_keys_numpy

        ks = np.arange(200, dtype=np.int64)
        shards = hash_keys_numpy(ks) % 4
        inside = ks[shards < 2][0]
        outside = ks[shards >= 2][0]
        op = WindowOperator(
            TumblingEventTimeWindows.of(1_000), aggregates.count(),
            num_shards=4, slots_per_shard=8, max_out_of_orderness_ms=0,
            shard_range=(0, 2), spill=True)
        op.allow_drops = True  # this test asserts the counted-drop path
        op.process_batch(np.array([inside, outside], np.int64),
                         np.array([100, 100], np.int64), {})
        assert op.records_dropped_full == 1
        assert op.records_spilled == 0
        fired = dict(op.advance_watermark(2_000))
        assert [int(k) for k in fired["key"]] == [int(inside)]


class TestSpillCheckpoint:
    def test_snapshot_restore_roundtrip(self, tmp_path):
        """Operator-level: snapshot mid-stream with host-resident state,
        restore into a fresh operator, continue — results match an
        uninterrupted twin."""
        def mk():
            return WindowOperator(
                TumblingEventTimeWindows.of(1_000), aggregates.count(),
                num_shards=4, slots_per_shard=2,
                max_out_of_orderness_ms=500, spill=True)

        keys1 = np.arange(40, dtype=np.int64)
        ts1 = np.full(40, 300, np.int64)
        keys2 = np.arange(40, dtype=np.int64)
        ts2 = np.full(40, 700, np.int64)

        straight = mk()
        straight.process_batch(keys1, ts1, {})
        straight.process_batch(keys2, ts2, {})
        want = dict(straight.advance_watermark(2_000))

        a = mk()
        a.process_batch(keys1, ts1, {})
        snap = a.snapshot_state()
        b = mk()
        b.restore_state(snap)
        b.process_batch(keys2, ts2, {})
        got = dict(b.advance_watermark(2_000))

        for f in want:
            w = np.asarray(want[f])
            g = np.asarray(got[f])
            ow = np.lexsort((np.asarray(want["key"]), np.asarray(want["window_end"])))
            og = np.lexsort((np.asarray(got["key"]), np.asarray(got["window_end"])))
            np.testing.assert_array_equal(w[ow], g[og], err_msg=f)


    def test_restore_into_hbm_backend_refuses_spill_state(self):
        """Switching state.backend to 'hbm' before a restore must not
        silently discard host-resident aggregates."""
        a = WindowOperator(
            TumblingEventTimeWindows.of(1_000), aggregates.count(),
            num_shards=1, slots_per_shard=1, max_out_of_orderness_ms=0,
            spill=True)
        a.process_batch(np.arange(10, dtype=np.int64),
                        np.full(10, 100, np.int64), {})
        snap = a.snapshot_state()
        b = WindowOperator(
            TumblingEventTimeWindows.of(1_000), aggregates.count(),
            num_shards=1, slots_per_shard=1, max_out_of_orderness_ms=0,
            spill=False)
        with pytest.raises(ValueError, match="spill"):
            b.restore_state(snap)


class TestSpillStoreUnit:
    def test_absorb_fire_purge(self):
        st = HostSpillStore(aggregates.multi(
            aggregates.sum_of("v"), aggregates.max_of("v")))
        keys = np.array([5, 5, 9, 5], np.int64)
        panes = np.array([0, 0, 0, 1], np.int64)
        v = np.array([10, 20, 7, 3], np.int64)
        st.absorb(keys, panes, {"v": v})
        # window = panes [0, 2) with ppw=2
        rows = st.fire([2], panes_per_window=2, pane_ms=1000,
                       offset_ms=0, size_ms=2000)
        got = {int(k): (s, m, c) for k, s, m, c in zip(
            rows["key"], rows["sum_v"], rows["max_v"], rows["count"])}
        assert got[5] == (33.0, 20.0, 3)
        assert got[9] == (7.0, 7.0, 1)
        st.purge_below(2)
        assert st.fire([2], 2, 1000, 0, 2000) is None
        assert st.records_spilled == 4
