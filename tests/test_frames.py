"""Binary DCN frame codec edge cases (ISSUE 12 satellite, mirroring
the columnar-format discipline of tests/test_formats_columnar.py):
randomized round-trip vs the old blobformat frames as oracle,
truncation mid-header and mid-array, CRC corruption, and version/magic
mismatch — every failure LOUD, never a silent partial decode.

ref role: the serialization tests of the reference's network stack
(NettyMessage framing + TypeSerializer round trips, SURVEY §3.6) —
except this wire format is self-contained (pure struct+numpy+zlib)."""
import struct

import numpy as np
import pytest

from flink_tpu.checkpoint import blobformat
from flink_tpu.exchange import frames
from flink_tpu.exchange.frames import FrameError


def _share(rng, n):
    """The production exchange payload shape: routed record columns +
    timestamps."""
    return {
        "data": {
            "auction": rng.integers(-2**40, 2**40, n).astype(np.int64),
            "price": rng.random(n).astype(np.float32),
            "d": rng.random(n).astype(np.float64),
            "flag": rng.integers(0, 2, n).astype(bool),
            "line": np.array(
                ["w" + str(int(v)) + ("é" if v % 3 == 0 else "")
                 for v in rng.integers(0, 1000, n)], dtype=object),
        },
        "ts": rng.integers(0, 2**42, n).astype(np.int64),
    }


PROD_META = {"wm": 12345, "done": False, "ckpt": True, "persisted": -1}


class TestRoundTrip:
    def test_production_shape_round_trip(self):
        rng = np.random.default_rng(0)
        payload = _share(rng, 257)
        raw = frames.encode_bytes(3, 9, PROD_META, payload)
        sender, step, meta, got = frames.decode(raw)
        assert (sender, step) == (3, 9)
        assert meta == PROD_META
        np.testing.assert_array_equal(got["ts"], payload["ts"])
        for name, col in payload["data"].items():
            np.testing.assert_array_equal(got["data"][name], col)

    def test_property_round_trip_vs_blobformat_oracle(self):
        """Randomized payloads: the binary frame and the legacy
        blobformat wire must reconstruct the SAME arrays from the same
        share — blobformat is the established oracle (it carried every
        DCN byte before this PR), binary must agree bit-exactly."""
        rng = np.random.default_rng(42)
        for trial in range(20):
            n = int(rng.integers(0, 200))
            payload = _share(rng, n)
            meta = {"wm": int(rng.integers(-2**60, 2**60)),
                    "done": bool(rng.integers(0, 2)),
                    "ckpt": bool(rng.integers(0, 2)),
                    "persisted": int(rng.integers(-1, 100))}
            _, _, via_bin_meta, via_bin = frames.decode(
                frames.encode_bytes(0, trial, meta, payload))
            legacy = blobformat.decode(
                blobformat.encode({"data": payload, "meta": meta}),
                allow_pickle=False)
            assert via_bin_meta == meta == legacy["meta"]
            np.testing.assert_array_equal(via_bin["ts"],
                                          legacy["data"]["ts"])
            for name in payload["data"]:
                np.testing.assert_array_equal(
                    via_bin["data"][name],
                    legacy["data"]["data"][name])

    def test_zero_copy_numeric_decode(self):
        """Numeric array leaves are VIEWS into the received buffer —
        the no-per-step-copy contract of the binary plane."""
        raw = frames.encode_bytes(
            0, 0, {"wm": 1}, {"ts": np.arange(64, dtype=np.int64)})
        _, _, _, payload = frames.decode(raw)
        assert np.shares_memory(payload["ts"],
                                np.frombuffer(raw, np.uint8))

    def test_none_empty_and_bare_payloads(self):
        """The rendezvous sends None (no share for that peer), {} is
        distinct from None, and the micro-benchmark ships bare
        arrays."""
        for payload in (None, {}, np.arange(5, dtype=np.int64)):
            raw = frames.encode_bytes(1, 0, {"wm": 0}, payload)
            _, _, _, got = frames.decode(raw)
            if payload is None:
                assert got is None
            elif isinstance(payload, dict):
                assert got == {}
            else:
                np.testing.assert_array_equal(got, payload)

    def test_meta_presence_exact(self):
        """Meta round-trips with EXACTLY the keys the sender set (the
        header flags carry presence, not just values) and non-standard
        keys ride the extras section."""
        for meta in ({}, {"wm": 7}, {"done": True}, {"latest": 3},
                     {"wm": 2**62, "persisted": 10, "latest": -1},
                     PROD_META):
            raw = frames.encode_bytes(0, 0, meta, None)
            _, _, got, _ = frames.decode(raw)
            assert got == meta
        # the hot-path production meta must produce NO extras JSON
        raw = frames.encode_bytes(0, 0, PROD_META, None)
        (extras_len,) = struct.unpack_from(">I", raw, frames.HEADER_LEN)
        assert extras_len == 0

    def test_zero_row_share_round_trips_typed(self):
        rng = np.random.default_rng(1)
        payload = _share(rng, 0)
        _, _, _, got = frames.decode(
            frames.encode_bytes(0, 0, {"wm": 0}, payload))
        assert len(got["ts"]) == 0 and got["ts"].dtype == np.int64
        assert got["data"]["price"].dtype == np.float32
        assert got["data"]["line"].dtype == object

    def test_any_column_name_round_trips(self):
        """No reserved characters in column names (the legacy wire
        carried arbitrary names; the binary path field is
        length-prefixed SEGMENTS, so separators need no escaping)."""
        payload = {"data": {"meta/id": np.arange(3, dtype=np.int64),
                            "a/b/c": np.arange(3, dtype=np.int64),
                            "": np.arange(3, dtype=np.int64)},
                   "ts": np.arange(3, dtype=np.int64)}
        _, _, _, got = frames.decode(
            frames.encode_bytes(0, 0, {"wm": 1}, payload))
        assert set(got["data"]) == {"meta/id", "a/b/c", ""}
        np.testing.assert_array_equal(got["data"]["meta/id"],
                                      payload["data"]["meta/id"])

    def test_scatter_buffers_equal_joined_bytes(self):
        """encode() (the sendmsg scatter list) and encode_bytes() are
        the same wire bytes — what the bench sends is what tests
        decode."""
        rng = np.random.default_rng(2)
        payload = _share(rng, 33)
        bufs = frames.encode(5, 2, PROD_META, payload)
        assert b"".join(bytes(b) for b in bufs) == frames.encode_bytes(
            5, 2, PROD_META, payload)


class TestLoudFailures:
    def _frame(self, n=64):
        return frames.encode_bytes(
            0, 0, PROD_META, _share(np.random.default_rng(3), n))

    def test_truncated_mid_header(self):
        with pytest.raises(FrameError, match="truncated"):
            frames.decode(self._frame()[:frames.HEADER_LEN // 2])

    def test_truncated_mid_descriptor(self):
        raw = self._frame()
        with pytest.raises(FrameError, match="truncated"):
            frames.decode(raw[:frames.HEADER_LEN + 12])

    def test_truncated_mid_array(self):
        raw = self._frame()
        with pytest.raises(FrameError, match="truncated"):
            frames.decode(raw[:-17])

    def test_crc_corruption_loud(self):
        raw = bytearray(self._frame())
        raw[-5] ^= 0xFF  # flip one payload byte in the last section
        with pytest.raises(FrameError, match="CRC mismatch"):
            frames.decode(bytes(raw))

    def test_bad_magic_rejected(self):
        raw = bytearray(self._frame())
        raw[0:4] = b"NOPE"
        with pytest.raises(FrameError, match="magic"):
            frames.decode(bytes(raw))

    def test_legacy_blobformat_frame_rejected_as_magic_mismatch(self):
        """A v0 wire frame (8-byte length + blobformat) read by the
        binary decoder fails at the MAGIC, naming the likely cause —
        the mixed-version tripwire below the hello fence."""
        legacy = blobformat.encode({"data": None, "meta": {}})
        wire = struct.pack(">Q", len(legacy)) + legacy
        with pytest.raises(FrameError, match="legacy blobformat"):
            frames.decode(wire)

    def test_version_mismatch_rejected(self):
        raw = bytearray(self._frame())
        struct.pack_into(">H", raw, 4, frames.VERSION + 1)
        with pytest.raises(FrameError, match="mixed-version"):
            frames.decode(bytes(raw))

    def test_hostile_body_len_rejected(self):
        """A corrupt/hostile header claiming a huge body must be
        rejected BEFORE any allocation."""
        raw = bytearray(self._frame())
        struct.pack_into(">Q", raw, frames.HEADER_LEN - 8, 1 << 60)
        with pytest.raises(FrameError, match="hostile|corrupt"):
            frames.decode(bytes(raw))

    def test_object_array_with_foreign_objects_rejected_at_encode(self):
        """No pickle escape exists in this format BY CONSTRUCTION —
        foreign objects die at encode, on the sender, loudly."""
        evil = np.array([{"x": 1}], dtype=object)
        with pytest.raises(FrameError, match="no pickle escape"):
            frames.encode_bytes(0, 0, {}, {"data": evil})

    def test_non_utf8_bytes_rejected_at_encode_on_the_sender(self):
        """A text column carrying non-UTF8 bytes must die at ENCODE on
        the sender (attributable) — never as a UnicodeDecodeError in
        the PEER's recv loop, which would be a poison pill every
        recovery attempt re-triggers."""
        bad = np.array([b"\xff\xfe"], dtype=object)
        with pytest.raises(FrameError, match="non-UTF8"):
            frames.encode_bytes(0, 0, {}, {"data": {"line": bad}})

    def test_utf8_bytes_round_trip_as_decoded_text(self):
        """np.bytes_/bytes values round-trip as DECODED TEXT, the same
        rule formats_columnar applies — never the repr "b'...'" and
        never a silent type flip the receiver can't predict."""
        b = {"s": np.array([b"abc", "caf\xc3\xa9".encode("latin-1")],
                           dtype=object)}
        _, _, _, got = frames.decode(frames.encode_bytes(0, 0, {}, b))
        assert list(got["s"]) == ["abc", "café"]

    def test_array_section_size_mismatch_rejected(self):
        """A descriptor whose nbytes disagrees with dtype x shape is a
        codec error, not a silent reshape."""
        raw = frames.encode_bytes(0, 0, {"wm": 0},
                                  {"ts": np.arange(8, dtype=np.int64)})
        b = bytearray(raw)
        # descriptor layout after extras: name_len,dtype_len,kind,ndim,
        # nbytes(u64),crc(u32) — shrink the declared shape's dim
        desc_off = frames.HEADER_LEN + 4
        name_len, dtype_len = struct.unpack_from(">HB", b, desc_off)
        shape_off = desc_off + 17 + name_len + dtype_len
        struct.pack_into(">I", b, shape_off, 4)  # shape (8,) -> (4,)
        with pytest.raises(FrameError, match="needs"):
            frames.decode(bytes(b))
