"""Foundation unit tests: config, records, watermarks, assigners.

Pattern per SURVEY.md §5 tier 1 (pure unit tests; ref:
flink-core/src/test configuration + eventtime tests).
"""
import numpy as np
import pytest

from flink_tpu.state.keyed import KeyDirectory
from flink_tpu.config import (
    Configuration,
    ConfigOption,
    PipelineOptions,
    StateOptions,
    duration_option,
    _parse_duration_ms,
)
from flink_tpu.records import (
    RecordBatch,
    Schema,
    hash_keys_device,
    hash_keys_numpy,
    hash_string_key,
    MIN_TS,
)
from flink_tpu.time.watermarks import (
    BoundedOutOfOrdernessWatermarks,
    MonotonousWatermarks,
    WatermarkTracker,
    LONG_MIN,
)
from flink_tpu.api.windowing import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
    TimeWindow,
    EventTimeTrigger,
    CountTrigger,
    PurgingTrigger,
    TriggerResult,
)


class TestConfiguration:
    def test_defaults(self):
        conf = Configuration()
        assert conf.get(PipelineOptions.MICROBATCH_SIZE) == 8192
        assert conf.get(StateOptions.NUM_KEY_SHARDS) == 128

    def test_set_overrides(self):
        conf = Configuration().set(PipelineOptions.MICROBATCH_SIZE, 1024)
        assert conf.get(PipelineOptions.MICROBATCH_SIZE) == 1024

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("FLINK_TPU_PIPELINE_MICROBATCH_SIZE", "2048")
        assert Configuration().get(PipelineOptions.MICROBATCH_SIZE) == 2048

    def test_file_loading(self, tmp_path):
        p = tmp_path / "config.yaml"
        p.write_text("pipeline.microbatch-size: 4096\n# comment\nstate.num-key-shards: 64\n")
        conf = Configuration.from_file(str(p))
        assert conf.get(PipelineOptions.MICROBATCH_SIZE) == 4096
        assert conf.get(StateOptions.NUM_KEY_SHARDS) == 64

    def test_duration_parsing(self):
        assert _parse_duration_ms("500ms") == 500
        assert _parse_duration_ms("10 s") == 10_000
        assert _parse_duration_ms("1 min") == 60_000
        assert _parse_duration_ms("250") == 250


class TestRecordBatch:
    def test_round_trip_and_padding(self):
        rb = RecordBatch.from_numpy(
            {"k": np.array([1, 2, 3])}, np.array([10, 20, 30]), capacity=8)
        assert rb.capacity == 8
        assert int(rb.num_valid()) == 3
        rows = rb.compacted_rows()
        np.testing.assert_array_equal(rows["k"], [1, 2, 3])
        np.testing.assert_array_equal(rows["__ts__"], [10, 20, 30])

    def test_mask_filter(self):
        rb = RecordBatch.from_numpy({"k": np.array([1, 2, 3])}, np.array([10, 20, 30]))
        filtered = rb.mask(rb.field("k") > 1)
        assert int(filtered.num_valid()) == 2

    def test_pytree(self):
        import jax
        rb = RecordBatch.from_numpy({"k": np.array([1, 2])}, np.array([1, 2]))
        leaves = jax.tree_util.tree_leaves(rb)
        assert len(leaves) == 3  # k, timestamps, valid

    def test_hash_host_device_identical(self):
        keys = np.array([0, 1, 7, 12345, 2**40, -17, 2**62], dtype=np.int64)
        h_host = hash_keys_numpy(keys)
        h_dev = np.asarray(hash_keys_device(keys))
        np.testing.assert_array_equal(h_host, h_dev)
        assert (h_host >= 0).all()
        # avalanche sanity: sequential keys land in distinct shards
        assert len(np.unique(hash_keys_numpy(np.arange(1000)) % 128)) > 100

    def test_string_hash_stable(self):
        assert hash_string_key("hello") == hash_string_key("hello")
        assert hash_string_key("hello") != hash_string_key("world")
        assert hash_string_key("hello") >= 0


class TestWatermarks:
    def test_monotonous(self):
        g = MonotonousWatermarks()
        assert g.current() == LONG_MIN
        assert g.on_batch(100) == 99
        assert g.on_batch(50) == 99  # never regress

    def test_bounded_out_of_orderness(self):
        # ref semantics: wm = max_ts - delay - 1
        g = BoundedOutOfOrdernessWatermarks(10)
        assert g.on_batch(100) == 89
        assert g.on_batch(200) == 189

    def test_tracker_min_over_inputs(self):
        t = WatermarkTracker()
        t.register_input("a")
        t.register_input("b")
        assert t.update("a", 100) == LONG_MIN  # b hasn't reported
        assert t.update("b", 50) == 50
        assert t.update("b", 150) == 100

    def test_tracker_never_regresses(self):
        t = WatermarkTracker()
        t.update("a", 100)
        assert t.update("b", 50) == 100  # late-joining input can't regress

    def test_tracker_idleness(self):
        t = WatermarkTracker()
        t.register_input("a")
        t.register_input("b")
        t.update("a", 100)
        t.update("b", 50)
        assert t.current() == 50
        assert t.update("b", 0, idle=True) == 100  # idle input leaves the min


class TestAssigners:
    def test_tumbling(self):
        a = TumblingEventTimeWindows.of(1000)
        assert a.pane_ms == 1000
        assert a.panes_per_window == 1
        assert a.assign_windows(1500) == [TimeWindow(1000, 2000)]
        assert a.assign_windows(999) == [TimeWindow(0, 1000)]

    def test_sliding_panes(self):
        a = SlidingEventTimeWindows.of(10_000, 1_000)
        assert a.pane_ms == 1000
        assert a.panes_per_window == 10
        assert a.panes_per_slide == 1
        ws = a.assign_windows(10_500)
        assert len(ws) == 10
        assert ws[0] == TimeWindow(1000, 11_000)
        assert ws[-1] == TimeWindow(10_000, 20_000)

    def test_tumbling_offset(self):
        a = TumblingEventTimeWindows.of(1000, offset_ms=200)
        assert a.assign_windows(1100) == [TimeWindow(200, 1200)]

    def test_triggers(self):
        w = TimeWindow(0, 1000)
        t = EventTimeTrigger.create()
        assert t.on_event_time(998, w) == TriggerResult.CONTINUE
        assert t.on_event_time(999, w) == TriggerResult.FIRE
        c = CountTrigger.of(3)
        assert c.on_element(5, w, 2) == TriggerResult.CONTINUE
        assert c.on_element(5, w, 3) == TriggerResult.FIRE
        p = PurgingTrigger.of(t)
        assert p.on_event_time(999, w) == TriggerResult.FIRE_AND_PURGE


class TestKeyDirectory:
    """The host hash-map half of the state backend (ref role:
    CopyOnWriteStateMap.get/put). The vectorized batch-insert path must
    be indistinguishable from a per-key dict model, including shard-FULL
    sentinels and reverse lookup."""

    def _model_assign(self, model, next_free, keys, num_shards, sps):
        # the directory allocates a batch's NEW keys in sorted-unique
        # order (dedupe via np.unique); the model must match that, not
        # arrival order — slot identity is deterministic either way
        for k in sorted(set(keys.tolist()) - set(model)):
            shard = int(hash_keys_numpy(np.asarray([k], np.int64))[0] % num_shards)
            if next_free[shard] >= sps:
                model[k] = KeyDirectory.FULL
            else:
                model[k] = shard * sps + next_free[shard]
                next_free[shard] += 1
        return np.asarray([model[k] for k in keys.tolist()], np.int64)

    def test_batch_insert_matches_dict_model(self):
        rng = np.random.default_rng(7)
        num_shards, sps = 4, 8
        d = KeyDirectory(num_shards, sps)
        model, next_free = {}, {s: 0 for s in range(num_shards)}
        for _ in range(30):
            # heavy churn + duplicates within a batch + eventual overflow
            keys = rng.integers(0, 120, size=rng.integers(1, 64)).astype(np.int64)
            got = d.assign(keys)
            want = self._model_assign(model, next_free, keys, num_shards, sps)
            np.testing.assert_array_equal(got, want)
        # reverse map agrees for every registered key
        live = {k: v for k, v in model.items() if v >= 0}
        slots = np.asarray(sorted(live.values()), np.int64)
        inv = {v: k for k, v in live.items()}
        np.testing.assert_array_equal(
            d.key_of_slots(slots), np.asarray([inv[int(s)] for s in slots]))
        assert d.num_keys() == len(live)

    def test_snapshot_restore_round_trip(self):
        rng = np.random.default_rng(3)
        d = KeyDirectory(8, 16)
        keys = rng.integers(0, 1000, size=500).astype(np.int64)
        before = d.assign(keys)
        d2 = KeyDirectory.restore(8, 16, d.snapshot())
        np.testing.assert_array_equal(d2.assign(keys), before)
        # new keys keep allocating from the restored free pointers
        more = np.arange(2000, 2050, dtype=np.int64)
        np.testing.assert_array_equal(d.assign(more), d2.assign(more))
