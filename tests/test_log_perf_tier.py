"""Perf-grade durable-log tier (ISSUE 13): group fsync ordering,
parallel partition I/O determinism, the zero-copy/mmap reader vs the
legacy copying reader (byte-identical, incl. str columns and sparse
``__offset`` compacted segments), read-batch coalescing position
exactness, and prefetch on/off equivalence.

The byte-identity discipline: every fast path here must produce — or
read back — EXACTLY what the legacy path does; speed may never change
bytes (the PR-5 HostPool determinism contract applied to the log
tier)."""
import os

import numpy as np
import pytest

from flink_tpu import faults
from flink_tpu.log import LogSink, LogSource, TopicReader, create_topic
from flink_tpu.log.bus import Compactor
from flink_tpu.log.topic import TopicAppender, _list_markers
from flink_tpu.fs import get_filesystem

pytestmark = [pytest.mark.log]


def _batch(rng, n, base=0):
    return {
        "k": (base + rng.integers(0, 50, n)).astype(np.int64),
        "seq": np.arange(base, base + n, dtype=np.int64),
        "v": rng.random(n).astype(np.float64),
        "tag": np.array([f"t{int(x)}" for x in rng.integers(0, 9, n)],
                        dtype=object),
    }


def _read_all(path, zero_copy):
    """Every committed row of every partition, fully materialized."""
    r = TopicReader(path, zero_copy=zero_copy)
    out = {}
    for p in range(r.partitions):
        rows = []
        for off, b in r.read(p):
            rows.append((off, {k: np.asarray(v).tolist()
                               for k, v in b.items()}))
        out[p] = rows
    return out


class TestGroupFsync:
    """fsync-mode=group: ONE fsync pass over all staged segments that
    completes strictly BEFORE the pre-commit marker publishes —
    asserted by injection, not by comment."""

    def test_fsync_fault_leaves_no_pre_marker(self, tmp_path):
        """An injected fsync failure in the group pass must abort the
        stage BEFORE the pre-commit marker exists: the 2PC visibility
        chain (durable segments -> marker) is ordered, so a crashed
        group pass can never leave a recoverable transaction over
        un-durable bytes."""
        topic = str(tmp_path / "t")
        ap = TopicAppender(topic, 2, fsync_mode="group")
        rng = np.random.default_rng(0)
        plan = faults.FaultPlan(seed=1).rule(
            "log.segment.fsync", "raise", count=1, after=0)
        with plan.activate():
            with pytest.raises(OSError):
                ap.stage(1, {0: [_batch(rng, 16)],
                             1: [_batch(rng, 16, base=100)]})
        assert [x[:2] for x in plan.log] == [("log.segment.fsync",
                                              "raise")]
        fs = get_filesystem(topic)
        assert _list_markers(fs, topic, "pre") == {}, (
            "group fsync must complete before the pre-commit marker "
            "publishes")
        # recovery sweeps the un-markered debris; a clean restage works
        ap.recover()
        assert ap.stage(1, {0: [_batch(rng, 16)]})
        ap.commit(1)

    def test_group_fsync_fires_once_per_segment(self, tmp_path):
        """Same log.segment.fsync count as per-segment mode — chaos
        schedules seeded on the legacy cadence keep their meaning."""
        rng = np.random.default_rng(1)
        pending = {0: [_batch(rng, 40)], 1: [_batch(rng, 40, 100)]}
        counts = {}
        for mode in ("group", "segment"):
            topic = str(tmp_path / mode)
            ap = TopicAppender(topic, 2, segment_records=16,
                               fsync_mode=mode)
            plan = faults.FaultPlan(seed=2).rule(
                "log.segment.fsync", "delay", delay_ms=0.0, after=0)
            with plan.activate():
                assert ap.stage(1, pending)
            counts[mode] = len(plan.log)
        assert counts["group"] == counts["segment"] > 0

    def test_modes_produce_identical_bytes(self, tmp_path):
        rng = np.random.default_rng(2)
        pending = {0: [_batch(rng, 33)], 1: [_batch(rng, 21, 500)]}
        reads = {}
        for mode in ("group", "segment"):
            topic = str(tmp_path / mode)
            ap = TopicAppender(topic, 2, segment_records=16,
                               fsync_mode=mode)
            assert ap.stage(1, pending)
            ap.commit(1)
            reads[mode] = _read_all(topic, zero_copy=False)
        assert reads["group"] == reads["segment"]

    def test_bad_mode_rejected(self, tmp_path):
        from flink_tpu.log.topic import LogError

        with pytest.raises(LogError, match="fsync-mode"):
            TopicAppender(str(tmp_path / "t"), 1, fsync_mode="bogus")


class TestParallelPartitionIO:
    """stage() through the driver's HostPool: per-partition segment
    writes overlap, files stay byte-identical to the serial path."""

    def test_pool_stage_matches_serial(self, tmp_path):
        from flink_tpu.parallel.hostpool import HostPool

        rng = np.random.default_rng(3)
        pending = {p: [_batch(rng, 50, base=1000 * p)]
                   for p in range(4)}
        pool = HostPool(4)
        try:
            ap_par = TopicAppender(str(tmp_path / "par"), 4,
                                   segment_records=16, host_pool=pool)
            assert ap_par.stage(1, pending)
            ap_par.commit(1)
        finally:
            pool.close()
        ap_ser = TopicAppender(str(tmp_path / "ser"), 4,
                               segment_records=16)
        assert ap_ser.stage(1, pending)
        ap_ser.commit(1)
        par = _read_all(str(tmp_path / "par"), zero_copy=False)
        ser = _read_all(str(tmp_path / "ser"), zero_copy=False)
        assert par == ser
        # the segment FILES are byte-identical too, not just the reads
        for p in range(4):
            names_par = sorted(os.listdir(tmp_path / "par" / f"p{p}"))
            names_ser = sorted(os.listdir(tmp_path / "ser" / f"p{p}"))
            assert names_par == names_ser
            for n in names_par:
                a = (tmp_path / "par" / f"p{p}" / n).read_bytes()
                b = (tmp_path / "ser" / f"p{p}" / n).read_bytes()
                assert a == b

    def test_logsink_host_pool_seam(self, tmp_path):
        from flink_tpu.parallel.hostpool import HostPool

        sink = LogSink(str(tmp_path / "t"), key_field="k",
                       partitions=2)
        pool = HostPool(2)
        try:
            sink.set_host_pool(pool)
            assert sink._appender.host_pool is pool
            rng = np.random.default_rng(4)
            sink.write(_batch(rng, 64))
            assert sink.stage_transaction(1)
            sink.commit_transaction(1)
        finally:
            pool.close()
        got = _read_all(str(tmp_path / "t"), zero_copy=True)
        assert sum(len(rows) for rows in got.values()) == 2


class TestZeroCopyReader:
    """The mmap/view read mode returns byte-identical batches to the
    copying reader — raw topics, compacted (sparse __offset) topics,
    str columns — and keeps every corruption loud."""

    def _make_topic(self, tmp_path, compact=False):
        topic = str(tmp_path / "t")
        ap = TopicAppender(topic, 2, segment_records=16, key_field="k")
        rng = np.random.default_rng(5)
        for cid in (1, 2, 3):
            assert ap.stage(cid, {0: [_batch(rng, 40)],
                                  1: [_batch(rng, 24, base=777)]})
            ap.commit(cid)
        if compact:
            res = Compactor(topic, min_segments=1).compact()
            assert res["gen"] == 1
        return topic

    @pytest.mark.parametrize("compact", [False, True])
    def test_randomized_round_trip_matches_legacy(self, tmp_path,
                                                  compact):
        topic = self._make_topic(tmp_path, compact=compact)
        assert _read_all(topic, True) == _read_all(topic, False)

    def test_decode_performs_no_payload_copy(self, tmp_path):
        """Regression guard: fixed-width columns come back as VIEWS
        (``.base`` chains to the file image) and are read-only — a
        future change silently reintroducing the copy fails here."""
        topic = self._make_topic(tmp_path)
        r = TopicReader(topic, zero_copy=True)
        _, batch = next(iter(r.read(0)))
        for name in ("k", "seq", "v"):
            arr = batch[name]
            assert arr.base is not None, (
                f"column {name} was copied, not viewed")
            assert not arr.flags.writeable
        # and the copying reader really copies (the control)
        r2 = TopicReader(topic, zero_copy=False)
        _, batch2 = next(iter(r2.read(0)))
        assert batch2["k"].base is None

    def test_corruption_truncation_footer_loss_still_loud(self,
                                                          tmp_path):
        from flink_tpu.formats_columnar import ColumnarError
        from flink_tpu.log.topic import LogError

        topic = self._make_topic(tmp_path)
        pdir = tmp_path / "t" / "p0"
        seg = sorted(p for p in pdir.iterdir()
                     if p.name.endswith(".colb"))[0]
        golden = seg.read_bytes()

        def read_all():
            return _read_all(topic, zero_copy=True)

        # CRC corruption: flip one payload byte mid-file
        seg.write_bytes(golden[:200] + bytes([golden[200] ^ 0xFF])
                        + golden[201:])
        with pytest.raises(ColumnarError, match="CRC"):
            read_all()
        # truncation: cut mid-block
        seg.write_bytes(golden[:len(golden) // 2])
        with pytest.raises((ColumnarError, LogError)):
            read_all()
        # footer loss: chop exactly the footer (16 bytes)
        seg.write_bytes(golden[:-16])
        with pytest.raises(ColumnarError):
            read_all()
        seg.write_bytes(golden)
        read_all()  # restored: clean again


class TestCoalescingAndPrefetch:
    """Read-batch coalescing + segment readahead: same rows, same
    replay positions, bigger batches."""

    def _topic(self, tmp_path, compact=False):
        topic = str(tmp_path / "t")
        ap = TopicAppender(topic, 1, segment_records=8, key_field="k")
        rng = np.random.default_rng(6)
        for cid in (1, 2):
            assert ap.stage(cid, {0: [_batch(rng, 40)]})
            ap.commit(cid)
        if compact:
            assert Compactor(topic, min_segments=1).compact()["gen"] == 1
        return topic

    def _drain(self, src, start=0, stop_after=None):
        """(rows, positions) — positions advanced per consumed batch
        exactly as the driver does (position_after on the identical
        dict)."""
        it = src.open_split("0", start)
        rows, pos, batches = [], start, 0
        try:
            for data, ts in it:
                pos = src.position_after(pos, data, ts)
                rows.extend(np.asarray(data["seq"]).tolist())
                batches += 1
                if stop_after is not None and batches >= stop_after:
                    break
        finally:
            close = getattr(it, "close", None)
            if close:
                close()
        return rows, pos, batches

    @pytest.mark.parametrize("compact", [False, True])
    def test_coalesced_resume_is_position_exact(self, tmp_path,
                                                compact):
        topic = self._topic(tmp_path, compact=compact)
        full, _, _ = self._drain(
            LogSource(topic, ts_field="seq", batch_records=0,
                      prefetch_segments=0))
        # consume ONE coalesced batch, then resume at its position:
        # head + tail must equal the full read, no gap, no re-delivery
        src = LogSource(topic, ts_field="seq", batch_records=24,
                        prefetch_segments=0)
        head, pos, batches = self._drain(src, stop_after=1)
        assert batches == 1 and len(head) >= 24, (
            "coalescing must merge the 8-row blocks")
        tail, _, _ = self._drain(
            LogSource(topic, ts_field="seq", batch_records=24,
                      prefetch_segments=0), start=pos)
        assert head + tail == full

    def test_prefetch_on_off_identical(self, tmp_path):
        topic = self._topic(tmp_path)
        base, pos0, _ = self._drain(
            LogSource(topic, ts_field="seq", prefetch_segments=0))
        pref, pos1, _ = self._drain(
            LogSource(topic, ts_field="seq", prefetch_segments=2))
        assert base == pref and pos0 == pos1

    def test_readahead_close_joins_feeder(self, tmp_path):
        import threading

        topic = self._topic(tmp_path)
        src = LogSource(topic, ts_field="seq", prefetch_segments=1)
        before = threading.active_count()
        it = src.open_split("0", 0)
        next(it)  # feeder live
        it.close()
        # bounded wait: the feeder must exit once closed
        deadline = 50
        while threading.active_count() > before and deadline:
            import time

            time.sleep(0.02)
            deadline -= 1
        assert threading.active_count() <= before

    def test_negative_knobs_rejected(self, tmp_path):
        from flink_tpu.log.topic import LogError

        topic = self._topic(tmp_path)
        with pytest.raises(LogError, match="batch_records"):
            LogSource(topic, batch_records=-1)
        with pytest.raises(LogError, match="prefetch_segments"):
            LogSource(topic, prefetch_segments=-2)
