"""Message-bus tier over the durable log (ISSUE 9): key compaction
with offset preservation and the committed-offset safety floor,
time/size retention, fenced per-partition writer leases, consumer
groups with cross-generation resume, and the backfill-then-live shape
(bootstrap from compacted history, cut over to the live tail). Chaos
coverage (injection at every ``log.compact.*`` / ``log.retention.*`` /
``log.lease.*`` / ``log.group.*`` point) lives in
tests/test_log_chaos.py."""
import os

import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import TransactionalCollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.config import Configuration
from flink_tpu.log import (
    Compactor,
    ConsumerGroups,
    LeaseError,
    LeaseManager,
    LogSink,
    LogSource,
    Retention,
    TopicAppender,
    TopicReader,
    describe_topic,
    topic_key_field,
)

pytestmark = pytest.mark.log

PARTS = 2
KEYS = 5


def fill_topic(topic, txns=4, rows=10, segment_records=8):
    """Keyed upsert stream: each transaction overwrites the same small
    key domain with a strictly increasing value — latest-per-key is
    well-defined and changes every transaction."""
    ap = TopicAppender(topic, PARTS, segment_records=segment_records,
                      key_field="k")
    for cid in range(1, txns + 1):
        batch = {}
        for p in range(PARTS):
            seq = (cid - 1) * rows + np.arange(rows, dtype=np.int64)
            batch[p] = [{"k": seq % KEYS + p * 100,
                         "v": seq,
                         "ts": seq * 10}]
        assert ap.stage(cid, batch)
        ap.commit(cid)
    return ap


def full_rows(topic, p, start=0):
    out = []
    for off, _nxt, b in TopicReader(topic).read3(p, start):
        for i in range(len(b["k"])):
            out.append((int(b["k"][i]), int(b["v"][i]), int(b["ts"][i])))
    return out


def latest_per_key(rows):
    d = {}
    for k, v, ts in rows:
        d[k] = (v, ts)
    return dict(sorted(d.items()))


class TestCompaction:
    def test_latest_per_key_offsets_and_end_preserved(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic)
        golden = {p: latest_per_key(full_rows(topic, p))
                  for p in range(PARTS)}
        end = TopicReader(topic).committed_offsets()
        ConsumerGroups.commit(topic, "g", dict(end))
        res = Compactor(topic).compact()
        assert res["gen"] == 1
        r = TopicReader(topic)
        assert r.generation == 1
        assert r.committed_offsets() == end, (
            "compaction must never move the committed end")
        for p in range(PARTS):
            rows = full_rows(topic, p)
            assert len(rows) == KEYS
            assert latest_per_key(rows) == golden[p]
            # surviving offsets are ORIGINAL: each survivor's v is the
            # key's last write, and mid-range reads slice sparsely
            assert res["partitions"][p]["rows_out"] == KEYS

    def test_key_field_from_topic_meta(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic)
        assert topic_key_field(topic) == "k"
        ConsumerGroups.commit(
            topic, "g", dict(TopicReader(topic).committed_offsets()))
        assert Compactor(topic).compact()["gen"] == 1  # key from meta

    def test_group_floor_bounds_compaction(self, tmp_path):
        """Never compact past the lowest consumer-group committed
        offset: the group at offset 16 pins everything above it."""
        topic = str(tmp_path / "t")
        fill_topic(topic)
        before = {p: full_rows(topic, p) for p in range(PARTS)}
        ConsumerGroups.commit(topic, "slow", {0: 16, 1: 16})
        ConsumerGroups.commit(topic, "fast", dict(
            TopicReader(topic).committed_offsets()))
        res = Compactor(topic, min_segments=1).compact()
        for p in range(PARTS):
            # the 16 floor aligns DOWN to the sealed-segment boundary
            # at 10 — a mid-segment group offset pins the segment raw
            assert res["partitions"][p]["floor"] == 10
            # the tail above the group offset is byte-identical
            assert full_rows(topic, p, 16) == before[p][16:]
            assert full_rows(topic, p, 10) == before[p][10:]

    def test_staged_txn_bounds_compaction(self, tmp_path):
        """An open pre-commit marker pins compaction below its base —
        an in-flight 2PC could still roll back or re-commit."""
        topic = str(tmp_path / "t")
        ap = fill_topic(topic)
        staged_base = ap.next_offset(0)
        batch = {0: [{"k": np.arange(4, dtype=np.int64),
                      "v": np.arange(4, dtype=np.int64),
                      "ts": np.arange(4, dtype=np.int64)}]}
        assert ap.stage(99, batch)  # staged, never committed
        ConsumerGroups.commit(topic, "g", {0: staged_base + 4, 1: 40})
        res = Compactor(topic, min_segments=1).compact()
        assert res["partitions"][0]["floor"] == staged_base

    def test_no_groups_compacts_to_committed_end(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic)
        end = TopicReader(topic).committed_offsets()
        res = Compactor(topic).compact()
        assert {p: e["floor"] for p, e in res["partitions"].items()} \
            == end

    def test_second_generation_supersedes(self, tmp_path):
        topic = str(tmp_path / "t")
        ap = fill_topic(topic)
        Compactor(topic).compact()
        # more history on top, then compact again: gen 2 folds the
        # gen-1 sparse segments with the new raw tail
        for cid in (10, 11):
            seq = cid * 100 + np.arange(10, dtype=np.int64)
            assert ap.stage(cid, {p: [{"k": seq % KEYS + p * 100,
                                       "v": seq, "ts": seq}]
                                  for p in range(PARTS)})
            ap.commit(cid)
        golden = {p: latest_per_key(full_rows(topic, p))
                  for p in range(PARTS)}
        res = Compactor(topic, min_segments=1).compact()
        assert res["gen"] == 2
        for p in range(PARTS):
            assert latest_per_key(full_rows(topic, p)) == golden[p]
            assert full_rows(topic, p) == sorted(
                full_rows(topic, p))  # offset order
            assert len(full_rows(topic, p)) == KEYS

    def test_reused_committed_cid_refused_loudly(self, tmp_path):
        """Verify-drive regression: a fresh producer run whose
        checkpoint ids restart at 1 must NOT silently lose its rows —
        commit(1) would see the previous run's marker and 'succeed'
        without publishing. stage() refuses the reused id loudly."""
        from flink_tpu.log import LogError

        topic = str(tmp_path / "t")
        ap = fill_topic(topic)  # committed cids 1..4
        ap2 = TopicAppender(topic, PARTS, segment_records=8)
        seq = np.arange(4, dtype=np.int64)
        with pytest.raises(LogError, match="reused checkpoint id"):
            ap2.stage(1, {0: [{"k": seq, "v": seq, "ts": seq}]})
        # fresh ids (the bounded-run ms-timestamp epoch path) work
        assert ap2.stage(10 ** 12, {0: [{"k": seq % KEYS, "v": seq,
                                         "ts": seq}]})
        ap2.commit(10 ** 12)
        assert TopicReader(topic).committed_offsets()[0] == 44

    def test_appender_continues_after_compaction(self, tmp_path):
        """Offsets chain on: a producer appending AFTER a compaction
        pass continues from the original committed end."""
        topic = str(tmp_path / "t")
        ap = fill_topic(topic)
        end = dict(TopicReader(topic).committed_offsets())
        Compactor(topic).compact()
        ap2 = TopicAppender(topic, PARTS, segment_records=8)
        assert {p: ap2.next_offset(p) for p in range(PARTS)} == end
        seq = np.arange(6, dtype=np.int64)
        assert ap2.stage(50, {0: [{"k": seq % KEYS, "v": seq + 999,
                                   "ts": seq}]})
        ap2.commit(50)
        r = TopicReader(topic)
        assert r.committed_offsets()[0] == end[0] + 6


class TestMaintenanceLock:
    def test_concurrent_pass_refused(self, tmp_path):
        """One maintenance pass at a time per topic: last-rename-wins
        on manifest.json would let two concurrent passes delete each
        other's referenced files."""
        from flink_tpu.log import LogError
        from flink_tpu.log.topic import (release_maintenance_lock,
                                         try_maintenance_lock)

        topic = str(tmp_path / "t")
        fill_topic(topic)
        fd = try_maintenance_lock(topic)
        assert fd is not None
        try:
            with pytest.raises(LogError,
                               match="another maintenance pass"):
                Compactor(topic).compact()
            with pytest.raises(LogError,
                               match="another maintenance pass"):
                Retention(topic, retention_ms=1, ts_field="ts",
                          now_fn=lambda: 10 ** 12).apply()
        finally:
            release_maintenance_lock(topic, fd)
        assert Compactor(topic).compact()["gen"] == 1  # lock released

    def test_sweep_keeps_cmp_files_while_pass_runs(self, tmp_path):
        """THE review race: a producer-attempt recovery sweep racing a
        live pass's pre-swap window must NOT delete unreferenced cmp
        files — the imminent manifest rename is about to reference
        them. While the maintenance lock is held, sweep skips cmp
        cleanup; afterwards it removes real debris."""
        import os as _os

        from flink_tpu.log.topic import (_partition_dir,
                                         release_maintenance_lock,
                                         try_maintenance_lock)

        topic = str(tmp_path / "t")
        ap = fill_topic(topic)
        # a live pass's pre-swap output: an unreferenced cmp file
        debris = _os.path.join(_partition_dir(topic, 0),
                               "cmp-000001-000000000000.colb")
        with open(debris, "wb") as f:
            f.write(b"pre-swap output of a live pass")
        fd = try_maintenance_lock(topic)
        try:
            ap.sweep_orphans()
            assert _os.path.exists(debris), (
                "sweep deleted a live pass's pre-swap cmp file")
        finally:
            release_maintenance_lock(topic, fd)
        ap.sweep_orphans()
        assert not _os.path.exists(debris)  # real debris now


class TestRetention:
    def test_time_retention_below_group_floor_only(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic)
        before = {p: full_rows(topic, p) for p in range(PARTS)}
        ConsumerGroups.commit(topic, "g", {0: 16, 1: 16})
        res = Retention(topic, retention_ms=1, ts_field="ts",
                        now_fn=lambda: 10 ** 12).apply()
        r = TopicReader(topic)
        # the 16 floor aligns DOWN to the segment boundary at 10:
        # retention drops whole sealed segments only
        assert res["start"] == {0: 10, 1: 10}
        assert r.start_offsets() == {0: 10, 1: 10}
        for p in range(PARTS):
            # the group's tail is untouched; below the floor is gone
            assert full_rows(topic, p, 16) == before[p][16:]
            assert full_rows(topic, p) == before[p][10:]

    def test_size_retention_respects_budget(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic, txns=6)
        sizes_before = describe_topic(topic)["segments"]
        res = Retention(topic, retention_bytes=1500).apply()
        assert res["dropped"], (res, sizes_before)
        # committed end unchanged — retention drops history, not the
        # high-water mark
        assert TopicReader(topic).committed_offsets() == {
            p: 60 for p in range(PARTS)}

    def test_replay_position_below_floor_is_loud(self, tmp_path):
        """Review regression: an ANONYMOUS reader's checkpointed
        position below the retention floor must raise, never silently
        yield nothing — the rows the checkpoint promised to replay are
        gone (its positions are not part of the safety floor; only
        groups pin history). start 0 stays legal: a fresh consumer
        reads 'from earliest available' by design."""
        from flink_tpu.log import LogError

        topic = str(tmp_path / "t")
        fill_topic(topic)
        Retention(topic, retention_ms=1, ts_field="ts",
                  now_fn=lambda: 10 ** 12).apply()
        r = TopicReader(topic)
        assert r.start_offsets()[0] == 40
        assert list(r.read3(0, 0)) == []  # from-earliest: legal, empty
        with pytest.raises(LogError, match="below the retention floor"):
            list(r.read3(0, 16))
        src = LogSource(topic, ts_field="ts")
        with pytest.raises(LogError, match="below the retention floor"):
            list(src.open_split("0", 16))

    def test_young_segments_survive(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic)
        res = Retention(topic, retention_ms=10 ** 15, ts_field="ts"
                        ).apply()
        assert res["dropped"] == {}
        assert TopicReader(topic).generation == 0

    def test_retention_of_compacted_segments(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic)
        Compactor(topic).compact()
        res = Retention(topic, retention_ms=1, ts_field="ts",
                        now_fn=lambda: 10 ** 12).apply()
        assert res["gen"] == 2
        for p in range(PARTS):
            assert full_rows(topic, p) == []
        # the high-water mark survives total expiry
        assert TopicReader(topic).committed_offsets() == {
            p: 40 for p in range(PARTS)}


class TestLeases:
    def test_acquire_renew_release_epochs(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic)
        a = LeaseManager(topic, "a", [0, 1], ttl_ms=60_000)
        assert a.acquire() == {0: 1, 1: 1}
        a.verify()  # renews
        # same owner re-acquire (attempt restart) keeps the epoch
        a2 = LeaseManager(topic, "a", [0, 1], ttl_ms=60_000)
        assert a2.acquire() == {0: 1, 1: 1}
        a2.release()
        # released: a fresh owner starts at epoch 2 (monotone fencing)
        b = LeaseManager(topic, "b", [0], ttl_ms=60_000)
        assert b.acquire() == {0: 2}

    def test_failed_acquire_rolls_back_partial_hold(self, tmp_path):
        """Review regression: acquire is all-or-nothing — when p1 is
        held by another producer, the p0 lease written moments earlier
        is rolled back (released) before the error escapes, so a
        correctly configured producer can take p0 immediately instead
        of waiting out the dead attempt's ttl."""
        topic = str(tmp_path / "t")
        fill_topic(topic)
        LeaseManager(topic, "a", [1], ttl_ms=60_000).acquire()
        with pytest.raises(LeaseError, match="leased by 'a'"):
            LeaseManager(topic, "b", [0, 1], ttl_ms=60_000).acquire()
        # p0 is free right now — no ttl wait
        c = LeaseManager(topic, "c", [0], ttl_ms=60_000)
        assert c.acquire() == {0: 2}

    def test_empty_owned_set_refused_at_construction(self, tmp_path):
        from flink_tpu.log import LogError

        with pytest.raises(LogError, match="non-empty"):
            LogSink(str(tmp_path / "t"), key_field="k", partitions=2,
                    owned_partitions=[], producer_id="w")

    def test_held_lease_rejects_second_owner(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic)
        LeaseManager(topic, "a", [0], ttl_ms=60_000).acquire()
        with pytest.raises(LeaseError, match="leased by 'a'"):
            LeaseManager(topic, "b", [0], ttl_ms=60_000).acquire()

    def test_expired_takeover_deposes_by_epoch(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic)
        a = LeaseManager(topic, "a", [0], ttl_ms=60_000)
        a.acquire()
        b = LeaseManager(topic, "b", [0], ttl_ms=60_000,
                         now_fn=lambda: int(1e18))
        assert b.acquire() == {0: 2}
        with pytest.raises(LeaseError, match="DEPOSED"):
            a.verify()

    def test_deposed_writer_stage_rejected(self, tmp_path):
        """The acceptance fence: a deposed leaseholder's late write
        raises at the marker-publication gate, never publishes."""
        topic = str(tmp_path / "t")
        sink_a = LogSink(topic, key_field="k", partitions=2,
                         owned_partitions=[0], producer_id="a",
                         lease_ttl_ms=1)
        sink_a.write({"k": np.arange(8, dtype=np.int64),
                      "v": np.arange(8, dtype=np.int64),
                      "ts": np.arange(8, dtype=np.int64)})
        import time as _t

        _t.sleep(0.01)  # a's 1ms lease expires
        sink_b = LogSink(topic, key_field="k", partitions=2,
                         owned_partitions=[0], producer_id="b",
                         lease_ttl_ms=60_000)
        # leases acquire lazily: b's first write takes the expired
        # partition over (epoch bump) — THEN a is deposed
        sink_b.write({"k": np.arange(4, dtype=np.int64),
                      "v": np.arange(4, dtype=np.int64),
                      "ts": np.arange(4, dtype=np.int64)})
        with pytest.raises(LeaseError, match="DEPOSED"):
            sink_a.prepare_commit(1)
        # b owns the partition and publishes fine
        sink_b.prepare_commit(1)
        sink_b.notify_checkpoint_complete(1)
        assert TopicReader(topic).committed_offsets()[0] == 4

    def test_legacy_recover_rolls_back_foreign_staged(self, tmp_path):
        """Review regression: a legacy (unleased) writer claims the
        WHOLE topic, so its recovery must roll back a dead LEASED
        producer's writer-scoped staged transaction too — left in
        place it holds its offsets forever and the never-committed
        range reads as a permanent contiguity gap."""
        topic = str(tmp_path / "t")
        sink = LogSink(topic, key_field="k", partitions=2,
                       owned_partitions=[0], producer_id="dead",
                       lease_ttl_ms=1)
        sink.write({"k": np.arange(8, dtype=np.int64),
                    "v": np.arange(8, dtype=np.int64),
                    "ts": np.arange(8, dtype=np.int64)})
        sink.prepare_commit(1)  # staged; the producer dies here
        legacy = LogSink(topic, key_field="k", partitions=2)
        d = describe_topic(topic)
        assert d["writer_transactions"]["staged"] == {}, d
        assert legacy._appender.next_offset(0) == 0
        legacy.write({"k": np.arange(4, dtype=np.int64),
                      "v": np.arange(4, dtype=np.int64),
                      "ts": np.arange(4, dtype=np.int64)})
        legacy.prepare_commit(1)
        legacy.notify_checkpoint_complete(1)
        # the topic reads whole — no contiguity gap (the legacy sink
        # hash-routes its 4 keys across both partitions)
        assert sum(len(full_rows(topic, p)) for p in range(PARTS)) == 4

    def test_renew_skips_fresh_deadlines(self, tmp_path):
        """Review regression: verify(renew=True) rewrites the lease
        file only once less than half the ttl remains — the 2PC hot
        path must not pay P fsyncs per marker for a fresh lease."""
        topic = str(tmp_path / "t")
        fill_topic(topic)
        now = [1000]
        lm = LeaseManager(topic, "a", [0], ttl_ms=10_000,
                          now_fn=lambda: now[0])
        lm.acquire()
        deadline0 = lm._read(0)["deadline_ms"]
        now[0] += 1000  # 9s remain > ttl/2: no rewrite
        lm.verify()
        assert lm._read(0)["deadline_ms"] == deadline0
        now[0] += 5000  # 4s remain < ttl/2: renewed
        lm.verify()
        assert lm._read(0)["deadline_ms"] == now[0] + 10_000

    def test_takeover_aborts_deposed_staged_txn(self, tmp_path):
        """A dead producer's pre-committed-but-uncommitted transaction
        on a taken-over partition is rolled back by the successor's
        recovery — never lingers as phantom stageable state."""
        topic = str(tmp_path / "t")
        sink_a = LogSink(topic, key_field="k", partitions=2,
                         owned_partitions=[0], producer_id="a",
                         lease_ttl_ms=1)
        sink_a.write({"k": np.arange(8, dtype=np.int64),
                      "v": np.arange(8, dtype=np.int64),
                      "ts": np.arange(8, dtype=np.int64)})
        sink_a.prepare_commit(1)  # staged, a dies before commit
        import time as _t

        _t.sleep(0.01)
        sink_b = LogSink(topic, key_field="k", partitions=2,
                         owned_partitions=[0], producer_id="b",
                         lease_ttl_ms=60_000)
        # leases acquire lazily: b's first WRITE opens it — acquire +
        # takeover recovery sweep
        sink_b.write({"k": np.arange(2, dtype=np.int64),
                      "v": np.arange(2, dtype=np.int64),
                      "ts": np.arange(2, dtype=np.int64)})
        d = describe_topic(topic)
        assert d["writer_transactions"]["staged"] == {}, d
        # the successor starts at offset 0 — a's staged rows are gone
        assert sink_b._appender.next_offset(0) == 0


class TestConsumerGroups:
    def test_invalid_group_name_refused_at_construction(self,
                                                        tmp_path):
        from flink_tpu.log import LogError

        with pytest.raises(LogError, match="consumer-group name"):
            LogSource(str(tmp_path / "t"), group="dash/boards")

    def test_static_assignment_disjoint(self, tmp_path):
        assert ConsumerGroups.assignment(4, 0, 2) == [0, 2]
        assert ConsumerGroups.assignment(4, 1, 2) == [1, 3]
        with pytest.raises(Exception):
            ConsumerGroups.assignment(4, 2, 2)

    def test_commit_max_merges_never_regresses(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic)
        ConsumerGroups.commit(topic, "g", {0: 30})
        ConsumerGroups.commit(topic, "g", {0: 10})  # replayed commit
        assert ConsumerGroups.committed(topic, "g") == {0: 30}

    def test_two_members_split_partitions_and_commit(self, tmp_path):
        topic = str(tmp_path / "t")
        fill_topic(topic)
        rows = {}
        for member in range(2):
            src = LogSource(topic, ts_field="ts", group="g",
                            member_index=member, members=2)
            assert src.splits() == [str(member)]
            sink = TransactionalCollectSink()
            env = StreamExecutionEnvironment(Configuration(
                {"pipeline.microbatch-size": 8}))
            env.from_source(src).add_sink(sink)
            env.execute(f"member-{member}")
            rows[member] = sorted(
                (int(r["k"]), int(r["v"])) for r in sink.committed)
        assert len(rows[0]) == 40 and len(rows[1]) == 40
        assert {k for k, _ in rows[0]}.isdisjoint(
            k for k, _ in rows[1])
        # both members' final positions are on file
        assert ConsumerGroups.committed(topic, "g") == {0: 40, 1: 40}

    def test_generation_resume_reads_exactly_once(self, tmp_path):
        topic = str(tmp_path / "t")
        ap = fill_topic(topic)

        def consume(tag):
            sink = TransactionalCollectSink()
            env = StreamExecutionEnvironment(Configuration(
                {"pipeline.microbatch-size": 8}))
            env.from_source(
                LogSource(topic, ts_field="ts", group="g")).add_sink(sink)
            env.execute(tag)
            return sorted((int(r["k"]), int(r["v"]))
                          for r in sink.committed)

        first = consume("gen1")
        assert len(first) == 80
        assert consume("gen2") == []  # the group already read it all
        # new history → generation 3 reads ONLY the tail
        seq = 777 + np.arange(6, dtype=np.int64)
        assert ap.stage(9, {0: [{"k": seq % KEYS, "v": seq,
                                 "ts": seq}]})
        ap.commit(9)
        third = consume("gen3")
        assert sorted(v for _, v in third) == list(range(777, 783))


class TestBackfillThenLive:
    def test_bootstrap_from_compacted_history_then_live_tail(
            self, tmp_path):
        """THE backfill-then-live shape (acceptance #5's correctness
        core): a new consumer group bootstraps from compacted history
        (latest row per key), cuts over to the live tail, and its
        committed output matches the never-compacted reference run's
        MATERIALIZED TABLE (latest-per-key — the contract a
        key-compacted topic makes; row-for-row history below the floor
        is intentionally gone)."""
        topic = str(tmp_path / "t")
        ref_topic = str(tmp_path / "ref")
        ap = fill_topic(topic)
        fill_topic(ref_topic)  # identical, never compacted
        Compactor(topic).compact()

        def consume(path, group):
            sink = TransactionalCollectSink()
            env = StreamExecutionEnvironment(Configuration(
                {"pipeline.microbatch-size": 8}))
            env.from_source(
                LogSource(path, ts_field="ts", group=group)
            ).add_sink(sink)
            env.execute(f"backfill-{group}")
            return [(int(r["k"]), int(r["v"])) for r in sink.committed]

        # phase 1: backfill from compacted history
        backfill = consume(topic, "job")
        assert len(backfill) == PARTS * KEYS  # latest per key only
        # phase 2: live tail lands (same appender generation), the
        # SAME group resumes past its committed offset
        for ap_, path in ((ap, topic),
                          (TopicAppender(ref_topic, PARTS,
                                         segment_records=8), ref_topic)):
            seq = 900 + np.arange(8, dtype=np.int64)
            assert ap_.stage(77, {p: [{"k": seq % KEYS + p * 100,
                                       "v": seq, "ts": seq}]
                                  for p in range(PARTS)})
            ap_.commit(77)
        live = consume(topic, "job")
        assert len(live) == PARTS * 8

        # reference: one never-compacted read of everything
        reference = consume(ref_topic, "ref")
        table = {}
        for k, v in reference:
            table[k] = max(table.get(k, -1), v)  # v increases per key
        got_table = {}
        for k, v in backfill + live:
            got_table[k] = max(got_table.get(k, -1), v)
        assert got_table == table

    def test_driver_restore_mid_compacted_read(self, tmp_path):
        """Replay positions are sparse-offset-exact: a checkpoint cut
        mid-way through compacted history restores WITHOUT duplicating
        or skipping surviving rows (position_after follows __offset)."""
        topic = str(tmp_path / "t")
        fill_topic(topic, txns=8, rows=10)
        ConsumerGroups.commit(
            topic, "pin", dict(TopicReader(topic).committed_offsets()))
        golden = {p: full_rows(topic, p) for p in range(PARTS)}
        Compactor(topic).compact()
        # sparse read equals itself across an arbitrary restore cut:
        # simulate the driver protocol — consume k batches, record
        # position_after, reopen at that position
        src = LogSource(topic, ts_field="ts")
        for p in ("0", "1"):
            it = src.open_split(p)
            data, ts = next(it)
            pos = src.position_after(0, data, ts)
            rest = []
            for data2, ts2 in src.open_split(p, pos):
                rest.extend(zip(data2["k"].tolist(),
                                data2["v"].tolist()))
            whole = list(zip(data["k"].tolist(), data["v"].tolist()))
            whole.extend(rest)
            assert whole == [(k, v) for k, v, _ in
                             full_rows(topic, int(p))]
            assert {k: v for k, v in whole} == {
                k: v for k, (v, _) in
                latest_per_key(golden[int(p)]).items()}
