"""Multi-tenant chaos (ISSUE 8 satellite): two CONCURRENT jobs on one
session cluster under ``faults.*`` injection — exactly-once per job,
and NO cross-job interference: one tenant's induced restart leaves the
other's committed output identical to its fault-free golden.

The isolation mechanism under test is the JOB-SCOPED fault plan
(faults.install_scoped + thread scopes): the faulty tenant's plan
injects only on threads serving that job (its run thread, drain
thread, checkpoint executor), so the co-resident tenant never sees an
injection even though both share one runner process — the situation
the process-global plan's docstring explicitly forbids co-scheduling
under.

Fault kinds: checkpoint-storage write failure (induces a full restart
+ restore of one tenant), RPC transport drop on a lifecycle report,
and the new ``session.admit`` dispatcher admission point.
"""
import os
import time

import pytest

from flink_tpu import faults
from flink_tpu.config import Configuration
from flink_tpu.runtime.session import LocalSessionCluster, SessionDispatcher

from test_runner_process import wait_until

pytestmark = [pytest.mark.session, pytest.mark.chaos]


def _cluster_conf():
    return Configuration({
        "heartbeat.interval": "200ms",
        "heartbeat.timeout": "5s",
        "session.autoscale": False,
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 3,
        "restart-strategy.fixed-delay.delay": "100ms",
    })


def _job_conf(tmp_path, tag, n_batches, faults_spec=None, seed=7):
    conf = {
        "test.n-batches": n_batches,
        "test.batch-sleep-ms": 40,
        "test.sink-dir": str(tmp_path / f"sink-{tag}"),
        "execution.checkpointing.dir": str(tmp_path / "chk"),
        "execution.checkpointing.interval": "150ms",
        "state.num-key-shards": 8,
        "state.slots-per-shard": 16,
    }
    if faults_spec:
        conf["faults.inject"] = faults_spec
        conf["faults.seed"] = seed
    return conf


def _committed(sink_dir):
    """Sorted committed rows (key, window_start, count) — the
    byte-equivalent comparable view of a FileTransactionalSink's
    output (row content is everything the sink commits; file
    boundaries follow checkpoint timing, which is wall-clock)."""
    from flink_tpu.api.sinks import FileTransactionalSink

    return sorted(
        (int(r["key"]), int(r["window_start"]), int(r["count"]))
        for r in FileTransactionalSink.committed_rows(sink_dir))


def _assert_exactly_once(sink_dir, n_batches):
    import runner_job
    from flink_tpu.api.sinks import FileTransactionalSink

    got = {}
    for r in FileTransactionalSink.committed_rows(sink_dir):
        kk = (int(r["key"]), int(r["window_start"]))
        assert kk not in got, f"duplicate emission for {kk}"
        got[kk] = int(r["count"])
    assert got == runner_job.golden_counts(n_batches)


class TestTwoTenantChaos:
    def test_storage_fault_restart_leaves_peer_untouched(self, tmp_path):
        """Tenant A takes an injected checkpoint-storage failure →
        full restart + restore from ITS checkpoint subtree; tenant B
        runs fault-free beside it the whole time. A must still commit
        exactly-once; B must commit its fault-free golden in ONE
        attempt, with its checkpoint subtree untouched by A's
        recovery."""
        n = 10
        # fault-free golden for B, alone on its own cluster
        with LocalSessionCluster(_cluster_conf(), runners=1,
                                 runner_prefix="golden") as c:
            r = c.submit("runner_job:build",
                         config=_job_conf(tmp_path / "solo", "b", n),
                         job_id="golden-b")
            assert r["admitted"]
            assert c.wait("golden-b") == "FINISHED"
        golden_b = _committed(str(tmp_path / "solo" / "sink-b"))
        assert golden_b

        with LocalSessionCluster(_cluster_conf(), runners=1,
                                 runner_prefix="chaos") as c:
            ra = c.submit(
                "runner_job:build",
                config=_job_conf(
                    tmp_path, "a", n,
                    # +1 (skip ONE write, then raise): the restart
                    # still restores from a completed checkpoint, and
                    # the schedule stays live on a loaded host where
                    # the ~400ms job may only reach 2 storage writes
                    # (with +2 the fault sometimes never fired and the
                    # 'induced a restart' assertion flaked under full-
                    # suite load)
                    faults_spec="checkpoint.storage.write=raise x1 +1"),
                job_id="chaos-a")
            rb = c.submit("runner_job:build",
                          config=_job_conf(tmp_path, "b", n),
                          job_id="live-b")
            assert ra["admitted"] and rb["admitted"]
            wait_until(
                lambda: all(c.dispatcher.jobs[j].state == "RUNNING"
                            for j in ("chaos-a", "live-b")), 30,
                what="both tenants running concurrently")
            assert c.wait("chaos-a") == "FINISHED"
            assert c.wait("live-b") == "FINISHED"
            # the fault fired and A actually recovered through restart
            assert c.dispatcher.jobs["chaos-a"].attempts >= 2, (
                "storage fault never induced a restart")
            # B never restarted: the injection was invisible to it
            assert c.dispatcher.jobs["live-b"].attempts == 1
        snap = faults.snapshot()
        assert snap.get("faults.checkpoint.storage.write.raise", 0) >= 1
        _assert_exactly_once(str(tmp_path / "sink-a"), n)
        # NO cross-job interference: B's committed output is identical
        # to its fault-free golden, row for row
        assert _committed(str(tmp_path / "sink-b")) == golden_b
        # and the checkpoint subtrees stayed disjoint per tenant
        assert sorted(os.listdir(tmp_path / "chk")) == [
            "chaos-a", "live-b"]

    def test_rpc_drop_scoped_to_one_tenant(self, tmp_path):
        """Transport drops on tenant A's lifecycle reports (scoped
        rpc.client.send) ride the report retry loop; tenant B's RPC
        traffic — sharing the same runner process and the same
        coordinator client — is never injected."""
        n = 6
        with LocalSessionCluster(_cluster_conf(), runners=1,
                                 runner_prefix="rpc") as c:
            ra = c.submit(
                "runner_job:build",
                config=_job_conf(tmp_path, "ra", n,
                                 faults_spec="rpc.client.send=drop x2"),
                job_id="rpc-a")
            rb = c.submit("runner_job:build",
                          config=_job_conf(tmp_path, "rb", n),
                          job_id="rpc-b")
            assert ra["admitted"] and rb["admitted"]
            assert c.wait("rpc-a") == "FINISHED"
            assert c.wait("rpc-b") == "FINISHED"
            assert c.dispatcher.jobs["rpc-b"].attempts == 1
        snap = faults.snapshot()
        assert snap.get("faults.rpc.client.send.drop", 0) >= 1
        _assert_exactly_once(str(tmp_path / "sink-ra"), n)
        _assert_exactly_once(str(tmp_path / "sink-rb"), n)


class TestAdmissionFaultPoint:
    def test_admit_fault_leaves_registry_consistent(self):
        """The dispatcher admission fault point (session.admit): an
        injected failure between RPC receipt and registry insert loses
        the submission cleanly — no half-registered job — and the
        caller's retry admits normally."""
        plan = faults.FaultPlan(seed=3).rule("session.admit", "raise",
                                             count=1)
        disp = SessionDispatcher(Configuration({
            "session.autoscale": False}))
        try:
            with plan.activate():
                with pytest.raises(RuntimeError) as e:
                    disp.rpc_submit_session_job("adm", "m:f", {})
                assert faults.is_injected(e.value)
                assert "adm" not in disp.jobs, (
                    "a failed admission must not half-register the job")
                r = disp.rpc_submit_session_job("adm", "m:f", {})
                assert r["admitted"]
                assert disp.jobs["adm"].state == "WAITING_FOR_RESOURCES"
            assert plan.log and plan.log[0][0] == "session.admit"
        finally:
            disp.close()


class TestScopedPlanMechanics:
    def test_scoped_plan_exclusive_to_its_thread_scope(self):
        faults.clear()
        plan = faults.install_scoped(
            "tenant-x",
            Configuration({"faults.inject": "host.pool.task=raise x1"}))
        try:
            assert plan is not None
            # unscoped thread: no injection
            faults.fire("host.pool.task")
            # peer scope: no injection
            with faults.job_scope("tenant-y"):
                faults.fire("host.pool.task")
            # the owning scope: injects
            with faults.job_scope("tenant-x"):
                with pytest.raises(RuntimeError):
                    faults.fire("host.pool.task")
                faults.fire("host.pool.task")  # x1 exhausted
        finally:
            faults.clear()

    def test_install_scoped_idempotent_preserves_counters(self):
        """A recovery re-deploy re-installs the same (spec, seed):
        the plan object — and its injection counters — must survive,
        or count-limited rules would re-fire on every attempt and the
        job could never complete."""
        faults.clear()
        conf = Configuration({"faults.inject": "dcn.send=drop x1",
                              "faults.seed": 11})
        try:
            p1 = faults.install_scoped("t", conf)
            with faults.job_scope("t"):
                with pytest.raises(ConnectionError):
                    faults.fire("dcn.send")
            p2 = faults.install_scoped("t", conf)  # the re-deploy
            assert p2 is p1
            with faults.job_scope("t"):
                faults.fire("dcn.send")  # still exhausted — no re-fire
            # a CHANGED spec is a new plan
            p3 = faults.install_scoped("t", Configuration(
                {"faults.inject": "dcn.send=drop x2", "faults.seed": 11}))
            assert p3 is not p1
            # empty spec uninstalls
            faults.install_scoped("t", Configuration({}))
            assert faults.scoped_plan("t") is None
        finally:
            faults.clear()

    def test_fresh_install_replaces_exhausted_plan(self):
        """A NEW submission reusing a job id (runner attempt 1 passes
        fresh=True) must not inherit a FAILED prior tenant's exhausted
        counters — its count-limited rules fire again (review
        regression)."""
        faults.clear()
        conf = Configuration({"faults.inject": "dcn.send=drop x1",
                              "faults.seed": 11})
        try:
            faults.install_scoped("t", conf)
            with faults.job_scope("t"):
                with pytest.raises(ConnectionError):
                    faults.fire("dcn.send")  # exhaust x1
            # same spec+seed, but a FRESH submission: counters reset
            p = faults.install_scoped("t", conf, fresh=True)
            assert p is faults.scoped_plan("t")
            with faults.job_scope("t"):
                with pytest.raises(ConnectionError):
                    faults.fire("dcn.send")
        finally:
            faults.clear()
