"""Stream partitioners (ref: streaming/runtime/partitioner/* and their
StreamPartitionerTest-style distribution property tests)."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.exchange.partitioners import (
    BroadcastPartitioner, GlobalPartitioner, RebalancePartitioner,
    RescalePartitioner, ShufflePartitioner, make_partitioner)
from flink_tpu.time.watermarks import WatermarkStrategy


class TestAssignmentProperties:
    def test_rebalance_exact_equal_spread(self):
        p = RebalancePartitioner()
        counts = np.zeros(4, np.int64)
        for b in (7, 13, 1, 11):  # ragged batches
            a = p.assign(b, 4)
            counts += np.bincount(a, minlength=4)
        assert counts.max() - counts.min() <= 1  # round-robin exactness

    def test_rebalance_cursor_continues_across_batches(self):
        p = RebalancePartitioner()
        a1 = p.assign(3, 4)
        a2 = p.assign(3, 4)
        assert list(a1) + list(a2) == [0, 1, 2, 3, 0, 1]

    def test_rescale_stays_in_group(self):
        p = RescalePartitioner(group=(2, 4))
        a = p.assign(10, 8)
        assert set(a.tolist()) == {2, 3}

    def test_shuffle_covers_and_replays_identically(self):
        p = ShufflePartitioner(seed=5)
        a = p.assign(10_000, 8)
        assert set(a.tolist()) == set(range(8))
        # restore replays the stream identically (exactly-once replays)
        snap = p.snapshot()
        nxt = p.assign(100, 8)
        q = ShufflePartitioner(seed=5)
        q.restore(snap)
        assert list(q.assign(100, 8)) == list(nxt)

    def test_global_and_broadcast(self):
        assert set(GlobalPartitioner().assign(50, 8).tolist()) == {0}
        bp = BroadcastPartitioner()
        assert bp.broadcast
        with pytest.raises(RuntimeError):
            bp.assign(1, 8)

    def test_factory(self):
        for s in ("rebalance", "rescale", "shuffle", "broadcast",
                  "global", "forward"):
            assert make_partitioner(s) is not None

    def test_shuffle_seeds_decorrelate(self):
        a = ShufflePartitioner(seed=1).assign(1000, 8)
        b = ShufflePartitioner(seed=2).assign(1000, 8)
        assert not np.array_equal(a, b)

    def test_advance_matches_assign_state(self):
        """advance() (the alloc-free p=1 path) must leave the same state
        as assign() — checkpointed cursors stay replay-consistent."""
        for mk in (RebalancePartitioner,
                   lambda: RescalePartitioner(group=(1, 3)),
                   lambda: ShufflePartitioner(seed=3)):
            p, q = mk(), mk()
            p.assign(7, 4)
            q.advance(7, 4)
            assert p.snapshot() == q.snapshot()

    def test_rebalance_snapshot_roundtrip(self):
        p = RebalancePartitioner()
        p.assign(5, 4)
        q = RebalancePartitioner()
        q.restore(p.snapshot())
        assert list(q.assign(3, 4)) == list(p.assign(3, 4))


class TestGraphAndDriver:
    def test_partition_breaks_chain_and_preserves_results(self):
        """A rebalance between two maps must not change results at
        parallelism 1 (the reference's behavior), and must lower to its
        own exchange node rather than fusing into the chain."""
        def gen(split, i):
            if i >= 3:
                return None
            return ({"v": np.arange(4, dtype=np.int64) + i * 4},
                    np.arange(4, dtype=np.int64) + i * 4)

        env = StreamExecutionEnvironment(Configuration(
            {"pipeline.microbatch-size": 8}))
        sink = CollectSink()
        (env.from_source(GeneratorSource(gen),
                         WatermarkStrategy.for_monotonous_timestamps())
         .map(lambda d: {"v": d["v"] * 2})
         .rebalance()
         .map(lambda d: {"v": d["v"] + 1})
         .add_sink(sink))
        from flink_tpu.graph.compiler import compile_job

        plan = compile_job(env._transforms, env.config,
                           env._watermark_strategy)
        kinds = [n.kind for n in plan.nodes.values()]
        assert "partition" in kinds
        env.execute("part")
        got = sorted(int(v) for r in sink.rows for v in
                     np.atleast_1d(r["v"]))
        assert got == sorted(int(v) * 2 + 1 for v in range(12))

    def test_all_strategies_run_e2e(self):
        for strat in ("rebalance", "rescale", "shuffle", "broadcast",
                      "global_"):
            def gen(split, i):
                if i >= 2:
                    return None
                return ({"k": np.arange(6, dtype=np.int64) % 3},
                        np.full(6, i * 1000 + 500, np.int64))

            env = StreamExecutionEnvironment(Configuration(
                {"pipeline.microbatch-size": 8,
                 "state.num-key-shards": 4, "state.slots-per-shard": 16}))
            sink = CollectSink()
            s = env.from_source(
                GeneratorSource(gen),
                WatermarkStrategy.for_monotonous_timestamps())
            s = getattr(s, strat)()
            (s.key_by("k").window(TumblingEventTimeWindows.of(1_000))
             .count().add_sink(sink))
            env.execute(f"p-{strat}")
            total = sum(int(r["count"]) for r in sink.rows)
            assert total == 12, strat  # parallelism 1: pass-through


class TestHybridRoute:
    """The two-coordinate keyed assignment of the hybrid ICI×DCN
    topology (exchange/partitioners.hybrid_route) — the ONE routing
    truth the host-side DCN router and the in-step local exchange
    share (ISSUE 12 layer 4)."""

    def test_process_coordinate_matches_contiguous_shard_spans(self):
        from flink_tpu.exchange.partitioners import (
            hash_shards,
            hybrid_route,
        )

        rng = np.random.default_rng(0)
        keys = rng.integers(-2**40, 2**40, 4096).astype(np.int64)
        proc, local = hybrid_route(keys, 128, 4, local_devices=8)
        shard = hash_shards(keys, 128)
        np.testing.assert_array_equal(proc, shard // 32)
        np.testing.assert_array_equal(local, (shard % 32) // 4)
        assert proc.dtype == np.int32 and local.dtype == np.int32
        assert set(np.unique(proc)) <= set(range(4))
        assert set(np.unique(local)) <= set(range(8))

    def test_routing_is_stable_across_calls(self):
        """Replay determinism: the same keys route identically — the
        exactly-once replay contract of the exchange."""
        from flink_tpu.exchange.partitioners import hybrid_route

        keys = np.arange(1000, dtype=np.int64) * 7919
        a = hybrid_route(keys, 64, 2, local_devices=4)
        b = hybrid_route(keys, 64, 2, local_devices=4)
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])

    def test_divisibility_enforced_loudly(self):
        from flink_tpu.exchange.partitioners import hybrid_route

        keys = np.arange(10, dtype=np.int64)
        with pytest.raises(ValueError, match="n_processes"):
            hybrid_route(keys, 100, 3)
        with pytest.raises(ValueError, match="device count"):
            hybrid_route(keys, 128, 4, local_devices=3)

    def test_cross_slice_fraction(self):
        from flink_tpu.exchange.partitioners import (
            cross_slice_fraction,
            hybrid_route,
        )

        rng = np.random.default_rng(1)
        keys = rng.integers(0, 2**40, 1 << 14).astype(np.int64)
        proc, _ = hybrid_route(keys, 128, 4)
        frac = cross_slice_fraction(proc, 1)
        # uniform hash: ~3/4 of the records leave slice 1
        assert 0.70 < frac < 0.80
        assert cross_slice_fraction(np.zeros(0, np.int32), 0) == 0.0


class TestHybridMeshPlan:
    def test_local_plan_owns_contiguous_global_span(self):
        from flink_tpu.parallel.mesh import AXIS, DCN_AXIS, \
            make_hybrid_mesh_plan

        import jax

        devs = jax.devices()[:4]
        mp = make_hybrid_mesh_plan(64, 16, n_processes=2, process_id=1,
                                   devices=devs)
        assert mp.num_shards == 32            # the LOCAL span
        assert mp.global_num_shards == 64
        assert mp.shard_lo == 32
        assert mp.mesh.axis_names == (DCN_AXIS, AXIS)
        assert mp.mesh.devices.shape == (1, 4)
        # owner() delegates to the shared hybrid_route truth
        keys = np.arange(512, dtype=np.int64) * 104729
        proc, local = mp.owner(keys)
        from flink_tpu.exchange.partitioners import hybrid_route

        p2, l2 = hybrid_route(keys, 64, 2, local_devices=4)
        np.testing.assert_array_equal(proc, p2)
        np.testing.assert_array_equal(local, l2)

    def test_divisibility_enforced(self):
        from flink_tpu.parallel.mesh import make_hybrid_mesh_plan

        import jax

        with pytest.raises(ValueError, match="num-processes"):
            make_hybrid_mesh_plan(63, 16, 2, 0,
                                  devices=jax.devices()[:2])
        with pytest.raises(ValueError, match="local device count"):
            make_hybrid_mesh_plan(64, 16, 2, 0,
                                  devices=jax.devices()[:3])


@pytest.mark.shard_map
class TestIntraSliceExchange:
    def test_collective_stays_on_the_inner_axis(self):
        """On a (DCN_AXIS, AXIS) hybrid mesh, intra_slice_exchange must
        move records only among the devices of one slice: with 2
        virtual slices x 2 devices, records bucketed for local device
        d land on device d OF THE SAME SLICE — the outer (DCN) axis
        never carries a byte, which is the hybrid topology's point."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from flink_tpu.exchange.keyby import intra_slice_exchange
        from flink_tpu.parallel.mesh import AXIS, DCN_AXIS
        from flink_tpu.utils.jaxcompat import hybrid_device_mesh, shard_map

        devs = jax.devices()[:4]
        arr = hybrid_device_mesh((1, 2), (2, 1), devs)  # 2 slices x 2
        mesh = Mesh(arr, (DCN_AXIS, AXIS))
        n_local, cap = 2, 8
        b = 4 * cap  # per-device rows x 4 devices
        rng = np.random.default_rng(7)
        # tag every record with its ORIGIN slice (axis_index over the
        # outer axis inside the step) and a payload naming its row
        dest = jnp.asarray(rng.integers(0, n_local, b).astype(np.int32))
        valid = jnp.ones(b, bool)
        payload = {"row": jnp.arange(b, dtype=jnp.int64)}

        def step(dest, valid, payload):
            from jax import lax

            slice_id = lax.axis_index(DCN_AXIS)
            tagged = dict(payload)
            tagged["origin_slice"] = jnp.full(
                dest.shape, slice_id, jnp.int64)
            recv, rv, ov = intra_slice_exchange(
                dest, valid, tagged, n_local=n_local, capacity=cap)
            # every received record's origin slice must equal OURS
            same = jnp.where(rv, recv["origin_slice"] == slice_id, True)
            # rank-1 per-device cells so out_specs can concatenate them
            return (jnp.all(same)[None], jnp.sum(rv)[None],
                    jnp.sum(ov)[None])

        fn = jax.jit(shard_map(
            step, mesh=mesh,
            in_specs=(P((DCN_AXIS, AXIS)), P((DCN_AXIS, AXIS)),
                      {"row": P((DCN_AXIS, AXIS))}),
            out_specs=(P((DCN_AXIS, AXIS)), P((DCN_AXIS, AXIS)),
                       P((DCN_AXIS, AXIS)))))
        all_same, n_recv, n_over = fn(dest, valid, payload)
        assert bool(np.all(np.asarray(all_same))), (
            "a record crossed the DCN axis inside the step")
        # nothing lost: every valid record landed somewhere in its slice
        assert int(np.asarray(n_recv).sum()) + int(
            np.asarray(n_over).sum()) == b
