"""Stream partitioners (ref: streaming/runtime/partitioner/* and their
StreamPartitionerTest-style distribution property tests)."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.exchange.partitioners import (
    BroadcastPartitioner, GlobalPartitioner, RebalancePartitioner,
    RescalePartitioner, ShufflePartitioner, make_partitioner)
from flink_tpu.time.watermarks import WatermarkStrategy


class TestAssignmentProperties:
    def test_rebalance_exact_equal_spread(self):
        p = RebalancePartitioner()
        counts = np.zeros(4, np.int64)
        for b in (7, 13, 1, 11):  # ragged batches
            a = p.assign(b, 4)
            counts += np.bincount(a, minlength=4)
        assert counts.max() - counts.min() <= 1  # round-robin exactness

    def test_rebalance_cursor_continues_across_batches(self):
        p = RebalancePartitioner()
        a1 = p.assign(3, 4)
        a2 = p.assign(3, 4)
        assert list(a1) + list(a2) == [0, 1, 2, 3, 0, 1]

    def test_rescale_stays_in_group(self):
        p = RescalePartitioner(group=(2, 4))
        a = p.assign(10, 8)
        assert set(a.tolist()) == {2, 3}

    def test_shuffle_covers_and_replays_identically(self):
        p = ShufflePartitioner(seed=5)
        a = p.assign(10_000, 8)
        assert set(a.tolist()) == set(range(8))
        # restore replays the stream identically (exactly-once replays)
        snap = p.snapshot()
        nxt = p.assign(100, 8)
        q = ShufflePartitioner(seed=5)
        q.restore(snap)
        assert list(q.assign(100, 8)) == list(nxt)

    def test_global_and_broadcast(self):
        assert set(GlobalPartitioner().assign(50, 8).tolist()) == {0}
        bp = BroadcastPartitioner()
        assert bp.broadcast
        with pytest.raises(RuntimeError):
            bp.assign(1, 8)

    def test_factory(self):
        for s in ("rebalance", "rescale", "shuffle", "broadcast",
                  "global", "forward"):
            assert make_partitioner(s) is not None

    def test_shuffle_seeds_decorrelate(self):
        a = ShufflePartitioner(seed=1).assign(1000, 8)
        b = ShufflePartitioner(seed=2).assign(1000, 8)
        assert not np.array_equal(a, b)

    def test_advance_matches_assign_state(self):
        """advance() (the alloc-free p=1 path) must leave the same state
        as assign() — checkpointed cursors stay replay-consistent."""
        for mk in (RebalancePartitioner,
                   lambda: RescalePartitioner(group=(1, 3)),
                   lambda: ShufflePartitioner(seed=3)):
            p, q = mk(), mk()
            p.assign(7, 4)
            q.advance(7, 4)
            assert p.snapshot() == q.snapshot()

    def test_rebalance_snapshot_roundtrip(self):
        p = RebalancePartitioner()
        p.assign(5, 4)
        q = RebalancePartitioner()
        q.restore(p.snapshot())
        assert list(q.assign(3, 4)) == list(p.assign(3, 4))


class TestGraphAndDriver:
    def test_partition_breaks_chain_and_preserves_results(self):
        """A rebalance between two maps must not change results at
        parallelism 1 (the reference's behavior), and must lower to its
        own exchange node rather than fusing into the chain."""
        def gen(split, i):
            if i >= 3:
                return None
            return ({"v": np.arange(4, dtype=np.int64) + i * 4},
                    np.arange(4, dtype=np.int64) + i * 4)

        env = StreamExecutionEnvironment(Configuration(
            {"pipeline.microbatch-size": 8}))
        sink = CollectSink()
        (env.from_source(GeneratorSource(gen),
                         WatermarkStrategy.for_monotonous_timestamps())
         .map(lambda d: {"v": d["v"] * 2})
         .rebalance()
         .map(lambda d: {"v": d["v"] + 1})
         .add_sink(sink))
        from flink_tpu.graph.compiler import compile_job

        plan = compile_job(env._transforms, env.config,
                           env._watermark_strategy)
        kinds = [n.kind for n in plan.nodes.values()]
        assert "partition" in kinds
        env.execute("part")
        got = sorted(int(v) for r in sink.rows for v in
                     np.atleast_1d(r["v"]))
        assert got == sorted(int(v) * 2 + 1 for v in range(12))

    def test_all_strategies_run_e2e(self):
        for strat in ("rebalance", "rescale", "shuffle", "broadcast",
                      "global_"):
            def gen(split, i):
                if i >= 2:
                    return None
                return ({"k": np.arange(6, dtype=np.int64) % 3},
                        np.full(6, i * 1000 + 500, np.int64))

            env = StreamExecutionEnvironment(Configuration(
                {"pipeline.microbatch-size": 8,
                 "state.num-key-shards": 4, "state.slots-per-shard": 16}))
            sink = CollectSink()
            s = env.from_source(
                GeneratorSource(gen),
                WatermarkStrategy.for_monotonous_timestamps())
            s = getattr(s, strat)()
            (s.key_by("k").window(TumblingEventTimeWindows.of(1_000))
             .count().add_sink(sink))
            env.execute(f"p-{strat}")
            total = sum(int(r["count"]) for r in sink.rows)
            assert total == 12, strat  # parallelism 1: pass-through
