"""Native codec tests — parity between the C fast path and the numpy
fallback, and bit-identity of string hashing with the Python router
(keys must land on the same shard regardless of which side encodes)."""
import numpy as np
import pytest

from flink_tpu import native_codec as nc
from flink_tpu.records import hash_string_key


class TestNativeCodec:
    def test_builds(self):
        assert nc.build(), "g++ build failed"
        assert nc.native_available()

    def test_tokenize_hash_matches_python(self):
        lines = ["to be or not to be", "  leading  and   double spaces ",
                 "", "tab\tseparated words", "unicode café naïve"]
        ids, lix = nc.tokenize_hash(lines)
        pids, plix = nc._tokenize_hash_numpy(lines)
        assert ids.tolist() == pids.tolist()
        assert lix.tolist() == plix.tolist()
        # and bit-identical with the keyBy router hash
        assert ids[0] == hash_string_key("to")

    def test_hash_strings(self):
        ss = ["alpha", "beta", "café", ""]
        got = nc.hash_strings(ss)
        assert got.tolist() == [hash_string_key(s) for s in ss]

    def test_parse_i64_table(self):
        data = b"1,2,3\n-4,5,6\n7,8,9\n"
        out = nc.parse_i64_table(data, 3)
        assert out.tolist() == [[1, 2, 3], [-4, 5, 6], [7, 8, 9]]

    def test_parse_f32_table(self):
        data = b"1.5,2\n-0.25,4.125\n"
        out = nc.parse_f32_table(data, 2)
        assert out.tolist() == [[1.5, 2.0], [-0.25, 4.125]]

    def test_encode_roundtrip(self):
        vals = np.array([[10, -20, 3], [0, 99999999999, -1]], np.int64)
        enc = nc.encode_i64_rows(vals)
        back = nc.parse_i64_table(enc, 3)
        assert back.tolist() == vals.tolist()

    def test_crc32_bit_identical_to_zlib(self):
        """The native CRC (slice-by-8 + the PCLMUL-folded fast path on
        CPUs that have it, ISSUE 13) must be BIT-IDENTICAL to
        ``zlib.crc32`` for every length, alignment, and init value —
        files and frames checksummed natively verify on fallback
        readers and vice versa. Lengths cover the PCLMUL entry
        threshold (64B), its 64B-block main loop, 16B folds, tails,
        the native-vs-zlib cutover (16KB), and unaligned starts."""
        import zlib

        rng = np.random.default_rng(42)
        base = rng.integers(0, 256, 1 << 17, dtype=np.uint8).tobytes()
        lengths = [0, 1, 7, 63, 64, 65, 80, 127, 128, 200, 1023,
                   (1 << 14) - 1, 1 << 14, (1 << 14) + 13, 1 << 16,
                   (1 << 17) - 3]
        for ln in lengths:
            for off in (0, 1, 3, 8):
                for init in (0, 1, 0xDEADBEEF, 0xFFFFFFFF):
                    buf = base[off:off + ln]
                    assert nc.crc32(buf, init) == zlib.crc32(buf, init), (
                        ln, off, hex(init))

    def test_crc32_chaining_equals_concatenation(self):
        """The scatter writer's chained CRC over column parts must
        equal the CRC of the concatenated payload (the byte-identity
        contract of the columnar block format)."""
        import zlib

        rng = np.random.default_rng(43)
        parts = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
                 for n in (100, 1 << 15, 17, 0, 1 << 14)]
        crc = 0
        for p in parts:
            crc = nc.crc32(p, crc)
        assert crc == zlib.crc32(b"".join(parts))

    def test_throughput_sanity(self):
        """The native tokenizer should beat the python fallback clearly
        on a sizable corpus (sanity, not a benchmark)."""
        import time

        lines = ["the quick brown fox jumps over the lazy dog"] * 20000
        t0 = time.perf_counter()
        ids, _ = nc.tokenize_hash(lines)
        native_t = time.perf_counter() - t0
        t0 = time.perf_counter()
        pids, _ = nc._tokenize_hash_numpy(lines)
        py_t = time.perf_counter() - t0
        assert ids.tolist() == pids.tolist()
        assert native_t < py_t, (native_t, py_t)


class TestNativeHashTable:
    """The C key-directory table must agree bit-for-bit with the numpy
    reference: same splitmix64 hash, same lookup/insert semantics —
    host ingest and device keyBy route by this hash."""

    def test_hash_parity(self):
        import numpy as np
        from flink_tpu import native_codec as nc
        from flink_tpu import records

        if not nc.native_available():
            import pytest
            pytest.skip("codec library unavailable")
        rng = np.random.default_rng(3)
        keys = rng.integers(-2**62, 2**62, 10_000)
        # reference mix in pure numpy (small slices dodge the native
        # fast path inside hash_keys_numpy)
        ref = np.concatenate([records.hash_keys_numpy(keys[i:i + 100])
                              for i in range(0, len(keys), 100)])
        assert np.array_equal(ref, nc.hash_keys_native(keys))

    def test_table_matches_numpy_reference(self):
        import numpy as np
        from flink_tpu import native_codec as nc
        from flink_tpu.records import hash_keys_numpy
        from flink_tpu.state.keyed import _NumpyHashTable

        t = nc.NativeHashTable.create(16)
        if t is None:
            import pytest
            pytest.skip("codec library unavailable")
        ref = _NumpyHashTable(16)
        rng = np.random.default_rng(4)
        for round_ in range(5):
            ks = np.unique(rng.integers(0, 5_000, 800))
            vs = rng.integers(-2, 10_000, len(ks))  # incl. negative sentinels
            t.insert_batch(ks, None, vs)
            ref.insert_batch(ks, hash_keys_numpy(ks), vs)
            q = rng.integers(0, 8_000, 3_000)
            v1, f1 = t.lookup_keys(q)
            v2, f2 = ref.lookup_keys(q)
            assert np.array_equal(f1, f2)
            assert np.array_equal(v1[f1], v2[f2])
            assert t._count == ref._count

    def test_directory_native_vs_numpy(self):
        import numpy as np
        from flink_tpu.state.keyed import KeyDirectory, _NumpyHashTable

        rng = np.random.default_rng(5)
        d1 = KeyDirectory(8, 32)
        d2 = KeyDirectory(8, 32)
        d2._table = _NumpyHashTable()  # force the fallback
        for _ in range(4):
            ks = rng.integers(0, 1_000, 5_000)
            assert np.array_equal(d1.assign(ks), d2.assign(ks))
        assert d1.num_keys() == d2.num_keys()
