"""Native codec tests — parity between the C fast path and the numpy
fallback, and bit-identity of string hashing with the Python router
(keys must land on the same shard regardless of which side encodes)."""
import numpy as np
import pytest

from flink_tpu import native_codec as nc
from flink_tpu.records import hash_string_key


class TestNativeCodec:
    def test_builds(self):
        assert nc.build(), "g++ build failed"
        assert nc.native_available()

    def test_tokenize_hash_matches_python(self):
        lines = ["to be or not to be", "  leading  and   double spaces ",
                 "", "tab\tseparated words", "unicode café naïve"]
        ids, lix = nc.tokenize_hash(lines)
        pids, plix = nc._tokenize_hash_numpy(lines)
        assert ids.tolist() == pids.tolist()
        assert lix.tolist() == plix.tolist()
        # and bit-identical with the keyBy router hash
        assert ids[0] == hash_string_key("to")

    def test_hash_strings(self):
        ss = ["alpha", "beta", "café", ""]
        got = nc.hash_strings(ss)
        assert got.tolist() == [hash_string_key(s) for s in ss]

    def test_parse_i64_table(self):
        data = b"1,2,3\n-4,5,6\n7,8,9\n"
        out = nc.parse_i64_table(data, 3)
        assert out.tolist() == [[1, 2, 3], [-4, 5, 6], [7, 8, 9]]

    def test_parse_f32_table(self):
        data = b"1.5,2\n-0.25,4.125\n"
        out = nc.parse_f32_table(data, 2)
        assert out.tolist() == [[1.5, 2.0], [-0.25, 4.125]]

    def test_encode_roundtrip(self):
        vals = np.array([[10, -20, 3], [0, 99999999999, -1]], np.int64)
        enc = nc.encode_i64_rows(vals)
        back = nc.parse_i64_table(enc, 3)
        assert back.tolist() == vals.tolist()

    def test_throughput_sanity(self):
        """The native tokenizer should beat the python fallback clearly
        on a sizable corpus (sanity, not a benchmark)."""
        import time

        lines = ["the quick brown fox jumps over the lazy dog"] * 20000
        t0 = time.perf_counter()
        ids, _ = nc.tokenize_hash(lines)
        native_t = time.perf_counter() - t0
        t0 = time.perf_counter()
        pids, _ = nc._tokenize_hash_numpy(lines)
        py_t = time.perf_counter() - t0
        assert ids.tolist() == pids.tolist()
        assert native_t < py_t, (native_t, py_t)
