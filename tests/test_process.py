"""KeyedProcessFunction + keyed state + user timers (ref:
KeyedProcessOperator / InternalTimerServiceImpl test patterns: state
updates per element, timers firing on watermark, timeout detection)."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.functions import KeyedProcessFunction
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.config import Configuration
from flink_tpu.ops.process import KeyedProcessOperator
from flink_tpu.state.api import (
    ListStateDescriptor, MapStateDescriptor, StateTtlConfig,
    ValueStateDescriptor)
from flink_tpu.time.watermarks import WatermarkStrategy


class RunningSum(KeyedProcessFunction):
    """Emit the running per-key sum after every batch (vectorized)."""

    def process_batch(self, ctx):
        vs = ctx.value_state(ValueStateDescriptor("sum", 0.0))
        # in-batch segment-accumulate, then one scatter into state
        order = np.argsort(ctx.slots, kind="stable")
        sl, v = ctx.slots[order], ctx.data["v"][order]
        uniq, starts = np.unique(sl, return_index=True)
        totals = np.add.reduceat(v.astype(np.float64), starts)
        vs[uniq] = vs[uniq] + totals
        ctx.emit({"key": ctx.keys[order][starts], "total": vs[uniq]},
                 ts=ctx.timestamps[order][starts])


class Dedup(KeyedProcessFunction):
    """First-occurrence filter via a seen flag (classic dedup)."""

    def process_batch(self, ctx):
        seen = ctx.value_state(ValueStateDescriptor("seen", 0.0))
        order = np.argsort(ctx.slots, kind="stable")
        sl = ctx.slots[order]
        first_in_batch = np.empty(len(sl), bool)
        first_in_batch[0:1] = True
        first_in_batch[1:] = sl[1:] != sl[:-1]
        fresh = first_in_batch & (seen[sl] == 0.0)
        seen[sl[fresh]] = 1.0
        keep = order[fresh]
        ctx.emit({"key": ctx.keys[keep]}, ts=ctx.timestamps[keep])


class IdleTimeout(KeyedProcessFunction):
    """Emit a timeout alert when a key sees no activity for ``gap`` ms —
    the canonical KeyedProcessFunction timer example."""

    def __init__(self, gap: int):
        self.gap = gap

    def process_batch(self, ctx):
        last = ctx.value_state(ValueStateDescriptor("last_ts", -1.0))
        order = np.argsort(ctx.slots, kind="stable")
        sl, ts = ctx.slots[order], ctx.timestamps[order]
        uniq, starts = np.unique(sl, return_index=True)
        ends = np.append(starts[1:], len(sl))
        mx = np.maximum.reduceat(ts, starts)
        newer = mx > last[uniq]
        last[uniq[newer]] = mx[newer].astype(np.float64)
        ctx.register_event_time_timers(mx[newer] + self.gap,
                                       slots=uniq[newer])

    def on_timer(self, ctx):
        last = ctx.value_state(ValueStateDescriptor("last_ts", -1.0))
        # fire only if the timer still matches the latest activity
        # (a newer record re-armed a later timer)
        live = last[ctx.slots] + self.gap == ctx.timestamps
        ctx.emit({"key": ctx.keys[live],
                  "idle_since": last[ctx.slots[live]].astype(np.int64)},
                 ts=ctx.timestamps[live])


class TestOperatorDirect:
    def test_running_sum(self):
        op = KeyedProcessOperator(RunningSum(), num_shards=4,
                                  slots_per_shard=16)
        op.process_batch(np.array([1, 2, 1], np.int64),
                         np.array([10, 20, 30], np.int64),
                         {"v": np.array([1.0, 5.0, 2.0])})
        f = dict(op.take_fired())
        got = {int(k): float(t) for k, t in zip(f["key"], f["total"])}
        assert got == {1: 3.0, 2: 5.0}
        op.process_batch(np.array([1], np.int64), np.array([40], np.int64),
                         {"v": np.array([4.0])})
        f = dict(op.take_fired())
        assert {int(k): float(t) for k, t in
                zip(f["key"], f["total"])} == {1: 7.0}

    def test_idle_timeout_timer(self):
        op = KeyedProcessOperator(IdleTimeout(1000), num_shards=4,
                                  slots_per_shard=16)
        op.process_batch(np.array([7], np.int64), np.array([100], np.int64), {})
        f = dict(op.advance_watermark(500))
        assert len(f.get("key", ())) == 0          # not idle yet
        f = dict(op.advance_watermark(1100))       # 100+1000 <= 1100
        assert [int(k) for k in f["key"]] == [7]
        assert [int(v) for v in f["idle_since"]] == [100]
        # re-armed timer: new activity supersedes the old timer
        op.process_batch(np.array([8], np.int64), np.array([2000], np.int64), {})
        op.process_batch(np.array([8], np.int64), np.array([2500], np.int64), {})
        f = dict(op.advance_watermark(3100))       # old timer (3000) stale
        assert len(f.get("key", ())) == 0
        f = dict(op.advance_watermark(3600))       # 2500+1000 fires
        assert [int(k) for k in f["key"]] == [8]

    def test_list_and_map_state(self):
        class Collect(KeyedProcessFunction):
            def process_batch(self, ctx):
                ls = ctx.list_state(ListStateDescriptor("vals"))
                ms = ctx.map_state(MapStateDescriptor("attrs"))
                ls.append_batch(ctx.slots, ctx.data["v"])
                ms.put_batch(ctx.slots, ctx.timestamps.tolist(),
                             ctx.data["v"].tolist())

            def on_timer(self, ctx):
                pass

        fn = Collect()
        op = KeyedProcessOperator(fn, num_shards=4, slots_per_shard=16)
        op.process_batch(np.array([1, 1, 2], np.int64),
                         np.array([10, 20, 30], np.int64),
                         {"v": np.array([1.0, 2.0, 3.0])})
        slot1 = int(op.directory.assign(np.array([1], np.int64))[0])
        ls = op._states["vals"]
        assert ls.get(slot1) == [1.0, 2.0]
        ms = op._states["attrs"]
        assert ms.get(slot1) == {10: 1.0, 20: 2.0}

    def test_value_state_ttl_expires(self):
        desc = ValueStateDescriptor("x", 0.0, ttl=StateTtlConfig(1000))

        class Ttl(KeyedProcessFunction):
            def process_batch(self, ctx):
                vs = ctx.value_state(desc)
                cur = vs.get(ctx.slots, int(ctx.timestamps.max()))
                vs.update(ctx.slots, cur + ctx.data["v"],
                          int(ctx.timestamps.max()))
                ctx.emit({"key": ctx.keys, "x": vs[ctx.slots]})

        op = KeyedProcessOperator(Ttl(), num_shards=4, slots_per_shard=16)
        op.process_batch(np.array([1], np.int64), np.array([100], np.int64),
                         {"v": np.array([5.0])})
        op.take_fired()
        # second write 2000ms later: the old value expired (ttl 1000)
        op.process_batch(np.array([1], np.int64), np.array([2100], np.int64),
                         {"v": np.array([3.0])})
        f = dict(op.take_fired())
        assert [float(x) for x in f["x"]] == [3.0]

    def test_per_element_adapter(self):
        class Alternate(KeyedProcessFunction):
            """Emit every 2nd record per key — sequential logic, authored
            per element (the reference's style)."""

            def process_element(self, key, ts, row, ctx, slot):
                vs = ctx.value_state(ValueStateDescriptor("n", 0.0))
                vs[slot] = vs[slot] + 1
                if int(vs[slot]) % 2 == 0:
                    ctx.emit({"key": np.array([key], np.int64)},
                             ts=np.array([ts], np.int64))

        op = KeyedProcessOperator(Alternate(), num_shards=4,
                                  slots_per_shard=16)
        op.process_batch(np.array([1, 1, 1, 2], np.int64),
                         np.array([10, 20, 30, 40], np.int64), {})
        f = dict(op.take_fired())
        assert [int(k) for k in f["key"]] == [1]  # 1's 2nd record only

    def test_snapshot_restore_roundtrip(self):
        def mk():
            return KeyedProcessOperator(RunningSum(), num_shards=4,
                                        slots_per_shard=16)

        a = mk()
        a.process_batch(np.array([1], np.int64), np.array([10], np.int64),
                        {"v": np.array([5.0])})
        a.take_fired()
        snap = a.snapshot_state()
        b = mk()
        b.restore_state(snap)
        b.process_batch(np.array([1], np.int64), np.array([20], np.int64),
                        {"v": np.array([2.0])})
        f = dict(b.take_fired())
        assert [float(t) for t in f["total"]] == [7.0]


class TestRegressions:
    def test_restore_empty_timers_then_final_watermark(self):
        a = KeyedProcessOperator(Dedup(), num_shards=4, slots_per_shard=16)
        snap = a.snapshot_state()  # zero timers
        b = KeyedProcessOperator(Dedup(), num_shards=4, slots_per_shard=16)
        b.restore_state(snap)
        assert b.final_watermark() == 0  # must not crash on empty set

    def test_ttl_state_rejects_unstamped_write(self):
        op = KeyedProcessOperator(Dedup(), num_shards=4, slots_per_shard=16)
        vs = op._state(ValueStateDescriptor("t", 0.0,
                                            ttl=StateTtlConfig(100)),
                       __import__("flink_tpu.state.api",
                                  fromlist=["ValueStateVector"]).ValueStateVector)
        with pytest.raises(TypeError, match="update"):
            vs[np.array([0])] = 1.0

    def test_partial_emit_without_ts_raises(self):
        class Bad(KeyedProcessFunction):
            def process_batch(self, ctx):
                ctx.emit({"key": ctx.keys[:1]})  # 1 of 2 rows, no ts

        op = KeyedProcessOperator(Bad(), num_shards=4, slots_per_shard=16)
        with pytest.raises(ValueError, match="full-batch"):
            op.process_batch(np.array([1, 2], np.int64),
                             np.array([10, 20], np.int64), {})

    def test_filtered_records_consume_no_slots(self):
        op = KeyedProcessOperator(Dedup(), num_shards=1, slots_per_shard=2)
        keys = np.arange(100, dtype=np.int64)
        valid = np.zeros(100, bool)
        valid[:2] = True  # only keys 0,1 are real
        op.process_batch(keys, np.zeros(100, np.int64), {}, valid)
        assert op.directory.num_keys() == 2  # 98 filtered keys: no slots
        assert op.records_dropped_full == 0

    def test_mixed_emit_schemas_raise(self):
        class Mixed(KeyedProcessFunction):
            def process_batch(self, ctx):
                ctx.emit({"a": ctx.keys}, ts=ctx.timestamps)
                ctx.emit({"b": ctx.keys}, ts=ctx.timestamps)

        op = KeyedProcessOperator(Mixed(), num_shards=4, slots_per_shard=16)
        op.process_batch(np.array([1], np.int64), np.array([10], np.int64), {})
        with pytest.raises(ValueError, match="schemas"):
            op.take_fired().materialize()

    def test_timer_dedup_and_delete(self):
        from flink_tpu.ops.process import TimerService

        t = TimerService()
        t.register_batch(np.array([3, 3, 5]), np.array([100, 100, 200]))
        t.register_batch(np.array([5]), np.array([150]))
        t.delete_batch(np.array([5]), np.array([200]))
        s, ts = t.due(1000)
        assert list(zip(s.tolist(), ts.tolist())) == [(3, 100), (5, 150)]
        assert t.pending_count == 0


class TestProcessE2E:
    def test_dedup_pipeline(self):
        def gen(split, i):
            if i >= 3:
                return None
            ks = np.array([[1, 2, 1], [2, 3, 3], [1, 4, 2]][i], np.int64)
            return {"k": ks}, np.full(3, i * 100, np.int64)

        env = StreamExecutionEnvironment(Configuration(
            {"pipeline.microbatch-size": 8,
             "state.num-key-shards": 4, "state.slots-per-shard": 16}))
        sink = CollectSink()
        (env.from_source(GeneratorSource(gen),
                         WatermarkStrategy.for_monotonous_timestamps())
         .key_by("k")
         .process(Dedup())
         .add_sink(sink))
        env.execute("dedup")
        assert sorted(int(r["key"]) for r in sink.rows) == [1, 2, 3, 4]

    def test_timeout_pipeline_fires_on_watermark(self):
        def gen(split, i):
            if i >= 4:
                return None
            if i == 0:
                return {"k": np.array([5], np.int64)}, np.array([0], np.int64)
            # keep the watermark advancing with other keys
            return ({"k": np.array([9], np.int64)},
                    np.array([i * 1000], np.int64))

        env = StreamExecutionEnvironment(Configuration(
            {"pipeline.microbatch-size": 8,
             "state.num-key-shards": 4, "state.slots-per-shard": 16}))
        sink = CollectSink()
        (env.from_source(GeneratorSource(gen),
                         WatermarkStrategy.for_monotonous_timestamps())
         .key_by("k")
         .process(IdleTimeout(1500))
         .add_sink(sink))
        env.execute("timeout")
        fired = {int(r["key"]) for r in sink.rows}
        assert 5 in fired  # idle after ts 0, alert at wm >= 1500
