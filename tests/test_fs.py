"""FileSystem abstraction + plugin loader (ref: core/fs/FileSystem
scheme registry + core/plugin/PluginManager; FileSystemTest patterns)."""
import os
import pickle

import numpy as np
import pytest

from flink_tpu.checkpoint.storage import FsCheckpointStorage
from flink_tpu.fs import (
    LocalFileSystem, get_filesystem, load_plugins, register_filesystem,
    schemes)


class TestLocalFs:
    def test_roundtrip_and_scheme_strip(self, tmp_path):
        fs = get_filesystem(str(tmp_path))
        assert isinstance(fs, LocalFileSystem)
        p = f"file://{tmp_path}/sub/a.bin"
        fs.mkdirs(f"file://{tmp_path}/sub")
        with fs.open_write(p) as f:
            f.write(b"hello")
        assert fs.exists(p) and fs.size(p) == 5
        with fs.open_read(p) as f:
            assert f.read() == b"hello"
        fs.rename(p, f"file://{tmp_path}/sub/b.bin")
        assert fs.listdir(f"file://{tmp_path}/sub") == ["b.bin"]

    def test_link_or_copy_prefers_hardlink(self, tmp_path):
        fs = get_filesystem(str(tmp_path))
        src = str(tmp_path / "x")
        open(src, "wb").write(b"z")
        fs.link_or_copy(src, str(tmp_path / "y"))
        assert os.path.samefile(src, str(tmp_path / "y"))

    def test_unknown_scheme_raises(self):
        with pytest.raises(ValueError, match="no filesystem registered"):
            get_filesystem("s3://bucket/x")

    def test_recursive_delete_propagates_failures(self, tmp_path,
                                                  monkeypatch):
        """PR-14 satellite regression: delete(recursive=True) used
        ``shutil.rmtree(ignore_errors=True)`` — a retention or abort
        pass that silently failed to delete violated the loud-failure
        convention. A filesystem error during the tree walk must now
        propagate (callers that tolerate sweep failures catch OSError
        themselves)."""
        fs = get_filesystem(str(tmp_path))
        d = tmp_path / "victim"
        d.mkdir()
        (d / "f").write_bytes(b"x")
        real_rmdir = os.rmdir

        def failing_rmdir(path, *a, **kw):
            if os.path.basename(str(path)) == "victim":
                raise OSError(5, "Input/output error", str(path))
            return real_rmdir(path, *a, **kw)

        monkeypatch.setattr(os, "rmdir", failing_rmdir)
        with pytest.raises(OSError, match="Input/output error"):
            fs.delete(str(d), recursive=True)
        monkeypatch.undo()
        fs.delete(str(d), recursive=True)  # now it works — and is gone
        assert not d.exists()

    def test_sync_write_and_fsync_barrier(self, tmp_path):
        """The PR-14 durability seam: open_write(sync=True) fsyncs
        before close returns; fsync(path) is the explicit barrier
        (files AND directories); write_atomic publishes whole."""
        from flink_tpu.fs import write_atomic

        fs = get_filesystem(str(tmp_path))
        p = str(tmp_path / "durable.bin")
        with fs.open_write(p, sync=True) as f:
            f.write(b"payload")
        assert open(p, "rb").read() == b"payload"
        fs.fsync(p)                 # file barrier
        fs.fsync(str(tmp_path))     # directory barrier
        write_atomic(fs, str(tmp_path / "pub.json"), b"{}")
        assert (tmp_path / "pub.json").read_bytes() == b"{}"
        assert not (tmp_path / "pub.json.tmp").exists()


class TestPluginLoader:
    def test_register_and_resolve_custom_scheme(self, tmp_path):
        class MemFs(LocalFileSystem):
            @staticmethod
            def _strip(path):
                return path.replace("testmem://", str(tmp_path) + "/")

        register_filesystem("testmem", MemFs)
        assert "testmem" in schemes()
        fs = get_filesystem("testmem://data/f")
        fs.mkdirs("testmem://data")
        with fs.open_write("testmem://data/f") as f:
            f.write(b"ok")
        assert (tmp_path / "data" / "f").read_bytes() == b"ok"

    def test_load_plugins_runs_register_hook(self, tmp_path, monkeypatch):
        import sys
        import types

        mod = types.ModuleType("fake_fs_plugin")
        calls = []
        mod.register = lambda reg: calls.append(reg)
        monkeypatch.setitem(sys.modules, "fake_fs_plugin", mod)
        assert load_plugins(["fake_fs_plugin"]) == ["fake_fs_plugin"]
        assert len(calls) == 1

    def test_plugin_without_hook_raises(self, monkeypatch):
        import sys
        import types

        monkeypatch.setitem(sys.modules, "bad_plugin",
                            types.ModuleType("bad_plugin"))
        with pytest.raises(ValueError, match="register"):
            load_plugins(["bad_plugin"])


class TestStorageThroughSeam:
    def test_checkpoint_storage_on_custom_scheme(self, tmp_path):
        """The whole checkpoint lifecycle (save/list/load/retire) runs on
        a plugin filesystem — nothing in storage touches os directly."""
        root = str(tmp_path / "backing")

        class ShimFs(LocalFileSystem):
            @staticmethod
            def _strip(path):
                return path.replace("shim://", root + "/")

        register_filesystem("shim", ShimFs)
        st = FsCheckpointStorage("shim://ckpts", "job")
        for cid in (1, 2, 3, 4, 5):
            st.save_v2(cid, {"op_versions": {"0": cid}},
                       {"0": pickle.dumps({"v": np.arange(cid)})}, {})
        hs = st.list_complete()
        assert [h.checkpoint_id for h in hs] == [3, 4, 5]  # retained=3
        payload = FsCheckpointStorage.load(st.latest())
        assert list(payload["operators"][0]["v"]) == [0, 1, 2, 3, 4]
        assert payload["op_files"][0].startswith("shim://")
