"""Blob distribution tests: content-addressed store, runner cache,
and shipping job code to a real runner process via --py-file (ref:
runtime/blob BlobServer/BlobCacheService — the job-jar channel)."""
import base64
import os
import subprocess
import sys
import time

import pytest

from flink_tpu.config import Configuration
from flink_tpu.runtime.blob import BlobCache, BlobStore, digest_of
from flink_tpu.runtime.coordinator import start_coordinator
from flink_tpu.runtime.rpc import RpcClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestBlobStore:
    def test_put_get_idempotent(self, tmp_path):
        s = BlobStore(str(tmp_path))
        d1 = s.put(b"hello")
        d2 = s.put(b"hello")
        assert d1 == d2 == digest_of(b"hello")
        assert s.get(d1) == b"hello"
        assert s.get("0" * 64) is None
        assert s.list() == [d1]

    def test_bad_digest_rejected(self, tmp_path):
        s = BlobStore(str(tmp_path))
        with pytest.raises(ValueError):
            s.get("../../etc/passwd")


class TestBlobRpc:
    def test_put_get_roundtrip_over_rpc(self):
        srv = start_coordinator(Configuration({}))
        try:
            c = RpcClient("127.0.0.1", srv.port)
            data = os.urandom(4096)
            r = c.call("put_blob", data_b64=base64.b64encode(data).decode())
            got = c.call("get_blob", digest=r["digest"])
            assert got["found"]
            assert base64.b64decode(got["data_b64"]) == data
            assert r["digest"] in c.call("list_blobs")["digests"]
            assert not c.call("get_blob", digest="f" * 64)["found"]
            c.close()
        finally:
            srv.close()

    def test_cache_fetch_and_materialize(self, tmp_path):
        srv = start_coordinator(Configuration({}))
        try:
            c = RpcClient("127.0.0.1", srv.port)
            d = c.call("put_blob", data_b64=base64.b64encode(
                b"x = 41\n").decode())["digest"]
            cache = BlobCache(c, str(tmp_path / "cache"))
            p1 = cache.fetch(d)
            p2 = cache.fetch(d)  # second hit: no RPC needed
            assert p1 == p2
            job = cache.materialize(d, str(tmp_path / "job"), "m.py")
            with open(job) as f:
                assert f.read() == "x = 41\n"
            c.close()
        finally:
            srv.close()

    def test_two_versions_same_name_do_not_shadow(self, tmp_path):
        srv = start_coordinator(Configuration({}))
        try:
            c = RpcClient("127.0.0.1", srv.port)
            d1 = c.call("put_blob", data_b64=base64.b64encode(
                b"v = 1\n").decode())["digest"]
            d2 = c.call("put_blob", data_b64=base64.b64encode(
                b"v = 2\n").decode())["digest"]
            cache = BlobCache(c, str(tmp_path / "cache"))
            j1 = cache.materialize(d1, str(tmp_path / "a1"), "job.py")
            j2 = cache.materialize(d2, str(tmp_path / "a2"), "job.py")
            assert open(j1).read() == "v = 1\n"
            assert open(j2).read() == "v = 2\n"
            c.close()
        finally:
            srv.close()


class TestBlobShippedJob:
    def test_py_file_job_runs_on_runner_process(self, tmp_path):
        """End to end: job code the runner host has never seen ships
        via the blob store and executes (the job-jar flow)."""
        out_file = tmp_path / "out.txt"
        job_src = f'''
import numpy as np

def build(env):
    from flink_tpu.api.sinks import FnSink
    from flink_tpu.api.windowing import TumblingEventTimeWindows

    rng = np.random.default_rng(0)
    n = 2000
    ts = np.sort(rng.integers(0, 5000, n)).astype(np.int64)
    total = [0]
    def write(b):
        total[0] += sum(int(x) for x in b.get("count", []))
        with open({str(out_file)!r}, "w") as f:
            f.write(str(total[0]))
    (env.from_collection({{"k": rng.integers(0, 10, n).astype(np.int64)}}, ts,
                         batch_size=500)
     .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
     .add_sink(FnSink(write)))
'''
        job_path = tmp_path / "shipjob.py"
        job_path.write_text(job_src)

        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        srv = start_coordinator(Configuration({}))
        runner = None
        try:
            runner = subprocess.Popen(
                [sys.executable, "-m", "flink_tpu.runtime.runner",
                 "--coordinator", f"127.0.0.1:{srv.port}",
                 "--runner-id", "blob-r1"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                cwd=str(tmp_path))
            c = RpcClient("127.0.0.1", srv.port)
            deadline = time.time() + 60
            while time.time() < deadline:
                if "blob-r1" in c.call("list_runners"):
                    break
                time.sleep(0.2)
            # submit THROUGH the CLI path: upload + reference by digest
            from flink_tpu.cli import main as cli_main

            rc = cli_main([
                "run", "--coordinator", f"127.0.0.1:{srv.port}",
                "--job-id", "shipped", "--entry", "shipjob:build",
                "--py-file", str(job_path)])
            assert rc == 0
            # the runner-hosted job takes 70-90s on a loaded CPU
            # container; the deadline bounds a hang, not the run time
            deadline = time.time() + 240
            state = None
            while time.time() < deadline:
                state = c.call("job_status", job_id="shipped")["state"]
                if state in ("FINISHED", "FAILED"):
                    break
                time.sleep(0.5)
            assert state == "FINISHED", c.call("job_status", job_id="shipped")
            assert out_file.exists() and int(out_file.read_text()) == 2000
            c.close()
        finally:
            if runner is not None:
                runner.terminate()
                runner.wait(timeout=10)
            srv.close()
