"""Deployable job for the multi-process runner tests — the "job jar".

The runner imports this module by name (``runner_job:build``) and calls
``build(env)`` to construct the pipeline, exactly like a TaskExecutor
materializing a shipped job (ref: TaskDeploymentDescriptor). Job
parameters ride in the submitted Configuration under ``test.*`` keys so
both attempts (original + post-kill recovery) build the identical,
deterministically replayable pipeline.
"""
import time

import numpy as np

from flink_tpu.api.sinks import FileTransactionalSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.time.watermarks import WatermarkStrategy

N_KEYS = 10
BATCH = 64


def batch_of(i: int):
    """Deterministic batch i (shared with the test's golden model)."""
    rng = np.random.default_rng(1234 + i)
    keys = rng.integers(0, N_KEYS, BATCH).astype(np.int64)
    ts = np.sort(rng.integers(i * 500, i * 500 + 1000, BATCH)).astype(np.int64)
    return keys, ts


def golden_counts(n_batches: int):
    expect = {}
    for i in range(n_batches):
        keys, ts = batch_of(i)
        for k, t in zip(keys, ts):
            kk = (int(k), (int(t) // 1000) * 1000)
            expect[kk] = expect.get(kk, 0) + 1
    return expect


def build(env):
    n_batches = int(env.config.get_raw("test.n-batches", 40))
    sleep_ms = int(env.config.get_raw("test.batch-sleep-ms", 0))
    sink_dir = env.config.get_raw("test.sink-dir")
    assert sink_dir, "test.sink-dir must be set"

    def gen(split, i):
        if i >= n_batches:
            return None
        if sleep_ms:
            time.sleep(sleep_ms / 1000)  # slow stream: killable mid-job
        keys, ts = batch_of(i)
        return {"k": keys}, ts

    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
        .key_by("k")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .add_sink(FileTransactionalSink(sink_dir)))
