"""Format v3 checkpoint payloads (ref: TypeSerializerSnapshot's
schema-evolution role, SURVEY §3.1): self-describing blobs, restore
across code changes, v1/v2 pickle compatibility, and no pickle in
framework-produced snapshots."""
import json
import os
import pickle
import struct

import numpy as np
import pytest

from flink_tpu.checkpoint import blobformat
from flink_tpu.checkpoint.storage import FsCheckpointStorage
from flink_tpu.state.keyed import PaneState


class TestBlobFormat:
    def test_round_trip_tree(self):
        payload = {
            "watermark": 12345,
            "arr": np.arange(10, dtype=np.int64),
            "f32": np.ones((3, 2), np.float32),
            "nested": {"a": [1, 2.5, "x", None, True],
                       "t": (1, "two", np.float64(3.5))},
            "intkeys": {1: "one", (2, 3): "pair"},
            "blob": b"\x00\x01\xff",
            "empty": np.zeros((0, 4), np.float32),
            "scalar0d": np.array(7, np.int32),
        }
        out = blobformat.decode(blobformat.encode(payload))
        assert out["watermark"] == 12345
        np.testing.assert_array_equal(out["arr"], payload["arr"])
        np.testing.assert_array_equal(out["f32"], payload["f32"])
        assert out["nested"]["a"] == [1, 2.5, "x", None, True]
        assert out["nested"]["t"] == (1, "two", np.float64(3.5))
        assert isinstance(out["nested"]["t"], tuple)
        assert out["intkeys"][1] == "one"
        assert out["intkeys"][(2, 3)] == "pair"
        assert out["blob"] == b"\x00\x01\xff"
        assert out["empty"].shape == (0, 4)
        assert out["scalar0d"] == 7 and out["scalar0d"].shape == ()

    def test_object_dtype_array_round_trips_via_pickle_escape(self):
        # A dtype=object array (user ValueState holding strings — the
        # line-source shape) must NOT enter the raw array section: its
        # buffer holds pointers, so decode would be garbage. It routes
        # through the counted pickle escape and round-trips exactly.
        payload = {"lines": np.array(["a", "bb", None], dtype=object),
                   "num": np.arange(3, dtype=np.int64)}
        blob = blobformat.encode(payload)
        header, _ = blobformat.read_header(blob)
        assert header["pickle_escapes"] == 1
        assert len(header["arrays"]) == 1  # only the int64 array
        out = blobformat.decode(blob)
        assert out["lines"].dtype == object
        assert list(out["lines"]) == ["a", "bb", None]
        np.testing.assert_array_equal(out["num"], payload["num"])

    def test_panestate_and_none_lanes(self):
        st = PaneState(sums=None, maxs=None, mins=None,
                       counts=np.arange(12, dtype=np.int32).reshape(3, 4))
        out = blobformat.decode(blobformat.encode({"panes": st}))
        assert isinstance(out["panes"], PaneState)
        assert out["panes"].sums is None
        np.testing.assert_array_equal(out["panes"].counts, st.counts)

    def test_header_readable_without_framework(self):
        """The format contract for non-Python tooling: magic + u32 len +
        JSON header + raw arrays at recorded offsets."""
        raw = blobformat.encode({"xs": np.arange(5, dtype=np.int64)})
        assert raw[:8] == b"FTCKPT3\n"
        hlen = struct.unpack("<I", raw[8:12])[0]
        header = json.loads(raw[12:12 + hlen].decode())
        spec = header["arrays"][0]
        base = 12 + hlen
        xs = np.frombuffer(raw, np.dtype(spec["dtype"]),
                           offset=base + spec["offset"], count=5)
        np.testing.assert_array_equal(xs, np.arange(5))
        assert header["pickle_escapes"] == 0

    def test_operator_snapshot_has_no_pickle_escapes(self):
        """The framework's own snapshots must be fully self-describing."""
        from flink_tpu.api.windowing import SlidingEventTimeWindows
        from flink_tpu.ops.aggregates import count
        from flink_tpu.ops.window import WindowOperator

        op = WindowOperator(SlidingEventTimeWindows.of(4000, 2000), count(),
                            num_shards=4, slots_per_shard=32)
        rng = np.random.default_rng(0)
        op.process_batch(rng.integers(0, 20, 500).astype(np.int64),
                         rng.integers(0, 6000, 500).astype(np.int64), {})
        op.advance_watermark(3000)
        snap = op.snapshot_state()
        from flink_tpu.checkpoint.coordinator import materialize_snapshot
        raw = blobformat.encode(materialize_snapshot(snap))
        hlen = struct.unpack("<I", raw[8:12])[0]
        header = json.loads(raw[12:12 + hlen].decode())
        assert header["pickle_escapes"] == 0

    def test_restore_across_code_change(self):
        """A field ADDED to a snapshotted structure between save and
        restore must not break the load (readers .get with defaults),
        and an UNKNOWN saved field must survive the round trip."""
        old_shape = {"panes": np.ones(4), "watermark": 7}
        raw = blobformat.encode(old_shape)
        out = blobformat.decode(raw)
        # new code reads a field the old snapshot lacks -> default
        assert out.get("refire", []) == []
        # old snapshot with an extra field new code doesn't know
        raw2 = blobformat.encode({**old_shape, "legacy_field": 42})
        out2 = blobformat.decode(raw2)
        assert out2["legacy_field"] == 42
        assert out2["watermark"] == 7


class TestStorageV3:
    def test_save_load_v3_single(self, tmp_path):
        st = FsCheckpointStorage(str(tmp_path), "job")
        payload = {"watermark": 5, "arr": np.arange(3)}
        h = st.save(1, payload)
        m = json.loads(open(os.path.join(h.path, "MANIFEST.json")).read())
        assert m["format_version"] == 3
        out = FsCheckpointStorage.load(h)
        np.testing.assert_array_equal(out["arr"], np.arange(3))

    def test_save_v2_blobs_are_v3_format(self, tmp_path):
        st = FsCheckpointStorage(str(tmp_path), "job")
        blob = blobformat.encode({"counts": np.ones(4, np.int32)})
        h = st.save_v2(1, {"op_versions": {"7": 1}}, {"7": blob}, {})
        out = FsCheckpointStorage.load(h)
        np.testing.assert_array_equal(out["operators"][7]["counts"],
                                      np.ones(4, np.int32))
        raw = open(os.path.join(h.path, "op-7.blob"), "rb").read()
        assert blobformat.is_v3(raw)

    def test_legacy_v2_pickle_checkpoint_still_loads(self, tmp_path):
        """A checkpoint written by the round-3 (v2/pickle) code must
        restore under the v3 loader."""
        d = tmp_path / "job" / "chk-9"
        d.mkdir(parents=True)
        (d / "meta.pkl").write_bytes(pickle.dumps({"watermark": 9}))
        (d / "op-3.pkl").write_bytes(
            pickle.dumps({"counts": np.arange(4)}))
        (d / "MANIFEST.json").write_text(json.dumps({
            "checkpoint_id": 9, "timestamp_ms": 0, "job_id": "job",
            "savepoint": False, "format_version": 2,
            "compression": "none",
            "ops": {"3": {"file": "op-3.pkl", "version": 1}}}))
        out = FsCheckpointStorage.load(str(d))
        assert out["watermark"] == 9
        np.testing.assert_array_equal(out["operators"][3]["counts"],
                                      np.arange(4))

    def test_v3_hardlinks_v2_pickle_base_blob(self, tmp_path):
        """Incremental reuse across an upgrade: a v3 checkpoint
        hardlinking an op blob written by a v2 (pickle) base must load
        — per-blob magic dispatch."""
        from flink_tpu.checkpoint.storage import ReusedOpState

        base = tmp_path / "job" / "chk-1"
        base.mkdir(parents=True)
        legacy = base / "op-5.pkl"
        legacy.write_bytes(pickle.dumps({"counts": np.arange(6)}))
        st = FsCheckpointStorage(str(tmp_path), "job")
        h = st.save_v2(2, {}, {}, {"5": ReusedOpState(str(legacy), 3)})
        out = FsCheckpointStorage.load(h)
        np.testing.assert_array_equal(out["operators"][5]["counts"],
                                      np.arange(6))

    def test_full_job_checkpoint_resume_v3(self, tmp_path):
        """End to end through the driver: checkpoint under v3, restore,
        and continue with identical results (the exactly-once contract
        exercised by test_checkpoint, on the new format)."""
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.windowing import TumblingEventTimeWindows
        from flink_tpu.config import Configuration
        from flink_tpu.time.watermarks import WatermarkStrategy

        def build(tag, extra=None):
            env = StreamExecutionEnvironment(Configuration({
                "state.num-key-shards": 4, "state.slots-per-shard": 32,
                "pipeline.microbatch-size": 64,
                "execution.checkpointing.dir": str(tmp_path / "ckpt"),
                "execution.checkpointing.interval": 1,
                **(extra or {}),
            }))
            keys = np.arange(200, dtype=np.int64) % 13
            ts = np.arange(200, dtype=np.int64) * 20
            sink = (env.from_collection({"k": keys}, ts)
                    .assign_timestamps_and_watermarks(
                        WatermarkStrategy.for_monotonous_timestamps())
                    .key_by("k")
                    .window(TumblingEventTimeWindows.of(1000))
                    .count()
                    .collect())
            return env, sink

        env, sink = build("a")
        env.execute("v3job")
        rows = sorted((int(r["key"]), int(r["window_start"]), int(r["count"]))
                      for r in sink.rows)
        ck = tmp_path / "ckpt" / "v3job"
        chks = [p for p in os.listdir(ck) if p.startswith("chk-")]
        assert chks, "no checkpoint written"
        m = json.loads(open(ck / sorted(chks)[-1] / "MANIFEST.json").read())
        assert m["format_version"] == 3
        # restore from the latest checkpoint into a fresh env: replayed
        # results must match the uninterrupted run's
        env2, sink2 = build(
            "b", {"execution.checkpointing.restore": "latest"})
        env2.execute("v3job")
        rows2 = sorted((int(r["key"]), int(r["window_start"]), int(r["count"]))
                       for r in sink2.rows)
        assert rows2 == rows or len(rows2) <= len(rows)
