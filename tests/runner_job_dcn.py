"""Deployable CROSS-HOST job for the coordinator-deploy tier-5 test:
one job spanning two runner processes through the DCN exchange. Same
"job jar" contract as runner_job.py; each process commits its shard
span's output under its own sink directory (epoch ids align across the
fleet — the checkpoint decision rides the step rendezvous — so a
shared directory would collide part names)."""
import numpy as np

from flink_tpu.api.sinks import FileTransactionalSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.time.watermarks import WatermarkStrategy

N_KEYS = 40
BATCH = 128


def batch_of(split: int, i: int):
    rng = np.random.default_rng(77 + 1000 * split + i)
    keys = rng.integers(0, N_KEYS, BATCH).astype(np.int64)
    ts = np.sort(rng.integers(i * 500, i * 500 + 1000, BATCH)).astype(np.int64)
    return keys, ts


def golden_counts(n_batches: int):
    expect = {}
    for split in (0, 1):
        for i in range(n_batches):
            keys, ts = batch_of(split, i)
            for k, t in zip(keys, ts):
                kk = (int(k), (int(t) // 1000) * 1000)
                expect[kk] = expect.get(kk, 0) + 1
    return expect


def build(env):
    n_batches = int(env.config.get_raw("test.n-batches", 20))
    sink_dir = env.config.get_raw("test.sink-dir")
    assert sink_dir, "test.sink-dir must be set"
    pid = int(env.config.get_raw("cluster.process-id", 0))

    def gen(split, i):
        if i >= n_batches:
            return None
        keys, ts = batch_of(int(split), i)
        return {"k": keys}, ts

    (env.from_source(GeneratorSource(gen, n_splits=2),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
        .key_by("k")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .add_sink(FileTransactionalSink(f"{sink_dir}-p{pid}")))
