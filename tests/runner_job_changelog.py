"""Deployable changelog-plane jobs for the CLI smoke (``python -m
flink_tpu run --local``): the two SQL shapes ISSUE 20 lifted, as
shippable ``--entry`` modules. Data is derived from fixed seeds so the
test recomputes the reference independently of the engine (the
committed-output diff)."""
import numpy as np

from flink_tpu.api.sinks import FileTransactionalSink, UpsertSink
from flink_tpu.table.api import TableEnvironment

N = 400
NK = 6


def left_events():
    rng = np.random.default_rng(99)
    k = rng.integers(0, NK, N).astype(np.int64)
    ts = np.sort(rng.integers(0, 4000, N)).astype(np.int64)
    return k, ts


def right_events():
    rng = np.random.default_rng(100)
    k = rng.integers(0, NK, N).astype(np.int64)
    w = rng.integers(1, 50, N).astype(np.int64)
    ts2 = np.sort(rng.integers(0, 4000, N)).astype(np.int64)
    return k, w, ts2


def reference_join_agg():
    """O(n^2) pair enumeration of the agg-over-join output — no engine
    machinery involved."""
    lk, lts = left_events()
    rk, rw, rts = right_events()
    out = {}
    for i in range(N):
        for j in range(N):
            if lk[i] == rk[j] and lts[i] // 1000 == rts[j] // 1000:
                key = (int(lk[i]), int(lts[i]) // 1000 * 1000)
                c, s = out.get(key, (0, 0))
                out[key] = (c + 1, s + int(rw[j]))
    return out


def build_join_agg(env):
    """Agg-over-join: COUNT/SUM over a tumbling window JOIN, committed
    through the transactional file sink."""
    sink_dir = env.config.get_raw("test.sink-dir")
    assert sink_dir, "test.sink-dir must be set"
    lk, lts = left_events()
    rk, rw, rts = right_events()
    t_env = TableEnvironment.create(env)
    left = env.from_collection({"k": lk, "ts": lts}, lts, batch_size=100)
    right = env.from_collection({"k2": rk, "w": rw, "ts2": rts}, rts,
                                batch_size=100)
    t_env.create_temporary_view("L", left, ["k", "ts"])
    t_env.create_temporary_view("R", right, ["k2", "w", "ts2"])
    t = t_env.sql_query(
        "SELECT L.k, window_start, COUNT(*) AS c, SUM(R.w) AS sw "
        "FROM TABLE(TUMBLE(TABLE L, DESCRIPTOR(ts), INTERVAL '1' SECOND)) "
        "JOIN TABLE(TUMBLE(TABLE R, DESCRIPTOR(ts2), INTERVAL '1' SECOND)) "
        "ON L.k = R.k2 GROUP BY k, window_start")
    t.stream.add_sink(FileTransactionalSink(sink_dir))


def group_by_events():
    rng = np.random.default_rng(101)
    k = rng.integers(0, NK, N).astype(np.int64)
    v = rng.integers(1, 50, N).astype(np.int64)
    ts = np.arange(N, dtype=np.int64)
    return k, v, ts


def reference_group_by():
    """Final per-key (count, sum) — a plain dict fold."""
    k, v, _ = group_by_events()
    out = {}
    for kk, vv in zip(k, v):
        c, s = out.get(int(kk), (0, 0))
        out[int(kk)] = (c + 1, s + int(vv))
    return out


# module-level so the --local smoke can read the materialized table
# back after cli_main returns (the run executes in-process)
group_by_sink = UpsertSink(key_fields=("k",))


def build_group_by(env):
    """Unwindowed GROUP BY: the retract-mode changelog materialized
    into an upsert view."""
    group_by_sink.state.clear()
    k, v, ts = group_by_events()
    t_env = TableEnvironment.create(env)
    stream = env.from_collection({"k": k, "v": v}, ts, batch_size=100)
    t_env.create_temporary_view(
        "t", stream, schema=["k", "v", "ts"], time_attr="ts")
    tbl = t_env.sql_query(
        "SELECT k, COUNT(*) AS c, SUM(v) AS sv FROM t GROUP BY k")
    tbl.stream.add_sink(group_by_sink)
