"""Async I/O operator (ref: AsyncWaitOperator / AsyncDataStream ITCases:
ordered vs unordered retrieval, capacity backpressure, watermark
hold-back, enrichment correctness)."""
import threading
import time

import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.functions import KeyedProcessFunction
from flink_tpu.api.sinks import CollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.ops.async_io import AsyncIOOperator
from flink_tpu.time.watermarks import WatermarkStrategy


def make_env():
    return StreamExecutionEnvironment(Configuration(
        {"pipeline.microbatch-size": 64,
         "state.num-key-shards": 4, "state.slots-per-shard": 32}))


def source(n_batches=6, b=64):
    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        return ({"k": rng.integers(0, 10, b).astype(np.int64),
                 "x": np.full(b, i, np.int64)},
                np.sort(rng.integers(i * 500, i * 500 + 900, b)).astype(np.int64))
    return gen


class TestOperatorDirect:
    def test_ordered_release(self):
        order = []

        def slow_first(data, ts):
            # batch 0 is the slowest: ordered mode must still release 0,1,2
            time.sleep(0.3 if data["i"][0] == 0 else 0.01)
            order.append(int(data["i"][0]))
            return dict(data)

        op = AsyncIOOperator(slow_first, capacity=4, ordered=True)
        for i in range(3):
            op.submit(({"i": np.array([i])}, np.array([i]), np.ones(1, bool)), i)
        out = op.poll(drain=True)
        assert [int(b[0]["i"][0]) for b in out] == [0, 1, 2]
        op.close()

    def test_unordered_release_as_completed(self):
        ev = threading.Event()

        def blocky(data, ts):
            if data["i"][0] == 0:
                ev.wait(5)
            return dict(data)

        op = AsyncIOOperator(blocky, capacity=4, ordered=False)
        for i in range(3):
            op.submit(({"i": np.array([i])}, np.array([i]), np.ones(1, bool)), i)
        deadline = time.time() + 5
        got = []
        while len(got) < 2 and time.time() < deadline:
            got += op.poll()
            time.sleep(0.01)
        assert sorted(int(b[0]["i"][0]) for b in got) == [1, 2]
        # watermark held at the oldest pending submit (batch 0, wm 0)
        assert op.watermark <= 0
        ev.set()
        got += op.poll(drain=True)
        assert sorted(int(b[0]["i"][0]) for b in got) == [0, 1, 2]
        op.close()

    def test_capacity_backpressure_via_throttle(self):
        """submit() never blocks (push-lock discipline); throttle() —
        the outside-the-lock hook the ingest loop calls — blocks while
        more than ``capacity`` batches are still running."""
        release = threading.Event()

        def gate(data, ts):
            release.wait(10)
            return dict(data)

        op = AsyncIOOperator(gate, capacity=2, ordered=True, workers=4)
        t0 = time.time()
        for i in range(3):
            op.submit(({"i": np.array([i])}, np.array([i]),
                       np.ones(1, bool)), i)
        assert time.time() - t0 < 0.2  # submits are non-blocking

        def delayed_release():
            time.sleep(0.25)
            release.set()

        threading.Thread(target=delayed_release, daemon=True).start()
        op.throttle()  # 3 running > capacity 2: blocks until release
        assert time.time() - t0 >= 0.2
        op.poll(drain=True)
        op.close()

    def test_length_change_rejected(self):
        op = AsyncIOOperator(lambda d, ts: {"x": np.zeros(3)}, capacity=2)
        op.submit(({"x": np.zeros(2)}, np.zeros(2, np.int64),
                   np.ones(2, bool)), 0)
        with pytest.raises(ValueError, match="1:1"):
            op.poll(drain=True)
        op.close()

    def test_user_exception_propagates(self):
        def boom(data, ts):
            raise RuntimeError("lookup failed")

        op = AsyncIOOperator(boom, capacity=2)
        op.submit(({"x": np.zeros(1)}, np.zeros(1, np.int64),
                   np.ones(1, bool)), 0)
        with pytest.raises(RuntimeError, match="lookup failed"):
            op.poll(drain=True)
        op.close()


class TestAsyncE2E:
    def test_enrichment_into_window(self):
        """Enriched field feeds a downstream window; results must match
        the synchronous equivalent exactly (watermark hold-back keeps
        late-drops at zero despite slow lookups)."""
        def enrich(data, ts):
            time.sleep(0.02)  # slow external lookup
            out = dict(data)
            out["v"] = data["x"] * 10 + 1
            return out

        def build(env, sink, use_async):
            s = env.from_source(
                GeneratorSource(source()),
                WatermarkStrategy.for_bounded_out_of_orderness(500))
            if use_async:
                s = s.async_io(enrich, capacity=3)
            else:
                s = s.map(lambda d: {**d, "v": d["x"] * 10 + 1})
            (s.key_by("k").window(TumblingEventTimeWindows.of(1_000))
             .sum("v").add_sink(sink))

        env1, s1 = make_env(), CollectSink()
        build(env1, s1, use_async=False)
        env1.execute("sync")
        env2, s2 = make_env(), CollectSink()
        build(env2, s2, use_async=True)
        r = env2.execute("async")
        rows = lambda s: sorted((int(x["key"]), int(x["window_end"]),
                                 float(x["sum_v"])) for x in s.rows)
        assert rows(s1) == rows(s2)
        assert r.metrics.get("late_records", 0) == 0

    def test_checkpointing_with_async_io(self, tmp_path):
        """Interval checkpoints must coexist with async_io: the barrier
        drains in-flight batches first, the (stateless) operator rides
        the snapshot seam, and the job completes exactly-once
        (regression: snapshot_state used to be missing entirely)."""
        def enrich(data, ts):
            time.sleep(0.005)
            return {**dict(data), "v": data["x"] + 1}

        env = StreamExecutionEnvironment(Configuration(
            {"pipeline.microbatch-size": 64,
             "state.num-key-shards": 4, "state.slots-per-shard": 32,
             "execution.checkpointing.dir": str(tmp_path),
             "execution.checkpointing.interval": 1}))
        sink = CollectSink()
        (env.from_source(GeneratorSource(source()),
                         WatermarkStrategy.for_bounded_out_of_orderness(500))
         .async_io(enrich, capacity=3)
         .key_by("k").window(TumblingEventTimeWindows.of(1_000))
         .sum("v").add_sink(sink))
        env.execute("ckpt-async")
        assert len(sink.rows) > 0

    def test_unordered_same_results(self):
        def enrich(data, ts):
            time.sleep(0.001 * int(data["x"][0] % 3))
            return {**dict(data), "v": data["x"] + 1}

        def build(env, sink, ordered):
            (env.from_source(GeneratorSource(source()),
                             WatermarkStrategy.for_bounded_out_of_orderness(500))
             .async_io(enrich, capacity=4, ordered=ordered)
             .key_by("k").window(TumblingEventTimeWindows.of(1_000))
             .sum("v").add_sink(sink))

        outs = []
        for ordered in (True, False):
            env, sink = make_env(), CollectSink()
            build(env, sink, ordered)
            env.execute(f"o-{ordered}")
            outs.append(sorted((int(x["key"]), int(x["window_end"]),
                                float(x["sum_v"])) for x in sink.rows))
        assert outs[0] == outs[1]
