"""Session-cluster runtime mode (flink_tpu/runtime/session.py) — the
multi-tenant control plane: slot quotas + FIFO admission queue, fair
drain scheduling, per-job isolation (checkpoint dirs, metrics,
fault plans), queue-depth autoscaling, and the `python -m flink_tpu
session ...` CLI surface (exit-code contract 0/1/2, like
tests/test_cli.py TestExitCodeContract).

ref: the session deployment mode + Dispatcher/slot-pool tests of the
reference (DispatcherTest / SlotPoolImplTest / session-cluster
ITCases), PAPER §3.4/§4; ROADMAP item 3.
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from flink_tpu.config import Configuration
from flink_tpu.runtime.coordinator import RunnerInfo
from flink_tpu.runtime.rpc import RpcEndpoint, RpcServer
from flink_tpu.runtime.session import (
    FairDrainGate,
    LocalSessionCluster,
    SessionDispatcher,
    SessionSlotPool,
)

from test_runner_process import wait_until

pytestmark = pytest.mark.session

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cluster_conf(extra=None):
    conf = {
        "heartbeat.interval": "200ms",
        "heartbeat.timeout": "5s",
        "session.autoscale": False,
    }
    conf.update(extra or {})
    return Configuration(conf)


def _job_conf(tmp_path, tag, n_batches=6, extra=None):
    conf = {
        "test.n-batches": n_batches,
        "test.sink-dir": str(tmp_path / f"sink-{tag}"),
        "execution.checkpointing.dir": str(tmp_path / "chk"),
        "execution.checkpointing.interval": "200ms",
        "state.num-key-shards": 8,
        "state.slots-per-shard": 16,
    }
    conf.update(extra or {})
    return conf


def _golden(sink_dir, n_batches):
    import runner_job
    from flink_tpu.api.sinks import FileTransactionalSink

    got = {}
    for r in FileTransactionalSink.committed_rows(sink_dir):
        kk = (int(r["key"]), int(r["window_start"]))
        assert kk not in got, f"duplicate emission for {kk}"
        got[kk] = int(r["count"])
    assert got == runner_job.golden_counts(n_batches)


class TestFairDrainGate:
    def test_solo_member_never_waits(self):
        g = FairDrainGate()
        g.register("a")
        t0 = time.perf_counter()
        for _ in range(1000):
            with g.turn("a"):
                pass
        assert time.perf_counter() - t0 < 1.0  # uncontended fast path
        g.unregister("a")
        assert g.members == 0

    def test_burst_requeues_behind_waiter(self):
        """THE fairness contract: a holder that releases and
        immediately re-requests goes BEHIND a waiting peer — a
        bursting job cannot starve another's drain."""
        g = FairDrainGate()
        g.register("burst")
        g.register("quiet")
        order = []
        inside = threading.Event()
        release = threading.Event()

        def burst():
            with g.turn("burst"):
                order.append("burst-1")
                inside.set()
                release.wait(5)
            with g.turn("burst"):  # immediate re-request
                order.append("burst-2")

        def quiet():
            inside.wait(5)
            # queue up WHILE burst holds the turn
            with g.turn("quiet"):
                order.append("quiet-1")

        tb = threading.Thread(target=burst)
        tq = threading.Thread(target=quiet)
        tb.start()
        tq.start()
        inside.wait(5)
        time.sleep(0.1)  # let quiet actually enqueue
        release.set()
        tb.join(5)
        tq.join(5)
        assert order == ["burst-1", "quiet-1", "burst-2"]

    def test_unregister_releases_held_turn(self):
        """A job whose drain thread dies while HOLDING the turn must
        not wedge its peers: unregister releases everything it held."""
        g = FairDrainGate()
        g.register("dead")
        g.register("live")
        got = threading.Event()
        cm = g.turn("dead")
        cm.__enter__()  # hold the turn, never cleanly release
        g.unregister("dead")

        def peer():
            with g.turn("live"):
                got.set()

        threading.Thread(target=peer).start()
        assert got.wait(5), "peer never acquired after unregister"


class TestSessionSlotPool:
    def _runner(self, rid, n=1):
        return RunnerInfo(rid, "127.0.0.1", n, time.time(), port=1)

    def test_capacity_is_logical_slots_not_devices(self):
        p = SessionSlotPool(4)
        r = self._runner("r1", n=1)  # 1 device, 4 session slots
        assert p.capacity(r) == 4
        assert p.free_slots(r) == 4
        p.allocate("j1", "r1", 1)
        p.allocate("j2", "r1", 2)
        assert p.free_slots(r) == 1
        assert p.pick("j3", 2, [r]) is None  # 2 > 1 free
        assert p.pick("j3", 1, [r]) is r
        p.release("j2")
        assert p.free_slots(r) == 3

    def test_best_fit_packs_shared_chips(self):
        p = SessionSlotPool(4)
        r1, r2 = self._runner("r1"), self._runner("r2")
        p.allocate("j1", "r1", 2)
        # r1 has 2 free, r2 has 4 free: best-fit picks the fuller one
        assert p.pick("j2", 2, [r1, r2]) is r1


class TestAdmission:
    """Quota validation + FIFO queueing against a fake runner gateway
    (the pattern of test_control_plane.TestActiveProvisioning — jobs
    deploy but never run, so the queue mechanics are deterministic)."""

    class _GW(RpcEndpoint):
        def __init__(self):
            self.jobs = []

        def rpc_run_job(self, job_id, entry, config=None, attempt=1,
                        **kw):
            self.jobs.append((job_id, dict(config or {})))
            return {"accepted": True}

        def rpc_cancel_job(self, job_id, attempt=None):
            return {"ok": True}

    def _register(self, disp, gw_port, rid):
        disp.rpc_register_runner(rid, "127.0.0.1", 1, port=gw_port)

    def test_quota_rejections(self):
        disp = SessionDispatcher(_cluster_conf({
            "session.runner-slots": 2}))
        try:
            r = disp.rpc_submit_session_job("a", "m:f",
                                            {"session.slots-per-job": 0})
            assert not r["admitted"] and "below 1" in r["reason"]
            r = disp.rpc_submit_session_job("b", "m:f",
                                            {"session.slots-per-job": 3})
            assert not r["admitted"] and "runner-slots" in r["reason"]
            r = disp.rpc_submit_session_job("c", "m:f", {})
            assert r["admitted"]
            # the SAME submission re-delivered (the HA client retries a
            # submit whose response died with the leader): ack'd as a
            # duplicate, never an error — the job IS admitted
            r = disp.rpc_submit_session_job("c", "m:f", {})
            assert r["admitted"] and r.get("duplicate")
            # a DIFFERENT job reusing an active id is still rejected
            r = disp.rpc_submit_session_job("c", "other:entry", {})
            assert not r["admitted"] and "already active" in r["reason"]
        finally:
            disp.close()

    def test_invalid_cluster_quotas_refuse_to_start(self):
        with pytest.raises(ValueError):
            SessionDispatcher(_cluster_conf({"session.max-jobs": 0}))
        with pytest.raises(ValueError):
            SessionDispatcher(_cluster_conf({"session.runner-slots": 0}))

    def test_max_jobs_queues_fifo_and_drains_on_finish(self):
        disp = SessionDispatcher(_cluster_conf({
            "session.max-jobs": 1, "session.runner-slots": 8}))
        gw = self._GW()
        srv = RpcServer(gw)
        try:
            self._register(disp, srv.port, "r1")
            for jid in ("j1", "j2", "j3"):
                assert disp.rpc_submit_session_job(
                    jid, "m:f", {})["admitted"]
            wait_until(lambda: disp.jobs["j1"].state == "RUNNING", 10,
                       what="j1 deployed")
            time.sleep(0.3)  # deploy kicks settle
            assert disp.jobs["j2"].state == "WAITING_FOR_RESOURCES"
            assert disp.jobs["j3"].state == "WAITING_FOR_RESOURCES"
            jobs = {j["job_id"]: j for j in
                    disp.rpc_session_jobs()["jobs"]}
            assert jobs["j2"]["queue_position"] == 0
            assert jobs["j3"]["queue_position"] == 1
            # finish j1 → FIFO admits j2, never j3 first
            disp.rpc_finish_job("j1", attempt=1)
            wait_until(lambda: disp.jobs["j2"].state == "RUNNING", 10,
                       what="j2 admitted after j1 finished")
            time.sleep(0.2)
            assert disp.jobs["j3"].state == "WAITING_FOR_RESOURCES"
            disp.rpc_finish_job("j2", attempt=1)
            wait_until(lambda: disp.jobs["j3"].state == "RUNNING", 10,
                       what="j3 admitted last")
        finally:
            disp.close()
            srv.close()

    def test_restarting_job_holds_its_admission(self):
        """max-jobs headroom counts RESTARTING jobs: an admitted
        tenant mid-recovery still owns its slot — a queued peer must
        not slip in during the restart window and over-admit the
        cluster (review regression)."""
        disp = SessionDispatcher(_cluster_conf({
            "session.max-jobs": 1, "session.runner-slots": 8,
            "restart-strategy.type": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 3,
            "restart-strategy.fixed-delay.delay": "100ms"}))
        gw = self._GW()
        srv = RpcServer(gw)
        try:
            self._register(disp, srv.port, "r1")
            assert disp.rpc_submit_session_job("j1", "m:f", {})["admitted"]
            wait_until(lambda: disp.jobs["j1"].state == "RUNNING", 10,
                       what="j1 running")
            d = disp.rpc_report_failure("j1", "boom", attempt=1)
            assert d["action"] == "restart"
            assert disp.rpc_submit_session_job("j2", "m:f", {})["admitted"]
            # j1 recovers into its own admission; j2 stays queued
            wait_until(lambda: disp.jobs["j1"].state == "RUNNING", 10,
                       what="j1 recovered")
            time.sleep(0.3)
            assert disp.jobs["j2"].state == "WAITING_FOR_RESOURCES"
            disp.rpc_finish_job("j1", attempt=disp.jobs["j1"].attempts)
            wait_until(lambda: disp.jobs["j2"].state == "RUNNING", 10,
                       what="j2 admitted after j1 finished")
        finally:
            disp.close()
            srv.close()

    def test_slot_exhaustion_queues_even_under_max_jobs(self):
        disp = SessionDispatcher(_cluster_conf({
            "session.max-jobs": 8, "session.runner-slots": 1}))
        gw = self._GW()
        srv = RpcServer(gw)
        try:
            self._register(disp, srv.port, "r1")
            assert disp.rpc_submit_session_job("a", "m:f", {})["admitted"]
            wait_until(lambda: disp.jobs["a"].state == "RUNNING", 10,
                       what="a deployed")
            assert disp.rpc_submit_session_job("b", "m:f", {})["admitted"]
            time.sleep(0.3)
            assert disp.jobs["b"].state == "WAITING_FOR_RESOURCES"
            # capacity registers → the queued job deploys
            gw2 = self._GW()
            srv2 = RpcServer(gw2)
            self._register(disp, srv2.port, "r2")
            wait_until(lambda: disp.jobs["b"].state == "RUNNING", 10,
                       what="b deployed on new capacity")
            srv2.close()
        finally:
            disp.close()
            srv.close()

    def test_isolation_stamping(self):
        """Admission stamps the per-tenant isolation config: namespaced
        checkpoint dir, scoped faults, fair drain; the deploy stamps
        the resource-share denominator."""
        disp = SessionDispatcher(_cluster_conf({
            "session.runner-slots": 4}))
        gw = self._GW()
        srv = RpcServer(gw)
        try:
            self._register(disp, srv.port, "r1")
            disp.rpc_submit_session_job(
                "iso", "m:f",
                {"execution.checkpointing.dir": "/tmp/base",
                 "faults.inject": "checkpoint.storage.write=raise x1"})
            disp.rpc_submit_session_job("iso2", "m:f", {})
            wait_until(lambda: len(gw.jobs) == 2, 10,
                       what="both deploys pushed")
            pushed = dict(gw.jobs)
            assert pushed["iso"]["execution.checkpointing.dir"] == (
                "/tmp/base/iso")
            assert pushed["iso"]["session.scoped-faults"] is True
            assert pushed["iso"]["session.fair-drain"] is True
            assert "session.scoped-faults" not in pushed["iso2"]
            # the share denominator is STATIC and slot-proportional
            # (runner-slots // slots-per-job = 4), identical for every
            # tenant regardless of deploy order — a resident-count
            # stamp would hand the first tenant the whole host pool
            # (review regression)
            assert pushed["iso"]["session.concurrent-jobs"] == 4
            assert pushed["iso2"]["session.concurrent-jobs"] == 4
        finally:
            disp.close()
            srv.close()


class TestAutoscaler:
    class _GW(TestAdmission._GW):
        pass

    def _mk(self, extra=None):
        conf = {"session.runner-slots": 1, "session.max-jobs": 8,
                "session.autoscale": False,  # drive ticks by hand
                "session.min-runners": 1,
                "session.scale-down-idle": "100ms"}
        conf.update(extra or {})
        return SessionDispatcher(_cluster_conf(conf))

    def test_queue_depth_pushes_provisioner_demand(self):
        disp = self._mk()
        gw = self._GW()
        srv = RpcServer(gw)
        try:
            disp.rpc_register_runner("r1", "127.0.0.1", 1, port=srv.port)
            assert disp.rpc_submit_session_job("a", "m:f", {})["admitted"]
            wait_until(lambda: disp.jobs["a"].state == "RUNNING", 10,
                       what="a running")
            assert disp.rpc_submit_session_job("b", "m:f", {})["admitted"]
            wait_until(
                lambda: disp.jobs["b"].state == "WAITING_FOR_RESOURCES",
                10, what="b queued")
            disp._autoscale_tick()
            assert disp.provisioner.requests, "no scale-out demand"
            assert disp.provisioner.requests[-1][0]["job_id"] == "b"
            snap = disp.registry.snapshot()
            assert snap["session.queued_jobs"] == 1.0
            assert snap["session.slot_pressure"] == 1.0
            assert snap["session.scale_up_requests"] >= 1
        finally:
            disp.close()
            srv.close()

    def test_full_slot_pressure_prewarms_capacity(self):
        disp = self._mk()
        gw = self._GW()
        srv = RpcServer(gw)
        try:
            disp.rpc_register_runner("r1", "127.0.0.1", 1, port=srv.port)
            assert disp.rpc_submit_session_job("a", "m:f", {})["admitted"]
            wait_until(lambda: disp.jobs["a"].state == "RUNNING", 10,
                       what="a running")
            disp._autoscale_tick()  # no queue, but every slot is used
            assert disp.provisioner.requests
            assert disp.provisioner.requests[-1][0]["job_id"] == (
                "(slot-pressure)")
        finally:
            disp.close()
            srv.close()

    def test_headroom_parked_jobs_drive_no_demand_and_allow_scale_in(
            self):
        """A job parked by max-jobs headroom cannot use new capacity:
        it must neither push provisioner demand nor pin idle runners
        alive (review regression — the old tick requested runners the
        admission gate would never let the queue use, then the waiting
        queue blocked their scale-in forever)."""
        disp = self._mk({"session.max-jobs": 1,
                         "session.runner-slots": 4})
        gw1, gw2 = self._GW(), self._GW()
        srv1, srv2 = RpcServer(gw1), RpcServer(gw2)
        try:
            disp.rpc_register_runner("r1", "127.0.0.1", 1, port=srv1.port)
            disp.rpc_register_runner("r2", "127.0.0.1", 1, port=srv2.port)
            assert disp.rpc_submit_session_job("a", "m:f", {})["admitted"]
            wait_until(lambda: disp.jobs["a"].state == "RUNNING", 10,
                       what="a running")
            assert disp.rpc_submit_session_job("b", "m:f", {})["admitted"]
            wait_until(
                lambda: disp.jobs["b"].state == "WAITING_FOR_RESOURCES",
                10, what="b parked by headroom")
            now = time.time()
            disp._autoscale_tick(now=now)
            assert not disp.provisioner.requests, (
                "headroom-parked job drove scale-out demand")
            disp._autoscale_tick(now=now + 1.0)
            # the idle runner is NOT pinned by the headroom queue
            assert len(disp.provisioner.releases) == 1
        finally:
            disp.close()
            srv1.close()
            srv2.close()

    def test_scale_out_demand_clamped_to_max_runners_budget(self):
        """session.max-runners clamps demand SIZE, not just whether a
        request fires: the provisioner is never asked for more slot
        capacity than the fleet may still grow by (review
        regression)."""
        disp = self._mk({"session.max-jobs": 16,
                         "session.runner-slots": 1,
                         "session.max-runners": 2})
        gw = self._GW()
        srv = RpcServer(gw)
        try:
            disp.rpc_register_runner("r1", "127.0.0.1", 1, port=srv.port)
            assert disp.rpc_submit_session_job("a", "m:f", {})["admitted"]
            wait_until(lambda: disp.jobs["a"].state == "RUNNING", 10,
                       what="a running")
            for jid in ("b", "c", "d", "e"):
                assert disp.rpc_submit_session_job(
                    jid, "m:f", {})["admitted"]
            wait_until(
                lambda: disp.jobs["e"].state == "WAITING_FOR_RESOURCES",
                10, what="queue formed")
            disp._autoscale_tick()
            assert disp.provisioner.requests
            demanded = sum(d["required_devices"]
                           for d in disp.provisioner.requests[-1])
            # fleet may grow by (2 - 1) runner × 1 slot = 1
            assert demanded <= 1, disp.provisioner.requests[-1]
        finally:
            disp.close()
            srv.close()

    def test_idle_runner_drained_and_released_above_floor(self):
        disp = self._mk()
        gw1, gw2 = self._GW(), self._GW()
        srv1, srv2 = RpcServer(gw1), RpcServer(gw2)
        try:
            disp.rpc_register_runner("r1", "127.0.0.1", 1, port=srv1.port)
            disp.rpc_register_runner("r2", "127.0.0.1", 1, port=srv2.port)
            now = time.time()
            disp._autoscale_tick(now=now)          # marks idle_since
            assert not disp.provisioner.releases   # not idle long enough
            disp._autoscale_tick(now=now + 1.0)    # > 100ms idle
            # min-runners=1: exactly ONE runner drains, one stays
            assert len(disp.provisioner.releases) == 1
            drained = disp.provisioner.releases[0][0]
            assert disp.runners[drained].draining
            alive = [r for r in disp.runners.values() if not r.draining]
            assert len(alive) == 1
            # the floor holds: further ticks never drain the last one
            disp._autoscale_tick(now=now + 10.0)
            assert len(disp.provisioner.releases) == 1
        finally:
            disp.close()
            srv1.close()
            srv2.close()

    def test_busy_runner_never_drained(self):
        disp = self._mk()
        gw1, gw2 = self._GW(), self._GW()
        srv1, srv2 = RpcServer(gw1), RpcServer(gw2)
        try:
            disp.rpc_register_runner("r1", "127.0.0.1", 1, port=srv1.port)
            disp.rpc_register_runner("r2", "127.0.0.1", 1, port=srv2.port)
            assert disp.rpc_submit_session_job("a", "m:f", {})["admitted"]
            assert disp.rpc_submit_session_job("b", "m:f", {})["admitted"]
            wait_until(lambda: disp.jobs["a"].state == "RUNNING"
                       and disp.jobs["b"].state == "RUNNING", 10,
                       what="both running")
            now = time.time()
            disp._autoscale_tick(now=now)
            disp._autoscale_tick(now=now + 10.0)
            assert not disp.provisioner.releases
        finally:
            disp.close()
            srv1.close()
            srv2.close()


class TestSessionE2E:
    """Tier-1 e2e on the real plane: dispatcher + in-process runners,
    real RPC, real drivers — K=2 concurrent jobs on one shared runner
    run to completion with fully isolated checkpoints and outputs
    (the acceptance bar of ROADMAP item 3's correctness half)."""

    def test_two_concurrent_jobs_one_runner_exactly_once(self, tmp_path):
        n = 6
        with LocalSessionCluster(_cluster_conf(), runners=1) as c:
            for tag in ("a", "b"):
                r = c.submit("runner_job:build",
                             config=_job_conf(tmp_path, tag, n),
                             job_id=f"job-{tag}")
                assert r["admitted"], r
            # both must be RUNNING at once — concurrency, not serial
            wait_until(
                lambda: all(
                    c.dispatcher.jobs[f"job-{t}"].state == "RUNNING"
                    for t in ("a", "b")), 30,
                what="both jobs running concurrently")
            assert c.wait("job-a") == "FINISHED"
            assert c.wait("job-b") == "FINISHED"
            # one shared runner hosted both
            assert (c.dispatcher.jobs["job-a"].assigned_runners
                    == c.dispatcher.jobs["job-b"].assigned_runners)
        _golden(str(tmp_path / "sink-a"), n)
        _golden(str(tmp_path / "sink-b"), n)
        # checkpoint isolation: one namespaced subtree per tenant
        assert sorted(os.listdir(tmp_path / "chk")) == ["job-a", "job-b"]

    def test_run_session_attaches_to_running_cluster(self, tmp_path):
        """`run --session H:P` submits through the dispatcher and
        blocks until terminal — the job rides the shared cluster, not
        a private runtime."""
        from flink_tpu.cli import main as cli_main

        n = 4
        with LocalSessionCluster(_cluster_conf(), runners=1) as c:
            conf_args = []
            for k, v in _job_conf(tmp_path, "att", n).items():
                conf_args += ["--conf", f"{k}={v}"]
            rc = cli_main(["run", "--session", c.address,
                           "--entry", "runner_job:build",
                           "--job-id", "attached", *conf_args])
            assert rc == 0
            assert c.dispatcher.jobs["attached"].state == "FINISHED"
        _golden(str(tmp_path / "sink-att"), n)


class TestSessionCliContract:
    """`python -m flink_tpu session ...` exit-code contract: 0 = ok,
    1 = cluster refused, 2 = usage error (argparse) — asserted like
    tests/test_cli.py TestExitCodeContract."""

    def test_usage_errors_exit_2(self, capsys):
        from flink_tpu.cli import main as cli_main

        for argv in (["session"],
                     ["session", "submit"],              # no --session
                     ["session", "submit", "--session", "x:1"],  # no entry
                     ["session", "cancel", "--session", "x:1"]):  # no job
            with pytest.raises(SystemExit) as e:
                cli_main(argv)
            assert e.value.code == 2, argv
        capsys.readouterr()

    def test_ok_0_refused_1(self, tmp_path, capsys):
        from flink_tpu.cli import main as cli_main

        with LocalSessionCluster(_cluster_conf(
                {"session.runner-slots": 2}), runners=1) as c:
            # 1: admission rejection (quota no runner can satisfy)
            rc = cli_main(["session", "submit", "--session", c.address,
                           "--entry", "runner_job:build",
                           "--conf", "session.slots-per-job=99"])
            out = json.loads(
                capsys.readouterr().out.strip().splitlines()[-1])
            assert rc == 1 and not out["admitted"]
            # 0: list
            assert cli_main(["session", "list", "--session",
                             c.address]) == 0
            out = json.loads(
                capsys.readouterr().out.strip().splitlines()[-1])
            assert out["jobs"] == []
            # 1: cancel of an unknown job id is an ERROR, not a silent
            # no-op (review regression)
            rc = cli_main(["session", "cancel", "--session", c.address,
                           "job-deadbeef"])
            out = json.loads(
                capsys.readouterr().out.strip().splitlines()[-1])
            assert rc == 1 and not out["ok"]
            # 0: stop
            assert cli_main(["session", "stop", "--session",
                             c.address]) == 0
            capsys.readouterr()

    def test_local_cluster_honors_requested_port(self):
        """`session start --port N` must bind N, not an ephemeral port
        (review regression: the flag was silently dropped)."""
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        with LocalSessionCluster(_cluster_conf(), runners=0,
                                 port=port) as c:
            assert c.port == port


class TestSessionCliSmoke:
    """Tier-1 CLI smoke (ISSUE 8 satellite): a REAL `session start
    --local-runners` subprocess, two bounded jobs submitted
    CONCURRENTLY via `python -m flink_tpu session submit`, both
    committed outputs verified independently, then `session stop` —
    every exit code asserted."""

    def _cli(self, env, *argv):
        p = subprocess.run([sys.executable, "-m", "flink_tpu", *argv],
                           env=env, capture_output=True, text=True,
                           cwd=REPO, timeout=120)
        out = p.stdout.strip().splitlines()
        return p.returncode, (json.loads(out[-1]) if out else {})

    def test_start_submit_concurrent_verify_stop(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + os.path.join(REPO, "tests")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        srv = subprocess.Popen(
            [sys.executable, "-m", "flink_tpu", "session", "start",
             "--local-runners", "1",
             "--conf", "heartbeat.interval=200ms"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        try:
            addr = json.loads(srv.stdout.readline())["session"]
            n = 5
            # submit both back-to-back: they run CONCURRENTLY on the
            # one local runner (runner-slots default 4)
            for tag in ("a", "b"):
                conf_args = []
                for k, v in _job_conf(tmp_path, tag, n).items():
                    conf_args += ["--conf", f"{k}={v}"]
                rc, out = self._cli(
                    env, "session", "submit", "--session", addr,
                    "--entry", "runner_job:build",
                    "--job-id", f"cli-{tag}", *conf_args)
                assert rc == 0 and out["admitted"], out
            deadline = time.time() + 120
            while time.time() < deadline:
                rc, out = self._cli(env, "session", "list",
                                    "--session", addr)
                assert rc == 0
                states = {j["job_id"]: j["state"] for j in out["jobs"]}
                assert "FAILED" not in states.values(), states
                if set(states.values()) == {"FINISHED"}:
                    break
                time.sleep(0.5)
            else:
                raise AssertionError(f"jobs never finished: {states}")
            _golden(str(tmp_path / "sink-a"), n)
            _golden(str(tmp_path / "sink-b"), n)
            rc, out = self._cli(env, "session", "stop",
                                "--session", addr)
            assert rc == 0 and out["ok"]
            assert srv.wait(timeout=30) == 0
        finally:
            if srv.poll() is None:
                srv.kill()


class TestMetricsIsolation:
    """ISSUE 8 satellite: per-job metrics isolation audit. Every
    job-facing metric registry/group is DRIVER-scoped — two concurrent
    jobs' snapshots are disjoint objects whose deterministic counters
    each match the single-job golden. The only module-level registries
    in the tree are process-PLANE observability (fault/recovery
    counters, per-topic log metrics), never job metrics; the
    structural audit below pins that allowlist so a shared counter
    cannot creep back in."""

    ALLOWED_MODULE_REGISTRIES = {
        # process-global by design: injections/recoveries are process
        # events (faults.py docstring), topic metrics are per-topic
        # groups and LOG_TOPIC_MULTI_WRITER forbids two jobs sharing a
        # topic writer; storage.enospc_retries (PR 14) counts a
        # PROCESS-level condition — the disk filling up is not
        # attributable to one tenant from inside the write seam
        "flink_tpu.faults",
        "flink_tpu.log.topic",
        "flink_tpu.fs",
        # cleaner metrics are per-topic groups like log.topic's, and
        # the fenced cleaner.lease means at most one cleaner service
        # maintains a topic at a time (PR 18) — process-plane, not
        # per-job
        "flink_tpu.log.cleaner",
    }

    def test_no_module_level_registry_outside_allowlist(self):
        import importlib
        import pkgutil

        import flink_tpu
        from flink_tpu.obs.metrics import MetricRegistry

        found = {}
        for m in pkgutil.walk_packages(flink_tpu.__path__, "flink_tpu."):
            if m.name.endswith("__main__"):
                continue  # importing it runs the CLI
            try:
                mod = importlib.import_module(m.name)
            except ImportError:
                continue  # optional-capability modules
            regs = [name for name, val in vars(mod).items()
                    if isinstance(val, MetricRegistry)]
            if regs:
                found[m.name] = regs
        stray = set(found) - self.ALLOWED_MODULE_REGISTRIES
        assert not stray, (
            f"module-level MetricRegistry outside the audited "
            f"allowlist: { {k: found[k] for k in stray} } — job metrics "
            "must live on the driver's own registry (per-job isolation)")

    DETERMINISTIC = ("records_in", "records_out", "batches",
                     "fired_windows")

    def _run_job(self, tag, results=None):
        import runner_job
        from flink_tpu.api.environment import StreamExecutionEnvironment

        conf = Configuration({
            "test.n-batches": 5,
            "test.sink-dir": str(self._tmp / f"ms-{tag}"),
            "state.num-key-shards": 8,
            "state.slots-per-shard": 16,
        })
        env = StreamExecutionEnvironment(conf)
        runner_job.build(env)
        res = env.execute(f"metrics-{tag}")
        snap = {k: res.metrics[k] for k in self.DETERMINISTIC}
        if results is not None:
            results[tag] = snap
        return snap

    def test_concurrent_jobs_snapshots_match_single_job_golden(
            self, tmp_path):
        self._tmp = tmp_path
        golden = self._run_job("golden")
        assert golden["records_in"] > 0
        results = {}
        ts = [threading.Thread(target=self._run_job, args=(t, results))
              for t in ("c1", "c2")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
        assert set(results) == {"c1", "c2"}
        # disjoint registries: neither job's counters absorbed the
        # other's records — each equals the single-job golden exactly
        assert results["c1"] == golden
        assert results["c2"] == golden
