"""Micro-benchmark suite smoke: every metric runs at toy size and
emits a parseable line (tier-7 analogue, SURVEY §5; BASELINE.md list)."""
import json

import pytest

import bench_micro


def test_bench_dcn_codec_axis_and_artifact(tmp_path):
    """The DCN micro-bench covers BOTH wire codecs and records the
    binary/legacy speedup as a machine-readable artifact line (ISSUE 12
    satellite: the >=5x claim is a recorded number, not a log grep)."""
    import json as _json

    art = tmp_path / "dcn.json"
    rows = bench_micro.bench_dcn(payloads=(0, 4096), procs=(2,),
                                 iters=2, artifact=str(art))
    metrics = {(r["metric"], r.get("codec")) for r in rows}
    for codec in ("legacy", "binary"):
        assert ("dcn_exchange_step_ms", codec) in metrics
        assert ("dcn_exchange_bytes_per_sec", codec) in metrics
    sp = [r for r in rows if r["metric"] == "dcn_codec_speedup"]
    assert sp and all(r["value"] > 0 for r in sp)
    persisted = _json.loads(art.read_text())
    assert persisted["lines"] == rows


def test_bench_dcn_q5_scaling_line_is_always_emitted(tmp_path):
    """dcn_q5_scaling either measures (enough cores) or SKIPs with the
    named hardware constraint — never silently absent (the ROADMAP
    item 2 acceptance line)."""
    import json as _json

    art = tmp_path / "q5.json"
    rows = bench_micro.bench_dcn_q5(n_batches=2, batch=512,
                                    artifact=str(art))
    (line,) = [r for r in rows if r["metric"] == "dcn_q5_scaling"]
    assert ("skipped" in line and "insufficient-cores" in line["skipped"]
            ) or "target_met" in line
    assert _json.loads(art.read_text())["lines"] == rows


def test_bench_columnar_axis_and_artifact(tmp_path):
    """The columnar codec axis (ISSUE 13 satellite) covers encode +
    decode across CRC impl x decode mode and records the
    zero-copy+native vs copy+zlib speedup with a target line at the
    1MB point — a recorded number, not a log grep."""
    import json as _json

    art = tmp_path / "columnar.json"
    rows = bench_micro.bench_columnar(sizes=(1 << 16, 1 << 20),
                                      artifact=str(art))
    metrics = {(r["metric"], r.get("crc"), r.get("mode"))
               for r in rows}
    for crc in ("zlib", "native"):
        if ("columnar_codec_skipped", None, None) in metrics \
                and crc == "native":
            continue  # honest constraint line instead (no compiler)
        assert ("columnar_encode_bytes_per_sec", crc, None) in metrics
        for mode in ("copy", "zero_copy"):
            assert ("columnar_decode_bytes_per_sec", crc,
                    mode) in metrics
    sp = [r for r in rows if r["metric"] == "columnar_decode_speedup"]
    if sp:  # present whenever the native cells ran
        assert all(r["value"] > 0 for r in sp)
        at_1mb = [r for r in sp if "target_met" in r]
        assert len(at_1mb) == 1, "exactly one target line (1MB)"
    persisted = _json.loads(art.read_text())
    assert persisted["lines"] == rows


def test_bench_control_probe_vs_piggyback_and_artifact(tmp_path):
    """The control-plane probe (ISSUE 15 satellite): per-wait cost of
    the is_ready spin vs the piggybacked announced-transfer consume,
    with the honest backend/core constraint recorded on every line and
    the speedup as a machine-readable artifact number."""
    import json as _json

    art = tmp_path / "control.json"
    rows = bench_micro.bench_control(iters=10, artifact=str(art))
    metrics = {r["metric"] for r in rows}
    assert {"control_wait_us_probe", "control_wait_us_piggyback",
            "control_readiness_speedup"} <= metrics
    for r in rows:
        assert "constraint" in r, r["metric"]
    persisted = _json.loads(art.read_text())
    assert persisted["lines"] == rows
    assert persisted["host_cores"] >= 1


@pytest.mark.shard_map
def test_all_micro_benchmarks_emit(capsys):
    bench_micro.bench_state_update(batch=1 << 12, iters=2)
    bench_micro.bench_all_to_all(iters=2)
    bench_micro.bench_codec(mb=1)
    bench_micro.bench_fire_flush(iters=2)
    bench_micro.bench_checkpoint()
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    metrics = {ln["metric"] for ln in lines}
    assert {"state_update_ops_per_sec", "keyby_exchange_gbps",
            "ingest_codec_mb_per_sec", "window_fire_flush_ms",
            "checkpoint_bytes_per_sec",
            "checkpoint_resume_ms"} <= metrics
    for ln in lines:
        assert "value" in ln and "unit" in ln
