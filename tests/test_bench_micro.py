"""Micro-benchmark suite smoke: every metric runs at toy size and
emits a parseable line (tier-7 analogue, SURVEY §5; BASELINE.md list)."""
import json

import pytest

import bench_micro


@pytest.mark.shard_map
def test_all_micro_benchmarks_emit(capsys):
    bench_micro.bench_state_update(batch=1 << 12, iters=2)
    bench_micro.bench_all_to_all(iters=2)
    bench_micro.bench_codec(mb=1)
    bench_micro.bench_fire_flush(iters=2)
    bench_micro.bench_checkpoint()
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    metrics = {ln["metric"] for ln in lines}
    assert {"state_update_ops_per_sec", "keyby_exchange_gbps",
            "ingest_codec_mb_per_sec", "window_fire_flush_ms",
            "checkpoint_bytes_per_sec",
            "checkpoint_resume_ms"} <= metrics
    for ln in lines:
        assert "value" in ln and "unit" in ln
