"""Plan-analyzer suite (flink_tpu/analysis/): one seeded-violation
pipeline per registered rule asserting the exact rule id + node fires,
clean-pipeline negatives, the driver's submit-time ``analysis.fail-on``
thresholds, the `flink_tpu analyze` CLI surface, and the DOGFOOD GATE —
the shipped tree and the golden pipelines must report zero findings,
so registry/config drift can never land silently (tier-1)."""
import json
import subprocess
import sys

import numpy as np
import pytest

from flink_tpu.analysis import AnalysisError, analyze_config
from flink_tpu.analysis.core import blocking, rule_catalog
from flink_tpu.api.datastream import DataStream
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import GlobalWindows, TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.graph.transformations import WindowAggregateTransformation
from flink_tpu.ops.aggregates import count
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = pytest.mark.analysis

WM = WatermarkStrategy.for_monotonous_timestamps


def gen(split, i):
    if i >= 2:
        return None
    return ({"word": np.arange(8, dtype=np.int64)},
            (np.arange(8, dtype=np.int64) + i * 8) * 100)


def make_env(extra=None):
    conf = {"state.num-key-shards": 8, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": 256}
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def clean_pipeline(extra=None):
    """The golden shape: watermarked bounded source, keyBy, bounded
    window, collect — nothing for any rule to say."""
    env = make_env(extra)
    (env.from_source(GeneratorSource(gen), WM())
        .key_by("word")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .collect())
    return env


# -- seeded violations: one builder per rule --------------------------------
# The coverage test parametrizes over rule_catalog(), so a rule added
# to the engine without a seeded-violation case here FAILS the suite.

SEEDS = {}


def seed(rule_id, node_name=None):
    def deco(fn):
        SEEDS[rule_id] = (fn, node_name)
        return fn
    return deco


@seed("EVENT_TIME_NO_WATERMARK", node_name="window_agg")
def _no_watermark(tmp_path):
    env = make_env()
    (env.from_source(GeneratorSource(gen))  # no WatermarkStrategy
        .key_by("word")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .collect())
    return env.analyze()


@seed("NON_TRANSACTIONAL_SINK", node_name="collect")
def _write_through_sink(tmp_path):
    env = clean_pipeline({"execution.checkpointing.interval": 500})
    return env.analyze()


@seed("UNBOUNDED_SOURCE_IN_BATCH", node_name="source")
def _unbounded_batch(tmp_path):
    # strict compilation rejects this plan outright — the analyzer's
    # non-strict lowering must still surface it as a structured finding
    env = make_env({"execution.runtime-mode": "batch"})
    (env.from_source(GeneratorSource(gen, is_bounded=False), WM())
        .key_by("word")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .collect())
    return env.analyze()


@seed("KEYED_OP_WITHOUT_KEYBY", node_name="rogue_window")
def _keyed_without_keyby(tmp_path):
    # the fluent API always inserts the keyBy exchange; build the
    # malformed graph the way a buggy planner/raw-transformation user
    # would — window fed directly by the source
    env = make_env()
    ds = env.from_source(GeneratorSource(gen), WM())
    t = WindowAggregateTransformation(
        "rogue_window", (ds.transform,),
        assigner=TumblingEventTimeWindows.of(1000), aggregate=count(),
        key_field="word")
    env._register(t)
    DataStream(env, t).collect()
    return env.analyze()


@seed("WINDOW_WITHOUT_FIRE_BOUND", node_name="window_agg")
def _global_window_no_trigger(tmp_path):
    env = make_env()
    (env.from_source(GeneratorSource(gen), WM())
        .key_by("word")
        .window(GlobalWindows.create())  # no .trigger(...)
        .count()
        .collect())
    return env.analyze()


@seed("LOG_TOPIC_MULTI_WRITER")
def _two_writers_one_topic(tmp_path):
    from flink_tpu.log.connectors import LogSink

    topic = str(tmp_path / "topic")
    env = make_env()
    ds = env.from_source(GeneratorSource(gen), WM())
    ds.add_sink(LogSink(topic), name="writer_a")
    ds.add_sink(LogSink(topic), name="writer_b")
    return env.analyze()


@seed("LOG_RETENTION_UNSAFE")
def _retention_below_checkpoint_interval(tmp_path):
    return analyze_config(Configuration({
        "execution.checkpointing.interval": 5000,
        "log.retention.ms": 100}))


@seed("CLEANER_DISABLED_WITH_RETENTION")
def _retention_with_no_executor(tmp_path):
    # a producing topic with a retention POLICY but no EXECUTOR: the
    # background cleaner is off and nothing else in the runtime
    # applies log.retention.* — the topic grows without bound while
    # its owner believes retention is active. Clean negatives in
    # TestCleanerDisabledWithRetention below.
    from flink_tpu.log.connectors import LogSink

    topic = str(tmp_path / "topic")
    env = make_env({"log.retention.ms": 60_000})
    ds = env.from_source(GeneratorSource(gen), WM())
    ds.add_sink(LogSink(topic), name="writer")
    return env.analyze()


@seed("LOG_PREFETCH_INVALID")
def _log_prefetch_invalid(tmp_path):
    return analyze_config(Configuration({
        "log.prefetch-segments": -1}))


@seed("FAULT_POINT_UNKNOWN")
def _fault_point_unknown(tmp_path):
    env = clean_pipeline({"faults.inject": "bogus.point=raise @1.0"})
    return env.analyze()


@seed("CONFIG_KEY_UNKNOWN")
def _config_key_typo(tmp_path):
    env = clean_pipeline({"execution.checkpointng.interval": 500})
    return env.analyze()


@seed("SESSION_QUOTA_INVALID")
def _session_quota_invalid(tmp_path):
    # a per-job slot quota above one runner's capacity: no fleet of any
    # size could place the job — the dispatcher rejects the submission
    # and the analyzer flags the conf before it is ever submitted
    env = clean_pipeline({"session.slots-per-job": 3,
                          "session.runner-slots": 2})
    return env.analyze()


@seed("SESSION_HA_UNSAFE")
def _session_checkpointing_without_ha(tmp_path):
    # a session cluster running checkpointing jobs with no
    # high-availability.dir: one dispatcher SIGKILL strands every
    # tenant even though their checkpoints would survive it. Clean
    # negatives: no session intent (plain checkpointing config) and a
    # session conf WITH an HA dir — both below.
    return analyze_config(Configuration({
        "session.max-jobs": 4,
        "execution.checkpointing.interval": 500}))


@seed("STORAGE_LOCAL_LOCKS_ON_REMOTE")
def _local_locks_on_remote_scheme(tmp_path):
    # lease dirs / HA dir / log topics on a non-file scheme: the
    # O_EXCL + rename-first lock discipline is local-fs-only (PR 9/11
    # honest residue) — acquisition degrades to read-check-write.
    # Clean negatives in TestStorageLocalLocksOnRemote below.
    return analyze_config(Configuration({
        "high-availability.dir": "s3://bucket/ha",
        "log.dir": "hdfs://nn/flink-log"}))


@seed("HOST_PARALLELISM_INVALID")
def _host_parallelism_invalid(tmp_path):
    # below 1: the driver rejects it at build; the analyzer must flag
    # it at submit (oversubscription past os.cpu_count() warns too,
    # but is machine-dependent — the < 1 case seeds deterministically)
    env = clean_pipeline({"host.parallelism": 0})
    return env.analyze()


@seed("SUBBATCH_INVALID")
def _subbatch_indivisible(tmp_path):
    # 3 does not divide the configured microbatch size (256); the
    # emit-defer-floor arm (explicit defer >= 100ms at K > 1) fires on
    # the same rule and is covered in tests/test_subbatch.py
    return analyze_config(Configuration({
        "pipeline.microbatch-size": 256,
        "pipeline.sub-batches": 3}))


@seed("FIRE_GATE_INVALID")
def _fire_gate_off_under_subbatching(tmp_path):
    # gating forced off under the config that needs it: K sub-batch
    # dispatches per logical batch each pay the full fire/top-n select
    # sort (the §8.6 tax)
    return analyze_config(Configuration({
        "pipeline.fire-gate": False,
        "pipeline.sub-batches": 4}))


@seed("READINESS_INVALID")
def _readiness_unknown_mode(tmp_path):
    # build-rejected config (Driver._build_ops ValueError) must block
    # at submit under the default fail-on=error — hence error severity,
    # unlike FIRE_GATE_INVALID's legitimate-A/B warn
    return analyze_config(Configuration({
        "pipeline.readiness": "telepathy"}))


@seed("DCN_OVERLAP_UNSAFE")
def _dcn_overlap_without_drain(tmp_path):
    # the loss-tolerant perf trade made silently: overlapped cross-host
    # exchange + checkpointing with the barrier drain off — a restore
    # would skip the one in-flight step's records. Clean negatives in
    # TestDcnOverlapUnsafeNegatives below.
    return analyze_config(Configuration({
        "cluster.num-processes": 2,
        "execution.checkpointing.interval": 500,
        "cluster.dcn-overlap-drain": False}))


@seed("CHECKPOINT_IN_BATCH")
def _checkpoint_in_batch(tmp_path):
    # config-only rule: no pipeline needed
    return analyze_config(Configuration({
        "execution.runtime-mode": "batch",
        "execution.checkpointing.interval": 500}))


@seed("RESCALE_INVALID")
def _reactive_rescale_without_checkpointing(tmp_path):
    # reactive mode with no checkpoint interval: every controller-armed
    # rescale's stop-with-savepoint would be rejected — arm/disarm loop
    return analyze_config(Configuration({"rescale.mode": "reactive"}))


@seed("RESCALE_COOLDOWN_THRASH")
def _rescale_cooldown_below_checkpoint_interval(tmp_path):
    return analyze_config(Configuration({
        "rescale.mode": "reactive",
        "execution.checkpointing.interval": "30s",
        "rescale.cooldown": "5s"}))


@seed("STATE_BUDGET_INVALID")
def _lsm_budget_below_run_floor(tmp_path):
    # budget below the run floor: every absorb seals a degenerate run
    return analyze_config(Configuration({
        "state.backend": "lsm",
        "state.memory-budget-bytes": 4096}))


@seed("STATE_BUDGET_IGNORED")
def _budget_set_on_resident_backend(tmp_path):
    # hbm/spill ignore the budget key — the bound does not exist
    return analyze_config(Configuration({
        "state.backend": "spill",
        "state.memory-budget-bytes": 1 << 20}))


# -- dataflow-plane seeds (the propagated lattices; full coverage and
# clean negatives live in tests/test_dataflow.py) ---------------------------

@seed("FIELD_NOT_IN_SCHEMA", node_name="window_agg")
def _keyby_on_dropped_field(tmp_path):
    # schema lattice: the map renames the key column away; the keyBy's
    # field reference is checked against the PROPAGATED schema
    env = make_env()
    (env.from_source(GeneratorSource(gen, schema={"word": "int64"}), WM())
        .map(lambda d: {"renamed": d["word"]}, name="drop_word")
        .key_by("word")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .collect())
    return env.analyze()


@seed("SCHEMA_MISMATCH_UNION", node_name="union")
def _union_of_different_schemas(tmp_path):
    env = make_env()
    a = env.from_collection({"k": np.array([1], np.int64)},
                            np.array([100], np.int64))
    b = env.from_collection({"other": np.array([2], np.int64)},
                            np.array([200], np.int64))
    a.union(b).collect()
    return env.analyze()


@seed("UNBOUNDED_STATE_GROWTH", node_name="window_agg")
def _global_window_nonpurging_trigger(tmp_path):
    # state lattice: GlobalWindows element buffer + non-purging
    # CountTrigger + no evictor, fed by an UNBOUNDED source
    from flink_tpu.api.windowing import CountTrigger

    env = make_env()
    (env.from_source(GeneratorSource(gen, is_bounded=False), WM())
        .key_by("word")
        .window(GlobalWindows.create())
        .trigger(CountTrigger.of(3))
        .count()
        .collect())
    return env.analyze()


@seed("STALLED_WATERMARK_LEG", node_name="window_agg")
def _event_time_window_fed_by_count_window(tmp_path):
    # watermark lattice: count-window fires carry no event time; the
    # downstream event-time window's panes can never be crossed
    env = make_env()
    (env.from_source(GeneratorSource(gen, schema={"word": "int64"}), WM())
        .key_by("word")
        .count_window(3)
        .count()
        .key_by("key")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .collect())
    return env.analyze()


@seed("NON_TXN_SINK_IN_CHAIN", node_name="collect")
def _log_chain_into_write_through_sink(tmp_path):
    # exactly-once taint through log topics: LogSource → CollectSink
    # under checkpointing escalates the generic sink warning to error
    from flink_tpu.log.connectors import LogSource

    env = make_env({"execution.checkpointing.interval": 500})
    (env.from_source(LogSource(str(tmp_path / "topic")), WM())
        .collect())
    return env.analyze()


@seed("STATE_BYTES_EXCEEDED", node_name="window_agg")
def _state_bytes_over_budget(tmp_path):
    # the --explain estimate as an admission check: a tiny per-key
    # budget trips on the clean pipeline's window geometry
    env = clean_pipeline({"analysis.max-state-bytes-per-key": 4})
    return env.analyze()


@seed("CHANGELOG_SINK_MISMATCH", node_name="collect")
def _changelog_into_write_through_sink(tmp_path):
    # op-typed retract rows (-U/+U) into a blind-append sink: every
    # retraction materializes as a duplicate record instead of a
    # deletion — the changelog contract needs an op-aware sink
    env = make_env()
    (env.from_source(GeneratorSource(gen), WM())
        .key_by("word")
        .running_aggregate(count(), retract=True)
        .collect())
    return env.analyze()


class TestChangelogSinkMismatchNegatives:
    """CHANGELOG_SINK_MISMATCH fires ONLY on op-typed rows meeting a
    changelog-blind sink: each changelog-capable sink, and the
    insert-only (non-retract) aggregate, keep it quiet (seeded
    violation in SEEDS above)."""

    def _hits(self, sink=None, retract=True):
        env = make_env()
        stream = (env.from_source(GeneratorSource(gen), WM())
                  .key_by("word")
                  .running_aggregate(count(), retract=retract))
        if sink is None:
            stream.collect()
        else:
            stream.add_sink(sink)
        return [f for f in env.analyze()
                if f.rule == "CHANGELOG_SINK_MISMATCH"]

    def test_retract_sink_is_clean(self):
        from flink_tpu.api.sinks import RetractSink

        assert self._hits(RetractSink(key_fields=("key",))) == []

    def test_upsert_sink_is_clean(self):
        from flink_tpu.api.sinks import UpsertSink

        assert self._hits(UpsertSink(key_fields=("key",))) == []

    def test_insert_only_aggregate_into_collect_is_clean(self):
        # upsert-shaped rows without the op lane: CollectSink sees
        # plain rows, nothing to mismatch
        assert self._hits(sink=None, retract=False) == []


class TestSessionHaUnsafeNegatives:
    """SESSION_HA_UNSAFE fires ONLY on the stranding shape: session
    intent + checkpointing + no HA dir. Each leg missing keeps it
    quiet (seeded violation in SEEDS above)."""

    def _hits(self, conf):
        return [f for f in analyze_config(Configuration(conf))
                if f.rule == "SESSION_HA_UNSAFE"]

    def test_checkpointing_without_session_intent_is_clean(self):
        assert self._hits(
            {"execution.checkpointing.interval": 500}) == []

    def test_session_without_checkpointing_is_clean(self):
        # nothing durable to strand: re-submission IS recovery
        assert self._hits({"session.max-jobs": 4}) == []

    def test_session_with_ha_dir_is_clean(self, tmp_path):
        assert self._hits({
            "session.max-jobs": 4,
            "execution.checkpointing.interval": 500,
            "high-availability.dir": str(tmp_path)}) == []


class TestDcnOverlapUnsafeNegatives:
    """DCN_OVERLAP_UNSAFE fires ONLY on the losing shape: cross-host +
    checkpointing + overlap on + drain off. Each leg missing keeps it
    quiet (seeded violation in SEEDS above)."""

    def _hits(self, conf):
        return [f for f in analyze_config(Configuration(conf))
                if f.rule == "DCN_OVERLAP_UNSAFE"]

    def test_default_drain_is_clean(self):
        assert self._hits({
            "cluster.num-processes": 2,
            "execution.checkpointing.interval": 500}) == []

    def test_single_process_is_clean(self):
        assert self._hits({
            "execution.checkpointing.interval": 500,
            "cluster.dcn-overlap-drain": False}) == []

    def test_no_checkpointing_is_clean(self):
        assert self._hits({
            "cluster.num-processes": 2,
            "cluster.dcn-overlap-drain": False}) == []

    def test_lockstep_loop_is_clean(self):
        assert self._hits({
            "cluster.num-processes": 2,
            "execution.checkpointing.interval": 500,
            "cluster.dcn-overlap": False,
            "cluster.dcn-overlap-drain": False}) == []


class TestRuleCatalog:
    def test_catalog_has_at_least_eight_rules(self):
        assert len(rule_catalog()) >= 8

    def test_dataflow_plane_has_at_least_six_rules(self):
        from flink_tpu.analysis.core import rule_catalog_full

        planes = [r.plane for r in rule_catalog_full()]
        assert planes.count("dataflow") >= 6
        for r in rule_catalog_full():
            assert r.description, f"{r.rule_id} has no description"
            assert r.fix, f"{r.rule_id} has no catalog fix hint"

    def test_finding_sort_puts_config_findings_after_node_zero(self):
        # regression: the old key `f.node or 0` conflated node 0 with
        # config-level findings (node=None) — None must sort LAST
        from flink_tpu.analysis.core import Finding, finding_sort_key

        at_node0 = Finding(rule="R", severity="warn", message="n0",
                           node=0)
        at_config = Finding(rule="R", severity="warn", message="conf")
        ordered = sorted([at_config, at_node0], key=finding_sort_key)
        assert ordered == [at_node0, at_config]

    @pytest.mark.parametrize("rule_id,severity",
                             rule_catalog(),
                             ids=[r for r, _ in rule_catalog()])
    def test_every_rule_fires_on_its_seeded_violation(
            self, rule_id, severity, tmp_path):
        assert rule_id in SEEDS, (
            f"rule {rule_id} has no seeded-violation case — every rule "
            "in the catalog must prove it fires")
        builder, node_name = SEEDS[rule_id]
        findings = builder(tmp_path)
        hits = [f for f in findings if f.rule == rule_id]
        assert hits, (f"{rule_id} did not fire; findings: "
                      f"{[f.rule for f in findings]}")
        for f in hits:
            assert f.severity == severity
            assert f.fix, f"{rule_id} finding has no fix hint"
        if node_name is not None:
            assert any(f.node_name == node_name for f in hits), (
                f"{rule_id} did not locate node {node_name!r}: "
                f"{[(f.node, f.node_name) for f in hits]}")

    def test_clean_pipeline_zero_findings(self):
        assert clean_pipeline().analyze() == []

    def test_clean_batch_pipeline_zero_findings(self):
        assert clean_pipeline(
            {"execution.runtime-mode": "batch"}).analyze() == []


class TestLeaseAwareMultiWriter:
    """ISSUE 9: LOG_TOPIC_MULTI_WRITER is lease-aware — two LogSinks
    on one topic with DISJOINT leased partitions are legal; the same
    partition without (or with an overlapping) lease still errors."""

    def _two_sinks(self, tmp_path, owned_a, owned_b):
        from flink_tpu.log.connectors import LogSink

        topic = str(tmp_path / "topic")
        env = make_env()
        ds = env.from_source(GeneratorSource(gen), WM())
        ds.add_sink(LogSink(topic, key_field="word", partitions=2,
                            owned_partitions=owned_a,
                            producer_id="prod-a"), name="writer_a")
        ds.add_sink(LogSink(topic, key_field="word", partitions=2,
                            owned_partitions=owned_b,
                            producer_id="prod-b"), name="writer_b")
        return [f for f in env.analyze()
                if f.rule == "LOG_TOPIC_MULTI_WRITER"]

    def test_disjoint_leased_partitions_are_legal(self, tmp_path):
        assert self._two_sinks(tmp_path, [0], [1]) == []

    def test_overlapping_leases_error_at_analyze(self, tmp_path):
        # leases acquire LAZILY (first use), so building the plan does
        # not raise — the analyzer flags the overlap BEFORE the runtime
        # fence would depose one of the writers mid-run
        hits = self._two_sinks(tmp_path, [0, 1], [0])
        assert len(hits) == 2
        assert "overlap" in hits[0].message

    def test_overlap_on_disk_is_flagged(self, tmp_path):
        # build the overlapping plan the way a deposed/raced pair would
        # look: construct the sinks against separate lease state, then
        # overlap their owned sets in one plan
        from flink_tpu.log.connectors import LogSink

        topic = str(tmp_path / "topic")
        env = make_env()
        ds = env.from_source(GeneratorSource(gen), WM())
        a = LogSink(topic, key_field="word", partitions=2,
                    owned_partitions=[0], producer_id="prod-a")
        b = LogSink(topic, key_field="word", partitions=2,
                    owned_partitions=[1], producer_id="prod-b")
        b._appender.owned = [0, 1]  # the raced/overlapped shape
        ds.add_sink(a, name="writer_a")
        ds.add_sink(b, name="writer_b")
        hits = [f for f in env.analyze()
                if f.rule == "LOG_TOPIC_MULTI_WRITER"]
        assert len(hits) == 2
        assert "overlap" in hits[0].message


class TestSubmitTimeAnalysis:
    """The driver runs the same rules at submit; ``analysis.fail-on``
    picks the blocking severity."""

    def test_error_finding_blocks_submit(self):
        env = clean_pipeline({"faults.inject": "bogus.point=raise"})
        with pytest.raises(AnalysisError) as ei:
            env.execute("blocked")
        assert any(f.rule == "FAULT_POINT_UNKNOWN"
                   for f in ei.value.findings)
        assert "analysis.fail-on" in str(ei.value)

    def test_fail_on_off_skips_analysis(self):
        env = clean_pipeline({"faults.inject": "bogus.point=raise",
                              "analysis.fail-on": "off"})
        r = env.execute("unblocked")
        assert r.metrics.get("records_in") == 16

    def test_warn_threshold_blocks_warn_findings(self):
        env = clean_pipeline({"no.such.key": 1,
                              "analysis.fail-on": "warn"})
        with pytest.raises(AnalysisError) as ei:
            env.execute("blocked")
        assert any(f.rule == "CONFIG_KEY_UNKNOWN"
                   for f in ei.value.findings)

    def test_warn_findings_pass_default_threshold_but_stay_visible(self):
        env = clean_pipeline({"no.such.key": 1})
        r = env.execute("warned")
        assert r.metrics.get("records_in") == 16
        assert any(f.rule == "CONFIG_KEY_UNKNOWN"
                   for f in env._driver.analysis_findings)

    def test_bad_fail_on_value_rejected(self):
        with pytest.raises(ValueError, match="fail-on"):
            blocking([], "sometimes")


class TestAnalyzeCli:
    def test_conf_file_violations_exit_1_with_json_findings(
            self, tmp_path, capsys):
        from flink_tpu.cli import main

        conf = tmp_path / "job.conf"
        conf.write_text("faults.inject: bogus.point=raise\n"
                        "execution.checkpointng.interval: 500\n")
        rc = main(["analyze", str(conf), "--json"])
        assert rc == 1
        rules = {json.loads(line)["rule"]
                 for line in capsys.readouterr().out.splitlines()}
        assert rules == {"FAULT_POINT_UNKNOWN", "CONFIG_KEY_UNKNOWN"}

    def test_clean_conf_exits_0(self, tmp_path, capsys):
        from flink_tpu.cli import main

        conf = tmp_path / "job.conf"
        conf.write_text("execution.checkpointing.interval: 500\n")
        assert main(["analyze", str(conf)]) == 0
        assert "no findings" in capsys.readouterr().out

    def test_fail_on_flag_overrides_conf(self, tmp_path, capsys):
        from flink_tpu.cli import main

        conf = tmp_path / "job.conf"
        conf.write_text("some.typo.key: 1\n")
        assert main(["analyze", str(conf)]) == 0  # warn < error
        assert main(["analyze", str(conf), "--fail-on", "warn"]) == 1
        capsys.readouterr()

    def test_golden_wordcount_entry_zero_findings(self, tmp_path, capsys):
        """Dogfood: the shipped golden pipeline (the batch-mode CLI
        smoke entry point) analyzes clean, plan rules included."""
        from flink_tpu.cli import main

        rc = main(["analyze", "--entry", "runner_job_wordcount:build",
                   "--conf", f"test.sink-dir={tmp_path / 'out'}"])
        assert rc == 0
        assert "no findings" in capsys.readouterr().out


class TestDogfoodGate:
    """Zero findings on the shipped tree — registry/config drift can
    never land silently again."""

    def test_repo_lints_zero_findings(self):
        from flink_tpu.analysis.pylints import lint_paths

        findings = lint_paths()
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_lint_cli_smoke(self):
        """`python -m flink_tpu lint` from a cold process — the tier-1
        wrapper's drift gate, exit status included."""
        proc = subprocess.run(
            [sys.executable, "-m", "flink_tpu", "lint"],
            capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "no findings" in proc.stdout

    def test_full_pass_fits_the_wallclock_budget(self):
        """PR 19 perf gate: the WHOLE default lint pass — call-graph
        index plus every interprocedural plane (taint, pool writes,
        lock order, fences, unfired registry) — stays under 3 s, so
        the dogfood gate remains cheap enough to run on every commit.
        The call-graph architecture this budget bought: one flattened
        ast.walk per module at index time, type-bucketed call/with
        views, and per-module prefilters on the lock-order walk."""
        import time

        from flink_tpu.analysis.pylints import lint_paths

        t0 = time.perf_counter()
        lint_paths()
        elapsed = time.perf_counter() - t0
        assert elapsed < 3.0, (
            f"full lint pass took {elapsed:.2f}s (budget 3.0s) — the "
            "interprocedural planes must stay commit-hook cheap")

    def test_rules_md_is_current(self):
        """RULES.md staleness gate: the committed catalog doc must be
        byte-identical to what the registrations render — a new rule
        (analysis plane OR pylint plane) cannot ship undocumented; run
        `python tools/gen_rules.py` after editing rules."""
        import os

        from flink_tpu.analysis.docs import render_rules_md
        from flink_tpu.analysis.pylints import repo_root

        path = os.path.join(repo_root(), "RULES.md")
        with open(path, "r", encoding="utf-8") as f:
            committed = f.read()
        assert committed == render_rules_md(), (
            "RULES.md is stale — regenerate with "
            "`python tools/gen_rules.py`")


class TestStorageLocalLocksOnRemote:
    """PR-14 satellite: STORAGE_LOCAL_LOCKS_ON_REMOTE clean negatives
    (the seeded violation lives in SEEDS)."""

    def _rules(self, conf):
        return [f.rule for f in analyze_config(Configuration(conf))]

    def test_local_paths_are_quiet(self, tmp_path):
        assert "STORAGE_LOCAL_LOCKS_ON_REMOTE" not in self._rules({
            "high-availability.dir": str(tmp_path / "ha"),
            "log.dir": str(tmp_path / "log")})

    def test_explicit_file_scheme_is_quiet(self, tmp_path):
        assert "STORAGE_LOCAL_LOCKS_ON_REMOTE" not in self._rules({
            "high-availability.dir": f"file://{tmp_path}/ha",
            "log.dir": f"file://{tmp_path}/log"})

    def test_unset_dirs_are_quiet(self):
        assert "STORAGE_LOCAL_LOCKS_ON_REMOTE" not in self._rules({})

    def test_each_key_flags_independently(self, tmp_path):
        findings = [f for f in analyze_config(Configuration({
            "high-availability.dir": "s3://bucket/ha",
            "log.dir": str(tmp_path / "log")}))
            if f.rule == "STORAGE_LOCAL_LOCKS_ON_REMOTE"]
        assert len(findings) == 1
        assert "high-availability.dir" in findings[0].message

    def test_conditional_put_scheme_is_quiet(self):
        """PR-18 driver-awareness: a scheme whose registered driver
        advertises conditional_put (the objstore CAS driver) ports
        every lock-dependent path onto compare-and-swap — the race
        the rule warns about is PREVENTED there, not bounded."""
        assert "STORAGE_LOCAL_LOCKS_ON_REMOTE" not in self._rules({
            "high-availability.dir": "objstore://ha",
            "log.dir": "objstore://flink-log"})

    def test_non_cas_remote_still_flags(self):
        rules = self._rules({"log.dir": "hdfs://nn/flink-log"})
        assert "STORAGE_LOCAL_LOCKS_ON_REMOTE" in rules


class TestCleanerDisabledWithRetention:
    """PR-18 satellite: CLEANER_DISABLED_WITH_RETENTION clean
    negatives (the seeded violation lives in SEEDS)."""

    def _analyze(self, conf, with_sink=True):
        env = make_env(conf)
        ds = env.from_source(GeneratorSource(gen), WM())
        if with_sink:
            from flink_tpu.log.connectors import LogSink

            ds.add_sink(LogSink(str(env.config.get_raw(
                "test.topic", "/tmp/_t"))), name="writer")
        else:
            ds.collect()
        return [f.rule for f in env.analyze()]

    def test_cleaner_enabled_is_quiet(self, tmp_path):
        assert "CLEANER_DISABLED_WITH_RETENTION" not in self._analyze({
            "test.topic": str(tmp_path / "t"),
            "log.retention.ms": 60_000,
            "log.cleaner.enabled": True})

    def test_no_retention_is_quiet(self, tmp_path):
        assert "CLEANER_DISABLED_WITH_RETENTION" not in self._analyze({
            "test.topic": str(tmp_path / "t")})

    def test_consume_only_plan_is_quiet(self):
        """No LogSink in the plan: the consumer inherits the
        producer's maintenance regime — nothing to warn."""
        assert "CLEANER_DISABLED_WITH_RETENTION" not in self._analyze(
            {"log.retention.ms": 60_000}, with_sink=False)

    def test_bytes_retention_alone_fires(self, tmp_path):
        rules = self._analyze({"test.topic": str(tmp_path / "t"),
                               "log.retention.bytes": 1_000_000})
        assert "CLEANER_DISABLED_WITH_RETENTION" in rules


class TestRescaleRule:
    """ISSUE 16: RESCALE_INVALID / RESCALE_COOLDOWN_THRASH — the
    rescale.* grammar's unsatisfiable shapes error at submit, the
    thrash-but-legal shapes warn, and legal configs stay silent."""

    def _rules(self, conf):
        return [(f.rule, f.severity) for f in analyze_config(
            Configuration(conf))
            if f.rule.startswith("RESCALE")]

    def test_reactive_without_checkpointing_errors(self):
        assert ("RESCALE_INVALID", "error") in self._rules(
            {"rescale.mode": "reactive"})

    def test_unknown_mode_errors(self):
        assert ("RESCALE_INVALID", "error") in self._rules(
            {"rescale.mode": "adaptive"})

    def test_inverted_pressure_band_errors(self):
        assert ("RESCALE_INVALID", "error") in self._rules(
            {"rescale.mode": "reactive",
             "execution.checkpointing.interval": "1s",
             "rescale.target-pressure-high": 30,
             "rescale.target-pressure-low": 40})

    def test_bounds_violating_key_group_discipline_error(self):
        # 8 shards / 1 process = share 8; min-devices 3 divides nothing
        assert ("RESCALE_INVALID", "error") in self._rules(
            {"rescale.mode": "reactive",
             "execution.checkpointing.interval": "1s",
             "state.num-key-shards": "8",
             "rescale.min-devices": 3})

    def test_empty_width_range_errors(self):
        assert ("RESCALE_INVALID", "error") in self._rules(
            {"rescale.mode": "reactive",
             "execution.checkpointing.interval": "1s",
             "rescale.min-devices": 4,
             "rescale.max-devices": 2})

    def test_cooldown_below_checkpoint_interval_warns(self):
        rules = self._rules({
            "rescale.mode": "reactive",
            "execution.checkpointing.interval": "30s",
            "rescale.cooldown": "5s"})
        assert ("RESCALE_COOLDOWN_THRASH", "warn") in rules
        assert ("RESCALE_INVALID", "error") not in rules

    def test_legal_reactive_config_is_silent(self):
        assert self._rules({
            "rescale.mode": "reactive",
            "execution.checkpointing.interval": "30s",
            "rescale.cooldown": "120s",
            "state.num-key-shards": "128",
            "rescale.min-devices": 2,
            "rescale.max-devices": 8}) == []

    def test_mode_off_never_fires_regardless_of_knobs(self):
        # manual-only mode: the controller never reads the band/bounds,
        # so even a nonsense band must not block a manual-rescale user
        assert self._rules({
            "rescale.target-pressure-high": 10,
            "rescale.target-pressure-low": 90,
            "rescale.cooldown": "0ms"}) == []


class TestStateBudgetRule:
    """ISSUE 17: STATE_BUDGET_INVALID / STATE_BUDGET_IGNORED — the
    state.* backend grammar's can-never-work shapes error at submit,
    the does-nothing shape warns, and legal configs stay silent."""

    def _rules(self, conf):
        return [(f.rule, f.severity) for f in analyze_config(
            Configuration(conf))
            if f.rule.startswith("STATE_BUDGET")]

    def test_unknown_backend_errors(self):
        assert ("STATE_BUDGET_INVALID", "error") in self._rules(
            {"state.backend": "rocksdb"})

    def test_lsm_budget_below_run_floor_errors(self):
        # default floor is 64 KiB; a 4 KiB budget seals per batch
        assert ("STATE_BUDGET_INVALID", "error") in self._rules(
            {"state.backend": "lsm",
             "state.memory-budget-bytes": 4096})

    def test_unparseable_budget_errors(self):
        assert ("STATE_BUDGET_INVALID", "error") in self._rules(
            {"state.backend": "lsm",
             "state.memory-budget-bytes": "lots"})

    def test_compact_min_runs_below_two_errors(self):
        assert ("STATE_BUDGET_INVALID", "error") in self._rules(
            {"state.backend": "lsm",
             "state.lsm.compact-min-runs": 1})

    def test_budget_on_resident_backend_warns_not_errors(self):
        rules = self._rules({"state.backend": "spill",
                             "state.memory-budget-bytes": 1 << 20})
        assert ("STATE_BUDGET_IGNORED", "warn") in rules
        assert ("STATE_BUDGET_INVALID", "error") not in rules

    def test_lowered_floor_makes_tiny_budget_legal(self):
        # the crash-test shape: tiny runs on purpose, floor lowered to
        # match — self-consistent, must stay silent
        assert self._rules({
            "state.backend": "lsm",
            "state.memory-budget-bytes": 4096,
            "state.lsm.run-floor-bytes": 4096}) == []

    def test_legal_lsm_config_is_silent(self):
        assert self._rules({
            "state.backend": "lsm",
            "state.memory-budget-bytes": 64 << 20,
            "state.lsm.compact-min-runs": 4}) == []

    def test_default_config_is_silent(self):
        assert self._rules({}) == []

    def test_budget_unset_on_resident_backend_is_silent(self):
        # hbm with no budget key: nothing to warn about
        assert self._rules({"state.backend": "hbm"}) == []
