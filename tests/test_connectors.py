"""Formats (csv/jsonlines) + file connectors: replayable FileSource,
exactly-once FileSink with rolling parts, end-to-end incl. crash/resume
(ref: flink-formats/* + flink-connector-files, SURVEY §3.9)."""
import os

import numpy as np
import pytest

from flink_tpu.config import Configuration
from flink_tpu.connectors import FileSink, FileSource
from flink_tpu.formats import CsvFormat, JsonLinesFormat


class TestCsvFormat:
    def test_i64_roundtrip_native(self):
        f = CsvFormat([("a", "i64"), ("b", "i64")])
        batch = {"a": np.array([1, -2, 3], np.int64),
                 "b": np.array([10, 20, 30], np.int64)}
        data = f.serialize(batch)
        back = f.deserialize(data)
        assert np.array_equal(back["a"], batch["a"])
        assert np.array_equal(back["b"], batch["b"])

    def test_f32_and_mixed(self):
        f = CsvFormat([("x", "f32"), ("y", "f32")])
        batch = {"x": np.array([1.5, 2.25], np.float32),
                 "y": np.array([-0.5, 3.0], np.float32)}
        back = f.deserialize(f.serialize(batch))
        assert np.allclose(back["x"], batch["x"])
        m = CsvFormat([("k", "i64"), ("name", "str"), ("v", "f32")])
        back = m.deserialize(b"7,alpha,1.5\n8,beta,2.5\n")
        assert back["k"].tolist() == [7, 8]
        assert back["name"].tolist() == ["alpha", "beta"]
        assert np.allclose(back["v"], [1.5, 2.5])

    def test_bad_type_rejected(self):
        with pytest.raises(ValueError, match="unknown column type"):
            CsvFormat([("a", "u8")])


class TestJsonLinesFormat:
    def test_roundtrip_and_missing_keys(self):
        f = JsonLinesFormat([("k", "i64"), ("v", "f32"), ("s", "str")])
        batch = {"k": np.array([1, 2], np.int64),
                 "v": np.array([0.5, 1.5], np.float32),
                 "s": np.array(["x", "y"], dtype=object)}
        back = f.deserialize(f.serialize(batch))
        assert back["k"].tolist() == [1, 2]
        assert back["s"].tolist() == ["x", "y"]
        sparse = f.deserialize(b'{"k": 9}\n')
        assert sparse["k"].tolist() == [9]
        assert sparse["v"].tolist() == [0.0]


class TestFileSource:
    def _write(self, path, rows):
        with open(path, "w") as f:
            for r in rows:
                f.write(",".join(str(x) for x in r) + "\n")

    def test_glob_splits_and_event_time(self, tmp_path):
        self._write(tmp_path / "a.csv", [(1, 100), (2, 200)])
        self._write(tmp_path / "b.csv", [(3, 300)])
        src = FileSource(str(tmp_path / "*.csv"),
                         CsvFormat([("k", "i64"), ("ts", "i64")]),
                         ts_field="ts")
        splits = src.splits()
        assert [os.path.basename(s) for s in splits] == ["a.csv", "b.csv"]
        batches = list(src.open_split(splits[0]))
        assert len(batches) == 1
        data, ts = batches[0]
        assert data["k"].tolist() == [1, 2]
        assert ts.tolist() == [100, 200]

    def test_replay_position_skips_consumed_batches(self, tmp_path):
        rows = [(i, i * 10) for i in range(10)]
        self._write(tmp_path / "x.csv", rows)
        src = FileSource(str(tmp_path / "x.csv"),
                         CsvFormat([("k", "i64"), ("ts", "i64")]),
                         ts_field="ts", batch_size=4)
        all_batches = list(src.open_split(str(tmp_path / "x.csv")))
        assert [len(t) for _, t in all_batches] == [4, 4, 2]
        resumed = list(src.open_split(str(tmp_path / "x.csv"), start_pos=2))
        assert len(resumed) == 1
        assert resumed[0][0]["k"].tolist() == [8, 9]

    def test_directory_source(self, tmp_path):
        d = tmp_path / "input"
        d.mkdir()
        self._write(d / "0001", [(5, 1)])
        src = FileSource(str(d), CsvFormat([("k", "i64"), ("ts", "i64")]))
        assert len(src.splits()) == 1


class TestFileSink:
    def test_rolling_parts_and_commit(self, tmp_path):
        f = CsvFormat([("k", "i64"), ("c", "i64")])
        sink = FileSink(str(tmp_path), f, rolling_records=2)
        sink.write({"k": np.arange(5, dtype=np.int64),
                    "c": np.arange(5, dtype=np.int64) * 10})
        sink.prepare_commit(1)
        staged = os.listdir(tmp_path / "staged")
        assert len(staged) == 3  # 2+2+1 rows
        assert os.listdir(tmp_path / "committed") == []
        sink.notify_checkpoint_complete(1)
        assert os.listdir(tmp_path / "staged") == []
        got = sink.committed_batches()
        ks = np.concatenate([b["k"] for b in got])
        assert sorted(ks.tolist()) == [0, 1, 2, 3, 4]

    def test_abort_discards_staged(self, tmp_path):
        f = CsvFormat([("k", "i64")])
        sink = FileSink(str(tmp_path), f)
        sink.write({"k": np.array([1, 2], np.int64)})
        sink.prepare_commit(1)
        sink.abort_uncommitted()
        assert os.listdir(tmp_path / "staged") == []
        sink.notify_checkpoint_complete(1)
        assert sink.committed_batches() == []

    def test_deposed_attempt_cannot_clobber_committed_part(self, tmp_path):
        """Attempt-epoch-qualified part names (the chk-<id>.e<epoch>
        fencing discipline): a deposed attempt restarting mid-commit
        renames to ITS epoch's name and the idempotence check sees the
        successor's committed copy — the committed part is never
        clobbered and readers resolve one (cid, part) to exactly one
        file (highest epoch)."""
        f = CsvFormat([("k", "i64")])
        deposed = FileSink(str(tmp_path), f)
        deposed.set_attempt_epoch(1)
        deposed.write({"k": np.array([1, 2], np.int64)})
        deposed.prepare_commit(1)  # staged under .e1, then the attempt
        # is deposed mid-commit; its successor re-stages and commits
        succ = FileSink(str(tmp_path), f)
        succ.set_attempt_epoch(2)
        succ.write({"k": np.array([1, 2], np.int64)})
        succ.prepare_commit(1)
        succ.notify_checkpoint_complete(1)
        committed = os.listdir(tmp_path / "committed")
        assert committed == ["part-0000000001-0000.e2"]
        # the deposed attempt wakes up and finishes ITS commit round
        deposed.notify_checkpoint_complete(1)
        assert os.listdir(tmp_path / "committed") == committed
        assert os.listdir(tmp_path / "staged") == []
        got = succ.committed_batches()
        assert len(got) == 1 and got[0]["k"].tolist() == [1, 2]

    def test_deposed_abort_cannot_delete_successor_staged(self, tmp_path):
        """Abort is epoch-fenced like the rename path: a deposed
        attempt's late cleanup skips staged parts a higher attempt
        epoch owns."""
        f = CsvFormat([("k", "i64")])
        deposed = FileSink(str(tmp_path), f)
        deposed.set_attempt_epoch(1)
        succ = FileSink(str(tmp_path), f)
        succ.set_attempt_epoch(2)
        succ.write({"k": np.array([7], np.int64)})
        succ.prepare_commit(3)
        deposed.abort_uncommitted()  # deposed failure path fires late
        assert os.listdir(tmp_path / "staged") == \
            ["part-0000000003-0000.e2"]
        succ.notify_checkpoint_complete(3)
        got = succ.committed_batches()
        assert len(got) == 1 and got[0]["k"].tolist() == [7]

    def test_epochless_legacy_part_names_still_read(self, tmp_path):
        f = CsvFormat([("k", "i64")])
        sink = FileSink(str(tmp_path), f)
        with open(tmp_path / "committed" / "part-0000000001-0000",
                  "w") as fh:
            fh.write("5\n")
        got = sink.committed_batches()
        assert len(got) == 1 and got[0]["k"].tolist() == [5]

    def test_snapshot_restore_reconstructs_staged(self, tmp_path):
        f = CsvFormat([("k", "i64")])
        sink = FileSink(str(tmp_path), f)
        sink.write({"k": np.array([7], np.int64)})
        sink.prepare_commit(3)
        snap = sink.snapshot_staged()
        sink.abort_uncommitted()  # crash cleanup deleted the files
        sink2 = FileSink(str(tmp_path), f)
        sink2.restore_staged(snap, 3)
        sink2.notify_checkpoint_complete(3)
        got = sink2.committed_batches()
        assert len(got) == 1 and got[0]["k"].tolist() == [7]


class TestEndToEnd:
    def test_csv_in_window_csv_out(self, tmp_path):
        rng = np.random.default_rng(0)
        n = 5000
        ts = np.sort(rng.integers(0, 10_000, n))
        keys = rng.integers(0, 8, n)
        inp = tmp_path / "in.csv"
        with open(inp, "w") as f:
            for k, t in zip(keys, ts):
                f.write(f"{k},{t}\n")

        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.windowing import TumblingEventTimeWindows
        from flink_tpu.time.watermarks import WatermarkStrategy

        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 8, "state.slots-per-shard": 16}))
        src = FileSource(str(inp), CsvFormat([("k", "i64"), ("ts", "i64")]),
                         ts_field="ts", batch_size=1000)
        out_fmt = CsvFormat([("key", "i64"), ("window_end", "i64"),
                             ("count", "i64")])
        sink = FileSink(str(tmp_path / "out"), out_fmt)
        (env.from_source(src, WatermarkStrategy.for_bounded_out_of_orderness(0))
         .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
         .add_sink(sink))
        env.execute("files")

        golden = {}
        for k, t in zip(keys, ts):
            golden[(int(k), (int(t) // 1000 + 1) * 1000)] = golden.get(
                (int(k), (int(t) // 1000 + 1) * 1000), 0) + 1
        got = {}
        for b in sink.committed_batches():
            for k, e, c in zip(b["key"], b["window_end"], b["count"]):
                got[(int(k), int(e))] = got.get((int(k), int(e)), 0) + int(c)
        assert got == golden

    def test_exactly_once_across_crash(self, tmp_path):
        """Flaky source + FileSink: after supervised recovery the
        committed files hold each window exactly once."""
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sources import GeneratorSource
        from flink_tpu.api.windowing import TumblingEventTimeWindows
        from flink_tpu.runtime.supervisor import run_with_recovery
        from flink_tpu.time.watermarks import WatermarkStrategy

        out_fmt = CsvFormat([("key", "i64"), ("window_end", "i64"),
                             ("count", "i64")])
        sink = FileSink(str(tmp_path / "out"), out_fmt)
        crashes = {"left": 1}

        def gen(split, i):
            if i >= 6:
                return None
            if i == 4 and crashes["left"] > 0:
                crashes["left"] -= 1
                raise RuntimeError("flaky")
            rng = np.random.default_rng(i)
            return ({"k": rng.integers(0, 4, 64).astype(np.int64)},
                    np.sort(rng.integers(i * 500, i * 500 + 900, 64)).astype(np.int64))

        conf = Configuration({
            "state.num-key-shards": 4, "state.slots-per-shard": 32,
            "pipeline.microbatch-size": 64,
            "execution.checkpointing.dir": str(tmp_path / "ckpt"),
            "execution.checkpointing.interval": 1,
            "restart-strategy.type": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 2,
            "restart-strategy.fixed-delay.delay": 1,
        })

        def build(c):
            env = StreamExecutionEnvironment(c)
            (env.from_source(
                GeneratorSource(gen),
                WatermarkStrategy.for_bounded_out_of_orderness(900))
             .key_by("k").window(TumblingEventTimeWindows.of(500)).count()
             .add_sink(sink))
            return env

        run_with_recovery(build, conf, "files-recovery")

        golden = {}
        for i in range(6):
            rng = np.random.default_rng(i)
            ks = rng.integers(0, 4, 64)
            tss = np.sort(rng.integers(i * 500, i * 500 + 900, 64))
            for k, t in zip(ks, tss):
                we = (int(t) // 500 + 1) * 500
                golden[(int(k), we)] = golden.get((int(k), we), 0) + 1
        got = {}
        for b in sink.committed_batches():
            for k, e, c in zip(b["key"], b["window_end"], b["count"]):
                key = (int(k), int(e))
                assert key not in got, f"duplicate window {key}"
                got[key] = int(c)
        assert got == golden
