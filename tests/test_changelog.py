"""The changelog/retraction plane end-to-end (ISSUE 20): op-typed rows
(records.OP_FIELD) emitted by retract-mode unwindowed aggregation and
session refires, folded by changelog-capable sinks, consumed by the
signed window lanes, and planned by the lifted SQL shapes (agg-over-join,
HAVING over an unwindowed aggregate).

The exactly-once half rides the chaos layer: a fault on
``changelog.retract.emit`` kills the job between a -U and its +U, and
run_with_recovery + RetractSink must still converge to the fault-free
table (the TwoPhaseCommit epoch discipline over retractions)."""
import contextlib
import sys

import numpy as np
import pytest

from flink_tpu import faults
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import FnSink, RetractSink, UpsertSink, rows_of
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.ops import aggregates
from flink_tpu.ops.session import SessionOperator
from flink_tpu.records import (
    OP_DELETE,
    OP_FIELD,
    OP_INSERT,
    OP_UPDATE_AFTER,
    OP_UPDATE_BEFORE,
)
from flink_tpu.runtime.supervisor import run_with_recovery
from flink_tpu.table.api import TableEnvironment
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = pytest.mark.changelog


def _env(extra=None):
    return StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8, "state.slots-per-shard": 64,
        "pipeline.microbatch-size": 100, **(extra or {})}))


def _data(n=600, nk=8, seed=11):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, nk, n).astype(np.int64),
            rng.random(n).astype(np.float32),
            np.arange(n, dtype=np.int64))


def _oracle(k, v):
    out = {}
    for kk, vv in zip(k, v):
        c, s = out.get(int(kk), (0, 0.0))
        out[int(kk)] = (c + 1, s + float(vv))
    return out


class TestOpTypedStream:
    """The raw changelog contract: every batch carries the op column,
    -U rows precede their +I/+U replacement, and folding the stream IN
    ORDER through a keyed table lands on the true finals."""

    def test_retract_stream_folds_to_oracle(self):
        env = _env()
        k, v, ts = _data()
        batches = []
        (env.from_collection({"k": k, "v": v}, ts, batch_size=100)
            .key_by("k")
            .running_aggregate(aggregates.multi(
                aggregates.count(), aggregates.sum_of("v")), retract=True)
            .add_sink(FnSink(batches.append)))
        env.execute("op-stream")

        table = {}
        seen_ops = set()
        for b in batches:
            assert OP_FIELD in b, "retract stream must carry the op lane"
            for row in rows_of(b):
                op = int(row[OP_FIELD])
                seen_ops.add(op)
                kk = int(row["key"])
                cur = (int(row["count"]), float(row["sum_v"]))
                if op == OP_UPDATE_BEFORE:
                    # a -U retracts EXACTLY the row that stands
                    prev = table.pop(kk)
                    assert prev[0] == cur[0]
                    assert prev[1] == pytest.approx(cur[1], rel=1e-3)
                elif op == OP_INSERT:
                    assert kk not in table  # first row for this key
                    table[kk] = cur
                elif op == OP_UPDATE_AFTER:
                    # its -U arrived earlier in the same ordered stream
                    assert kk not in table
                    table[kk] = cur
                else:
                    raise AssertionError(f"unexpected op {op}")
        assert {OP_INSERT, OP_UPDATE_BEFORE, OP_UPDATE_AFTER} <= seen_ops
        want = _oracle(k, v)
        assert set(table) == set(want)
        for kk in want:
            assert table[kk][0] == want[kk][0]
            assert table[kk][1] == pytest.approx(want[kk][1], rel=1e-3)


class TestChangelogWindowLanes:
    """Windowed aggregation OVER a changelog input: the signed lanes
    subtract -U/-D contributions instead of double-counting them."""

    def _stream(self, seed=7, n=400, nk=6):
        rng = np.random.default_rng(seed)
        k = rng.integers(0, nk, n).astype(np.int64)
        v = rng.random(n).astype(np.float32)
        # insert-biased op mix with genuine retractions in every window
        ops = rng.choice(
            np.array([OP_INSERT, OP_INSERT, OP_UPDATE_AFTER,
                      OP_UPDATE_BEFORE, OP_DELETE], np.int8), n)
        ts = np.sort(rng.integers(0, 2000, n)).astype(np.int64)
        return k, v, ops, ts

    def test_signed_lanes_match_oracle(self):
        k, v, ops, ts = self._stream()
        env = _env()
        rows = []
        (env.from_collection({"key": k, "v": v, OP_FIELD: ops}, ts,
                             batch_size=100)
            .key_by("key")
            .window(TumblingEventTimeWindows.of(500))
            .aggregate(aggregates.multi(
                aggregates.changelog_count("net"),
                aggregates.changelog_sum_of("v"),
                aggregates.changelog_avg_of("v")))
            .add_sink(FnSink(rows.append)))
        env.execute("changelog-windows")

        sign = np.where((ops == OP_UPDATE_BEFORE) | (ops == OP_DELETE),
                        -1.0, 1.0)
        want = {}
        for i in range(len(k)):
            key = (int(k[i]), int(ts[i]) // 500 * 500)
            c, s = want.get(key, (0.0, 0.0))
            want[key] = (c + sign[i], s + sign[i] * float(v[i]))

        got = {}
        for b in rows:
            for r in rows_of(b):
                got[(int(r["key"]), int(r["window_start"]))] = (
                    int(r["net"]), float(r["sum_v"]), float(r["avg_v"]))
        assert set(got) == set(want)
        for key, (c, s) in want.items():
            assert got[key][0] == int(round(c))
            assert got[key][1] == pytest.approx(s, abs=1e-3)
            # engine clamps the signed divisor at 1 (net-empty panes)
            assert got[key][2] == pytest.approx(
                s / max(round(c), 1.0), abs=1e-3)

    def test_order_sensitive_lanes_refuse_changelog(self):
        with pytest.raises(NotImplementedError, match="MAX"):
            aggregates.changelog_max_of("v")
        with pytest.raises(NotImplementedError, match="MIN"):
            aggregates.changelog_min_of("v")


class TestSessionRetractRefire:
    """A late event bridging into an already-fired session retracts the
    stale pane (-U with the OLD accumulators) before the merged session
    refires as +U — the session half of the changelog plane."""

    def test_merge_emits_minus_u_then_plus_u(self):
        op = SessionOperator(10, aggregates.sum_of("v"),
                             allowed_lateness_ms=1000, retract=True)
        op.process_batch(np.array([7, 7], np.int64),
                         np.array([0, 5], np.int64),
                         {"v": np.array([1.0, 2.0], np.float32)})
        assert op.take_fired() is None  # no merge yet → no retraction

        f1 = dict(op.advance_watermark(16))
        assert [int(x) for x in f1[OP_FIELD]] == [OP_INSERT]
        assert float(f1["sum_v"][0]) == pytest.approx(3.0)
        assert (int(f1["window_start"][0]), int(f1["window_end"][0])) \
            == (0, 15)

        # late-but-allowed event extends the fired span
        op.process_batch(np.array([7], np.int64), np.array([12], np.int64),
                         {"v": np.array([4.0], np.float32)})
        r = dict(op.take_fired())
        assert [int(x) for x in r[OP_FIELD]] == [OP_UPDATE_BEFORE]
        assert float(r["sum_v"][0]) == pytest.approx(3.0)  # the OLD row
        assert (int(r["window_start"][0]), int(r["window_end"][0])) \
            == (0, 15)

        f2 = dict(op.advance_watermark(40))
        assert [int(x) for x in f2[OP_FIELD]] == [OP_UPDATE_AFTER]
        assert float(f2["sum_v"][0]) == pytest.approx(7.0)
        assert (int(f2["window_start"][0]), int(f2["window_end"][0])) \
            == (0, 22)


# ---------------------------------------------------------------------------
# Exactly-once: RetractSink under a mid-retraction crash.
# ---------------------------------------------------------------------------

CHAOS_SEED = 4321
N_BATCHES, BATCH, NKEYS = 8, 64, 8


def _chaos_source():
    def gen(split, i):
        if i >= N_BATCHES:
            return None
        rng = np.random.default_rng(7000 + i)
        return ({"k": rng.integers(0, NKEYS, BATCH).astype(np.int64),
                 "v": rng.random(BATCH).astype(np.float32)},
                (i * BATCH + np.arange(BATCH)).astype(np.int64))
    return gen


def _chaos_oracle():
    ks, vs = [], []
    for i in range(N_BATCHES):
        rng = np.random.default_rng(7000 + i)
        ks.append(rng.integers(0, NKEYS, BATCH).astype(np.int64))
        vs.append(rng.random(BATCH).astype(np.float32))
    return _oracle(np.concatenate(ks), np.concatenate(vs))


@contextlib.contextmanager
def _replayable(plan):
    try:
        yield
    except BaseException:
        print(f"\nCHAOS REPLAY: seed={plan.seed} spec={plan.spec!r} "
              f"log={plan.log}", file=sys.stderr)
        raise


def _retract_job(conf, sink):
    env = StreamExecutionEnvironment(conf)
    (env.from_source(GeneratorSource(_chaos_source()),
                     WatermarkStrategy.for_monotonous_timestamps())
        .key_by("k")
        .running_aggregate(aggregates.multi(
            aggregates.count(), aggregates.sum_of("v")), retract=True)
        .add_sink(sink))
    return env


def _check_view(sink):
    want = _chaos_oracle()
    got = {int(r["key"]): (int(r["count"]), float(r["sum_v"]))
           for r in sink.view()}
    assert set(got) == set(want)
    for kk in want:
        assert got[kk][0] == want[kk][0], kk
        assert got[kk][1] == pytest.approx(want[kk][1], rel=1e-3)


@pytest.mark.chaos
class TestRetractSinkExactlyOnce:
    def _conf(self, tmp_path, extra=None):
        c = {
            "state.num-key-shards": 8, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": BATCH,
            "execution.checkpointing.dir": str(tmp_path / "ckpt"),
            "execution.checkpointing.interval": 1,
            "restart-strategy.type": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 20,
            "restart-strategy.fixed-delay.delay": 1,
        }
        c.update(extra or {})
        return Configuration(c)

    def test_fault_free_materialization(self, tmp_path):
        sink = RetractSink(key_fields=("key",))
        env = _retract_job(self._conf(tmp_path), sink)
        env.execute("retract-golden")
        _check_view(sink)

    def test_crash_on_retract_emit_converges(self, tmp_path):
        """KNOWN_FAULT_POINTS['changelog.retract.emit'] fires mid-epoch:
        the -U batch dies before reaching a committed epoch, the job
        restarts from the last checkpoint, and the committed table must
        equal the fault-free golden — no half-applied retraction."""
        sink = RetractSink(key_fields=("key",))  # survives the restarts
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "changelog.retract.emit", "raise", count=1, after=2)

        def build_env(conf):
            return _retract_job(conf, sink)

        with plan.activate(), _replayable(plan):
            run_with_recovery(build_env, self._conf(tmp_path),
                              job_name="retract-chaos")
        assert any(p == "changelog.retract.emit" for p, _, _ in plan.log), \
            "fault point never fired — the schedule tests nothing"
        _check_view(sink)


# ---------------------------------------------------------------------------
# SQL goldens over the lifted shapes.
# ---------------------------------------------------------------------------

class TestSqlChangelogShapes:
    def test_unwindowed_group_by_sql_equals_datastream(self):
        k, v, ts = _data(seed=23)

        env = _env()
        t_env = TableEnvironment.create(env)
        stream = env.from_collection({"k": k, "v": v}, ts, batch_size=100)
        t_env.create_temporary_view(
            "t", stream, schema=["k", "v", "ts"], time_attr="ts")
        tbl = t_env.sql_query(
            "SELECT k, COUNT(*) AS c, SUM(v) AS sv FROM t GROUP BY k")
        sql_sink = UpsertSink(key_fields=("k",))
        tbl.stream.add_sink(sql_sink)
        env.execute("sql-running")

        env2 = _env()
        ds_sink = UpsertSink(key_fields=("key",))
        (env2.from_collection({"k": k, "v": v}, ts, batch_size=100)
             .key_by("k")
             .running_aggregate(aggregates.multi(
                 aggregates.count(), aggregates.sum_of("v")), retract=True)
             .add_sink(ds_sink))
        env2.execute("ds-running")

        got_sql = {int(r["k"]): (int(r["c"]), float(r["sv"]))
                   for r in sql_sink.view()}
        got_ds = {int(r["key"]): (int(r["count"]), float(r["sum_v"]))
                  for r in ds_sink.view()}
        assert set(got_sql) == set(got_ds) == set(_oracle(k, v))
        for kk in got_sql:
            assert got_sql[kk][0] == got_ds[kk][0]
            assert got_sql[kk][1] == pytest.approx(got_ds[kk][1], rel=1e-5)

    def test_having_over_unwindowed_agg(self):
        """HAVING over the changelog (the lifted refusal): the retract
        filter keeps only rows passing the predicate, so the
        materialized table equals the filtered finals — identically
        through RetractSink and UpsertSink."""
        k, v, ts = _data(seed=31)
        views = []
        for sink in (RetractSink(key_fields=("k",)),
                     UpsertSink(key_fields=("k",))):
            env = _env()
            t_env = TableEnvironment.create(env)
            stream = env.from_collection(
                {"k": k, "v": v}, ts, batch_size=100)
            t_env.create_temporary_view(
                "t", stream, schema=["k", "v", "ts"], time_attr="ts")
            tbl = t_env.sql_query(
                "SELECT k, COUNT(*) AS c FROM t GROUP BY k HAVING c > 50")
            tbl.stream.add_sink(sink)
            env.execute("sql-having")
            views.append({int(r["k"]): int(r["c"]) for r in sink.view()})
        want = {kk: c for kk, (c, _) in _oracle(k, v).items() if c > 50}
        assert want  # predicate must actually bite
        assert views[0] == views[1] == want

    def test_agg_over_join_sql_vs_oracle(self):
        """The second lifted refusal: COUNT/SUM over a tumbling window
        JOIN (Nexmark Q8-then-count), golden against the O(n^2) pair
        enumeration."""
        rng = np.random.default_rng(5)
        n = 300
        ts_p = np.sort(rng.integers(0, 6000, n)).astype(np.int64)
        persons = {"person": rng.integers(0, 8, n).astype(np.int64),
                   "ts": ts_p}
        ts_a = np.sort(rng.integers(0, 6000, n)).astype(np.int64)
        auctions = {"seller": rng.integers(0, 8, n).astype(np.int64),
                    "reserve": rng.integers(1, 100, n).astype(np.int64),
                    "ts2": ts_a}

        env = _env()
        t_env = TableEnvironment.create(env)
        p = env.from_collection(persons, ts_p, batch_size=100)
        a = env.from_collection(auctions, ts_a, batch_size=100)
        t_env.create_temporary_view("P", p, ["person", "ts"])
        t_env.create_temporary_view("A", a, ["seller", "reserve", "ts2"])
        t = t_env.sql_query(
            "SELECT P.person, window_start, COUNT(*) AS c, "
            "SUM(A.reserve) AS sr "
            "FROM TABLE(TUMBLE(TABLE P, DESCRIPTOR(ts), "
            "INTERVAL '1' SECOND)) "
            "JOIN TABLE(TUMBLE(TABLE A, DESCRIPTOR(ts2), "
            "INTERVAL '1' SECOND)) "
            "ON P.person = A.seller "
            "GROUP BY person, window_start")
        rows = t.execute("sql-join-agg").collect()

        want = {}
        for i in range(n):
            for j in range(n):
                if (persons["person"][i] == auctions["seller"][j]
                        and ts_p[i] // 1000 == ts_a[j] // 1000):
                    key = (int(persons["person"][i]),
                           int(ts_p[i]) // 1000 * 1000)
                    c, s = want.get(key, (0, 0))
                    want[key] = (c + 1, s + int(auctions["reserve"][j]))

        got = {(int(r["person"]), int(r["window_start"])):
               (int(r["c"]), int(round(float(r["sr"])))) for r in rows}
        assert len(got) > 0
        assert got == want


class TestCliSmoke:
    """`python -m flink_tpu run --local` over the two lifted SQL shapes
    (tests/runner_job_changelog.py), committed output diffed against a
    reference the test computes without the engine."""

    def _cli(self, capsys, *argv):
        import json

        from flink_tpu.cli import main as cli_main
        rc = cli_main(list(argv))
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1]) if out else {}

    def test_agg_over_join_entry(self, tmp_path, capsys):
        import runner_job_changelog as jobs

        from flink_tpu.api.sinks import FileTransactionalSink

        sink_dir = str(tmp_path / "sink")
        rc, out = self._cli(
            capsys, "run", "--local",
            "--entry", "runner_job_changelog:build_join_agg",
            "--job-id", "cl-join",
            "--conf", f"test.sink-dir={sink_dir}",
            "--conf", "state.num-key-shards=4",
            "--conf", "state.slots-per-shard=32",
            "--conf", "pipeline.microbatch-size=100")
        assert rc == 0
        assert out["state"] == "FINISHED"
        got = {}
        for r in FileTransactionalSink.committed_rows(sink_dir):
            key = (int(r["k"]), int(r["window_start"]))
            assert key not in got  # exactly-once committed output
            got[key] = (int(r["c"]), int(round(float(r["sw"]))))
        assert got == jobs.reference_join_agg()

    def test_unwindowed_group_by_entry(self, tmp_path, capsys):
        import runner_job_changelog as jobs

        rc, out = self._cli(
            capsys, "run", "--local",
            "--entry", "runner_job_changelog:build_group_by",
            "--job-id", "cl-upsert",
            "--conf", "state.num-key-shards=4",
            "--conf", "state.slots-per-shard=32",
            "--conf", "pipeline.microbatch-size=100")
        assert rc == 0
        assert out["state"] == "FINISHED"
        got = {int(r["k"]): (int(r["c"]), int(round(float(r["sv"]))))
               for r in jobs.group_by_sink.view()}
        assert got == jobs.reference_group_by()
