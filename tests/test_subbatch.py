"""Sub-batch fire/emit decoupling (``pipeline.sub-batches``, ISSUE 6).

The contract under test, exactly as shipped:

- K = 1 is the pre-change path (every new driver branch guards on
  K > 1), so the whole existing suite is its regression gate.
- The headline DEVGEN Q5 pipeline is **byte-identical including row
  order** at every K: the subdivided device generator re-slices the
  bit-exact record stream, and emit-ring rows append in fire order.
- Host-plane pipelines (wordcount, sessions) commit the **identical
  row set with per-key order preserved**; the global interleave across
  keys follows the fire cadence (a K=1 advance packs many window ends
  into one fire batch; K=4 fires the same ends in ascending groups).
  Runs with late-beyond-watermark records may additionally emit
  corrective late REFIRES earlier than K=1 would — the allowed-
  lateness semantics of a finer watermark cadence, not a defect — so
  the parity goldens here are refire-free by construction.
- Checkpoints cut at SUB-batch boundaries (positions count sub-batches
  on subdivided device chains); restore resumes mid-logical-batch, and
  cross-factor restores re-base positions or fail loudly.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from flink_tpu import faults
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import FnSink, TransactionalCollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import (
    EventTimeSessionWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.config import Configuration
from flink_tpu.nexmark.generator import NexmarkConfig, bid_stream_device
from flink_tpu.nexmark.queries import q5_hot_items
from flink_tpu.runtime.driver import _rebase_position
from flink_tpu.runtime.supervisor import run_with_recovery
from flink_tpu.time.watermarks import WatermarkStrategy

from test_chaos import replayable

pytestmark = pytest.mark.subbatch

Q5_CFG = dict(batch_size=4096, n_batches=6, events_per_ms=100,
              num_active_auctions=500, hot_ratio=4)


def _capture_sink():
    rows = []

    def cap(b):
        if len(b.get("window_end", ())):
            rows.append({k: np.asarray(v).copy() for k, v in b.items()})

    def cat():
        return {k: np.concatenate([r[k] for r in rows]) for k in rows[0]}

    return cat, FnSink(cap)


def _sorted_view(rows):
    keys = sorted(rows)
    return sorted(zip(*(rows[k].tolist() for k in keys)))


def _per_key_seq(rows):
    out = {}
    fields = [f for f in sorted(rows) if f != "key"]
    for i, k in enumerate(rows["key"].tolist()):
        out.setdefault(k, []).append(
            tuple(rows[f][i].item() for f in fields))
    return out


class TestDevgenQ5Parity:
    """The headline contract: any K produces byte-identical committed
    output to K=1 — including ROW ORDER (ring rows append in fire
    order; the subdivided generator is a bit-exact re-slice)."""

    def _run(self, k):
        cat, sink = _capture_sink()
        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 16, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": Q5_CFG["batch_size"],
            "pipeline.sub-batches": k,
        }))
        q5_hot_items(env, bid_stream_device(NexmarkConfig(**Q5_CFG)),
                     sink, window_ms=2000, slide_ms=500,
                     out_of_orderness_ms=100)
        metrics = env.execute(f"q5-sub{k}").metrics
        return cat(), metrics

    def test_k_1_2_4_byte_identical_in_order(self):
        golden, m1 = self._run(1)
        assert len(golden["window_end"]) > 0
        for k in (2, 4):
            got, mk = self._run(k)
            assert mk["records_in"] == m1["records_in"]
            assert set(got) == set(golden)
            for f in golden:
                assert np.array_equal(golden[f], got[f]), (k, f)

    def test_subdivided_stream_is_bit_exact(self):
        import jax.numpy as jnp

        src = bid_stream_device(NexmarkConfig(**Q5_CFG))
        sub = src.subdivided(4)
        b = src.batch_size // 4
        assert sub.batch_size == b
        assert sub.n_batches == src.n_batches * 4
        for i in range(2):
            k1, t1 = (np.asarray(x)
                      for x in src.device_keys_ts(jnp.int64(i)))
            for j in range(4):
                s = 4 * i + j
                kd, td = (np.asarray(x)
                          for x in sub.device_keys_ts(jnp.int64(s)))
                sl = slice(j * b, (j + 1) * b)
                assert np.array_equal(kd, k1[sl]), s
                assert np.array_equal(td, t1[sl]), s
                # host repair copy and ts bounds match the same slice
                kh, th = sub.keys_ts_host(s)
                assert np.array_equal(kh, k1[sl]), s
                lo, hi = sub.ts_bounds(s)
                assert (lo, hi) == (int(th[0]), int(th[-1]))

    def test_subdivide_rejects_indivisible(self):
        src = bid_stream_device(NexmarkConfig(**Q5_CFG))
        with pytest.raises(ValueError, match="does not divide"):
            src.subdivided(3)


class TestHostPlaneParity:
    """Host-fed pipelines: identical committed row SET, per-key order
    preserved, at every K (goldens are refire-free: the watermark's
    out-of-orderness bound covers the generator's disorder)."""

    @staticmethod
    def _wc_gen(split, i):
        if i >= 6:
            return None
        rng = np.random.default_rng(i)
        w = (rng.random(512) ** 2 * 50).astype(np.int64)
        ts = (i * 512 + np.arange(512, dtype=np.int64)) * 4
        return {"word": w}, ts

    def _run_wordcount(self, k):
        cat, sink = _capture_sink()
        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 8, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": 512,
            "pipeline.sub-batches": k}))
        (env.from_source(
            GeneratorSource(self._wc_gen),
            WatermarkStrategy.for_bounded_out_of_orderness(0))
            .key_by("word")
            .window(TumblingEventTimeWindows.of(500))
            .count().add_sink(sink))
        env.execute(f"wc-sub{k}")
        return cat()

    @staticmethod
    def _sess_gen(split, i):
        if i >= 6:
            return None
        rng = np.random.default_rng(500 + i)
        u = rng.integers(0, 30, 256).astype(np.int64)
        ts = (i * 400 + rng.integers(0, 600, 256)).astype(np.int64)
        return {"u": u}, ts

    def _run_sessions(self, k):
        cat, sink = _capture_sink()
        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 8, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": 256,
            "pipeline.sub-batches": k}))
        (env.from_source(
            GeneratorSource(self._sess_gen),
            # 600 covers the generator's intra-batch disorder exactly:
            # no record is ever late, so the fire SET is cadence-free
            WatermarkStrategy.for_bounded_out_of_orderness(600))
            .key_by("u")
            .window(EventTimeSessionWindows.with_gap(150))
            .allowed_lateness(1000)
            .count().add_sink(sink))
        env.execute(f"sess-sub{k}")
        return cat()

    @pytest.mark.parametrize("runner", ["wordcount", "sessions"])
    def test_rows_and_per_key_order_identical(self, runner):
        run = (self._run_wordcount if runner == "wordcount"
               else self._run_sessions)
        golden = run(1)
        assert len(golden["window_end"]) > 0
        for k in (2, 4):
            got = run(k)
            assert _sorted_view(got) == _sorted_view(golden), (runner, k)
            assert _per_key_seq(got) == _per_key_seq(golden), (runner, k)


class TestCheckpointAcrossSubBatch:
    """Positions on a subdivided device chain count SUB-batches: a
    checkpoint can cut mid-logical-batch, and recovery resumes there —
    committed output stays byte-identical to the fault-free run (which
    by the parity gate equals K=1)."""

    def _build(self, sink):
        def build_env(conf):
            env = StreamExecutionEnvironment(conf)
            q5_hot_items(env, bid_stream_device(NexmarkConfig(**Q5_CFG)),
                         sink, window_ms=2000, slide_ms=500,
                         out_of_orderness_ms=100)
            return env
        return build_env

    @staticmethod
    def _view(sink):
        return [tuple(sorted(r.items())) for r in sink.committed]

    def _conf(self, tmp_path, name, extra=None):
        c = {
            "state.num-key-shards": 16, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": Q5_CFG["batch_size"],
            "pipeline.sub-batches": 4,
            "execution.checkpointing.dir": str(tmp_path / name),
            "execution.checkpointing.interval": 1,
            "restart-strategy.type": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 20,
            "restart-strategy.fixed-delay.delay": 1,
        }
        c.update(extra or {})
        return Configuration(c)

    def test_restore_mid_logical_batch_exactly_once(self, tmp_path):
        from flink_tpu.checkpoint.storage import FsCheckpointStorage

        golden_sink = TransactionalCollectSink()
        self._build(golden_sink)(
            self._conf(tmp_path, "golden-ckpt")).execute("sub-golden")
        golden = self._view(golden_sink)
        assert golden

        sink = TransactionalCollectSink()
        plan = (faults.FaultPlan(seed=77)
                .rule("checkpoint.storage.write", "raise", count=1,
                      after=2))
        with plan.activate(), replayable(plan):
            run_with_recovery(self._build(sink),
                              self._conf(tmp_path, "chaos-ckpt"),
                              job_name="sub-chaos")
        assert self._view(sink) == golden

        # the cut crossed a sub-batch boundary: at least one completed
        # checkpoint recorded a position mid-logical-batch (not % 4),
        # stamped with the sub-batch factor restore re-bases against
        mid = 0
        for root, job in (("golden-ckpt", "sub-golden"),
                          ("chaos-ckpt", "sub-chaos")):
            storage = FsCheckpointStorage(
                str(tmp_path / root), job_id=job)
            seen = 0
            for h in storage.list_complete():
                seen += 1
                payload = FsCheckpointStorage.load(h)
                assert all(int(v) == 4 for v in
                           payload.get("sub_factors", {}).values())
                for pos in payload["sources"].values():
                    mid += sum(1 for p in pos.values() if int(p) % 4)
            assert seen > 0, f"no completed checkpoints under {root}"
        assert mid > 0, ("every checkpoint landed on a logical-batch "
                         "boundary — the mid-batch cut went untested")

    def test_position_rebase_between_factors(self):
        assert _rebase_position(6, 4, 2) == 3    # sub 6 of 4 = 1.5 logical
        assert _rebase_position(8, 4, 1) == 2
        assert _rebase_position(2, 1, 4) == 8
        assert _rebase_position(0, 4, 3) == 0
        with pytest.raises(ValueError, match="does not align"):
            _rebase_position(5, 4, 2)            # 1.25 logical batches
        with pytest.raises(ValueError, match="does not align"):
            _rebase_position(7, 4, 1)


class TestSubbatchChaosK4:
    """The K=4 chaos gate: the sessions pipeline recovers exactly-once
    with ``host.pool.task`` + checkpoint-storage faults armed while
    sub-batching is on (golden = fault-free at the SAME K: replay from
    sub-batch positions reproduces the same advance cadence, so even
    late-refire rows are deterministic under recovery)."""

    pytestmark = [pytest.mark.subbatch, pytest.mark.chaos]

    SUB_CONF = {"pipeline.sub-batches": 4, "host.parallelism": 4}

    def test_sessions_chaos_exactly_once_at_k4(self, tmp_path):
        from test_chaos import TestHostPoolChaos

        t = TestHostPoolChaos()
        golden = t._golden(t._sessions_builder, t._session_view,
                           tmp_path, extra={"pipeline.sub-batches": 4})
        plan = (faults.FaultPlan(seed=4321)
                .rule("host.pool.task", "raise", count=1, after=6)
                .rule("checkpoint.storage.write", "raise", count=1,
                      after=1))
        got, recoveries, fault_spans = t._chaos(
            t._sessions_builder, t._session_view, tmp_path, plan,
            extra=self.SUB_CONF)
        with replayable(plan):
            assert got == golden
            assert len(fault_spans) == len(plan.log) == 2
            assert 1 <= len(recoveries) <= 2


class TestValidation:
    def test_driver_rejects_below_one(self):
        env = StreamExecutionEnvironment(Configuration({
            "pipeline.sub-batches": 0}))
        (env.from_source(GeneratorSource(TestHostPlaneParity._wc_gen),
                         WatermarkStrategy.for_monotonous_timestamps())
            .key_by("word").window(TumblingEventTimeWindows.of(500))
            .count().collect())
        with pytest.raises(ValueError, match="sub-batches"):
            env.execute("bad-sub")

    def test_driver_rejects_indivisible_microbatch(self):
        env = StreamExecutionEnvironment(Configuration({
            "pipeline.microbatch-size": 512,
            "pipeline.sub-batches": 3,
            "analysis.fail-on": "off"}))  # reach the driver's own guard
        (env.from_source(GeneratorSource(TestHostPlaneParity._wc_gen),
                         WatermarkStrategy.for_monotonous_timestamps())
            .key_by("word").window(TumblingEventTimeWindows.of(500))
            .count().collect())
        with pytest.raises(ValueError, match="must divide"):
            env.execute("bad-sub-div")

    def test_analyzer_emit_defer_floor(self):
        from flink_tpu.analysis import analyze_config

        findings = analyze_config(Configuration({
            "pipeline.microbatch-size": 4096,
            "pipeline.sub-batches": 4,
            "pipeline.emit-defer": 200}))
        assert any(f.rule == "SUBBATCH_INVALID"
                   and "emit-defer" in f.message for f in findings)
        # K=1 with the same deferral is fine (no sub-batch cadence to
        # defeat), as is K=4 with the deferral on auto
        assert not analyze_config(Configuration({
            "pipeline.microbatch-size": 4096,
            "pipeline.emit-defer": 200}))
        assert not analyze_config(Configuration({
            "pipeline.microbatch-size": 4096,
            "pipeline.sub-batches": 4}))


class TestCliSmoke:
    def test_wordcount_sub_batches_via_cli(self, tmp_path):
        """Tier-1 smoke (ISSUE 6 satellite): bounded WordCount runs
        end-to-end with ``pipeline.sub-batches=4`` through ``python -m
        flink_tpu run --local`` and commits the same totals the K=1
        golden computes."""
        import runner_job_wordcount as job
        from flink_tpu.formats_columnar import ColumnarFormat

        sink_dir = str(tmp_path / "sink")
        n_batches = 6
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.pathsep.join(
                       [os.path.dirname(__file__),
                        os.path.join(os.path.dirname(__file__), ".."),
                        os.environ.get("PYTHONPATH", "")]))
        proc = subprocess.run(
            [sys.executable, "-m", "flink_tpu", "run", "--local",
             "--entry", "runner_job_wordcount:build",
             "--job-id", "cli-sub-wc",
             "--conf", f"test.n-batches={n_batches}",
             "--conf", f"test.sink-dir={sink_dir}",
             "--conf", "pipeline.sub-batches=4",
             "--conf", "state.num-key-shards=4",
             "--conf", "state.slots-per-shard=32"],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=os.path.dirname(__file__))
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["state"] == "FINISHED"
        assert out["records_in"] == n_batches * job.BATCH

        fmt = ColumnarFormat(job.OUT_SCHEMA)
        total = 0
        committed = os.path.join(sink_dir, "committed")
        for name in sorted(os.listdir(committed)):
            with open(os.path.join(committed, name), "rb") as f:
                cols = fmt.deserialize(f.read())
            total += int(np.sum(cols["count"]))
        assert total == job.golden_total(n_batches)
