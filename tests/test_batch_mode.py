"""Bounded execution (``execution.runtime-mode=batch``) — ISSUE 2.

Covers: stage planning (blocking edges, topological waves), loud mode
validation, the golden WordCount parity test (batch and streaming
produce byte-identical committed output, and batch is measurably
faster on the same input — wall clocks printed to the test log),
multi-stage (3-wave) pipelines, the columnar FileSink→FileSource
round trip, and the CLI smoke (``python -m flink_tpu run --local
--runtime-mode batch``).

ref: the reference's batch runtime — BLOCKING result partitions +
stage-wise scheduling (SURVEY §3.6/§3.7); golden parity is the
DataStream batch/streaming unification contract (same program, same
results, different schedule)."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import FnSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.connectors import FileSink, FileSource
from flink_tpu.formats import CsvFormat
from flink_tpu.formats_columnar import ColumnarError, ColumnarFormat
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = pytest.mark.batch

N_BATCHES, BATCH, VOCAB = 300, 128, 64

OUT_SCHEMA = (("key", "i64"), ("window_end", "i64"), ("count", "i64"))


def word_batch(i: int, n_batches: int = N_BATCHES):
    if i >= n_batches:
        return None
    rng = np.random.default_rng(i)
    words = (rng.random(BATCH) ** 2 * VOCAB).astype(np.int64)
    ts = (i * BATCH + np.arange(BATCH, dtype=np.int64)) * 10
    return {"word": words}, ts


def make_env(mode, **conf):
    base = {"state.num-key-shards": 8, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": BATCH,
            "execution.runtime-mode": mode}
    base.update(conf)
    return StreamExecutionEnvironment(Configuration(base))


def build_wordcount(env, sink, n_batches: int = N_BATCHES):
    (env.from_source(GeneratorSource(
        lambda split, i: word_batch(i, n_batches)),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("word")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .add_sink(sink))
    return env


class TestStagePlanning:
    def test_batch_plan_levels_and_blocking_edges(self):
        env = build_wordcount(make_env("batch"), FnSink(lambda b: None))
        plan = env.compile_plan()
        assert plan.runtime_mode == "batch"
        (win,) = [n.id for n in plan.nodes.values() if n.kind == "window"]
        (src,) = plan.sources
        # the window's input edge blocks; the window lives one wave down
        assert all(v == win for _, v in plan.blocking_edges)
        assert plan.stage_of[win] == 1
        assert plan.stage_of[src] == 0
        sink = [n.id for n in plan.nodes.values() if n.kind == "sink"][0]
        assert plan.stage_of[sink] == 1  # pipelined with the window

    def test_streaming_plan_has_no_stages(self):
        env = build_wordcount(make_env("streaming"),
                              FnSink(lambda b: None))
        plan = env.compile_plan()
        assert plan.runtime_mode == "streaming"
        assert plan.stage_of == {} and plan.blocking_edges == []

    def test_scheduler_waves(self):
        from flink_tpu.runtime.scheduler import BatchStageScheduler

        env = build_wordcount(make_env("batch"), FnSink(lambda b: None))
        sched = BatchStageScheduler(env.compile_plan())
        assert len(sched.waves) == 2
        assert sched.waves[0].in_edges == []
        assert len(sched.waves[1].in_edges) == 1
        snap = sched.snapshot()
        assert [w["state"] for w in snap["waves"]] == ["CREATED"] * 2


class TestValidation:
    def test_unbounded_source_rejected(self):
        env = make_env("batch")
        (env.from_source(GeneratorSource(
            lambda s, i: word_batch(i), is_bounded=False),
            WatermarkStrategy.for_bounded_out_of_orderness(0))
            .key_by("word")
            .window(TumblingEventTimeWindows.of(1000))
            .count().add_sink(FnSink(lambda b: None)))
        with pytest.raises(ValueError, match="bounded"):
            env.compile_plan()

    def test_unknown_mode_rejected(self):
        env = build_wordcount(make_env("BATCHY"), FnSink(lambda b: None))
        with pytest.raises(ValueError, match="runtime-mode"):
            env.compile_plan()

    def test_checkpoint_interval_rejected(self, tmp_path):
        env = build_wordcount(
            make_env("batch", **{
                "execution.checkpointing.interval": 100,
                "execution.checkpointing.dir": str(tmp_path)}),
            FnSink(lambda b: None), n_batches=2)
        with pytest.raises(ValueError, match="incompatible"):
            env.execute("batch-ckpt")

    def test_explicit_restore_path_rejected(self, tmp_path):
        env = build_wordcount(
            make_env("batch", **{
                "execution.checkpointing.restore": str(tmp_path / "x"),
                "execution.checkpointing.dir": str(tmp_path)}),
            FnSink(lambda b: None), n_batches=2)
        with pytest.raises(ValueError, match="incompatible"):
            env.execute("batch-restore")

    def test_recovery_injected_restore_latest_degrades_to_rerun(
            self, tmp_path):
        """Coordinator/supervisor redeploys inject restore=latest on
        every retry attempt; a batch job must treat that as a fresh
        re-execution (its recovery model), not a config error that
        burns the restart budget."""
        rows = [0]
        env = build_wordcount(
            make_env("batch", **{
                "execution.checkpointing.restore": "latest",
                "execution.checkpointing.dir": str(tmp_path)}),
            FnSink(lambda b: rows.__setitem__(
                0, rows[0] + len(next(iter(b.values()), [])))),
            n_batches=4)
        res = env.execute("batch-retry")
        assert res.metrics["records_in"] == 4 * BATCH and rows[0] > 0

    def test_self_join_rejected(self):
        env = make_env("batch")
        s = env.from_source(GeneratorSource(
            lambda sp, i: word_batch(i, 2)),
            WatermarkStrategy.for_bounded_out_of_orderness(0))
        (s.key_by("word")
          .window(TumblingEventTimeWindows.of(1000)).count()
          .add_sink(FnSink(lambda b: None)))
        (s.join(s).where("word").equal_to("word")
          .window(TumblingEventTimeWindows.of(1000))
          .apply().add_sink(FnSink(lambda b: None)))
        with pytest.raises(NotImplementedError, match="same upstream"):
            env.compile_plan()

    def test_armed_savepoint_request_rejected(self):
        """A directly-armed savepoint request must fail the batch job
        loudly, not leave the requester waiting forever (the runner
        path is already rejected up front — batch jobs have no
        checkpoint storage)."""
        import threading

        req = threading.Event()
        req.set()
        env = build_wordcount(make_env("batch"), FnSink(lambda b: None),
                              n_batches=2)
        with pytest.raises(ValueError, match="savepoint"):
            env.execute("batch-sp", savepoint_request=req)

    def test_cross_process_rejected(self):
        env = build_wordcount(
            make_env("batch", **{"cluster.num-processes": 2,
                                 "cluster.process-id": 0}),
            FnSink(lambda b: None), n_batches=2)
        with pytest.raises(NotImplementedError, match="single-process"):
            env.execute("batch-dcn")


def _committed_sorted_bytes(sink_dir: str) -> bytes:
    committed = os.path.join(sink_dir, "committed")
    lines = []
    for name in sorted(os.listdir(committed)):
        with open(os.path.join(committed, name), "rb") as f:
            lines.extend(f.read().splitlines())
    return b"\n".join(sorted(lines))


class TestGoldenParity:
    def test_batch_equals_streaming_and_is_faster(self, tmp_path):
        """Acceptance criterion: bounded WordCount → byte-identical
        committed output in both modes, and batch measurably faster on
        the same input (generous margin — calibration on this suite's
        config shows ~2.5×; the assertion only requires 1.18×). Wall
        clocks go to the test log."""
        fmt = CsvFormat(OUT_SCHEMA)

        def run(mode, warmup: bool, tag: str = "w"):
            d = str(tmp_path / f"{mode}-{tag}")
            env = build_wordcount(
                make_env(mode), FileSink(d, fmt),
                n_batches=20 if warmup else N_BATCHES)
            t0 = time.perf_counter()
            res = env.execute(f"wc-{mode}")
            return time.perf_counter() - t0, d, res

        run("streaming", warmup=True)  # jit warmup, both modes share
        run("batch", warmup=True)      # kernels + batch-only paths
        # one retry on the TIMING comparison only: a noisy-neighbor
        # stall during exactly one of the timed runs must not fail a
        # correct build (the calibrated gap is ~2.5x, asserted at
        # 1.18x; parity is asserted on every attempt, never retried)
        for attempt in (1, 2):
            t_stream, d_stream, r_stream = run(
                "streaming", warmup=False, tag=f"m{attempt}")
            t_batch, d_batch, r_batch = run(
                "batch", warmup=False, tag=f"m{attempt}")
            out_s = _committed_sorted_bytes(d_stream)
            out_b = _committed_sorted_bytes(d_batch)
            assert out_s == out_b and len(out_b) > 0
            assert (r_batch.metrics["records_in"]
                    == r_stream.metrics["records_in"]
                    == N_BATCHES * BATCH)
            # the mode's perf case: ONE fire pass instead of per batch
            print(f"\n[batch-golden] attempt {attempt}: "
                  f"streaming={t_stream:.2f}s batch={t_batch:.2f}s "
                  f"speedup={t_stream / t_batch:.2f}x "
                  f"(waves={r_batch.metrics['batch_waves']}, spooled="
                  f"{r_batch.metrics['shuffle_bytes_spooled']}B)")
            if t_batch < t_stream * 0.85:
                break
        else:
            raise AssertionError(
                f"batch ({t_batch:.2f}s) not measurably faster than "
                f"streaming ({t_stream:.2f}s) in 2 attempts")


class TestMultiStage:
    def test_three_wave_pipeline_matches_streaming(self):
        """source → 1s count per word (wave 1) → 10s sum of counts per
        word (wave 2): two blocking exchanges, three waves, identical
        results to the streaming schedule."""
        def run(mode):
            env = make_env(mode)
            rows = []
            (env.from_source(GeneratorSource(
                lambda s, i: word_batch(i, 60)),
                WatermarkStrategy.for_bounded_out_of_orderness(0))
                .key_by("word")
                .window(TumblingEventTimeWindows.of(1000))
                .count()
                .key_by("key")
                .window(TumblingEventTimeWindows.of(10_000))
                .sum("count")
                .add_sink(FnSink(lambda b: rows.append(
                    {k: np.asarray(v).copy() for k, v in b.items()}))))
            res = env.execute(f"ms-{mode}")
            out = {}
            for b in rows:
                cols = sorted(b)
                for vals in zip(*(b[c] for c in cols)):
                    kk = tuple(int(v) for v in vals)
                    out[kk] = out.get(kk, 0) + 1
            return res, out

        res_b, out_b = run("batch")
        _, out_s = run("streaming")
        assert out_b == out_s and len(out_b) > 0
        assert res_b.metrics["batch_waves"] == 3


class TestPartitionedShuffle:
    def test_hash_partitioned_edge_matches_single_partition(self):
        """execution.batch.shuffle-partitions > 1: records hash-route
        by the consumer's key column into disjoint partition files;
        results must be identical to the single-partition spool (and
        to streaming — per-key order is preserved within a file)."""
        def run(mode, parts):
            env = make_env(mode, **{
                "execution.batch.shuffle-partitions": parts})
            out = {}

            def cap(b):
                for k, w, c in zip(b["key"], b["window_end"],
                                   b["count"]):
                    out[(int(k), int(w))] = (
                        out.get((int(k), int(w)), 0) + int(c))

            build_wordcount(env, FnSink(cap), n_batches=40)
            env.execute(f"part-{mode}-{parts}")
            return out

        ref = run("streaming", 1)
        assert run("batch", 4) == ref
        assert run("batch", 1) == ref and len(ref) > 0


class TestColumnarConnectors:
    def test_file_sink_to_file_source_round_trip(self, tmp_path):
        """Batch WordCount commits columnar part files; a second batch
        job re-reads them through FileSource with the SAME schema and
        reproduces the totals — the self-contained at-rest format loop
        (acceptance criterion: schema-checked both ways, numpy/struct
        only)."""
        fmt = ColumnarFormat(OUT_SCHEMA)
        d = str(tmp_path / "colb")
        env = build_wordcount(make_env("batch"), FileSink(d, fmt),
                              n_batches=40)
        env.execute("wc-colb")

        total = [0]
        env2 = make_env("batch")
        (env2.from_source(FileSource(
            os.path.join(d, "committed"), fmt, ts_field="window_end"))
            .key_by("key")
            .window(TumblingEventTimeWindows.of(3600_000))
            .sum("count")
            .add_sink(FnSink(lambda b: total.__setitem__(
                0, total[0] + int(np.sum(b["sum_count"]))))))
        env2.execute("wc-colb-read")
        assert total[0] == 40 * BATCH  # counts sum back to every record

        # read-back with a DIFFERENT schema must fail loudly
        bad = ColumnarFormat((("key", "i64"), ("window_end", "i64"),
                              ("count", "f64")))
        env3 = make_env("batch")
        (env3.from_source(FileSource(
            os.path.join(d, "committed"), bad, ts_field="window_end"))
            .key_by("key")
            .window(TumblingEventTimeWindows.of(3600_000))
            .sum("count").add_sink(FnSink(lambda b: None)))
        with pytest.raises(ColumnarError, match="schema mismatch"):
            env3.execute("wc-colb-bad")

    def test_no_pyarrow_or_fastavro_anywhere(self):
        """The format must stay self-contained (acceptance criterion:
        no pyarrow/fastavro imports anywhere in the package)."""
        import re

        root = os.path.join(os.path.dirname(__file__), "..", "flink_tpu")
        pat = re.compile(r"^\s*(import|from)\s+(pyarrow|fastavro)\b",
                         re.M)
        hits = []
        for dirpath, _, files in os.walk(root):
            for f in files:
                if not f.endswith(".py"):
                    continue
                p = os.path.join(dirpath, f)
                with open(p, "r", encoding="utf-8") as fh:
                    if pat.search(fh.read()):
                        hits.append(p)
        assert hits == []


class TestCliSmoke:
    def test_bounded_wordcount_via_cli_batch_mode(self, tmp_path):
        """Tier-1 smoke (ISSUE 2 satellite): a bounded WordCount runs
        end-to-end through ``python -m flink_tpu run --local
        --runtime-mode batch`` and commits columnar output."""
        import runner_job_wordcount as job

        sink_dir = str(tmp_path / "sink")
        n_batches = 6
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.pathsep.join(
                       [os.path.dirname(__file__),
                        os.path.join(os.path.dirname(__file__), ".."),
                        os.environ.get("PYTHONPATH", "")]))
        proc = subprocess.run(
            [sys.executable, "-m", "flink_tpu", "run", "--local",
             "--entry", "runner_job_wordcount:build",
             "--runtime-mode", "batch", "--job-id", "cli-batch-wc",
             "--conf", f"test.n-batches={n_batches}",
             "--conf", f"test.sink-dir={sink_dir}",
             "--conf", "state.num-key-shards=4",
             "--conf", "state.slots-per-shard=32"],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=os.path.dirname(__file__))
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["state"] == "FINISHED"
        assert out["records_in"] == n_batches * job.BATCH

        fmt = ColumnarFormat(job.OUT_SCHEMA)
        total = 0
        committed = os.path.join(sink_dir, "committed")
        for name in sorted(os.listdir(committed)):
            with open(os.path.join(committed, name), "rb") as f:
                cols = fmt.deserialize(f.read())
            total += int(np.sum(cols["count"]))
        assert total == job.golden_total(n_batches)
