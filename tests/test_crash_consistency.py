"""Crash-state explorer — power-cut verification of every durable tier.

The PR-14 acceptance gate: for EVERY durable tier (checkpoint storage
incl. incremental link_or_copy, log segments + 2PC markers, compaction
manifest swaps, leases + consumer-group offsets, FileSink parts, the
HA session registry), a mutation phase is journaled through CrashFS
(flink_tpu/fs_crash.py), POSIX-legal post-crash images are sampled at
seeded crash points, and each image's RECOVERY — the tier's real
recovery machinery replaying the work idempotently — must converge to
committed output byte-identical to the fault-free golden, or fail
loudly. Zero silent-loss, zero silent-corruption states. A failing
image prints (tier, seed, image index, cut, decisions) for exact
replay.

Tier-1 runs a bounded schedule (3 seeds x 8 images per tier); the
``slow`` soak runs the acceptance bar (>= 200 images per tier across
>= 3 seeds).
"""
import json
import os
import random
import shutil

import numpy as np
import pytest

from flink_tpu import fs_crash
from flink_tpu.checkpoint import blobformat
from flink_tpu.checkpoint.storage import FsCheckpointStorage, ReusedOpState
from flink_tpu.connectors import FileSink
from flink_tpu.formats import JsonLinesFormat
from flink_tpu.log.bus import Compactor, ConsumerGroups, LeaseManager
from flink_tpu.log.topic import (
    TopicAppender,
    TopicReader,
    create_topic,
    list_group_offsets,
    list_leases,
)
from flink_tpu.runtime.ha import JobStore

pytestmark = pytest.mark.chaos

# recovery is allowed to FAIL LOUDLY on an image (LogError /
# ColumnarError / LeaseError are ValueErrors; torn reads are OSErrors)
# — what it must never do is succeed with different committed output
LOUD = (ValueError, OSError)


@pytest.fixture(autouse=True)
def _restore_objstore_default():
    """The objstore-bus tier re-registers ``objstore://`` over each
    crash image's root; put the default (prefix-free) registration
    back so later test modules resolve objstore paths verbatim."""
    yield
    import flink_tpu.fs_objstore as fso

    fso.install(inner_prefix="")


def _canon(obj):
    """Numpy-free canonical form for golden comparison."""
    if isinstance(obj, dict):
        return {str(k): _canon(v) for k, v in sorted(obj.items(),
                                                     key=lambda kv: str(kv[0]))}
    if isinstance(obj, (list, tuple)):
        return [_canon(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return [_canon(v) for v in obj.tolist()]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, bytes):
        return obj.hex()
    return obj


def _read_topic(topic: str):
    r = TopicReader(topic)
    out = {}
    for p in range(r.partitions):
        rows = []
        for off, block in r.read(p):
            rows.append([off, _canon(block)])
        out[p] = rows
    return {"rows": out,
            "committed": _canon(r.committed_offsets()),
            "compacted_end": _canon(r.compacted_ends()),
            "start": _canon(r.start_offsets())}


# -- tier scenarios -------------------------------------------------------
# Each tier: setup(root) runs PRE-journal (base-snapshot state),
# mutate(root) is the journaled phase returning the aux payload the
# recovery needs (the role of the checkpoint payload staged 2PC
# transactions ride in), recover(root, aux) completes the protocol on a
# crashed image, observe(root) returns the committed-visible output.


class CheckpointTier:
    """Checkpoint storage: v1 single, v2/v3 per-op blobs, and the
    incremental link_or_copy reuse path."""

    name = "checkpoint"

    def setup(self, root):
        pass

    def mutate(self, root):
        st = FsCheckpointStorage(os.path.join(root, "chk"), "job")
        st.save(1, {"sources": {"0": 4}, "operators": {}})
        h2 = st.save_v2(
            2, {"op_versions": {"7": 1, "8": 1}},
            {"7": blobformat.encode(list(range(50))),
             "8": blobformat.encode({"table": ["a", "b"]})}, {})
        st.save_v2(
            3, {"op_versions": {"7": 2, "8": 1}},
            {"7": blobformat.encode(list(range(50, 120)))},
            {"8": ReusedOpState(
                file=os.path.join(h2.path, h2.op_files["8"]),
                version=1)})
        return None

    def recover(self, root, aux):
        # restart-from-scratch recovery: re-running the deterministic
        # save sequence is exactly what a restarted attempt does (save
        # is last-writer-wins at each final name)
        self.mutate(root)

    def observe(self, root):
        st = FsCheckpointStorage(os.path.join(root, "chk"), "job")
        h = st.latest()
        payload = FsCheckpointStorage.load(h)
        return {"id": h.checkpoint_id,
                "ops": _canon(payload.get("operators", {})),
                "versions": _canon(payload.get("op_file_versions", {}))}

    def check_image(self, root):
        """The durability promise, asserted BEFORE recovery re-runs
        anything: every checkpoint the store lists as COMPLETE must
        actually load — a manifest-durable checkpoint whose (linked)
        op blob entry vanished in the power cut is an acked checkpoint
        the job cannot restore from (the save_v2 reuse-link dir-fsync
        guards exactly this)."""
        st = FsCheckpointStorage(os.path.join(root, "chk"), "job")
        for h in st.list_complete():
            FsCheckpointStorage.load(h)


class LogTxnTier:
    """Log segments + 2PC markers: two committed transactions across
    two partitions, recovered by rebuild-from-checkpoint-payload +
    idempotent re-commit (the restore_staged path)."""

    name = "log-2pc"

    def setup(self, root):
        pass

    def _batches(self):
        b1 = {"k": np.arange(8, dtype=np.int64),
              "v": np.arange(8, dtype=np.float64) * 1.5}
        b2 = {"k": np.arange(8, 13, dtype=np.int64),
              "v": np.arange(5, dtype=np.float64) - 2.0}
        return b1, b2

    def mutate(self, root):
        topic = os.path.join(root, "events")
        ap = TopicAppender(topic, partitions=2, segment_records=4)
        b1, b2 = self._batches()
        aux = {}
        ap.stage(1, {0: [b1], 1: [b1]})
        aux["1"] = ap.snapshot(1)
        ap.commit(1)
        ap.stage(2, {0: [b2], 1: [b2]})
        aux["2"] = ap.snapshot(2)
        ap.commit(2)
        return aux

    def recover(self, root, aux):
        topic = os.path.join(root, "events")
        ap = TopicAppender(topic, partitions=2, segment_records=4)
        for cid in ("1", "2"):
            ap.rebuild(int(cid), aux[cid])
            ap.commit(int(cid))
        ap.sweep_orphans()

    def observe(self, root):
        return _read_topic(os.path.join(root, "events"))


class CompactionTier:
    """The compaction manifest swap: committed history exists BEFORE
    journaling (base snapshot); the journaled phase is one compaction
    pass; recovery re-runs the pass on whatever generation the crash
    left visible."""

    name = "compaction-swap"

    def _batch(self, lo):
        return {"k": (np.arange(lo, lo + 6, dtype=np.int64) % 4),
                "v": np.arange(lo, lo + 6, dtype=np.int64)}

    def setup(self, root):
        topic = os.path.join(root, "keyed")
        create_topic(topic, 1, key_field="k")
        ap = TopicAppender(topic, partitions=1, segment_records=6)
        for cid in (1, 2, 3):
            ap.stage(cid, {0: [self._batch(cid * 10)]})
            ap.commit(cid)

    def mutate(self, root):
        Compactor(os.path.join(root, "keyed"), min_segments=2).compact()
        return None

    def recover(self, root, aux):
        topic = os.path.join(root, "keyed")
        Compactor(topic, min_segments=2).compact()
        TopicAppender(topic, partitions=1, segment_records=6).sweep_orphans()

    def observe(self, root):
        return _read_topic(os.path.join(root, "keyed"))


class LeaseGroupTier:
    """Writer leases + consumer-group offsets: both are control files
    published through write_atomic; recovery re-runs the idempotent
    acquire/commit sequence (max-merge, keep-epoch)."""

    name = "lease-group"

    def setup(self, root):
        create_topic(os.path.join(root, "t"), 2, key_field="k")

    def mutate(self, root):
        topic = os.path.join(root, "t")
        lm = LeaseManager(topic, "producer-a", [0, 1], ttl_ms=3_600_000)
        lm.acquire()
        ConsumerGroups.commit(topic, "g1", {0: 5, 1: 3})
        ConsumerGroups.commit(topic, "g1", {0: 9})
        ConsumerGroups.commit(topic, "g2", {0: 2, 1: 2})
        return None

    def recover(self, root, aux):
        self.mutate(root)

    def observe(self, root):
        topic = os.path.join(root, "t")
        leases = {p: {"owner": rec.get("owner"),
                      "epoch": rec.get("epoch"),
                      "released": rec.get("released", False)}
                  for p, rec in list_leases(topic).items()}
        return {"groups": _canon(list_group_offsets(topic)),
                "leases": _canon(leases)}


class FileSinkTier:
    """FileSink staged/committed parts (attempt-epoch-qualified),
    recovered through the real restore_staged path."""

    name = "filesink"
    FMT = JsonLinesFormat([("k", "i64"), ("v", "str")])

    def setup(self, root):
        pass

    def _write(self, sink, lo, n):
        sink.write({"k": np.arange(lo, lo + n, dtype=np.int64),
                    "v": np.array([f"row-{i}" for i in range(lo, lo + n)],
                                  dtype=object)})

    def mutate(self, root):
        sink = FileSink(os.path.join(root, "out"), self.FMT,
                        rolling_records=3)
        aux = {}
        self._write(sink, 0, 5)
        sink.prepare_commit(1)
        aux["1"] = sink.snapshot_transaction(1)
        sink.commit_transaction(1)
        self._write(sink, 5, 4)
        sink.prepare_commit(2)
        aux["2"] = sink.snapshot_transaction(2)
        sink.commit_transaction(2)
        return aux

    def recover(self, root, aux):
        sink = FileSink(os.path.join(root, "out"), self.FMT,
                        rolling_records=3)
        sink.restore_staged(
            {"txn": {c: p for c, p in aux.items()}}, 2)

    def observe(self, root):
        sink = FileSink(os.path.join(root, "out"), self.FMT,
                        rolling_records=3)
        return _canon(sink.committed_batches())


class HaRegistryTier:
    """The durable session registry (runtime/ha.py JobStore): every
    put is atomic-durable, terminal states archive; recovery re-runs
    the lifecycle (idempotent same-content puts)."""

    name = "ha-registry"

    def setup(self, root):
        pass

    def mutate(self, root):
        js = JobStore(os.path.join(root, "ha"))
        js.put("job-a", entry="m:f", config={"x": 1}, state="WAITING",
               attempts=1, submitted_at=100.0)
        js.put("job-b", entry="m:g", config={}, state="WAITING",
               attempts=1, submitted_at=101.0)
        js.put("job-a", entry="m:f", config={"x": 1}, state="RUNNING",
               attempts=1, submitted_at=100.0,
               assigned_runners=["runner-1"])
        js.put("job-b", entry="m:g", config={}, state="FINISHED",
               attempts=1, submitted_at=101.0)
        return None

    def recover(self, root, aux):
        self.mutate(root)

    def observe(self, root):
        js = JobStore(os.path.join(root, "ha"))
        recs = sorted(js.recoverable(), key=lambda r: r["job_id"])
        return {"active": _canon(recs),
                "archived_b": _canon(js.get("job-b"))}

    def check_image(self, root):
        """No-torn-record invariant, asserted BEFORE recovery: a
        power cut must never leave garbage at a registry record's
        final name (recoverable() silently skips parse failures — a
        torn record would be a SILENTLY lost job)."""
        for sub in ("jobs", "jobs-archive"):
            d = os.path.join(root, "ha", sub)
            if not os.path.isdir(d):
                continue
            for name in os.listdir(d):
                if not name.endswith(".json"):
                    continue
                with open(os.path.join(d, name)) as f:
                    json.load(f)  # raises on a torn record


class LsmTier:
    """The lsm keyed-state disk tier (flink_tpu/state/lsm.py, ISSUE
    17): budget=0 seals one run per absorbed batch, so the durable
    manifest's ``seq`` IS the applied-batch count; a final explicit
    compaction exercises the manifest swap. Recovery adopts whatever
    manifest the cut left visible (orphan runs are swept by _open),
    re-absorbs the missing batches, and re-compacts — the fold (seal
    order, delta last) must then be byte-identical to the fault-free
    golden."""

    name = "lsm-state"
    N = 5

    class _Agg:
        sum_width = max_width = min_width = 1

        def lift_masked(self, data, valid):
            v = np.asarray(data["v"], np.float32)[:, None]
            return v, v, v

    def _mk(self, root):
        from flink_tpu.state.lsm import LsmSpillStore
        return LsmSpillStore(
            self._Agg(), store_dir=os.path.join(root, "store"),
            memory_budget_bytes=0, num_shards=8, compact_min_runs=99)

    def _absorb(self, store, i):
        k = (np.arange(24, dtype=np.int64) * (i + 3)) % 7
        p = np.full(24, i % 3, dtype=np.int64)
        v = np.arange(24, dtype=np.float32) * 0.37 + i
        store.absorb(k, p, {"v": v})

    def setup(self, root):
        pass

    def mutate(self, root):
        store = self._mk(root)
        for i in range(self.N):
            self._absorb(store, i)
        store.compact()
        return None

    def recover(self, root, aux):
        store = self._mk(root)
        for i in range(min(store._seq, self.N), self.N):
            self._absorb(store, i)
        store.compact()

    def observe(self, root):
        store = self._mk(root)
        scratch = store._fold_runs(store._live_runs(),
                                   include_delta=True)
        return {int(p): _canon(list(scratch.panes[p]))
                for p in sorted(scratch.panes)}

    def check_image(self, root):
        """The tier's fsync promise, asserted BEFORE recovery touches
        anything: a durable manifest must parse (write_atomic — never
        torn) and every run it lists must exist and decode to its
        promised row count (the run's write_atomic + fsync
        happens-before the manifest swap)."""
        from flink_tpu.state.lsm import _decode_run_panes

        sdir = os.path.join(root, "store")
        mpath = os.path.join(sdir, "MANIFEST.json")
        if not os.path.exists(mpath):
            return
        with open(mpath) as f:
            man = json.load(f)
        assert man.get("format") == "lsm-state"
        for meta in man.get("runs", []):
            rows = sum(
                len(t[0]) for _, t in _decode_run_panes(
                    os.path.join(sdir, meta["name"]), 0))
            assert rows == int(meta["rows"]), (
                f"run {meta['name']}: {rows} rows != "
                f"promised {meta['rows']}")


class ObjstoreBusTier:
    """PR 18: the bus tier served THROUGH the objstore CAS driver
    composed over CrashFS (``install(inner_prefix="crash://<root>/")``
    — every object put becomes a journaled atomic publish): a
    committed 2PC transaction, CAS writer leases, dynamic-group
    membership (two joins → generation 2) + a generation-keyed offset
    commit, and a compaction pass whose manifest swap is a
    conditional put. Recovery re-runs the idempotent sequence
    (rebuild+re-commit, keep-epoch re-acquire, idempotent re-join,
    max-merge re-commit at the CURRENT generation, re-compact) on
    whatever objects the cut left visible."""

    name = "objstore-bus"
    TOPIC = "objstore://t"

    def _install(self, root):
        import flink_tpu.fs_objstore as fso

        fso.install(inner_prefix=root.rstrip("/") + "/")

    def _batch(self, lo):
        return {"k": (np.arange(lo, lo + 8, dtype=np.int64) % 4),
                "v": np.arange(lo, lo + 8, dtype=np.float64)}

    def setup(self, root):
        self._install(root)
        create_topic(self.TOPIC, 2, key_field="k")
        ap = TopicAppender(self.TOPIC, partitions=2, segment_records=4)
        for cid in (1, 2):
            b = self._batch(cid * 10)
            ap.stage(cid, {0: [b], 1: [b]})
            ap.commit(cid)

    def mutate(self, root):
        self._install(root)
        ap = TopicAppender(self.TOPIC, partitions=2, segment_records=4)
        b = self._batch(30)
        ap.stage(3, {0: [b], 1: [b]})
        aux = {"3": ap.snapshot(3)}
        ap.commit(3)
        LeaseManager(self.TOPIC, "producer-a", [0, 1],
                     ttl_ms=3_600_000).acquire()
        ConsumerGroups.join(self.TOPIC, "g1", "m1")
        ConsumerGroups.join(self.TOPIC, "g1", "m2")
        ConsumerGroups.commit(self.TOPIC, "g1", {0: 5, 1: 3},
                              generation=2)
        Compactor(self.TOPIC, min_segments=2).compact()
        return aux

    def recover(self, root, aux):
        self._install(root)
        ap = TopicAppender(self.TOPIC, partitions=2, segment_records=4)
        ap.rebuild(3, aux["3"])
        ap.commit(3)
        LeaseManager(self.TOPIC, "producer-a", [0, 1],
                     ttl_ms=3_600_000).acquire()
        ConsumerGroups.join(self.TOPIC, "g1", "m1")
        ConsumerGroups.join(self.TOPIC, "g1", "m2")
        gen = ConsumerGroups.read_membership(
            self.TOPIC, "g1")["generation"]
        ConsumerGroups.commit(self.TOPIC, "g1", {0: 5, 1: 3},
                              generation=gen)
        Compactor(self.TOPIC, min_segments=2).compact()
        ap.sweep_orphans()

    def observe(self, root):
        self._install(root)
        view = _read_topic(self.TOPIC)
        leases = {p: {"owner": rec.get("owner"),
                      "epoch": rec.get("epoch"),
                      "released": rec.get("released", False)}
                  for p, rec in list_leases(self.TOPIC).items()}
        return {"topic": view,
                "groups": _canon(list_group_offsets(self.TOPIC)),
                "membership": _canon(ConsumerGroups.read_membership(
                    self.TOPIC, "g1")),
                "leases": _canon(leases)}

    def check_image(self, root):
        """PUT-is-durable, asserted BEFORE recovery: an object either
        exists whole or not at all — every .json object in the image
        must parse (a torn one would mean the buffered-put publish
        leaked a partial object through the crash)."""
        for dirpath, _dirs, files in os.walk(os.path.join(root, "t")):
            for name in files:
                if name.endswith(".json"):
                    with open(os.path.join(dirpath, name)) as f:
                        json.load(f)


TIERS = (CheckpointTier(), LogTxnTier(), CompactionTier(),
         LeaseGroupTier(), FileSinkTier(), HaRegistryTier(),
         LsmTier(), ObjstoreBusTier())


# -- the explorer ---------------------------------------------------------

def explore(tier, tmp_path, seeds, images_per_seed):
    # fault-free golden
    groot = os.path.join(str(tmp_path), "golden")
    os.makedirs(groot)
    tier.setup(groot)
    tier.mutate(groot)
    golden = tier.observe(groot)

    recovered = loud = 0
    for seed in seeds:
        root = os.path.join(str(tmp_path), f"run-{seed}")
        os.makedirs(root)
        tier.setup(root)
        cfs = fs_crash.install(root)
        try:
            aux = tier.mutate("crash://" + root)
            assert cfs.journal, (
                f"tier {tier.name}: journaled phase recorded no "
                "mutations — the tier is not routed through the seam")
            img = os.path.join(str(tmp_path), "img")
            for k in range(images_per_seed):
                rng = random.Random((seed << 20) ^ k)
                dec = cfs.crash(img, rng=rng, seed=seed)
                ctx = (f"tier={tier.name} seed={seed} image={k} "
                       f"cut={dec['cut']}/{len(cfs.journal)} "
                       f"decisions={dec['decisions']}")
                check = getattr(tier, "check_image", None)
                if check is not None:
                    check(img)  # pre-recovery invariants (loud if torn)
                try:
                    tier.recover(img, aux)
                    got = tier.observe(img)
                except LOUD:
                    loud += 1
                    continue
                assert _canon(got) == _canon(golden), (
                    f"SILENT DIVERGENCE after recovery — {ctx}\n"
                    f"got:    {got}\ngolden: {golden}")
                recovered += 1
        finally:
            cfs.close()
    # a recovery path that always fails loudly would pass vacuously —
    # require that the tier actually converges on a healthy majority
    total = recovered + loud
    assert recovered >= max(1, total // 2), (
        f"tier {tier.name}: only {recovered}/{total} images recovered "
        f"cleanly ({loud} loud) — recovery is broken, not just loud")
    return recovered, loud


@pytest.mark.parametrize("tier", TIERS, ids=[t.name for t in TIERS])
def test_crash_images_recover_to_golden(tier, tmp_path):
    """Bounded tier-1 schedule: 3 seeds x 8 sampled crash images per
    durable tier, each recovering byte-identical to the fault-free
    golden or failing loudly."""
    explore(tier, tmp_path, seeds=(0, 1, 2), images_per_seed=8)


@pytest.mark.slow
@pytest.mark.parametrize("tier", TIERS, ids=[t.name for t in TIERS])
def test_crash_soak(tier, tmp_path):
    """The acceptance bar: >= 200 sampled crash images per durable
    tier across >= 3 seeds (70 x 3 = 210)."""
    recovered, loud = explore(tier, tmp_path, seeds=(0, 1, 2),
                              images_per_seed=70)
    assert recovered + loud >= 200


class TestFsFaultPointChaos:
    """The new fs.* fault points wired into exception-shaped chaos
    schedules (the KNOWN_FAULT_POINTS satellite): an injected failure
    at the seam mid-stage leaves only unreferenced debris; the retried
    stage converges byte-identical to the fault-free run."""

    def _run(self, topic_dir, plan):
        from flink_tpu import faults

        b = {"k": np.arange(6, dtype=np.int64),
             "v": np.arange(6, dtype=np.float64)}
        ap = TopicAppender(topic_dir, partitions=1, segment_records=4)
        with plan.activate() if plan else _null():
            try:
                ap.stage(1, {0: [b]})
            except OSError:
                # the attempt died at the injected seam — recover and
                # replay, exactly what run_with_recovery does
                ap = TopicAppender(topic_dir, partitions=1,
                                   segment_records=4)
                ap.recover()
                ap.stage(1, {0: [b]})
            ap.commit(1)
        return _read_topic(topic_dir)

    @pytest.mark.parametrize("point", ["fs.rename", "fs.fsync",
                                       "fs.write.enospc"])
    def test_injected_fs_fault_recovers_byte_identical(
            self, tmp_path, point):
        from flink_tpu import faults
        from flink_tpu.fs import install_enospc_policy

        golden = self._run(os.path.join(str(tmp_path), "g"), None)
        # policy 'fail' so the enospc injection propagates as a fault
        # (the retry path has its own acceptance test in test_enospc)
        install_enospc_policy("fail")
        try:
            plan = faults.FaultPlan(seed=7).rule(point, "raise",
                                                 count=1, after=2)
            got = self._run(os.path.join(str(tmp_path), "c"), plan)
        finally:
            install_enospc_policy("retry")
        assert got == golden
        assert plan.log, f"schedule injected nothing at {point}"


import contextlib


@contextlib.contextmanager
def _null():
    yield
