"""Bounded WordCount entry point for the batch-mode CLI smoke test —
``python -m flink_tpu run --local --entry runner_job_wordcount:build
--runtime-mode batch``. The sink is a FileSink in the self-contained
columnar format, so the smoke test also proves the binary at-rest path
end to end (FileSink write → commit → ColumnarFormat read-back)."""
import numpy as np

from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.connectors import FileSink
from flink_tpu.formats_columnar import ColumnarFormat
from flink_tpu.time.watermarks import WatermarkStrategy

BATCH = 128
VOCAB = 40

OUT_SCHEMA = (("key", "i64"), ("window_end", "i64"), ("count", "i64"))


def batch_of(i: int):
    rng = np.random.default_rng(7000 + i)
    words = (rng.random(BATCH) ** 2 * VOCAB).astype(np.int64)
    ts = (i * BATCH + np.arange(BATCH, dtype=np.int64)) * 10
    return {"word": words}, ts


def golden_total(n_batches: int) -> int:
    return n_batches * BATCH  # count() sums to one row per input record


def build(env):
    n_batches = int(env.config.get_raw("test.n-batches", 6))
    sink_dir = env.config.get_raw("test.sink-dir")
    assert sink_dir, "test.sink-dir must be set"

    def gen(split, i):
        return batch_of(i) if i < n_batches else None

    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("word")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .add_sink(FileSink(sink_dir, ColumnarFormat(OUT_SCHEMA))))
