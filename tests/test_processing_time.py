"""Processing-time windows, triggers, and timers with MOCK time
(ref: WindowOperatorTest's processing-time cases driven by
TestProcessingTimeService; SURVEY §3.2 windowing + §3.3 timer
service)."""
import numpy as np
import pytest

from flink_tpu.api.windowing import (
    ProcessingTimeTrigger, SlidingProcessingTimeWindows,
    TumblingProcessingTimeWindows)
from flink_tpu.ops.aggregates import count, sum_of
from flink_tpu.ops.window import WindowOperator
from flink_tpu.time.clock import ManualProcessingTimeService


def mk_op(assigner, agg=None, **kw):
    op = WindowOperator(assigner, agg or count(), num_shards=4,
                        slots_per_shard=64, **kw)
    clock = ManualProcessingTimeService(0)
    op.clock = clock
    return op, clock


def rows(fired):
    return sorted((int(k), int(ws), int(we), int(c)) for k, ws, we, c in zip(
        fired["key"], fired["window_start"], fired["window_end"],
        fired["count"]))


class TestTumblingProcessingTime:
    def test_assign_by_clock_and_fire_on_clock(self):
        op, clock = mk_op(TumblingProcessingTimeWindows.of(1000))
        clock.advance_to(100)
        # event timestamps are IGNORED: the clock stamps arrival
        op.process_batch(np.array([1, 1, 2]), np.array([99999, 0, 5]), {})
        clock.advance_to(900)
        op.process_batch(np.array([1]), np.array([0]), {})
        # clock still inside the window: nothing fires
        assert len(op.advance_processing_time()["key"]) == 0
        clock.advance_to(1000)  # window [0,1000) complete at t=1000
        f = op.advance_processing_time()
        assert rows(f) == [(1, 0, 1000, 3), (2, 0, 1000, 1)]
        # next window
        op.process_batch(np.array([2]), np.array([0]), {})
        clock.advance_to(2500)
        f = op.advance_processing_time()
        assert rows(f) == [(2, 1000, 2000, 1)]

    def test_no_late_records_by_construction(self):
        op, clock = mk_op(TumblingProcessingTimeWindows.of(1000))
        clock.advance_to(5000)
        op.advance_processing_time()
        # records arriving now go in the CURRENT window regardless of
        # their event timestamps — nothing can be late
        op.process_batch(np.array([7]), np.array([0]), {})
        clock.advance_to(6000)
        f = op.advance_processing_time()
        assert rows(f) == [(7, 5000, 6000, 1)]
        assert op.late_records == 0

    def test_lateness_rejected(self):
        with pytest.raises(ValueError, match="lateness"):
            WindowOperator(TumblingProcessingTimeWindows.of(1000), count(),
                           num_shards=4, slots_per_shard=8,
                           allowed_lateness_ms=100)


class TestSlidingProcessingTime:
    def test_sliding_panes_over_clock(self):
        op, clock = mk_op(SlidingProcessingTimeWindows.of(2000, 1000),
                          sum_of("v"))
        clock.advance_to(500)
        op.process_batch(np.array([1]), np.array([0]),
                         {"v": np.array([10.0])})
        clock.advance_to(1500)
        op.process_batch(np.array([1]), np.array([0]),
                         {"v": np.array([5.0])})
        clock.advance_to(2000)
        f = op.advance_processing_time()
        got = sorted((int(k), int(ws), float(s)) for k, ws, s in zip(
            f["key"], f["window_start"], f["sum_v"]))
        # windows ending <= 2000: [-1000,1000) holds the t=500 record,
        # [0,2000) holds both
        assert got == [(1, -1000, 10.0), (1, 0, 15.0)]

    def test_trigger_object_semantics(self):
        from flink_tpu.api.windowing import TimeWindow, TriggerResult

        t = ProcessingTimeTrigger.create()
        w = TimeWindow(0, 1000)
        assert t.on_processing_time(998, w) == TriggerResult.CONTINUE
        assert t.on_processing_time(999, w) == TriggerResult.FIRE
        assert not t.fires_on_watermark()


class TestApiValidation:
    def _ws(self, assigner):
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.config import Configuration

        env = StreamExecutionEnvironment(Configuration({}))
        s = env.from_collection({"k": np.array([1])},
                                np.array([0], np.int64))
        return s.key_by("k").window(assigner)

    def test_proc_trigger_on_event_windows_rejected(self):
        from flink_tpu.api.windowing import TumblingEventTimeWindows

        ws = self._ws(TumblingEventTimeWindows.of(1000))
        with pytest.raises(NotImplementedError, match="ProcessingTime"):
            ws.trigger(ProcessingTimeTrigger.create()).count()

    def test_event_trigger_on_proc_windows_rejected(self):
        from flink_tpu.api.windowing import EventTimeTrigger

        ws = self._ws(TumblingProcessingTimeWindows.of(1000))
        with pytest.raises(NotImplementedError, match="EventTimeTrigger"):
            ws.trigger(EventTimeTrigger.create()).count()

    def test_lateness_on_proc_windows_rejected(self):
        ws = self._ws(TumblingProcessingTimeWindows.of(1000))
        with pytest.raises(NotImplementedError, match="lateness"):
            ws.allowed_lateness(10).count()


class TestProcessingTimeTimers:
    def test_register_and_fire_with_mock_clock(self):
        from flink_tpu.ops.process import KeyedProcessOperator

        fired = []

        class Fn:
            def process_batch(self, ctx):
                ctx.register_processing_time_timers(
                    np.full(len(ctx.slots), ctx.current_processing_time()
                            + 1000, np.int64))

            def on_timer(self, ctx):
                fired.append((ctx.time_domain, ctx.keys.copy(),
                              ctx.timestamps.copy()))
                ctx.emit({"k": ctx.keys}, ts=ctx.timestamps)

        op = KeyedProcessOperator(Fn(), num_shards=4, slots_per_shard=16)
        clock = ManualProcessingTimeService(100)
        op.clock = clock
        op.process_batch(np.array([5, 6]), np.array([0, 0]), {})
        assert op.advance_processing_time_timers() is None  # not due
        clock.advance_to(1100)
        out = op.advance_processing_time_timers()
        assert out is not None
        assert sorted(np.asarray(out["k"]).tolist()) == [5, 6]
        assert fired[0][0] == "processing"
        assert list(fired[0][2]) == [1100, 1100]

    def test_event_and_processing_timers_coexist(self):
        from flink_tpu.ops.process import KeyedProcessOperator

        domains = []

        class Fn:
            def process_batch(self, ctx):
                ctx.register_event_time_timers(
                    np.full(len(ctx.slots), 500, np.int64))
                ctx.register_processing_time_timers(
                    np.full(len(ctx.slots), 800, np.int64))

            def on_timer(self, ctx):
                domains.append(ctx.time_domain)
                ctx.emit({"k": ctx.keys}, ts=ctx.timestamps)

        op = KeyedProcessOperator(Fn(), num_shards=4, slots_per_shard=16)
        clock = ManualProcessingTimeService(0)
        op.clock = clock
        op.process_batch(np.array([1]), np.array([0]), {})
        op.advance_watermark(600)          # event timer fires
        clock.advance_to(900)
        op.advance_processing_time_timers()  # proc timer fires
        assert domains == ["event", "processing"]

    def test_proc_timers_survive_snapshot_restore(self):
        from flink_tpu.ops.process import KeyedProcessOperator

        class Fn:
            def __init__(self):
                self.fired = []

            def process_batch(self, ctx):
                ctx.register_processing_time_timers(
                    np.full(len(ctx.slots), 700, np.int64))

            def on_timer(self, ctx):
                self.fired.append(ctx.keys.copy())
                ctx.emit({"k": ctx.keys}, ts=ctx.timestamps)

        f1 = Fn()
        op = KeyedProcessOperator(f1, num_shards=4, slots_per_shard=16)
        op.clock = ManualProcessingTimeService(0)
        op.process_batch(np.array([9]), np.array([0]), {})
        snap = op.snapshot_state()
        f2 = Fn()
        op2 = KeyedProcessOperator(f2, num_shards=4, slots_per_shard=16)
        clock2 = ManualProcessingTimeService(1000)
        op2.clock = clock2
        op2.restore_state(snap)
        out = op2.advance_processing_time_timers()
        assert out is not None and f2.fired and list(f2.fired[0]) == [9]


class TestEndToEndProcTime:
    def test_pipeline_with_proc_windows(self):
        """Full driver path: proc-time windows fire via the runtime's
        clock advance; end of input drains everything."""
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.config import Configuration

        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 4, "state.slots-per-shard": 32,
            "pipeline.microbatch-size": 64}))
        keys = np.arange(100, dtype=np.int64) % 5
        ts = np.zeros(100, np.int64)  # event time irrelevant
        sink = (env.from_collection({"k": keys}, ts)
                .key_by("k")
                .window(TumblingProcessingTimeWindows.of(50))
                .count()
                .collect())
        env.execute("proc-job")
        got = {}
        for r in sink.rows:
            got[int(r["key"])] = got.get(int(r["key"]), 0) + int(r["count"])
        # the drain at end of input must deliver every record exactly once
        assert got == {k: 20 for k in range(5)}
